"""Phase timers, counters, and .perf-compatible reporting.

Replaces ``performance/Measurements.{h,cpp}`` (SURVEY.md §5.1): the
reference's ~60 static start/stop functions around `gettimeofday` + PAPI
cycles, compile-gated sub-timers (``MEASUREMENT_DETAILS_*``), per-rank
``<rank>.perf`` tag files gathered to rank 0 over MPI_Send/Recv
(Measurements.cpp:548-590), the printed per-phase table (:592-702), and the
``/proc/self/status`` memory probe (:825-851).

TPU design: a timer registry keyed by the reference's own tag vocabulary
(JTOTAL, JHIST, JMPI, JPROC, SWINALLOC, ...) so baseline comparison is
mechanical; fences are ``jax.block_until_ready`` (device work is async);
hardware-counter analogs come from ``jax.profiler`` traces rather than PAPI.
Everything under one jit cannot be phase-timed from the host, so phase timing
is honest at the granularity the driver actually executes (histogram program /
join program), with the jit-internal split available via profiler traces
(:meth:`Measurements.trace`).  The fine-grained *counter* details the
reference accumulates in its hot loops (tuple sums, per-Put byte/call counts,
Measurements.cpp:272-349) are exact here without instrumenting the hot path —
block geometry is static, so the driver derives them from config + results
(:meth:`Measurements.record_exchange`).
"""

from __future__ import annotations

import json
import os
import socket
import time
from collections import defaultdict
from typing import Dict, Iterable, List, Optional

import jax

# Reference tag vocabulary (Measurements.cpp:136-142,176-178,351-368,533-542)
JTOTAL = "JTOTAL"          # end-to-end join wall time
JHIST = "JHIST"            # histogram phase
JMPI = "JMPI"              # network partitioning phase
JPROC = "JPROC"            # local processing phase
SWINALLOC = "SWINALLOC"    # window allocation (capacity measurement + compile)
SNETCOMPL = "SNETCOMPL"    # network completion wait
SLOCPREP = "SLOCPREP"      # local preparation

MWINWAIT = "MWINWAIT"      # time spent on retried (undersized-window) attempts
JCOMPILE = "JCOMPILE"      # XLA compilation (no reference analog: it has none
                           # at runtime; kept out of every phase column)
SDISPATCH = "SDISPATCH"    # per-program dispatch round-trip floor (not a
                           # cumulative phase: the amortized cost of ONE
                           # empty-program dispatch through the host
                           # attachment, measured once per run)

_GATHER_BUF_BYTES = 1 << 16   # fixed allgather slot per process (gather_all)

# Detail tags (MEASUREMENT_DETAILS_* analogs).  Counters carry the exact
# quantities the reference sums per call site; rates are derived on report.
RTUPLES = "RTUPLES"        # inner tuples joined (counter)
STUPLES = "STUPLES"        # outer tuples joined (counter)
RESULTS = "RESULTS"        # global match count (RESULT_COUNTER analog)
BPBUILD = "BPBUILD"        # bucket-path build phase timer (hash_join)
BPPROBE = "BPPROBE"        # bucket-path probe phase timer
BPBUILDTUPLES = "BPBUILDTUPLES"  # tuples hashed into build buckets
BPPROBETUPLES = "BPPROBETUPLES"  # tuples probed against the buckets
RETRIES = "RETRIES"        # engine capacity-regrow attempts superseded
                           # (hash_join rollback; distinct from the
                           # robustness layer's RETRYN policy attempts)
MWINPUTCNT = "MWINPUTCNT"  # logical block transfers shuffled (MPI_Put count analog)
MWINBYTES = "MWINBYTES"    # shuffle wire bytes incl. padding (8B/tuple slots)
WIREBYTES = "WIREBYTES"    # actual wire bytes shipped per exchange under the
                           # active codec (== MWINBYTES when codec="off";
                           # smaller under the bit-packed format)
PACKRATIO = "PACKRATIO"    # gauge: packed wire bytes as a percent of the raw
                           # two/three-lane format (100 = no compression)
XSTAGES = "XSTAGES"        # gauge: column groups per staged exchange (1 = fused)
WINCAPR = "WINCAPR"        # per-(sender,dest) block capacity, inner window
WINCAPS = "WINCAPS"        # per-(sender,dest) block capacity, outer window
FINJECT = "FINJECT"        # injected faults fired (robustness/faults.py)
RETRYN = "RETRYN"          # robustness-layer retry attempts (robustness/retry.py)
BACKOFFMS = "BACKOFFMS"    # total retry backoff slept, milliseconds
CKPTSAVE = "CKPTSAVE"      # checkpoints written (robustness/checkpoint.py)
CKPTLOAD = "CKPTLOAD"      # checkpoints resumed from
GRIDPAIRS = "GRIDPAIRS"    # chunk pairs actually probed by chunked_join_grid
                           # (resume skips completed pairs — see ops/chunked.py)
PREFETCH = "PREFETCH"      # chunks staged by the grid prefetch thread before
                           # the consuming pair asked for them (ops/chunked.py
                           # pipelined mode; each carries a "prefetch" span)
SORTREUSE = "SORTREUSE"    # grid pair probes that reused the row's presorted
                           # inner chunk instead of re-sorting the packed
                           # union — rows x (cols - 1) on a full grid
VCHK = "VCHK"              # integrity-verification timing tag (times_us ONLY:
                           # summary() merges counters over times on a shared
                           # key, so the check count lives under VCHKN)
VCHKN = "VCHKN"            # integrity checksum comparisons performed
VFAIL = "VFAIL"            # checksum mismatches detected (robustness/verify.py)
VREPAIR = "VREPAIR"        # damaged partitions recomputed under --verify repair
QADMIT = "QADMIT"          # queries admitted by the service queue
QREJECT = "QREJECT"        # queries rejected at admission (depth / quota)
QDEADLINE = "QDEADLINE"    # queries cancelled by their deadline
QWARM = "QWARM"            # warm queries (capacity-cache hit: no sizing pass)
QDEGRADED = "QDEGRADED"    # queries served by the degraded fallback engine
BRKTRIP = "BRKTRIP"        # circuit-breaker trips (closed/half-open -> open)
BRKPROBE = "BRKPROBE"      # half-open health probes dispatched
PLANDRIFT = "PLANDRIFT"    # gauge: |actual - predicted| JTOTAL as a percent of
                           # the planner's prediction (planner/audit.py) — the
                           # plan-vs-actual closed-loop signal; lower is better
WDOGTRIP = "WDOGTRIP"      # hang-watchdog trips (observability/watchdog.py)
PMBUNDLE = "PMBUNDLE"      # forensics bundles written (observability/postmortem)
MEPOCH = "MEPOCH"          # gauge: current membership epoch (robustness/
                           # membership.py) — bumps fence out stale collectives
RANKLOST = "RANKLOST"      # ranks declared lost on lease lapse (membership.py)
RECOVERN = "RECOVERN"      # partitions recomputed during elastic recovery
                           # (robustness/recovery.py); < the total partition
                           # count means resume was partition-granular
RECOVERMS = "RECOVERMS"    # total elastic-recovery wall milliseconds (detect ->
                           # re-plan -> recompute -> splice)
RANKJOIN = "RANKJOIN"      # ranks admitted from a `joining` lease — the growth
                           # mirror of RANKLOST (robustness/membership.py)
HEDGED = "HEDGED"          # straggler hedges launched: speculative out-of-band
                           # recomputes of a slow-but-alive rank's unfinished
                           # partitions (robustness/straggler.py)
HEDGEWIN = "HEDGEWIN"      # hedged partitions whose speculative recompute won
                           # the manifest's first-writer-wins fence — the
                           # original never double-counts past these
SPECWASTE = "SPECWASTE"    # hedged partitions whose claim LOST (the original
                           # owner's realized line landed first): wasted
                           # speculative work, the hedging overhead gauge
JXAUDIT = "JXAUDIT"        # gauge: live graftcheck (jaxpr IR audit) findings
                           # on the traced entry points — the static twin of
                           # the lint gate; lower is better, clean repo holds 0
STATICMEM = "STATICMEM"    # gauge: static live-set peak bytes of the traced
                           # fused pipeline (analysis/jaxpr/memory.py) — plan
                           # geometry descriptor feeding the feasibility gate
NCOMPILE = "NCOMPILE"      # backend compiles observed via jax.monitoring
                           # (observability/compilemon.py); a resident serve
                           # session recompiling after warmup is a storm
COMPILEMS = "COMPILEMS"    # total backend-compile wall milliseconds (the
                           # counter twin of the JCOMPILE bracket: hears
                           # every compile, not just the bracketed one)
PARTPASS = "PARTPASS"      # fused (pallas) radix-partition passes selected at
                           # trace time (ops/radix.py); one per traced scatter/
                           # reorder site, so a recompiling session ticks it
                           # per program build, not per execution
PARTFALLBACK = "PARTFALLBACK"  # partition/histogram auto-select fell back to
                           # the XLA sort path (Pallas unavailable or fanout
                           # past MAX_PARTITIONS) — the silent-degrade signal;
                           # more of these on a TPU backend is a regression
SORTPASS = "SORTPASS"      # Pallas LSD radix sorts selected at trace time
                           # (ops/sorting.py resolve_sort_impl); one per
                           # traced sort site, like PARTPASS
SORTFALLBACK = "SORTFALLBACK"  # sort auto-select degraded to lax.sort
                           # (Pallas unavailable on this backend) — ticked
                           # ONCE per process (the decision is per-process,
                           # not per-sort) and paired with a log-once
                           # stderr line; 1 on a TPU backend is a regression
FAILOVER = "FAILOVER"      # fleet queries failed over to another worker after
                           # the routed worker died mid-query (service/fleet.py)
REPLAYN = "REPLAYN"        # journal intents replayed (failover retries plus
                           # restart-time unacknowledged-intent replay)
WINCARN = "WINCARN"        # fleet worker incarnations spawned (boot + restarts)
WRESTART = "WRESTART"      # dead-worker restarts (WINCARN minus the boot pool)
JDEPTH = "JDEPTH"          # gauge: peak unacknowledged query-journal depth
DOUBLEEXEC = "DOUBLEEXEC"  # fingerprints with >1 journaled outcome — the
                           # exactly-once invariant; any nonzero is a bug
RCHIT = "RCHIT"            # result-cache hits: queries short-circuited by a
                           # content-fingerprint match before admission
                           # (service/resultcache.py); the whole-result
                           # amortization win — fewer at the same traffic
                           # means repeated work stopped deduping
RCMISS = "RCMISS"          # result-cache misses (cold content, TTL expiry,
                           # or a digest/epoch check dropping a stale entry)
BATCHN = "BATCHN"          # fused micro-batches dispatched as ONE device
                           # program (service/microbatch.py); scenario-
                           # shaped — the fuse ratio BATCHQ/BATCHN is the
                           # gated observable, not the raw count
BATCHQ = "BATCHQ"          # queries served through fused micro-batches
                           # (each batch of k ticks this k times)
DELTAMERGE = "DELTAMERGE"  # queries served O(N+Δ): delta sorted + merged
                           # into the device-resident sorted union instead
                           # of re-sorting the full relation
                           # (service/resident.py + ops/merge_delta.py)
RESBYTES = "RESBYTES"      # gauge: device-resident sorted-union bytes held
                           # by the resident-state manager (bounded by
                           # ServiceConfig.resident_budget_bytes)
JRATE = "JRATE"            # derived: (R+S) tuples / JTOTAL second
JPROCRATE = "JPROCRATE"    # derived: (R+S) tuples / JPROC second
HILOCRATE = "HILOCRATE"    # derived: inner tuples / JHIST second
HOLOCRATE = "HOLOCRATE"    # derived: outer tuples / JHIST second


class Measurements:
    """Per-process measurement registry.

    ``init`` -> ``Measurements::init`` (Measurements.cpp:707-749) minus the
    MPI_Bcast of the experiment id (single-process drivers name their own).
    """

    def __init__(self, node_id: int = 0, num_nodes: int = 1,
                 tag: str = "experiment"):
        self.node_id = node_id
        self.num_nodes = num_nodes
        self.tag = tag
        self._starts: Dict[str, float] = {}
        self.times_us: Dict[str, float] = defaultdict(float)
        self.counters: Dict[str, int] = defaultdict(int)
        self._tracer = None
        # paired wall/monotonic anchors, taken back to back: perf_counter is
        # not comparable across processes, so every timestamp this registry
        # emits carries an epoch-relative twin — the alignment key merged
        # multi-rank timelines sort by (observability/timeline.py)
        self._mono0 = time.perf_counter()
        self.meta: Dict[str, object] = {
            "host": socket.gethostname(),
            "node": node_id,
            "nodes": num_nodes,
            "epoch_s": time.time(),
        }
        # always-on flight recorder (observability/flightrec.py): every
        # start/stop/incr/event below mirrors into this bounded ring with
        # no opt-in flag — the black box a post-mortem bundle freezes and
        # the idle clock the hang watchdog polls.  Deliberately NOT gated
        # on a tracer/config: the downed-tunnel failure mode left nothing
        # behind precisely because recording was opt-in.
        from tpu_radix_join.observability.flightrec import FlightRecorder
        self.flightrec = FlightRecorder(epoch_s=self.meta["epoch_s"],
                                        mono_s=self._mono0)

    # ------------------------------------------------------------ span tracer
    def attach_tracer(self, tracer=None, trace_id=None, **tags):
        """Attach (or build) an observability.SpanTracer sharing this
        registry's clock anchors: every ``start``/``stop`` pair then mirrors
        into a timeline span and every :meth:`event` into an instant event.
        Returns the tracer.

        ``trace_id`` is the join-level trace identity (rank 0 mints one,
        peers adopt it over the lease-dir channel) — it lands in the span
        file metadata, ``meta["trace_id"]``, and the flight-recorder
        context, so span files, ledger rows, and forensics bundles all
        join on the same key."""
        if tracer is None:
            from tpu_radix_join.observability.spans import SpanTracer
            tracer = SpanTracer(rank=self.node_id, trace_id=trace_id,
                                tags=tags,
                                epoch_s=self.meta["epoch_s"],
                                mono_s=self._mono0)
        self.meta["trace_id"] = tracer.trace_id
        self.flightrec.set_context(trace_id=tracer.trace_id)
        self._tracer = tracer
        return tracer

    @property
    def tracer(self):
        return self._tracer

    def set_trace_tags(self, **tags) -> None:
        """Stamp tags (plan strategy, engine, ...) onto future spans; a
        no-op without an attached tracer."""
        if self._tracer is not None:
            self._tracer.set_tags(**tags)

    def span(self, name: str, **args):
        """Timeline-only span context (grid pairs, checkpoint writes):
        shows on the trace without minting a ``times_us`` tag per instance
        — per-pair tags would make .perf files unbounded.  Always mirrors
        into the flight-recorder ring (the tracer remains opt-in)."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            self.flightrec.record("span", name, **args)
            try:
                if self._tracer is not None:
                    with self._tracer.span(name, **args):
                        yield
                else:
                    yield
            finally:
                self.flightrec.record("span_end", name)

        return _ctx()

    # ----------------------------------------------------------------- timers
    def start(self, key: str) -> None:
        self._starts[key] = time.perf_counter()
        self.flightrec.record("begin", key)
        if self._tracer is not None:
            self._tracer.begin(key)

    def stop(self, key: str, fence=None) -> float:
        """Stop a timer; ``fence`` (any pytree of jax arrays) is
        block_until_ready'd first so async device work is included — the
        equivalent of the reference's MPI barrier + gettimeofday pairing
        (Measurements.cpp:90-134)."""
        if fence is not None:
            jax.block_until_ready(fence)
        dt = (time.perf_counter() - self._starts.pop(key)) * 1e6
        self.times_us[key] += dt
        self.flightrec.record("end", key, us=round(dt, 1))
        if self._tracer is not None:
            # the span records the real wall interval; exclude_from_running
            # shifts only the accumulated column (a compile excluded from
            # JTOTAL still happened on the timeline, under its own span)
            self._tracer.end(key)
        return dt

    def add_time_us(self, key: str, us: float) -> None:
        self.times_us[key] += us

    def exclude_from_running(self, us: float) -> None:
        """Shift every currently-running timer's start forward by ``us`` so an
        interval that must not land in their columns (XLA compilation — the
        reference's phase timers contain no compile because none exists at
        runtime, Measurements.cpp:137-141) is excluded from whatever spans it
        (JTOTAL, SWINALLOC).  JCOMPILE keeps the time under its own tag."""
        for k in self._starts:
            self._starts[k] += us / 1e6

    def incr(self, key: str, by: int = 1) -> None:
        self.counters[key] += by
        self.flightrec.record("incr", key, by=by, total=self.counters[key])

    def event(self, name: str, **data) -> None:
        """Append a trace event to ``meta["events"]`` (lands in the
        ``<rank>.info`` JSON).  The robustness layer records faults fired,
        retries taken, and checkpoints written here so a post-mortem can
        reconstruct the failure/recovery timeline without logs; values must
        be JSON-serializable.

        Timestamps: ``t_s`` is this process's raw monotonic clock (kept for
        artifact compatibility, NOT comparable across processes) and
        ``t_epoch_s`` its wall-clock twin via the init-time anchor pair —
        the field merged multi-rank timelines align on."""
        now = time.perf_counter()
        events = self.meta.setdefault("events", [])
        events.append({"event": name,
                       "t_s": round(now, 6),
                       "t_epoch_s": round(
                           self.meta["epoch_s"] + (now - self._mono0), 6),
                       **data})
        self.flightrec.record("event", name, **data)
        if self._tracer is not None:
            self._tracer.instant(name, **data)

    # ----------------------------------------------------- detail accumulators
    def record_exchange(self, num_nodes: int, cap_r: int, cap_s: int,
                        tuple_bytes: int = 8,
                        wire_bytes: Optional[int] = None,
                        pack_ratio_pct: Optional[float] = None,
                        stages: Optional[int] = None) -> None:
        """Shuffle-detail counters (MEASUREMENT_DETAILS_NETWORK analog,
        Measurements.cpp:272-349): the reference counts every 64KB ``MPI_Put``
        and its bytes in the hot loop; here block geometry is static so the
        equivalent quantities are derived — per relation, each node ships N
        blocks of ``capacity`` wire tuples (window.block_all_to_all).
        ``tuple_bytes``: 8 for two uint32 lanes (the reference's
        CompressedTuple size), 12 when the key_hi lane travels too.

        ``wire_bytes``: actual bytes shipped per node per exchange under the
        active codec (packed block words x 4; defaults to the raw lane
        bytes when the codec is off).  ``pack_ratio_pct`` and ``stages`` are
        gauges describing the exchange plan (100 / 1 = codec off, fused)."""
        self.incr(MWINPUTCNT, 2 * num_nodes)
        raw_bytes = tuple_bytes * num_nodes * (cap_r + cap_s)
        self.incr(MWINBYTES, raw_bytes)
        self.incr(WIREBYTES,
                  raw_bytes if wire_bytes is None else int(wire_bytes))
        if pack_ratio_pct is not None:
            self.counters[PACKRATIO] = int(round(pack_ratio_pct))
        if stages is not None:
            self.counters[XSTAGES] = int(stages)
        self.counters[WINCAPR] = cap_r
        self.counters[WINCAPS] = cap_s
        # gauge assignments above bypass incr(); one ring record keeps the
        # exchange geometry visible in the flight recorder too
        self.flightrec.record(
            "gauge", "exchange", wirebytes=self.counters[WIREBYTES],
            pack_ratio_pct=self.counters.get(PACKRATIO),
            stages=self.counters.get(XSTAGES))

    def derive_rates(self) -> None:
        """Derived throughput tags (the HILOCRATE/HOLOCRATE pattern,
        Measurements.cpp:251-260: quantity / sub-phase time)."""
        tuples = self.counters.get(RTUPLES, 0) + self.counters.get(STUPLES, 0)
        for rate_key, time_key in ((JRATE, JTOTAL), (JPROCRATE, JPROC)):
            us = self.times_us.get(time_key, 0.0)
            if tuples and us > 0:
                self.counters[rate_key] = int(tuples / (us / 1e6))
        # histogram scan rates, tuples/s per side (the reference reports MB/s
        # over the same quantities, Measurements.cpp:251-260)
        jh = self.times_us.get(JHIST, 0.0)
        if jh > 0:
            for rate_key, cnt_key in ((HILOCRATE, RTUPLES),
                                      (HOLOCRATE, STUPLES)):
                cnt = self.counters.get(cnt_key, 0)
                if cnt:
                    self.counters[rate_key] = int(cnt / (jh / 1e6))

    def measure_dispatch_floor(self, iters: int = 20) -> float:
        """Record SDISPATCH: the amortized round-trip of dispatching one
        trivial program and fencing it — the floor every split-phase column
        (JMPI/JHIST/SLOCPREP/JPROC) pays per program through the host
        attachment.  On a tunnel-attached chip this is ~100ms and dominates
        small split columns (BASELINE r3 phase tables); readers subtract it
        to see work net of dispatch.  The reference keeps comparable
        "special" timers for accounting honesty (Measurements.cpp:176-178).
        Stored as a floor (assignment, not +=); returns microseconds."""
        import jax.numpy as jnp
        fn = jax.jit(lambda x: x + jnp.uint32(1))
        x = jnp.zeros((8,), jnp.uint32)
        jax.block_until_ready(fn(x))   # compile outside the timed loop
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(fn(x))
        us = (time.perf_counter() - t0) / iters * 1e6
        self.times_us[SDISPATCH] = us
        return us

    # ------------------------------------------------------- memory / tracing
    def memory_utilization(self) -> Dict[str, int]:
        """Host VmSize/VmRSS (printMemoryUtilization parity,
        Measurements.cpp:825-851) plus per-device HBM stats where the backend
        exposes them.  Values in bytes; also merged into ``meta``."""
        out: Dict[str, int] = {}
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith(("VmSize:", "VmRSS:")):
                        k, v = line.split(":", 1)
                        out[k] = int(v.split()[0]) * 1024
        except OSError:   # non-Linux host
            pass
        for i, dev in enumerate(jax.local_devices()):
            stats = getattr(dev, "memory_stats", lambda: None)()
            if stats and "bytes_in_use" in stats:
                out[f"device{i}_bytes_in_use"] = int(stats["bytes_in_use"])
        self.meta["memory"] = out
        return out

    def trace(self, trace_dir: str, record: bool = True):
        """Profiler context (PAPI/CUDA-event analog, Measurements.cpp:90-107 /
        eth.cu:179-222): wraps ``jax.profiler.trace`` AND, on exit, parses
        the written xplane artifact (performance/trace.py) so the
        jit-internal phase split (histogram/shuffle/probe/sort) becomes
        registry data, not just a TensorBoard file:

          * ``CTOTAL`` (times_us) — device busy time, the analog of the
            reference's PAPI total-cycles bracket (CTOTAL,
            Measurements.cpp:90-107);
          * ``meta["trace"]`` — the busiest-timeline per-op breakdown
            ({op: {us, count}}, heaviest first).

        ``record=False`` restores the bare passthrough."""
        if not record:
            return jax.profiler.trace(trace_dir)

        import contextlib

        @contextlib.contextmanager
        def _ctx():
            with jax.profiler.trace(trace_dir):
                yield self
            from tpu_radix_join.performance.trace import (
                _is_device_plane, summarize_trace)
            summary = summarize_trace(trace_dir)
            if summary is not None:
                self.meta["trace"] = summary
                # CTOTAL only from a real device timeline: a host plane's
                # busiest line sums nested Python frames, which is not a
                # cycles-analog (CPU-backend traces have no device plane)
                if _is_device_plane(summary["plane"]):
                    self.times_us["CTOTAL"] = summary["busy_us"]

        return _ctx()

    # ---------------------------------------------------------------- output
    def lines(self):
        """Tagged key/value/unit lines in the reference's .perf format
        (Measurements.cpp:136-142)."""
        for k in sorted(self.times_us):
            yield f"{k}\t{self.times_us[k]:.0f}\tus"
        for k in sorted(self.counters):
            yield f"{k}\t{self.counters[k]}\tcount"

    def store(self, out_dir: str) -> str:
        """Write ``<rank>.perf`` and ``<rank>.info`` (Measurements.cpp:707-770)."""
        self.derive_rates()
        os.makedirs(out_dir, exist_ok=True)
        perf = os.path.join(out_dir, f"{self.node_id}.perf")
        with open(perf, "w") as f:
            for line in self.lines():
                f.write(line + "\n")
        with open(os.path.join(out_dir, f"{self.node_id}.info"), "w") as f:
            json.dump(self.meta, f, indent=2)
        return perf

    def summary(self) -> Dict[str, float]:
        self.derive_rates()
        return {**{k: v for k, v in self.times_us.items()},
                **{k: float(v) for k, v in self.counters.items()}}

    # ----------------------------------------------------------- aggregation
    def _slim_meta(self) -> Dict[str, object]:
        """Truncated stand-in for an oversized meta in :meth:`gather_all`:
        never fail the report of an already-successful join over big
        metadata — drop the bulk but preserve the fields the aggregate
        report and timeline merge read (a truncated rank must not silently
        vanish from the [RESULTS] FailureClasses line)."""
        slim: Dict[str, object] = {"truncated": True}
        for k in ("failure_class", "epoch_s"):
            if k in self.meta:
                slim[k] = self.meta[k]
        if isinstance(self.meta.get("events"), list):
            slim["events_count"] = len(self.meta["events"])
        return slim

    def gather_all(self) -> List["Measurements"]:
        """Network gather of every process's registry — the analog of the
        reference's rank-0 result gather over MPI_Send/Recv
        (serializeResults/receiveAllMeasurements, Measurements.cpp:548-590).
        Replaces the shared-directory assumption of :meth:`load` for
        multi-process worlds: each process JSON-serializes its registry into
        a fixed-size byte buffer and an allgather hands every process all of
        them (rank 0 reports; the others get the same data for free, which
        the reference's point-to-point gather cannot do).  Single-process
        worlds return ``[self]`` without touching the runtime."""
        import jax as _jax
        if _jax.process_count() == 1:
            return [self]
        import numpy as np
        from jax.experimental import multihost_utils
        rec = {
            "node": self.node_id,
            "num_nodes": self.num_nodes,
            "times_us": self.times_us,
            "counters": self.counters,
            "meta": self.meta,
        }
        payload = json.dumps(rec, default=str).encode()
        cap = _GATHER_BUF_BYTES - 4
        if len(payload) > cap:
            rec["meta"] = self._slim_meta()
            payload = json.dumps(rec, default=str).encode()
        if len(payload) > cap:
            raise ValueError(
                f"measurement payload ({len(payload)}B) exceeds the "
                f"{cap}B gather buffer even without meta")
        buf = np.zeros(_GATHER_BUF_BYTES, np.uint8)
        buf[:4] = np.frombuffer(
            np.uint32(len(payload)).tobytes(), dtype=np.uint8)
        buf[4:4 + len(payload)] = np.frombuffer(payload, dtype=np.uint8)
        rows = np.asarray(multihost_utils.process_allgather(buf))
        out = []
        for row in rows:
            n = int(np.frombuffer(row[:4].tobytes(), dtype=np.uint32)[0])
            rec = json.loads(row[4:4 + n].tobytes().decode())
            m = Measurements(node_id=int(rec["node"]),
                             num_nodes=int(rec["num_nodes"]))
            m.times_us.update({k: float(v)
                               for k, v in rec["times_us"].items()})
            m.counters.update({k: int(v)
                               for k, v in rec["counters"].items()})
            m.meta = rec["meta"]
            out.append(m)
        return out

    @classmethod
    def load(cls, out_dir: str) -> List["Measurements"]:
        """Read every ``<rank>.perf`` in a directory back into registries —
        the file-based analog of the rank-0 result gather
        (serializeResults/receiveAllMeasurements, Measurements.cpp:548-590)."""
        out = []
        for name in sorted(os.listdir(out_dir)):
            if not name.endswith(".perf"):
                continue
            try:
                node_id = int(name[:-5])
            except ValueError:
                continue   # stray non-rank .perf file (e.g. notes.perf)
            m = cls(node_id=node_id)
            with open(os.path.join(out_dir, name)) as f:
                for line in f:
                    key, value, unit = line.rstrip("\n").split("\t")
                    if unit == "us":
                        m.times_us[key] = float(value)
                    else:
                        m.counters[key] = int(value)
            out.append(m)
        return out


def print_results(measurements: Iterable[Measurements],
                  file=None) -> Dict[str, Dict[str, float]]:
    """Rank-0 report: per-tag max/avg across nodes plus the ``[RESULTS]``
    line (printMeasurements, Measurements.cpp:592-702 — the reference prints
    per-rank phase columns and the total tuple count; max-over-ranks is the
    number that bounds the critical path in an SPMD phase).  Returns the
    aggregate dict it printed."""
    ms = list(measurements)
    agg: Dict[str, Dict[str, float]] = {}
    keys = sorted({k for m in ms for k in (*m.times_us, *m.counters)})
    for k in keys:
        vals = [m.times_us.get(k, m.counters.get(k, 0)) for m in ms]
        agg[k] = {"max": float(max(vals)), "avg": float(sum(vals) / len(vals))}
    print(f"[RESULTS] Nodes: {len(ms)}", file=file)
    total = sum(m.counters.get(RESULTS, 0) for m in ms) // max(1, len(ms))
    print(f"[RESULTS] Tuples: {total}", file=file)
    # per-rank failure classes (robustness/retry.py taxonomy, stamped into
    # meta by main.py): one degraded rank must be visible in the aggregate
    # summary, not only in that rank's own .info file.  "ok" ranks are
    # summarized; anything else is named rank by rank.
    classes = {m.node_id: str(m.meta.get("failure_class"))
               for m in ms if m.meta.get("failure_class") is not None}
    if classes:
        bad = {rank: c for rank, c in sorted(classes.items()) if c != "ok"}
        if bad:
            per_rank = " ".join(f"rank{rank}={c}" for rank, c in bad.items())
            print(f"[RESULTS] FailureClasses: {len(bad)}/{len(classes)} "
                  f"ranks not ok — {per_rank}", file=file)
        else:
            print(f"[RESULTS] FailureClasses: ok x{len(classes)}", file=file)
    # per-site fault-injection accounting (faults.FaultInjector.site_stats,
    # stamped into meta as "fault_sites" by main.py / the chaos runner): a
    # soak report must show which sites were exercised, not just that
    # FINJECT ticked.  Summed across ranks.
    sites: Dict[str, Dict[str, int]] = {}
    for m in ms:
        for site, st in (m.meta.get("fault_sites") or {}).items():
            acc = sites.setdefault(site, {"hits": 0, "fired": 0})
            acc["hits"] += int(st.get("hits", 0))
            acc["fired"] += int(st.get("fired", 0))
    if sites:
        per_site = " ".join(
            f"{site}={st['fired']}/{st['hits']}"
            for site, st in sorted(sites.items()))
        print(f"[RESULTS] FaultSites (fired/hits): {per_site}", file=file)
    for k in keys:
        unit = "us" if any(k in m.times_us for m in ms) else "count"
        print(f"[RESULTS] {k}: max {agg[k]['max']:.0f} {unit}, "
              f"avg {agg[k]['avg']:.0f} {unit}", file=file)
    return agg
