"""Phase timers, counters, and .perf-compatible reporting.

Replaces ``performance/Measurements.{h,cpp}`` (SURVEY.md §5.1): the
reference's ~60 static start/stop functions around `gettimeofday` + PAPI
cycles, compile-gated sub-timers, and per-rank ``<rank>.perf`` tag files
gathered to rank 0.

TPU design: a timer registry keyed by the reference's own tag vocabulary
(JTOTAL, JHIST, JMPI, JPROC, SWINALLOC, ...) so baseline comparison is
mechanical; fences are ``jax.block_until_ready`` (device work is async);
hardware-counter analogs come from ``jax.profiler`` traces rather than PAPI.
Everything under one jit cannot be phase-timed from the host, so phase timing
is honest at the granularity the driver actually executes (histogram program /
join program), with the jit-internal split available via profiler traces.
"""

from __future__ import annotations

import json
import os
import socket
import time
from collections import defaultdict
from typing import Dict, Optional

import jax

# Reference tag vocabulary (Measurements.cpp:136-142,176-178,351-368,533-542)
JTOTAL = "JTOTAL"          # end-to-end join wall time
JHIST = "JHIST"            # histogram phase
JMPI = "JMPI"              # network partitioning phase
JPROC = "JPROC"            # local processing phase
SWINALLOC = "SWINALLOC"    # window allocation (capacity measurement + compile)
SNETCOMPL = "SNETCOMPL"    # network completion wait
SLOCPREP = "SLOCPREP"      # local preparation


class Measurements:
    """Per-process measurement registry.

    ``init`` -> ``Measurements::init`` (Measurements.cpp:707-749) minus the
    MPI_Bcast of the experiment id (single-process drivers name their own).
    """

    def __init__(self, node_id: int = 0, num_nodes: int = 1,
                 tag: str = "experiment"):
        self.node_id = node_id
        self.num_nodes = num_nodes
        self.tag = tag
        self._starts: Dict[str, float] = {}
        self.times_us: Dict[str, float] = defaultdict(float)
        self.counters: Dict[str, int] = defaultdict(int)
        self.meta: Dict[str, object] = {
            "host": socket.gethostname(),
            "node": node_id,
            "nodes": num_nodes,
        }

    # ----------------------------------------------------------------- timers
    def start(self, key: str) -> None:
        self._starts[key] = time.perf_counter()

    def stop(self, key: str, fence=None) -> float:
        """Stop a timer; ``fence`` (any pytree of jax arrays) is
        block_until_ready'd first so async device work is included — the
        equivalent of the reference's MPI barrier + gettimeofday pairing
        (Measurements.cpp:90-134)."""
        if fence is not None:
            jax.block_until_ready(fence)
        dt = (time.perf_counter() - self._starts.pop(key)) * 1e6
        self.times_us[key] += dt
        return dt

    def add_time_us(self, key: str, us: float) -> None:
        self.times_us[key] += us

    def incr(self, key: str, by: int = 1) -> None:
        self.counters[key] += by

    # ---------------------------------------------------------------- output
    def lines(self):
        """Tagged key/value/unit lines in the reference's .perf format
        (Measurements.cpp:136-142)."""
        for k in sorted(self.times_us):
            yield f"{k}\t{self.times_us[k]:.0f}\tus"
        for k in sorted(self.counters):
            yield f"{k}\t{self.counters[k]}\tcount"

    def store(self, out_dir: str) -> str:
        """Write ``<rank>.perf`` and ``<rank>.info`` (Measurements.cpp:707-770)."""
        os.makedirs(out_dir, exist_ok=True)
        perf = os.path.join(out_dir, f"{self.node_id}.perf")
        with open(perf, "w") as f:
            for line in self.lines():
                f.write(line + "\n")
        with open(os.path.join(out_dir, f"{self.node_id}.info"), "w") as f:
            json.dump(self.meta, f, indent=2)
        return perf

    def summary(self) -> Dict[str, float]:
        return {**{k: v for k, v in self.times_us.items()},
                **{k: float(v) for k, v in self.counters.items()}}
