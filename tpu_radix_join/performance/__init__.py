from tpu_radix_join.performance.measurements import Measurements, print_results

__all__ = ["Measurements", "print_results"]
