from tpu_radix_join.performance.measurements import Measurements

__all__ = ["Measurements"]
