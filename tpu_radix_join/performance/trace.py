"""Profiler-trace parsing: per-op device time from ``jax.profiler`` traces.

The hardware-counter analog the reference gets from PAPI (total cycles
bracketing the join, ``performance/Measurements.cpp:90-107`` -> ``CTOTAL``)
and from CUDA events around each kernel (``operators/gpu/eth.cu:179-222``).
A TPU program is one XLA binary, so the equivalent visibility comes from the
profiler's trace: per-op rows on the device timeline.  This module turns the
``*.xplane.pb`` artifacts ``jax.profiler.trace`` writes into:

  * ``CTOTAL`` — device busy time (the busiest device timeline's summed event
    durations), the cycles-analog recorded into ``.perf`` via
    :meth:`Measurements.trace`;
  * a per-op breakdown ({op name: total time, count}) — the evidence for
    claims like "the fused 16M pipeline is >= 95% sort" (VERDICT r3 weak #2's
    last unverified link).

The xplane file is a protobuf (tensorflow/tsl XSpace), but importing
tensorflow for five field numbers is a heavy, fragile dependency — this is a
minimal wire-format decoder instead, hardcoding the XSpace schema:

  XSpace.planes = 1;  XPlane{ name = 2, lines = 3, event_metadata = 4 }
  XLine{ name = 2, display_name = 11, events = 4 }
  XEvent{ metadata_id = 1, duration_ps = 3, num_occurrences = 5 }
  XEventMetadata map entry{ key = 1, value = 2 };  XEventMetadata{ id = 1,
  name = 2, display_name = 4 }

(field numbers verified against tensorflow.tsl.profiler.protobuf.xplane_pb2
in this image; the schema is append-only so unknown fields are skipped by
wire type, which is exactly what protobuf guarantees is safe).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Iterator, List, Optional, Tuple


def _iter_fields(buf: memoryview) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over one message's bytes.
    Varints -> int; length-delimited -> memoryview; 32/64-bit -> raw bytes."""
    i, n = 0, len(buf)
    while i < n:
        tag = 0
        shift = 0
        while True:
            b = buf[i]
            i += 1
            tag |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        field, wire = tag >> 3, tag & 7
        if wire == 0:           # varint
            v = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wire, v
        elif wire == 2:         # length-delimited
            ln = 0
            shift = 0
            while True:
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wire, buf[i:i + ln]
            i += ln
        elif wire == 1:         # 64-bit
            yield field, wire, bytes(buf[i:i + 8])
            i += 8
        elif wire == 5:         # 32-bit
            yield field, wire, bytes(buf[i:i + 4])
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")


def _parse_line(buf: memoryview) -> Tuple[str, Dict[int, List[int]]]:
    """One XLine -> (name, {metadata_id: [total_ps, occurrences]})."""
    name = ""
    display = ""
    per_md: Dict[int, List[int]] = {}
    for field, wire, val in _iter_fields(buf):
        if field == 2 and wire == 2:
            name = bytes(val).decode(errors="replace")
        elif field == 11 and wire == 2:
            display = bytes(val).decode(errors="replace")
        elif field == 4 and wire == 2:    # XEvent
            md, dur, occ = 0, 0, 1
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1 and w2 == 0:
                    md = v2
                elif f2 == 3 and w2 == 0:
                    dur = v2
                elif f2 == 5 and w2 == 0:
                    occ = max(1, v2)
            acc = per_md.setdefault(md, [0, 0])
            acc[0] += dur
            acc[1] += occ
    return display or name, per_md


def _parse_plane(buf: memoryview) -> dict:
    """One XPlane -> {"name", "lines": [(line_name, {md: [ps, n]})],
    "metadata": {id: name}}."""
    name = ""
    lines = []
    metadata: Dict[int, str] = {}
    for field, wire, val in _iter_fields(buf):
        if field == 2 and wire == 2:
            name = bytes(val).decode(errors="replace")
        elif field == 3 and wire == 2:
            lines.append(_parse_line(val))
        elif field == 4 and wire == 2:    # map<int64, XEventMetadata> entry
            md_id, md_name, md_disp = 0, "", ""
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1 and w2 == 0:
                    md_id = v2
                elif f2 == 2 and w2 == 2:   # XEventMetadata
                    for f3, w3, v3 in _iter_fields(v2):
                        if f3 == 1 and w3 == 0:
                            md_id = v3
                        elif f3 == 2 and w3 == 2:
                            md_name = bytes(v3).decode(errors="replace")
                        elif f3 == 4 and w3 == 2:
                            md_disp = bytes(v3).decode(errors="replace")
            metadata[md_id] = md_disp or md_name
    return {"name": name, "lines": lines, "metadata": metadata}


def parse_xspace(data: bytes) -> List[dict]:
    """All XPlanes of one serialized XSpace."""
    return [_parse_plane(val)
            for field, wire, val in _iter_fields(memoryview(data))
            if field == 1 and wire == 2]


def find_xplane_files(trace_dir: str) -> List[str]:
    return sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True))


def is_device_plane(name: str) -> bool:
    """Whether an XPlane name denotes an accelerator (vs host) plane —
    the observability timeline uses this to pick the device track."""
    n = name.lower()
    return n.startswith("/device:") or "tpu" in n or "gpu" in n


_is_device_plane = is_device_plane


def summarize_trace(trace_dir: str) -> Optional[dict]:
    """Aggregate the trace directory into the device-op breakdown.

    Returns {"plane": name, "busy_us": float, "ops": {op: {"us", "count"}}}
    for the busiest device plane (falling back to the busiest plane of any
    kind — CPU-backend traces put XLA ops on host planes), or None when the
    directory holds no parsable xplane artifact."""
    best = None
    for path in find_xplane_files(trace_dir):
        with open(path, "rb") as f:
            planes = parse_xspace(f.read())
        for plane in planes:
            # busiest line = the execution timeline; other lines (launch,
            # framework annotations) overlap it
            busy = 0
            busy_line = None
            for line_name, per_md in plane["lines"]:
                tot = sum(ps for ps, _ in per_md.values())
                if tot > busy:
                    busy, busy_line = tot, per_md
            if busy_line is None:
                continue
            entry = {
                "plane": plane["name"],
                "busy_us": busy / 1e6,
                "ops": {
                    plane["metadata"].get(md, f"op_{md}"):
                        {"us": ps / 1e6, "count": n}
                    for md, (ps, n) in sorted(
                        busy_line.items(), key=lambda kv: -kv[1][0])
                },
            }
            rank = (1 if _is_device_plane(plane["name"]) else 0, busy)
            if best is None or rank > best[0]:
                best = (rank, entry)
    return best[1] if best else None


def top_ops(summary: dict, k: int = 12) -> List[Tuple[str, float, int]]:
    """[(op, total_us, count)] for the k heaviest ops of a summary."""
    items = [(name, v["us"], v["count"]) for name, v in summary["ops"].items()]
    items.sort(key=lambda t: -t[1])
    return items[:k]
