"""Host memory pool bound to the native bump allocator.

Python face of ``native/pool.cc`` — the replacement for ``memory/Pool.{h,cpp}``
(static region bump allocator, 64B aligned, overflow fallback, reset;
Pool.cpp:25-79).  ``get_array`` hands out numpy views into pool memory so
relation staging buffers are allocated once and reused across joins (the
reference allocates its relations the same way, Relation.cpp:33).

Falls back to plain numpy allocation when no C++ toolchain is present.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from tpu_radix_join.native.build import load


class Pool:
    """Aligned bump allocator over one native region.

    ``Pool(capacity_bytes)`` -> ``Pool::allocate`` (main.cpp:86-88 sizes it at
    1.1x the relation footprint; callers here choose their own factor).
    """

    def __init__(self, capacity_bytes: int):
        self._lib = load()
        self._handle = None
        self.capacity = int(capacity_bytes)
        if self._lib is not None:
            self._handle = self._lib.pool_create(self.capacity)
            if not self._handle:
                raise MemoryError(f"pool_create({self.capacity}) failed")
        self._fallback_allocs = []

    @property
    def native(self) -> bool:
        return self._handle is not None

    def get_array(self, shape, dtype=np.uint32) -> np.ndarray:
        """A numpy array backed by pool memory (Pool::getMemory).

        The returned array keeps the Pool alive (via its buffer's base), so
        views never dangle after the Pool object goes out of scope; only an
        explicit ``reset()``/``close()`` invalidates them.
        """
        dtype = np.dtype(dtype)
        n_bytes = int(np.prod(shape)) * dtype.itemsize
        if self._handle is None:
            arr = np.empty(shape, dtype)
            self._fallback_allocs.append(arr)
            return arr
        ptr = self._lib.pool_get_memory(self._handle, n_bytes)
        if not ptr:
            raise MemoryError(f"pool_get_memory({n_bytes}) failed")
        # ctypes array subclass instances accept attributes: pin the Pool to
        # the buffer object that numpy keeps as the array's base.
        buf_cls = type("PoolBuf", ((ctypes.c_uint8 * n_bytes),), {})
        buf = buf_cls.from_address(ptr)
        buf._pool_keepalive = self
        return np.frombuffer(buf, dtype=dtype).reshape(shape)

    def used(self) -> int:
        if self._handle is None:
            return sum(a.nbytes for a in self._fallback_allocs)
        return self._lib.pool_used(self._handle)

    def reset(self) -> None:
        """Rewind (Pool::reset) — previously returned arrays become invalid."""
        if self._handle is None:
            self._fallback_allocs.clear()
        else:
            self._lib.pool_reset(self._handle)

    def close(self) -> None:
        if self._handle is not None:
            self._lib.pool_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
