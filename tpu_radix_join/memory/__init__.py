from tpu_radix_join.memory.pool import Pool

__all__ = ["Pool"]
