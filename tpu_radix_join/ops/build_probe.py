"""Build-probe: match counting and materialization.

Replaces the CPU chained-bucket hash join (``tasks/BuildProbe.cpp:47-121``) and
the GPU probe kernel families (``operators/gpu/eth.cu:25-109``,
``kernels.cu:199-246`` SD::probe, ``kernels.cu:314-463`` probe_match_rate /
probe_count).  Pointer-chasing hash tables are hostile to TPUs (SURVEY.md
§7.2); the idiomatic equivalents provided here:

  * :func:`probe_count` — sort the inner side by key, then a dual
    ``searchsorted`` (left/right bounds) gives each outer tuple its exact,
    duplicate-aware match count.  ``method='sort'`` lowers to a concat+sort,
    fully parallel on the MXU-adjacent sort units; this is the default
    BuildProbe (`probe_count` analog, kernels.cu:423-463).
  * :func:`probe_count_bucketized` — after a radix pass each bucket is small
    and dense, so probe = per-bucket dense equality reduction, the analog of
    the shared-memory ``SD::probe`` that stages an R partition in shared memory
    and nested-loops S against it (kernels.cu:199-246).
  * :func:`probe_materialize` — emits matching (r_rid, s_rid) pairs up to a
    static per-outer-tuple cap with an overflow flag, the analog of
    ``probe_match_rate``'s per-thread ``matches[MAX_MATCH_RATE]`` buffer +
    retry flag ``pFlag`` (kernels.cu:314-411).

Padding contract: invalid slots carry side-specific sentinel keys
(R_PAD != S_PAD, tuples.py) so padding can never match padding or real tuples;
counts therefore need no extra masking.

Match counts are accumulated in uint32 per partition; partitions are summed on
host in uint64 (SURVEY.md §7.4 item 2 — avoids both int32 overflow and slow
TPU int64).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from tpu_radix_join.data.tuples import CompressedBatch, pad_sentinel
from tpu_radix_join.ops.sorting import (
    sort_kv_unstable,
    sort_lex_unstable,
    sort_unstable,
)


def _sort_key(comp: CompressedBatch) -> jnp.ndarray:
    """Single-lane comparable key for sort/searchsorted — 32-bit keys only.

    Wide (64-bit) keys have no single uint32 lane and device int64 is
    off-limits (SURVEY.md §7.4 item 3); every probe entry point routes them
    to the hi/lo lexicographic disciplines instead (``_wide_weights`` /
    ``merge_count_wide_per_partition``), so this helper is never reached
    with a wide batch."""
    assert comp.key_rem_hi is None, "wide keys take the lexicographic paths"
    return comp.key_rem


def _probe_bounds(r_keys: jnp.ndarray, s_keys: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(sorted r, left bounds, right bounds) for each s key."""
    r_sorted = sort_unstable(r_keys)
    lo = jnp.searchsorted(r_sorted, s_keys, side="left", method="sort")
    hi = jnp.searchsorted(r_sorted, s_keys, side="right", method="sort")
    return r_sorted, lo, hi


def _wide_union_scan(inner: CompressedBatch, outer: CompressedBatch,
                     *carried: jnp.ndarray):
    """Rank-space scan of the (hi, lo) union: the wide-key replacement for
    searchsorted, which has no pair-key form without a device uint64 lane
    (SURVEY.md §7.4 item 3).

    One three-key lexicographic sort of both sides — (hi, lo, side-tag), the
    tag keeping every equal-key run's R tuples ahead of its S tuples — then
    the cumsum/cummax pass of ops/merge_count.  At each OUTER position,
    ``[base, c_r)`` is exactly its matching inner index range in
    sorted-inner-only coordinates (all of a run's inner tuples precede its
    outer tuples, and inner relative order matches a standalone inner sort).

    ``carried`` lanes ([n_outer] each, padded with PAD_RID at inner slots)
    ride through the sort.  Returns (is_outer u32, base, c_r, *carried_sorted)
    — all int32 ranks except the uint32 tag/carried.
    """
    n_r = inner.size
    hi = jnp.concatenate([inner.key_rem_hi, outer.key_rem_hi])
    lo = jnp.concatenate([inner.key_rem, outer.key_rem])
    tag = jnp.concatenate([jnp.zeros((n_r,), jnp.uint32),
                           jnp.ones((outer.size,), jnp.uint32)])
    pad_lane = jnp.full((n_r,), 0xFFFFFFFF, jnp.uint32)
    carried_full = [jnp.concatenate([pad_lane, c]) for c in carried]
    out = sort_lex_unstable(hi, lo, tag, *carried_full, num_keys=3)
    hi, lo, tag, carried_sorted = out[0], out[1], out[2], out[3:]

    prev_hi = jnp.concatenate([jnp.full((1,), 0xFFFFFFFF, jnp.uint32), hi[:-1]])
    prev_lo = jnp.concatenate([jnp.full((1,), 0xFFFFFFFF, jnp.uint32), lo[:-1]])
    run_start = (hi != prev_hi) | (lo != prev_lo)
    is_r = (jnp.uint32(1) - tag).astype(jnp.int32)
    c_r = jnp.cumsum(is_r, dtype=jnp.int32)
    base_at_start = jnp.where(run_start, c_r - is_r, 0)
    base = jax.lax.cummax(base_at_start)
    return (tag, base, c_r) + tuple(carried_sorted)


def probe_count(inner: CompressedBatch, outer: CompressedBatch) -> jnp.ndarray:
    """Exact number of matching (r, s) pairs, as uint32.

    Handles duplicate keys on both sides (count per outer tuple = size of its
    equal-key run in the sorted inner side).  Padding sentinels fall out: no
    real or padded outer key ever equals an inner sentinel and vice versa.
    64-bit keys take the lexicographic union scan — no x64.
    """
    if inner.key_rem_hi is not None:
        tag, base, c_r = _wide_union_scan(inner, outer)
        return jnp.sum((tag.astype(jnp.int32) * (c_r - base)).astype(jnp.uint32))
    _, lo, hi = _probe_bounds(_sort_key(inner), _sort_key(outer))
    return jnp.sum((hi - lo).astype(jnp.uint32))


def _per_partition_counts(r_sorted: jnp.ndarray, s_keys: jnp.ndarray,
                          pid: jnp.ndarray, num_partitions: int):
    """Dual searchsorted against the sorted inner + pid-bincount: the shared
    counting core of the resident and chunked probes.  Returns
    ``(counts, max per-outer-tuple count)`` — the latter feeds the driver's
    uint32-overflow risk bound (hash_join._count_risk)."""
    lo = jnp.searchsorted(r_sorted, s_keys, side="left", method="sort")
    hi = jnp.searchsorted(r_sorted, s_keys, side="right", method="sort")
    per_s = (hi - lo).astype(jnp.uint32)
    counts = jnp.bincount(
        pid.astype(jnp.int32), weights=per_s, length=num_partitions
    ).astype(jnp.uint32)
    return counts, jnp.max(per_s)


def probe_count_per_partition(
    inner: CompressedBatch, outer: CompressedBatch,
    outer_pid: jnp.ndarray, num_partitions: int,
    return_max_weight: bool = False,
):
    """Per-partition match counts, uint32 [num_partitions].

    Keeps each accumulator < 2**32 so host-side uint64 summation is exact even
    at billions of total matches (see module docstring).  Wide keys carry the
    partition id through the union sort and weight-sum per partition.
    ``return_max_weight`` also returns the max single-outer-tuple match count
    (the overflow-risk bound input, see merge_count.merge_count_per_partition).
    """
    if inner.key_rem_hi is not None:
        tag, base, c_r, pid = _wide_union_scan(inner, outer, outer_pid)
        weight = tag.astype(jnp.int32) * (c_r - base)
        # inner slots carry the PAD_RID pid lane but tag=0 zeroes their weight
        counts = jnp.bincount(
            jnp.minimum(pid, jnp.uint32(num_partitions)).astype(jnp.int32),
            weights=weight.astype(jnp.uint32),
            length=num_partitions + 1)[:num_partitions].astype(jnp.uint32)
        if return_max_weight:
            return counts, jnp.max(weight).astype(jnp.uint32)
        return counts
    counts, maxw = _per_partition_counts(
        sort_unstable(_sort_key(inner)), _sort_key(outer), outer_pid,
        num_partitions)
    if return_max_weight:
        return counts, maxw
    return counts


def probe_count_chunked(
    inner: CompressedBatch, outer: CompressedBatch,
    outer_pid: jnp.ndarray, num_partitions: int, slab_size: int,
    return_max_weight: bool = False,
):
    """Per-partition counts with the outer side streamed in ``slab_size``
    slabs under ``lax.scan`` — the distributed realisation of the reference's
    LD (large-data) chunked probe (``iterCount``-indexed kernels,
    kernels.cu:778-856; data.hpp:13-20): the inner side is sorted once and
    stays resident; per-step working set is O(inner + slab) regardless of
    the outer buffer size.

    Identical results to :func:`probe_count_per_partition` (tested); the
    outer buffer is padded to a slab multiple with S-side sentinels, which
    match nothing by the pad-key contract (tuples.py).

    Wide keys: the narrow path's resident-sorted-inner + searchsorted trick
    has no pair-key form, so each slab runs the lexicographic union scan
    against the inner side instead — the inner re-sorts per slab (more
    compute), but the per-step working set keeps the LD contract:
    O(inner + slab) live sort buffers regardless of the outer size.
    """
    n = outer.size
    pad = (-n) % slab_size
    fill = int(pad_sentinel("outer"))
    if inner.key_rem_hi is not None:
        s_lo, s_hi = outer.key_rem, outer.key_rem_hi
        if pad:
            # pad BOTH lanes with the sentinel (the make_padding(wide=True)
            # contract): 0x00000000_FFFFFFFF would be a legal real key
            pad_lane = jnp.full((pad,), fill, jnp.uint32)
            s_lo = jnp.concatenate([s_lo, pad_lane])
            s_hi = jnp.concatenate([s_hi, pad_lane])
            outer_pid = jnp.concatenate(
                [outer_pid, jnp.zeros((pad,), outer_pid.dtype)])

        def step_wide(carry, slab):
            lo, hi, pid = slab
            slab_batch = CompressedBatch(key_rem=lo, rid=pid, key_rem_hi=hi)
            return carry, probe_count_per_partition(
                inner, slab_batch, pid, num_partitions,
                return_max_weight=True)

        _, (per_slab, maxw) = jax.lax.scan(
            step_wide, (), (s_lo.reshape(-1, slab_size),
                            s_hi.reshape(-1, slab_size),
                            outer_pid.reshape(-1, slab_size)))
        counts = jnp.sum(per_slab, axis=0, dtype=jnp.uint32)
        if return_max_weight:
            return counts, jnp.max(maxw)
        return counts

    r_sorted = sort_unstable(_sort_key(inner))
    sk = _sort_key(outer)
    if pad:
        sk = jnp.concatenate([sk, jnp.full((pad,), fill, sk.dtype)])
        outer_pid = jnp.concatenate(
            [outer_pid, jnp.zeros((pad,), outer_pid.dtype)])
    slabs = sk.reshape(-1, slab_size)
    pids = outer_pid.reshape(-1, slab_size)

    def step(carry, slab):
        keys, pid = slab
        # carry stays empty: emitting per-slab counts (summed below) keeps the
        # accumulator's sharding derived from the inputs, which an unvarying
        # zeros-carry would violate inside shard_map.
        return carry, _per_partition_counts(r_sorted, keys, pid,
                                            num_partitions)

    _, (per_slab, maxw) = jax.lax.scan(step, (), (slabs, pids))
    counts = jnp.sum(per_slab, axis=0, dtype=jnp.uint32)
    if return_max_weight:
        return counts, jnp.max(maxw)
    return counts


# Above this per-bucket slot count, the O(bi*bo) dense compare loses to the
# batched sort-merge (the dense form is the reference's shared-memory probe
# trade, profitable only for buckets that fit "shared memory"-sized tiles).
DENSE_BUCKET_LIMIT = 256


def probe_count_bucketized(
    inner_blocks: jnp.ndarray, outer_blocks: jnp.ndarray,
    inner_hi: jnp.ndarray | None = None,
    outer_hi: jnp.ndarray | None = None,
    return_max_weight: bool = False,
):
    """Per-bucket match counts, uint32 [nb], for sentinel-padded key blocks
    inner_blocks [nb, bi] / outer_blocks [nb, bo] (wide keys add the matching
    hi-lane blocks).

    Auto-selects the discipline: the O(bi*bo) dense equality reduction (the
    GPU shared-memory probe analog, kernels.cu:199-246) for tiny buckets,
    else the batched per-bucket sort-merge — O(b log b) rows under one
    batched ``lax.sort``, which keeps the two-level path feasible when
    capacity-padded buckets are large.  ``return_max_weight`` also returns
    the max single-outer-tuple match count (overflow-risk bound input;
    a bucket's count is statically <= bi * bo, so callers only need this
    when that product can reach 2**32).
    """
    if max(inner_blocks.shape[1], outer_blocks.shape[1]) <= DENSE_BUCKET_LIMIT:
        eq = inner_blocks[:, :, None] == outer_blocks[:, None, :]
        if inner_hi is not None:
            eq &= inner_hi[:, :, None] == outer_hi[:, None, :]
        counts = jnp.sum(eq.astype(jnp.uint32), axis=(1, 2))
        if return_max_weight:
            return counts, jnp.max(jnp.sum(eq.astype(jnp.uint32), axis=1))
        return counts
    return probe_count_bucketized_merge(inner_blocks, outer_blocks,
                                        inner_hi, outer_hi,
                                        return_max_weight=return_max_weight)


def bucket_rows_sort(
    inner_blocks: jnp.ndarray, outer_blocks: jnp.ndarray,
    inner_hi: jnp.ndarray | None = None,
    outer_hi: jnp.ndarray | None = None,
):
    """BUILD stage of the bucketized merge probe: one batched lexicographic
    row sort of the concatenated (inner | outer) bucket rows — (key, tag) or
    (hi, lo, tag) for wide keys.  The sorted-row layout is this framework's
    "hash table": the structure the probe scan walks, making the stage the
    honest analog of the reference's per-task hash-table build (BPBUILD,
    tasks/BuildProbe.cpp:47-77 / Measurements.cpp:471-505).  Returns the
    sorted lanes ``(keys, tag)`` or ``(his, keys, tag)`` for
    :func:`bucket_rows_count`."""
    keys = jnp.concatenate([inner_blocks, outer_blocks], axis=1)
    tag = jnp.concatenate([
        jnp.zeros(inner_blocks.shape, jnp.uint32),
        jnp.ones(outer_blocks.shape, jnp.uint32)], axis=1)
    if inner_hi is not None:
        his = jnp.concatenate([inner_hi, outer_hi], axis=1)
        return sort_lex_unstable(his, keys, tag, num_keys=3, dimension=1)
    return sort_lex_unstable(keys, tag, num_keys=2, dimension=1)


def bucket_rows_count(*sorted_lanes, return_max_weight: bool = False):
    """PROBE stage of the bucketized merge probe: the merge-count weight
    scan (cumsum/cummax of ops/merge_count) along pre-sorted bucket rows
    from :func:`bucket_rows_sort` — the analog of the reference's per-task
    probe loop (BPPROBE, tasks/BuildProbe.cpp:79-121 /
    Measurements.cpp:506-542).  R/S pad sentinels differ (tuples.py), so
    padding forms its own runs and contributes zero."""
    from tpu_radix_join.ops.merge_count import _run_weights
    fill = jnp.full((sorted_lanes[0].shape[0], 1), 0xFFFFFFFF, jnp.uint32)
    if len(sorted_lanes) == 3:
        his, keys, tag = sorted_lanes
        prev_hi = jnp.concatenate([fill, his[:, :-1]], axis=1)
        prev_lo = jnp.concatenate([fill, keys[:, :-1]], axis=1)
        run_start = (his != prev_hi) | (keys != prev_lo)
    else:
        keys, tag = sorted_lanes
        run_start = keys != jnp.concatenate([fill, keys[:, :-1]], axis=1)
    # vmap the 1-D weight scan over bucket rows (cumsum/cummax are along the
    # row, independent per bucket)
    weights = jax.vmap(_run_weights)(tag, run_start)
    counts = jnp.sum(weights, axis=1, dtype=jnp.uint32)
    if return_max_weight:
        return counts, jnp.max(weights)
    return counts


def probe_count_bucketized_merge(
    inner_blocks: jnp.ndarray, outer_blocks: jnp.ndarray,
    inner_hi: jnp.ndarray | None = None,
    outer_hi: jnp.ndarray | None = None,
    return_max_weight: bool = False,
):
    """Batched per-bucket sort-merge counting (same contract as
    :func:`probe_count_bucketized`): :func:`bucket_rows_sort` (the build
    stage) + :func:`bucket_rows_count` (the probe scan) fused in one
    program — the phase-split driver runs the two stages as separate
    programs to time BPBUILD/BPPROBE from the host clock.
    """
    sorted_lanes = bucket_rows_sort(inner_blocks, outer_blocks,
                                    inner_hi, outer_hi)
    return bucket_rows_count(*sorted_lanes,
                             return_max_weight=return_max_weight)


class MaterializedMatches(NamedTuple):
    r_rid: jnp.ndarray      # uint32 [n_outer * cap]
    s_rid: jnp.ndarray      # uint32 [n_outer * cap]
    valid: jnp.ndarray      # bool   [n_outer * cap]
    overflow: jnp.ndarray   # uint32 — tuples whose match count exceeded cap


def probe_materialize(
    inner: CompressedBatch, outer: CompressedBatch, cap: int
) -> MaterializedMatches:
    """Materialize matching rid pairs, up to ``cap`` matches per outer tuple.

    The analog of ``probe_match_rate`` (kernels.cu:314-411): a static output
    buffer (``n_outer * cap`` pairs for 32-bit keys, union-length x cap for
    wide — inner positions emit valid=False rows) plus an overflow indicator
    standing in for the kernel's retry flag ``pFlag``.  Wide keys: the
    union scan's [base, c_r) ranks index sorted-inner order directly, no
    searchsorted, no x64.
    """
    k = jnp.arange(cap, dtype=jnp.int32)[None, :]              # [1, cap]
    if inner.key_rem_hi is not None:
        _, _, r_rid_sorted = sort_lex_unstable(
            inner.key_rem_hi, inner.key_rem, inner.rid, num_keys=2)
        tag, base, c_r, s_rid_sorted = _wide_union_scan(inner, outer,
                                                        outer.rid)
        is_outer = tag.astype(jnp.int32)
        idx = base[:, None] + k                                # [n_union, cap]
        valid = (idx < c_r[:, None]) & (is_outer[:, None] == 1)
        idx = jnp.minimum(idx, inner.size - 1)
        r_rid = r_rid_sorted[idx]
        s_rid = jnp.broadcast_to(s_rid_sorted[:, None], idx.shape)
        overflow = jnp.sum((((c_r - base) > cap) & (is_outer == 1))
                           .astype(jnp.uint32))
        return MaterializedMatches(
            r_rid=r_rid.reshape(-1), s_rid=s_rid.reshape(-1),
            valid=valid.reshape(-1), overflow=overflow,
        )
    r_sorted, r_rid_sorted = sort_kv_unstable(_sort_key(inner), inner.rid)
    r_rid, s_rid, valid, overflow = _materialize_rows_narrow(
        r_sorted, r_rid_sorted, _sort_key(outer), outer.rid, cap)
    return MaterializedMatches(
        r_rid=r_rid.reshape(-1), s_rid=s_rid.reshape(-1),
        valid=valid.reshape(-1), overflow=overflow,
    )


def _materialize_rows_narrow(r_sorted, r_rid_sorted, outer_keys, outer_rids,
                             cap: int):
    """Narrow-key materialization core against a pre-sorted inner side:
    ([n, cap] r_rid, [n, cap] s_rid, [n, cap] valid, overflow) — shared by
    the resident probe and each slab of the chunked probe."""
    k = jnp.arange(cap, dtype=jnp.int32)[None, :]              # [1, cap]
    lo = jnp.searchsorted(r_sorted, outer_keys, side="left", method="sort")
    hi = jnp.searchsorted(r_sorted, outer_keys, side="right", method="sort")
    idx = lo[:, None] + k                                      # [n, cap]
    valid = idx < hi[:, None]
    idx = jnp.minimum(idx, r_sorted.shape[0] - 1)
    r_rid = r_rid_sorted[idx]
    s_rid = jnp.broadcast_to(outer_rids[:, None], idx.shape)
    overflow = jnp.sum(((hi - lo) > cap).astype(jnp.uint32))
    return r_rid, s_rid, valid, overflow


def probe_materialize_chunked(
    inner: CompressedBatch, outer: CompressedBatch, cap: int, slab_size: int
) -> MaterializedMatches:
    """Materializing probe with the outer side streamed in ``slab_size``
    slabs under ``lax.scan`` — the output-producing form of the reference's
    LD chunked kernels, which write match arrays per ``iterCount`` chunk
    (kernels.cu:778-856: probe writes R[], S[] output columns per chunk).

    Same contract and output size as :func:`probe_materialize` for narrow
    keys (``n_outer_padded * cap`` rows); wide-key output is also
    ``n_outer_padded * cap`` — each slab's union-scan rows are compacted
    back to slab positions before stacking, so shrinking the slab (the
    out-of-core lever) never inflates the result buffer.  The per-step
    intermediate working set is O(inner + slab) instead of
    O(inner + outer).  The outer buffer is padded to a slab multiple with S
    sentinels (match nothing, valid=False); overflow is summed across slabs.
    """
    n = outer.size
    pad = (-n) % slab_size
    fill = int(pad_sentinel("outer"))
    wide = inner.key_rem_hi is not None

    def padded(lane, fill_value):
        if not pad:
            return lane
        return jnp.concatenate(
            [lane, jnp.full((pad,), fill_value, lane.dtype)])

    s_rid = padded(outer.rid, 0xFFFFFFFF)
    s_lo = padded(outer.key_rem, fill)
    if wide:
        s_hi = padded(outer.key_rem_hi, fill)
        # inner sorted once, resident across slabs (matches the narrow path)
        _, _, r_rid_sorted = sort_lex_unstable(
            inner.key_rem_hi, inner.key_rem, inner.rid, num_keys=2)
        k = jnp.arange(cap, dtype=jnp.int32)[None, :]
        pos_lane = jnp.arange(slab_size, dtype=jnp.uint32)

        def step_wide(carry, slab):
            lo, hi, rid = slab
            sb = CompressedBatch(key_rem=lo, rid=rid, key_rem_hi=hi)
            tag, base, c_r, rid_sorted, pos_sorted = _wide_union_scan(
                inner, sb, rid, pos_lane)
            is_outer = tag.astype(jnp.int32)
            idx = base[:, None] + k                    # [n_r + slab, cap]
            valid = (idx < c_r[:, None]) & (is_outer[:, None] == 1)
            idx = jnp.minimum(idx, inner.size - 1)
            rows_r = r_rid_sorted[idx]
            rows_s = jnp.broadcast_to(rid_sorted[:, None], idx.shape)
            # compact union rows back to slab positions: inner rows carry the
            # PAD_RID pos lane (out of range) and drop
            pos = jnp.where(tag == 1, pos_sorted, jnp.uint32(slab_size))
            shape = (slab_size, cap)
            out_r = jnp.zeros(shape, jnp.uint32).at[pos].set(
                rows_r, mode="drop")
            out_s = jnp.zeros(shape, jnp.uint32).at[pos].set(
                rows_s, mode="drop")
            out_v = jnp.zeros(shape, bool).at[pos].set(valid, mode="drop")
            ovf = jnp.sum((((c_r - base) > cap) & (is_outer == 1))
                          .astype(jnp.uint32))
            return carry, (out_r.reshape(-1), out_s.reshape(-1),
                           out_v.reshape(-1), ovf)

        _, (rr, sr, vv, ovf) = jax.lax.scan(
            step_wide, (), (s_lo.reshape(-1, slab_size),
                            s_hi.reshape(-1, slab_size),
                            s_rid.reshape(-1, slab_size)))
        return MaterializedMatches(
            r_rid=rr.reshape(-1), s_rid=sr.reshape(-1),
            valid=vv.reshape(-1),
            overflow=jnp.sum(ovf, dtype=jnp.uint32))

    r_sorted, r_rid_sorted = sort_kv_unstable(_sort_key(inner), inner.rid)

    def step(carry, slab):
        keys, rids = slab
        r_rid, s_rid_b, valid, ovf = _materialize_rows_narrow(
            r_sorted, r_rid_sorted, keys, rids, cap)
        return carry, (r_rid.reshape(-1), s_rid_b.reshape(-1),
                       valid.reshape(-1), ovf)

    _, (rr, sr, vv, ovf) = jax.lax.scan(
        step, (), (s_lo.reshape(-1, slab_size),
                   s_rid.reshape(-1, slab_size)))
    return MaterializedMatches(
        r_rid=rr.reshape(-1), s_rid=sr.reshape(-1), valid=vv.reshape(-1),
        overflow=jnp.sum(ovf, dtype=jnp.uint32))
