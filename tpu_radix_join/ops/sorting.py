"""Shared sort primitives and the xla-vs-Pallas radix-sort switch.

Every hot reorder in the pipeline is an *unstable* sort: the join's
semantics never depend on the relative order of equal keys (payload lanes
travel with their key in key-value sorts; probe disciplines are
order-independent within an equal-key run), and on v5e an unstable
``lax.sort`` is ~2x the speed of the stable sort ``jnp.sort``/
``jnp.argsort`` emit (measured 44.6ms vs 93ms at 32M uint32).

Centralised here so the *implementation* is one edit for every caller:
``merge_count.presort_keys``, the build/probe bucket paths, chunked.py,
the verify xor-fold, and the grouped codec all route through these three
functions, and as of PR 12 each resolves between two arms at trace time:

  * ``xla`` — ``jax.lax.sort`` (the pre-kernel floor);
  * ``pallas`` / ``pallas_interpret`` — the Pallas LSD radix sort
    (ops/pallas/radix_sort.py): 4 digit passes worst case for uint32,
    fewer when a key bound shrinks the effective width, no compare
    network at all.

Resolution mirrors ops/radix.resolve_partition_impl: ``auto`` (the
default, process-bindable via ``set_default_sort_impl`` from
JoinConfig.sort_impl) prefers the radix sort on a TPU backend above
``PALLAS_SORT_MIN_ELEMS`` for the shapes it can express (equal-length 1-D
uint32 lanes), and degrades to ``lax.sort`` LOUDLY when Pallas is
unavailable — the SORTFALLBACK counter ticks ONCE per process and a
log-once stderr line names the first site.  Structural ineligibility
(batched 2-D sorts, non-uint32 lanes) routes to XLA quietly even when the
kernel is forced: forcing selects the impl for the sorts the kernel can
express, it does not redefine what it can express.
"""

from __future__ import annotations

import sys
from contextlib import nullcontext

import jax
import jax.numpy as jnp

from tpu_radix_join.ops.pallas.radix_sort import (pallas_radix_sort_available,
                                                  radix_sort_pallas)
from tpu_radix_join.performance.measurements import SORTFALLBACK, SORTPASS

#: below this many elements the fixed costs of the radix machinery (4
#: kernel launches + 4 scatters worst case) beat its pass-count win over
#: the O(log^2 n)-stage lax.sort, so ``auto`` keeps small sorts on XLA
#: even on a TPU backend.  The planner's plan_sort arm uses the same
#: threshold so predictions match trace-time selection.
PALLAS_SORT_MIN_ELEMS = 1 << 18

SORT_IMPLS = ("auto", "xla", "pallas", "pallas_interpret")

# Sort-impl auto-selection happens at TRACE time (these functions run
# inside jit/shard_map bodies where no host counter can tick per
# execution), so the observability hook lives at module level, exactly
# like ops/radix's partition observer: the engine registers its
# Measurements once and every traced sort site records which arm it took.
_sort_observer: dict = {"meas": None}
_default_impl: dict = {"impl": "auto"}
_fallback_logged = False
_fallback_ticked = False


def install_sort_observer(measurements) -> None:
    """Register a performance.Measurements (or None) to receive SORTPASS
    ticks, radix-sort spans, and the once-per-process SORTFALLBACK tick
    from trace-time impl selection.  Process-global: the most recent
    engine wins, which is the engine whose programs are being traced."""
    _sort_observer["meas"] = measurements


def set_default_sort_impl(impl: str) -> None:
    """Bind the process-default sort impl (JoinConfig.sort_impl lands here
    via HashJoin).  The sort primitives are called from deep inside ops/
    with no config in reach — that is the point of the switch: callers
    inherit it with zero call-site edits — so the engine re-asserts its
    configured impl before tracing.  Compiled programs keep the impl they
    traced with."""
    if impl not in SORT_IMPLS:
        raise ValueError(
            f"unknown sort impl {impl!r} (expected one of {SORT_IMPLS})")
    _default_impl["impl"] = impl


def pallas_sort_available() -> bool:
    """True when the compiled radix sort can run (TPU backend; never
    initializes the backend — see partition.pallas_partition_available)."""
    return pallas_radix_sort_available()


def _sort_span(impl: str, site: str, elems: int):
    """Span bracketing the trace-time construction of one radix sort —
    mirrored into the flight recorder ring like every span."""
    m = _sort_observer["meas"]
    if m is None:
        return nullcontext()
    m.incr(SORTPASS)
    return m.span("radix_sort", impl=impl, site=site, elems=int(elems))


def _note_fallback(site: str, elems: int, why: str) -> None:
    """Auto-select degraded to lax.sort: tick SORTFALLBACK once per
    process and log once instead of staying silent (a TPU run quietly
    paying the sort floor where the radix kernel was expected is a perf
    bug).  One tick, not one per sort site: the degrade is a per-process
    backend fact, and a counter that scales with traced sort count would
    bury the regress gate's 0-vs-1 signal in retrace noise."""
    global _fallback_logged, _fallback_ticked
    m = _sort_observer["meas"]
    if m is not None and not _fallback_ticked:
        _fallback_ticked = True
        m.incr(SORTFALLBACK)
    if not _fallback_logged:
        _fallback_logged = True
        print(f"[sorting] sort auto-select fell back to lax.sort at "
              f"{site} ({elems} elems: {why}); further sorts degrade "
              f"silently — force --sort-impl xla to acknowledge, or run "
              f"a TPU backend for the radix arm", file=sys.stderr)


def _radix_eligible(operands, dimension: int) -> bool:
    """Shapes the radix kernel expresses: equal-length 1-D uint32 lanes
    sorted along their only axis.  Batched (2-D) sorts and non-uint32
    lanes stay on lax.sort."""
    first = operands[0]
    if first.ndim != 1 or dimension not in (-1, 0):
        return False
    return all(o.ndim == 1 and o.shape == first.shape
               and o.dtype == jnp.uint32 for o in operands)


def resolve_sort_impl(impl: str | None, elems: int, site: str,
                      eligible: bool = True) -> str:
    """Resolve a sort ``impl`` request to a concrete arm.

    ``None`` reads the process default (``set_default_sort_impl``).
    ``auto`` prefers the Pallas radix sort when the backend compiles
    Mosaic, the operands are radix-eligible, and the sort is big enough
    to amortize the pass machinery; a missing backend degrades to
    ``xla`` with SORTFALLBACK visibility (once per process).  ``xla``
    forces ``lax.sort``; ``pallas``/``pallas_interpret`` force the kernel
    for every eligible sort (interpret = traced JAX ops, the tier-1 CPU
    parity path)."""
    if impl is None:
        impl = _default_impl["impl"]
    if impl == "xla":
        return "xla"
    if impl == "auto":
        if not eligible:
            return "xla"
        if not pallas_sort_available():
            _note_fallback(site, elems, "Pallas unavailable")
            return "xla"
        if elems < PALLAS_SORT_MIN_ELEMS:
            return "xla"
        return "pallas"
    if not eligible:
        return "xla"
    return impl


def sort_unstable(x: jnp.ndarray, dimension: int = -1, *,
                  impl: str | None = None,
                  key_bound: int | None = None) -> jnp.ndarray:
    """Unstable sort of one array along ``dimension``."""
    eligible = _radix_eligible((x,), dimension)
    r = resolve_sort_impl(impl, x.size, "sort_unstable", eligible)
    if r in ("pallas", "pallas_interpret"):
        with _sort_span(r, "sort_unstable", x.size):
            return radix_sort_pallas(
                (x,), num_keys=1, key_bounds=(key_bound,),
                interpret=(r == "pallas_interpret"))[0]
    return jax.lax.sort([x], dimension=dimension, is_stable=False)[0]


def sort_kv_unstable(key: jnp.ndarray, *values: jnp.ndarray,
                     impl: str | None = None, key_bound: int | None = None):
    """Unstable key-value sort; returns (sorted key, *values in key order)."""
    eligible = _radix_eligible((key, *values), -1)
    r = resolve_sort_impl(impl, key.size, "sort_kv_unstable", eligible)
    if r in ("pallas", "pallas_interpret"):
        with _sort_span(r, "sort_kv_unstable", key.size):
            return radix_sort_pallas(
                (key, *values), num_keys=1, key_bounds=(key_bound,),
                interpret=(r == "pallas_interpret"))
    return jax.lax.sort((key, *values), num_keys=1, is_stable=False)


def sort_lex_unstable(*operands: jnp.ndarray, num_keys: int,
                      dimension: int = -1, impl: str | None = None,
                      key_bounds=None):
    """Unstable lexicographic sort on the first ``num_keys`` operands
    (remaining operands ride along as values).  Split-lane 64-bit keys
    are the ``num_keys=2`` (hi, lo) case; on the radix arm the lo lane's
    digit passes run first and stability chains them under the hi
    lane's."""
    eligible = _radix_eligible(operands, dimension)
    r = resolve_sort_impl(impl, operands[0].size, "sort_lex_unstable",
                          eligible)
    if r in ("pallas", "pallas_interpret"):
        with _sort_span(r, "sort_lex_unstable", operands[0].size):
            return radix_sort_pallas(
                operands, num_keys=num_keys, key_bounds=key_bounds,
                interpret=(r == "pallas_interpret"))
    return jax.lax.sort(operands, num_keys=num_keys, dimension=dimension,
                        is_stable=False)


def segmented_xor_fold(segment: jnp.ndarray, values: jnp.ndarray,
                       num_segments: int) -> jnp.ndarray:
    """Per-segment xor-fold: ``out[q] = XOR of values[i] where segment[i] == q``.

    XLA has no scatter-xor, so the fold goes through the pipeline's native
    reorder primitive instead: sort values by segment id, prefix-xor them
    with an associative scan, then difference the prefix at consecutive
    segment boundaries (located by searchsorted, which also handles empty
    segments — their fold is 0).  Order-independence is inherited from xor
    itself, so the unstable sort is safe (and the sort inherits the
    xla-vs-pallas switch through sort_kv_unstable, with the segment count
    as a free key bound).  The segment ``num_segments`` itself acts as a
    discard bucket — callers route invalid lanes to exactly that value
    (not merely "anything larger": the bounded radix passes only order
    segments below ``num_segments + 1``).

    The integrity-verification checksums (robustness/verify.py) are the
    consumer: xor catches the bit-flip corruptions that a wrapping uint32
    sum can miss (paired flips cancel in addition far more easily than in
    parity per bit position).
    """
    seg_s, val_s = sort_kv_unstable(segment.astype(jnp.uint32),
                                    values.astype(jnp.uint32),
                                    key_bound=num_segments + 1)
    prefix = jax.lax.associative_scan(jnp.bitwise_xor, val_s)
    # E[q] = prefix-xor through the last element with segment <= q
    idx = jnp.searchsorted(seg_s, jnp.arange(num_segments, dtype=jnp.uint32),
                           side="right") - 1
    bounded = jnp.where(idx >= 0, prefix[jnp.clip(idx, 0)], jnp.uint32(0))
    shifted = jnp.concatenate([jnp.zeros((1,), jnp.uint32), bounded[:-1]])
    return bounded ^ shifted
