"""Shared sort primitives.

Every hot reorder in the pipeline is an *unstable* ``lax.sort``: the join's
semantics never depend on the relative order of equal keys (payload lanes
travel with their key in key-value sorts; probe disciplines are
order-independent within an equal-key run), and on v5e an unstable sort is
~2x the speed of the stable sort ``jnp.sort``/``jnp.argsort`` emit (measured
44.6ms vs 93ms at 32M uint32).  Centralised here so a backend where that
tradeoff flips needs one edit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sort_unstable(x: jnp.ndarray, dimension: int = -1) -> jnp.ndarray:
    """Unstable sort of one array along ``dimension``."""
    return jax.lax.sort([x], dimension=dimension, is_stable=False)[0]


def sort_kv_unstable(key: jnp.ndarray, *values: jnp.ndarray):
    """Unstable key-value sort; returns (sorted key, *values in key order)."""
    return jax.lax.sort((key, *values), num_keys=1, is_stable=False)


def sort_lex_unstable(*operands: jnp.ndarray, num_keys: int,
                      dimension: int = -1):
    """Unstable lexicographic sort on the first ``num_keys`` operands
    (remaining operands ride along as values)."""
    return jax.lax.sort(operands, num_keys=num_keys, dimension=dimension,
                        is_stable=False)
