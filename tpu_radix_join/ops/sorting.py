"""Shared sort primitives.

Every hot reorder in the pipeline is an *unstable* ``lax.sort``: the join's
semantics never depend on the relative order of equal keys (payload lanes
travel with their key in key-value sorts; probe disciplines are
order-independent within an equal-key run), and on v5e an unstable sort is
~2x the speed of the stable sort ``jnp.sort``/``jnp.argsort`` emit (measured
44.6ms vs 93ms at 32M uint32).  Centralised here so a backend where that
tradeoff flips needs one edit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sort_unstable(x: jnp.ndarray, dimension: int = -1) -> jnp.ndarray:
    """Unstable sort of one array along ``dimension``."""
    return jax.lax.sort([x], dimension=dimension, is_stable=False)[0]


def sort_kv_unstable(key: jnp.ndarray, *values: jnp.ndarray):
    """Unstable key-value sort; returns (sorted key, *values in key order)."""
    return jax.lax.sort((key, *values), num_keys=1, is_stable=False)


def sort_lex_unstable(*operands: jnp.ndarray, num_keys: int,
                      dimension: int = -1):
    """Unstable lexicographic sort on the first ``num_keys`` operands
    (remaining operands ride along as values)."""
    return jax.lax.sort(operands, num_keys=num_keys, dimension=dimension,
                        is_stable=False)


def segmented_xor_fold(segment: jnp.ndarray, values: jnp.ndarray,
                       num_segments: int) -> jnp.ndarray:
    """Per-segment xor-fold: ``out[q] = XOR of values[i] where segment[i] == q``.

    XLA has no scatter-xor, so the fold goes through the pipeline's native
    reorder primitive instead: sort values by segment id, prefix-xor them
    with an associative scan, then difference the prefix at consecutive
    segment boundaries (located by searchsorted, which also handles empty
    segments — their fold is 0).  Order-independence is inherited from xor
    itself, so the unstable sort is safe.  Segments >= ``num_segments`` act
    as a discard bucket (callers route invalid lanes there).

    The integrity-verification checksums (robustness/verify.py) are the
    consumer: xor catches the bit-flip corruptions that a wrapping uint32
    sum can miss (paired flips cancel in addition far more easily than in
    parity per bit position).
    """
    seg_s, val_s = sort_kv_unstable(segment.astype(jnp.uint32),
                                    values.astype(jnp.uint32))
    prefix = jax.lax.associative_scan(jnp.bitwise_xor, val_s)
    # E[q] = prefix-xor through the last element with segment <= q
    idx = jnp.searchsorted(seg_s, jnp.arange(num_segments, dtype=jnp.uint32),
                           side="right") - 1
    bounded = jnp.where(idx >= 0, prefix[jnp.clip(idx, 0)], jnp.uint32(0))
    shifted = jnp.concatenate([jnp.zeros((1,), jnp.uint32), bounded[:-1]])
    return bounded ^ shifted
