"""Single-chip radix join: the flagship one-device pipeline.

The reference run with one rank still executes histogram -> (self-)partition ->
build-probe (main.cpp with np=1); this module is that slice on one TPU chip,
and the compute core the distributed pipeline shares.

Two disciplines:

  * :func:`local_join_sorted` — global sort of the inner side + dual
    searchsorted.  Minimal number of passes; the partition structure is
    implicit in the sort.
  * :func:`local_join_partitioned` — explicit radix partition into [P, cap]
    blocks (scatter_to_blocks), then per-partition row sorts + row searchsorted
    via vmap.  This is the literal analog of the reference's partition ->
    per-partition build-probe task structure (HashJoin.cpp:131-204), and the
    shorter per-row sorts are the TPU counterpart of making each build-probe
    bucket cache-resident.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from tpu_radix_join.data.tuples import TupleBatch, partition_ids
from tpu_radix_join.ops.radix import scatter_to_blocks
from tpu_radix_join.ops.sorting import sort_unstable


def local_join_sorted(r: TupleBatch, s: TupleBatch) -> jnp.ndarray:
    """Total match count (uint32) via sort + dual searchsorted."""
    r_sorted = sort_unstable(r.key)
    lo = jnp.searchsorted(r_sorted, s.key, side="left", method="sort")
    hi = jnp.searchsorted(r_sorted, s.key, side="right", method="sort")
    return jnp.sum((hi - lo).astype(jnp.uint32))


def local_join_merge(r: TupleBatch, s: TupleBatch) -> jnp.ndarray:
    """Chunked match counts (uint32 [4096], host-sum in uint64) via the
    sort-merge counting discipline (ops/merge_count.py) — the fastest
    single-chip probe measured on v5e (one 2n sort + scans; no searchsorted,
    no gathers).  32-bit keys only (compares the low lane)."""
    if r.key_hi is not None or s.key_hi is not None:
        raise NotImplementedError(
            "local_join_merge compares the 32-bit key lane only; 64-bit "
            "keys take merge_count.merge_count_wide_per_partition (hi/lo "
            "lexicographic, x64-free)")
    return _local_join_merge(r.key, s.key)


@jax.jit
def _local_join_merge(r_keys: jnp.ndarray, s_keys: jnp.ndarray) -> jnp.ndarray:
    from tpu_radix_join.ops.merge_count import merge_count_chunks
    return merge_count_chunks(r_keys, s_keys)


@functools.partial(jax.jit, static_argnames=("fanout_bits", "capacity"))
def local_join_partitioned(
    r: TupleBatch, s: TupleBatch, fanout_bits: int, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-partition match counts (uint32 [P]) + overflow flag (uint32).

    Radix-partitions both sides into [P, capacity] sentinel-padded blocks and
    probes each partition independently (vmapped row sort + searchsorted).
    ``capacity`` must cover the largest partition (overflow is reported, not
    silently dropped).
    """
    num_p = 1 << fanout_bits
    r_pid = partition_ids(r, fanout_bits)
    s_pid = partition_ids(s, fanout_bits)
    r_blocks, _, r_ovf = scatter_to_blocks(r, r_pid, num_p, capacity, "inner")
    s_blocks, _, s_ovf = scatter_to_blocks(s, s_pid, num_p, capacity, "outer")
    rk = sort_unstable(r_blocks.key.reshape(num_p, capacity), dimension=1)
    sk = s_blocks.key.reshape(num_p, capacity)

    def row(rrow, srow):
        lo = jnp.searchsorted(rrow, srow, side="left", method="sort")
        hi = jnp.searchsorted(rrow, srow, side="right", method="sort")
        return jnp.sum((hi - lo).astype(jnp.uint32))

    counts = jax.vmap(row)(rk, sk)
    return counts, r_ovf + s_ovf
