from tpu_radix_join.ops.radix import (
    local_histogram,
    reorder_by_partition,
    scatter_to_blocks,
)
from tpu_radix_join.ops.build_probe import (
    probe_count,
    probe_count_bucketized,
    probe_materialize,
)

__all__ = [
    "local_histogram",
    "reorder_by_partition",
    "scatter_to_blocks",
    "probe_count",
    "probe_count_bucketized",
    "probe_materialize",
]
