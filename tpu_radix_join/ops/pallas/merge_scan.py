"""Pallas TPU kernel: fused merge-count scan.

After the combined sort (ops/merge_count.py), XLA computes the match weights
with ~5 separate passes over the 2n array (cumsum, shift-compare, cummax,
elementwise, chunk reduction) — each a full HBM round trip.  This kernel fuses
them into ONE pass: a sequential grid walks the sorted packed keys tile by
tile, carrying the running R-count, run base, and previous key in SMEM
scratch, and emits one uint32 partial match count per tile.

This is the hand-written counterpart of the reference's fused GPU probe
kernels (probe_count, kernels.cu:423-463): where the GPU kernel chases hash
buckets per thread, the TPU kernel turns the probe into a carried scan at HBM
bandwidth.

In-tile layout: tiles are [ROWS, 128] uint32 in VMEM (row-major order of the
flat sorted array); full-tile scans decompose into a lane scan (axis=1) plus
an exclusive row-offset scan, all on the VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROWS = 256          # tile = ROWS x 128 uint32 = 128KB VMEM
LANES = 128
TILE = ROWS * LANES


def pallas_available() -> bool:
    """True when running on a real TPU backend (else use interpret=True or
    the XLA fallback in merge_count.py)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def out_struct(shape, dtype, like) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct whose varying-manual-axes (vma) annotation is
    inherited from ``like``: inside a ``shard_map`` with check_vma=True,
    pallas_call outputs must declare how they vary over the mesh axes — a
    per-device kernel output varies exactly like its per-device input."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _iscan(x: jnp.ndarray, op, ident, axis: int) -> jnp.ndarray:
    """Inclusive Hillis-Steele scan along ``axis`` built from circular roll +
    iota mask (Mosaic lowers neither the cumsum/cummax primitives nor
    lane-offset slices, but pltpu.roll is native)."""
    n = x.shape[axis]
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, axis)
    sh = 1
    while sh < n:
        rolled = pltpu.roll(x, sh, axis=axis)
        x = op(x, jnp.where(idx >= sh, rolled, ident))
        sh *= 2
    return x


def _tile_cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive cumsum over a [ROWS, 128] int32 tile in flat row-major order."""
    lane = _iscan(x, jnp.add, 0, axis=1)
    row_tot = jnp.sum(x, axis=1, keepdims=True)
    row_off = _iscan(row_tot, jnp.add, 0, axis=0) - row_tot   # exclusive
    return lane + row_off


def _tile_cummax(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive cummax over a [ROWS, 128] int32 tile in flat row-major order."""
    lane = _iscan(x, jnp.maximum, 0, axis=1)
    row_max = jnp.max(x, axis=1, keepdims=True)
    row_carry = _iscan(row_max, jnp.maximum, 0, axis=0)
    # exclusive over rows: shift down one row
    row_idx = jax.lax.broadcasted_iota(jnp.int32, row_carry.shape, 0)
    prev = jnp.where(row_idx >= 1, pltpu.roll(row_carry, 1, axis=0), 0)
    return jnp.maximum(lane, prev)


def _tile_scan(packed, carry_c_r, carry_base, carry_prev):
    """Shared per-tile merge-weight scan.  All arithmetic is int32: Mosaic
    does not legalize unsigned max or reductions, and every quantity here
    fits — keys are packed>>1 < 2^31, counts <= n < 2^31.  The prev-key
    sentinel is -1 (no valid key < 0).

    Returns (weight, key, new_c_r, new_base, new_prev_key); the carries'
    "last flat element" is expressed as a reduction (Mosaic cannot extract a
    VMEM scalar): c_r and base_run are nondecreasing in flat order and keys
    are sorted, so last == max (or carry + tile sum)."""
    key = (packed >> jnp.uint32(1)).astype(jnp.int32)
    is_s = (packed & jnp.uint32(1)).astype(jnp.int32)
    is_r = 1 - is_s

    c_r = _tile_cumsum(is_r) + carry_c_r

    # previous key in flat row-major order via circular rolls: lane roll
    # brings key[r, j-1] (and key[r, 127] into lane 0); a row roll on top
    # fixes lane 0 to key[r-1, 127]; element (0, 0) takes the carry.
    lane_idx = jax.lax.broadcasted_iota(jnp.int32, key.shape, 1)
    row_idx = jax.lax.broadcasted_iota(jnp.int32, key.shape, 0)
    rl = pltpu.roll(key, 1, axis=1)
    prev_key = jnp.where(lane_idx == 0, pltpu.roll(rl, 1, axis=0), rl)
    prev_key = jnp.where((lane_idx == 0) & (row_idx == 0), carry_prev,
                         prev_key)
    run_start = key != prev_key

    base_at_start = jnp.where(run_start, c_r - is_r, 0)
    base_run = jnp.maximum(_tile_cummax(base_at_start), carry_base)

    weight = is_s * (c_r - base_run)
    return (weight, key, carry_c_r + jnp.sum(is_r), jnp.max(base_run),
            jnp.max(key))


def _kernel(packed_ref, out_ref, c_r_ref, base_ref, prev_key_ref):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        c_r_ref[0] = jnp.int32(0)
        base_ref[0] = jnp.int32(0)
        prev_key_ref[0] = jnp.int32(-1)   # never equals a real key

    weight, _, c_r, base, prev = _tile_scan(
        packed_ref[:], c_r_ref[0], base_ref[0], prev_key_ref[0])
    out_ref[t, 0] = jnp.sum(weight).astype(jnp.uint32)
    c_r_ref[0] = c_r
    base_ref[0] = base
    prev_key_ref[0] = prev


def _kernel_partitions(packed_ref, out_ref, maxw_ref, c_r_ref, base_ref,
                       prev_key_ref, *, num_partitions: int, pid_shift: int):
    """Merge-weight scan fused with per-partition accumulation.

    Input is sorted in PARTITION-MAJOR packing (pid in the top bits, see
    merge_count._pack_pm), so each tile intersects only a narrow contiguous
    pid range; the per-partition masked reductions are ``pl.when``-guarded on
    that range, so only ~2 of them execute per tile regardless of the fanout.
    Accumulation is int32 (wraps identically to the uint32 contract); the
    caller bitcasts.  ``maxw_ref`` carries the max single-tuple match weight
    (max inner multiplicity among matched outer tuples) — the quantity the
    driver's uint32-overflow risk bound needs (hash_join._count_risk).
    """
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        for p in range(num_partitions):
            out_ref[p] = jnp.int32(0)
        maxw_ref[0] = jnp.int32(0)
        c_r_ref[0] = jnp.int32(0)
        base_ref[0] = jnp.int32(0)
        prev_key_ref[0] = jnp.int32(-1)

    packed = packed_ref[:]
    weight, _, c_r, base, prev = _tile_scan(
        packed, c_r_ref[0], base_ref[0], prev_key_ref[0])
    maxw_ref[0] = jnp.maximum(maxw_ref[0], jnp.max(jnp.max(weight, axis=0)))
    if num_partitions == 1:
        out_ref[0] = out_ref[0] + jnp.sum(jnp.sum(weight, axis=0))
    else:
        pid = (packed >> jnp.uint32(pid_shift)).astype(jnp.int32)
        pid_min = jnp.min(pid)
        pid_max = jnp.max(pid)
        for p in range(num_partitions):
            @pl.when((pid_min <= p) & (p <= pid_max))
            def _acc(p=p):
                c = jnp.sum(jnp.sum(jnp.where(pid == p, weight, 0), axis=0))
                out_ref[p] = out_ref[p] + c

    c_r_ref[0] = c_r
    base_ref[0] = base
    prev_key_ref[0] = prev


@functools.partial(jax.jit, static_argnames=("num_partitions", "interpret"))
def merge_scan_partitions(packed_sorted: jnp.ndarray, *, num_partitions: int,
                          interpret: bool = False):
    """Per-partition match counts (uint32 [num_partitions]) in ONE pass over
    a partition-major sorted packed array (merge_count._pack_pm layout:
    pid in the top log2(num_partitions) bits, then key remainder, then the
    side tag in bit 0).

    Replaces sort + ~5 XLA scan passes + a 33.5M-weight ``jnp.bincount``
    scatter-add (measured 375.7 ms at 16M⋈16M on the round-2 chip; this
    kernel's whole post-sort phase is ~one HBM pass).  Length must be a tile
    multiple (pad post-sort with 0xFFFFFFFF = the S pad, which sorts last and
    carries zero weight).

    Returns ``(counts, max_weight)``: the second output is the max
    single-outer-tuple match count (uint32 scalar), accumulated in the same
    pass — the driver's uint32-overflow risk bound consumes it
    (hash_join._count_risk).
    """
    n = packed_sorted.shape[0]
    if n % TILE:
        raise ValueError(f"length {n} must be a multiple of {TILE}")
    if num_partitions & (num_partitions - 1):
        raise ValueError("num_partitions must be a power of two")
    num_tiles = n // TILE
    pid_shift = 32 - (num_partitions.bit_length() - 1)
    kernel = functools.partial(_kernel_partitions,
                               num_partitions=num_partitions,
                               pid_shift=pid_shift)
    out, maxw = pl.pallas_call(
        kernel,
        grid=(num_tiles,),
        in_specs=[pl.BlockSpec((ROWS, LANES), lambda t: (t, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((num_partitions,), lambda t: (0,),
                                memory_space=pltpu.SMEM),
                   pl.BlockSpec((1,), lambda t: (0,),
                                memory_space=pltpu.SMEM)),
        out_shape=(out_struct((num_partitions,), jnp.int32, packed_sorted),
                   out_struct((1,), jnp.int32, packed_sorted)),
        scratch_shapes=[
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
        ],
        interpret=interpret,
    )(packed_sorted.reshape(num_tiles * ROWS, LANES))
    return (jax.lax.bitcast_convert_type(out, jnp.uint32),
            maxw[0].astype(jnp.uint32))


def _kernel_partitions_wide(lo_ref, hi_ref, tag_ref, out_ref, maxw_ref,
                            c_r_ref, base_ref, prev_lo_ref, prev_hi_ref,
                            *, num_partitions: int, pid_shift: int):
    """Wide-key (hi/lo lane) variant of :func:`_kernel_partitions`.

    Input is the three-lane partition-major sort order (lo_rot, hi, tag)
    where ``lo_rot`` is the low key lane rotated so the pid sits in its top
    bits (merge_count._rotate_pid).  Both 32-bit key lanes use all 32 bits,
    and Mosaic legalizes neither unsigned max nor uint->int converts of
    values >= 2^31, so comparisons ride an order-preserving bitcast:
    ``x ^ 0x8000_0000`` reinterpreted as int32 (run equality and max-based
    carry extraction are both preserved).  A tile's first element losing its
    run_start against the initial carry is harmless: its run base is 0,
    exactly what the carry init encodes.
    """
    t = pl.program_id(0)
    int32_min = jnp.int32(-2147483648)

    @pl.when(t == 0)
    def _init():
        for p in range(num_partitions):
            out_ref[p] = jnp.int32(0)
        maxw_ref[0] = jnp.int32(0)
        c_r_ref[0] = jnp.int32(0)
        base_ref[0] = jnp.int32(0)
        prev_lo_ref[0] = int32_min
        prev_hi_ref[0] = int32_min

    flip = jnp.uint32(0x80000000)
    lo = jax.lax.bitcast_convert_type(lo_ref[:] ^ flip, jnp.int32)
    hi = jax.lax.bitcast_convert_type(hi_ref[:] ^ flip, jnp.int32)
    is_s = tag_ref[:].astype(jnp.int32)
    is_r = 1 - is_s

    carry_c_r = c_r_ref[0]
    carry_base = base_ref[0]
    c_r = _tile_cumsum(is_r) + carry_c_r

    lane_idx = jax.lax.broadcasted_iota(jnp.int32, lo.shape, 1)
    row_idx = jax.lax.broadcasted_iota(jnp.int32, lo.shape, 0)

    def shift_prev(x, carry):
        rl = pltpu.roll(x, 1, axis=1)
        prev = jnp.where(lane_idx == 0, pltpu.roll(rl, 1, axis=0), rl)
        return jnp.where((lane_idx == 0) & (row_idx == 0), carry, prev)

    run_start = ((lo != shift_prev(lo, prev_lo_ref[0]))
                 | (hi != shift_prev(hi, prev_hi_ref[0])))
    base_at_start = jnp.where(run_start, c_r - is_r, 0)
    base_run = jnp.maximum(_tile_cummax(base_at_start), carry_base)
    weight = is_s * (c_r - base_run)
    maxw_ref[0] = jnp.maximum(maxw_ref[0], jnp.max(jnp.max(weight, axis=0)))

    if num_partitions == 1:
        out_ref[0] = out_ref[0] + jnp.sum(jnp.sum(weight, axis=0))
    else:
        pid = (lo_ref[:] >> jnp.uint32(pid_shift)).astype(jnp.int32)
        pid_min = jnp.min(pid)
        pid_max = jnp.max(pid)
        for p in range(num_partitions):
            @pl.when((pid_min <= p) & (p <= pid_max))
            def _acc(p=p):
                c = jnp.sum(jnp.sum(jnp.where(pid == p, weight, 0), axis=0))
                out_ref[p] = out_ref[p] + c

    c_r_ref[0] = carry_c_r + jnp.sum(is_r)
    base_ref[0] = jnp.max(base_run)
    # last flat element of (lo, hi): lo is sorted so last lo == max; the
    # last hi is the max over the final lo run (hi sorted within equal lo)
    last_lo = jnp.max(lo)
    c_r_dummy = jnp.where(lo == last_lo, hi, int32_min)
    prev_lo_ref[0] = last_lo
    prev_hi_ref[0] = jnp.max(c_r_dummy)


@functools.partial(jax.jit, static_argnames=("num_partitions", "interpret"))
def merge_scan_partitions_wide(lo_rot_sorted: jnp.ndarray,
                               hi_sorted: jnp.ndarray,
                               tag_sorted: jnp.ndarray, *,
                               num_partitions: int,
                               interpret: bool = False):
    """Per-partition match counts for 64-bit keys in one pass over the
    three-lane partition-major sort order (see merge_count's wide Pallas
    path).  Lengths must be a tile multiple (pad post-sort with the all-ones
    triple (0xFFFFFFFF, 0xFFFFFFFF, 1) — the wide S pad image, lexicographic
    maximum, zero weight).  Returns ``(counts, max_weight)`` as
    :func:`merge_scan_partitions` does."""
    n = lo_rot_sorted.shape[0]
    if n % TILE:
        raise ValueError(f"length {n} must be a multiple of {TILE}")
    if num_partitions & (num_partitions - 1):
        raise ValueError("num_partitions must be a power of two")
    num_tiles = n // TILE
    pid_shift = 32 - (num_partitions.bit_length() - 1)
    kernel = functools.partial(_kernel_partitions_wide,
                               num_partitions=num_partitions,
                               pid_shift=pid_shift)
    spec = pl.BlockSpec((ROWS, LANES), lambda t: (t, 0),
                        memory_space=pltpu.VMEM)
    out, maxw = pl.pallas_call(
        kernel,
        grid=(num_tiles,),
        in_specs=[spec, spec, spec],
        out_specs=(pl.BlockSpec((num_partitions,), lambda t: (0,),
                                memory_space=pltpu.SMEM),
                   pl.BlockSpec((1,), lambda t: (0,),
                                memory_space=pltpu.SMEM)),
        out_shape=(out_struct((num_partitions,), jnp.int32, lo_rot_sorted),
                   out_struct((1,), jnp.int32, lo_rot_sorted)),
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32) for _ in range(4)],
        interpret=interpret,
    )(lo_rot_sorted.reshape(num_tiles * ROWS, LANES),
      hi_sorted.reshape(num_tiles * ROWS, LANES),
      tag_sorted.reshape(num_tiles * ROWS, LANES))
    return (jax.lax.bitcast_convert_type(out, jnp.uint32),
            maxw[0].astype(jnp.uint32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def merge_scan_chunks(packed_sorted: jnp.ndarray,
                      interpret: bool = False) -> jnp.ndarray:
    """Per-tile match counts (uint32 [n / TILE]) for a sorted packed array.

    ``packed_sorted`` must be sorted uint32 with length a multiple of TILE
    (callers pad with the S pack-pad value 0xFFFFFFFF, which sorts last and
    contributes zero weight)."""
    n = packed_sorted.shape[0]
    if n % TILE:
        raise ValueError(f"length {n} must be a multiple of {TILE}")
    num_tiles = n // TILE
    return pl.pallas_call(
        _kernel,
        grid=(num_tiles,),
        in_specs=[pl.BlockSpec((ROWS, LANES), lambda t: (t, 0),
                               memory_space=pltpu.VMEM)],
        # full-array SMEM block (one uint32 per tile): the TPU lowering
        # rejects sub-(8,128) blocks unless they span the whole array, so
        # every grid step maps the same block and writes its own row.
        out_specs=pl.BlockSpec((num_tiles, 1), lambda t: (0, 0),
                               memory_space=pltpu.SMEM),
        out_shape=out_struct((num_tiles, 1), jnp.uint32, packed_sorted),
        scratch_shapes=[
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
        ],
        interpret=interpret,
    )(packed_sorted.reshape(num_tiles * ROWS, LANES)).reshape(num_tiles)
