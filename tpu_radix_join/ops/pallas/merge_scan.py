"""Pallas TPU kernel: fused merge-count scan.

After the combined sort (ops/merge_count.py), XLA computes the match weights
with ~5 separate passes over the 2n array (cumsum, shift-compare, cummax,
elementwise, chunk reduction) — each a full HBM round trip.  This kernel fuses
them into ONE pass: a sequential grid walks the sorted packed keys tile by
tile, carrying the running R-count, run base, and previous key in SMEM
scratch, and emits one uint32 partial match count per tile.

This is the hand-written counterpart of the reference's fused GPU probe
kernels (probe_count, kernels.cu:423-463): where the GPU kernel chases hash
buckets per thread, the TPU kernel turns the probe into a carried scan at HBM
bandwidth.

In-tile layout: tiles are [ROWS, 128] uint32 in VMEM (row-major order of the
flat sorted array); full-tile scans decompose into a lane scan (axis=1) plus
an exclusive row-offset scan, all on the VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROWS = 256          # tile = ROWS x 128 uint32 = 128KB VMEM
LANES = 128
TILE = ROWS * LANES


def pallas_available() -> bool:
    """True when running on a real TPU backend (else use interpret=True or
    the XLA fallback in merge_count.py)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _tile_cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive cumsum over a [ROWS, 128] tile in flat row-major order."""
    lane = jnp.cumsum(x, axis=1)
    row_tot = lane[:, -1:]
    row_off = jnp.cumsum(row_tot, axis=0) - row_tot   # exclusive over rows
    return lane + row_off


def _tile_cummax(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive cummax over a [ROWS, 128] tile in flat row-major order."""
    lane = jax.lax.cummax(x, axis=1)
    row_max = lane[:, -1:]
    row_carry = jax.lax.cummax(row_max, axis=0)
    # exclusive over rows: shift down one row
    prev = jnp.concatenate(
        [jnp.zeros_like(row_carry[:1]), row_carry[:-1]], axis=0)
    return jnp.maximum(lane, prev)


def _kernel(packed_ref, out_ref, c_r_ref, base_ref, prev_key_ref):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        c_r_ref[0] = jnp.uint32(0)
        base_ref[0] = jnp.uint32(0)
        prev_key_ref[0] = jnp.uint32(0xFFFFFFFF)   # never equals a real key

    packed = packed_ref[:]                      # [ROWS, 128] uint32
    one = jnp.uint32(1)
    key = packed >> one
    is_s = (packed & one).astype(jnp.uint32)
    is_r = one - is_s

    carry_c_r = c_r_ref[0]
    carry_base = base_ref[0]
    carry_prev = prev_key_ref[0]

    c_r = _tile_cumsum(is_r) + carry_c_r

    # previous key in flat order: shift within rows; row heads take the last
    # lane of the previous row; the very first element takes the carry.
    row_last = key[:, -1:]                       # [ROWS, 1]
    row_heads = jnp.concatenate(
        [jnp.full_like(row_last[:1], carry_prev), row_last[:-1]], axis=0)
    prev_key = jnp.concatenate([row_heads, key[:, :-1]], axis=1)
    run_start = key != prev_key

    base_at_start = jnp.where(run_start, c_r - is_r, jnp.uint32(0))
    base_run = jnp.maximum(_tile_cummax(base_at_start), carry_base)

    weight = is_s * (c_r - base_run)
    out_ref[0, 0] = jnp.sum(weight).astype(jnp.uint32)

    c_r_ref[0] = c_r[-1, -1]
    base_ref[0] = base_run[-1, -1]
    prev_key_ref[0] = key[-1, -1]


@functools.partial(jax.jit, static_argnames=("interpret",))
def merge_scan_chunks(packed_sorted: jnp.ndarray,
                      interpret: bool = False) -> jnp.ndarray:
    """Per-tile match counts (uint32 [n / TILE]) for a sorted packed array.

    ``packed_sorted`` must be sorted uint32 with length a multiple of TILE
    (callers pad with the S pack-pad value 0xFFFFFFFF, which sorts last and
    contributes zero weight)."""
    n = packed_sorted.shape[0]
    if n % TILE:
        raise ValueError(f"length {n} must be a multiple of {TILE}")
    num_tiles = n // TILE
    return pl.pallas_call(
        _kernel,
        grid=(num_tiles,),
        in_specs=[pl.BlockSpec((ROWS, LANES), lambda t: (t, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, 1), lambda t: (t, 0),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((num_tiles, 1), jnp.uint32),
        scratch_shapes=[
            pltpu.SMEM((1,), jnp.uint32),
            pltpu.SMEM((1,), jnp.uint32),
            pltpu.SMEM((1,), jnp.uint32),
        ],
        interpret=interpret,
    )(packed_sorted.reshape(num_tiles * ROWS, LANES)).reshape(num_tiles)
