"""Pallas TPU kernel: fused merge-count scan.

After the combined sort (ops/merge_count.py), XLA computes the match weights
with ~5 separate passes over the 2n array (cumsum, shift-compare, cummax,
elementwise, chunk reduction) — each a full HBM round trip.  This kernel fuses
them into ONE pass: a sequential grid walks the sorted packed keys tile by
tile, carrying the running R-count, run base, and previous key in SMEM
scratch, and emits one uint32 partial match count per tile.

This is the hand-written counterpart of the reference's fused GPU probe
kernels (probe_count, kernels.cu:423-463): where the GPU kernel chases hash
buckets per thread, the TPU kernel turns the probe into a carried scan at HBM
bandwidth.

In-tile layout: tiles are [ROWS, 128] uint32 in VMEM (row-major order of the
flat sorted array); full-tile scans decompose into a lane scan (axis=1) plus
an exclusive row-offset scan, all on the VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROWS = 256          # tile = ROWS x 128 uint32 = 128KB VMEM
LANES = 128
TILE = ROWS * LANES


def pallas_available() -> bool:
    """True when running on a real TPU backend (else use interpret=True or
    the XLA fallback in merge_count.py)."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _iscan(x: jnp.ndarray, op, ident, axis: int) -> jnp.ndarray:
    """Inclusive Hillis-Steele scan along ``axis`` built from circular roll +
    iota mask (Mosaic lowers neither the cumsum/cummax primitives nor
    lane-offset slices, but pltpu.roll is native)."""
    n = x.shape[axis]
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, axis)
    sh = 1
    while sh < n:
        rolled = pltpu.roll(x, sh, axis=axis)
        x = op(x, jnp.where(idx >= sh, rolled, ident))
        sh *= 2
    return x


def _tile_cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive cumsum over a [ROWS, 128] int32 tile in flat row-major order."""
    lane = _iscan(x, jnp.add, 0, axis=1)
    row_tot = jnp.sum(x, axis=1, keepdims=True)
    row_off = _iscan(row_tot, jnp.add, 0, axis=0) - row_tot   # exclusive
    return lane + row_off


def _tile_cummax(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive cummax over a [ROWS, 128] int32 tile in flat row-major order."""
    lane = _iscan(x, jnp.maximum, 0, axis=1)
    row_max = jnp.max(x, axis=1, keepdims=True)
    row_carry = _iscan(row_max, jnp.maximum, 0, axis=0)
    # exclusive over rows: shift down one row
    row_idx = jax.lax.broadcasted_iota(jnp.int32, row_carry.shape, 0)
    prev = jnp.where(row_idx >= 1, pltpu.roll(row_carry, 1, axis=0), 0)
    return jnp.maximum(lane, prev)


def _kernel(packed_ref, out_ref, c_r_ref, base_ref, prev_key_ref):
    """All arithmetic is int32: Mosaic does not legalize unsigned max or
    reductions, and every quantity here fits — keys are packed>>1 < 2^31,
    counts <= n < 2^31.  The prev-key sentinel is -1 (no valid key < 0)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        c_r_ref[0] = jnp.int32(0)
        base_ref[0] = jnp.int32(0)
        prev_key_ref[0] = jnp.int32(-1)   # never equals a real key

    packed = packed_ref[:]                      # [ROWS, 128] uint32
    key = (packed >> jnp.uint32(1)).astype(jnp.int32)
    is_s = (packed & jnp.uint32(1)).astype(jnp.int32)
    is_r = 1 - is_s

    carry_c_r = c_r_ref[0]
    carry_base = base_ref[0]
    carry_prev = prev_key_ref[0]

    c_r = _tile_cumsum(is_r) + carry_c_r

    # previous key in flat row-major order via circular rolls: lane roll
    # brings key[r, j-1] (and key[r, 127] into lane 0); a row roll on top
    # fixes lane 0 to key[r-1, 127]; element (0, 0) takes the carry.
    lane_idx = jax.lax.broadcasted_iota(jnp.int32, key.shape, 1)
    row_idx = jax.lax.broadcasted_iota(jnp.int32, key.shape, 0)
    rl = pltpu.roll(key, 1, axis=1)
    prev_key = jnp.where(lane_idx == 0, pltpu.roll(rl, 1, axis=0), rl)
    prev_key = jnp.where((lane_idx == 0) & (row_idx == 0), carry_prev,
                         prev_key)
    run_start = key != prev_key

    base_at_start = jnp.where(run_start, c_r - is_r, 0)
    base_run = jnp.maximum(_tile_cummax(base_at_start), carry_base)

    weight = is_s * (c_r - base_run)
    out_ref[t, 0] = jnp.sum(weight).astype(jnp.uint32)

    # last flat element of each carry, expressed as a reduction (Mosaic
    # cannot extract a VMEM scalar): c_r and base_run are nondecreasing in
    # flat order and keys are sorted, so last == max (or carry + tile sum).
    c_r_ref[0] = carry_c_r + jnp.sum(is_r)
    base_ref[0] = jnp.max(base_run)
    prev_key_ref[0] = jnp.max(key)


@functools.partial(jax.jit, static_argnames=("interpret",))
def merge_scan_chunks(packed_sorted: jnp.ndarray,
                      interpret: bool = False) -> jnp.ndarray:
    """Per-tile match counts (uint32 [n / TILE]) for a sorted packed array.

    ``packed_sorted`` must be sorted uint32 with length a multiple of TILE
    (callers pad with the S pack-pad value 0xFFFFFFFF, which sorts last and
    contributes zero weight)."""
    n = packed_sorted.shape[0]
    if n % TILE:
        raise ValueError(f"length {n} must be a multiple of {TILE}")
    num_tiles = n // TILE
    return pl.pallas_call(
        _kernel,
        grid=(num_tiles,),
        in_specs=[pl.BlockSpec((ROWS, LANES), lambda t: (t, 0),
                               memory_space=pltpu.VMEM)],
        # full-array SMEM block (one uint32 per tile): the TPU lowering
        # rejects sub-(8,128) blocks unless they span the whole array, so
        # every grid step maps the same block and writes its own row.
        out_specs=pl.BlockSpec((num_tiles, 1), lambda t: (0, 0),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((num_tiles, 1), jnp.uint32),
        scratch_shapes=[
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
        ],
        interpret=interpret,
    )(packed_sorted.reshape(num_tiles * ROWS, LANES)).reshape(num_tiles)
