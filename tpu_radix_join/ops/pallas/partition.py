"""Pallas TPU kernel: fused radix partition — histogram → scan → scatter.

The sort-based partitioning path (ops/radix.scatter_to_blocks) pays a full
``sort_kv_unstable`` over every lane to group tuples by destination, even
though the destination key has only ``fanout_bits`` of entropy and the
partition offsets are just an exclusive prefix scan of the per-tile
histograms (PAPERS.md: arXiv 2505.15112; the MPI_Scan-offload paper,
arXiv 1408.4939, is the same insight at the network layer).  This kernel
replaces the O(log^2 n)-stage sort with two streaming passes over the ids:

  * **pass 1** (grid phase 0): per-tile per-partition histograms,
    accumulated into one SMEM output block across sequential grid steps —
    no atomics, because TPU grid steps serialize on a core (the same
    freedom histogram.py exploits);
  * **carry** (first step of phase 1): the histogram is folded into
    per-partition write cursors in SMEM — the exclusive scan, a P-step
    scalar loop;
  * **pass 2** (grid phase 1): each tile is re-read and every tuple is
    assigned its final slot ``cursor[g] + rank_in_tile`` via masked
    VPU prefix sums; the cursors advance by the tile counts.

The kernel emits the per-tuple destination **slots** and the exact
histogram in one launch.  The physical lane movement is then a single
unique-index scatter per lane (``lane.at[slots].set``, radix.py) — each
lane crosses HBM exactly twice (read + scattered write) instead of riding
every stage of a bitonic sort.  Per-element scatter inside the kernel is
not expressible in Mosaic (no lane-granular dynamic stores), so the
slot/scatter split is the TPU-shaped factoring of the fused kernel: all
index arithmetic fused into two ids passes, data movement left to XLA's
scatter with indices known to be collision-free.

Like merge_scan.py, all in-kernel arithmetic is int32 (Mosaic does not
legalize unsigned reductions) and the in-tile prefix sums are
roll-and-mask Hillis-Steele scans on the Mosaic path; under
``interpret=True`` (tier-1 CPU parity and the host-CPU bench) the scans
use ``jnp.cumsum`` directly — byte-identical results, and the interpreted
kernel stays bandwidth-bound instead of paying the log-stage roll
emulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_radix_join.ops.pallas.merge_scan import _tile_cumsum, out_struct

ROWS = 2048          # max tile = ROWS x 128 uint32 = 1MB VMEM per ref
LANES = 128
#: Per-group work is one masked prefix sum per tile, so the unrolled loop
#: tolerates a wider fanout than histogram.py's 128; 256 covers the grouped
#: composite key (num_blocks * num_sub) at the default 8-node x 32-sub mesh.
MAX_PARTITIONS = 256


def _kernel(ids_ref, slots_ref, hist_ref, cur_ref, *, num_groups: int,
            group_size: int, capacity: int | None, interpret: bool):
    """Grid (2, num_tiles): phase 0 = histogram, phase 1 = slot assignment."""
    ph = pl.program_id(0)
    t = pl.program_id(1)
    ids = ids_ref[:].astype(jnp.int32)      # invalid/pad ids == num_groups

    @pl.when(jnp.logical_and(ph == 0, t == 0))
    def _init_hist():
        for g in range(num_groups):
            hist_ref[g] = jnp.int32(0)

    @pl.when(ph == 0)
    def _histogram():
        if interpret:
            # traced-JAX path: one scatter-add pass (fine on CPU; it is
            # only on TPU that XLA serializes bincount, and there the
            # Mosaic branch below runs instead)
            hist_ref[...] = hist_ref[...] + jnp.bincount(
                ids.reshape(-1), length=num_groups).astype(jnp.int32)
        else:
            for g in range(num_groups):
                hit = (ids == g).astype(jnp.int32)
                # staged reduction (sublane, then lane) vectorizes on the
                # VPU where a flat jnp.sum lowers row-serially
                hist_ref[g] = hist_ref[g] + jnp.sum(jnp.sum(hit, axis=0))
        # deterministic writeback for the not-yet-assigned slot block (it
        # is revisited and overwritten in phase 1)
        slots_ref[:] = jnp.zeros(ids.shape, jnp.uint32)

    @pl.when(jnp.logical_and(ph == 1, t == 0))
    def _init_cursors():
        # the exclusive scan of the histogram, folded straight into the
        # write cursors: dense mode chains globally; blocked mode restarts
        # at every destination (group_size consecutive groups share one
        # block) and offsets by the block base.  A num_groups-step scalar
        # SMEM loop — the "carry" between the two passes.
        off = jnp.int32(0)
        for g in range(num_groups):
            if capacity is None:
                cur_ref[g] = off
            else:
                if g % group_size == 0:
                    off = jnp.int32(0)
                cur_ref[g] = jnp.int32((g // group_size) * capacity) + off
            off = off + hist_ref[g]

    @pl.when(ph == 1)
    def _assign_slots():
        if interpret:
            # vectorized cumcount: one [tile, num_groups] one-hot prefix
            # sum ranks every group at once — a handful of wide traced ops
            # instead of num_groups masked scans (invalid ids match no
            # one-hot column, so they advance no cursor; their gathered
            # rank is garbage and masked below)
            flat = ids.reshape(-1)
            g = jnp.minimum(flat, num_groups - 1)
            onehot = (flat[:, None]
                      == jnp.arange(num_groups, dtype=jnp.int32)[None, :]
                      ).astype(jnp.int32)
            incl = jnp.cumsum(onehot, axis=0)
            rank = jnp.take_along_axis(incl, g[:, None], axis=1)[:, 0] - 1
            cur_vec = cur_ref[...]
            slots = (cur_vec[g] + rank).reshape(ids.shape)
            cur_ref[...] = cur_vec + incl[-1, :]
        else:
            slots = jnp.zeros(ids.shape, jnp.int32)
            for gi in range(num_groups):
                hit = ids == gi
                m = hit.astype(jnp.int32)
                incl = _tile_cumsum(m)
                cur = cur_ref[gi]
                slots = slots + jnp.where(hit, cur + (incl - m), 0)
                cur_ref[gi] = cur + jnp.sum(jnp.sum(m, axis=0))
        ok = ids < num_groups
        if capacity is not None:
            # a tuple whose *unclipped* within-destination position passed
            # capacity overflowed its block: drop it (counted by the exact
            # histogram; Window's overflow contract retries at 2x capacity)
            pos = slots - (ids // group_size) * capacity
            ok = jnp.logical_and(ok, pos < capacity)
        # -1 casts to 0xFFFFFFFF — out of range for every caller, so the
        # XLA-side .at[slots].set(..., mode="drop") discards these rows
        slots_ref[:] = jnp.where(ok, slots, jnp.int32(-1)).astype(jnp.uint32)


@functools.partial(
    jax.jit,
    static_argnames=("num_groups", "group_size", "capacity", "interpret"))
def partition_slots_pallas(ids: jnp.ndarray, *, num_groups: int,
                           group_size: int = 1,
                           capacity: int | None = None,
                           interpret: bool = False):
    """(slots uint32 [n], hist uint32 [num_groups]) for ``ids`` uint32 [n].

    ``slots[i]`` is tuple i's final position: with ``capacity=None`` a
    dense permutation target in [0, n) grouping equal ids contiguously in
    id order (input order within a group); with a capacity, a position in
    the ``[num_groups // group_size, capacity * group_size]``-shaped block
    layout where ``group_size`` consecutive ids share the block
    ``id // group_size`` and overflowing/invalid tuples get the
    0xFFFFFFFF sentinel (callers scatter with ``mode="drop"``).
    ``hist`` is the exact per-id count regardless of clipping.  Ids >=
    ``num_groups`` are counted nowhere and dropped — callers route invalid
    slots there, exactly as with histogram_pallas.
    """
    if num_groups > MAX_PARTITIONS:
        raise ValueError(f"num_groups {num_groups} > {MAX_PARTITIONS}")
    if num_groups % group_size:
        raise ValueError(f"num_groups {num_groups} not a multiple of "
                         f"group_size {group_size}")
    n = ids.shape[0]
    # shrink the tile for small inputs so tier-1-sized calls don't pay a
    # full 1MB pad (sublane counts must stay multiples of 8)
    rows = max(8, min(ROWS, ((n + LANES - 1) // LANES + 7) // 8 * 8))
    tile = rows * LANES
    pad = (-n) % tile
    if pad:
        ids = jnp.concatenate(
            [ids, jnp.full((pad,), num_groups, jnp.uint32)])
    num_tiles = (n + pad) // tile

    kernel = functools.partial(_kernel, num_groups=num_groups,
                               group_size=group_size, capacity=capacity,
                               interpret=interpret)
    slots, hist = pl.pallas_call(
        kernel,
        grid=(2, num_tiles),
        in_specs=[pl.BlockSpec((rows, LANES), lambda ph, t: (t, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec((rows, LANES), lambda ph, t: (t, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((num_groups,), lambda ph, t: (0,),
                                memory_space=pltpu.SMEM)],
        out_shape=(out_struct((num_tiles * rows, LANES), jnp.uint32, ids),
                   out_struct((num_groups,), jnp.int32, ids)),
        scratch_shapes=[pltpu.SMEM((num_groups,), jnp.int32)],
        interpret=interpret,
    )(ids.reshape(num_tiles * rows, LANES))
    return slots.reshape(-1)[:n], hist.astype(jnp.uint32)


def pallas_partition_available() -> bool:
    """True when the fused kernel can run compiled (TPU backend).

    Must never *initialize* the backend: the planner asks this before
    bench.py's tunnel probe has blessed the device, and ``jax.devices()``
    on a downed tunnel blocks on a native futex no signal can interrupt
    (bench._wait_for_backend's whole reason for probing in a child
    process).  An already-initialized backend answers directly; otherwise
    the configured platform string decides without touching the runtime.
    """
    try:
        from jax._src import xla_bridge
        backends = getattr(xla_bridge, "_backends", None) or {}
        if backends:
            return any(getattr(b, "platform", "") == "tpu"
                       for b in backends.values())
        platforms = jax.config.jax_platforms or ""
        return any(p in platforms for p in ("tpu", "axon"))
    except Exception:
        return False
