"""Pallas TPU kernel: partition histogram at stream bandwidth.

The histogram is the pipeline's first hot pass (LocalHistogram.cpp:44-47;
GPU ``histogram_build_L1/L2``, kernels.cu:19-185).  XLA's options are both
bandwidth-catastrophes on TPU for this shape: ``jnp.bincount`` lowers to a
serialized scatter-add (~58 ms at 16M keys measured on v5e) and a broadcast
compare-reduce streams an [n, P] intermediate (~24 ms).  This kernel reads
the ids exactly once and keeps the P accumulators in registers/SMEM:
per tile, P masked reductions on the VPU — ~1 ms at 16M for P = 32.

Grid steps run sequentially on a TPU core, so accumulating into one SMEM
output block across steps needs no atomics (the same freedom the GPU kernels
buy with shared-memory atomics).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_radix_join.ops.pallas.merge_scan import out_struct

ROWS = 2048          # tile = ROWS x 128 uint32 = 1MB VMEM
LANES = 128
MAX_PARTITIONS = 128  # unrolled per-partition reductions; keep the loop sane


def _kernel(pid_ref, w_ref, out_ref, num_partitions: int, weighted: bool):
    """int32 arithmetic throughout: Mosaic does not legalize unsigned
    reductions (see merge_scan.py); counts/weight sums fit int32 by the
    n < 2**31 contract."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        for p in range(num_partitions):
            out_ref[p] = jnp.int32(0)

    pid = pid_ref[:]
    w = w_ref[:].astype(jnp.int32) if weighted else None
    for p in range(num_partitions):
        hit = pid == jnp.uint32(p)
        if weighted:
            contrib = jnp.where(hit, w, jnp.int32(0))
        else:
            contrib = hit.astype(jnp.int32)
        # staged reduction (sublane sum, then lane sum) vectorizes on the
        # VPU where a flat jnp.sum lowers row-serially
        c = jnp.sum(jnp.sum(contrib, axis=0))
        out_ref[p] = out_ref[p] + c


@functools.partial(jax.jit,
                   static_argnames=("num_partitions", "interpret"))
def histogram_pallas(pid: jnp.ndarray,
                     weights: jnp.ndarray | None = None,
                     *, num_partitions: int,
                     interpret: bool = False) -> jnp.ndarray:
    """uint32 [num_partitions] counts (or weight sums) of ``pid`` uint32 [n].

    ``n`` is padded internally to a tile multiple; padding ids are routed to
    ``num_partitions`` (out of range, counted nowhere).  Ids >=
    ``num_partitions`` in the input are likewise ignored — callers route
    invalid slots to an out-of-range id (radix.local_histogram).
    """
    if num_partitions > MAX_PARTITIONS:
        raise ValueError(f"num_partitions {num_partitions} > {MAX_PARTITIONS}")
    n = pid.shape[0]
    tile = ROWS * LANES
    pad = (-n) % tile
    weighted = weights is not None
    if pad:
        pid = jnp.concatenate(
            [pid, jnp.full((pad,), num_partitions, jnp.uint32)])
    w = weights if weighted else pid   # dummy ref keeps one kernel signature
    if weighted and pad:
        w = jnp.concatenate([w, jnp.zeros((pad,), w.dtype)])
    num_tiles = (n + pad) // tile

    kernel = functools.partial(_kernel, num_partitions=num_partitions,
                               weighted=weighted)
    return pl.pallas_call(
        kernel,
        grid=(num_tiles,),
        in_specs=[pl.BlockSpec((ROWS, LANES), lambda t: (t, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((ROWS, LANES), lambda t: (t, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((num_partitions,), lambda t: (0,),
                               memory_space=pltpu.SMEM),
        out_shape=out_struct((num_partitions,), jnp.int32, pid),
        interpret=interpret,
    )(pid.reshape(num_tiles * ROWS, LANES),
      w.astype(jnp.uint32).reshape(num_tiles * ROWS, LANES)
      ).astype(jnp.uint32)


def pallas_histogram_available() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False
