"""Pallas TPU kernel: LSD radix sort as iterated partition passes.

PERF_NOTES' stage model pins single-chip throughput to ``lax.sort`` — the
floor under merge_count, the bucket build/probe, the verify xor-fold and
the grouped codec alike — and concludes a hand-written compare-exchange
network cannot beat it.  An LSD radix sort needs no compare network at
all: the fused histogram→carried-scan→scatter kernel of partition.py *is*
one digit pass, so sorting is iteration, not invention.  Each pass here

  * extracts an 8-bit digit from the key tile **in-kernel** (no
    materialized digit array crosses HBM),
  * accumulates per-tile SMEM histograms whose carry across sequential
    grid steps is the exclusive scan (partition.py's phase structure,
    generalizing the tiled-carry scan of PAPERS.md arXiv 2505.15112),
  * emits per-tuple slots, after which every lane moves with one
    collision-free ``.at[slots].set(..., mode="drop")`` scatter.

A pass groups equal digits contiguously **preserving input order within a
digit** (the partition kernel's documented dense-mode contract), so each
pass is stable and the least-significant-digit iteration is a correct
sort: 4 passes worst case for uint32, fewer whenever JHIST/WireSpec key
bounds prove the high digits constant (``data/tuples.effective_key_bits``
is the shared source of truth — a 16-bit-bounded key sorts in 2 passes).
64-bit keys ride split uint32 hi/lo lanes: the lo lane's passes run
first, then the hi lane's, chained by per-pass stability — exactly the
lexicographic ``num_keys=2`` contract of ``sort_lex_unstable``.

Like partition.py, in-kernel arithmetic is int32 except the uint32 digit
extraction (elementwise shifts legalize fine; it is unsigned *reductions*
Mosaic rejects), and ``interpret=True`` runs byte-identical traced-JAX
scans for CPU tier-1 parity and the host-mesh ``--sort-bench``.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_radix_join.data.tuples import effective_key_bits
from tpu_radix_join.ops.pallas.merge_scan import _tile_cumsum, out_struct
from tpu_radix_join.ops.pallas.partition import pallas_partition_available

RADIX_BITS = 8
RADIX = 1 << RADIX_BITS      # == partition.MAX_PARTITIONS: the digit fanout
                             # the unrolled Mosaic scan loop tolerates
LANES = 128
#: smaller tile than partition.py's 2048: the slot phase ranks against all
#: 256 digit columns at once, so the interpret-mode one-hot is
#: [ROWS*128, 256] i32 — 32MB at 256 rows, which keeps the host-CPU bench
#: and tier-1 parity runs in cache-friendly territory.  On the Mosaic path
#: the tile is 128KB of VMEM per ref, well under budget.
ROWS = 256


def num_radix_passes(key_bound: Optional[int] = None,
                     key_bits: int = 32) -> int:
    """Digit passes needed for keys < ``key_bound`` (None = full width).

    The pass-skip decision: passes the bound proves constant-zero are
    never launched.  ``ceil(effective_key_bits / 8)`` — 4 for full uint32,
    2 for a 16-bit bound, 1 for an 8-bit bound.
    """
    return -(-effective_key_bits(key_bound, 0, key_bits) // RADIX_BITS)


def _digit_kernel(keys_ref, slots_ref, hist_ref, cur_ref, *, shift: int,
                  n: int, interpret: bool):
    """Grid (2, num_tiles): phase 0 = digit histogram, phase 1 = slots.

    partition._kernel specialized to the sort pass: ``num_groups=RADIX``,
    dense mode (the slots are a permutation of [0, n)), ids produced
    in-kernel from the key tile instead of arriving precomputed, and pad
    rows invalidated by their flat position (every uint32 *key* value is
    valid, so there is no sentinel id to pad with).
    """
    ph = pl.program_id(0)
    t = pl.program_id(1)
    keys = keys_ref[:]
    rows, lanes = keys.shape
    # the 8-bit digit, extracted in uint32 (logical shift) then cast for
    # the int32 scan arithmetic below
    d = keys if shift == 0 else jnp.right_shift(keys, jnp.uint32(shift))
    d = (d & jnp.uint32(RADIX - 1)).astype(jnp.int32)
    # flat row-major position across the padded input: pad rows (>= n)
    # become the invalid id RADIX — counted nowhere, slot -1, dropped
    flat = (t * (rows * lanes)
            + jax.lax.broadcasted_iota(jnp.int32, keys.shape, 0) * lanes
            + jax.lax.broadcasted_iota(jnp.int32, keys.shape, 1))
    ids = jnp.where(flat < n, d, jnp.int32(RADIX))

    @pl.when(jnp.logical_and(ph == 0, t == 0))
    def _init_hist():
        if interpret:
            # one vector store: 256 unrolled scalar SMEM writes cost ~4s
            # of trace/lower time PER (shape, shift) jit entry on the
            # interpret path, which tier-1 pays for every pass
            hist_ref[...] = jnp.zeros((RADIX,), jnp.int32)
        else:
            for g in range(RADIX):
                hist_ref[g] = jnp.int32(0)

    @pl.when(ph == 0)
    def _histogram():
        if interpret:
            hist_ref[...] = hist_ref[...] + jnp.bincount(
                ids.reshape(-1), length=RADIX).astype(jnp.int32)
        else:
            for g in range(RADIX):
                hit = (ids == g).astype(jnp.int32)
                hist_ref[g] = hist_ref[g] + jnp.sum(jnp.sum(hit, axis=0))
        slots_ref[:] = jnp.zeros(ids.shape, jnp.uint32)

    @pl.when(jnp.logical_and(ph == 1, t == 0))
    def _init_cursors():
        # exclusive scan of the digit histogram -> write cursors: the
        # carry between the two passes.  Dense mode only, so the scan has
        # no per-block restart — on the interpret path it is one cumsum
        # (same trace-time economy as _init_hist); Mosaic keeps the
        # RADIX-step scalar SMEM loop partition.py uses
        if interpret:
            h = hist_ref[...]
            cur_ref[...] = jnp.cumsum(h) - h
        else:
            off = jnp.int32(0)
            for g in range(RADIX):
                cur_ref[g] = off
                off = off + hist_ref[g]

    @pl.when(ph == 1)
    def _assign_slots():
        if interpret:
            flat_ids = ids.reshape(-1)
            g = jnp.minimum(flat_ids, RADIX - 1)
            onehot = (flat_ids[:, None]
                      == jnp.arange(RADIX, dtype=jnp.int32)[None, :]
                      ).astype(jnp.int32)
            incl = jnp.cumsum(onehot, axis=0)
            rank = jnp.take_along_axis(incl, g[:, None], axis=1)[:, 0] - 1
            cur_vec = cur_ref[...]
            slots = (cur_vec[g] + rank).reshape(ids.shape)
            cur_ref[...] = cur_vec + incl[-1, :]
        else:
            slots = jnp.zeros(ids.shape, jnp.int32)
            for gi in range(RADIX):
                hit = ids == gi
                m = hit.astype(jnp.int32)
                incl = _tile_cumsum(m)
                cur = cur_ref[gi]
                slots = slots + jnp.where(hit, cur + (incl - m), 0)
                cur_ref[gi] = cur + jnp.sum(jnp.sum(m, axis=0))
        ok = ids < RADIX
        slots_ref[:] = jnp.where(ok, slots, jnp.int32(-1)).astype(jnp.uint32)


@functools.partial(jax.jit, static_argnames=("shift", "interpret"))
def radix_pass_slots_pallas(keys: jnp.ndarray, *, shift: int,
                            interpret: bool = False) -> jnp.ndarray:
    """Slots uint32 [n]: the stable grouping permutation of one digit pass.

    ``slots[i]`` is key i's destination when grouping by digit
    ``(keys >> shift) & 0xFF`` — a dense permutation of [0, n), digit
    order across groups, input order within a group.
    """
    if keys.dtype != jnp.uint32 or keys.ndim != 1:
        raise ValueError(
            f"radix pass wants a 1-D uint32 key lane, got "
            f"{keys.dtype} rank {keys.ndim}")
    n = keys.shape[0]
    rows = max(8, min(ROWS, ((n + LANES - 1) // LANES + 7) // 8 * 8))
    tile = rows * LANES
    pad = (-n) % tile
    if pad:
        # pad value is irrelevant: pad rows are invalidated by position
        keys = jnp.concatenate([keys, jnp.zeros((pad,), jnp.uint32)])
    num_tiles = (n + pad) // tile

    kernel = functools.partial(_digit_kernel, shift=shift, n=n,
                               interpret=interpret)
    slots, _ = pl.pallas_call(
        kernel,
        grid=(2, num_tiles),
        in_specs=[pl.BlockSpec((rows, LANES), lambda ph, t: (t, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec((rows, LANES), lambda ph, t: (t, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((RADIX,), lambda ph, t: (0,),
                                memory_space=pltpu.SMEM)],
        out_shape=(out_struct((num_tiles * rows, LANES), jnp.uint32, keys),
                   out_struct((RADIX,), jnp.int32, keys)),
        scratch_shapes=[pltpu.SMEM((RADIX,), jnp.int32)],
        interpret=interpret,
    )(keys.reshape(num_tiles * rows, LANES))
    return slots.reshape(-1)[:n]


def _apply_permutation(slots, arrs):
    # zeros_like + a[0]*0 inherits the vma under shard_map (same trick as
    # radix.reorder_by_partition); slots are collision-free by construction
    return [(jnp.zeros_like(a) + a[0] * a.dtype.type(0)
             ).at[slots].set(a, mode="drop") for a in arrs]


def radix_sort_pallas(operands: Sequence[jnp.ndarray], *, num_keys: int = 1,
                      key_bounds: Optional[Sequence[Optional[int]]] = None,
                      interpret: bool = False) -> Tuple[jnp.ndarray, ...]:
    """LSD radix sort of 1-D uint32 lanes; drop-in for ``lax.sort``.

    The first ``num_keys`` operands are lexicographic sort keys (most
    significant first — ``sort_lex_unstable``'s contract; split-lane
    64-bit keys pass (hi, lo) with ``num_keys=2``); the rest ride along as
    values.  ``key_bounds``, when given, holds one exclusive upper bound
    (or None) per key operand and shrinks that key's digit passes via
    ``num_radix_passes``.  Output order matches ``lax.sort`` exactly for
    any uint32 input — radix order *is* unsigned numeric order, sentinels
    (0xFFFFFFFE/0xFFFFFFFF pads) included.
    """
    arrs = [jnp.asarray(a) for a in operands]
    if not 1 <= num_keys <= len(arrs):
        raise ValueError(f"num_keys {num_keys} out of range for "
                         f"{len(arrs)} operands")
    first = arrs[0]
    for a in arrs:
        if a.ndim != 1 or a.shape != first.shape or a.dtype != jnp.uint32:
            raise ValueError(
                "radix sort wants equal-length 1-D uint32 lanes, got "
                f"{[(str(x.dtype), x.shape) for x in arrs]}")
    if key_bounds is not None and len(key_bounds) != num_keys:
        raise ValueError(f"key_bounds has {len(key_bounds)} entries for "
                         f"{num_keys} keys")
    n = first.shape[0]
    if n <= 1:
        return tuple(arrs)
    # least-significant key first; per-pass stability chains the passes
    # into a lexicographic sort across keys
    for ki in range(num_keys - 1, -1, -1):
        bound = None if key_bounds is None else key_bounds[ki]
        for p in range(num_radix_passes(bound)):
            slots = radix_pass_slots_pallas(
                arrs[ki], shift=RADIX_BITS * p, interpret=interpret)
            arrs = _apply_permutation(slots, arrs)
    return tuple(arrs)


def pallas_radix_sort_available() -> bool:
    """True when the compiled radix sort can run — same backend probe as
    the partition kernel (never initializes the backend)."""
    return pallas_partition_available()
