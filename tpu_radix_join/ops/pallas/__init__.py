from tpu_radix_join.ops.pallas.merge_scan import merge_scan_chunks, pallas_available

__all__ = ["merge_scan_chunks", "pallas_available"]
