"""Sort-merge match counting: the fast TPU probe discipline.

Replaces the searchsorted-based probe where profiling on v5e shows
``jnp.searchsorted(method='sort')`` costs ~470ms at 16M keys (it re-sorts per
side) while a single combined sort costs ~80ms.  This is the TPU-idiomatic
realisation of BuildProbe (tasks/BuildProbe.cpp:47-121): where the reference
chases hash-bucket chains per tuple, we sort the *union* of both key sets once
and recover every outer tuple's duplicate-aware match count with cumulative
scans — no random gathers, no per-tuple loops, everything a sort or a scan.

Scheme (keys must fit 31 bits; the pipeline's key-range check enforces it):

  packed = key << 1 | side_tag        (R tag 0 sorts before S within a key)
  sort packed;  runs of equal key are contiguous, R-part first.
  c_r[i]        = inclusive cumsum of "is R"
  base_run[i]   = c_r just before this run's start (cummax propagation)
  weight[i]     = is_S[i] ? c_r[i] - base_run[i] : 0     # |R with equal key|
  matches       = sum(weight)   (chunked uint32 partial sums, host uint64 total)

Padding slots (side sentinels, tuples.py) map to two reserved top key values
with no cross-side partner, so they contribute zero without any masking pass.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# Largest valid key for the merge path (inclusive): 31-bit packing with two
# reserved pad key slots (0x7FFFFFFE, 0x7FFFFFFF) above it.  The pipeline's
# keys_ok check enforces key <= MAX_MERGE_KEY; violations are routed to the
# pad values here (no match) and flagged there.
MAX_MERGE_KEY = 0x7FFFFFFD
# Plain ints, not jnp scalars: module import must never initialize a backend.
_R_PACK_PAD = 0xFFFFFFFC   # key slot 0x7FFFFFFE, tag 0
_S_PACK_PAD = 0xFFFFFFFF   # key slot 0x7FFFFFFF, tag 1

# The packed value carries the side tag, so equal values are fully
# interchangeable and an unstable sort loses nothing (ops/sorting.py).
from tpu_radix_join.ops.sorting import sort_unstable as _sort_unstable


def _pack(r_keys: jnp.ndarray, s_keys: jnp.ndarray) -> jnp.ndarray:
    one = jnp.uint32(1)
    r_ok = r_keys <= jnp.uint32(MAX_MERGE_KEY)
    s_ok = s_keys <= jnp.uint32(MAX_MERGE_KEY)
    pr = jnp.where(r_ok, r_keys << one, jnp.uint32(_R_PACK_PAD))
    ps = jnp.where(s_ok, (s_keys << one) | one, jnp.uint32(_S_PACK_PAD))
    return jnp.concatenate([pr, ps])


def _weights(packed_sorted: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(weight per position, key per position) for the sorted packed array."""
    one = jnp.uint32(1)
    key = packed_sorted >> one
    is_s = (packed_sorted & one).astype(jnp.uint32)
    is_r = one - is_s
    c_r = jnp.cumsum(is_r, dtype=jnp.uint32)
    prev_key = jnp.concatenate([jnp.full((1,), 0xFFFFFFFF, jnp.uint32), key[:-1]])
    run_start = key != prev_key
    # c_r *before* the run start, propagated across the run via cummax
    # (c_r is monotone non-decreasing, so cummax of the starts is exact).
    base_at_start = jnp.where(run_start, c_r - is_r, jnp.uint32(0))
    base_run = jax.lax.cummax(base_at_start)
    weight = is_s * (c_r - base_run)
    return weight, key


def merge_count_chunks(r_keys: jnp.ndarray, s_keys: jnp.ndarray,
                       num_chunks: int = 4096) -> jnp.ndarray:
    """Match count as uint32 partial sums over fixed position chunks
    (sum on host in uint64).  Safe against uint32 overflow as long as any
    ``(n/num_chunks)``-position window's weights stay < 2**32 — guaranteed
    when per-key inner multiplicity * chunk width < 2**32 (canonical
    workloads: inner multiplicity ~1)."""
    packed = _sort_unstable(_pack(r_keys, s_keys))
    weight, _ = _weights(packed)
    n = weight.shape[0]
    c = max(1, num_chunks)
    pad = (-n) % c
    weight = jnp.concatenate([weight, jnp.zeros((pad,), jnp.uint32)])
    return jnp.sum(weight.reshape(c, -1), axis=1, dtype=jnp.uint32)


def merge_count_pallas(r_keys: jnp.ndarray, s_keys: jnp.ndarray,
                       interpret: bool = False) -> jnp.ndarray:
    """Match counting with the fused Pallas scan kernel for the post-sort
    phase (ops/pallas/merge_scan.py): sort + ONE pass instead of sort + ~5
    XLA scan passes.  Returns uint32 per-tile partial counts (host uint64
    sum).  Pads to the kernel tile size with the S pack-pad (sorts last,
    weight 0)."""
    from tpu_radix_join.ops.pallas.merge_scan import TILE, merge_scan_chunks
    packed = _pack(r_keys, s_keys)
    n = packed.shape[0]
    pad = (-n) % TILE
    if pad:
        packed = jnp.concatenate(
            [packed, jnp.full((pad,), _S_PACK_PAD, jnp.uint32)])
    return merge_scan_chunks(_sort_unstable(packed), interpret=interpret)


def merge_count_per_partition(r_keys: jnp.ndarray, s_keys: jnp.ndarray,
                              fanout_bits: int) -> jnp.ndarray:
    """Per-network-partition match counts, uint32 [1 << fanout_bits].

    One extra scatter-add pass (bincount) over the sort order; partitions are
    the low key bits so they interleave in sorted order.  Each partition's
    count must stay < 2**32 (SURVEY.md §7.4 item 2 contract)."""
    packed = _sort_unstable(_pack(r_keys, s_keys))
    weight, key = _weights(packed)
    pid = (key & jnp.uint32((1 << fanout_bits) - 1)).astype(jnp.int32)
    return jnp.bincount(pid, weights=weight, length=1 << fanout_bits).astype(jnp.uint32)
