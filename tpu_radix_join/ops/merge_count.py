"""Sort-merge match counting: the fast TPU probe discipline.

Replaces the searchsorted-based probe where profiling on v5e shows
``jnp.searchsorted(method='sort')`` costs ~470ms at 16M keys (it re-sorts per
side) while a single combined sort costs ~80ms.  This is the TPU-idiomatic
realisation of BuildProbe (tasks/BuildProbe.cpp:47-121): where the reference
chases hash-bucket chains per tuple, we sort the *union* of both key sets once
and recover every outer tuple's duplicate-aware match count with cumulative
scans — no random gathers, no per-tuple loops, everything a sort or a scan.

Scheme (keys must fit 31 bits; the pipeline's key-range check enforces it):

  packed = key << 1 | side_tag        (R tag 0 sorts before S within a key)
  sort packed;  runs of equal key are contiguous, R-part first.
  c_r[i]        = inclusive cumsum of "is R"
  base_run[i]   = c_r just before this run's start (cummax propagation)
  weight[i]     = is_S[i] ? c_r[i] - base_run[i] : 0     # |R with equal key|
  matches       = sum(weight)   (chunked uint32 partial sums, host uint64 total)

Padding slots (side sentinels, tuples.py) map to two reserved top key values
with no cross-side partner, so they contribute zero without any masking pass.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# Largest valid key for the merge path (inclusive): 31-bit packing with two
# reserved pad key slots (0x7FFFFFFE, 0x7FFFFFFF) above it.  The pipeline's
# keys_ok check enforces key <= MAX_MERGE_KEY; violations are routed to the
# pad values here (no match) and flagged there.
MAX_MERGE_KEY = 0x7FFFFFFD
# Plain ints, not jnp scalars: module import must never initialize a backend.
_R_PACK_PAD = 0xFFFFFFFC   # key slot 0x7FFFFFFE, tag 0
_S_PACK_PAD = 0xFFFFFFFF   # key slot 0x7FFFFFFF, tag 1

# The packed value carries the side tag, so equal values are fully
# interchangeable and an unstable sort loses nothing (ops/sorting.py).
from tpu_radix_join.ops.sorting import (
    sort_lex_unstable as _sort_lex_unstable,
    sort_unstable as _sort_unstable,
)


def _resolve_impl(impl: str | None, fanout_bits: int) -> str:
    """Shared impl auto-routing for every count discipline: the fused Pallas
    kernels on TPU (their SMEM accumulators cap the partition count at 128),
    the portable XLA scans elsewhere."""
    if impl is not None:
        return impl
    from tpu_radix_join.ops.pallas.merge_scan import pallas_available
    return ("pallas" if (pallas_available() and (1 << fanout_bits) <= 128)
            else "xla")


def _pack(r_keys: jnp.ndarray, s_keys: jnp.ndarray) -> jnp.ndarray:
    one = jnp.uint32(1)
    r_ok = r_keys <= jnp.uint32(MAX_MERGE_KEY)
    s_ok = s_keys <= jnp.uint32(MAX_MERGE_KEY)
    pr = jnp.where(r_ok, r_keys << one, jnp.uint32(_R_PACK_PAD))
    ps = jnp.where(s_ok, (s_keys << one) | one, jnp.uint32(_S_PACK_PAD))
    return jnp.concatenate([pr, ps])


def _run_weights(is_s: jnp.ndarray, run_start: jnp.ndarray) -> jnp.ndarray:
    """Per-position match weights for a sorted sequence: at every S position,
    the number of R tuples in its equal-key run (the module docstring's
    cumsum/cummax scheme).  ``is_s``: uint32 0/1 side tags in sort order
    (R before S within a run); ``run_start``: bool, True where a new
    equal-key run begins."""
    is_r = jnp.uint32(1) - is_s
    c_r = jnp.cumsum(is_r, dtype=jnp.uint32)
    # c_r *before* the run start, propagated across the run via cummax
    # (c_r is monotone non-decreasing, so cummax of the starts is exact).
    base_at_start = jnp.where(run_start, c_r - is_r, jnp.uint32(0))
    base_run = jax.lax.cummax(base_at_start)
    return is_s * (c_r - base_run)


def _weights(packed_sorted: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(weight per position, key per position) for the sorted packed array."""
    one = jnp.uint32(1)
    key = packed_sorted >> one
    is_s = (packed_sorted & one).astype(jnp.uint32)
    prev_key = jnp.concatenate([jnp.full((1,), 0xFFFFFFFF, jnp.uint32), key[:-1]])
    return _run_weights(is_s, key != prev_key), key


@jax.jit
def presort_keys(keys: jnp.ndarray) -> jnp.ndarray:
    """Sort a raw key lane once for reuse across many probes.

    The sorted array is the "inner side" input of
    :func:`merge_count_presorted`: the out-of-core grid sorts each inner
    chunk once per grid *row* and probes every outer chunk of the row
    against it, eliminating the ``(n_outer_chunks - 1)`` redundant sorts
    the packed-union discipline pays per row (ops/chunked.py pipeline).
    No packing, no side tag: the raw uint32 keys sort as-is, so the full
    sub-sentinel key range is supported without the 31-bit
    :data:`MAX_MERGE_KEY` ceiling."""
    return _sort_unstable(keys)


def merge_count_presorted(r_sorted: jnp.ndarray, s_keys: jnp.ndarray,
                          return_max_weight: bool = False):
    """Duplicate-aware match count of ``s_keys`` against an ALREADY-SORTED
    inner key lane (:func:`presort_keys` output): two binary searches per
    outer key — ``upper_bound - lower_bound`` over the sorted inner is
    exactly the per-outer-tuple match weight — instead of re-sorting the
    packed union per probe.  O(m log n) gathers against the resident
    sorted inner; on the sort-bound grid engine this converts the per-pair
    sort into a once-per-row sort.

    Key-range discipline: none needed — raw uint32 comparisons cover every
    sub-sentinel key, so there is no narrow/full split on this path.  The
    caller must keep real keys out of the reserved sentinel range
    (``<= 0xFFFFFFFD``, tuples.py): an outer S pad (0xFFFFFFFF) can then
    never equal an inner key and contributes zero weight, and an inner
    sentinel would silently pad-match — the grid's per-chunk key-bound
    check (ops/chunked.py) enforces this loudly.

    Returns the uint32 total (overflow-safe iff ``max_weight * len(s_keys)
    < 2**32``, the same window guard as ``merge_count_chunks``);
    ``return_max_weight`` also returns the max per-outer-tuple weight."""
    lb = jnp.searchsorted(r_sorted, s_keys, side="left").astype(jnp.uint32)
    ub = jnp.searchsorted(r_sorted, s_keys, side="right").astype(jnp.uint32)
    weight = ub - lb
    total = jnp.sum(weight, dtype=jnp.uint32)
    if return_max_weight:
        return total, jnp.max(weight)
    return total


def merge_count_chunks(r_keys: jnp.ndarray, s_keys: jnp.ndarray,
                       num_chunks: int = 4096,
                       return_max_weight: bool = False):
    """Match count as uint32 partial sums over fixed position chunks
    (sum on host in uint64).  Safe against uint32 overflow as long as any
    ``(n/num_chunks)``-position window's weights stay < 2**32 — guaranteed
    when per-key inner multiplicity * chunk width < 2**32 (canonical
    workloads: inner multiplicity ~1).  ``return_max_weight`` also returns
    the max single-outer-tuple match count (uint32 scalar), from which the
    caller checks that guarantee at runtime (``max_weight * chunk_width <
    2**32``, see ops/chunked.chunked_join_count)."""
    packed = _sort_unstable(_pack(r_keys, s_keys))
    weight, _ = _weights(packed)
    n = weight.shape[0]
    c = max(1, num_chunks)
    pad = (-n) % c
    weight = jnp.concatenate([weight, jnp.zeros((pad,), jnp.uint32)])
    counts = jnp.sum(weight.reshape(c, -1), axis=1, dtype=jnp.uint32)
    if return_max_weight:
        return counts, jnp.max(weight)
    return counts


def merge_count_pallas(r_keys: jnp.ndarray, s_keys: jnp.ndarray,
                       interpret: bool = False) -> jnp.ndarray:
    """Match counting with the fused Pallas scan kernel for the post-sort
    phase (ops/pallas/merge_scan.py): sort + ONE pass instead of sort + ~5
    XLA scan passes.  Returns uint32 per-tile partial counts (host uint64
    sum).  Pads to the kernel tile size with the S pack-pad (sorts last,
    weight 0)."""
    from tpu_radix_join.ops.pallas.merge_scan import TILE, merge_scan_chunks
    packed = _pack(r_keys, s_keys)
    n = packed.shape[0]
    pad = (-n) % TILE
    if pad:
        packed = jnp.concatenate(
            [packed, jnp.full((pad,), _S_PACK_PAD, jnp.uint32)])
    return merge_scan_chunks(_sort_unstable(packed), interpret=interpret)


def _pack_pm(r_keys: jnp.ndarray, s_keys: jnp.ndarray,
             fanout_bits: int) -> jnp.ndarray:
    """Partition-major packing: ``pid | key_remainder | side_tag`` from top to
    bottom bits, so a single sort groups tuples by network partition first and
    by full key within it (equal full keys stay adjacent: same pid + same
    remainder).  This is what lets the fused Pallas kernel accumulate
    per-partition counts with ~2 active reductions per tile
    (merge_scan._kernel_partitions).

    Pad handling mirrors ``_pack``: out-of-range keys map to the reserved key
    slots 0x7FFFFFFE (R) / 0x7FFFFFFF (S), which land at the TOP of the
    remainder range of partitions (P-2) and (P-1) — interior to the array,
    not at its end, but in runs no cross-side real key can share (real keys
    <= MAX_MERGE_KEY exclude exactly those two (pid, remainder) pairs), so
    they carry zero weight wherever they sort."""
    one = jnp.uint32(1)
    f = jnp.uint32(fanout_bits)
    mask = jnp.uint32((1 << fanout_bits) - 1)

    def pm(keys, ok, pad_key, tag):
        k = jnp.where(ok, keys, jnp.uint32(pad_key))
        pid = k & mask
        rem = k >> f
        if fanout_bits:
            top = pid << jnp.uint32(32 - fanout_bits)
        else:
            top = jnp.uint32(0)
        return top | (rem << one) | jnp.uint32(tag)

    r_ok = r_keys <= jnp.uint32(MAX_MERGE_KEY)
    s_ok = s_keys <= jnp.uint32(MAX_MERGE_KEY)
    return jnp.concatenate([
        pm(r_keys, r_ok, 0x7FFFFFFE, 0),
        pm(s_keys, s_ok, 0x7FFFFFFF, 1),
    ])


def merge_count_per_partition(r_keys: jnp.ndarray, s_keys: jnp.ndarray,
                              fanout_bits: int,
                              impl: str | None = None,
                              return_max_weight: bool = False):
    """Per-network-partition match counts, uint32 [1 << fanout_bits].

    Each partition's count must stay < 2**32 (SURVEY.md §7.4 item 2
    contract).  ``impl``: None = auto (fused Pallas kernel on TPU, XLA
    elsewhere), or one of "xla", "pallas", "pallas_interpret".

    The Pallas path sorts in partition-major packing and fuses the weight
    scan + per-partition accumulation into one pass
    (merge_scan.merge_scan_partitions); the XLA path is the portable
    fallback: low-bit packing + a weights bincount (a scatter-add XLA
    serializes on TPU — measured 375.7 ms vs ~55 ms total for the Pallas
    path at 16M⋈16M, round 2).

    ``return_max_weight`` also returns the max single-outer-tuple match
    count (uint32 scalar; free in the Pallas pass, one extra reduction in
    XLA) — the driver's overflow-risk bound input (hash_join._count_risk):
    a partition's count is <= max_weight x its outer tuple count, so the
    guard needs no wider accumulators (the reference is immune via its
    uint64 RESULT_COUNTER, HashJoin.h:26; uint32 counts + this bound are
    the no-device-int64 equivalent).
    """
    impl = _resolve_impl(impl, fanout_bits)
    if impl == "xla":
        packed = _sort_unstable(_pack(r_keys, s_keys))
        weight, key = _weights(packed)
        pid = (key & jnp.uint32((1 << fanout_bits) - 1)).astype(jnp.int32)
        counts = jnp.bincount(pid, weights=weight,
                              length=1 << fanout_bits).astype(jnp.uint32)
        if return_max_weight:
            return counts, jnp.max(weight)
        return counts
    from tpu_radix_join.ops.pallas.merge_scan import TILE, merge_scan_partitions
    packed = _sort_unstable(_pack_pm(r_keys, s_keys, fanout_bits))
    pad = (-packed.shape[0]) % TILE
    if pad:
        # post-sort padding: 0xFFFFFFFF is the partition-major S pad (all-ones
        # pid and remainder), >= every packed value, so sortedness holds
        packed = jnp.concatenate(
            [packed, jnp.full((pad,), _S_PACK_PAD, jnp.uint32)])
    counts, maxw = merge_scan_partitions(
        packed, num_partitions=1 << fanout_bits,
        interpret=(impl == "pallas_interpret"))
    if return_max_weight:
        return counts, maxw
    return counts


def merge_count_per_partition_full(r_keys: jnp.ndarray, s_keys: jnp.ndarray,
                                   fanout_bits: int,
                                   impl: str | None = None,
                                   return_max_weight: bool = False):
    """Full-range uint32 merge count: accepts every sub-sentinel key
    (``key <= 0xFFFFFFFD`` — the R/S pad values stay reserved, tuples.py),
    removing the 31-bit :data:`MAX_MERGE_KEY` ceiling of the packed path.

    Discipline: a 2-key lexicographic unstable sort on (pid-rotated key,
    side tag) — the explicit tag lane keeps every equal-key run's R tuples
    ahead of its S tuples, doing the job of the packing's stolen bit — then
    the usual cumsum/cummax weight pass.  Per-partition counts come from
    prefix-sum differences at the P+1 partition boundary positions of the
    pid-major order (``searchsorted``, P scalar binary searches) instead of
    a weights bincount: a scatter-add XLA serializes on TPU (measured ~98ms
    per 16M pass) while the boundary gather is O(P log n).  The uint32
    prefix sums may wrap; boundary differences stay exact modulo 2**32, so
    each partition's count is exact under the pipeline's "partition count
    < 2**32" contract (guarded by ``max_weight`` at the call sites).

    Cost: a 2-lane sort, ~1.7x the packed single-lane path — the engine
    routes here only when keys exceed the packing (config.key_range) and it
    beats the 3-lane ``key_bits=64`` escape (~2.6x).  The reference needs no
    analog: its hash-bucket chains never pack key bits (BuildProbe.cpp:81-106).

    ``impl`` as in :func:`merge_count_per_partition`: on TPU the post-sort
    scan fuses into one Pallas pass by feeding the wide kernel a zero hi
    lane — run equality on (rot, 0) degenerates to run equality on rot, so
    ``merge_scan_partitions_wide`` computes exactly these counts; "xla" is
    the portable scan-passes + boundary-differences fallback.
    """
    impl = _resolve_impl(impl, fanout_bits)
    rot = jnp.concatenate([_rotate_pid(r_keys, fanout_bits),
                           _rotate_pid(s_keys, fanout_bits)])
    tag = jnp.concatenate([
        jnp.zeros(r_keys.shape, jnp.uint32), jnp.ones(s_keys.shape, jnp.uint32)])
    rot, tag = _sort_lex_unstable(rot, tag, num_keys=2)
    if impl != "xla":
        from tpu_radix_join.ops.pallas.merge_scan import (
            TILE, merge_scan_partitions_wide)
        n = rot.shape[0]
        pad = (-n) % TILE
        if pad:
            # post-sort padding with the (all-ones rot, tag 1) S-pad image:
            # the lexicographic maximum (real keys stay below the sentinels,
            # so their rotations never reach all-ones), zero weight
            ones = jnp.full((pad,), 0xFFFFFFFF, jnp.uint32)
            rot = jnp.concatenate([rot, ones])
            tag = jnp.concatenate([tag, jnp.ones((pad,), jnp.uint32)])
        # hi derived FROM rot — not a fresh constant — so it inherits rot's
        # varying-manual-axes annotation inside shard_map-traced pipelines
        # (a fresh zero lane fails pallas_call's vma consistency check):
        # zero for real keys, all-ones on the pad image (rot == all-ones is
        # unreachable for real keys by the sentinel contract)
        hi = jnp.where(rot == jnp.uint32(0xFFFFFFFF), rot,
                       rot & jnp.uint32(0))
        counts, maxw = merge_scan_partitions_wide(
            rot, hi, tag, num_partitions=1 << fanout_bits,
            interpret=(impl == "pallas_interpret"))
        if return_max_weight:
            return counts, maxw
        return counts
    prev = jnp.concatenate(
        [jnp.full((1,), 0xFFFFFFFF, jnp.uint32), rot[:-1]])
    # position 0: the synthetic prev (all-ones) can only suppress a run
    # start when rot[0] is itself the global-max value — i.e. every element
    # is an S pad, whose weights are zero regardless
    weight = _run_weights(tag, rot != prev)
    cw = jnp.concatenate([jnp.zeros((1,), jnp.uint32),
                          jnp.cumsum(weight, dtype=jnp.uint32)])
    if fanout_bits:
        bnd_vals = (jnp.arange(1 << fanout_bits, dtype=jnp.uint32)
                    << jnp.uint32(32 - fanout_bits))
        idx = jnp.searchsorted(rot, bnd_vals)
        idx = jnp.concatenate(
            [idx, jnp.full((1,), rot.shape[0], idx.dtype)])
        counts = cw[idx[1:]] - cw[idx[:-1]]
    else:
        counts = cw[-1:]
    if return_max_weight:
        return counts, jnp.max(weight)
    return counts


def _rotate_pid(lo: jnp.ndarray, fanout_bits: int) -> jnp.ndarray:
    """Rotate the low key lane right by ``fanout_bits`` so the partition id
    occupies the top bits: sorting by (lo_rot, hi) groups by partition first,
    then by (key remainder, hi) — equal (hi, lo) keys stay adjacent, which is
    all the weight scan needs (run equality, not numeric order)."""
    if not fanout_bits:
        return lo
    f = jnp.uint32(fanout_bits)
    return (lo << jnp.uint32(32 - fanout_bits)) | (lo >> f)


def merge_count_wide_per_partition(
    r_lo: jnp.ndarray, r_hi: jnp.ndarray,
    s_lo: jnp.ndarray, s_hi: jnp.ndarray,
    fanout_bits: int,
    impl: str | None = None,
    return_max_weight: bool = False,
):
    """64-bit-key match counting without 64-bit arithmetic.

    TPU int64 is limited/slow (SURVEY.md §7.4 item 3), so wide keys ride as
    two uint32 lanes and the combined sort is a three-key lexicographic
    ``lax.sort`` — the tag key keeps every equal-key run's R tuples ahead of
    its S tuples, exactly what the 31-bit packing achieves in the single-lane
    path.  The weight scheme is the module's usual cumsum/cummax pass with
    run boundaries on (hi, lo).  No jax x64 needed.

    ``impl`` as in :func:`merge_count_per_partition`: the TPU path sorts by
    (pid-rotated lo, hi, tag) and fuses the scan + per-partition histogram
    into one Pallas pass (merge_scan_partitions_wide); the XLA fallback
    sorts (hi, lo, tag) and bincounts the weights.

    Pad sentinels sit in BOTH lanes (make_padding wide=True), and R/S pads
    differ in the hi lane, so padding contributes zero weight on either path.
    ``return_max_weight`` as in :func:`merge_count_per_partition`.
    """
    impl = _resolve_impl(impl, fanout_bits)
    hi = jnp.concatenate([r_hi, s_hi])
    lo = jnp.concatenate([r_lo, s_lo])
    tag = jnp.concatenate([
        jnp.zeros(r_lo.shape, jnp.uint32), jnp.ones(s_lo.shape, jnp.uint32)])
    if impl != "xla":
        from tpu_radix_join.ops.pallas.merge_scan import (
            TILE, merge_scan_partitions_wide)
        lo_rot, hi, tag = _sort_lex_unstable(
            _rotate_pid(lo, fanout_bits), hi, tag, num_keys=3)
        pad = (-lo_rot.shape[0]) % TILE
        if pad:
            # the wide S pad's image (all-ones lanes, tag 1) is the
            # lexicographic maximum, so post-sort padding keeps sortedness
            ones = jnp.full((pad,), 0xFFFFFFFF, jnp.uint32)
            lo_rot = jnp.concatenate([lo_rot, ones])
            hi = jnp.concatenate([hi, ones])
            tag = jnp.concatenate([tag, jnp.ones((pad,), jnp.uint32)])
        counts, maxw = merge_scan_partitions_wide(
            lo_rot, hi, tag, num_partitions=1 << fanout_bits,
            interpret=(impl == "pallas_interpret"))
        if return_max_weight:
            return counts, maxw
        return counts

    hi, lo, tag = _sort_lex_unstable(hi, lo, tag, num_keys=3)
    prev_hi = jnp.concatenate([jnp.full((1,), 0xFFFFFFFF, jnp.uint32), hi[:-1]])
    prev_lo = jnp.concatenate([jnp.full((1,), 0xFFFFFFFF, jnp.uint32), lo[:-1]])
    # position 0 is always a run start: (prev_hi, prev_lo) = the S pad pair,
    # which real keys can't equal (hi < 0xFFFFFFFE contract) — and if x[0] IS
    # an S pad, its weight is 0 anyway (no R pad shares the run).
    run_start = (hi != prev_hi) | (lo != prev_lo)
    weight = _run_weights(tag, run_start)
    pid = (lo & jnp.uint32((1 << fanout_bits) - 1)).astype(jnp.int32)
    counts = jnp.bincount(pid, weights=weight,
                          length=1 << fanout_bits).astype(jnp.uint32)
    if return_max_weight:
        return counts, jnp.max(weight)
    return counts
