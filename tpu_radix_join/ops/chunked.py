"""Out-of-core chunked join: relations larger than device memory.

Replaces the reference's ``LD`` (large data) GPU capability — histograms,
reorders and probes indexed by ``iterCount`` so relations bigger than GPU
memory stream through in 128M-tuple chunks (``data/data.hpp:13-20,69-84``;
``LD`` kernels ``operators/gpu/kernels.cu:563-858``).

TPU design: ``jax.lax.scan`` over probe-side slabs.  The build side is sorted
once and stays resident in HBM; each scan step counts one outer slab's
matches with the merge-count discipline against the sorted inner.  Because
scan reuses one compiled step, HBM working-set per step is
O(inner + slab) regardless of total outer size — the `lax.scan`-over-slabs
shape SURVEY.md §5.7 prescribes.  For inner sides that exceed memory as well,
``chunked_join_grid`` streams both sides (outer scan nested in a Python loop
over inner chunks, accumulating partial counts — every (i, j) chunk pair is
probed exactly once, matching the LD kernels' two-level iterCount indexing).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.ops.merge_count import merge_count_chunks


@functools.partial(jax.jit, static_argnames=("num_slabs",))
def _scan_probe(r_keys: jnp.ndarray, s_keys: jnp.ndarray, num_slabs: int):
    """Counts for s_keys split into ``num_slabs`` slabs, uint32 [num_slabs]."""
    slabs = s_keys.reshape(num_slabs, -1)

    def step(carry, slab):
        # per-slab partial counts; chunked uint32 sums stay overflow-safe
        c = merge_count_chunks(r_keys, slab, num_chunks=1024)
        return carry, jnp.sum(c, dtype=jnp.uint32)

    _, per_slab = jax.lax.scan(step, jnp.uint32(0), slabs)
    return per_slab


def chunked_join_count(r: TupleBatch, s: TupleBatch, slab_size: int) -> int:
    """Exact match count streaming the outer side in ``slab_size`` slabs.

    ``slab_size`` must divide the outer size (pad the relation with S
    sentinels otherwise — the generators always produce pow2-friendly sizes).
    """
    n = s.key.shape[0]
    if n % slab_size:
        raise ValueError(f"outer size {n} not divisible by slab size {slab_size}")
    per_slab = _scan_probe(r.key, s.key, n // slab_size)
    return int(np.asarray(per_slab).astype(np.uint64).sum())


def chunked_join_grid(r_chunks, s_chunks, slab_size: int) -> int:
    """Both sides streamed: iterables of TupleBatch chunks (host-resident);
    each inner chunk is joined against every outer chunk exactly once.

    ``s_chunks`` is consumed once per inner chunk, so a one-shot iterator
    (e.g. ``data/streaming.stream_chunks``) is materialized up front — a
    silently-exhausted generator would drop every outer chunk after the
    first inner one."""
    if not isinstance(s_chunks, (list, tuple)):
        s_chunks = list(s_chunks)
    total = 0
    for r in r_chunks:
        for s in s_chunks:
            total += chunked_join_count(r, s, min(slab_size, s.key.shape[0]))
    return total
