"""Out-of-core chunked join: relations larger than device memory.

Replaces the reference's ``LD`` (large data) GPU capability — histograms,
reorders and probes indexed by ``iterCount`` so relations bigger than GPU
memory stream through in 128M-tuple chunks (``data/data.hpp:13-20,69-84``;
``LD`` kernels ``operators/gpu/kernels.cu:563-858``).

TPU design: ``jax.lax.scan`` over probe-side slabs.  The build side is sorted
once and stays resident in HBM; each scan step counts one outer slab's
matches with the merge-count discipline against the sorted inner.  Because
scan reuses one compiled step, HBM working-set per step is
O(inner + slab) regardless of total outer size — the `lax.scan`-over-slabs
shape SURVEY.md §5.7 prescribes.  For inner sides that exceed memory as well,
``chunked_join_grid`` streams both sides (outer scan nested in a Python loop
over inner chunks, accumulating partial counts — every (i, j) chunk pair is
probed exactly once, matching the LD kernels' two-level iterCount indexing).
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.ops.merge_count import (
    MAX_MERGE_KEY,
    merge_count_chunks,
    merge_count_per_partition_full,
    merge_count_wide_per_partition,
)


@functools.partial(jax.jit, static_argnames=("num_slabs",))
def _scan_probe(r_keys: jnp.ndarray, s_keys: jnp.ndarray, num_slabs: int):
    """(per-slab counts uint32 [num_slabs], max single-tuple match weight)
    for s_keys split into ``num_slabs`` slabs.  The max weight feeds the
    caller's uint32-overflow guard (chunked_join_count)."""
    slabs = s_keys.reshape(num_slabs, -1)

    def step(carry, slab):
        # per-slab partial counts; chunked uint32 sums stay overflow-safe
        # as long as the caller-checked weight bound holds
        c, mw = merge_count_chunks(r_keys, slab, num_chunks=1024,
                                   return_max_weight=True)
        return carry, (jnp.sum(c, dtype=jnp.uint32), mw)

    _, (per_slab, mws) = jax.lax.scan(step, jnp.uint32(0), slabs)
    return per_slab, jnp.max(mws)


@functools.partial(jax.jit, static_argnames=("num_slabs",))
def _scan_probe_full(r_keys: jnp.ndarray, s_keys: jnp.ndarray,
                     num_slabs: int):
    """Full-key-range twin of :func:`_scan_probe`: the 2-key lexicographic
    count (merge_count_per_partition_full, fanout 0) for workloads whose
    keys exceed the 31-bit packing — which would silently map to the
    reserved pack-pads (zero matches) in the packed discipline."""
    slabs = s_keys.reshape(num_slabs, -1)

    def step(carry, slab):
        c, mw = merge_count_per_partition_full(r_keys, slab, 0,
                                               return_max_weight=True)
        return carry, (c[0], mw)

    _, (per_slab, mws) = jax.lax.scan(step, jnp.uint32(0), slabs)
    return per_slab, jnp.max(mws)


@functools.partial(jax.jit, static_argnames=("num_slabs",))
def _scan_probe_wide(r_lo, r_hi, s_lo, s_hi, num_slabs: int):
    """Wide-key (hi/lo lane) twin of :func:`_scan_probe`."""
    slabs = (s_lo.reshape(num_slabs, -1), s_hi.reshape(num_slabs, -1))

    def step(carry, slab):
        lo, hi = slab
        c, mw = merge_count_wide_per_partition(r_lo, r_hi, lo, hi, 0,
                                               return_max_weight=True)
        return carry, (jnp.sum(c, dtype=jnp.uint32), mw)

    _, (per_slab, mws) = jax.lax.scan(step, jnp.uint32(0), slabs)
    return per_slab, jnp.max(mws)


def chunked_join_count(r: TupleBatch, s: TupleBatch, slab_size: int,
                       key_range: str = "auto") -> int:
    """Exact match count streaming the outer side in ``slab_size`` slabs.

    Ragged sizes (streamed chunks, short final chunks) are padded up to a
    slab multiple with the outer-side sentinel, which matches nothing by the
    pad-key contract (tuples.py).  Wide (64-bit) batches — e.g. from a
    ``Relation(key_bits=64)`` stream — take the hi/lo lexicographic count;
    mixed-width inputs raise rather than silently truncate.

    ``key_range`` mirrors ``JoinConfig.key_range`` for the 32-bit path:
    "auto" probes the chunks' max key (2 HBM scans + a readback per call)
    and routes keys above the 31-bit packing to the full-range count;
    callers with a static bound — e.g. grid drivers over unique Relations,
    whose keys never reach 2**31 (relation.py size cap) — pass "narrow"
    (or "full") to skip the probe on every grid pair.
    """
    if key_range not in ("auto", "narrow", "full"):
        raise ValueError(f"unknown key range mode {key_range!r}")
    from tpu_radix_join.data.tuples import pad_sentinel
    if (r.key_hi is None) != (s.key_hi is None):
        raise ValueError(
            "mixed key widths: one side carries a key_hi lane and the other "
            "does not — refusing to run a silently-truncated join")
    keys = s.key
    n = keys.shape[0]
    pad = (-n) % slab_size
    fill = pad_sentinel("outer")
    mx_narrow = None
    if pad:
        keys = jnp.concatenate(
            [keys, jnp.full((pad,), fill, keys.dtype)])
    if r.key_hi is not None:
        s_hi = s.key_hi
        if pad:
            # sentinel in BOTH lanes (the make_padding wide=True contract)
            s_hi = jnp.concatenate(
                [s_hi, jnp.full((pad,), fill, s_hi.dtype)])
        per_slab, maxw = _scan_probe_wide(r.key, r.key_hi, keys, s_hi,
                                          (n + pad) // slab_size)
    else:
        # keys above the 31-bit packing would silently land on the reserved
        # pack-pads (zero matches) in merge_count_chunks; under "auto",
        # probe the real max (pre-padding — the sentinel fill is always the
        # uint32 max) and route to the full-range lexicographic count
        full = key_range == "full"
        if key_range == "auto":
            mx = int(np.asarray(jnp.maximum(jnp.max(r.key), jnp.max(s.key))))
            if mx >= int(pad_sentinel("inner")):
                from tpu_radix_join.robustness.verify import DataCorruption
                raise DataCorruption(
                    f"keys reach the pad sentinel range (max {mx:#x}): "
                    f"uint32 keys must stay <= "
                    f"{int(pad_sentinel('inner')) - 1:#x} — a key lane in "
                    f"the sentinel range is the streamed-lane corruption "
                    f"signature (such tuples would silently pad-match)")
            full = mx > MAX_MERGE_KEY
        if full:
            per_slab, maxw = _scan_probe_full(r.key, keys,
                                              (n + pad) // slab_size)
        else:
            per_slab, maxw = _scan_probe(r.key, keys, (n + pad) // slab_size)
            if key_range == "narrow":
                # "narrow" asserts a static key bound instead of paying
                # "auto"'s pre-scan sync — but an asserted contract still
                # has to be *checked*: keys above the 31-bit packing land on
                # the reserved pack-pads and count zero matches, an
                # undercount with ok-looking output.  Dispatch the max-key
                # reduction after the scan so it rides the maxw readback
                # below (detection without the extra sync point).
                mx_narrow = jnp.maximum(jnp.max(r.key), jnp.max(s.key))
    if mx_narrow is not None:
        mx = int(np.asarray(mx_narrow))
        if mx > MAX_MERGE_KEY:
            raise ValueError(
                f"key contract violation: key_range='narrow' but max key "
                f"{mx:#x} exceeds the 31-bit packing limit "
                f"{MAX_MERGE_KEY:#x} — such keys pack to the reserved "
                f"zero-match pads (silent undercount); use key_range='full' "
                f"or 'auto'")
    # uint32-overflow guard: every accumulation window (the per-slab total
    # and the 1024-position chunk partials inside it) is bounded by
    # max_weight x window width; a wrapped window would return a wrong count
    # silently (the reference's uint64 RESULT_COUNTER is immune, HashJoin.h:26)
    window = max(slab_size, -(-(r.key.shape[0] + slab_size) // 1024))
    if int(np.asarray(maxw)) > (2**32 - 1) // window:
        raise OverflowError(
            f"uint32 count-window overflow risk: max inner multiplicity "
            f"{int(np.asarray(maxw))} x window {window} can reach 2**32 — "
            f"shrink slab_size or deduplicate the inner side")
    return int(np.asarray(per_slab).astype(np.uint64).sum())


def chunked_join_grid(r_chunks, s_chunks, slab_size: int,
                      checkpoint_path: str | None = None,
                      checkpoint_tag: str = "",
                      progress: bool = False,
                      key_range: str = "auto",
                      measurements=None,
                      retry_policy=None,
                      retry_on=None,
                      plan=None) -> int:
    """Both sides streamed; each inner chunk is joined against every outer
    chunk exactly once.

    ``s_chunks`` is consumed once per inner chunk, so pass either a
    re-iterable (list/tuple) or — for outer sides too large to keep resident
    — a zero-argument factory returning a fresh iterator per inner chunk
    (e.g. ``lambda: stream_chunks(s_rel, node, c)``), which keeps device
    memory at O(chunk).  A bare one-shot iterator is materialized up front
    (resident, but never silently exhausted).

    ``checkpoint_path`` adds resume support for long grid joins — a
    capability the single-shot reference lacks entirely (SURVEY.md §5.4):
    after every (inner, outer) chunk pair the accumulated count and the next
    pair's (i, j) indices are written atomically (fsync + rename); a rerun
    with the same arguments skips completed pairs (skipped chunks are
    regenerated but not probed — generation is cheap, probes are not).  The
    file is left in place on completion with ``"done": true``.  A
    fingerprint (slab size + caller-supplied ``checkpoint_tag`` + the
    planner's strategy/chunking when a ``plan`` is given) guards
    against resuming a different join from a stale file — pass a tag that
    identifies the input relations; mismatches raise instead of silently
    returning the wrong total, and unreadable files restart from zero.
    Checkpoint mechanics (atomic rename, corruption policy, counters) live
    in robustness/checkpoint.CheckpointManager.

    ``measurements`` (optional) receives CKPTSAVE/CKPTLOAD from the
    manager plus GRIDPAIRS — the number of chunk pairs actually probed,
    which a resumed run keeps at (total pairs - completed pairs): the
    zero-recompute guarantee tests assert on.  ``retry_policy`` (a
    robustness.retry.RetryPolicy) retries each pair probe on transient
    errors (``retry_on`` exception classes, default the injectable
    TransientFault) — the chip-tunnel hiccup that killed three rounds of
    128M/1B grids (VERDICT r5) instead of costing one backoff.
    """
    if callable(s_chunks):
        s_iter = s_chunks
    else:
        if not isinstance(s_chunks, (list, tuple)):
            s_chunks = list(s_chunks)
        s_iter = lambda: s_chunks

    if checkpoint_path and not checkpoint_tag:
        raise ValueError(
            "checkpoint_path requires a checkpoint_tag identifying the input "
            "relations — an untagged checkpoint resumed against different "
            "data would silently return a wrong total")
    from tpu_radix_join.performance.measurements import GRIDPAIRS
    from tpu_radix_join.robustness import faults as _faults
    from tpu_radix_join.robustness.checkpoint import CheckpointManager
    from tpu_radix_join.robustness.retry import execute as _retry_execute

    fingerprint = {"slab": int(slab_size), "tag": checkpoint_tag,
                   "rows": len(r_chunks) if isinstance(r_chunks, (list, tuple))
                   else None,
                   "cols": len(s_chunks) if isinstance(s_chunks, (list, tuple))
                   else None}
    if plan is not None:
        # a planner-driven grid (main.py --plan) folds the plan identity in:
        # resuming under a different chunking or strategy walks a different
        # grid, so the stale checkpoint must mismatch, not mis-resume
        fingerprint["plan"] = {"strategy": plan.strategy,
                               "chunk_tuples": plan.chunk_tuples}
    ckpt = (CheckpointManager(checkpoint_path, fingerprint, measurements)
            if checkpoint_path else None)
    start_i, start_j, total = 0, 0, 0
    if ckpt is not None:
        state = ckpt.load()
        if state is not None:
            if state.get("done"):
                return int(state["total"])
            start_i, start_j = int(state["i"]), int(state["j"])
            total = int(state["total"])

    def save(i: int, j: int, total: int, done: bool = False) -> None:
        if ckpt is not None:
            ckpt.save({"i": i, "j": j, "total": total}, done=done)

    import time as _time

    from tpu_radix_join.utils.locks import (
        bench_pause_file, grid_presence_file, pid_file_alive,
        remove_pid_file, write_pid_file)

    pause_file = bench_pause_file()
    # reciprocal presence file: bench.py drains the chip only when a live
    # grid actually holds it (utils/locks.py — ONE path definition for
    # both sides of the handshake)
    grid_file = grid_presence_file()
    if write_pid_file(grid_file):
        # a prior grid killed hard while parked leaves a stale .parked that
        # would let the bench skip its drain while THIS run computes
        remove_pid_file(grid_file + ".parked")
    else:
        grid_file = None

    def yield_chip():
        """Cooperative chip yield: while the pause file exists (bench.py
        holds it during its timed window), park between chunk pairs so a
        long grid run cannot contaminate the official benchmark's timings
        on the shared single chip.  Liveness comes from the PID stamped in
        the file — a bench killed hard never parks the grid beyond one
        check, and a long-running live bench is never declared stale."""
        waited = False
        while pause_file and os.path.exists(pause_file):
            alive = pid_file_alive(pause_file)
            if alive is False:
                print("[grid] removing dead bench's pause file", flush=True)
                remove_pid_file(pause_file)
                break
            if alive is None and not os.path.exists(pause_file):
                break   # removed between the exists() check and the read
            if not waited:
                print(f"[grid] paused: {pause_file} present", flush=True)
                waited = True
                if measurements is not None:
                    # park/resume are timeline instants: a grid whose pairs
                    # suddenly stretch must show WHY (bench held the chip)
                    measurements.event("grid_parked", pause_file=pause_file)
                if grid_file:
                    # tells the bench the chip is actually drained (the
                    # presence file alone only says the grid process lives)
                    write_pid_file(grid_file + ".parked")
            _time.sleep(5)
        if waited:
            if grid_file:
                remove_pid_file(grid_file + ".parked")
            if measurements is not None:
                measurements.event("grid_resumed")
            print("[grid] resumed", flush=True)

    t0 = _time.perf_counter()
    last_i = start_i
    try:
        for i, r in enumerate(r_chunks):
            if i < start_i:
                continue
            row_start_j = start_j if i == start_i else 0
            for j, s in enumerate(s_iter()):
                if j < row_start_j:
                    continue
                yield_chip()
                # a simulated hard kill lands between the last save and the
                # next probe — the checkpoint already covers every finished
                # pair, so the resume recomputes nothing
                _faults.check(_faults.GRID_KILL, measurements)

                def probe(r=r, s=s):
                    _faults.check(_faults.GRID_TRANSIENT, measurements)
                    return chunked_join_count(r, s,
                                              min(slab_size, s.key.shape[0]),
                                              key_range=key_range)

                pair_span = (measurements.span("grid_pair", i=i, j=j)
                             if measurements is not None
                             else contextlib.nullcontext())
                with pair_span:
                    if retry_policy is not None:
                        total += _retry_execute(
                            probe, retry_policy,
                            retryable=retry_on or (_faults.TransientFault,),
                            measurements=measurements,
                            label=f"grid_pair({i},{j})")
                    else:
                        total += probe()
                if measurements is not None:
                    measurements.incr(GRIDPAIRS)
                save(i, j + 1, total)
                if progress:
                    print(f"[grid] pair ({i}, {j}) done, total={total:,}, "
                          f"t={_time.perf_counter() - t0:.1f}s", flush=True)
            last_i = i + 1
        save(last_i, 0, total, done=True)
        return total
    finally:
        if grid_file:
            remove_pid_file(grid_file)
            remove_pid_file(grid_file + ".parked")
