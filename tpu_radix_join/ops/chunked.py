"""Out-of-core chunked join: relations larger than device memory.

Replaces the reference's ``LD`` (large data) GPU capability — histograms,
reorders and probes indexed by ``iterCount`` so relations bigger than GPU
memory stream through in 128M-tuple chunks (``data/data.hpp:13-20,69-84``;
``LD`` kernels ``operators/gpu/kernels.cu:563-858``).

TPU design: ``jax.lax.scan`` over probe-side slabs.  The build side is sorted
once and stays resident in HBM; each scan step counts one outer slab's
matches with the merge-count discipline against the sorted inner.  Because
scan reuses one compiled step, HBM working-set per step is
O(inner + slab) regardless of total outer size — the `lax.scan`-over-slabs
shape SURVEY.md §5.7 prescribes.  For inner sides that exceed memory as well,
``chunked_join_grid`` streams both sides (outer scan nested in a Python loop
over inner chunks, accumulating partial counts — every (i, j) chunk pair is
probed exactly once, matching the LD kernels' two-level iterCount indexing).

Pipelined grid engine (``pipeline="on"``): the synchronous grid loop pays
three serial taxes per pair — it re-sorts the same inner chunk inside every
pair, blocks on a per-pair host readback (the ~5-8 ms non-pipelining tunnel
dispatch, PERF_NOTES "Dispatch overhead"), and fsyncs a checkpoint on the
critical path.  The pipelined engine removes all three, the same
overlap discipline as the reference's double-buffered 64KB ``MPI_Put``
windows (NetworkPartitioning.cpp:116-173):

  * **inner-sort reuse** — each inner chunk is sorted once per grid *row*
    (ops/merge_count.presort_keys) and every outer slab of the row probes
    it by binary search (merge_count_presorted): ``(n_outer_chunks - 1)``
    redundant sorts per row eliminated, observable as the SORTREUSE
    counter;
  * **double-buffered prefetch** — a bounded background stage
    (:class:`_Prefetcher`) generates/stages chunk ``j+1`` on device (and
    hoists its ``key_range="auto"`` max-key bound off the critical path)
    while pair ``(i, j)`` computes; per-pair counts stay on-device and
    readbacks drain through a bounded pending queue ("readback_flush"
    spans), so the host loop stops serializing on the tunnel round trip;
  * **write-behind checkpoints** — realized totals flush through
    robustness/checkpoint.AsyncCheckpointWriter ("ckpt_flush" spans)
    while the next pair computes; only *resolved* pair totals are ever
    enqueued, so every state on disk still satisfies the "every saved
    pair is realized" resume invariant, with a flush barrier + one final
    synchronous save at completion.

``pipeline="off"`` (the function default) keeps the synchronous loop as
the fallback and A/B lever; ``"auto"`` turns the pipeline on for any grid
larger than 1x1 (the CLI ``--grid-pipeline`` default).
"""

from __future__ import annotations

import contextlib
import functools
import os
import queue as _queue
import threading
from collections import deque
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.utils.hostsync import host_readback
from tpu_radix_join.ops.merge_count import (
    MAX_MERGE_KEY,
    merge_count_chunks,
    merge_count_per_partition_full,
    merge_count_presorted,
    merge_count_wide_per_partition,
    presort_keys,
)


@functools.partial(jax.jit, static_argnames=("num_slabs",))
def _scan_probe(r_keys: jnp.ndarray, s_keys: jnp.ndarray, num_slabs: int):
    """(per-slab counts uint32 [num_slabs], max single-tuple match weight)
    for s_keys split into ``num_slabs`` slabs.  The max weight feeds the
    caller's uint32-overflow guard (chunked_join_count)."""
    slabs = s_keys.reshape(num_slabs, -1)

    def step(carry, slab):
        # per-slab partial counts; chunked uint32 sums stay overflow-safe
        # as long as the caller-checked weight bound holds
        c, mw = merge_count_chunks(r_keys, slab, num_chunks=1024,
                                   return_max_weight=True)
        return carry, (jnp.sum(c, dtype=jnp.uint32), mw)

    _, (per_slab, mws) = jax.lax.scan(step, jnp.uint32(0), slabs)
    return per_slab, jnp.max(mws)


@functools.partial(jax.jit, static_argnames=("num_slabs",))
def _scan_probe_full(r_keys: jnp.ndarray, s_keys: jnp.ndarray,
                     num_slabs: int):
    """Full-key-range twin of :func:`_scan_probe`: the 2-key lexicographic
    count (merge_count_per_partition_full, fanout 0) for workloads whose
    keys exceed the 31-bit packing — which would silently map to the
    reserved pack-pads (zero matches) in the packed discipline."""
    slabs = s_keys.reshape(num_slabs, -1)

    def step(carry, slab):
        c, mw = merge_count_per_partition_full(r_keys, slab, 0,
                                               return_max_weight=True)
        return carry, (c[0], mw)

    _, (per_slab, mws) = jax.lax.scan(step, jnp.uint32(0), slabs)
    return per_slab, jnp.max(mws)


@functools.partial(jax.jit, static_argnames=("num_slabs",))
def _scan_probe_wide(r_lo, r_hi, s_lo, s_hi, num_slabs: int):
    """Wide-key (hi/lo lane) twin of :func:`_scan_probe`."""
    slabs = (s_lo.reshape(num_slabs, -1), s_hi.reshape(num_slabs, -1))

    def step(carry, slab):
        lo, hi = slab
        c, mw = merge_count_wide_per_partition(r_lo, r_hi, lo, hi, 0,
                                               return_max_weight=True)
        return carry, (jnp.sum(c, dtype=jnp.uint32), mw)

    _, (per_slab, mws) = jax.lax.scan(step, jnp.uint32(0), slabs)
    return per_slab, jnp.max(mws)


@functools.partial(jax.jit, static_argnames=("num_slabs",))
def _scan_probe_presorted(r_sorted: jnp.ndarray, s_keys: jnp.ndarray,
                          num_slabs: int):
    """Presorted-inner twin of :func:`_scan_probe`: the binary-search probe
    (ops/merge_count.merge_count_presorted) against a row-resident sorted
    inner — no per-pair union sort, no 31-bit packing (the full
    sub-sentinel key range joins natively).  The pipelined grid's
    sort-reuse engine: one :func:`presort_keys` per grid row feeds every
    outer chunk of that row through here."""
    slabs = s_keys.reshape(num_slabs, -1)

    def step(carry, slab):
        c, mw = merge_count_presorted(r_sorted, slab, return_max_weight=True)
        return carry, (c, mw)

    _, (per_slab, mws) = jax.lax.scan(step, jnp.uint32(0), slabs)
    return per_slab, jnp.max(mws)


def _sentinel_corruption(mx: int):
    from tpu_radix_join.robustness.verify import DataCorruption
    from tpu_radix_join.data.tuples import pad_sentinel
    return DataCorruption(
        f"keys reach the pad sentinel range (max {mx:#x}): "
        f"uint32 keys must stay <= "
        f"{int(pad_sentinel('inner')) - 1:#x} — a key lane in "
        f"the sentinel range is the streamed-lane corruption "
        f"signature (such tuples would silently pad-match)")


def _narrow_violation(mx: int) -> ValueError:
    return ValueError(
        f"key contract violation: key_range='narrow' but max key "
        f"{mx:#x} exceeds the 31-bit packing limit "
        f"{MAX_MERGE_KEY:#x} — such keys pack to the reserved "
        f"zero-match pads (silent undercount); use key_range='full' "
        f"or 'auto'")


def _check_weight_window(maxw: int, window: int) -> None:
    """uint32-overflow guard: every accumulation window (the per-slab total
    and the chunk partials inside it) is bounded by max_weight x window
    width; a wrapped window would return a wrong count silently (the
    reference's uint64 RESULT_COUNTER is immune, HashJoin.h:26)."""
    if maxw > (2**32 - 1) // window:
        raise OverflowError(
            f"uint32 count-window overflow risk: max inner multiplicity "
            f"{maxw} x window {window} can reach 2**32 — "
            f"shrink slab_size or deduplicate the inner side")


def chunked_join_count(r: TupleBatch, s: TupleBatch, slab_size: int,
                       key_range: str = "auto",
                       key_bound: int | None = None) -> int:
    """Exact match count streaming the outer side in ``slab_size`` slabs.

    Ragged sizes (streamed chunks, short final chunks) are padded up to a
    slab multiple with the outer-side sentinel, which matches nothing by the
    pad-key contract (tuples.py).  Wide (64-bit) batches — e.g. from a
    ``Relation(key_bits=64)`` stream — take the hi/lo lexicographic count;
    mixed-width inputs raise rather than silently truncate.

    ``key_range`` mirrors ``JoinConfig.key_range`` for the 32-bit path:
    "auto" probes the chunks' max key (2 HBM scans + a readback per call)
    and routes keys above the 31-bit packing to the full-range count;
    callers with a static bound — e.g. grid drivers over unique Relations,
    whose keys never reach 2**31 (relation.py size cap) — pass "narrow"
    (or "full") to skip the probe on every grid pair.

    ``key_bound`` (optional) is a precomputed INCLUSIVE max over both
    chunks' key lanes: it replaces "auto"'s per-call device probe (and
    "narrow"'s deferred contract reduction) with host arithmetic, so a
    grid driver that caches one max-key readback per *chunk* stops paying
    a 2-scan + readback sync on every *pair* (chunked_join_grid does
    exactly this).  The sentinel-range corruption check and the narrow
    31-bit contract check still fire, from the bound.
    """
    if key_range not in ("auto", "narrow", "full"):
        raise ValueError(f"unknown key range mode {key_range!r}")
    from tpu_radix_join.data.tuples import pad_sentinel
    if (r.key_hi is None) != (s.key_hi is None):
        raise ValueError(
            "mixed key widths: one side carries a key_hi lane and the other "
            "does not — refusing to run a silently-truncated join")
    keys = s.key
    n = keys.shape[0]
    pad = (-n) % slab_size
    fill = pad_sentinel("outer")
    mx_narrow = None
    if pad:
        keys = jnp.concatenate(
            [keys, jnp.full((pad,), fill, keys.dtype)])
    if r.key_hi is not None:
        s_hi = s.key_hi
        if pad:
            # sentinel in BOTH lanes (the make_padding wide=True contract)
            s_hi = jnp.concatenate(
                [s_hi, jnp.full((pad,), fill, s_hi.dtype)])
        per_slab, maxw = _scan_probe_wide(r.key, r.key_hi, keys, s_hi,
                                          (n + pad) // slab_size)
    else:
        # keys above the 31-bit packing would silently land on the reserved
        # pack-pads (zero matches) in merge_count_chunks; under "auto",
        # probe the real max (pre-padding — the sentinel fill is always the
        # uint32 max) and route to the full-range lexicographic count
        full = key_range == "full"
        if key_range == "auto":
            mx = (int(key_bound) if key_bound is not None else
                  int(host_readback(jnp.maximum(jnp.max(r.key),
                                             jnp.max(s.key)))))
            if mx >= int(pad_sentinel("inner")):
                raise _sentinel_corruption(mx)
            full = mx > MAX_MERGE_KEY
        if full:
            per_slab, maxw = _scan_probe_full(r.key, keys,
                                              (n + pad) // slab_size)
        else:
            per_slab, maxw = _scan_probe(r.key, keys, (n + pad) // slab_size)
            if key_range == "narrow":
                # "narrow" asserts a static key bound instead of paying
                # "auto"'s pre-scan sync — but an asserted contract still
                # has to be *checked*: keys above the 31-bit packing land on
                # the reserved pack-pads and count zero matches, an
                # undercount with ok-looking output.  With a precomputed
                # bound the check is host arithmetic; otherwise dispatch
                # the max-key reduction after the scan so it rides the
                # maxw readback below (detection without an extra sync).
                if key_bound is not None:
                    if int(key_bound) > MAX_MERGE_KEY:
                        raise _narrow_violation(int(key_bound))
                else:
                    mx_narrow = jnp.maximum(jnp.max(r.key), jnp.max(s.key))
    if mx_narrow is not None:
        mx = int(host_readback(mx_narrow))
        if mx > MAX_MERGE_KEY:
            raise _narrow_violation(mx)
    window = max(slab_size, -(-(r.key.shape[0] + slab_size) // 1024))
    _check_weight_window(int(host_readback(maxw)), window)
    return int(host_readback(per_slab).astype(np.uint64).sum())


class _Prefetcher:
    """Bounded background chunk stager for the pipelined grid.

    Pulls chunks from ``it`` on a daemon thread, forces their device
    generation (JAX dispatch is lazy for generator-fed grids — see
    data/streaming.stream_chunks_device), and — for 32-bit chunks —
    hoists the ``key_range="auto"`` max-key readback off the critical
    path.  Hands ``(chunk, bound)`` pairs to the consumer through a
    queue of ``depth`` slots: with the consumer busy on pair ``(i, j)``
    the thread is already staging chunk ``j+1`` (and blocks once the
    queue fills — bounded lookahead, bounded memory).

    Each staged chunk is one "prefetch" span (recorded from this thread;
    SpanTracer keeps per-name stacks, so producer spans interleave safely
    with the consumer's "grid_pair" spans) plus one PREFETCH count.
    Iterator exceptions are captured and re-raised at the consuming
    ``next()`` — a corrupt or failing stream fails the pair loop, not a
    daemon thread.
    """

    _DONE = object()

    def __init__(self, it, depth: int, measurements, side: str):
        self._q = _queue.Queue(maxsize=max(1, depth))
        self._meas = measurements
        self._side = side
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(it,),
            name=f"grid-prefetch-{side}", daemon=True)
        self._thread.start()

    def _run(self, it):
        from tpu_radix_join.performance.measurements import PREFETCH
        try:
            for idx, chunk in enumerate(it):
                if self._stop.is_set():
                    return
                span = (self._meas.span("prefetch", side=self._side,
                                        chunk=idx)
                        if self._meas is not None
                        else contextlib.nullcontext())
                with span:
                    bound = None
                    if getattr(chunk, "key_hi", None) is None:
                        # the bound readback doubles as the staging fence
                        bound = int(host_readback(jnp.max(chunk.key)))
                    else:
                        jax.block_until_ready(chunk.key)
                if self._meas is not None:
                    self._meas.incr(PREFETCH)
                self._put((chunk, bound))
            self._put(self._DONE)
        except BaseException as e:      # re-raised at the consumer
            self._put(e)

    def _put(self, item) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except _queue.Full:
                continue

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def close(self) -> None:
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except _queue.Empty:
            pass
        self._thread.join(timeout=5)


def chunked_join_grid(r_chunks, s_chunks, slab_size: int,
                      checkpoint_path: str | None = None,
                      checkpoint_tag: str = "",
                      progress: bool = False,
                      key_range: str = "auto",
                      measurements=None,
                      retry_policy=None,
                      retry_on=None,
                      plan=None,
                      pipeline: str = "off",
                      prefetch_depth: int = 2,
                      readback_depth: int = 2) -> int:
    """Both sides streamed; each inner chunk is joined against every outer
    chunk exactly once.

    ``s_chunks`` is consumed once per inner chunk, so pass either a
    re-iterable (list/tuple) or — for outer sides too large to keep resident
    — a zero-argument factory returning a fresh iterator per inner chunk
    (e.g. ``lambda: stream_chunks(s_rel, node, c)``), which keeps device
    memory at O(chunk).  A bare one-shot iterator is materialized up front
    (resident, but never silently exhausted).

    ``pipeline`` selects the engine: "off" (default) is the synchronous
    loop — one probe, one readback, one checkpoint fsync per pair, in
    program order; "on" is the pipelined engine (module docstring):
    once-per-row inner sorts probed by binary search, ``prefetch_depth``
    chunks of background staging, readbacks deferred through a
    ``readback_depth`` pending window, and write-behind checkpoints.
    "auto" resolves to "on" for any grid larger than a single pair.  Both
    modes return identical totals and share the checkpoint format — a run
    killed under either mode resumes under either mode.

    ``checkpoint_path`` adds resume support for long grid joins — a
    capability the single-shot reference lacks entirely (SURVEY.md §5.4):
    after every (inner, outer) chunk pair the accumulated count and the next
    pair's (i, j) indices are written atomically (fsync + rename); a rerun
    with the same arguments skips completed pairs (skipped chunks are
    regenerated but not probed — generation is cheap, probes are not).  The
    file is left in place on completion with ``"done": true``.  A
    fingerprint (slab size + caller-supplied ``checkpoint_tag`` + the
    planner's strategy/chunking when a ``plan`` is given) guards
    against resuming a different join from a stale file — pass a tag that
    identifies the input relations; mismatches raise instead of silently
    returning the wrong total, and unreadable files restart from zero.
    Saved states additionally record the grid's discovered row/col extents:
    a generator-fed grid has ``rows``/``cols`` None in its fingerprint, so
    without them a resume with the same tag but a different chunking would
    mis-resume — the extent check fails fast instead (CheckpointMismatch).
    Checkpoint mechanics (atomic rename, corruption policy, counters) live
    in robustness/checkpoint.CheckpointManager; pipelined mode flushes
    saves through AsyncCheckpointWriter (write-behind, latest-wins
    coalescing — CKPTSAVE may be lower than the pair count, but every
    saved state is realized).

    ``measurements`` (optional) receives CKPTSAVE/CKPTLOAD from the
    manager plus GRIDPAIRS — the number of chunk pairs actually probed,
    which a resumed run keeps at (total pairs - completed pairs): the
    zero-recompute guarantee tests assert on — and, in pipelined mode,
    PREFETCH/SORTREUSE with "prefetch"/"readback_flush"/"ckpt_flush"
    spans.  ``retry_policy`` (a robustness.retry.RetryPolicy) retries each
    pair probe on transient errors (``retry_on`` exception classes,
    default the injectable TransientFault) — the chip-tunnel hiccup that
    killed three rounds of 128M/1B grids (VERDICT r5) instead of costing
    one backoff.
    """
    if callable(s_chunks):
        s_iter = s_chunks
    else:
        if not isinstance(s_chunks, (list, tuple)):
            s_chunks = list(s_chunks)
        s_iter = lambda: s_chunks

    if pipeline not in ("off", "on", "auto"):
        raise ValueError(f"unknown grid pipeline mode {pipeline!r} "
                         f"(want off|on|auto)")
    rows_known = (len(r_chunks) if isinstance(r_chunks, (list, tuple))
                  else None)
    cols_known = (len(s_chunks) if isinstance(s_chunks, (list, tuple))
                  else None)
    if pipeline == "auto":
        # a 1x1 grid has nothing to overlap (one pair, one readback); any
        # larger grid amortizes the prefetch/writer threads immediately
        pipeline = "off" if rows_known == 1 and cols_known == 1 else "on"

    if checkpoint_path and not checkpoint_tag:
        raise ValueError(
            "checkpoint_path requires a checkpoint_tag identifying the input "
            "relations — an untagged checkpoint resumed against different "
            "data would silently return a wrong total")
    from tpu_radix_join.performance.measurements import (GRIDPAIRS,
                                                         SORTREUSE)
    from tpu_radix_join.robustness import faults as _faults
    from tpu_radix_join.robustness.checkpoint import (AsyncCheckpointWriter,
                                                      CheckpointManager,
                                                      CheckpointMismatch)
    from tpu_radix_join.robustness.retry import execute as _retry_execute

    fingerprint = {"slab": int(slab_size), "tag": checkpoint_tag,
                   "rows": rows_known, "cols": cols_known}
    if plan is not None:
        # a planner-driven grid (main.py --plan) folds the plan identity in:
        # resuming under a different chunking or strategy walks a different
        # grid, so the stale checkpoint must mismatch, not mis-resume
        fingerprint["plan"] = {"strategy": plan.strategy,
                               "chunk_tuples": plan.chunk_tuples}
    ckpt = (CheckpointManager(checkpoint_path, fingerprint, measurements)
            if checkpoint_path else None)
    start_i, start_j, total = 0, 0, 0
    saved_rows = saved_cols = None
    if ckpt is not None:
        state = ckpt.load()
        if state is not None:
            saved_rows, saved_cols = state.get("rows"), state.get("cols")
            # extent hardening: the fingerprint's rows/cols are None for
            # generator-fed grids, so a stale file with the same tag but a
            # different grid shape would otherwise mis-resume
            for name, saved, known in (("rows", saved_rows, rows_known),
                                       ("cols", saved_cols, cols_known)):
                if saved is not None and known is not None and saved != known:
                    raise CheckpointMismatch(
                        f"checkpoint {checkpoint_path} was saved from a grid "
                        f"with {saved} {name.rstrip('s')} chunk(s), but this "
                        f"run walks {known} — same tag, different grid "
                        f"shape; remove the checkpoint or fix the inputs")
            if state.get("done"):
                return int(state["total"])
            start_i, start_j = int(state["i"]), int(state["j"])
            total = int(state["total"])
    # best-known column extent (list length, checkpoint, or discovered at
    # the end of the first iterated row) — feeds ETA + resume accounting
    cols = cols_known if cols_known is not None else saved_cols
    if progress and (start_i or start_j):
        if cols:
            print(f"[grid] resume: skipping {start_i * cols + start_j} "
                  f"completed pair(s) (cursor i={start_i}, j={start_j})",
                  flush=True)
        else:
            print(f"[grid] resume: skipping completed pairs before cursor "
                  f"(i={start_i}, j={start_j})", flush=True)

    def state_dict(i: int, j: int, total: int, done: bool = False) -> dict:
        state = {"i": i, "j": j, "total": total}
        if cols is not None:
            state["cols"] = cols
        rows = rows_known if rows_known is not None else (i if done else None)
        if rows is not None:
            state["rows"] = rows
        return state

    def note_cols(n: int) -> None:
        nonlocal cols
        if saved_cols is not None and n != saved_cols:
            raise CheckpointMismatch(
                f"checkpoint {checkpoint_path} was saved from a grid with "
                f"{saved_cols} outer chunk(s) per row, but this run "
                f"discovered {n} — same tag, different grid shape; remove "
                f"the checkpoint or fix the inputs")
        if cols is None:
            cols = n

    import time as _time

    from tpu_radix_join.utils.locks import (
        bench_pause_file, grid_presence_file, pid_file_alive,
        remove_pid_file, write_pid_file)

    pause_file = bench_pause_file()
    # reciprocal presence file: bench.py drains the chip only when a live
    # grid actually holds it (utils/locks.py — ONE path definition for
    # both sides of the handshake)
    grid_file = grid_presence_file()
    if write_pid_file(grid_file):
        # a prior grid killed hard while parked leaves a stale .parked that
        # would let the bench skip its drain while THIS run computes
        remove_pid_file(grid_file + ".parked")
    else:
        grid_file = None

    def yield_chip():
        """Cooperative chip yield: while the pause file exists (bench.py
        holds it during its timed window), park between chunk pairs so a
        long grid run cannot contaminate the official benchmark's timings
        on the shared single chip.  Liveness comes from the PID stamped in
        the file — a bench killed hard never parks the grid beyond one
        check, and a long-running live bench is never declared stale."""
        waited = False
        while pause_file and os.path.exists(pause_file):
            alive = pid_file_alive(pause_file)
            if alive is False:
                print("[grid] removing dead bench's pause file", flush=True)
                remove_pid_file(pause_file)
                break
            if alive is None and not os.path.exists(pause_file):
                break   # removed between the exists() check and the read
            if not waited:
                print(f"[grid] paused: {pause_file} present", flush=True)
                waited = True
                if measurements is not None:
                    # park/resume are timeline instants: a grid whose pairs
                    # suddenly stretch must show WHY (bench held the chip)
                    measurements.event("grid_parked", pause_file=pause_file)
                if grid_file:
                    # tells the bench the chip is actually drained (the
                    # presence file alone only says the grid process lives)
                    write_pid_file(grid_file + ".parked")
            _time.sleep(5)
        if waited:
            if grid_file:
                remove_pid_file(grid_file + ".parked")
            if measurements is not None:
                measurements.event("grid_resumed")
            print("[grid] resumed", flush=True)

    def span(name, **kw):
        return (measurements.span(name, **kw) if measurements is not None
                else contextlib.nullcontext())

    t0 = _time.perf_counter()
    start_pairs = start_i * cols + start_j if cols else 0
    done_this_run = 0

    def report(i: int, j: int) -> None:
        if not progress:
            return
        elapsed = _time.perf_counter() - t0
        rate = done_this_run / elapsed if elapsed > 0 else 0.0
        line = (f"[grid] pair ({i}, {j}) done, total={total:,}, "
                f"t={elapsed:.1f}s, {rate:.2f} pairs/s")
        if rows_known is not None and cols and rate > 0:
            remaining = max(0, rows_known * cols - start_pairs
                            - done_this_run)
            line += f", eta={remaining / rate:.0f}s"
        print(line, flush=True)

    # ``key_range="auto"`` max-key hoist: one device max + readback per
    # CHUNK (cached by chunk id — the outer side repeats every row),
    # instead of the per-PAIR 2-scan + readback sync inside
    # chunked_join_count.  Wide chunks have no 32-bit range discipline.
    s_bounds: dict = {}

    def chunk_bound(batch) -> int:
        return int(host_readback(jnp.max(batch.key)))

    last_i = start_i

    def run_sync() -> int:
        nonlocal total, last_i, done_this_run
        for i, r in enumerate(r_chunks):
            if i < start_i:
                continue
            row_start_j = start_j if i == start_i else 0
            rb = (chunk_bound(r)
                  if key_range == "auto" and r.key_hi is None else None)
            row_cols = 0
            for j, s in enumerate(s_iter()):
                row_cols = j + 1
                if j < row_start_j:
                    continue
                yield_chip()
                # a simulated hard kill lands between the last save and the
                # next probe — the checkpoint already covers every finished
                # pair, so the resume recomputes nothing
                _faults.check(_faults.GRID_KILL, measurements)
                kb = None
                if rb is not None and s.key_hi is None:
                    sb = s_bounds.get(j)
                    if sb is None:
                        sb = s_bounds[j] = chunk_bound(s)
                    kb = max(rb, sb)

                def probe(r=r, s=s, kb=kb):
                    _faults.check(_faults.GRID_TRANSIENT, measurements)
                    return chunked_join_count(r, s,
                                              min(slab_size, s.key.shape[0]),
                                              key_range=key_range,
                                              key_bound=kb)

                with span("grid_pair", i=i, j=j):
                    if retry_policy is not None:
                        total += _retry_execute(
                            probe, retry_policy,
                            retryable=retry_on or (_faults.TransientFault,),
                            measurements=measurements,
                            label=f"grid_pair({i},{j})")
                    else:
                        total += probe()
                if measurements is not None:
                    measurements.incr(GRIDPAIRS)
                done_this_run += 1
                if ckpt is not None:
                    ckpt.save(state_dict(i, j + 1, total))
                report(i, j)
            note_cols(row_cols)
            last_i = i + 1
        if ckpt is not None:
            ckpt.save(state_dict(last_i, 0, total, done=True), done=True)
        return total

    def dispatch_probe(r, s, r_sorted, kb):
        """Dispatch one pair's device probe, leaving the counts on device:
        (per_slab device array, maxw device scalar, overflow window)."""
        from tpu_radix_join.data.tuples import pad_sentinel
        if (r.key_hi is None) != (s.key_hi is None):
            raise ValueError(
                "mixed key widths: one side carries a key_hi lane and the "
                "other does not — refusing to run a silently-truncated join")
        slab = min(slab_size, s.key.shape[0])
        keys = s.key
        n = keys.shape[0]
        pad = (-n) % slab
        fill = pad_sentinel("outer")
        if pad:
            keys = jnp.concatenate(
                [keys, jnp.full((pad,), fill, keys.dtype)])
        if r.key_hi is not None:
            # wide chunks keep the per-pair union sort (no presorted-probe
            # discipline for 2-lane keys yet) but still ride the prefetch +
            # deferred-readback + write-behind stages
            s_hi = s.key_hi
            if pad:
                s_hi = jnp.concatenate(
                    [s_hi, jnp.full((pad,), fill, s_hi.dtype)])
            per_slab, maxw = _scan_probe_wide(r.key, r.key_hi, keys, s_hi,
                                              (n + pad) // slab)
            window = max(slab, -(-(r.key.shape[0] + slab) // 1024))
        else:
            # the binary-search probe compares raw uint32 keys, so an inner
            # key in the sentinel range would pad-match the outer fill —
            # the bound check makes that loud for every key_range mode
            if kb is None:
                kb = max(chunk_bound(r), chunk_bound(s))
            if kb >= int(pad_sentinel("inner")):
                raise _sentinel_corruption(kb)
            if key_range == "narrow" and kb > MAX_MERGE_KEY:
                raise _narrow_violation(kb)
            per_slab, maxw = _scan_probe_presorted(r_sorted, keys,
                                                   (n + pad) // slab)
            window = slab
        return per_slab, maxw, window

    def run_pipelined() -> int:
        nonlocal total, last_i, done_this_run
        writer = AsyncCheckpointWriter(ckpt) if ckpt is not None else None
        pending = deque()   # (i, j, per_slab, maxw, window), dispatch order

        def resolve_until(limit: int) -> None:
            nonlocal total, done_this_run
            if len(pending) <= limit:
                return
            # batched host readbacks: pairs resolve in dispatch order, so
            # the realized prefix — the only thing ever checkpointed —
            # advances in row-major order, same as the synchronous loop
            with span("readback_flush", drained=len(pending) - limit):
                while len(pending) > limit:
                    pi, pj, per_slab, maxw, window = pending.popleft()
                    _check_weight_window(int(host_readback(maxw)), window)
                    total += int(host_readback(per_slab)
                                 .astype(np.uint64).sum())
                    done_this_run += 1
                    if writer is not None:
                        writer.save(state_dict(pi, pj + 1, total))
                    report(pi, pj)

        prefetchers = []

        def open_prefetcher(it, depth, side):
            pf = _Prefetcher(it, depth, measurements, side)
            prefetchers.append(pf)
            return pf

        try:
            inner_pf = open_prefetcher(iter(r_chunks), 1, "inner")
            for i, (r, rb) in enumerate(inner_pf):
                if i < start_i:
                    continue
                row_start_j = start_j if i == start_i else 0
                r_sorted = None     # built at the row's first probed pair
                outer_pf = open_prefetcher(iter(s_iter()), prefetch_depth,
                                           "outer")
                row_cols = 0
                for j, (s, sb) in enumerate(outer_pf):
                    row_cols = j + 1
                    if j < row_start_j:
                        continue
                    yield_chip()
                    _faults.check(_faults.GRID_KILL, measurements)
                    reused = r_sorted is not None
                    if r.key_hi is None and r_sorted is None:
                        r_sorted = presort_keys(r.key)
                    kb = (max(rb, sb) if rb is not None and sb is not None
                          else None)

                    def dispatch(r=r, s=s, rs=r_sorted, kb=kb):
                        _faults.check(_faults.GRID_TRANSIENT, measurements)
                        return dispatch_probe(r, s, rs, kb)

                    with span("grid_pair", i=i, j=j):
                        if retry_policy is not None:
                            res = _retry_execute(
                                dispatch, retry_policy,
                                retryable=retry_on
                                or (_faults.TransientFault,),
                                measurements=measurements,
                                label=f"grid_pair({i},{j})")
                        else:
                            res = dispatch()
                    if measurements is not None:
                        measurements.incr(GRIDPAIRS)
                        if reused:
                            measurements.incr(SORTREUSE)
                    pending.append((i, j, *res))
                    resolve_until(readback_depth)
                prefetchers.remove(outer_pf)
                outer_pf.close()
                note_cols(row_cols)
                last_i = i + 1
            resolve_until(0)
            if writer is not None:
                # flush barrier, then ONE synchronous final save: the done
                # marker must be durable before the total is returned
                writer.flush()
                ckpt.save(state_dict(last_i, 0, total, done=True), done=True)
            return total
        finally:
            for pf in prefetchers:
                pf.close()
            if writer is not None:
                # close() flushes whatever realized state was enqueued —
                # on an error path that preserves the most progress a
                # resume may legally claim (every flushed pair resolved)
                writer.close()

    try:
        return run_pipelined() if pipeline == "on" else run_sync()
    finally:
        if grid_file:
            remove_pid_file(grid_file)
            remove_pid_file(grid_file + ".parked")
