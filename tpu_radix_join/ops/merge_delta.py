"""Serving fast-path device programs: O(N+Δ) delta merges and fused
multi-query batched counts.

Two primitives back the serving fast paths (service/resident.py and
service/microbatch.py), both built on the presorted binary-search probe
discipline of :func:`~tpu_radix_join.ops.merge_count.merge_count_presorted`:

  * **Delta merge** — a session keeps each relation's sorted key lane
    device-resident; an incremental query sorts only its Δ new keys and
    :func:`merge_sorted` splices them into the resident union with one
    Δ-sided ``searchsorted``, a marker cumsum, and a monotone gather
    (O(N+Δ) streaming data movement, no O(N log N) re-sort).  The probe
    binary-searches the merged union exactly like the grid's presorted
    probe when the outer changes (:func:`delta_merge_count`); when the
    outer is UNCHANGED, :func:`delta_merge_increment` probes only the Δ
    against the session's resident sorted outer lane and the running
    total absorbs the increment — multiset counts are additive, so the
    shared M·log N full-lane probe drops off the hot path entirely.

  * **Batched count** — the micro-batch coalescer concatenates several
    small queries' key lanes, tags each element with its query index in
    the bits ABOVE the key bound (the composite-key trick of
    ``ops/radix.py scatter_to_blocks_grouped``: ``dest * num_sub + sub``
    under one sort), and ONE sort + ONE probe serves the whole batch;
    per-query counts split back out of a cumulative-sum of the per-outer
    weights at the (static) query boundaries — the same boundary
    discipline ``merge_count_per_partition_full`` uses for per-partition
    counts.

Key-range contract: like every presorted-probe path, real keys must stay
below the sentinel range (``<= 0xFFFFFFFD``); the batched composite
additionally needs ``num_queries << shift`` to fit uint32
(:func:`batch_feasible`), where ``shift = ceil(log2(key_bound))``.
Infeasible batches are the coalescer's problem — it executes them
serially instead (service/microbatch.py).
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp

#: exclusive ceiling real keys must stay under for the presorted probe
#: (tuples.py sentinel discipline — 0xFFFFFFFE/0xFFFFFFFF are pads)
MAX_SERVE_KEY = 0xFFFFFFFD


def composite_shift(key_bound: int) -> int:
    """Bits the query tag must shift past: ``ceil(log2(key_bound))`` so
    ``(qid << shift) | key`` is injective over (qid, key)."""
    if key_bound < 1:
        raise ValueError("key_bound must be >= 1")
    return max(1, math.ceil(math.log2(max(2, key_bound))))


def batch_feasible(num_queries: int, key_bound: int) -> bool:
    """True when ``num_queries`` queries with keys < ``key_bound`` fit the
    uint32 composite word below the sentinel range — the coalescer's
    fuse/serial decision."""
    shift = composite_shift(key_bound)
    if shift >= 32:
        return False
    top = (num_queries << shift) - 1
    return top <= MAX_SERVE_KEY


def merge_sorted(a_sorted: jnp.ndarray, b_sorted: jnp.ndarray) -> jnp.ndarray:
    """Merge two ALREADY-SORTED uint32 lanes in O(N+Δ) with the work on
    the Δ side: only the SMALL lane is binary-searched into the big one
    (Δ·log N), then the big lane's slots fall out of a marker cumsum —
    for an unmarked slot ``j``, ``prefix[j]`` counts the b-elements
    placed before it, so it holds ``a[j - prefix[j]]`` (a monotone,
    coalesced gather).  The earlier formulation searchsorted the BIG
    lane into the small one (N·log Δ random gathers), which profiling
    showed costs as much as the full re-sort it was meant to replace;
    marker + cumsum + monotone gather are genuine streaming passes.
    ``side="right"`` tie-breaks a-before-b so the merge is stable across
    the seam."""
    n, d = a_sorted.shape[0], b_sorted.shape[0]
    if d == 0:
        return a_sorted
    if n == 0:
        return b_sorted
    pos_b = (jnp.arange(d, dtype=jnp.int32)
             + jnp.searchsorted(a_sorted, b_sorted,
                                side="right").astype(jnp.int32))
    marker = jnp.zeros(n + d, dtype=jnp.int32).at[pos_b].set(
        1, unique_indices=True)
    prefix = jnp.cumsum(marker)
    idx = jnp.arange(n + d, dtype=jnp.int32) - prefix
    out = a_sorted[jnp.clip(idx, 0, n - 1)]
    out = out.at[pos_b].set(b_sorted, unique_indices=True)
    return out


def delta_merge_count(resident_sorted: jnp.ndarray,
                      delta_keys: jnp.ndarray,
                      outer_keys: jnp.ndarray
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One incremental query as a single traceable program: sort ONLY the
    Δ delta keys, merge them into the resident sorted union, probe the
    outer lane against the merged union with the two-binary-search weight
    rule.  Returns ``(new_resident_sorted, total_matches)`` — the caller
    (service/resident.py) keeps ``new_resident_sorted`` on device for the
    next delta."""
    from tpu_radix_join.ops.merge_count import merge_count_presorted
    from tpu_radix_join.ops.sorting import sort_unstable

    delta_sorted = sort_unstable(delta_keys)
    union = merge_sorted(resident_sorted, delta_sorted)
    total = merge_count_presorted(union, outer_keys)
    return union, total


@functools.lru_cache(maxsize=64)
def compiled_delta_merge_count(n_resident: int, n_delta: int, n_outer: int):
    """Jitted :func:`delta_merge_count` for one (N, Δ, M) shape class —
    the session's per-shape compile cache (an LRU so a long-lived worker
    cannot grow an unbounded executable set)."""
    del n_resident, n_delta, n_outer   # shape key only; jit re-specializes
    return jax.jit(delta_merge_count)


def delta_merge_increment(resident_sorted: jnp.ndarray,
                          delta_keys: jnp.ndarray,
                          outer_sorted: jnp.ndarray
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One incremental query whose OUTER is unchanged since the last
    query on this relation: sort the Δ, splice it into the resident
    union, and count only the Δ's matches against the session's resident
    SORTED outer lane — ``total = previous_total + increment`` is exact
    for multiset counts because ``count(s, A ⊎ Δ) = count(s, A) +
    count(s, Δ)``.  This keeps the whole hot query O(N+Δ): the full-lane
    probe (M·log N random gathers, as costly as the re-sort it rides on)
    is paid only when the outer actually changes
    (:func:`delta_merge_count`).  Returns ``(new_resident_sorted,
    increment)``."""
    from tpu_radix_join.ops.sorting import sort_unstable

    delta_sorted = sort_unstable(delta_keys)
    union = merge_sorted(resident_sorted, delta_sorted)
    lb = jnp.searchsorted(outer_sorted, delta_sorted, side="left")
    ub = jnp.searchsorted(outer_sorted, delta_sorted, side="right")
    inc = jnp.sum((ub - lb).astype(jnp.uint32))
    return union, inc


@functools.lru_cache(maxsize=64)
def compiled_delta_merge_increment(n_resident: int, n_delta: int,
                                   n_outer: int):
    """Jitted :func:`delta_merge_increment` for one (N, Δ, M) shape class
    (same per-shape compile-cache discipline as
    :func:`compiled_delta_merge_count`)."""
    del n_resident, n_delta, n_outer   # shape key only; jit re-specializes
    return jax.jit(delta_merge_increment)


def batched_merge_count(r_keys: jnp.ndarray, s_keys: jnp.ndarray,
                        r_sizes: Tuple[int, ...], s_sizes: Tuple[int, ...],
                        key_bound: int) -> jnp.ndarray:
    """Fused multi-query count: ONE sort + ONE probe over the
    concatenated per-query lanes.

    ``r_keys``/``s_keys`` are the queries' inner/outer key lanes
    concatenated in query order; ``r_sizes``/``s_sizes`` are the static
    per-query lengths.  Each element is tagged with its query index above
    the key bits (``(qid << shift) | key``), so one unstable sort groups
    the whole batch by query with keys ordered within each group — the
    ``scatter_to_blocks_grouped`` composite trick at serving scope.  The
    probe's per-outer weights can never cross a query boundary (the tag
    bits differ), and the per-query totals fall out of one cumulative sum
    read at the static query offsets (the
    ``merge_count_per_partition_full`` boundary idiom, minus the
    searchsorted: concatenation order makes the boundaries static).

    Returns the uint32 per-query match counts, shape ``[num_queries]``.
    Caller must have checked :func:`batch_feasible`.
    """
    from tpu_radix_join.ops.sorting import sort_unstable

    q = len(r_sizes)
    if q != len(s_sizes):
        raise ValueError(f"r_sizes/s_sizes disagree: {q} != {len(s_sizes)}")
    if not batch_feasible(q, key_bound):
        raise ValueError(
            f"{q} queries at key_bound {key_bound} overflow the uint32 "
            f"composite (shift {composite_shift(key_bound)})")
    shift = jnp.uint32(composite_shift(key_bound))
    import numpy as np
    r_qid = jnp.asarray(np.repeat(np.arange(q, dtype=np.uint32),
                                  np.asarray(r_sizes)))
    s_qid = jnp.asarray(np.repeat(np.arange(q, dtype=np.uint32),
                                  np.asarray(s_sizes)))
    rc = (r_qid << shift) | r_keys
    sc = (s_qid << shift) | s_keys
    rc_sorted = sort_unstable(rc)
    lb = jnp.searchsorted(rc_sorted, sc, side="left").astype(jnp.uint32)
    ub = jnp.searchsorted(rc_sorted, sc, side="right").astype(jnp.uint32)
    csum = jnp.concatenate([
        jnp.zeros(1, jnp.uint32),
        jnp.cumsum(ub - lb, dtype=jnp.uint32)])
    bounds = np.concatenate([[0], np.cumsum(np.asarray(s_sizes))])
    return csum[jnp.asarray(bounds[1:])] - csum[jnp.asarray(bounds[:-1])]


@functools.lru_cache(maxsize=64)
def compiled_batched_merge_count(r_sizes: Tuple[int, ...],
                                 s_sizes: Tuple[int, ...], key_bound: int):
    """Jitted :func:`batched_merge_count` for one batch shape class (the
    static sizes and key bound are closed over, so the whole batch is one
    compiled device program)."""
    fn = functools.partial(batched_merge_count, r_sizes=r_sizes,
                           s_sizes=s_sizes, key_bound=key_bound)
    return jax.jit(lambda r, s: fn(r, s))
