"""Radix partitioning primitives, TPU-style.

The reference's hot partitioning loops are per-tuple scattered writes made
cache-friendly with software write-combining buffers and AVX non-temporal
streams (``NetworkPartitioning.cpp:116-173,224-260``;
``LocalPartitioning.cpp:194-250``).  SWWC has no TPU analog — the idiomatic
equivalent (SURVEY.md §7.2) is *sort by partition id + offsets from a cumsum of
the histogram*: one vectorized, statically-shaped reorder instead of per-tuple
scatter.  These primitives are the shared core under both NetworkPartitioning
(partition-to-destination-node routing) and LocalPartitioning (second radix
pass), i.e. the TPU equivalents of the GPU ``histogram_build_L1/L2`` +
``reorder_L1/L2`` kernel families (operators/gpu/kernels.cu:19-185).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from tpu_radix_join.data.tuples import CompressedBatch, make_padding_like


def local_histogram(pid: jnp.ndarray, num_partitions: int,
                    valid: jnp.ndarray | None = None) -> jnp.ndarray:
    """Count tuples per partition (LocalHistogram.cpp:44-47).

    ``pid`` uint32 [n]; returns uint32 [num_partitions].  ``valid`` masks out
    padding slots (the reference never needs this because MPI buffers are
    exactly sized; statically-shaped TPU blocks do).
    """
    weights = None if valid is None else valid.astype(jnp.uint32)
    hist = jnp.bincount(pid.astype(jnp.int32), weights=weights, length=num_partitions)
    return hist.astype(jnp.uint32)


def exclusive_cumsum(hist: jnp.ndarray) -> jnp.ndarray:
    """Partition base offsets = exclusive prefix sum of the histogram
    (LocalPartitioning.cpp:165-192, minus the cacheline padding which has no
    meaning for a dense reorder)."""
    return jnp.concatenate([jnp.zeros((1,), hist.dtype), jnp.cumsum(hist)[:-1]])


def reorder_by_partition(
    batch: CompressedBatch, pid: jnp.ndarray, num_partitions: int,
    valid: jnp.ndarray | None = None,
) -> Tuple[CompressedBatch, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Stable reorder so each partition's tuples are contiguous.

    Returns (reordered batch, reordered pid, histogram, base offsets).  Invalid
    (padding) slots are routed to a virtual partition after all real ones so
    they land at the tail.  The reorder itself is ``argsort`` on the partition
    id — XLA lowers this to a parallel sort, the TPU replacement for the SWWC
    scatter loop (see module docstring).
    """
    sort_key = pid.astype(jnp.uint32)
    if valid is not None:
        sort_key = jnp.where(valid, sort_key, jnp.uint32(num_partitions))
    order = jnp.argsort(sort_key, stable=True)
    out = jax.tree.map(lambda x: x[order], batch)
    hist = local_histogram(pid, num_partitions, valid)
    return out, pid[order], hist, exclusive_cumsum(hist)


def scatter_to_blocks(
    batch,
    dest: jnp.ndarray,
    num_blocks: int,
    capacity: int,
    side: str,
    valid: jnp.ndarray | None = None,
):
    """Route tuples into ``num_blocks`` statically-sized blocks of ``capacity``
    slots, padding unused slots with the side's sentinel.

    This is the send half of the Window data plane: where the reference
    ``MPI_Put``s exactly-sized slices computed by OffsetMap
    (``Window.cpp:86-144``), XLA needs static shapes, so each destination gets
    a fixed-capacity block and a valid count (SURVEY.md §7.2).

    Returns (blocks batch with arrays shaped [num_blocks * capacity],
    counts uint32 [num_blocks] — the *unclipped* per-destination demand, and
    overflow uint32 — how many tuples did not fit; 0 in correct runs, checked
    by Window.assert_all_tuples_written).
    """
    n = dest.shape[0]
    sort_key = dest.astype(jnp.uint32)
    if valid is not None:
        sort_key = jnp.where(valid, sort_key, jnp.uint32(num_blocks))
    order = jnp.argsort(sort_key, stable=True)
    sorted_dest = sort_key[order]

    counts = jnp.bincount(sort_key.astype(jnp.int32), length=num_blocks + 1)[
        :num_blocks
    ].astype(jnp.uint32)
    starts = exclusive_cumsum(counts)
    # Rank of each tuple within its destination run of the sorted order.
    safe_dest = jnp.minimum(sorted_dest, jnp.uint32(num_blocks - 1))
    rank = jnp.arange(n, dtype=jnp.uint32) - starts[safe_dest]
    in_cap = rank < jnp.uint32(capacity)
    is_real = sorted_dest < jnp.uint32(num_blocks)
    ok = in_cap & is_real
    slot = jnp.where(ok, safe_dest * jnp.uint32(capacity) + rank,
                     jnp.uint32(num_blocks * capacity))  # OOB slot -> dropped

    pad = make_padding_like(batch, num_blocks * capacity, side)
    sorted_batch = jax.tree.map(lambda x: x[order], batch)
    blocks = jax.tree.map(
        lambda p, v: p.at[slot].set(v, mode="drop"), pad, sorted_batch
    )
    overflow = jnp.sum(jnp.where(is_real & ~in_cap, 1, 0)).astype(jnp.uint32)
    return blocks, counts, overflow
