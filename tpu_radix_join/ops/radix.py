"""Radix partitioning primitives, TPU-style.

The reference's hot partitioning loops are per-tuple scattered writes made
cache-friendly with software write-combining buffers and AVX non-temporal
streams (``NetworkPartitioning.cpp:116-173,224-260``;
``LocalPartitioning.cpp:194-250``).  SWWC has no TPU analog — the idiomatic
equivalent (SURVEY.md §7.2) is *sort by partition id + offsets from a cumsum of
the histogram*: one vectorized, statically-shaped reorder instead of per-tuple
scatter.  These primitives are the shared core under both NetworkPartitioning
(partition-to-destination-node routing) and LocalPartitioning (second radix
pass), i.e. the TPU equivalents of the GPU ``histogram_build_L1/L2`` +
``reorder_L1/L2`` kernel families (operators/gpu/kernels.cu:19-185).
"""

from __future__ import annotations

import sys
from contextlib import nullcontext
from typing import Tuple

import jax
import jax.numpy as jnp

from tpu_radix_join.data.tuples import CompressedBatch, make_padding_like
from tpu_radix_join.ops.sorting import sort_kv_unstable
from tpu_radix_join.performance.measurements import PARTFALLBACK, PARTPASS


# ------------------------------------------------------------- impl selection
#
# Partition-impl auto-selection happens at TRACE time (these functions run
# inside jit/shard_map bodies, where no host counter can tick per
# execution), so the observability hook lives at module level: the engine
# registers its Measurements once (HashJoin.__init__) and every traced
# scatter/reorder site records which path it took — PARTPASS for the fused
# Pallas kernel, PARTFALLBACK when auto degrades to the XLA sort path.
_partition_observer: dict = {"meas": None}
_fallback_logged = False


def install_partition_observer(measurements) -> None:
    """Register a performance.Measurements (or None) to receive PARTPASS /
    PARTFALLBACK ticks and partition spans from trace-time impl selection.
    Process-global: the most recent engine wins, which is the engine whose
    programs are being traced."""
    _partition_observer["meas"] = measurements


def _partition_span(impl: str, site: str, num_partitions: int):
    """Span bracketing the trace-time construction of one fused partition
    op — mirrored into the flight recorder ring like every span."""
    m = _partition_observer["meas"]
    if m is None:
        return nullcontext()
    m.incr(PARTPASS)
    return m.span("partition_pass", impl=impl, site=site,
                  num_partitions=num_partitions)


def _note_fallback(site: str, num_partitions: int, why: str) -> None:
    """Auto-select degraded to the XLA sort path: tick the counter and log
    once per process instead of staying silent (a TPU run quietly paying
    the sort where the fused kernel was expected is a perf bug)."""
    global _fallback_logged
    m = _partition_observer["meas"]
    if m is not None:
        m.incr(PARTFALLBACK)
    if not _fallback_logged:
        _fallback_logged = True
        print(f"[radix] partition auto-select fell back to the XLA sort "
              f"path at {site} (num_partitions={num_partitions}: {why}); "
              f"further fallbacks tick PARTFALLBACK silently",
              file=sys.stderr)


def resolve_partition_impl(impl: str | None, num_partitions: int,
                           site: str) -> str:
    """Resolve a partition ``impl`` request to a concrete path.

    ``None``/"auto" prefers the fused Pallas kernel when the backend has
    one and the fanout fits its unrolled loop, else falls back to the
    sort-based path ("loop") with PARTFALLBACK visibility.  "sort" is an
    explicit alias for the default sort discipline; "loop"/"gather" name
    its two fill disciplines; "pallas"/"pallas_interpret" force the fused
    kernel (interpret = traced JAX ops, the tier-1 CPU parity path)."""
    from tpu_radix_join.ops.pallas.partition import (
        MAX_PARTITIONS, pallas_partition_available)
    if impl in (None, "auto"):
        if not pallas_partition_available():
            _note_fallback(site, num_partitions, "Pallas unavailable")
            return "loop"
        if num_partitions > MAX_PARTITIONS:
            _note_fallback(site, num_partitions,
                           f"> MAX_PARTITIONS {MAX_PARTITIONS}")
            return "loop"
        return "pallas"
    if impl == "sort":
        return "loop"
    return impl


def local_histogram(pid: jnp.ndarray, num_partitions: int,
                    valid: jnp.ndarray | None = None,
                    impl: str | None = None) -> jnp.ndarray:
    """Count tuples per partition (LocalHistogram.cpp:44-47).

    ``pid`` uint32 [n]; returns uint32 [num_partitions].  ``valid`` masks out
    padding slots (the reference never needs this because MPI buffers are
    exactly sized; statically-shaped TPU blocks do).

    ``impl``: None = auto — the Pallas streaming histogram on TPU (one HBM
    pass, masked VPU reductions; 7.5-10 ms at 16M, round-2 chip) vs the XLA
    ``bincount`` scatter-add elsewhere (XLA serializes it on TPU: 154 ms at
    16M).  "xla" / "pallas" / "pallas_interpret" force a path.
    """
    from tpu_radix_join.ops.pallas.histogram import (
        MAX_PARTITIONS, histogram_pallas, pallas_histogram_available)
    if impl is None:
        if (pallas_histogram_available()
                and num_partitions <= MAX_PARTITIONS):
            impl = "pallas"
        else:
            impl = "xla"
            _note_fallback("local_histogram", num_partitions,
                           f"> MAX_PARTITIONS {MAX_PARTITIONS}"
                           if pallas_histogram_available()
                           else "Pallas unavailable")
    weights = None if valid is None else valid.astype(jnp.uint32)
    if impl == "xla":
        # bincount stages two scalar () device_put eqns (weak-typed
        # bounds, ALIAS semantics — free on every backend); the jaxpr
        # transfer rule's byte threshold (analysis/jaxpr/rules_ir.py)
        # keeps them out of the audit while still catching bulk traffic
        hist = jnp.bincount(pid.astype(jnp.int32), weights=weights,
                            length=num_partitions)
        return hist.astype(jnp.uint32)
    return histogram_pallas(pid, weights, num_partitions=num_partitions,
                            interpret=(impl == "pallas_interpret"))


def exclusive_cumsum(hist: jnp.ndarray) -> jnp.ndarray:
    """Partition base offsets = exclusive prefix sum of the histogram
    (LocalPartitioning.cpp:165-192, minus the cacheline padding which has no
    meaning for a dense reorder)."""
    return jnp.concatenate([jnp.zeros((1,), hist.dtype), jnp.cumsum(hist)[:-1]])


def reorder_by_partition(
    batch: CompressedBatch, pid: jnp.ndarray, num_partitions: int,
    valid: jnp.ndarray | None = None,
    impl: str | None = None,
) -> Tuple[CompressedBatch, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Reorder so each partition's tuples are contiguous (order *within* a
    partition is unspecified — every consumer re-sorts or is order-free).

    Returns (reordered batch, reordered pid, histogram, base offsets).  Invalid
    (padding) slots are routed to a virtual partition after all real ones so
    they land at the tail.

    ``impl`` (resolve_partition_impl): the fused Pallas kernel assigns every
    tuple its dense destination in two streaming passes over the ids and the
    lanes move in one unique-index scatter; the sort fallback is ``argsort``
    on the partition id — XLA lowers this to a parallel sort, the TPU
    replacement for the SWWC scatter loop (see module docstring) — with the
    histogram derived from ``searchsorted`` run bounds over the sorted keys
    (one fewer HBM pass than a separate ``local_histogram``).
    """
    sort_key = pid.astype(jnp.uint32)
    if valid is not None:
        sort_key = jnp.where(valid, sort_key, jnp.uint32(num_partitions))
    impl = resolve_partition_impl(impl, num_partitions, "reorder_by_partition")
    if impl in ("pallas", "pallas_interpret"):
        from tpu_radix_join.ops.pallas.partition import partition_slots_pallas
        with _partition_span(impl, "reorder_by_partition", num_partitions):
            # num_partitions + 1 dense groups: the virtual invalid partition
            # is a REAL group here so every tuple lands (a permutation), with
            # invalid rows contiguous at the tail exactly like the sort path
            slots, hist_x = partition_slots_pallas(
                sort_key, num_groups=num_partitions + 1, group_size=1,
                capacity=None, interpret=(impl == "pallas_interpret"))
        scatter = lambda x: (jnp.zeros_like(x) + x[0] * x.dtype.type(0)
                             ).at[slots].set(x, mode="drop")
        out = jax.tree.map(scatter, batch)
        hist = hist_x[:num_partitions]
        return out, scatter(pid), hist, exclusive_cumsum(hist)
    # kv-sort through the ops/sorting switch instead of argsort + gather:
    # the payload lanes travel with their key in one fused sort (a
    # profiled 3x win over argsort+gather on v5e — see scatter_to_blocks),
    # and the site inherits the xla-vs-pallas arm for free.  The key
    # bound (ids are < num_partitions + 1, invalid rows routed to exactly
    # num_partitions) lets the radix arm skip digit passes.
    leaves, treedef = jax.tree.flatten(batch)
    sorted_lanes = sort_kv_unstable(sort_key, *leaves, pid,
                                    key_bound=num_partitions + 1)
    key_s = sorted_lanes[0]
    out = jax.tree.unflatten(treedef, sorted_lanes[1:-1])
    # run bounds over the already-sorted keys replace the separate
    # local_histogram pass: bounds[p] = #keys < p, so adjacent differences
    # are exactly the per-partition counts with invalid rows (key ==
    # num_partitions) excluded — byte-identical to the bincount, one fewer
    # pass over the ids
    bounds = jnp.searchsorted(
        key_s,
        jnp.arange(num_partitions + 1, dtype=jnp.uint32)).astype(jnp.uint32)
    hist = bounds[1:] - bounds[:-1]
    return out, sorted_lanes[-1], hist, exclusive_cumsum(hist)


def scatter_to_blocks(
    batch,
    dest: jnp.ndarray,
    num_blocks: int,
    capacity: int,
    side: str,
    valid: jnp.ndarray | None = None,
    impl: str | None = None,
):
    """Route tuples into ``num_blocks`` statically-sized blocks of ``capacity``
    slots, padding unused slots with the side's sentinel.

    This is the send half of the Window data plane: where the reference
    ``MPI_Put``s exactly-sized slices computed by OffsetMap
    (``Window.cpp:86-144``), XLA needs static shapes, so each destination gets
    a fixed-capacity block and a valid count (SURVEY.md §7.2).

    ``impl`` (resolve_partition_impl; None = auto):
      * "pallas" / "pallas_interpret": the fused histogram→scan→scatter
        kernel (ops/pallas/partition.py) — slot assignment in two streaming
        passes over the ids, then ONE unique-index scatter per lane; no sort.
      * "sort"/"loop"/"gather": sort by destination, then place each run;
        "loop" is a ``fori_loop`` of per-destination dynamic-slice copies
        (one contiguous DMA per destination), "gather" ONE vectorized row
        gather over the [num_blocks, capacity] grid
        (experiments/exp_block_scatter.py holds the on-chip measurements —
        the reference has the same obsession with this inner loop's
        discipline, NetworkPartitioning.cpp:224-260).

    Returns (blocks batch with arrays shaped [num_blocks * capacity],
    counts uint32 [num_blocks] — the *unclipped* per-destination demand, and
    overflow uint32 — how many tuples did not fit; 0 in correct runs, checked
    by Window.assert_all_tuples_written).
    """
    impl = resolve_partition_impl(impl, num_blocks, "scatter_to_blocks")
    if impl in ("pallas", "pallas_interpret"):
        blocks, counts, _, overflow = _scatter_blocks_fused(
            batch, dest, None, num_blocks, 1, capacity, side, valid, impl)
        return blocks, counts, overflow
    sort_key = dest.astype(jnp.uint32)
    if valid is not None:
        sort_key = jnp.where(valid, sort_key, jnp.uint32(num_blocks))

    # One key-value sort carries every lane along (no random gathers — a
    # profiled 3x win over argsort+gather on v5e), then each destination's
    # run is a *contiguous* slice of the sorted lanes.  Unstable: tuple
    # order within a destination block is free (the local probe re-sorts).
    lanes, treedef = jax.tree.flatten(batch)
    sorted_all = sort_kv_unstable(sort_key, *lanes)
    sorted_dest, sorted_lanes = sorted_all[0], sorted_all[1:]

    # Run boundaries via binary search over the sorted keys (num_blocks+1
    # queries) instead of a 16M-wide scatter-add histogram.
    bounds = jnp.searchsorted(
        sorted_dest, jnp.arange(num_blocks + 1, dtype=jnp.uint32)).astype(jnp.uint32)
    counts = bounds[1:] - bounds[:-1]
    starts = bounds[:-1]

    blocks, overflow = _fill_blocks(batch, lanes, treedef, sorted_lanes,
                                    starts, counts, num_blocks, capacity,
                                    side, impl)
    return blocks, counts, overflow


def scatter_to_blocks_grouped(
    batch,
    dest: jnp.ndarray,
    sub: jnp.ndarray,
    num_blocks: int,
    num_sub: int,
    capacity: int,
    side: str,
    valid: jnp.ndarray | None = None,
    impl: str | None = None,
):
    """:func:`scatter_to_blocks` with a secondary ordering key: tuples within
    each destination block land sorted by ``sub`` (the partition id on the
    wire-codec path), and the per-(block, sub) occupancy comes back as an
    extra ``[num_blocks, num_sub]`` array.

    That pair — pid-sorted blocks + per-pid counts — is exactly what the
    packed exchange needs to drop the fanout bits from keys and reconstruct
    them positionally on receipt (data/tuples.pack_blocks).  ``sub`` may be
    ANY value in [0, num_sub) regardless of ``dest`` (skew spreading routes
    hot tuples to destinations that don't own their partition; the header
    records the truth).

    Returns ``(blocks, counts, group_counts, overflow)`` where ``counts`` is
    the unclipped per-destination demand (same contract as
    ``scatter_to_blocks``) and ``group_counts`` is uint32
    [num_blocks, num_sub], *clipped* to capacity so it sums to the tuples
    actually present in each block."""
    impl = resolve_partition_impl(impl, num_blocks * num_sub,
                                  "scatter_to_blocks_grouped")
    if impl in ("pallas", "pallas_interpret"):
        return _scatter_blocks_fused(batch, dest, sub, num_blocks, num_sub,
                                     capacity, side, valid, impl)
    comp = dest.astype(jnp.uint32) * jnp.uint32(num_sub) + sub.astype(
        jnp.uint32)
    sort_key = comp
    if valid is not None:
        sort_key = jnp.where(valid, sort_key,
                             jnp.uint32(num_blocks * num_sub))

    lanes, treedef = jax.tree.flatten(batch)
    sorted_all = sort_kv_unstable(sort_key, *lanes)
    sorted_comp, sorted_lanes = sorted_all[0], sorted_all[1:]

    group_bounds = jnp.searchsorted(
        sorted_comp,
        jnp.arange(num_blocks * num_sub + 1, dtype=jnp.uint32)
    ).astype(jnp.uint32)
    # destination run bounds are every num_sub-th group bound
    bounds = group_bounds[::num_sub]
    counts = bounds[1:] - bounds[:-1]
    starts = bounds[:-1]
    group_raw = (group_bounds[1:] - group_bounds[:-1]).reshape(
        num_blocks, num_sub)
    # clip to capacity the way the block fill does: the first ``capacity``
    # slots of each destination run survive, i.e. the lowest pids keep their
    # tuples and the clip eats the tail
    cum = jnp.minimum(jnp.cumsum(group_raw, axis=1),
                      jnp.uint32(capacity))
    group_counts = jnp.concatenate([cum[:, :1], cum[:, 1:] - cum[:, :-1]],
                                   axis=1)

    blocks, overflow = _fill_blocks(batch, lanes, treedef, sorted_lanes,
                                    starts, counts, num_blocks, capacity,
                                    side, impl)
    return blocks, counts, group_counts, overflow


def _fill_blocks(batch, lanes, treedef, sorted_lanes, starts, counts,
                 num_blocks, capacity, side, impl):
    """Shared block-fill core: place each destination's sorted run into its
    fixed-capacity block, pad the rest with the side sentinel."""
    pad_leaves = jax.tree.leaves(make_padding_like(batch, 1, side))
    col = jnp.arange(capacity, dtype=jnp.uint32)[None, :]
    col_ok = (col < jnp.minimum(counts, jnp.uint32(capacity))[:, None]
              ).reshape(-1)

    if impl == "gather":
        n = sorted_lanes[0].shape[0]
        idx = jnp.minimum((starts[:, None] + col).reshape(-1),
                          jnp.uint32(n - 1))
        masked = [
            jnp.where(col_ok, lane[idx], pad[0])
            for lane, pad in zip(sorted_lanes, pad_leaves)
        ]
    else:
        padded_lanes = [
            jnp.concatenate([lane, jnp.full((capacity,), pad[0], lane.dtype)])
            for lane, pad in zip(sorted_lanes, pad_leaves)
        ]

        def copy_block(d, outs):
            return tuple(
                jax.lax.dynamic_update_slice(
                    out,
                    jax.lax.dynamic_slice(lane, (starts[d],), (capacity,)),
                    (d * capacity,))
                for out, lane in zip(outs, padded_lanes)
            )

        # Derive the init buffers from the input lanes (not fresh zeros) so
        # their varying-manual-axes type matches inside shard_map bodies.
        init = tuple(
            jnp.zeros((num_blocks * capacity,), l.dtype) + l[0] * l.dtype.type(0)
            for l in lanes)
        outs = jax.lax.fori_loop(0, num_blocks, copy_block, init)
        # Mask slots past each destination's count back to the pad value
        # (covers both partial blocks and slice overread into the next run).
        masked = [
            jnp.where(col_ok, out, pad[0])
            for out, pad in zip(outs, pad_leaves)
        ]
    blocks = jax.tree.unflatten(treedef, masked)
    overflow = jnp.sum(
        jnp.maximum(counts, jnp.uint32(capacity)) - jnp.uint32(capacity))
    return blocks, overflow.astype(jnp.uint32)


def _scatter_blocks_fused(batch, dest, sub, num_blocks, num_sub, capacity,
                          side, valid, impl):
    """Fused block fill: the Pallas kernel assigns slots + exact histogram
    in two streaming passes over the (composite) ids, then each lane moves
    in ONE unique-index scatter (``mode="drop"`` discards the overflow/
    invalid sentinel rows).  Returns the 4-tuple shape of the grouped
    entry; the flat entry drops the group_counts member.

    Contract parity with the sort path: counts are the UNCLIPPED demand,
    group_counts the clip that keeps the lowest pids (the kernel drops
    exactly the tuples whose unclipped within-destination position passed
    capacity, i.e. the highest-pid tail), overflow the same
    sum(max(counts - capacity, 0)).  Within-block order is input order
    grouped by pid — sorted by ``sub`` as pack_blocks requires."""
    from tpu_radix_join.ops.pallas.partition import partition_slots_pallas
    key = dest.astype(jnp.uint32)
    if sub is not None:
        key = key * jnp.uint32(num_sub) + sub.astype(jnp.uint32)
    num_groups = num_blocks * num_sub
    if valid is not None:
        key = jnp.where(valid, key, jnp.uint32(num_groups))
    with _partition_span(impl, "scatter_to_blocks", num_groups):
        slots, ghist = partition_slots_pallas(
            key, num_groups=num_groups, group_size=num_sub,
            capacity=capacity, interpret=(impl == "pallas_interpret"))
    lanes, treedef = jax.tree.flatten(batch)
    pad_leaves = jax.tree.leaves(make_padding_like(batch, 1, side))
    # init buffers carry the pad value everywhere (dropped/overflow slots
    # stay sentinel-filled) and derive from the input lanes so their
    # varying-manual-axes type matches inside shard_map bodies
    masked = [
        (jnp.zeros((num_blocks * capacity,), lane.dtype)
         + lane[0] * lane.dtype.type(0) + pad[0]
         ).at[slots].set(lane, mode="drop")
        for lane, pad in zip(lanes, pad_leaves)
    ]
    blocks = jax.tree.unflatten(treedef, masked)
    group_raw = ghist.reshape(num_blocks, num_sub)
    counts = jnp.sum(group_raw, axis=1, dtype=jnp.uint32)
    cum = jnp.minimum(jnp.cumsum(group_raw, axis=1), jnp.uint32(capacity))
    group_counts = jnp.concatenate([cum[:, :1], cum[:, 1:] - cum[:, :-1]],
                                   axis=1)
    overflow = jnp.sum(
        jnp.maximum(counts, jnp.uint32(capacity)) - jnp.uint32(capacity))
    return blocks, counts, group_counts, overflow.astype(jnp.uint32)
