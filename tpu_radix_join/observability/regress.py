"""Perf-regression gate: compare a fresh result against a baseline.

Compares the numeric tags of a fresh ``BENCH_*.json`` (or any flat JSON of
measurements — a ``summary()`` dump, a distilled profile) against a
baseline file, with per-tag relative thresholds and a named-tag allowlist.
The CLI wrapper (tools_check_regress.py) exits non-zero on any regression
and prints the per-tag delta table either way, so a round's bench can gate
a merge the way the tier-1 tests gate correctness.

Direction discipline: throughput-like tags (``value``, ``vs_baseline``,
``*RATE``, ``*gbps``) regress when they *drop*; everything else — the
time-tag vocabulary (JTOTAL, JPROC, ``*_ms``, ``*_us``) — regresses when
it *grows*.  Lower-is-better overrides are checked FIRST: the serve-mode
SLO tags end in words the higher-better vocabulary would otherwise claim
(``admission_rejection_rate`` contains "rate", but MORE rejections is
worse; ``slo_p99_ms`` is a latency), so ``_LOWER_BETTER_SUBSTRINGS``
pins their direction before the substring scan.  A tag only in the
baseline is reported as ``missing`` (a silently vanished measurement is
itself a signal) but fails the gate only under ``strict``.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

DEFAULT_THRESHOLD = 0.25       # bench timings through a shared tunnel are
                               # noisy; per-tag overrides tighten hot tags

# tags where larger is better (everything else is treated as a cost)
_HIGHER_BETTER = {"value", "vs_baseline",
                  # warm queries are capacity-cache hits: fewer means the
                  # resident session stopped amortizing its sizing passes
                  "QWARM",
                  # of the hedges a round launches, the ones whose claim
                  # wins the manifest fence are the ones that bought tail
                  # latency: fewer wins at the same HEDGED count means the
                  # hedges stopped landing before the originals
                  "HEDGEWIN",
                  # lowercase twin for the --recovery-bench --straggle
                  # artifact key (fence wins per hedge round)
                  "hedgewin",
                  # serving fast paths (--serve-throughput-bench): result-
                  # cache hits and delta-merge serves are whole-query
                  # amortization wins — fewer at the same traffic means a
                  # fast path silently stopped firing
                  "RCHIT", "DELTAMERGE",
                  # lowercase twins for the --serve-throughput-bench
                  # artifact keys (same counters, JSON-cased)
                  "rchit", "deltamerge",
                  # queries per fused micro-batch (BATCHQ / BATCHN): a
                  # falling fuse ratio means the window coalescer is
                  # dispatching per-query programs again.  Pinned exactly
                  # because "ratio" is not a direction substring.
                  "batch_fuse_ratio"}
_HIGHER_BETTER_SUBSTRINGS = ("rate", "gbps", "throughput", "tuples/sec",
                             "tuples_per_sec", "per_sec", "pairs/sec",
                             "speedup",
                             # pipelined-grid work counters (--grid-bench):
                             # fewer staged chunks / reused sorts = the
                             # pipeline silently fell back to serial work
                             "prefetch", "sortreuse")
# serve-mode SLO tags that LOOK throughput-like but are costs: rejection /
# miss / degraded fractions regress when they GROW, and every latency
# percentile is a time.  Checked before the higher-better scan, so
# "admission_rejection_rate" is not captured by the "rate" substring.
_LOWER_BETTER_SUBSTRINGS = ("rejection_rate", "miss_rate", "degraded_rate",
                            "latency", "p50_ms", "p95_ms", "p99_ms",
                            # exchange-codec footprint tags (--exchange-bench
                            # and the WIREBYTES counter): more bytes on the
                            # wire or a larger live exchange allocation is
                            # a codec/staging regression even though the
                            # join may still pass
                            "wirebytes", "peak_exchange_bytes",
                            "bytes_per_tuple",
                            # plan-vs-actual drift (planner/audit.py
                            # PLANDRIFT gauge): a growing gap between the
                            # cost model's prediction and the clock means
                            # a stale device profile, even when absolute
                            # perf holds.  Bundle/watchdog counters
                            # (PMBUNDLE/WDOGTRIP) count deaths per round —
                            # more of either is strictly worse.
                            "plandrift", "pmbundle", "wdogtrip",
                            # compile telemetry (observability/compilemon):
                            # more backend compiles / compile milliseconds
                            # per round means shape churn is eating the
                            # resident session's amortization win.  The
                            # calibration tags (tools_profile_fit.py):
                            # growing fit residuals or stale-constant
                            # counts mean the profile is losing contact
                            # with the hardware.
                            "ncompile", "compilems", "compile_ms",
                            "recompile_storms", "fit_residual",
                            "stale_constants",
                            # partition A/B tags (--partition-bench): both
                            # arms' walls and the reduced kernel unit are
                            # times (the headline speedup rides the
                            # "speedup" substring above); PARTFALLBACK
                            # counts silent degrades to the XLA sort path —
                            # on a TPU backend more of them means the fused
                            # kernel stopped being selected
                            "partition_ms", "partition_kernel_ms",
                            "partition_sort_ms", "partition_unit_ms",
                            "partfallback",
                            # flat-sort A/B tags (--sort-bench): both arms'
                            # walls, the radix slot-kernel wall, the reduced
                            # per-digit-pass unit, and the pass counts are
                            # all times or work counts (more LSD passes per
                            # sort means the key-bound pass skip stopped
                            # firing); SORTFALLBACK counts the auto-select
                            # degrading to lax.sort — it ticks once per
                            # process by design, so on a TPU backend any
                            # nonzero value means the Pallas sort engine
                            # stopped being selected
                            "sort_ms", "sort_xla_ms", "sort_kernel_ms",
                            "sort_pass_unit_ms", "sort_passes",
                            "sort_bounded_ms", "sort_bounded_passes",
                            "sortfallback",
                            # elastic-recovery tags (--recovery-bench and
                            # the membership counters): more ranks lost,
                            # a longer detect→recompute→splice wall, more
                            # partitions recomputed, or a higher membership
                            # epoch per round are all strictly worse — a
                            # healthy fleet holds MEPOCH at 0
                            "ranklost", "recover_ms", "recoverms",
                            "recovern", "mepoch", "restart_ms",
                            # straggler hedging (--recovery-bench --straggle
                            # and the SPECWASTE counter): both tail walls are
                            # times (the headline tail speedup rides the
                            # "speedup" substring above), and more wasted
                            # speculative recomputes per round means the
                            # detector is hedging partitions the original
                            # was about to finish anyway
                            "specwaste", "hedged_ms", "unhedged_ms",
                            # mesh growth (--recovery-bench --grow): both
                            # arms' recompute walls are times
                            "grown_ms", "fixed_ms",
                            # static-analysis gate (tools_lint.py --json):
                            # more live lint findings is strictly worse —
                            # a finding-count regression gates like a perf
                            # regression
                            "lint_findings", "stale_baseline",
                            # graftcheck (tools_jaxpr_audit.py --json): live
                            # IR-level findings gate the same way
                            "jaxpr_findings",
                            # critical-path attribution (--critpath-bench
                            # and observability/critpath.py): instrumented-
                            # vs-bare overhead must stay a rounding error
                            # (the <1% acceptance bar), and a growing
                            # wait fraction means more of the bounding
                            # rank's path is collective-wait/straggle
                            # rather than work — a fleet-balance
                            # regression even when JTOTAL holds
                            "critpath_overhead_pct", "wait_fraction",
                            # fleet serving (--fleet-bench and the fleet
                            # counters, service/fleet.py): failover wall,
                            # replayed intents, journal depth, and worker
                            # restarts per round all regress when they
                            # GROW; double_exec is the exactly-once
                            # invariant — its baseline is 0, so compare_
                            # tags' zero-base rule makes ANY nonzero an
                            # infinite delta: a hard fail at every
                            # threshold, by design
                            "failover", "replayn", "jdepth",
                            "worker_restarts", "double_exec",
                            "wincarn", "wrestart", "doubleexec")
# Exact-name lower-is-better pins for the Measurements counter/timer
# vocabulary (performance/measurements.py).  Historically these rode the
# "unmatched tags default to cost" rule; the counter-tag lint rule
# (analysis/rules_tags.py) now requires every emitted tag to be
# *declared* — pinned here, in _HIGHER_BETTER, or explicitly neutral —
# so the default never decides a gate silently.  Phase walls and waits
# are times; retry/backoff, rejection/deadline/degrade verdicts, breaker
# trips, verification failures/repairs, per-trace pass selections, and
# the wire-byte/pack-ratio gauges all regress when they GROW.
_COST_TAGS = {"JTOTAL", "JPROC", "JHIST", "JMPI", "JCOMPILE", "SWINALLOC",
              "SNETCOMPL", "SLOCPREP", "MWINWAIT", "SDISPATCH", "CTOTAL",
              "BPBUILD", "BPPROBE", "VCHK",
              "RETRYN", "BACKOFFMS", "RETRIES",
              "QREJECT", "QDEADLINE", "QDEGRADED", "BRKTRIP",
              "VFAIL", "VREPAIR",
              "PARTPASS", "SORTPASS",
              "MWINBYTES", "PACKRATIO",
              "JXAUDIT",
              # straggler hedging: more hedges per round means more ranks
              # fell below the relative-progress threshold (the detector
              # may be right every time and it is still a fleet-health
              # regression); SPECWASTE also rides the lower-is-better
              # substring for the bench artifact keys
              "HEDGED", "SPECWASTE",
              # result-cache misses (cold content, TTL expiry, digest or
              # epoch drop): more misses at the same traffic means the
              # content fingerprint stopped deduping equal work
              "RCMISS",
              # lowercase twin for the --serve-throughput-bench artifact key
              "rcmiss"}
# Explicitly neutral tags: workload/geometry descriptors with no
# regression direction (tuple counts scale with the input, capacities
# and stage counts describe the plan, chaos/checkpoint counters describe
# the scenario).  Declared so the counter-tag rule can tell "decided
# neutral" from "nobody looked"; when one shows up in a baseline diff it
# is still compared under the conservative cost default.
NEUTRAL_TAGS = {"RTUPLES", "STUPLES", "RESULTS",
                "MWINPUTCNT", "WINCAPR", "WINCAPS", "XSTAGES",
                "BPBUILDTUPLES", "BPPROBETUPLES",
                "VCHKN", "QADMIT", "BRKPROBE",
                "FINJECT", "CKPTSAVE", "CKPTLOAD", "GRIDPAIRS",
                "STATICMEM",
                # admissions describe the scenario (a grow arm admits by
                # design); losses regress, joins don't
                "RANKJOIN", "rankjoin",
                # micro-batch shape descriptors: batches formed and queries
                # batched scale with traffic — the gated observable is the
                # fuse ratio (batch_fuse_ratio, pinned higher-better)
                "BATCHN", "BATCHQ", "batchn", "batchq",
                # liveness polls answered during a bench run: a scenario
                # count (the bench gates that every poll answered)
                "statusz_polls",
                # resident sorted-union bytes: a gauge bounded by the
                # operator's resident_budget_bytes — more resident state
                # is neither win nor loss by itself (the delta_speedup it
                # buys is the gated observable)
                "RESBYTES", "resbytes"}
# bookkeeping fields that are not measurements at all
_SKIP = {"n", "rc", "probe_attempts", "wait_budget_s", "size", "iters",
         "schema_version",
         # --recovery-bench --grow/--straggle scenario descriptors: the
         # injected slowdown, the membership split, and the audit total
         # parameterize the arm, they do not measure it
         "straggle_factor", "survivors_fixed", "survivors_grown",
         "manifest_total",
         # --fleet-bench scenario descriptors: pool size and per-arm query
         # count parameterize the A/B, they do not measure it
         "workers", "queries"}


def higher_is_better(tag: str) -> bool:
    t = tag.lower()
    if tag in _COST_TAGS or any(s in t for s in _LOWER_BETTER_SUBSTRINGS):
        return False
    return (tag in _HIGHER_BETTER
            or any(s in t for s in _HIGHER_BETTER_SUBSTRINGS))


def tag_is_declared(tag: str) -> bool:
    """True when the tag's gate direction was *decided*: an exact pin
    (_HIGHER_BETTER / _COST_TAGS / NEUTRAL_TAGS / _SKIP) or a substring
    match in either direction list.  The counter-tag lint rule
    (analysis/rules_tags.py) fails any emitted tag for which this is
    False — the implicit cost default must never decide a gate."""
    t = tag.lower()
    return (tag in _HIGHER_BETTER or tag in _COST_TAGS
            or tag in NEUTRAL_TAGS or tag in _SKIP
            or any(s in t for s in _LOWER_BETTER_SUBSTRINGS)
            or any(s in t for s in _HIGHER_BETTER_SUBSTRINGS))


def extract_tags(obj: dict) -> Dict[str, float]:
    """Numeric measurement tags of one result JSON.

    Accepts a bare BENCH dict, a ``{"tags": {...}}`` wrapper, or a runner
    artifact wrapper whose payload sits under ``"parsed"``.
    """
    if isinstance(obj.get("parsed"), dict):
        obj = obj["parsed"]
    if isinstance(obj.get("tags"), dict):
        obj = obj["tags"]
    out = {}
    for k, v in obj.items():
        if k in _SKIP or isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[k] = float(v)
    return out


def parse_tag_thresholds(specs: Iterable[str]) -> Dict[str, float]:
    """``["JTOTAL=0.10", ...]`` -> {"JTOTAL": 0.10}."""
    out = {}
    for spec in specs:
        tag, _, val = spec.partition("=")
        if not _ or not tag:
            raise ValueError(f"bad tag threshold {spec!r} (want TAG=REL)")
        out[tag] = float(val)
    return out


def compare_tags(baseline: Dict[str, float], fresh: Dict[str, float],
                 threshold: float = DEFAULT_THRESHOLD,
                 tag_thresholds: Optional[Dict[str, float]] = None,
                 allow: Iterable[str] = (),
                 strict: bool = False) -> List[dict]:
    """Per-tag delta rows, worst regressions first.

    A row's ``status``: ``regressed`` (worsened past its threshold),
    ``allowed`` (would have regressed but is allowlisted), ``missing``
    (baseline tag absent from fresh; regresses only under ``strict``),
    ``new`` (fresh-only, informational), ``ok`` otherwise.
    """
    tag_thresholds = tag_thresholds or {}
    allow = set(allow)
    rows = []
    for tag in sorted(set(baseline) | set(fresh)):
        if tag not in baseline:
            rows.append({"tag": tag, "base": None, "fresh": fresh[tag],
                         "delta_rel": None, "threshold": None,
                         "status": "new"})
            continue
        thr = tag_thresholds.get(tag, threshold)
        if tag not in fresh:
            status = ("allowed" if tag in allow
                      else ("regressed" if strict else "missing"))
            rows.append({"tag": tag, "base": baseline[tag], "fresh": None,
                         "delta_rel": None, "threshold": thr,
                         "status": status})
            continue
        base, new = baseline[tag], fresh[tag]
        # signed relative delta, positive = worse (cost grew / rate fell)
        if base == 0:
            worse = (new - base) if not higher_is_better(tag) else (base - new)
            delta = 0.0 if worse <= 0 else float("inf")
        elif higher_is_better(tag):
            delta = (base - new) / abs(base)
        else:
            delta = (new - base) / abs(base)
        if delta > thr:
            status = "allowed" if tag in allow else "regressed"
        else:
            status = "ok"
        rows.append({"tag": tag, "base": base, "fresh": new,
                     "delta_rel": delta, "threshold": thr,
                     "status": status})
    order = {"regressed": 0, "missing": 1, "allowed": 2, "ok": 3, "new": 4}
    rows.sort(key=lambda r: (order[r["status"]],
                             -(r["delta_rel"] or 0.0), r["tag"]))
    return rows


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float) and v == float("inf"):
        return "inf"
    return f"{v:.4g}"


def format_table(rows: List[dict]) -> str:
    """Readable per-tag delta table (worse > 0 means regression)."""
    head = ["tag", "baseline", "fresh", "worse%", "limit%", "status"]
    body = []
    for r in rows:
        pct = ("-" if r["delta_rel"] is None
               else ("inf" if r["delta_rel"] == float("inf")
                     else f"{100 * r['delta_rel']:+.1f}"))
        lim = "-" if r["threshold"] is None else f"{100 * r['threshold']:.0f}"
        body.append([r["tag"], _fmt(r["base"]), _fmt(r["fresh"]),
                     pct, lim, r["status"]])
    widths = [max(len(row[i]) for row in [head] + body)
              for i in range(len(head))]
    lines = ["  ".join(h.ljust(w) for h, w in zip(head, widths)),
             "  ".join("-" * w for w in widths)]
    lines += ["  ".join(c.ljust(w) for c, w in zip(row, widths))
              for row in body]
    return "\n".join(lines)


def regressions(rows: List[dict]) -> List[dict]:
    return [r for r in rows if r["status"] == "regressed"]


def check_result(fresh: dict, baseline_path: str,
                 threshold: float = DEFAULT_THRESHOLD,
                 tag_thresholds: Optional[Dict[str, float]] = None,
                 allow: Iterable[str] = (),
                 strict: bool = False) -> tuple:
    """(exit_code, report_text) for an in-memory fresh result — the hook
    bench.py calls as its ``--check-regress`` post-step.  A baseline with
    no numeric tags (e.g. the repo's published-{} BASELINE.json) passes
    with a note: nothing to compare is not a regression."""
    with open(baseline_path) as f:
        base = extract_tags(json.load(f))
    if not base:
        return 0, (f"regress-check: baseline {baseline_path} carries no "
                   f"numeric tags; nothing to compare")
    rows = compare_tags(base, extract_tags(fresh), threshold=threshold,
                        tag_thresholds=tag_thresholds, allow=allow,
                        strict=strict)
    bad = regressions(rows)
    verdict = (f"REGRESSED: {len(bad)} tag(s) past threshold"
               if bad else "ok: no tag past threshold")
    return (1 if bad else 0), format_table(rows) + "\n" + verdict


def check_files(fresh_path: str, baseline_path: str, **kw) -> tuple:
    with open(fresh_path) as f:
        fresh = json.load(f)
    return check_result(fresh, baseline_path, **kw)
