"""Compile-time telemetry: jax.monitoring events -> NCOMPILE/COMPILEMS.

XLA compilation is the one cost the reference has no analog for
(Measurements.cpp keeps none because C++ has no runtime compile), and
here it is both large (~seconds per program through the tunnel) and
*recurring* when shapes churn: a resident serve session that recompiles
after warmup is leaking its amortization win.  JCOMPILE only times the
window-allocation compile the engine brackets explicitly; this monitor
hears EVERY backend compile via ``jax.monitoring``'s
``/jax/core/compile/backend_compile_duration`` event and mirrors it into
the registry's counters:

  * ``NCOMPILE``  — backend compiles observed (count);
  * ``COMPILEMS`` — total backend-compile wall milliseconds.

Because they are ordinary counters they ride everywhere counters already
go: heartbeat ticks (MetricsSampler snapshots ``m.counters``), the
run-end ledger row, forensics bundles, and the regress gate (pinned
lower-is-better).  service/session.py watches the per-query NCOMPILE
delta to warn on recompile storms after warmup.

jax.monitoring offers no per-listener deregistration (only a global
clear), so ONE module-level listener is registered on first install and
dispatches to the currently-installed registries; ``uninstall`` removes
a registry from that set, after which the listener is inert for it.
"""

from __future__ import annotations

from typing import List

from tpu_radix_join.performance.measurements import COMPILEMS, NCOMPILE

#: the duration event XLA fires once per backend compile (jax 0.4.x)
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_registered = False
_active: List[object] = []      # installed Measurements registries


def _on_duration(event: str, duration_secs: float, **kw) -> None:
    if event != BACKEND_COMPILE_EVENT:
        return
    ms = max(0, int(round(duration_secs * 1e3)))
    for m in list(_active):
        try:
            m.incr(NCOMPILE)
            m.incr(COMPILEMS, by=ms)
        except Exception:   # noqa: BLE001 — telemetry must not fail a compile
            pass


def install_compile_monitor(measurements):
    """Start mirroring backend-compile events into ``measurements``'
    NCOMPILE/COMPILEMS counters.  Idempotent per registry; returns the
    registry for chaining."""
    global _registered
    if not _registered:
        import jax.monitoring
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _registered = True
    if measurements not in _active:
        _active.append(measurements)
    return measurements


def uninstall_compile_monitor(measurements) -> None:
    """Stop mirroring into ``measurements`` (the global listener stays
    registered but becomes a no-op for it — jax.monitoring cannot drop a
    single listener)."""
    try:
        _active.remove(measurements)
    except ValueError:
        pass
