"""Cross-rank critical-path attribution over exported span timelines.

The span tracer (observability/spans.py) records *what* each rank did and
*when*; the timeline merger aligns the clocks.  Neither answers the
question an operator actually asks: "this 8-way join took 5.9 s — which
rank's which phase bounded the wall clock, and how much of the path was
waiting rather than work?"  This module reconstructs the causal DAG of
one join from the per-rank span streams and walks its critical path:

  * **nodes** — phase spans (the Measurements tag vocabulary: JHIST,
    JMPI, JPROC, SWINALLOC, exchange_pack, ... ) per rank;
  * **cross-rank edges** — sync points where every rank must rendezvous:
    the histogram psum (JHIST), the all_to_all exchange (JMPI /
    exchange_pack / exchange_stage), lease-epoch bumps (rank_lost /
    rank_join instants) and manifest first-writer-wins claims
    (hedge_claim instants).  The k-th occurrence of a sync span across
    ranks forms one barrier; the barrier completes when the slowest
    rank arrives, so the path between consecutive barriers runs through
    the *bounding* rank of the later one.

Per-segment decomposition splits the bounding rank's time into

  * ``compute``          — covered by ordinary phase spans,
  * ``collective_wait``  — covered by exchange/collective spans, plus
    any gap no span covers (idle at a sub-barrier),
  * ``straggle``         — covered by hedge/recovery/regrow spans, plus
    the barrier skew (how far the bounding rank's arrival trailed the
    median peer — the excess one slow rank cost everyone else).

Partial-tolerant by design: a torn or missing rank degrades the result
to a partial path with a warning (never a crash) — the same discipline
as timeline.merge_timeline.  All public entry points return plain dicts
(ms units) that serialize straight into ``meta["critical_path"]``,
ledger rows, statusz snapshots, and post-mortem bundles.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from tpu_radix_join.observability.spans import HOST_TID, SPAN_SUFFIX

# --------------------------------------------------------------------------
# phase vocabulary → path classes
# --------------------------------------------------------------------------

# umbrella spans cover the whole run / query; they are the envelope, not
# path segments, and are excluded from coverage
UMBRELLA_PHASES = frozenset({"JTOTAL", "CTOTAL", "query"})

# spans that imply a cross-rank rendezvous: histogram psum, the
# all_to_all exchange and its staged variants, window-completion fences
BARRIER_PHASES = ("JHIST", "exchange_pack", "JMPI", "exchange_stage",
                  "SNETCOMPL")

# time inside these spans is collective/wait, not local compute
COLLECTIVE_PHASES = frozenset({"JMPI", "SNETCOMPL", "MWINWAIT",
                               "exchange_pack", "exchange_stage"})

# robustness detours: time here exists only because a peer straggled,
# died, or joined — straggle class, attributed to the causing rank
STRAGGLE_PHASES = frozenset({"hedge", "recovery", "regrow"})

# classification priority when spans nest (exchange inside JPROC → that
# window is collective); higher wins
_PRIO_WAIT, _PRIO_COMPUTE, _PRIO_COLLECTIVE, _PRIO_STRAGGLE = 0, 1, 2, 3
_CLASS_NAMES = {_PRIO_WAIT: "collective_wait", _PRIO_COMPUTE: "compute",
                _PRIO_COLLECTIVE: "collective_wait",
                _PRIO_STRAGGLE: "straggle"}


def _phase_prio(name: str) -> Optional[int]:
    if name in UMBRELLA_PHASES:
        return None
    if name in STRAGGLE_PHASES:
        return _PRIO_STRAGGLE
    if name in COLLECTIVE_PHASES:
        return _PRIO_COLLECTIVE
    return _PRIO_COMPUTE


# --------------------------------------------------------------------------
# stream ingestion
# --------------------------------------------------------------------------

def stream_from_tracer(tracer) -> dict:
    """In-memory stream from a live SpanTracer (the local rank's view —
    lets the driver print a [CRITPATH] line without a file round-trip)."""
    return {
        "rank": int(tracer.rank),
        "trace_id": tracer.trace_id,
        "epoch_s": float(tracer.epoch_s),
        "tags": dict(tracer.tags),
        "events": list(tracer.events),
        "file": None,
    }


def _stream_from_doc(path: str, doc: dict) -> Optional[dict]:
    md = doc.get("metadata", {})
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return None
    return {
        "rank": int(md.get("rank", 0)),
        "trace_id": md.get("trace_id"),
        "epoch_s": float(md.get("epoch_s", 0.0)),
        "tags": md.get("tags", {}) or {},
        "events": events,
        "file": os.path.basename(path),
    }


def load_streams(timeline_dir: str, trace_id: Optional[str] = None,
                 ) -> Tuple[List[dict], List[str]]:
    """Load per-rank span streams from ``timeline_dir``.

    Files are correlated by **trace identity**, not directory mtime: with
    ``trace_id`` given only matching files join the group; otherwise the
    largest trace-id cohort wins (latest epoch anchor breaks ties), so a
    directory holding several runs' exports still yields one coherent
    join.  Unreadable files degrade to warnings, never exceptions.
    """
    # local import: timeline depends on spans only, no cycle back here
    from tpu_radix_join.observability.timeline import (_load,
                                                       find_span_files)
    warnings: List[str] = []
    streams: List[dict] = []
    for path in find_span_files(timeline_dir):
        doc, reason = _load(path)
        if doc is None:
            warnings.append(f"skipped {os.path.basename(path)}: {reason}")
            continue
        st = _stream_from_doc(path, doc)
        if st is None:
            warnings.append(f"skipped {os.path.basename(path)}: "
                            "no traceEvents list")
            continue
        streams.append(st)
    if not streams:
        return [], warnings

    if trace_id:
        chosen = trace_id
    else:
        cohorts: Dict[str, List[dict]] = {}
        for st in streams:
            cohorts.setdefault(st["trace_id"] or "", []).append(st)
        chosen = max(cohorts,
                     key=lambda t: (len(cohorts[t]),
                                    max(s["epoch_s"] for s in cohorts[t])))
    kept = [s for s in streams if (s["trace_id"] or "") == (chosen or "")]
    dropped = len(streams) - len(kept)
    if dropped:
        warnings.append(f"{dropped} span file(s) from other trace_ids "
                        f"ignored (selected trace {chosen or '<none>'})")
    if not kept:       # requested trace_id matched nothing: say so
        warnings.append(f"no span files match trace_id {chosen}")
    # one stream per rank: newest anchor wins on duplicates
    by_rank: Dict[int, dict] = {}
    for st in kept:
        prev = by_rank.get(st["rank"])
        if prev is None or st["epoch_s"] >= prev["epoch_s"]:
            by_rank[st["rank"]] = st
    if len(by_rank) < len(kept):
        warnings.append(f"{len(kept) - len(by_rank)} duplicate rank "
                        "file(s) superseded by newer anchors")
    return [by_rank[r] for r in sorted(by_rank)], warnings


def _aligned_spans(streams: Sequence[dict]) -> Tuple[dict, dict, List[str]]:
    """Shift every rank onto the earliest epoch anchor (the timeline
    merge discipline) and index complete host spans / instants per rank.
    Returns (spans_by_rank, instants_by_rank, warnings); timestamps µs on
    the shared clock."""
    warnings: List[str] = []
    t0 = min(st["epoch_s"] for st in streams)
    spans: Dict[int, List[dict]] = {}
    instants: Dict[int, List[dict]] = {}
    for st in streams:
        shift = (st["epoch_s"] - t0) * 1e6
        rank = st["rank"]
        torn = 0
        for ev in st["events"]:
            ph = ev.get("ph")
            if ev.get("tid", HOST_TID) != HOST_TID:
                continue
            if ph == "X":
                args = ev.get("args") or {}
                if args.get("unclosed"):
                    torn += 1
                spans.setdefault(rank, []).append({
                    "name": ev.get("name", "?"),
                    "ts": float(ev.get("ts", 0.0)) + shift,
                    "dur": max(0.0, float(ev.get("dur", 0.0))),
                    "args": args,
                })
            elif ph == "i":
                instants.setdefault(rank, []).append({
                    "name": ev.get("name", "?"),
                    "ts": float(ev.get("ts", 0.0)) + shift,
                    "args": ev.get("args") or {},
                })
        if torn:
            warnings.append(f"rank {rank}: {torn} span(s) torn open at "
                            "save (crash/cancel path) — durations "
                            "truncated at export time")
    for lst in spans.values():
        lst.sort(key=lambda s: s["ts"])
    for lst in instants.values():
        lst.sort(key=lambda s: s["ts"])
    return spans, instants, warnings


# --------------------------------------------------------------------------
# DAG: barriers (cross-rank edges) + classified coverage (node weights)
# --------------------------------------------------------------------------

def _median(vals: Sequence[float]) -> float:
    vs = sorted(vals)
    n = len(vs)
    return vs[n // 2] if n % 2 else 0.5 * (vs[n // 2 - 1] + vs[n // 2])


def _build_barriers(spans_by_rank: Dict[int, List[dict]]) -> List[dict]:
    """k-th occurrence of each sync-phase span across ranks = one
    barrier; completion = slowest arrival."""
    if len(spans_by_rank) < 2:
        return []
    occ: Dict[Tuple[str, int], Dict[int, float]] = {}
    for rank, spans in spans_by_rank.items():
        counts: Dict[str, int] = {}
        for sp in spans:
            name = sp["name"]
            if name not in BARRIER_PHASES:
                continue
            k = counts.get(name, 0)
            counts[name] = k + 1
            occ.setdefault((name, k), {})[rank] = sp["ts"] + sp["dur"]
    barriers = []
    for (name, k), arrivals in occ.items():
        if len(arrivals) < 2:
            continue        # a lone rank's span is a node, not an edge
        t = max(arrivals.values())
        bounding = max(arrivals, key=lambda r: arrivals[r])
        skew = max(0.0, t - _median(list(arrivals.values())))
        barriers.append({
            "name": name, "occurrence": k, "t_us": t,
            "bounding_rank": bounding, "skew_us": skew,
            "arrivals_us": dict(arrivals),
        })
    barriers.sort(key=lambda b: b["t_us"])
    return barriers


def _classified_window(spans: Sequence[dict], a: float, b: float,
                       ) -> Tuple[Dict[int, float], Dict[str, float]]:
    """Sweep the owner rank's spans over window [a, b]: at every instant
    the highest-priority covering span class wins (nesting-safe); gaps
    class as wait.  Returns (class_prio→µs, phase name→µs on path)."""
    bounds: List[Tuple[float, int, int, str]] = []
    for sp in spans:
        prio = _phase_prio(sp["name"])
        if prio is None:
            continue
        s, e = max(a, sp["ts"]), min(b, sp["ts"] + sp["dur"])
        if e > s:
            bounds.append((s, 1, prio, sp["name"]))
            bounds.append((e, -1, prio, sp["name"]))
    acc = {_PRIO_WAIT: 0.0, _PRIO_COMPUTE: 0.0,
           _PRIO_COLLECTIVE: 0.0, _PRIO_STRAGGLE: 0.0}
    phase_us: Dict[str, float] = {}
    if not bounds:
        acc[_PRIO_WAIT] = max(0.0, b - a)
        return acc, phase_us
    bounds.sort(key=lambda x: (x[0], -x[1]))
    # active[prio] -> {name: depth}
    active: Dict[int, Dict[str, int]] = {p: {} for p in acc}
    prev = a
    i = 0
    while i <= len(bounds):
        t = bounds[i][0] if i < len(bounds) else b
        t = min(max(t, a), b)
        if t > prev:
            top = max((p for p in active if active[p]),
                      default=_PRIO_WAIT)
            acc[top] += t - prev
            if active.get(top):
                name = next(iter(active[top]))
                phase_us[name] = phase_us.get(name, 0.0) + (t - prev)
            prev = t
        if i == len(bounds):
            break
        _, delta, prio, name = bounds[i]
        d = active[prio]
        d[name] = d.get(name, 0) + delta
        if d[name] <= 0:
            d.pop(name, None)
        i += 1
    if b > prev:
        acc[_PRIO_WAIT] += b - prev
    return acc, phase_us


# --------------------------------------------------------------------------
# hedge / recovery claims
# --------------------------------------------------------------------------

def _hedge_summary(spans_by_rank: Dict[int, List[dict]],
                   instants_by_rank: Dict[int, List[dict]],
                   t_start: float, t_end: float) -> Optional[dict]:
    """Condense manifest first-writer-wins claims + hedge events into a
    shortening estimate.  Measured basis when the straggler's own stream
    is visible (its late arrival vs the claim that released the
    barrier); projected basis otherwise (rate-extrapolated from the
    hedge event's progress counters)."""
    claims: List[dict] = []
    hedge_events: List[dict] = []
    for rank, insts in instants_by_rank.items():
        for ev in insts:
            if ev["name"] == "hedge_claim":
                claims.append({"rank": rank, "t_ms": ev["ts"] / 1e3,
                               **{k: ev["args"].get(k)
                                  for k in ("partition", "owner", "epoch")
                                  if k in ev["args"]}})
            elif ev["name"] in ("hedge", "straggle"):
                hedge_events.append({"rank": rank, "t_us": ev["ts"],
                                     "args": ev["args"]})
    if not claims and not hedge_events:
        return None
    straggler = None
    for ev in hedge_events:
        if ev["args"].get("straggler") is not None:
            straggler = int(ev["args"]["straggler"])
            break

    saved_ms = None
    basis = None
    claim_t = max((c["t_ms"] * 1e3 for c in claims), default=None)
    if claim_t is not None and straggler is not None:
        strag_spans = spans_by_rank.get(straggler)
        if strag_spans:
            # measured: the claim released the barrier at claim_t; the
            # straggler itself only arrived at its last span end
            arrival = max(sp["ts"] + sp["dur"] for sp in strag_spans)
            saved_ms = max(0.0, (arrival - claim_t) / 1e3)
            basis = "measured"
        else:
            for ev in hedge_events:
                args = ev["args"]
                try:
                    progress = float(args.get("progress", 0.0))
                    outstanding = float(args.get("outstanding", 0.0))
                except (TypeError, ValueError):
                    continue
                elapsed = max(0.0, ev["t_us"] - t_start)
                if progress > 0 and outstanding > 0 and elapsed > 0:
                    # rate-extrapolate the straggler's finish had nobody
                    # reclaimed its partitions
                    projected = t_start + elapsed * (
                        (progress + outstanding) / progress)
                    saved_ms = max(0.0, (projected - t_end) / 1e3)
                    basis = "projected"
                    break
                if progress == 0 and outstanding > 0 and elapsed > 0:
                    # stalled straggler: it finished nothing in `elapsed`,
                    # so each outstanding partition costs > elapsed — a
                    # conservative floor on the finish nobody waited for
                    projected = ev["t_us"] + outstanding * elapsed
                    saved_ms = max(0.0, (projected - t_end) / 1e3)
                    basis = "projected"
                    break
    return {
        "claims": claims,
        "n_claims": len(claims),
        "straggler": straggler,
        "saved_ms_estimate": (round(saved_ms, 3)
                              if saved_ms is not None else None),
        "basis": basis,
    }


# --------------------------------------------------------------------------
# the path itself
# --------------------------------------------------------------------------

def compute_critical_path(streams: Sequence[dict],
                          warnings: Optional[List[str]] = None,
                          window_us: Optional[Tuple[float, float]] = None,
                          ) -> dict:
    """Reconstruct the critical path over aligned per-rank streams.

    Returns a plain-dict report (ms units) with the path length, the
    bounding rank, compute / collective-wait / straggle fractions,
    per-rank attribution, the barrier list, and any hedge shortening —
    or a degraded ``{"error": ...}`` dict when no usable spans exist
    (degrade, never raise: this runs on crash-path artifacts).
    """
    warnings = list(warnings or [])
    streams = [s for s in streams if s and s.get("events")]
    if not streams:
        return {"error": "no span streams", "warnings": warnings,
                "partial": True}
    spans_by_rank, instants_by_rank, torn_warn = _aligned_spans(streams)
    warnings.extend(torn_warn)
    spans_by_rank = {r: s for r, s in spans_by_rank.items() if s}
    if not spans_by_rank:
        return {"error": "no complete spans in any stream",
                "warnings": warnings, "partial": True}

    if window_us is not None:
        lo, hi = window_us
        spans_by_rank = {
            r: [s for s in sp if s["ts"] < hi and s["ts"] + s["dur"] > lo]
            for r, sp in spans_by_rank.items()}
        spans_by_rank = {r: s for r, s in spans_by_rank.items() if s}
        instants_by_rank = {
            r: [e for e in iv if lo <= e["ts"] <= hi]
            for r, iv in instants_by_rank.items()}
        if not spans_by_rank:
            return {"error": "no spans in window", "warnings": warnings,
                    "partial": True}

    # envelope: prefer the JTOTAL umbrella (single-rank path length ==
    # measured JTOTAL by construction); fall back to the event hull
    jt_starts, jt_ends, jt_durs = [], [], {}
    for rank, spans in spans_by_rank.items():
        for sp in spans:
            if sp["name"] in UMBRELLA_PHASES:
                jt_starts.append(sp["ts"])
                jt_ends.append(sp["ts"] + sp["dur"])
                jt_durs[rank] = max(jt_durs.get(rank, 0.0), sp["dur"])
    if jt_starts:
        t_start, t_end = min(jt_starts), max(jt_ends)
        # a hedge/recovery detour is causally part of the join even when
        # the umbrella aborted before it (the straggle abort ends JTOTAL,
        # then the reclaimed partitions re-execute under a straggle-phase
        # span): extend the envelope so the detour lands on the path
        for spans in spans_by_rank.values():
            for sp in spans:
                if (sp["name"] in STRAGGLE_PHASES
                        and sp["ts"] >= t_start):
                    t_end = max(t_end, sp["ts"] + sp["dur"])
    else:
        t_start = min(sp["ts"] for s in spans_by_rank.values() for sp in s)
        t_end = max(sp["ts"] + sp["dur"]
                    for s in spans_by_rank.values() for sp in s)
        warnings.append("no JTOTAL umbrella span found; envelope taken "
                        "from the event hull")
    if window_us is not None:
        t_start = max(t_start, window_us[0])
        t_end = min(t_end, window_us[1])
    path_us = max(0.0, t_end - t_start)
    if path_us <= 0.0:
        return {"error": "empty envelope", "warnings": warnings,
                "partial": True}

    # missing ranks: the contiguous-rank convention (0..max) — a hole
    # means a peer died before saving; path degrades to partial
    present = sorted(spans_by_rank)
    missing = sorted(set(range(max(present) + 1)) - set(present))
    if missing:
        warnings.append(f"rank(s) {missing} missing from the trace "
                        "cohort; path is partial")

    barriers = _build_barriers(spans_by_rank)
    barriers = [b for b in barriers if t_start < b["t_us"] <= t_end]

    # rank bounding the finish line owns the tail segment
    last_end = {r: max(sp["ts"] + sp["dur"] for sp in s)
                for r, s in spans_by_rank.items()}
    tail_owner = max(last_end, key=lambda r: last_end[r])

    segments: List[dict] = []
    totals = {"compute": 0.0, "collective_wait": 0.0, "straggle": 0.0}
    attribution: Dict[int, float] = {}
    phase_on_path: Dict[str, float] = {}
    peer_wait_us = 0.0
    prev = t_start
    cut_points = [(b["t_us"], b) for b in barriers] + [(t_end, None)]
    for t_cut, barrier in cut_points:
        if t_cut <= prev:
            continue
        owner = barrier["bounding_rank"] if barrier else tail_owner
        acc, phase_us = _classified_window(
            spans_by_rank.get(owner, []), prev, t_cut)
        seg_len = t_cut - prev
        compute = acc[_PRIO_COMPUTE]
        collective = acc[_PRIO_COLLECTIVE] + acc[_PRIO_WAIT]
        straggle = acc[_PRIO_STRAGGLE]
        if barrier:
            # barrier skew = the bounding rank's excess over the median
            # peer: reclassify that much of its compute as straggle (the
            # amount one slow rank cost everyone waiting at the fence)
            carve = min(barrier["skew_us"], compute)
            compute -= carve
            straggle += carve
            peer_wait_us += sum(
                max(0.0, barrier["t_us"] - arr)
                for r, arr in barrier["arrivals_us"].items() if r != owner)
        totals["compute"] += compute
        totals["collective_wait"] += collective
        totals["straggle"] += straggle
        for name, us in phase_us.items():
            phase_on_path[name] = phase_on_path.get(name, 0.0) + us
        attribution[owner] = attribution.get(owner, 0.0) + seg_len
        segments.append({
            "rank": owner,
            "start_ms": round((prev - t_start) / 1e3, 3),
            "dur_ms": round(seg_len / 1e3, 3),
            "via": (f"{barrier['name']}#{barrier['occurrence']}"
                    if barrier else "finish"),
            "compute_ms": round(compute / 1e3, 3),
            "collective_wait_ms": round(collective / 1e3, 3),
            "straggle_ms": round(straggle / 1e3, 3),
            "skew_ms": round((barrier["skew_us"] if barrier else 0.0)
                             / 1e3, 3),
        })
        prev = t_cut

    bounding_rank = max(attribution, key=lambda r: attribution[r])
    denom = max(path_us, 1e-9)
    fractions = {k: round(v / denom, 4) for k, v in totals.items()}
    wait_fraction = round(
        (totals["collective_wait"] + totals["straggle"]) / denom, 4)
    jtotal_ms = (max(jt_durs.values()) / 1e3) if jt_durs else None
    top_phase = (max(phase_on_path, key=lambda n: phase_on_path[n])
                 if phase_on_path else None)

    # lease-epoch bumps ride the path as annotations (cross-rank edges
    # from the membership layer)
    epoch_bumps = []
    for rank, insts in instants_by_rank.items():
        for ev in insts:
            if ev["name"] in ("rank_lost", "rank_join"):
                epoch_bumps.append({
                    "rank": rank, "event": ev["name"],
                    "t_ms": round((ev["ts"] - t_start) / 1e3, 3),
                    "epoch": ev["args"].get("epoch")})
    epoch_bumps.sort(key=lambda e: e["t_ms"])

    return {
        "trace_id": streams[0].get("trace_id"),
        "ranks": present,
        "missing_ranks": missing,
        "partial": bool(missing
                        or any("torn" in w for w in warnings)),
        "warnings": warnings,
        "path_ms": round(path_us / 1e3, 3),
        "jtotal_ms": (round(jtotal_ms, 3)
                      if jtotal_ms is not None else None),
        "bounding_rank": bounding_rank,
        "fractions": fractions,
        "wait_fraction": wait_fraction,
        "attribution_ms": {str(r): round(us / 1e3, 3)
                           for r, us in sorted(attribution.items())},
        "top_phase": ({"name": top_phase, "rank": bounding_rank,
                       "ms": round(phase_on_path[top_phase] / 1e3, 3)}
                      if top_phase else None),
        "phase_ms": {n: round(us / 1e3, 3)
                     for n, us in sorted(phase_on_path.items(),
                                         key=lambda kv: -kv[1])},
        "barriers": [{
            "name": b["name"], "occurrence": b["occurrence"],
            "t_ms": round((b["t_us"] - t_start) / 1e3, 3),
            "bounding_rank": b["bounding_rank"],
            "skew_ms": round(b["skew_us"] / 1e3, 3),
            "arrivals_ms": {str(r): round((a - t_start) / 1e3, 3)
                            for r, a in sorted(b["arrivals_us"].items())},
        } for b in barriers],
        "peer_wait_ms": round(peer_wait_us / 1e3, 3),
        "segments": segments,
        "epoch_bumps": epoch_bumps,
        "hedge": _hedge_summary(spans_by_rank, instants_by_rank,
                                t_start, t_end),
    }


def critical_path_for_dir(timeline_dir: str,
                          trace_id: Optional[str] = None) -> dict:
    """Load span files under ``timeline_dir`` (trace-id correlated) and
    compute the critical path; degraded dict on empty/unreadable dirs."""
    streams, warnings = load_streams(timeline_dir, trace_id=trace_id)
    if not streams:
        return {"error": f"no span files ({SPAN_SUFFIX}) usable under "
                         f"{timeline_dir}",
                "warnings": warnings, "partial": True}
    return compute_critical_path(streams, warnings=warnings)


def critical_path_from_tracer(tracer, window_us=None) -> dict:
    """Path over the local rank's in-memory spans (no file round-trip)."""
    return compute_critical_path([stream_from_tracer(tracer)],
                                 window_us=window_us)


# --------------------------------------------------------------------------
# rendering
# --------------------------------------------------------------------------

def format_summary(res: dict) -> str:
    """One-line body for the ``[CRITPATH]`` log line."""
    if "error" in res:
        return f"unavailable ({res['error']})"
    f = res["fractions"]
    parts = [f"path_ms={res['path_ms']:.1f}"]
    if res.get("jtotal_ms") is not None:
        parts.append(f"jtotal_ms={res['jtotal_ms']:.1f}")
    parts.append(f"bound=rank{res['bounding_rank']}")
    parts.append(f"compute={f['compute'] * 100:.1f}%")
    parts.append(f"wait={f['collective_wait'] * 100:.1f}%")
    parts.append(f"straggle={f['straggle'] * 100:.1f}%")
    top = res.get("top_phase")
    if top:
        parts.append(f"top={top['name']}@r{top['rank']}:{top['ms']:.1f}ms")
    parts.append(f"barriers={len(res.get('barriers', []))}")
    hedge = res.get("hedge")
    if hedge and hedge.get("n_claims"):
        saved = hedge.get("saved_ms_estimate")
        parts.append(
            f"hedge_claims={hedge['n_claims']}"
            + (f" saved_ms~{saved:.1f}" if saved is not None else ""))
    if res.get("trace_id"):
        parts.append(f"trace={res['trace_id']}")
    if res.get("partial"):
        parts.append("PARTIAL")
    return " ".join(parts)


def render_report(res: dict) -> str:
    """Multi-line human report for tools_critical_path.py / postmortem."""
    lines: List[str] = []
    if "error" in res:
        lines.append(f"critical path unavailable: {res['error']}")
        for w in res.get("warnings", []):
            lines.append(f"  WARNING: {w}")
        return "\n".join(lines)
    f = res["fractions"]
    lines.append(f"critical path: {res['path_ms']:.1f} ms across "
                 f"{len(res['ranks'])} rank(s)"
                 + (" [PARTIAL]" if res.get("partial") else ""))
    if res.get("trace_id"):
        lines.append(f"  trace_id: {res['trace_id']}")
    if res.get("jtotal_ms") is not None:
        jt = res["jtotal_ms"]
        delta = (abs(res["path_ms"] - jt) / jt * 100.0) if jt else 0.0
        lines.append(f"  measured JTOTAL: {jt:.1f} ms "
                     f"(path within {delta:.1f}%)")
    lines.append(f"  bounding rank: {res['bounding_rank']}   "
                 f"compute {f['compute'] * 100:.1f}% / "
                 f"collective-wait {f['collective_wait'] * 100:.1f}% / "
                 f"straggle {f['straggle'] * 100:.1f}%")
    attr = res.get("attribution_ms", {})
    if attr:
        top = sorted(attr.items(), key=lambda kv: -kv[1])[:4]
        lines.append("  attribution: " + "  ".join(
            f"rank{r}={ms:.1f}ms" for r, ms in top))
    for b in res.get("barriers", []):
        lines.append(f"  barrier {b['name']}#{b['occurrence']} "
                     f"@{b['t_ms']:.1f}ms bound=rank{b['bounding_rank']} "
                     f"skew={b['skew_ms']:.1f}ms")
    for seg in res.get("segments", []):
        lines.append(f"  segment rank{seg['rank']} via {seg['via']}: "
                     f"{seg['dur_ms']:.1f}ms (compute "
                     f"{seg['compute_ms']:.1f} / wait "
                     f"{seg['collective_wait_ms']:.1f} / straggle "
                     f"{seg['straggle_ms']:.1f})")
    for e in res.get("epoch_bumps", []):
        lines.append(f"  epoch bump: {e['event']} rank{e['rank']} "
                     f"@{e['t_ms']:.1f}ms epoch={e['epoch']}")
    hedge = res.get("hedge")
    if hedge:
        strag = hedge.get("straggler")
        lines.append(f"  hedge: {hedge['n_claims']} claim(s)"
                     + (f", straggler=rank{strag}"
                        if strag is not None else ""))
        saved = hedge.get("saved_ms_estimate")
        if saved is not None:
            lines.append(f"  hedge shortened the path by ~{saved:.1f} ms "
                         f"({hedge.get('basis')})")
    for w in res.get("warnings", []):
        lines.append(f"  WARNING: {w}")
    return "\n".join(lines)
