"""Live read-only introspection endpoint for the resident service.

``--serve --statusz PORT`` starts a stdlib ``http.server`` thread that
answers JSON snapshots of whatever the session is doing *right now* —
current phase + open spans, lease board + membership epochs,
straggler/hedge state, breaker/SLO/queue, the counter registry, and the
last N critical paths — so an operator can ask a live fleet what it is
doing without attaching a debugger or killing it (the fleet-scope
heartbeat surface ROADMAP item 3 asks for).

Design constraints:

  * **read-only** — GET only; every handler renders a snapshot callable,
    nothing mutates session state;
  * **isolated** — a section provider that throws renders as
    ``{"error": ...}`` in place; a statusz request can never take the
    serving path down with it;
  * **pull-priced** — zero cost until someone asks: no background
    sampling thread, so the serve-path overhead is the span tagging the
    session already pays.

Routes: ``/statusz`` (all sections), ``/statusz/<section>`` (one),
``/healthz`` (readiness).  ``/healthz`` consults an optional
``readiness`` callable (the serve wiring supplies one): ``{"ok": true}``
200 while the plane can take a query, ``{"ok": false, "reason": ...}``
503 when it cannot (session closed, breaker open, heartbeat stale, fleet
draining) — so the fleet supervisor or an external LB can route on the
status code alone instead of parsing ``/statusz``.  Binds 127.0.0.1
only — this is an operator plane, not a public API.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional


class StatuszServer:
    """Serve read-only JSON snapshots from registered section callables."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 sections: Optional[Dict[str, Callable[[], object]]] = None,
                 readiness: Optional[Callable[[], object]] = None):
        self._host = host
        self._port = int(port)
        self._sections: Dict[str, Callable[[], object]] = dict(
            sections or {})
        self._readiness = readiness
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.requests_served = 0

    # ------------------------------------------------------------- sections
    def add_section(self, name: str, provider: Callable[[], object]
                    ) -> None:
        self._sections[name] = provider

    def set_readiness(self, provider: Callable[[], object]) -> None:
        """Install the ``/healthz`` readiness callable.  It returns either
        a bool or a ``{"ok": bool, "reason": ...}`` dict; ``ok=False``
        answers 503.  Without one, ``/healthz`` stays a liveness ping
        (the process answering IS the health)."""
        self._readiness = provider

    def health(self) -> tuple:
        """(status_code, body) for ``/healthz`` — testable in-process.
        A readiness provider that *raises* reads as not-ready: a plane
        that cannot even describe its health must not take traffic."""
        body = {"ok": True, "t_epoch_s": time.time()}
        if self._readiness is not None:
            try:
                verdict = self._readiness()
            except Exception as e:     # noqa: BLE001 — render, never raise
                verdict = {"ok": False,
                           "reason": f"readiness error: "
                                     f"{type(e).__name__}: {e}"}
            if isinstance(verdict, dict):
                body.update(verdict)
            else:
                body["ok"] = bool(verdict)
        return (200 if body.get("ok") else 503), body

    def _render_section(self, name: str) -> object:
        provider = self._sections.get(name)
        if provider is None:
            return {"error": f"unknown section {name!r}",
                    "sections": sorted(self._sections)}
        try:
            return provider()
        except Exception as e:     # snapshot errors render, never raise
            return {"error": f"{type(e).__name__}: {e}"}

    def snapshot(self, section: Optional[str] = None) -> dict:
        """The same payload the HTTP plane serves (testable in-process)."""
        body = {"t_epoch_s": time.time()}
        if section:
            body[section] = self._render_section(section)
        else:
            for name in sorted(self._sections):
                body[name] = self._render_section(name)
        return body

    # ------------------------------------------------------------ lifecycle
    @property
    def port(self) -> int:
        """Bound port (resolves an ephemeral port=0 after start)."""
        return self._port

    def start(self) -> int:
        if self._httpd is not None:
            return self._port
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0].rstrip("/")
                code = 200
                if path == "/healthz":
                    code, body = server.health()
                elif path == "/statusz":
                    body = server.snapshot()
                elif path.startswith("/statusz/"):
                    body = server.snapshot(path[len("/statusz/"):])
                else:
                    self.send_error(404, "try /statusz or /healthz")
                    return
                # default=str: snapshots may carry exotica (paths, enums)
                data = json.dumps(body, default=str).encode()
                server.requests_served += 1
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # quiet: stdout carries BENCH/JSON
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name=f"statusz:{self._port}", daemon=True)
        self._thread.start()
        return self._port

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # context-manager sugar for tests
    def __enter__(self) -> "StatuszServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def measurements_sections(measurements) -> Dict[str, Callable[[], object]]:
    """Standard sections derivable from a Measurements registry alone:
    current phase (open spans + ring context), and the counter/tag
    registry.  Service-level sections (leases, breaker/SLO, critpaths)
    are added by the serve wiring, which owns those objects."""
    def phase() -> dict:
        rec = getattr(measurements, "flightrec", None)
        tracer = getattr(measurements, "tracer", None)
        open_spans = {}
        if tracer is not None:
            open_spans = {name: len(stack)
                          for name, stack in tracer._open.items() if stack}
        out = {"open_spans": open_spans}
        if rec is not None:
            out["context"] = dict(rec.context)
            out["idle_s"] = round(rec.idle_s(), 3)
        return out

    def counters() -> dict:
        times = getattr(measurements, "times_us", {}) or {}
        counts = getattr(measurements, "counters", {}) or {}
        return {
            "times_us": {k: round(float(v), 1)
                         for k, v in sorted(times.items())},
            "counters": {k: int(v) for k, v in sorted(counts.items())},
        }

    return {"phase": phase, "counters": counters}
