"""Post-mortem forensics bundles: one self-contained JSON per death.

Bench rounds 3-5 died on a downed TPU tunnel and left behind nothing but
a ``backend_unavailable`` string — no stacks, no last-known phase, no
record of what the planner predicted versus what ran.  A *bundle* is the
answer: on any terminal failure, deadline expiry, breaker trip, watchdog
trip, or chaos violation, :func:`write_bundle` freezes everything a
post-mortem needs into one file —

  * identity: reason, failure class, epoch, rank/host/nodes, query_id
    (from the flight-recorder context when the serve path stamped one);
  * configuration: the JoinConfig (as a dict) + a stable fingerprint
    hash, the JoinPlan (``meta["plan"]``), the plan-vs-actual audit
    table (``meta["plan_vs_actual"]``, planner/audit.py);
  * the black box: the flight-recorder ring snapshot, the counter/timer
    registries, the tail of ``meta["events"]``, the tail of the
    heartbeat ``.metrics.jsonl`` when its path is known;
  * the substrate: python/jax versions, ``JAX_PLATFORMS``, device
    platform + count; all-thread stacks when the caller captured them
    (the watchdog always does);
  * chaos: the active injector's ``(seed, arms)`` schedule, fire
    history, and per-site stats — enough to replay the failure.

Bundles are plain JSON (no pickle — they cross machines and versions),
written atomically (tmp + rename) so a bundle that exists is complete.
``tools_postmortem.py`` renders and merges them.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Optional

BUNDLE_PREFIX = "bundle_"

_EVENTS_TAIL = 80        # most-recent meta["events"] kept in a bundle
_HEARTBEAT_TAIL = 20     # most-recent heartbeat samples kept


def _config_dict(config) -> Optional[dict]:
    if config is None:
        return None
    if dataclasses.is_dataclass(config) and not isinstance(config, type):
        return dataclasses.asdict(config)
    if isinstance(config, dict):
        return dict(config)
    return {"repr": repr(config)}


def config_fingerprint(config_dict: Optional[dict]) -> Optional[str]:
    """Stable short hash of a config dict (key-sorted JSON, sha256/16)."""
    if not config_dict:
        return None
    blob = json.dumps(config_dict, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _env_info() -> dict:
    import platform
    import sys
    info = {
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "pid": os.getpid(),
        "jax_platforms": os.environ.get("JAX_PLATFORMS"),
    }
    try:
        import jax
        info["jax"] = jax.__version__
        devs = jax.local_devices()
        info["device_count"] = len(devs)
        info["device_platform"] = devs[0].platform if devs else None
    except Exception as e:   # noqa: BLE001 — a dead backend is exactly the
        info["jax_error"] = repr(e)[:200]   # case bundles exist for
    return info


def _chaos_info(chaos=None) -> Optional[dict]:
    """``(seed, arms)`` replay record: from an explicit chaos Schedule
    (robustness/chaos.py) or, failing that, the ambient FaultInjector."""
    if chaos is not None:
        if hasattr(chaos, "to_json"):
            return chaos.to_json()
        if isinstance(chaos, dict):
            return dict(chaos)
    from tpu_radix_join.robustness import faults as _faults
    inj = _faults.active()
    if inj is None:
        return None
    return {"seed": inj.seed,
            "arms": sorted(inj._arms),
            "history": [list(h) for h in inj.history],
            "site_stats": inj.site_stats()}


def _heartbeat_tail(path: Optional[str]) -> Optional[dict]:
    if not path or not os.path.exists(path):
        return None
    try:
        from tpu_radix_join.observability.metrics import load_samples
        samples = load_samples(path)
    except OSError:
        return None
    return {"path": path, "total_samples": len(samples),
            "tail": samples[-_HEARTBEAT_TAIL:]}


def build_bundle(measurements=None, reason: str = "failure",
                 failure_class: Optional[str] = None, plan=None,
                 config=None, stacks=None, chaos=None,
                 heartbeat_path: Optional[str] = None,
                 extra: Optional[dict] = None) -> dict:
    """Assemble the bundle dict (see module docstring) without touching
    disk — :func:`write_bundle` persists it.  Every section degrades to
    None/absent instead of raising: forensics must not mask the failure
    being forensicked."""
    m = meta = None
    if measurements is not None:
        m, meta = measurements, measurements.meta
    cfg = _config_dict(config)
    if cfg is None and meta is not None and isinstance(
            meta.get("config"), dict):
        cfg = meta["config"]
    bundle: dict = {
        "bundle_version": 1,
        "reason": reason,
        "failure_class": failure_class,
        "created_epoch_s": round(time.time(), 6),
        "env": _env_info(),
        "config": cfg,
        "config_fingerprint": config_fingerprint(cfg),
        "chaos": _chaos_info(chaos),
        "stacks": stacks,
    }
    if m is not None:
        ring = m.flightrec.snapshot()
        qid = ring["context"].get("query_id")
        # trace identity joins this bundle to span files / ledger rows /
        # merged timelines of the same join across every store
        tid = ring["context"].get("trace_id") or meta.get("trace_id")
        bundle.update({
            "rank": m.node_id,
            "host": meta.get("host"),
            "nodes": m.num_nodes,
            "query_id": qid,
            "trace_id": tid,
            "critical_path": meta.get("critical_path"),
            "ring": ring,
            "counters": dict(m.counters),
            "times_us": {k: round(v, 1) for k, v in m.times_us.items()},
            "open_phases": sorted(m._starts),
            "events_tail": list(meta.get("events", []))[-_EVENTS_TAIL:],
            "plan": plan if plan is not None else meta.get("plan"),
            "plan_vs_actual": meta.get("plan_vs_actual"),
            "heartbeat": _heartbeat_tail(
                heartbeat_path or meta.get("heartbeat_path")),
        })
    else:
        bundle["plan"] = plan
        bundle["heartbeat"] = _heartbeat_tail(heartbeat_path)
    if extra:
        bundle["extra"] = dict(extra)
    return bundle


def write_bundle(out_dir: str, measurements=None, reason: str = "failure",
                 failure_class: Optional[str] = None, plan=None,
                 config=None, stacks=None, chaos=None,
                 heartbeat_path: Optional[str] = None,
                 extra: Optional[dict] = None) -> str:
    """Write one forensics bundle into ``out_dir``; returns its path.

    Atomic (tmp + rename), JSON-only, uniquely named by reason + rank +
    nanosecond timestamp.  Ticks the ``PMBUNDLE`` counter and records a
    ``bundle`` event so bundle emission itself is observable (and
    regress-gated: more bundles per round means more deaths)."""
    bundle = build_bundle(measurements=measurements, reason=reason,
                          failure_class=failure_class, plan=plan,
                          config=config, stacks=stacks, chaos=chaos,
                          heartbeat_path=heartbeat_path, extra=extra)
    os.makedirs(out_dir, exist_ok=True)
    rank = bundle.get("rank", 0) or 0
    name = f"{BUNDLE_PREFIX}{reason}_r{rank}_{time.time_ns()}.json"
    path = os.path.join(out_dir, name)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(bundle, f, indent=2, default=str)
    os.replace(tmp, path)
    if measurements is not None:
        from tpu_radix_join.performance.measurements import PMBUNDLE
        measurements.incr(PMBUNDLE)
        measurements.event("bundle", reason=reason, path=path,
                           failure_class=failure_class)
    return path


def load_bundle(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def list_bundles(dir_path: str) -> list:
    """Bundle paths under ``dir_path``, oldest first (name-ordered: the
    nanosecond timestamp in the name sorts chronologically per rank)."""
    if not os.path.isdir(dir_path):
        return []
    return [os.path.join(dir_path, n) for n in sorted(os.listdir(dir_path))
            if n.startswith(BUNDLE_PREFIX) and n.endswith(".json")]


# ------------------------------------------------------------------ rendering
def render_bundle(bundle: dict, ring_tail: int = 20,
                  stacks: bool = True) -> str:
    """Human-readable report of one bundle (tools_postmortem.py)."""
    ln = []
    add = ln.append
    add(f"== bundle: {bundle.get('reason')} "
        f"[{bundle.get('failure_class')}] ==")
    created = bundle.get("created_epoch_s")
    if created:
        add(f"created: {time.strftime('%Y-%m-%d %H:%M:%S', time.gmtime(created))}Z")
    add(f"rank: {bundle.get('rank')} host: {bundle.get('host')} "
        f"nodes: {bundle.get('nodes')}")
    if bundle.get("query_id"):
        add(f"query_id: {bundle['query_id']}")
    if bundle.get("trace_id"):
        add(f"trace_id: {bundle['trace_id']}")
    cp = bundle.get("critical_path")
    if cp and not cp.get("error"):
        from tpu_radix_join.observability.critpath import format_summary
        add(f"critical path: {format_summary(cp)}")
    env = bundle.get("env") or {}
    add("env: " + " ".join(f"{k}={v}" for k, v in sorted(env.items())
                           if v is not None))
    if bundle.get("config_fingerprint"):
        add(f"config_fingerprint: {bundle['config_fingerprint']}")
    plan = bundle.get("plan")
    if plan:
        add(f"plan: strategy={plan.get('strategy')} "
            f"predicted_ms={plan.get('predicted_ms')} "
            f"profile={plan.get('profile_name')}")
    pva = bundle.get("plan_vs_actual")
    if pva:
        add("plan-vs-actual:")
        add(f"  strategy={pva.get('strategy')} "
            f"predicted_ms={pva.get('predicted_ms')} "
            f"actual_ms={pva.get('actual_ms')} "
            f"drift_pct={pva.get('drift_pct')}")
        for row in pva.get("terms", []):
            add(f"    {row.get('term'):<12} predicted_ms="
                f"{row.get('predicted_ms')} actual_ms={row.get('actual_ms')}")
    if bundle.get("open_phases"):
        add(f"open phases at death: {bundle['open_phases']}")
    chaos = bundle.get("chaos")
    if chaos:
        add(f"chaos: seed={chaos.get('seed')} arms={chaos.get('arms')}")
    hb = bundle.get("heartbeat")
    if hb:
        add(f"heartbeat: {hb.get('total_samples')} samples at "
            f"{hb.get('path')}")
    ring = bundle.get("ring") or {}
    recs = ring.get("records", [])
    add(f"flight recorder: {ring.get('recorded', 0)} recorded, "
        f"{len(recs)} retained; last {min(ring_tail, len(recs))}:")
    for rec in recs[-ring_tail:]:
        extras = {k: v for k, v in rec.items()
                  if k not in ("t_s", "kind", "name")}
        tail = f"  {extras}" if extras else ""
        add(f"  {rec.get('t_s')}: {rec.get('kind'):<8} "
            f"{rec.get('name')}{tail}")
    events = bundle.get("events_tail") or []
    if events:
        add(f"events tail ({len(events)}):")
        for ev in events[-10:]:
            extras = {k: v for k, v in ev.items()
                      if k not in ("event", "t_s", "t_epoch_s")}
            add(f"  {ev.get('t_epoch_s')}: {ev.get('event')}"
                + (f"  {extras}" if extras else ""))
    if stacks and bundle.get("stacks"):
        add("thread stacks:")
        for label, frames in bundle["stacks"].items():
            add(f"  -- {label} --")
            for fr in frames:
                for sub in fr.split("\n"):
                    if sub:
                        add(f"    {sub}")
    if bundle.get("extra"):
        add(f"extra: {bundle['extra']}")
    return "\n".join(ln)


def merge_bundles(paths) -> dict:
    """Cross-bundle summary (the merger half of tools_postmortem.py):
    counts by reason and failure class, the time range, per-rank
    presence, and each bundle's one-line identity — the shape a fleet
    report wants before anyone opens individual bundles."""
    reasons: dict = {}
    classes: dict = {}
    ranks: dict = {}
    epochs: dict = {}
    incarnations: dict = {}
    timeline = []
    rows = []
    t_min = t_max = None
    for p in paths:
        try:
            b = load_bundle(p)
        except (OSError, ValueError) as e:
            rows.append({"path": p, "error": repr(e)[:120]})
            continue
        reasons[b.get("reason")] = reasons.get(b.get("reason"), 0) + 1
        fc = b.get("failure_class")
        classes[fc] = classes.get(fc, 0) + 1
        rank = b.get("rank")
        ranks[str(rank)] = ranks.get(str(rank), 0) + 1
        t = b.get("created_epoch_s")
        if t is not None:
            t_min = t if t_min is None else min(t_min, t)
            t_max = t if t_max is None else max(t_max, t)
        pva = b.get("plan_vs_actual") or {}
        # membership epoch: every epoch bump / hedge stamps
        # membership_epoch into the flight-recorder context, so the ring
        # carries it directly — the live context first, else the newest
        # stamped record, else the exception's own stamp in extra.  No
        # more inferring from a neighboring record's MEPOCH gauge.
        ring = b.get("ring") or {}
        mepoch = (ring.get("context") or {}).get("membership_epoch")
        if mepoch is None:
            for rec in reversed(ring.get("records") or []):
                if "membership_epoch" in rec:
                    mepoch = rec["membership_epoch"]
                    break
        if mepoch is None:
            mepoch = (b.get("extra") or {}).get("membership_epoch")
        epochs[str(mepoch)] = epochs.get(str(mepoch), 0) + 1
        # worker incarnation: fleet workers (service/fleet.py) stamp their
        # incarnation id (w<slot>i<n>) into the flight-recorder context at
        # serve start, so a crash-looping slot's bundles — one per death —
        # group into a single per-incarnation timeline instead of reading
        # as unrelated failures.  Same extraction chain as the membership
        # epoch above.
        wincarn = (ring.get("context") or {}).get("worker_incarnation")
        if wincarn is None:
            for rec in reversed(ring.get("records") or []):
                if "worker_incarnation" in rec:
                    wincarn = rec["worker_incarnation"]
                    break
        if wincarn is None:
            wincarn = (b.get("extra") or {}).get("worker_incarnation")
        incarnations[str(wincarn)] = incarnations.get(str(wincarn), 0) + 1
        # the recovery timeline: membership + recovery events from every
        # bundle's event tail, aligned on the cross-process wall clock —
        # losses and recoveries, plus the growth/hedging vocabulary
        # (admissions, hedge fence claims, regrow/hedge recoveries,
        # straggle verdicts)
        for ev in b.get("events_tail") or []:
            if ev.get("event") in ("rank_lost", "recovery", "rank_join",
                                   "hedge_claim", "regrow", "hedge",
                                   "straggle"):
                timeline.append(dict(ev, rank=rank, bundle=p))
        rows.append({"path": p, "reason": b.get("reason"),
                     "failure_class": fc, "rank": rank,
                     "query_id": b.get("query_id"),
                     "trace_id": b.get("trace_id"),
                     "critical_path": b.get("critical_path"),
                     "membership_epoch": mepoch,
                     "worker_incarnation": wincarn,
                     "strategy": pva.get("strategy")
                     or (b.get("plan") or {}).get("strategy"),
                     "drift_pct": pva.get("drift_pct"),
                     "created_epoch_s": t})
    timeline.sort(key=lambda ev: ev.get("t_epoch_s") or 0)
    return {"bundles": len(rows), "by_reason": reasons,
            "by_failure_class": classes, "by_rank": ranks,
            "by_membership_epoch": epochs,
            "by_worker_incarnation": incarnations,
            "recovery_timeline": timeline,
            "t_first": t_min, "t_last": t_max, "rows": rows}
