"""Hierarchical span tracer: cross-rank Chrome-trace timelines.

The reference's per-rank ``.perf`` files record *how long* each phase took
(Measurements.cpp:136-142) but not *when* — there is no way to align a
JMPI stall on rank 3 with the JPROC retry on rank 0 that caused it, or to
watch a multi-hour grid join in flight.  This module records the same tag
vocabulary as intervals on a wall-clock-anchored timeline and exports them
per rank in Chrome trace-event JSON (the format Perfetto / ``chrome://
tracing`` load natively), so host phases, robustness instant events
(fault/retry/checkpoint), planner decisions, and the xplane per-op device
summary all land in ONE view.

Clock discipline: each tracer pins a wall-clock epoch anchor
(``epoch_s = time.time()``) and a monotonic anchor (``time.perf_counter()``)
at the same instant.  Event timestamps are monotonic-relative microseconds
(immune to NTP steps mid-run); the epoch anchor rides the file metadata so
the merger (observability/timeline.py) can shift every rank onto one shared
clock — the alignment the reference's ``gettimeofday``-stamped timers get
implicitly from NTP and we get explicitly, with the skew visible.

Wiring: ``Measurements.attach_tracer()`` builds a tracer sharing the
registry's anchors; every ``start``/``stop`` pair then mirrors into a
complete span and every ``Measurements.event`` into an instant event —
the whole codebase (hash_join phases, grid pairs, checkpoint saves,
planner cache hits) is on the timeline without a second instrumentation
vocabulary.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Dict, List, Optional

# Perfetto track layout: one process per rank, host phases on tid 0,
# the synthetic device-op summary track (timeline.py) on tid 1.
HOST_TID = 0
DEVICE_TID = 1

SPAN_SUFFIX = ".spans.json"


def _new_trace_id() -> str:
    return os.urandom(8).hex()


class SpanTracer:
    """Per-rank span recorder; export with :meth:`save`.

    ``tags`` (e.g. the planner's strategy/engine choice) are stamped into
    every subsequently emitted event's ``args`` and into the file metadata
    — set them before the spans they should describe.
    """

    def __init__(self, rank: int = 0, trace_id: Optional[str] = None,
                 tags: Optional[dict] = None,
                 epoch_s: Optional[float] = None,
                 mono_s: Optional[float] = None):
        self.rank = int(rank)
        self.trace_id = trace_id or _new_trace_id()
        self.tags: Dict[str, object] = dict(tags or {})
        # both anchors taken at (as close as possible to) the same instant;
        # callers with an existing anchor pair (Measurements) pass theirs so
        # spans and meta["events"] share one clock
        self.epoch_s = time.time() if epoch_s is None else float(epoch_s)
        self._mono0 = (time.perf_counter() if mono_s is None
                       else float(mono_s))
        # per-name begin stacks: phases re-enter on retry (JPROC attempt 2)
        # and overlap without strict nesting (JTOTAL ⊃ JMPI ⊃ SNETCOMPL),
        # so spans are keyed, not a single stack
        self._open: Dict[str, List[tuple]] = {}
        self.events: List[dict] = []

    # ------------------------------------------------------------------ clock
    def now_us(self) -> float:
        """Microseconds since this tracer's anchors (monotonic)."""
        return (time.perf_counter() - self._mono0) * 1e6

    # ------------------------------------------------------------------- tags
    def set_tags(self, **tags) -> None:
        """Stamp tags (strategy=..., engine=...) onto future events."""
        self.tags.update(tags)

    # ------------------------------------------------------------------ spans
    def begin(self, name: str, **args) -> None:
        self._open.setdefault(name, []).append((self.now_us(), args))

    def end(self, name: str, **args) -> None:
        """Complete the innermost open span of ``name``; a stray ``end``
        with no matching ``begin`` is dropped (a registry loaded from disk
        replays stops without starts)."""
        stack = self._open.get(name)
        if not stack:
            return
        ts, begin_args = stack.pop()
        self.events.append({
            "name": name, "ph": "X", "ts": ts,
            "dur": max(0.0, self.now_us() - ts),
            "pid": self.rank, "tid": HOST_TID,
            "args": {**self.tags, **begin_args, **args},
        })

    @contextlib.contextmanager
    def span(self, name: str, **args):
        self.begin(name, **args)
        try:
            yield self
        finally:
            self.end(name)

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker (robustness events, planner decisions)."""
        self.events.append({
            "name": name, "ph": "i", "s": "p",   # process-scoped flow pip
            "ts": self.now_us(), "pid": self.rank, "tid": HOST_TID,
            "args": {**self.tags, **args},
        })

    # ----------------------------------------------------------------- export
    def _metadata_events(self) -> List[dict]:
        return [
            {"name": "process_name", "ph": "M", "pid": self.rank,
             "args": {"name": f"rank {self.rank}"}},
            {"name": "process_sort_index", "ph": "M", "pid": self.rank,
             "args": {"sort_index": self.rank}},
            {"name": "thread_name", "ph": "M", "pid": self.rank,
             "tid": HOST_TID, "args": {"name": "host phases"}},
        ]

    def to_chrome(self, shift_us: float = 0.0) -> dict:
        """Chrome trace-event JSON object; ``shift_us`` moves this rank's
        events onto a shared clock (the merger's epoch-anchor delta)."""
        events = self._metadata_events()
        for ev in self.events:
            ev = dict(ev)
            ev["ts"] = ev["ts"] + shift_us
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "trace_id": self.trace_id,
                "rank": self.rank,
                "epoch_s": self.epoch_s,
                "tags": self.tags,
                "clock": "us since rank epoch anchor (monotonic)",
            },
        }

    def save(self, out_dir: str, device_summary: Optional[dict] = None,
             filename: Optional[str] = None) -> str:
        """Write ``<rank>.spans.json``; any still-open spans are closed at
        now (a crash-path save must not lose the run's outermost span).

        ``device_summary`` (the xplane per-op breakdown from
        performance/trace.summarize_trace, i.e. ``meta["trace"]``) is
        embedded in the metadata so the merger can graft a device track
        next to this rank's host phases without re-parsing the xplane.
        """
        for name in [n for n, stack in self._open.items() if stack]:
            while self._open[name]:
                self.end(name, unclosed=True)
        doc = self.to_chrome()
        if device_summary is not None:
            doc["metadata"]["device_summary"] = device_summary
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir,
                            filename or f"{self.rank}{SPAN_SUFFIX}")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path
