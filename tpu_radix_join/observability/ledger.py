"""Cross-run telemetry ledger: the planner's long-term memory.

Every subsystem already measures itself — phase timers and counters
(performance/measurements.py), plan-vs-actual audit tables
(planner/audit.py), BENCH JSON lines (bench.py), per-query service
outcomes (service/session.py) — but each run's evidence dies with its
artifact directory.  The ledger is the append-only, schema-versioned
JSONL store that outlives runs: one row per observation, written at run
end from the live registry (main.py ``--ledger-dir``), per query by a
resident session, per bench by bench.py, and backfillable from committed
artifacts (``tools_make_report.py --emit-ledger``).

``planner/calibrate.py`` consumes these rows to re-fit the device
profile's REQUIRED_CONSTANTS and to attribute persistent PLANDRIFT to
the constant behind the drifting cost term — the continuously refreshed
profile ROADMAP item 2's layout search is blocked on.

Row shape (schema v1)::

    {"schema_version": 1, "kind": "run"|"bench"|"query"|"obs",
     "run_id": ..., "t_epoch_s": ..., **payload}

Reader discipline matches metrics.load_samples: torn lines (a killed
writer's last record) are skipped, and rows stamped with a NEWER schema
than this build understands are skipped rather than misread — an old
reader must never silently misinterpret a future field.
"""

from __future__ import annotations

import glob
import itertools
import json
import os
import socket
import time
from typing import Dict, List, Optional, Tuple

LEDGER_SCHEMA_VERSION = 1
LEDGER_BASENAME = "ledger.jsonl"

#: row kinds the fitter understands ("obs" = a pre-reduced single-constant
#: observation, the extension point for future probes)
KINDS = ("run", "bench", "query", "obs")

#: bench.py's fixed workload — BENCH rows that predate the "size" tag
#: (rounds 1..9) all measured this 16M-per-side join
BENCH_DEFAULT_SIZE = 1 << 24

_seq = itertools.count()


def default_ledger_dir() -> str:
    """Where ``--profile auto`` looks for a ledger + fitted profile when no
    ``--ledger-dir`` is given: the environment override, else the
    repo-conventional ``artifacts/ledger``."""
    return (os.environ.get("TPU_RADIX_LEDGER_DIR")
            or os.path.join("artifacts", "ledger"))


def run_fingerprint(extra: Optional[dict] = None) -> dict:
    """Identity of the software stack a row was measured under (config and
    mesh ride in the payload; jax/jaxlib versions and backend here) — a
    fit must be able to exclude rows from a different XLA."""
    fp: Dict[str, object] = {"host": socket.gethostname()}
    try:
        import jax
        fp["jax"] = jax.__version__
        fp["backend"] = jax.default_backend()
    except Exception:                      # noqa: BLE001 — best-effort only
        pass
    try:
        import jaxlib.version
        fp["jaxlib"] = jaxlib.version.__version__
    except Exception:                      # noqa: BLE001
        pass
    if extra:
        fp.update(extra)
    return fp


class Ledger:
    """Append-only JSONL ledger at ``<dir>/ledger.jsonl`` (or an explicit
    ``*.jsonl`` path).  Appends are single-write + flush, so concurrent
    writers interleave whole lines and a SIGKILL tears at most one row —
    which the tolerant reader then skips."""

    def __init__(self, dir_or_path: str):
        self.path = (dir_or_path if dir_or_path.endswith(".jsonl")
                     else os.path.join(dir_or_path, LEDGER_BASENAME))

    def append(self, kind: str, payload: dict,
               run_id: Optional[str] = None,
               t_epoch_s: Optional[float] = None) -> dict:
        if kind not in KINDS:
            raise ValueError(f"unknown ledger row kind {kind!r} "
                             f"(want one of {KINDS})")
        row = {"schema_version": LEDGER_SCHEMA_VERSION,
               "kind": kind,
               "run_id": run_id or
               f"{kind}-{os.getpid()}-{int(time.time())}-{next(_seq)}",
               "t_epoch_s": round(t_epoch_s if t_epoch_s is not None
                                  else time.time(), 3)}
        for k, v in payload.items():
            if k not in row and v is not None:
                row[k] = v
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(row, default=str) + "\n")
            f.flush()
        return row

    def rows(self, kind: Optional[str] = None) -> List[dict]:
        return load_rows(self.path, kind=kind)


def load_rows(path: str, kind: Optional[str] = None) -> List[dict]:
    """Tolerant ledger read: missing file -> [], torn lines skipped,
    rows from a newer schema skipped (never misread)."""
    if path and not path.endswith(".jsonl"):
        path = os.path.join(path, LEDGER_BASENAME)
    out: List[dict] = []
    try:
        f = open(path)
    except OSError:
        return out
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if not isinstance(row, dict):
                continue
            if int(row.get("schema_version", 1)) > LEDGER_SCHEMA_VERSION:
                continue
            if kind is not None and row.get("kind") != kind:
                continue
            out.append(row)
    return out


# --------------------------------------------------------- payload builders
def run_payload(measurements, config: Optional[dict] = None,
                workload: Optional[dict] = None,
                fingerprint: Optional[dict] = None) -> dict:
    """Distill a live Measurements registry into one ``kind="run"`` row:
    phase times, non-zero counters, the plan and its plan-vs-actual audit
    table when present, the workload geometry, and the stack fingerprint.
    The flight-recorder ring stays in forensics bundles — the ledger keeps
    reduced observations, not raw event streams."""
    m = measurements
    payload: Dict[str, object] = {
        "fingerprint": fingerprint or run_fingerprint(
            {"nodes": getattr(m, "num_nodes", 1)}),
        "times_us": {k: round(float(v), 1) for k, v in m.times_us.items()},
        "counters": {k: int(v) for k, v in m.counters.items() if v},
    }
    wl = workload or {k: m.meta[k] for k in
                      ("tuples_per_node", "global_size", "nodes")
                      if k in m.meta}
    if wl:
        payload["workload"] = wl
    for key in ("plan", "plan_vs_actual", "exchange_plan", "failure_class"):
        if m.meta.get(key) is not None:
            payload[key] = m.meta[key]
    cfg = config if config is not None else m.meta.get("config")
    if isinstance(cfg, dict):
        payload["config"] = {k: v for k, v in cfg.items()
                             if isinstance(v, (int, float, str, bool))}
        if cfg.get("repeat"):
            payload["repeat"] = int(cfg["repeat"])
    return payload


def bench_payload(doc: dict,
                  size_default: int = BENCH_DEFAULT_SIZE) -> Optional[dict]:
    """One ``kind="bench"`` row from a BENCH result dict or the runner's
    artifact wrapper (``{"parsed": {...}, "rc": N, ...}``).  Returns None
    when there is no parsed result at all (a round whose capture died
    before the JSON line)."""
    parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) else doc
    if not isinstance(parsed, dict) or "metric" not in parsed:
        return None
    payload: Dict[str, object] = {
        "metric": parsed["metric"],
        "value": float(parsed.get("value") or 0.0),
        "unit": parsed.get("unit", ""),
        "size": int(parsed.get("size") or size_default),
    }
    for k, v in parsed.items():
        if k not in payload and isinstance(v, (int, float, str, bool)):
            payload[k] = v
    if doc is not parsed and "rc" in doc:
        payload["rc"] = doc["rc"]
    return payload


def rows_from_perf_dir(d: str) -> List[Tuple[str, dict]]:
    """``(run_id, payload)`` run rows from one committed perf artifact dir
    (``<rank>.perf`` + ``<rank>.info``) — the backfill path that turns
    rounds 1..8's chip evidence into fit samples."""
    from tpu_radix_join.performance.measurements import Measurements

    out: List[Tuple[str, dict]] = []
    try:
        ranks = Measurements.load(d)
    except (OSError, ValueError):
        return out
    base = os.path.basename(d.rstrip("/"))
    for m in ranks:
        meta: dict = {}
        info_path = os.path.join(d, f"{m.node_id}.info")
        try:
            with open(info_path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            pass
        cfg = meta.get("config") or {}
        wl = {k: meta[k] for k in
              ("tuples_per_node", "global_size", "nodes") if k in meta}
        payload = run_payload(
            m, config=cfg, workload=wl or None,
            fingerprint={"host": meta.get("host", "?"),
                         "nodes": meta.get("nodes", m.num_nodes),
                         "artifact": d})
        for key in ("plan", "plan_vs_actual", "failure_class"):
            if meta.get(key) is not None:
                payload[key] = meta[key]
        out.append((f"{base}:{m.node_id}", payload))
    return out


def ingest_artifacts(base_dir: str, out_path: str,
                     bench_dir: Optional[str] = None) -> Dict[str, int]:
    """Backfill: distill committed ``BENCH_r*.json`` (under ``bench_dir``,
    default the repo root) and every ``perf_*`` dir under ``base_dir``
    (one level of nesting allowed: ``artifacts/chip_*/perf_*``) into
    ledger rows at ``out_path``.  Row timestamps are the artifacts' file
    mtimes, so backfilled provenance keeps its real age.  Returns
    ``{"bench": n, "run": n}``."""
    led = Ledger(out_path)
    counts = {"bench": 0, "run": 0}
    bench_dir = bench_dir or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        payload = bench_payload(doc)
        if payload is None:
            continue
        stem = os.path.splitext(os.path.basename(path))[0]
        led.append("bench", payload, run_id=stem,
                   t_epoch_s=os.path.getmtime(path))
        counts["bench"] += 1
    perf_dirs = sorted(glob.glob(os.path.join(base_dir, "perf_*")))
    perf_dirs += sorted(glob.glob(os.path.join(base_dir, "*", "perf_*")))
    for d in perf_dirs:
        if not os.path.isdir(d):
            continue
        for run_id, payload in rows_from_perf_dir(d):
            led.append("run", payload, run_id=run_id,
                       t_epoch_s=os.path.getmtime(d))
            counts["run"] += 1
    return counts
