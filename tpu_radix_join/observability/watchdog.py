"""Hang watchdog: phase-progress monitor over the flight recorder.

The downed-tunnel failure mode (ROADMAP "Bench trajectory" rounds 3-5)
is a collective that never completes: the host thread blocks inside a
dispatch, no exception fires, and the run stalls silently until someone
kills it by hand.  This monitor converts that into a *classified*
``backend_unavailable`` outcome with forensics:

  * **progress signal** — the Measurements flight recorder timestamps
    every begin/end/incr/event; a phase timer left open
    (``m._starts`` non-empty) while the ring goes quiet for
    ``timeout_s`` means the pipeline stopped making progress;
  * **evidence first** — on a trip the watchdog dumps every live
    thread's stack and (when a forensics dir is known) writes a
    post-mortem bundle BEFORE attempting the kill, so even a thread
    that never reaches a cancel point leaves a black box behind;
  * **kill path** — the engine's cooperative ``cancel`` hook
    (operators/hash_join.py ``_check_cancel``): the watchdog rebinds it
    to raise :class:`HangDetected` at the next phase boundary / stall
    poll.  Rebinding over a deadline's hook is deliberate — once the
    hang is established, the hang verdict outranks the budget clock.

The watchdog is a daemon thread; ``stop()`` (or the context manager
exit) joins it.  One trip per instance: after firing it only waits for
``stop``.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from tpu_radix_join.observability.flightrec import dump_all_stacks

#: mirrors robustness.retry.BACKEND_UNAVAILABLE without importing the
#: robustness package from the observability layer (kept dependency-free
#: so flightrec/watchdog can be wired into Measurements itself)
BACKEND_UNAVAILABLE = "backend_unavailable"

DEFAULT_TIMEOUT_S = 30.0


class HangDetected(RuntimeError):
    """A watched run made no recorded progress for the timeout window."""

    failure_class = BACKEND_UNAVAILABLE

    def __init__(self, idle_s: float, open_phases, bundle: Optional[str]):
        phases = sorted(open_phases)
        super().__init__(
            f"watchdog: no progress for {idle_s:.1f}s with open phase(s) "
            f"{phases}; classified {BACKEND_UNAVAILABLE}"
            + (f"; bundle at {bundle}" if bundle else ""))
        self.idle_s = idle_s
        self.open_phases = phases
        self.bundle = bundle


class Watchdog:
    """Monitor one Measurements registry for stalled progress.

    ``kill(exc)`` is invoked once on trip with the :class:`HangDetected`
    instance; use :func:`engine_killer` to target a HashJoin's ``cancel``
    hook.  ``bundle_kw`` is forwarded to postmortem.write_bundle (plan,
    config, chaos schedule, ...) so the bundle written at trip time is as
    complete as the terminal-failure one.
    """

    def __init__(self, measurements, timeout_s: float = DEFAULT_TIMEOUT_S,
                 kill: Optional[Callable] = None,
                 bundle_dir: Optional[str] = None,
                 poll_s: Optional[float] = None,
                 membership=None,
                 **bundle_kw):
        self.measurements = measurements
        self.timeout_s = float(timeout_s)
        self.kill = kill
        self.bundle_dir = bundle_dir
        #: duck-typed membership view (robustness/membership.py — the
        #: observability layer stays import-free of robustness): an object
        #: with ``suspect() -> Optional[Exception]``.  On a trip the
        #: watchdog asks it FIRST — a stalled collective plus a lapsed
        #: lease is a dead peer (``rank_lost``, recoverable), not a downed
        #: backend (``backend_unavailable``, terminal).
        self.membership = membership
        self.bundle_kw = bundle_kw
        # poll fast enough that a trip lands well inside one timeout
        # window even for sub-second test timeouts
        self.poll_s = poll_s if poll_s is not None \
            else max(0.01, min(1.0, self.timeout_s / 5.0))
        self.tripped = False
        self.exc: Optional[Exception] = None   # HangDetected or the
                                               # membership view's RankLost
        self.bundle_path: Optional[str] = None
        self.stacks = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="join-watchdog", daemon=True)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Watchdog":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -------------------------------------------------------------- monitor
    def _run(self) -> None:
        m = self.measurements
        while not self._stop.wait(self.poll_s):
            # progress = something recorded recently OR nothing in flight
            # (an idle session between queries is not a hang)
            if not m._starts:
                continue
            idle = m.flightrec.idle_s()
            if idle >= self.timeout_s:
                self._trip(idle)
                return

    def _suspect(self):
        """Stall triage: ask the membership view whether a lapsed lease
        explains the stall.  Returns the exception to deliver (``None``
        means no membership / all peers live — keep the hang verdict)."""
        if self.membership is None:
            return None
        try:
            return self.membership.suspect()
        except Exception as e:   # noqa: BLE001 — triage must not mask
            self.measurements.event("membership_suspect_error",
                                    error=repr(e)[:200])
            return None

    def _trip(self, idle_s: float) -> None:
        m = self.measurements
        # one-shot trip on the only watchdog thread; readers
        # synchronize via stop()'s join before touching these
        self.tripped = True  # lint: unguarded-ok(single trip; read after join)
        open_phases = list(m._starts)
        self.stacks = dump_all_stacks()  # lint: unguarded-ok(single trip; read after join)
        from tpu_radix_join.performance.measurements import WDOGTRIP
        # "suspect rank, check leases, fence" before "kill self": a dead
        # peer's stall is recoverable and must not be booked as a
        # watchdog death (the chaos soak asserts WDOGTRIP==0 for
        # recovered runs)
        rank_exc = self._suspect()
        cls = getattr(rank_exc, "failure_class", BACKEND_UNAVAILABLE)
        reason = "rank_lost" if rank_exc is not None else "watchdog_trip"
        if rank_exc is None:
            m.incr(WDOGTRIP)
        m.event("watchdog_trip", idle_s=round(idle_s, 3),
                open_phases=sorted(open_phases),
                failure_class=cls)
        if self.bundle_dir:
            try:
                from tpu_radix_join.observability.postmortem import \
                    write_bundle
                self.bundle_path = write_bundle(  # lint: unguarded-ok(single trip; read after join)
                    self.bundle_dir, measurements=m,
                    reason=reason,
                    failure_class=cls,
                    stacks=self.stacks,
                    extra={"idle_s": round(idle_s, 3),
                           "open_phases": sorted(open_phases)},
                    **self.bundle_kw)
            except Exception as e:   # noqa: BLE001 — forensics must not
                m.event("bundle_error", error=repr(e)[:200])  # mask the hang
        if rank_exc is not None:
            rank_exc.bundle = self.bundle_path
            self.exc = rank_exc  # lint: unguarded-ok(single trip; read after join)
        else:
            self.exc = HangDetected(  # lint: unguarded-ok(single trip; read after join)
                idle_s, open_phases, self.bundle_path)
        if self.kill is not None:
            try:
                self.kill(self.exc)
            except Exception as e:   # noqa: BLE001
                m.event("watchdog_kill_error", error=repr(e)[:200])


def engine_killer(engine) -> Callable:
    """Kill-path factory for a HashJoin-like engine: rebinds the
    cooperative ``cancel`` hook so the hung thread raises the watchdog's
    exception at its next ``_check_cancel`` (phase boundary or stall
    poll)."""

    def _kill(exc: Exception) -> None:
        def _raise(phase: str, _exc=exc):
            raise _exc
        engine.cancel = _raise

    return _kill
