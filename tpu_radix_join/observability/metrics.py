"""Live metrics sampler: a background heartbeat for in-flight joins.

The reference prints its ``/proc/self/status`` memory probe once, after the
join (Measurements.cpp:825-851); a multi-hour out-of-core grid run here is a
black box until it exits.  This sampler writes one JSON line per tick to
``<rank>.metrics.jsonl`` — host RSS/VmSize, per-device HBM ``bytes_in_use``,
and a snapshot of the counter registry (GRIDPAIRS, CKPTSAVE, RETRYN, ...) —
so progress and memory growth are watchable live (``tail -f``) and
post-mortem-able (the last line is the state at death).

Discipline: the sampler is a daemon thread, samples immediately on start
(short runs still get >= 1 line), never raises into the join (a failed
sample records its error and carries on), and flushes every line (a
SIGKILL loses at most the current tick).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

METRICS_SUFFIX = ".metrics.jsonl"

#: rotation defaults: a long-lived serve session must not grow its
#: heartbeat file unboundedly — at the cap the live file becomes
#: ``<path>.1`` (older rotations shift to .2, .3, ... and the oldest
#: beyond ``keep`` is dropped) and sampling continues into a fresh file
DEFAULT_ROTATE_BYTES = 16 << 20
DEFAULT_ROTATE_KEEP = 3


def host_memory() -> Dict[str, int]:
    """VmSize/VmRSS in bytes from /proc (empty off-Linux)."""
    out: Dict[str, int] = {}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith(("VmSize:", "VmRSS:")):
                    k, v = line.split(":", 1)
                    out[k] = int(v.split()[0]) * 1024
    except OSError:
        pass
    return out


def device_memory() -> Dict[str, int]:
    """Per-device ``bytes_in_use`` where the backend exposes memory_stats
    (TPU/GPU do; the CPU backend returns nothing)."""
    out: Dict[str, int] = {}
    import jax
    for i, dev in enumerate(jax.local_devices()):
        stats = getattr(dev, "memory_stats", lambda: None)()
        if stats and "bytes_in_use" in stats:
            out[f"device{i}_bytes_in_use"] = int(stats["bytes_in_use"])
    return out


class MetricsSampler:
    """Append-only JSONL heartbeat; ``start()``/``stop()`` or use as a
    context manager.  ``measurements`` (optional) contributes counter and
    timer snapshots plus the epoch anchor so samples align with the span
    timeline and ``meta["events"]``."""

    def __init__(self, path: str, interval_s: float = 1.0,
                 measurements=None, extra=None,
                 rotate_bytes: int = DEFAULT_ROTATE_BYTES,
                 rotate_keep: int = DEFAULT_ROTATE_KEEP):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if extra is not None and not callable(extra):
            raise TypeError("extra must be a zero-arg callable or None")
        if rotate_bytes <= 0 or rotate_keep < 1:
            raise ValueError("rotate_bytes must be > 0 and rotate_keep >= 1")
        self.path = path
        self.interval_s = float(interval_s)
        self.rotate_bytes = int(rotate_bytes)
        self.rotate_keep = int(rotate_keep)
        self.rotations = 0
        self.measurements = measurements
        #: zero-arg provider merged into every tick — the serve loop
        #: passes the session's SLO/breaker snapshot so ``tail -f`` shows
        #: live percentiles next to the counter registry
        self.extra = extra
        self.samples_written = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._file = None
        # sample() runs on the daemon tick AND on the main thread
        # (start's first line, stop's final line — which races a
        # straggler tick if the 5s join times out); reentrant so
        # _rotate can re-enter from inside a locked sample()
        self._lock = threading.RLock()
        m = measurements
        self._epoch0 = (float(m.meta["epoch_s"])
                        if m is not None and "epoch_s" in m.meta
                        else time.time())
        self._mono0 = time.perf_counter()

    # --------------------------------------------------------------- sampling
    def _record(self) -> dict:
        rel_s = time.perf_counter() - self._mono0
        rec: dict = {
            "t_epoch_s": round(self._epoch0 + rel_s, 6),
            "t_rel_s": round(rel_s, 6),
        }
        try:
            rec["host"] = host_memory()
            rec["devices"] = device_memory()
            m = self.measurements
            if m is not None:
                # plain dict() snapshots under the GIL; values are scalars
                rec["counters"] = dict(m.counters)
                rec["times_us"] = {k: round(v, 1)
                                   for k, v in m.times_us.items()}
                rec["open_phases"] = sorted(m._starts)
                # explicit exchange block: the cumulative WIREBYTES counter
                # only lands after a join completes, so mid-join ticks fall
                # back to the resolved plan's static geometry
                # (meta["exchange_plan"], stamped at sizing time) — wire
                # regressions stay visible live, not only in the summary
                c = rec["counters"]
                xp = m.meta.get("exchange_plan") or {}
                if c.get("WIREBYTES") or xp:
                    rec["exchange"] = {
                        "wirebytes": int(c.get("WIREBYTES", 0)),
                        "pack_ratio_pct": c.get(
                            "PACKRATIO", xp.get("pack_ratio_pct")),
                        "stages": c.get("XSTAGES", xp.get("stages")),
                        "planned_wire_bytes": xp.get("wire_bytes"),
                    }
            if self.extra is not None:
                rec.update(self.extra())
        except Exception as e:     # a sampler tick must never kill the join
            rec["error"] = repr(e)
        return rec

    def sample(self) -> dict:
        """Take and persist one sample (also called by the thread loop)."""
        rec = self._record()
        with self._lock:
            f = self._file
            if f is not None:
                f.write(json.dumps(rec) + "\n")
                f.flush()
                self.samples_written += 1
                try:
                    if f.tell() >= self.rotate_bytes:
                        self._rotate()
                except Exception:   # rotation must never kill the join
                    pass
        return rec

    def _rotate(self) -> None:
        """Size-cap rotation: live file -> .1, .k -> .(k+1), the rotation
        past ``rotate_keep`` dropped; sampling continues into a fresh live
        file.  tail -f keeps following the live path (the fd reopens)."""
        with self._lock:
            f, self._file = self._file, None
            if f is not None:
                f.close()
            oldest = f"{self.path}.{self.rotate_keep}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for k in range(self.rotate_keep - 1, 0, -1):
                src = f"{self.path}.{k}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{k + 1}")
            if os.path.exists(self.path):
                os.replace(self.path, f"{self.path}.1")
            self._file = open(self.path, "a")
            self.rotations += 1

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "MetricsSampler":
        if self._thread is not None:
            return self
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._file = open(self.path, "a")
        self.sample()                       # >= 1 line however short the run
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="metrics-sampler")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:
                pass                        # see class docstring

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        try:
            self.sample()                   # final state at shutdown
        finally:
            # under the lock: a straggler tick (join timed out above)
            # must not write into a closing fd
            with self._lock:
                f, self._file = self._file, None
                if f is not None:
                    f.close()

    def __enter__(self) -> "MetricsSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def load_samples(path: str, include_rotated: bool = False) -> list:
    """Read a ``.metrics.jsonl`` back; unparseable lines (torn final write
    of a killed run) are skipped.  ``include_rotated`` prepends the
    size-cap rotations (``<path>.N`` .. ``<path>.1``) oldest-first, so the
    result stays chronological across the cap."""
    paths = [path]
    if include_rotated:
        k = 1
        older = []
        while os.path.exists(f"{path}.{k}"):
            older.append(f"{path}.{k}")
            k += 1
        paths = list(reversed(older)) + paths
    out = []
    for p in paths:
        if p == path:
            f = open(p)        # a missing live file stays an error
        else:
            try:
                f = open(p)
            except OSError:
                continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    return out
