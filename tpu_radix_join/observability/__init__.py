"""Unified observability layer: span timelines, live metrics, regression gate.

Three pillars over the ``performance`` registry (ISSUE 3):

  * :mod:`spans` — hierarchical cross-rank span tracer; every
    ``Measurements.start/stop`` mirrors into a Chrome-trace span, every
    ``Measurements.event`` into an instant event; per-rank export.
  * :mod:`metrics` — opt-in background heartbeat (``--metrics-interval``)
    sampling host RSS, device HBM, and the counter registry to JSONL.
  * :mod:`regress` — baseline-vs-fresh per-tag comparison behind
    ``tools_check_regress.py`` and bench.py's ``--check-regress``.

Merging per-rank span files onto one aligned clock lives in
:mod:`timeline` (driven by ``tools_make_report.py --emit-timeline``).
"""

from tpu_radix_join.observability.metrics import MetricsSampler, load_samples
from tpu_radix_join.observability.regress import (check_files, check_result,
                                                  compare_tags, extract_tags,
                                                  format_table,
                                                  parse_tag_thresholds)
from tpu_radix_join.observability.spans import SpanTracer
from tpu_radix_join.observability.timeline import (find_span_files,
                                                   merge_timeline)

__all__ = [
    "MetricsSampler", "SpanTracer", "check_files", "check_result",
    "compare_tags", "extract_tags", "find_span_files", "format_table",
    "load_samples", "merge_timeline", "parse_tag_thresholds",
]
