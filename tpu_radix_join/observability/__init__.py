"""Unified observability layer: span timelines, live metrics, regression gate.

Three pillars over the ``performance`` registry (ISSUE 3):

  * :mod:`spans` — hierarchical cross-rank span tracer; every
    ``Measurements.start/stop`` mirrors into a Chrome-trace span, every
    ``Measurements.event`` into an instant event; per-rank export.
  * :mod:`metrics` — opt-in background heartbeat (``--metrics-interval``)
    sampling host RSS, device HBM, and the counter registry to JSONL.
  * :mod:`regress` — baseline-vs-fresh per-tag comparison behind
    ``tools_check_regress.py`` and bench.py's ``--check-regress``.

Merging per-rank span files onto one aligned clock lives in
:mod:`timeline` (driven by ``tools_make_report.py --emit-timeline``).

The cross-run memory layer (ISSUE 9) adds two:

  * :mod:`ledger` — append-only schema-versioned JSONL store of per-run
    observations (phase spans, counters, plan-vs-actual tables, bench
    lines, query outcomes, stack fingerprints), written at run end and
    backfillable from committed artifacts; feeds the profile
    auto-calibration loop in ``planner/calibrate.py``;
  * :mod:`compilemon` — jax.monitoring listener mirroring every backend
    compile into the NCOMPILE/COMPILEMS counters (recompile-storm canary
    for ``--serve``).

The always-on black-box layer (ISSUE 8) adds three more:

  * :mod:`flightrec` — bounded ring of recent spans/counter deltas/events
    wired into every Measurements registry with no opt-in flag;
  * :mod:`watchdog` — phase-progress monitor that converts a hung
    collective into a classified ``backend_unavailable`` outcome through
    the engine's cancel hook, dumping stacks + ring on the way;
  * :mod:`postmortem` — self-contained forensics bundles on any terminal
    failure, rendered/merged by ``tools_postmortem.py``.

The attribution layer (ISSUE 18) adds two more:

  * :mod:`critpath` — cross-rank critical-path reconstruction over
    exported span streams: which rank's which phase bounded the wall
    clock, decomposed into compute / collective-wait / straggle, with
    hedge-claim shortening estimates (``[CRITPATH]`` driver line,
    ``tools_critical_path.py``, the ``--plan explain`` measured column);
  * :mod:`statusz` — read-only live JSON introspection endpoint for the
    resident service (``--serve --statusz PORT``).
"""

from tpu_radix_join.observability.compilemon import (install_compile_monitor,
                                                     uninstall_compile_monitor)
from tpu_radix_join.observability.critpath import (compute_critical_path,
                                                   critical_path_for_dir,
                                                   critical_path_from_tracer,
                                                   format_summary,
                                                   render_report)
from tpu_radix_join.observability.flightrec import (FlightRecorder,
                                                    dump_all_stacks)
from tpu_radix_join.observability.ledger import (Ledger, bench_payload,
                                                 default_ledger_dir,
                                                 ingest_artifacts, load_rows,
                                                 run_payload)
from tpu_radix_join.observability.metrics import MetricsSampler, load_samples
from tpu_radix_join.observability.postmortem import (build_bundle,
                                                     list_bundles,
                                                     load_bundle,
                                                     merge_bundles,
                                                     render_bundle,
                                                     write_bundle)
from tpu_radix_join.observability.regress import (check_files, check_result,
                                                  compare_tags, extract_tags,
                                                  format_table,
                                                  parse_tag_thresholds)
from tpu_radix_join.observability.spans import SpanTracer
from tpu_radix_join.observability.statusz import (StatuszServer,
                                                  measurements_sections)
from tpu_radix_join.observability.timeline import (find_span_files,
                                                   merge_timeline)
from tpu_radix_join.observability.watchdog import (HangDetected, Watchdog,
                                                   engine_killer)

__all__ = [
    "FlightRecorder", "HangDetected", "Ledger", "MetricsSampler",
    "SpanTracer", "StatuszServer", "Watchdog", "bench_payload",
    "build_bundle", "check_files", "check_result", "compare_tags",
    "compute_critical_path", "critical_path_for_dir",
    "critical_path_from_tracer", "default_ledger_dir", "dump_all_stacks",
    "engine_killer", "extract_tags", "find_span_files", "format_summary",
    "format_table", "ingest_artifacts", "install_compile_monitor",
    "list_bundles", "load_bundle", "load_rows", "load_samples",
    "measurements_sections", "merge_bundles", "merge_timeline",
    "parse_tag_thresholds", "render_bundle", "render_report",
    "run_payload", "uninstall_compile_monitor", "write_bundle",
]
