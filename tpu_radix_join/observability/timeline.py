"""Merge per-rank span files into one aligned Chrome-trace timeline.

Each rank's ``<rank>.spans.json`` (observability/spans.py) carries event
timestamps relative to that rank's own wall-clock epoch anchor.  The merge
shifts every rank onto the earliest anchor's clock — host phase spans,
robustness instant events, and the grafted device-op track from any rank
then share one timeline a single Perfetto load can scrub across ranks
(the cross-rank view the reference's per-rank ``.perf`` scalars never had).

Device track: when a rank's span file embeds an xplane per-op summary
(``--trace`` runs: performance/trace.summarize_trace via
``meta["trace"]``), its ops are laid out as a synthetic sequential track
(tid 1) under that rank — total durations are real, op order and start
offsets are a summary layout, which each event's ``args`` say out loud.
Without embedded summaries the merger scans the input dir for raw
``*.xplane.pb`` artifacts as a fallback.
"""

from __future__ import annotations

import glob
import json
import os
from typing import List, Optional, Tuple

from tpu_radix_join.observability.spans import (DEVICE_TID, SPAN_SUFFIX)

# cap the synthetic device track: a full xplane op table can run to
# thousands of rows, and the graft is a summary view, not a dump
DEVICE_TRACK_MAX_OPS = 64


def find_span_files(timeline_dir: str) -> List[str]:
    return sorted(
        glob.glob(os.path.join(timeline_dir, "**", f"*{SPAN_SUFFIX}"),
                  recursive=True))


def _load(path: str) -> Tuple[Optional[dict], Optional[str]]:
    """Read one span file; returns (doc, None) or (None, skip-reason).
    The reason travels into the merge metadata and warnings so a partial
    merge names *why* each file was dropped, not just that it was."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        return None, f"unreadable ({e.__class__.__name__}: {e})"
    except ValueError as e:
        return None, f"malformed JSON (torn write? {e})"
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return None, "not a span file (no traceEvents object)"
    return doc, None


def _device_track_events(rank: int, summary: dict, start_us: float,
                         source: str) -> List[dict]:
    """Synthetic sequential layout of a per-op device summary."""
    events = [{
        "name": "thread_name", "ph": "M", "pid": rank, "tid": DEVICE_TID,
        "args": {"name": f"device ops (summary: {summary.get('plane', '?')})"},
    }]
    t = start_us
    ops = sorted(summary.get("ops", {}).items(),
                 key=lambda kv: -kv[1]["us"])
    for name, v in ops[:DEVICE_TRACK_MAX_OPS]:
        events.append({
            "name": name, "ph": "X", "ts": t, "dur": max(0.0, v["us"]),
            "pid": rank, "tid": DEVICE_TID,
            "args": {"count": v.get("count", 1), "source": source,
                     "layout": "sequential summary (durations real, "
                               "offsets synthetic)"},
        })
        t += max(0.0, v["us"])
    if len(ops) > DEVICE_TRACK_MAX_OPS:
        rest = sum(v["us"] for _, v in ops[DEVICE_TRACK_MAX_OPS:])
        events.append({
            "name": f"... {len(ops) - DEVICE_TRACK_MAX_OPS} more ops",
            "ph": "X", "ts": t, "dur": max(0.0, rest),
            "pid": rank, "tid": DEVICE_TID,
            "args": {"source": source, "layout": "tail aggregate"},
        })
    return events


def merge_timeline(timeline_dir: str, out_path: Optional[str] = None,
                   trace_dir: Optional[str] = None) -> Optional[dict]:
    """Merge every ``*.spans.json`` under ``timeline_dir``.

    Returns the merged Chrome-trace object (written to ``out_path`` when
    given), or None when the directory holds no span files.  ``trace_dir``
    (default: ``timeline_dir`` itself) is scanned for xplane artifacts only
    for ranks whose span files embed no device summary.

    Partial-tolerant by design: a rank killed mid-run (watchdog, SIGKILL)
    leaves a truncated or absent span file, and the surviving ranks'
    timeline is exactly what the post-mortem needs.  Unreadable files are
    skipped but *named* (``metadata["corrupt_files"]``), and ranks absent
    from a world whose size the tracer tags declare (``tags.nodes``) are
    listed in ``metadata["missing_ranks"]`` so the merge says out loud
    that it is partial instead of silently narrowing the world.
    """
    docs: List[Tuple[str, dict]] = []
    corrupt: List[str] = []
    corrupt_reasons: List[dict] = []
    for path in find_span_files(timeline_dir):
        doc, reason = _load(path)
        if doc is not None:
            docs.append((path, doc))
        else:
            corrupt.append(os.path.basename(path))
            corrupt_reasons.append({"file": os.path.basename(path),
                                    "reason": reason})
    if not docs:
        return None

    anchors = []
    for path, doc in docs:
        md = doc.get("metadata", {})
        anchors.append(float(md.get("epoch_s", 0.0)))
    t0 = min(anchors)

    merged: List[dict] = []
    ranks = {}
    any_device_summary = False
    min_host_ts = {}
    for (path, doc), epoch_s in zip(docs, anchors):
        md = doc.get("metadata", {})
        rank = int(md.get("rank", 0))
        shift_us = (epoch_s - t0) * 1e6
        ranks[rank] = {
            "file": os.path.basename(path),
            "trace_id": md.get("trace_id"),
            "epoch_s": epoch_s,
            "clock_shift_us": round(shift_us, 3),
            "tags": md.get("tags", {}),
        }
        for ev in doc["traceEvents"]:
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + shift_us
                key = ev.get("pid", rank)
                if ev.get("ph") == "X":
                    min_host_ts[key] = min(min_host_ts.get(key, ev["ts"]),
                                           ev["ts"])
            merged.append(ev)
        summary = md.get("device_summary")
        if summary:
            any_device_summary = True
            merged.extend(_device_track_events(
                rank, summary, min_host_ts.get(rank, shift_us),
                source=f"{os.path.basename(path)}:metadata.device_summary"))

    if not any_device_summary:
        # fallback: raw xplane artifacts next to the span files (a --trace
        # run whose spans predate the embedded-summary save path)
        from tpu_radix_join.performance.trace import summarize_trace
        scan = trace_dir or timeline_dir
        try:
            summary = summarize_trace(scan)
        except Exception:
            summary = None
        if summary:
            rank0 = min(ranks)
            merged.extend(_device_track_events(
                rank0, summary, min_host_ts.get(rank0, 0.0),
                source=f"xplane scan of {scan}"))

    # expected world size: the largest ``nodes`` tag any rank declared
    # (Measurements.attach_tracer stamps it); 0 when no rank carried one
    expected = 0
    for info in ranks.values():
        try:
            expected = max(expected, int(info["tags"].get("nodes", 0)))
        except (TypeError, ValueError):
            pass
    missing = sorted(set(range(expected)) - set(ranks))
    doc = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "metadata": {
            "t0_epoch_s": t0,
            "ranks": {str(r): info for r, info in sorted(ranks.items())},
            "clock": "us since earliest rank epoch anchor",
            "expected_ranks": expected or len(ranks),
            "missing_ranks": missing,
            "corrupt_files": corrupt,
            "corrupt_file_reasons": corrupt_reasons,
            "partial": bool(missing or corrupt),
        },
    }
    if out_path:
        os.makedirs(os.path.dirname(os.path.abspath(out_path)),
                    exist_ok=True)
        tmp = f"{out_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, out_path)
    return doc
