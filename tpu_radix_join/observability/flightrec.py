"""Always-on flight recorder: a bounded ring of recent telemetry.

The black-box layer under every mode (one-shot, ``--serve``, bench, chaos):
a fixed-capacity ``collections.deque`` of small dicts mirroring the
Measurements registry's activity — phase begin/end pairs, counter deltas,
instant events — with no opt-in flag and no I/O on the hot path.  When a
run dies (hang, deadline, breaker trip, chaos violation) the ring is the
last ~N things the process did, and postmortem.write_bundle freezes it
into the forensics bundle; while a run is alive, ``idle_s()`` is the
watchdog's progress signal (time since the registry last recorded
anything — a hung collective stops the clock, a busy phase keeps ticking).

Overhead discipline: one deque append per record (deque handles eviction
in C), one dict build, no locks on the writer path (appends on a bounded
deque are atomic under the GIL; the watchdog/bundle readers tolerate a
torn-by-one snapshot).  Measured <2% on the 1M x 1M host-mesh reference
join (PERF_NOTES round 9).

Context stamping (``set_context`` / ``clear_context``) attaches ambient
keys — the serve path's ``query_id`` — to every record made while set, so
per-query slices of the ring are filterable after the fact.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 512


class FlightRecorder:
    """Bounded ring of recent telemetry records.

    Each record is ``{"t_s": <epoch seconds>, "kind": ..., "name": ...}``
    plus the active context keys and any per-record data.  Kinds in use:
    ``begin`` / ``end`` (phase timers), ``incr`` (counter deltas),
    ``gauge`` (counter assignments), ``event`` (instant events),
    ``span`` / ``span_end`` (timeline-only spans).
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 epoch_s: Optional[float] = None,
                 mono_s: Optional[float] = None):
        # paired clock anchors, same discipline as Measurements/SpanTracer:
        # perf_counter timestamps are converted to epoch seconds on record
        # so ring contents align with heartbeat samples and merged timelines
        self._mono0 = time.perf_counter() if mono_s is None else mono_s
        self._epoch0 = time.time() if epoch_s is None else epoch_s
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._n_recorded = 0
        self._context: Dict[str, object] = {}
        # monotonic instant of the most recent record — the watchdog's
        # progress signal.  Seeded at construction so idle_s() is sane
        # before the first record.
        self.last_record_mono = self._mono0

    # ------------------------------------------------------------- context
    def set_context(self, **kv) -> None:
        """Stamp ambient keys (e.g. ``query_id``) onto every future record.
        Replaces per-key; other context keys are preserved."""
        # rebuild instead of mutating in place: writers read self._context
        # without a lock, and a rebound dict is an atomic swap
        ctx = dict(self._context)
        ctx.update(kv)
        self._context = ctx

    def clear_context(self, *keys) -> None:
        """Drop the named context keys (all of them when called bare)."""
        if not keys:
            self._context = {}
            return
        ctx = {k: v for k, v in self._context.items() if k not in keys}
        self._context = ctx

    @property
    def context(self) -> Dict[str, object]:
        return dict(self._context)

    # -------------------------------------------------------------- writer
    def record(self, kind: str, name: str, **data) -> None:
        now = time.perf_counter()
        rec = {"t_s": round(self._epoch0 + (now - self._mono0), 6),
               "kind": kind, "name": name}
        if self._context:
            rec.update(self._context)
        if data:
            rec.update(data)
        self._ring.append(rec)
        self._n_recorded += 1
        self.last_record_mono = now

    # ------------------------------------------------------------- readers
    def idle_s(self) -> float:
        """Seconds since the last record — the watchdog progress signal."""
        return time.perf_counter() - self.last_record_mono

    def __len__(self) -> int:
        return len(self._ring)

    def records(self) -> List[dict]:
        """Copy of the ring, oldest first."""
        return list(self._ring)

    def snapshot(self) -> dict:
        """Self-contained dump for bundles/heartbeats: capacity, total
        records ever made (evicted ones included in the count), the active
        context, and the surviving records oldest-first."""
        return {"capacity": self.capacity,
                "recorded": self._n_recorded,
                "context": dict(self._context),
                "records": list(self._ring)}


def dump_all_stacks() -> Dict[str, List[str]]:
    """Formatted stacks of every live thread, keyed ``"name (tid)"`` —
    the bundle's answer to "where was everyone when it died".  Uses
    ``sys._current_frames``; safe to call from any thread."""
    import sys
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, 'unknown')} ({tid})"
        out[label] = [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)]
    return out
