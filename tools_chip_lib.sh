# Shared machinery for the chip-gated task runners (sourced, not executed):
# tunnel probe, bounded wait, and the retrying .done-marker task wrapper.
# Callers set OUT (artifact dir) before sourcing; MAX_ATTEMPTS may be
# overridden after.  NOTE: a bash script that is already RUNNING reads its
# file incrementally — deploy edits to the runner scripts with `mv` (atomic
# rename keeps the running process on the old inode), never in-place.
export PYTHONPATH=/root/repo${PYTHONPATH:+:$PYTHONPATH}
mkdir -p "$OUT"
MAX_ATTEMPTS=${MAX_ATTEMPTS:-6}

probe() { timeout 60 python -c "import jax; print(jax.devices()[0])" >/dev/null 2>&1; }

wait_tunnel() {
  for i in $(seq 1 400); do
    if probe; then return 0; fi
    echo "$(date -u +%H:%M:%S) tunnel down, waiting..."
    sleep 90
  done
  echo "tunnel never came back"; return 1
}

run() {
  name=$1; shift
  tmo=$1; shift
  if [ -f "$OUT/$name.done" ]; then echo "=== $name: already done, skipping ==="; return 0; fi
  echo "=== $name: $* ==="
  for attempt in $(seq 1 $MAX_ATTEMPTS); do
    wait_tunnel || return 1
    # per-attempt logs: a retry must not destroy the prior attempt's
    # failure evidence; $name.log always points at the latest attempt
    timeout "$tmo" "$@" > "$OUT/$name.a$attempt.log" 2>&1
    rc=$?
    ln -sf "$name.a$attempt.log" "$OUT/$name.log"
    echo "$name attempt $attempt rc=$rc ($(date -u +%H:%M:%S))"
    if [ "$rc" = 0 ]; then touch "$OUT/$name.done"; return 0; fi
    sleep 30
  done
  echo "$name FAILED after $MAX_ATTEMPTS attempts"
  return 1
}
