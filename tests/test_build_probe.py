import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.relation import host_join_count
from tpu_radix_join.data.tuples import CompressedBatch, make_padding
from tpu_radix_join.ops.build_probe import (
    probe_count,
    probe_count_bucketized,
    probe_count_per_partition,
    probe_materialize,
)


def _comp(keys, rids=None):
    keys = np.asarray(keys, np.uint32)
    rids = np.arange(len(keys)) if rids is None else rids
    return CompressedBatch(key_rem=jnp.asarray(keys, jnp.uint32),
                           rid=jnp.asarray(rids, jnp.uint32))


def test_probe_count_with_duplicates():
    rng = np.random.default_rng(0)
    r = rng.integers(0, 500, 3000).astype(np.uint32)   # heavy duplicates
    s = rng.integers(0, 500, 2000).astype(np.uint32)
    got = int(probe_count(_comp(r), _comp(s)))
    assert got == host_join_count(r, s)


def test_probe_count_ignores_padding():
    r = np.array([1, 2, 3], np.uint32)
    s = np.array([2, 2, 9], np.uint32)
    rb = _comp(np.concatenate([r, np.full(5, 0xFFFFFFFE, np.uint32)]))
    sb = _comp(np.concatenate([s, np.full(7, 0xFFFFFFFF, np.uint32)]))
    assert int(probe_count(rb, sb)) == 2


def test_probe_count_per_partition():
    rng = np.random.default_rng(3)
    r = rng.integers(0, 256, 2000).astype(np.uint32)
    s = rng.integers(0, 256, 1500).astype(np.uint32)
    pid = (s % 8).astype(np.uint32)
    per = np.asarray(probe_count_per_partition(_comp(r), _comp(s), jnp.asarray(pid), 8))
    assert per.sum() == host_join_count(r, s)
    # spot-check one partition
    expect0 = host_join_count(r, s[pid == 0])
    assert per[0] == expect0


def test_probe_bucketized():
    nb, cap = 4, 8
    rkeys = np.full((nb, cap), 0xFFFFFFFE, np.uint32)
    skeys = np.full((nb, cap), 0xFFFFFFFF, np.uint32)
    rkeys[0, :3] = [1, 1, 2]
    skeys[0, :4] = [1, 2, 2, 3]
    rkeys[2, :1] = [7]
    skeys[2, :2] = [7, 7]
    per_bucket = np.asarray(probe_count_bucketized(jnp.asarray(rkeys), jnp.asarray(skeys)))
    np.testing.assert_array_equal(per_bucket, [2 + 2, 0, 2, 0])


def test_probe_materialize():
    r = _comp([5, 5, 9], rids=np.array([10, 11, 12], np.uint32))
    s = _comp([5, 9, 9, 7], rids=np.array([20, 21, 22, 23], np.uint32))
    m = probe_materialize(r, s, cap=4)
    pairs = {(int(a), int(b)) for a, b, v in
             zip(np.asarray(m.r_rid), np.asarray(m.s_rid), np.asarray(m.valid)) if v}
    assert pairs == {(10, 20), (11, 20), (12, 21), (12, 22)}
    assert int(m.overflow) == 0


def test_probe_materialize_overflow_flag():
    r = _comp([5] * 10)
    s = _comp([5])
    m = probe_materialize(r, s, cap=4)
    assert int(m.overflow) == 1
    assert int(np.asarray(m.valid).sum()) == 4


def test_bucketized_merge_equals_dense():
    from tpu_radix_join.ops.build_probe import (
        probe_count_bucketized_merge,
    )
    from tpu_radix_join.data.tuples import R_PAD_KEY, S_PAD_KEY
    rng = np.random.default_rng(5)
    nb, bi, bo = 16, 40, 56
    inner = rng.integers(0, 64, (nb, bi), dtype=np.uint32)
    outer = rng.integers(0, 64, (nb, bo), dtype=np.uint32)
    # sentinel-pad ragged tails like local_partition does
    for row in range(nb):
        inner[row, rng.integers(0, bi):] = R_PAD_KEY
        outer[row, rng.integers(0, bo):] = S_PAD_KEY
    dense = (inner[:, :, None] == outer[:, None, :]).sum((1, 2))
    got = np.asarray(probe_count_bucketized_merge(
        jnp.asarray(inner), jnp.asarray(outer)))
    np.testing.assert_array_equal(got, dense.astype(np.uint32))


def test_two_level_join_large_buckets():
    """Buckets above DENSE_BUCKET_LIMIT route to the batched sort-merge; the
    two-level pipeline must stay exact."""
    from tpu_radix_join import HashJoin, JoinConfig, Relation
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=2, local_fanout_bits=2,
                     two_level=True, allocation_factor=2.0)
    size = 1 << 14    # /4 nodes /4 net /4 local => ~256+ slot buckets
    r = Relation(size, 4, "unique", seed=1)
    s = Relation(size, 4, "unique", seed=9)
    res = HashJoin(cfg).join(r, s)
    assert res.ok
    assert res.matches == size
