"""End-to-end join tests: the reference's unique-key oracle (main.cpp:95-98)
as automated assertions, single-node and on an 8-virtual-device mesh."""

import numpy as np
import pytest

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.data.relation import host_join_count


def _run(cfg, r, s):
    return HashJoin(cfg).join(r, s)


def test_single_node_unique():
    cfg = JoinConfig(num_nodes=1, network_fanout_bits=5)
    size = 1 << 14
    r = Relation(size, 1, "unique", seed=1)
    s = Relation(size, 1, "unique", seed=2)
    res = _run(cfg, r, s)
    assert res.ok
    assert res.matches == size


def test_multi_node_unique():
    cfg = JoinConfig(num_nodes=8, network_fanout_bits=5)
    size = 1 << 15
    r = Relation(size, 8, "unique", seed=1)
    s = Relation(size, 8, "unique", seed=9)
    res = _run(cfg, r, s)
    assert res.ok
    assert res.matches == size


def test_multi_node_modulo_match_rate():
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=4)
    r = Relation(1 << 14, 4, "unique", seed=1)
    s = Relation(1 << 14, 4, "modulo", modulo=1 << 10)
    res = _run(cfg, r, s)
    assert res.ok
    assert res.matches == r.expected_matches(s) == 1 << 14


def test_multi_node_skew_load_aware():
    cfg = JoinConfig(num_nodes=8, network_fanout_bits=5,
                     assignment_policy="load_aware", allocation_factor=4.0)
    r = Relation(1 << 14, 8, "unique", seed=1)
    s = Relation(1 << 14, 8, "zipf", zipf_theta=0.75, key_domain=1 << 14, seed=3)
    res = _run(cfg, r, s)
    assert res.ok
    # oracle: every zipf key is in [0, 2**14) and R covers it exactly once
    assert res.matches == 1 << 14


def test_duplicates_vs_host_oracle():
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=4, allocation_factor=2.0)
    r = Relation(1 << 12, 4, "modulo", modulo=512)
    s = Relation(1 << 12, 4, "modulo", modulo=512)
    rk = np.concatenate([r.shard_np(i)[0] for i in range(4)])
    sk = np.concatenate([s.shard_np(i)[0] for i in range(4)])
    res = _run(cfg, r, s)
    assert res.ok
    assert res.matches == host_join_count(rk, sk)


def test_bucketized_probe_path():
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=4,
                     probe_algorithm="bucket", local_fanout_bits=6,
                     allocation_factor=2.0)
    size = 1 << 13
    r = Relation(size, 4, "unique", seed=1)
    s = Relation(size, 4, "unique", seed=5)
    res = _run(cfg, r, s)
    assert res.ok
    assert res.matches == size


def test_sentinel_key_input_flips_ok():
    import jax.numpy as jnp
    from tpu_radix_join.data.tuples import TupleBatch
    cfg = JoinConfig(num_nodes=1, network_fanout_bits=3)
    hj = HashJoin(cfg)
    n = 64
    keys = np.arange(n, dtype=np.uint32)
    keys[5] = 0xFFFFFFFE   # collides with the inner padding sentinel
    r = TupleBatch(key=jnp.asarray(keys), rid=jnp.arange(n, dtype=jnp.uint32))
    s = TupleBatch(key=jnp.arange(n, dtype=jnp.uint32),
                   rid=jnp.arange(n, dtype=jnp.uint32))
    res = hj.join_arrays(r, s)
    assert not res.ok


def test_static_window_sizing():
    cfg = JoinConfig(num_nodes=4, window_sizing="static", allocation_factor=2.0)
    size = 1 << 13
    r = Relation(size, 4, "unique", seed=1)
    s = Relation(size, 4, "unique", seed=2)
    res = HashJoin(cfg).join(r, s)
    assert res.ok and res.matches == size


def test_static_window_sizing_overflow_flips_ok():
    # tight capacity + heavy skew must be *detected*, never silently dropped
    cfg = JoinConfig(num_nodes=8, window_sizing="static", allocation_factor=1.0)
    r = Relation(1 << 13, 8, "unique", seed=1)
    s = Relation(1 << 13, 8, "zipf", zipf_theta=0.75, key_domain=1 << 13, seed=3)
    res = HashJoin(cfg).join(r, s)
    assert not res.ok


def test_round_robin_vs_load_aware_same_result():
    size = 1 << 13
    r = Relation(size, 4, "unique", seed=1)
    s = Relation(size, 4, "unique", seed=2)
    m1 = _run(JoinConfig(num_nodes=4), r, s).matches
    m2 = _run(JoinConfig(num_nodes=4, assignment_policy="load_aware"), r, s).matches
    assert m1 == m2 == size


def test_debug_checks_per_partition_invariant():
    """debug_checks turns on the strong per-partition conservation form; a
    healthy join must still pass it, skewed or not."""
    cfg = JoinConfig(num_nodes=8, debug_checks=True)
    size = 1 << 14
    res = _run(cfg, Relation(size, 8, "unique", seed=1),
               Relation(size, 8, "unique", seed=9))
    assert res.ok
    assert res.matches == size
    cfg = JoinConfig(num_nodes=8, debug_checks=True,
                     assignment_policy="load_aware", allocation_factor=4.0)
    res = _run(cfg, Relation(size, 8, "unique", seed=1),
               Relation(size, 8, "zipf", zipf_theta=0.75, key_domain=size,
                        seed=3))
    assert res.ok
    assert res.matches == size


def test_join_arrays_pipelined_matches_sync():
    """The pipelined-repeat path must agree with the synchronous path on
    matches, flags, and cumulative counter conventions."""
    import jax.numpy as jnp

    from tpu_radix_join.data.tuples import TupleBatch
    from tpu_radix_join.performance import Measurements

    n = 1 << 12
    r = TupleBatch(key=jnp.arange(n, dtype=jnp.uint32),
                   rid=jnp.arange(n, dtype=jnp.uint32))
    s = TupleBatch(key=jnp.arange(n, dtype=jnp.uint32)[::-1],
                   rid=jnp.arange(n, dtype=jnp.uint32))
    m = Measurements()
    res = HashJoin(JoinConfig(num_nodes=4), measurements=m
                   ).join_arrays_pipelined(r, s, repeats=3)
    assert res.ok and res.matches == n
    assert m.counters["RTUPLES"] == 3 * n        # cumulative convention
    assert m.counters["RESULTS"] == 3 * n
    assert m.times_us.get("JPROC", 0) > 0 and m.times_us.get("JTOTAL", 0) > 0
    # exchange counters accumulate once per dispatched join, exactly like
    # the synchronous loop (r5 review: a single record would undercount 3x)
    m_sync = Measurements()
    hj = HashJoin(JoinConfig(num_nodes=4), measurements=m_sync)
    for _ in range(3):
        assert hj.join_arrays(r, s).ok
    assert m.counters["MWINBYTES"] == m_sync.counters["MWINBYTES"]
    assert m.counters["MWINPUTCNT"] == m_sync.counters["MWINPUTCNT"]


def test_join_clean_under_transfer_guard(transfer_guard):
    """The whole engine path — placement and join — must run under
    ``jax.transfer_guard("disallow")`` (the fixture arms it): every
    device->host readback in the hot path goes through the explicit
    ``utils.hostsync.host_readback`` (jax.device_get), so an implicit
    sync anywhere raises here.  Runtime twin of tools_lint.py's static
    sync-point rule — each catches what the other cannot (dynamic paths
    vs. paths this workload doesn't execute)."""
    cfg = JoinConfig(num_nodes=8, network_fanout_bits=5)
    eng = HashJoin(cfg)
    size = 1 << 15
    rb = eng.place(Relation(size, 8, "unique", seed=1))
    sb = eng.place(Relation(size, 8, "unique", seed=9))
    res = eng.join_arrays(rb, sb)
    assert res.ok
    assert res.matches == size
