"""Engine-integrated tests for the resident join service: the tier-1
serve-mode smoke (3 queries through one session via the CLI), warm
plan/capacity reuse, deadline expiry mid-phase, admission rejection
through the serve loop, breaker trip/recovery driven by FaultInjector
arms, thread-lifecycle stability, and a session chaos mini-soak.
"""

import json
import threading
import time

import pytest

from tpu_radix_join.core.config import JoinConfig, ServiceConfig
from tpu_radix_join.performance.measurements import (JHIST, QDEADLINE,
                                                     QDEGRADED, QWARM,
                                                     Measurements)
from tpu_radix_join.robustness import faults
from tpu_radix_join.robustness.faults import TransientFault
from tpu_radix_join.robustness.retry import (BACKEND_UNAVAILABLE,
                                             DEADLINE_EXCEEDED)
from tpu_radix_join.service import (AdmissionRejected, JoinSession,
                                    QueryRequest)

NODES = 8
TPN = 1 << 10          # 1K tuples/node: compile-bound, not data-bound


def _req(qid, tenant="default", **kw):
    kw.setdefault("tuples_per_node", TPN)
    kw.setdefault("seed", 7)
    return QueryRequest(query_id=qid, tenant=tenant, **kw)


def _outcome_lines(out):
    recs = [json.loads(line) for line in out.splitlines()
            if line.startswith("{")]
    return ([r for r in recs if r.get("event") == "outcome"],
            next((r for r in recs if r.get("event") == "summary"), None))


# ----------------------------------------------------------- CLI serve smoke

def test_serve_smoke_three_queries_one_session(capsys, tmp_path):
    """Tier-1 serve smoke: 3 queries through ONE resident session on host
    CPU — all ok, later same-shape queries warm (sizing pre-pass
    skipped), summary carries the SLO percentiles."""
    from tpu_radix_join.main import main
    reqs = tmp_path / "reqs.jsonl"
    reqs.write_text("".join(
        json.dumps({"query_id": f"q{i}", "tuples_per_node": TPN,
                    "seed": 7}) + "\n"
        for i in range(3)))
    rc = main(["--serve", str(reqs), "--nodes", str(NODES)])
    outcomes, summary = _outcome_lines(capsys.readouterr().out)
    assert rc == 0
    assert [o["query_id"] for o in outcomes] == ["q0", "q1", "q2"]
    assert all(o["status"] == "ok" for o in outcomes)
    expect = TPN * NODES
    assert all(o["matches"] == expect for o in outcomes)
    assert not outcomes[0]["warm"]
    assert outcomes[1]["warm"] and outcomes[2]["warm"]
    assert summary is not None
    assert summary["queries_ok"] == 3 and summary["queries_failed"] == 0
    assert summary["warm_queries"] == 2
    assert summary["slo_p50_ms"] > 0 and summary["slo_p99_ms"] > 0
    # cold pays compile + sizing; warm must be far under it
    assert outcomes[1]["latency_ms"] < outcomes[0]["latency_ms"]


def test_serve_rejections_classified_no_hang(capsys, tmp_path):
    """Over-quota and queue-full submissions come back as classified
    rejection outcomes through the CLI — and rejections alone do not fail
    the run (backpressure is the feature working)."""
    from tpu_radix_join.main import main
    reqs = tmp_path / "reqs.jsonl"
    reqs.write_text("".join(
        json.dumps({"query_id": f"q{i}", "tenant": "noisy",
                    "tuples_per_node": TPN, "seed": 7}) + "\n"
        for i in range(5)))
    rc = main(["--serve", str(reqs), "--nodes", str(NODES),
               "--serve-batch", "10", "--serve-tenant-quota", "2"])
    outcomes, summary = _outcome_lines(capsys.readouterr().out)
    assert rc == 0
    rejected = [o for o in outcomes if o["status"] == "rejected"]
    assert len(rejected) == 3
    assert all(o["failure_class"] == "admission_rejected" for o in rejected)
    assert all("tenant_quota" in o["detail"] for o in rejected)
    assert summary["queries_ok"] == 2 and summary["queries_rejected"] == 3
    assert summary["admission_rejection_rate"] == pytest.approx(0.6)


def test_serve_malformed_line_fails_run_but_not_session(capsys, tmp_path):
    from tpu_radix_join.main import main
    reqs = tmp_path / "reqs.jsonl"
    reqs.write_text(
        json.dumps({"query_id": "good", "tuples_per_node": TPN,
                    "seed": 7}) + "\n"
        + "this is not json\n"
        + json.dumps({"query_id": "also_good", "tuples_per_node": TPN,
                      "seed": 7}) + "\n")
    rc = main(["--serve", str(reqs), "--nodes", str(NODES)])
    out = capsys.readouterr().out
    outcomes, summary = _outcome_lines(out)
    assert rc == 1                       # a client bug fails the run...
    assert [o["query_id"] for o in outcomes] == ["good", "also_good"]
    assert all(o["status"] == "ok" for o in outcomes)   # ...not the session
    assert '"event": "request_error"' in out


# --------------------------------------------------------- resident session

@pytest.fixture(scope="module")
def session():
    m = Measurements()
    sess = JoinSession(JoinConfig(num_nodes=NODES),
                       ServiceConfig(breaker_threshold=2,
                                     breaker_cooldown_s=0.05),
                       measurements=m)
    yield sess
    sess.close()


def test_warm_queries_skip_sizing_pre_pass(session):
    m = session.measurements
    session.submit(_req("w0", seed=21))
    cold = session.run_next()
    jhist_after_cold = m.times_us.get(JHIST, 0.0)
    qwarm0 = m.counters.get(QWARM, 0)
    session.submit(_req("w1", seed=21))
    warm = session.run_next()
    assert cold.status == "ok" and warm.status == "ok"
    assert warm.warm and warm.matches == cold.matches
    # the observable: NO new JHIST time (the sizing pre-pass never ran)
    assert m.times_us.get(JHIST, 0.0) == jhist_after_cold
    assert m.counters.get(QWARM, 0) == qwarm0 + 1


def test_deadline_expires_mid_phase_and_session_survives(session):
    m = session.measurements
    qdl0 = m.counters.get(QDEADLINE, 0)
    # generous enough to pass admission, far too tight for placement+join
    # of a cold shape (different seed -> new relations, same compiled fn)
    session.submit(_req("dl", seed=99, deadline_s=1e-6))
    out = session.run_next()
    assert out.status == "failed"
    assert out.failure_class == DEADLINE_EXCEEDED
    assert "at phase" in out.detail      # aborted AT a phase boundary
    assert m.counters.get(QDEADLINE, 0) == qdl0 + 1
    # failure isolation: the next query is unaffected
    session.submit(_req("after_dl", seed=21))
    assert session.run_next().status == "ok"


def test_breaker_trip_degrade_probe_recover(session):
    m = session.measurements
    qdeg0 = m.counters.get(QDEGRADED, 0)
    trips0 = session.breaker.trips
    inj = faults.FaultInjector(seed=5, measurements=m)
    inj.arm(faults.BACKEND_DISPATCH, at=(1, 2), exc=TransientFault)
    with inj:
        outs = []
        for i in range(3):
            session.submit(_req(f"brk{i}", seed=21))
            outs.append(session.run_next())
    # threshold 2: two classified outages trip the breaker...
    assert [o.failure_class for o in outs[:2]] == [BACKEND_UNAVAILABLE] * 2
    assert session.breaker.trips == trips0 + 1
    # ...and the third query is served degraded, correctly, while open
    assert outs[2].status == "ok" and outs[2].engine == "cpu_fallback"
    assert m.counters.get(QDEGRADED, 0) == qdeg0 + 1
    time.sleep(0.06)                     # cooldown (0.05s) elapses
    session.submit(_req("probe", seed=21))
    probe = session.run_next()
    assert probe.status == "ok" and probe.engine == "primary"
    assert session.breaker.state == "closed"


def test_session_threads_stable_across_queries_and_close(tmp_path):
    n0 = threading.active_count()
    m = Measurements()
    sess = JoinSession(JoinConfig(num_nodes=4), measurements=m)
    sess.attach_heartbeat(str(tmp_path / "hb.metrics.jsonl"),
                          interval_s=0.05)
    assert threading.active_count() == n0 + 1   # exactly the heartbeat
    for i in range(3):
        sess.submit(_req(f"t{i}", tuples_per_node=256))
        assert sess.run_next().status == "ok"
        # no thread accumulates per query (the daemon-leak satellite)
        assert threading.active_count() == n0 + 1
    sess.close()
    deadline = time.monotonic() + 5.0
    while threading.active_count() > n0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() == n0       # heartbeat joined
    recs = [json.loads(ln) for ln in
            (tmp_path / "hb.metrics.jsonl").read_text().splitlines()]
    assert recs and "slo" in recs[-1] and "breaker" in recs[-1]
    assert recs[-1]["slo"]["queries_ok"] == 3
    with pytest.raises(RuntimeError):
        sess.submit(_req("late"))               # closed session refuses


def test_session_close_is_idempotent():
    sess = JoinSession(JoinConfig(num_nodes=2))
    sess.close()
    sess.close()


# ------------------------------------------------------------- chaos soak

@pytest.mark.slow
def test_session_chaos_soak_no_isolation_violations():
    from tpu_radix_join.robustness import chaos
    runner = chaos.SessionChaosRunner(num_nodes=4, size=1 << 10, queries=4)
    outcomes, summary = chaos.soak_session(3, base_seed=100, runner=runner)
    assert summary["violations"] == 0, [o.detail for o in outcomes
                                        if o.status == chaos.VIOLATION]
    assert summary["pass"] + summary["classified"] == 3


def test_session_chaos_single_stream_classifies_backend_outage():
    from tpu_radix_join.robustness import chaos
    runner = chaos.SessionChaosRunner(num_nodes=4, size=1 << 10, queries=3)
    out = runner.run(chaos.Schedule(
        seed=1, arms=((faults.BACKEND_DISPATCH, (("at", 2),)),)))
    assert out.status == chaos.CLASSIFIED
    assert BACKEND_UNAVAILABLE in out.failure_class
    # breaker threshold 1 + zero cooldown: the stream recovers in-line
    assert "q2=ok" in out.detail
