"""Native C++ runtime: pool allocator + multithreaded generators.

Skipped when no toolchain is available (the package falls back to numpy)."""

import numpy as np
import pytest

from tpu_radix_join.native.build import load

lib = load()
pytestmark = pytest.mark.skipif(lib is None, reason="no native toolchain")


def test_pool_bump_and_reset():
    from tpu_radix_join.memory import Pool
    pool = Pool(1 << 16)
    assert pool.native
    a = pool.get_array((100,), np.uint32)
    b = pool.get_array((100,), np.uint32)
    a[:] = 1
    b[:] = 2
    assert a.sum() == 100 and b.sum() == 200   # disjoint regions
    used = pool.used()
    assert used >= 800 and used % 64 == 0       # 64B-aligned bumps
    # overflow fallback past capacity must still hand out valid memory
    big = pool.get_array((1 << 15,), np.uint32)
    big[:] = 3
    assert big.sum() == 3 * (1 << 15)
    pool.reset()
    assert pool.used() == 0
    pool.close()


def test_native_unique_matches_numpy():
    from tpu_radix_join.data.relation import Relation, feistel_permutation_np
    rel = Relation(1 << 12, 4, "unique", seed=17)
    for node in (0, 3):
        native_keys, _ = rel.shard_np(node)             # native path
        lo = node * rel.local_size
        idx = np.arange(lo, lo + rel.local_size, dtype=np.uint64)
        bits = max(2, (rel.global_size - 1).bit_length())
        ref = feistel_permutation_np(idx, bits, rel.seed)
        while (ref >= rel.global_size).any():
            out = ref >= rel.global_size
            ref[out] = feistel_permutation_np(ref[out], bits, rel.seed)
        np.testing.assert_array_equal(native_keys, ref.astype(np.uint32))


def test_native_unique_is_permutation():
    from tpu_radix_join.data.relation import Relation
    rel = Relation(3000, 3, "unique", seed=5)
    keys = np.concatenate([rel.shard_np(i)[0] for i in range(3)])
    np.testing.assert_array_equal(np.sort(keys), np.arange(3000))


def test_native_zipf_matches_numpy_twin():
    from tpu_radix_join.data.relation import (Relation, zipf_keys_np,
                                              zipf_tables)
    rel = Relation(4096, 2, "zipf", zipf_theta=0.75, key_domain=1024, seed=9)
    for node in (0, 1):
        native_keys, _ = rel.shard_np(node)
        head_cdf, tail_keys = zipf_tables(0.75, 1024)
        twin = zipf_keys_np(node * rel.local_size, rel.local_size, head_cdf,
                            tail_keys, 1024, 9)
        np.testing.assert_array_equal(native_keys, twin)
    # skew sanity: rank 0 must dominate
    keys = np.concatenate([rel.shard_np(i)[0] for i in range(2)])
    assert (keys == 0).mean() > 0.2


def test_native_zipf_covers_large_domains():
    # domains beyond the 65536-rank table must still be reachable via the
    # interpolated power-law tail (and match the numpy twin bit-for-bit)
    from tpu_radix_join.data.relation import (Relation, zipf_keys_np,
                                              zipf_tables)
    domain = 1 << 20
    rel = Relation(1 << 16, 1, "zipf", zipf_theta=0.75, key_domain=domain, seed=4)
    keys, _ = rel.shard_np(0)
    assert keys.max() > 65536          # tail ranks appear
    assert keys.max() < domain
    head_cdf, tail_keys = zipf_tables(0.75, domain)
    twin = zipf_keys_np(0, 1 << 16, head_cdf, tail_keys, domain, 4)
    np.testing.assert_array_equal(keys, twin)


def test_pool_survives_gc():
    # arrays returned by a temporary Pool must keep the region alive
    import gc
    from tpu_radix_join.memory import Pool
    arr = Pool(1 << 16).get_array((1000,), np.uint32)
    gc.collect()
    arr[:] = 0xABCD
    assert int(arr.sum()) == 1000 * 0xABCD


def test_native_modulo():
    from tpu_radix_join.data.relation import Relation
    rel = Relation(1 << 10, 2, "modulo", modulo=17)
    k, rid = rel.shard_np(1)
    np.testing.assert_array_equal(k, rid % 17)
