"""Test rig: single-process 8-device virtual CPU mesh (the JAX analog of the
reference's oversubscribed ``mpirun``, SURVEY.md §4 item 5).  Platform-forcing
mechanics live in tpu_radix_join/utils/platform.py."""

import os
import tempfile

# Isolate the bench/grid chip handshake (utils/locks.py): without this,
# grid tests would join the repo's REAL artifacts/BENCH_RUNNING and
# GRID_RUNNING files — parking on a live bench and clobbering a live grid's
# presence file.  Tests that exercise the handshake monkeypatch their own.
_lock_dir = tempfile.mkdtemp(prefix="tpu_rj_locks_")
os.environ.setdefault("TPU_RJ_PAUSE_FILE",
                      os.path.join(_lock_dir, "BENCH_RUNNING"))
os.environ.setdefault("TPU_RJ_GRID_FILE",
                      os.path.join(_lock_dir, "GRID_RUNNING"))

from tpu_radix_join.utils.platform import force_host_cpu_devices

force_host_cpu_devices(8, respect_existing=True)
