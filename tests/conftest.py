"""Test rig: single-process 8-device virtual CPU mesh (the JAX analog of the
reference's oversubscribed ``mpirun``, SURVEY.md §4 item 5).  Platform-forcing
mechanics live in tpu_radix_join/utils/platform.py."""

from tpu_radix_join.utils.platform import force_host_cpu_devices

force_host_cpu_devices(8, respect_existing=True)
