"""Test rig: single-process multi-device CPU mesh.

The reference tests multi-node behavior with plain oversubscribed ``mpirun``
(SURVEY.md §4.5); the JAX analog is 8 virtual CPU devices via
``--xla_force_host_platform_device_count``.

The container's sitecustomize imports jax at interpreter start with
``JAX_PLATFORMS=axon`` (the live-TPU tunnel), which locks the config default
before this file runs — so we must update jax.config directly, not just the
environment.  XLA_FLAGS is still read at first backend use, which has not
happened yet at conftest import time.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
