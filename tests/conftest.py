"""Test rig: single-process 8-device virtual CPU mesh (the JAX analog of the
reference's oversubscribed ``mpirun``, SURVEY.md §4 item 5).  Platform-forcing
mechanics live in tpu_radix_join/utils/platform.py."""

import os
import tempfile

# Isolate the bench/grid chip handshake (utils/locks.py): without this,
# grid tests would join the repo's REAL artifacts/BENCH_RUNNING and
# GRID_RUNNING files — parking on a live bench and clobbering a live grid's
# presence file.  Tests that exercise the handshake monkeypatch their own.
_lock_dir = tempfile.mkdtemp(prefix="tpu_rj_locks_")
os.environ.setdefault("TPU_RJ_PAUSE_FILE",
                      os.path.join(_lock_dir, "BENCH_RUNNING"))
os.environ.setdefault("TPU_RJ_GRID_FILE",
                      os.path.join(_lock_dir, "GRID_RUNNING"))

from tpu_radix_join.utils.platform import force_host_cpu_devices

force_host_cpu_devices(8, respect_existing=True)

import pytest


@pytest.fixture
def transfer_guard():
    """Arm ``jax.transfer_guard("disallow")`` for the test body: any
    implicit device<->host transfer raises.  The runtime twin of
    tools_lint.py's static sync-point rule — explicit readbacks through
    ``utils.hostsync.host_readback`` (jax.device_get) stay legal, so a
    test passing under this fixture proves the code path only syncs
    where it says it does.  Build inputs BEFORE requesting the fixture
    value's context (it is already armed when the test body runs), or
    pre-place them with jax.device_put, which is likewise explicit."""
    import jax

    with jax.transfer_guard("disallow"):
        yield
