"""Regression tests for the artifact-analysis tools: the evidence-summary
generator (tools_make_report.py) and the net-of-dispatch phase table
(experiments/exp_phase_net.py) parse the committed round-3 chip artifacts
to known values, so a refactor of the perf format or the tools cannot
silently corrupt the numbers BASELINE.md quotes."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
R3 = os.path.join(REPO, "artifacts", "chip_r3")


def _run(*argv):
    out = subprocess.run([sys.executable, *argv], capture_output=True,
                         text=True, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr
    return out.stdout


def test_make_report_reproduces_r3_numbers():
    out = _run("tools_make_report.py", R3)
    # the committed BASELINE.md round-3 table, straight from the artifacts
    assert "| perf_16m_sort_devgen | 3 |  |  |  |  | 108.5 | 309.4 |" in out
    assert "| perf_20m_phases_devgen | 3 |  | 83.2 | 317.1 | 366.3 | 507.4 " \
           "| 78.8 |" in out
    assert "## Task status" in out


def test_make_report_empty_dir(tmp_path):
    out = _run("tools_make_report.py", str(tmp_path))
    assert "Evidence summary" in out      # no artifacts -> no tables, no crash


def test_phase_net_r3_table():
    out = _run("experiments/exp_phase_net.py",
               os.path.join(R3, "perf_16m_phases_devgen"),
               os.path.join(R3, "perf_16m_sort_devgen"))
    # r3 artifacts predate SDISPATCH: net == gross, flagged loudly
    assert "no SDISPATCH tag" in out
    assert "JPROC" in out and "fused dir" in out
    assert "JPROC gross 108.5 ms/join" in out
