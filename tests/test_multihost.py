"""Hierarchical (dcn, ici) mesh tests on the 8-virtual-device rig: the
two-stage exchange must be indistinguishable from the flat all_to_all, and
the full join must hold its oracle over a 2-host x 4-chip mesh."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.parallel.mesh import make_hierarchical_mesh, make_mesh
from tpu_radix_join.parallel.window import block_all_to_all

H, L = 2, 4
N = H * L
BLOCK = 16


def _run_flat(x):
    mesh = make_mesh(N)
    return jax.jit(jax.shard_map(
        lambda v: block_all_to_all(v, N, BLOCK, "nodes"),
        mesh=mesh, in_specs=P("nodes"), out_specs=P("nodes")))(x)


def _run_hier(x):
    mesh = make_hierarchical_mesh(H, N)
    return jax.jit(jax.shard_map(
        lambda v: block_all_to_all(v, N, BLOCK, ("dcn", "ici")),
        mesh=mesh, in_specs=P(("dcn", "ici")), out_specs=P(("dcn", "ici"))))(x)


def test_hierarchical_exchange_matches_flat():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 1 << 31, N * N * BLOCK, dtype=np.uint32))
    np.testing.assert_array_equal(np.asarray(_run_flat(x)),
                                  np.asarray(_run_hier(x)))


def test_axis_index_row_major():
    """Pins the rank convention the pipeline relies on: axis_index over the
    ("dcn", "ici") pair is the row-major flat rank (the MPI_Comm_rank
    analog), matching assignment destination ids."""
    mesh = make_hierarchical_mesh(H, N)
    out = jax.jit(jax.shard_map(
        lambda: jax.lax.axis_index(("dcn", "ici")).reshape(1),
        mesh=mesh, in_specs=(), out_specs=P(("dcn", "ici"))))()
    np.testing.assert_array_equal(np.asarray(out), np.arange(N))


def test_join_on_hierarchical_mesh():
    cfg = JoinConfig(num_nodes=N, num_hosts=H, network_fanout_bits=5)
    size = 1 << 14
    r = Relation(size, N, "unique", seed=1)
    s = Relation(size, N, "unique", seed=9)
    res = HashJoin(cfg).join(r, s)
    assert res.ok
    assert res.matches == size


def test_two_process_plumbing():
    """REAL multi-process world (VERDICT r2 next #5): two CPU processes of 4
    virtual devices each join via jax.distributed on a localhost coordinator
    (the mpirun analog), run the hierarchical-mesh join across the 8 global
    devices, and rank 0 aggregates measurements via the network gather —
    multihost.initialize exercised beyond the single-process fallback."""
    import os
    import socket
    import subprocess
    import sys

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    worker = os.path.join(os.path.dirname(__file__), "_multiproc_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(rank), "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True, cwd=repo)
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    joined = "\n---- rank boundary ----\n".join(outs)
    assert all(p.returncode == 0 for p in procs), joined
    assert "MULTIPROC_OK matches=4096 ranks=2" in outs[0], joined
    for rank, out in enumerate(outs):
        assert f"RANK_DONE {rank}" in out, joined


def test_two_process_driver():
    """The full mpirun composition at the DRIVER level (VERDICT r4 missing
    #4): two real jax.distributed CPU processes x 4 virtual devices run
    ``python -m tpu_radix_join.main --hosts 2`` end to end — env-driven
    multihost bootstrap (the mpirun rank environment), hierarchical mesh,
    full join, network measurement gather, rank-0 aggregate report, oracle
    exit code, and per-rank .perf artifacts in a shared experiment dir
    (main.cpp:36-48 + Measurements.cpp:548-590 in one shape)."""
    import os
    import socket
    import subprocess
    import sys
    import tempfile

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_dir = tempfile.mkdtemp(prefix="driver2p_")
    procs = []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(rank),
            PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "tpu_radix_join.main",
             "--tuples-per-node", "1024", "--nodes", "8", "--hosts", "2",
             "--output-dir", out_dir],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True, cwd=repo))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    joined = "\n---- rank boundary ----\n".join(outs)
    assert all(p.returncode == 0 for p in procs), joined
    assert "[RESULTS] Expected: 8192 (OK)" in outs[0], joined
    assert "[RESULTS] Nodes: 2" in outs[0], joined        # gathered registries
    assert "[RESULTS]" not in outs[1], joined             # rank 0 alone prints
    for rank in range(2):                                 # per-rank artifacts
        assert os.path.exists(os.path.join(out_dir, f"{rank}.perf")), joined


def test_join_hierarchical_skew_load_aware():
    cfg = JoinConfig(num_nodes=N, num_hosts=H, network_fanout_bits=5,
                     assignment_policy="load_aware", allocation_factor=4.0)
    r = Relation(1 << 14, N, "unique", seed=1)
    s = Relation(1 << 14, N, "zipf", zipf_theta=0.75, key_domain=1 << 14,
                 seed=3)
    res = HashJoin(cfg).join(r, s)
    assert res.ok
    assert res.matches == (1 << 14)
