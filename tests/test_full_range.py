"""Full-range uint32 key discipline (VERDICT r4 weak #4 / next #8): the
31-bit packed fast path's ceiling must not silently reject — or worse,
silently undercount — any sub-sentinel uint32 workload.  Covers the
full-range lexicographic count op, the config routing (narrow/full/auto),
the Relation static bound, and the out-of-core chunked path."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.ops.merge_count import (
    MAX_MERGE_KEY,
    merge_count_per_partition,
    merge_count_per_partition_full,
)


def _oracle_counts(r_keys, s_keys, fanout_bits):
    """Per-partition duplicate-aware match counts via numpy."""
    num_p = 1 << fanout_bits
    out = np.zeros(num_p, dtype=np.uint64)
    common, r_idx, s_idx = np.intersect1d(
        *(np.unique(k) for k in (r_keys, s_keys)), return_indices=True)
    rc = dict(zip(*np.unique(r_keys, return_counts=True)))
    sc = dict(zip(*np.unique(s_keys, return_counts=True)))
    for k in common:
        out[int(k) & (num_p - 1)] += int(rc[k]) * int(sc[k])
    return out


@pytest.mark.parametrize("fanout", [0, 3, 5])
def test_merge_full_oracle_full_range(fanout):
    rng = np.random.default_rng(7 + fanout)
    # keys straddling 2**31 with duplicates, right up to the sentinel floor
    r = rng.integers(0, 0xFFFFFFFE, size=4096, dtype=np.uint32)
    s = rng.integers(0, 0xFFFFFFFE, size=4096, dtype=np.uint32)
    dup = rng.integers(1 << 31, 0xFFFFFFFD, size=64, dtype=np.uint32)
    r = np.concatenate([r, np.repeat(dup, 3)])
    s = np.concatenate([s, np.repeat(dup, 2)])
    counts, maxw = merge_count_per_partition_full(
        jnp.asarray(r), jnp.asarray(s), fanout, return_max_weight=True)
    got = np.asarray(counts).astype(np.uint64)
    want = _oracle_counts(r, s, fanout)
    np.testing.assert_array_equal(got, want)
    # max single-outer-tuple weight == max inner multiplicity among matched keys
    assert int(np.asarray(maxw)) == 3


def test_merge_full_matches_packed_on_low_keys():
    rng = np.random.default_rng(3)
    r = jnp.asarray(rng.integers(0, 1 << 20, size=8192, dtype=np.uint32))
    s = jnp.asarray(rng.integers(0, 1 << 20, size=8192, dtype=np.uint32))
    full, mw_full = merge_count_per_partition_full(
        r, s, 5, return_max_weight=True)
    packed, mw_packed = merge_count_per_partition(
        r, s, 5, impl="xla", return_max_weight=True)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(packed))
    assert int(np.asarray(mw_full)) == int(np.asarray(mw_packed))


def _big_key_batches(n, num_nodes, seed=0):
    """TupleBatch pair with keys above 2**31 and a known match count."""
    rng = np.random.default_rng(seed)
    base = (1 << 31) + 17
    r_keys = base + np.arange(n, dtype=np.uint64) * 7      # distinct
    s_keys = rng.permutation(r_keys)
    s_keys[: n // 2] = 3                                   # half never match
    mk = lambda k: TupleBatch(key=jnp.asarray(k.astype(np.uint32)),
                              rid=jnp.arange(n, dtype=jnp.uint32))
    return mk(r_keys), mk(s_keys), n - n // 2


@pytest.mark.parametrize("nodes,phases", [(1, False), (8, False), (8, True)])
def test_join_arrays_full_routes_and_counts(nodes, phases):
    """key_range='full' joins keys >= 2**31 exactly, on the n==1
    specialization, the fused distributed path, and the split-phase path."""
    r, s, want = _big_key_batches(1 << 12, nodes)
    cfg = JoinConfig(num_nodes=nodes, key_range="full",
                     measure_phases=phases)
    res = HashJoin(cfg).join_arrays(r, s)
    assert res.ok, res.diagnostics
    assert res.matches == want


def test_join_arrays_auto_probes_and_routes():
    """Default key_range='auto' on raw arrays detects big keys via the
    device max probe and still produces the exact count."""
    r, s, want = _big_key_batches(1 << 12, 8, seed=1)
    res = HashJoin(JoinConfig(num_nodes=8)).join_arrays(r, s)
    assert res.ok, res.diagnostics
    assert res.matches == want


def test_join_arrays_narrow_flags_big_keys():
    """Explicit key_range='narrow' keeps the packed fast path and flags —
    never silently drops — out-of-range keys."""
    r, s, _ = _big_key_batches(1 << 10, 1)
    res = HashJoin(JoinConfig(num_nodes=1, key_range="narrow")).join_arrays(r, s)
    assert not res.ok
    assert res.diagnostics["key_contract_violations"] > 0


def test_join_relation_static_bound_routes():
    """join(Relation, Relation) resolves 'auto' statically: a zipf outer
    drawn over a > 2**31 key domain rides the full-range discipline (no
    contract flag), oracle-checked against the host-generated shards."""
    n, nodes = 1 << 12, 8
    inner = Relation(n, nodes, "unique", seed=2)
    outer = Relation(n, nodes, "zipf", seed=5, zipf_theta=0.75,
                     key_domain=(1 << 32) - 64)
    assert outer.key_bound() == (1 << 32) - 64
    assert inner.key_bound() == n
    res = HashJoin(JoinConfig(num_nodes=nodes)).join(inner, outer)
    assert res.ok, res.diagnostics
    o_keys = outer.fill_np(0, n)[0]
    want = int(np.sum(o_keys < n))   # inner is a permutation of [0, n)
    assert res.matches == want


def test_chunked_join_count_full_range():
    """Out-of-core chunked count must route big keys to the full-range
    discipline instead of silently zeroing them on the pack-pads."""
    from tpu_radix_join.ops.chunked import chunked_join_count
    rng = np.random.default_rng(11)
    n = 1 << 12
    r_keys = ((1 << 31) + np.arange(n, dtype=np.uint64) * 5).astype(np.uint32)
    s_keys = rng.permutation(r_keys)
    s_keys[: n // 4] = 1
    mk = lambda k: TupleBatch(key=jnp.asarray(k),
                              rid=jnp.arange(n, dtype=jnp.uint32))
    got = chunked_join_count(mk(r_keys), mk(s_keys), slab_size=1 << 10)
    assert got == n - n // 4


def test_chunked_join_count_sentinel_keys_raise():
    from tpu_radix_join.ops.chunked import chunked_join_count
    n = 256
    keys = np.arange(n, dtype=np.uint32)
    keys[3] = 0xFFFFFFFE
    mk = lambda k: TupleBatch(key=jnp.asarray(k),
                              rid=jnp.arange(n, dtype=jnp.uint32))
    with pytest.raises(ValueError, match="sentinel"):
        chunked_join_count(mk(keys), mk(np.arange(n, dtype=np.uint32)),
                           slab_size=128)


def test_key_range_config_validation():
    with pytest.raises(ValueError, match="key range"):
        JoinConfig(key_range="wat")
    with pytest.raises(ValueError, match="wide"):
        JoinConfig(key_bits=64, key_range="full")


def test_cli_key_range_flag(capsys):
    from tpu_radix_join.main import main
    rc = main(["--tuples-per-node", "1024", "--nodes", "4",
               "--key-range", "full"])
    assert rc == 0
    assert "[RESULTS] Tuples: 4096" in capsys.readouterr().out


@pytest.mark.parametrize("fanout", [0, 1, 5])
def test_merge_full_pallas_matches_xla(fanout):
    """The fused Pallas realization (wide kernel with a zero hi lane) must
    agree exactly with the XLA scan fallback on full-range keys."""
    rng = np.random.default_rng(21 + fanout)
    r = rng.integers(0, 0xFFFFFFFE, size=5000, dtype=np.uint32)
    s = rng.integers(0, 0xFFFFFFFE, size=5000, dtype=np.uint32)
    dup = rng.integers(1 << 31, 0xFFFFFFFD, size=32, dtype=np.uint32)
    r = jnp.asarray(np.concatenate([r, np.repeat(dup, 4)]))
    s = jnp.asarray(np.concatenate([s, np.repeat(dup, 2)]))
    cx, mx = merge_count_per_partition_full(
        r, s, fanout, impl="xla", return_max_weight=True)
    cp, mp = merge_count_per_partition_full(
        r, s, fanout, impl="pallas_interpret", return_max_weight=True)
    np.testing.assert_array_equal(np.asarray(cx), np.asarray(cp))
    assert int(np.asarray(mx)) == int(np.asarray(mp))


def test_full_range_composes_with_skew_split():
    """The skew split (replicated hot inner riding the local probe) must
    stay exact when the probe runs the full-range discipline."""
    n = 1 << 12
    half = n // 2
    big = lambda a: ((1 << 31) + a.astype(np.uint64) * 3).astype(np.uint32)
    r = TupleBatch(key=jnp.asarray(big(np.arange(n))),
                   rid=jnp.arange(n, dtype=jnp.uint32))
    hot = np.concatenate([np.full(half, big(np.array([3]))[0], np.uint32),
                          big(np.arange(half))])
    s = TupleBatch(key=jnp.asarray(hot), rid=jnp.arange(n, dtype=jnp.uint32))
    cfg = JoinConfig(num_nodes=8, skew_threshold=4.0, allocation_factor=4.0,
                     key_range="full")
    res = HashJoin(cfg).join_arrays(r, s)
    assert res.ok, res.diagnostics
    # key 2**31+9 (= big(3)) matches half+1 outer tuples; the other half-1
    # distinct outer keys match once each
    assert res.matches == (half + 1) + (half - 1)


def test_merge_full_inside_shard_map():
    """The full-range count must trace inside a shard_map body — the chip
    pipeline's exact shape (hash_join._local_process).  The portable XLA
    realization is asserted here; interpret-mode Pallas cannot run under
    shard_map at all (the HLO interpreter re-traces kernel-internal
    constants without mesh annotations — a pre-existing property shared by
    EVERY kernel in ops/pallas, asserted below so a JAX upgrade that lifts
    it is noticed), while compiled Pallas traces its kernel outside the
    mesh and is chip-validated (artifacts/chip_r3 ran the packed kernel
    inside the fused shard_map pipeline)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from tpu_radix_join.parallel.mesh import make_mesh

    n_dev, n = 4, 4096
    rng = np.random.default_rng(2)
    r = ((1 << 31) + 3 * np.arange(n, dtype=np.uint64)).astype(np.uint32)
    s = rng.permutation(r)
    mesh = make_mesh(n_dev)

    def body(impl):
        def run(rk, sk):
            c, mw = merge_count_per_partition_full(
                rk, sk, 3, impl=impl, return_max_weight=True)
            return jax.lax.psum(c, "nodes"), jax.lax.pmax(mw, "nodes")
        return jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=(P("nodes"), P("nodes")),
            out_specs=(P(), P())))

    counts, mw = body("xla")(jnp.asarray(r), jnp.asarray(s))
    # keys are globally distinct and both sides shard identically, so each
    # shard-local count sees only its own slice's permuted intersection;
    # the psum total is exactly the number of keys co-resident on a shard
    total = int(np.asarray(counts).astype(np.uint64).sum())
    shard = n // n_dev
    want = sum(
        len(np.intersect1d(r[i * shard:(i + 1) * shard],
                           s[i * shard:(i + 1) * shard]))
        for i in range(n_dev))
    assert total == want, (total, want)
    assert int(np.asarray(mw)) == 1
    from tpu_radix_join.utils import compat
    if not compat.is_legacy():
        # the "varying manual axes" rejection is a current-jax vma check;
        # the legacy shard_map (check_rep=False shim) predates it
        with pytest.raises(ValueError, match="varying manual axes"):
            body("pallas_interpret")(jnp.asarray(r), jnp.asarray(s))


def test_key_boundary_values_exact():
    """Boundary keys around the packing cap and the sentinel floor: every
    sub-sentinel value joins exactly on the full path; the narrow path is
    exact up to MAX_MERGE_KEY inclusive."""
    from tpu_radix_join.ops.merge_count import merge_count_chunks

    edge = np.array([0, 1, MAX_MERGE_KEY - 1, MAX_MERGE_KEY,
                     MAX_MERGE_KEY + 1, 1 << 31, 0xFFFFFFFC, 0xFFFFFFFD],
                    dtype=np.uint32)
    pad = np.arange(100, 100 + 120, dtype=np.uint32)     # fill to size
    keys = np.concatenate([edge, pad])
    # full path: every key matches itself exactly once, in its partition
    c = merge_count_per_partition_full(
        jnp.asarray(keys), jnp.asarray(keys), 3)
    np.testing.assert_array_equal(
        np.asarray(c).astype(np.uint64), _oracle_counts(keys, keys, 3))
    # narrow path on the in-contract prefix only
    ok = keys[keys <= MAX_MERGE_KEY]
    cn = merge_count_chunks(jnp.asarray(ok), jnp.asarray(ok))
    assert int(np.asarray(cn).astype(np.uint64).sum()) == ok.size
