"""Failure diagnostics + detect-and-retry tests: capacity shortfalls are
reported with a structured breakdown and, with max_retries > 0, fixed by
shape respecialization (SURVEY.md §7.4 item 1's detect-and-retry answer to
runtime-sized windows)."""

from tpu_radix_join import HashJoin, JoinConfig, Relation


def _skewed(n=8, size=1 << 13):
    r = Relation(size, n, "unique", seed=1)
    s = Relation(size, n, "zipf", zipf_theta=0.75, key_domain=size, seed=3)
    return r, s


def test_overflow_diagnosed():
    # static sizing with no slack under heavy skew: shuffle blocks overflow
    cfg = JoinConfig(num_nodes=8, window_sizing="static",
                     allocation_factor=1.0)
    r, s = _skewed()
    res = HashJoin(cfg).join(r, s)
    assert not res.ok
    # the zipf outer side is what concentrates on one destination
    assert res.diagnostics["shuffle_overflow_s_tuples"] > 0
    assert res.diagnostics["key_contract_violations"] == 0
    assert res.diagnostics["conservation_violations"] == 0


def test_retry_recovers_exact_count():
    cfg = JoinConfig(num_nodes=8, window_sizing="static",
                     allocation_factor=1.0, max_retries=4)
    r, s = _skewed()
    res = HashJoin(cfg).join(r, s)
    assert res.ok, res.diagnostics
    assert res.matches == (1 << 13)


def test_retry_grows_only_overflowing_window():
    # Side-separated overflow flags (Window.cpp:168-177 sizes each relation's
    # window independently): an S-only overflow must leave the R window alone.
    import jax.numpy as jnp
    import numpy as np
    from tpu_radix_join.data.tuples import TupleBatch
    from tpu_radix_join.performance import Measurements
    n, size = 4, 1 << 12
    cfg = JoinConfig(num_nodes=n, window_sizing="static",
                     allocation_factor=2.0, max_retries=5)
    meas = Measurements(0, n)
    hj = HashJoin(cfg, measurements=meas)
    r = TupleBatch(key=jnp.arange(size, dtype=jnp.uint32),
                   rid=jnp.arange(size, dtype=jnp.uint32))
    # every outer tuple carries ONE key -> one destination block overflows
    s = TupleBatch(key=jnp.zeros(size, jnp.uint32),
                   rid=jnp.arange(size, dtype=jnp.uint32))
    res = hj.join_arrays(r, s)
    assert res.ok, res.diagnostics
    assert res.matches == size       # all of S matches the single key 0 in R
    cap0 = cfg.shuffle_block_capacity(size // n)
    assert meas.counters["WINCAPR"] == cap0      # R window never grew
    assert meas.counters["WINCAPS"] > cap0       # S window did


def test_materialize_rate_cap_retry():
    # inner side repeats each key 4x; cap 1 forces a match-rate retry
    n = 4
    cfg = JoinConfig(num_nodes=n, network_fanout_bits=4, match_rate_cap=1,
                     max_retries=3)
    r = Relation(1 << 12, n, "modulo", modulo=1 << 10)
    s = Relation(1 << 12, n, "unique", seed=5)
    res = HashJoin(cfg).join_materialize(r, s)
    assert res.ok, res.diagnostics
    # outer keys 0..1023 each hit 4 inner duplicates; keys 1024..4095 hit none
    assert res.matches == (1 << 10) * 4


def test_key_contract_violation_not_retried():
    import jax.numpy as jnp
    from tpu_radix_join.data.tuples import TupleBatch
    n = 4
    # key_range="narrow" pins the packed discipline: under the default
    # "auto" these keys now legitimately route to the full-range count
    # (tests/test_full_range.py) and the join simply succeeds
    cfg = JoinConfig(num_nodes=n, max_retries=3, key_range="narrow")
    sz = 1 << 10
    # keys above the merge packing limit violate the narrow input contract
    bad = TupleBatch(key=jnp.full((sz,), 0xF0000000, dtype=jnp.uint32),
                     rid=jnp.arange(sz, dtype=jnp.uint32))
    good = TupleBatch(key=jnp.arange(sz, dtype=jnp.uint32),
                      rid=jnp.arange(sz, dtype=jnp.uint32))
    res = HashJoin(cfg).join_arrays(bad, good)
    assert not res.ok
    assert res.diagnostics["key_contract_violations"] > 0
