"""graftlint framework tests: every rule gets a fire + pass fixture,
the baseline round-trips (suppress / stale / reasonless-rejected), the
CLI honors the 0/1/2 exit contract, and — the gate the rest exists for
— the repo itself lints clean under --strict."""

import json
import os
import textwrap

import pytest

from tpu_radix_join.analysis import (LintError, register_builtin_rules,
                                     run_lint)

register_builtin_rules()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(tmp_path, rel, code, rules, baseline=None):
    """Lint one synthetic file at ``rel`` under a tmp repo root; returns
    (findings-in-that-file, LintResult).  Filtering by path matters for
    counter-tag, whose dead-pin direction reports against regress.py."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(code))
    res = run_lint(str(tmp_path), rule_ids=rules, baseline_path=baseline,
                   paths=[str(path)])
    return [f for f in res.findings if f.path == rel], res


# ------------------------------------------------------------- sort-bypass
def test_sort_bypass_fires_outside_sorting_module(tmp_path):
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py",
                     "import jax.numpy as jnp\n"
                     "def f(x):\n"
                     "    return jnp.argsort(x)\n",
                     ["sort-bypass"])
    assert [f.key for f in found] == ["jnp.argsort"]
    assert found[0].line == 3
    assert found[0].record() == "tpu_radix_join/foo.py:3:sort-bypass"


def test_sort_bypass_allows_sorting_module_and_host_numpy(tmp_path):
    # the switch's own home is the allowed site
    found, _ = _lint(tmp_path, "tpu_radix_join/ops/sorting.py",
                     "import jax.numpy as jnp\n"
                     "def f(x):\n"
                     "    return jnp.argsort(x)\n",
                     ["sort-bypass"])
    assert found == []
    # host numpy is the oracle idiom, never flagged
    found, _ = _lint(tmp_path, "tpu_radix_join/bar.py",
                     "import numpy as np\n"
                     "def f(x):\n"
                     "    return np.argsort(x), np.sort(x), x.argsort()\n",
                     ["sort-bypass"])
    assert [f.key for f in found] == [".argsort()"]   # bare method: unknown
    # receiver rooted at np stays allowed even spelled as a method
    found, _ = _lint(tmp_path, "tpu_radix_join/baz.py",
                     "import numpy as np\n"
                     "def f(h):\n"
                     "    return np.abs(h).argsort()\n",
                     ["sort-bypass"])
    assert found == []


# ------------------------------------------------------------- counter-tag
def test_counter_tag_fires_on_undeclared_tag(tmp_path):
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py",
                     "def f(m):\n"
                     "    m.incr(\"TOTALLYNEWTAG\")\n",
                     ["counter-tag"])
    assert [f.key for f in found] == ["TOTALLYNEWTAG"]


def test_counter_tag_passes_declared_and_neutral_tags(tmp_path):
    # RTUPLES is explicitly neutral; JPROC matches a substring pattern;
    # lower-case names are generic plumbing and skipped
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py",
                     "def f(m, k):\n"
                     "    m.incr(\"RTUPLES\", 4)\n"
                     "    m.start(\"JPROC\")\n"
                     "    m.stop(k)\n",
                     ["counter-tag"])
    assert found == []


def test_counter_tag_reports_dead_pins(tmp_path):
    # with the corpus reduced to one tag-free file, every exact pin is
    # dead — the reverse direction of the cross-check
    _, res = _lint(tmp_path, "tpu_radix_join/foo.py", "x = 1\n",
                   ["counter-tag"])
    dead = [f for f in res.findings
            if f.path == "tpu_radix_join/observability/regress.py"]
    assert dead, "dead-pin direction never fired"
    assert any(f.key == "RTUPLES" for f in dead)


# ----------------------------------------------------------- failure-class
def test_failure_class_fires_on_handrolled_strings(tmp_path):
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py",
                     "def f(g):\n"
                     "    g(failure_class=\"oom\")\n"
                     "    d = {\"failure_class\": \"rank-lost\"}\n"
                     "    d[\"failure_class\"] = \"boom\"\n",
                     ["failure-class"])
    assert sorted(f.key for f in found) == ["boom", "oom", "rank-lost"]


def test_failure_class_passes_taxonomy_members(tmp_path):
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py",
                     "from tpu_radix_join.robustness.retry import RANK_LOST\n"
                     "def f(g, cls):\n"
                     "    g(failure_class=\"rank_lost\")\n"
                     "    g(failure_class=\"unclassified\")\n"
                     "    g(failure_class=RANK_LOST)\n"   # names not checked
                     "    g(failure_class=cls)\n",
                     ["failure-class"])
    assert found == []


# -------------------------------------------------------------- sync-point
def test_sync_point_fires_on_implicit_syncs(tmp_path):
    found, _ = _lint(tmp_path, "tpu_radix_join/ops/chunked.py",
                     "import numpy as np\n"
                     "import jax.numpy as jnp\n"
                     "def f(x):\n"
                     "    a = x.item()\n"
                     "    b = int(jnp.max(x))\n"
                     "    c = np.asarray(x)\n"
                     "    return a, b, c\n",
                     ["sync-point"])
    assert sorted(f.key for f in found) == [".item()", "int(jnp.max)",
                                            "np.asarray"]


def test_sync_point_passes_explicit_and_host_spellings(tmp_path):
    # host_readback is the sanctioned spelling; literal-list asarray is
    # host array building; asarray outside the hot files is unscoped
    found, _ = _lint(tmp_path, "tpu_radix_join/ops/chunked.py",
                     "import numpy as np\n"
                     "from tpu_radix_join.utils.hostsync import "
                     "host_readback\n"
                     "def f(x, n):\n"
                     "    a = int(host_readback(x))\n"
                     "    b = np.asarray([n, n + 1], np.uint32)\n"
                     "    return a, b\n",
                     ["sync-point"])
    assert found == []
    found, _ = _lint(tmp_path, "tpu_radix_join/planner/cold.py",
                     "import numpy as np\n"
                     "def f(x):\n"
                     "    return np.asarray(x)\n",     # not a hot file
                     ["sync-point"])
    assert found == []


# -------------------------------------------------------- recompile-hazard
def test_recompile_hazard_fires(tmp_path):
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py",
                     "import jax, functools\n"
                     "def f(xs, g, n):\n"
                     "    for x in xs:\n"
                     "        jax.jit(g)(x)\n"
                     "    self_key = None\n"
                     "    h = jax.jit(g, static_argnums=tuple(range(n)))\n"
                     "    return h\n"
                     "def k(self, g, cap):\n"
                     "    return self._compile_timed(f\"cap={cap}\", g)\n",
                     ["recompile-hazard"])
    assert sorted(f.key for f in found) == ["dynamic-static_argnums",
                                            "fstring-compile-key",
                                            "jit-in-loop"]


def test_recompile_hazard_passes_hoisted_and_literal(tmp_path):
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py",
                     "import jax\n"
                     "def f(xs, g):\n"
                     "    h = jax.jit(g, static_argnums=(0, 1))\n"
                     "    for x in xs:\n"
                     "        h(x)\n"
                     "    return h\n"
                     "def k(self, g, cap):\n"
                     "    return self._compile_timed((\"probe\", cap), g)\n",
                     ["recompile-hazard"])
    assert found == []


# --------------------------------------------------------- lock-discipline
_THREADED = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def start(self):
            self._t = threading.Thread(target=self._loop)
            self._t.start()

        def _loop(self):
            {body}
"""


def test_lock_discipline_fires_on_unguarded_thread_write(tmp_path):
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py",
                     _THREADED.format(body="self.count += 1"),
                     ["lock-discipline"])
    assert [f.key for f in found] == ["Worker._loop:self.count"]


def test_lock_discipline_passes_guarded_write(tmp_path):
    found, _ = _lint(
        tmp_path, "tpu_radix_join/foo.py",
        _THREADED.format(body="with self._lock:\n"
                              "                self.count += 1"),
        ["lock-discipline"])
    assert found == []


def test_lock_discipline_follows_self_call_closure(tmp_path):
    # the write hides one self-call away from the thread target
    code = _THREADED.format(body="self._step()") + (
        "\n        def _step(self):\n"
        "            self.count += 1\n")
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py", code,
                     ["lock-discipline"])
    assert [f.key for f in found] == ["Worker._step:self.count"]


# ---------------------------------------------------------- inline waivers
def test_waiver_needs_a_reason(tmp_path):
    waived = _THREADED.format(
        body="self.count += 1  # lint: unguarded-ok(one-shot flag; "
             "readers join first)")
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py", waived,
                     ["lock-discipline"])
    assert found == []
    bare = _THREADED.format(body="self.count += 1  # lint: unguarded-ok()")
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py", bare,
                     ["lock-discipline"])
    assert len(found) == 1, "a reasonless waiver must suppress nothing"


# -------------------------------------------------------------- lock-order

_CROSS_CLASS_CYCLE = """
    import threading

    class MetricsSampler:
        def __init__(self):
            self._lock = threading.Lock()
            self._board = LeaseBoard()

        def tick(self):
            with self._lock:
                self._board.heartbeat()   # acquires LeaseBoard._lock

    class LeaseBoard:
        def __init__(self):
            self._lock = threading.Lock()
            self._sampler = MetricsSampler()

        def heartbeat(self):
            with self._lock:
                pass

        def report(self):
            with self._lock:
                self._sampler.tick()      # acquires MetricsSampler._lock
"""


def test_lock_order_fires_on_cross_class_cycle(tmp_path):
    """The known-bad fixture: sampler-tick holds its lock while taking
    the board's; board-report holds its lock while taking the
    sampler's.  Two threads interleaving deadlock — one finding, the
    cycle spelled out."""
    found, _ = _lint(tmp_path, "bad_order.py", _CROSS_CLASS_CYCLE,
                     ["lock-order"])
    assert len(found) == 1
    assert "MetricsSampler._lock" in found[0].message
    assert "LeaseBoard._lock" in found[0].message
    assert found[0].key.startswith("cycle:")


def test_lock_order_fires_on_nested_with_inversion(tmp_path):
    found, _ = _lint(tmp_path, "bad_nested.py", """
        class AdmissionQueue:
            def submit(self):
                with self._lock:
                    with self._brk_lock:
                        pass

            def drain(self):
                with self._brk_lock:
                    with self._lock:
                        pass
        """, ["lock-order"])
    assert len(found) == 1          # one canonical cycle, not one per entry
    assert "deadlock" in found[0].message


def test_lock_order_passes_consistent_global_order(tmp_path):
    # everyone takes _lock before _brk_lock: edges, but no cycle
    found, _ = _lint(tmp_path, "good_order.py", """
        class AdmissionQueue:
            def submit(self):
                with self._lock:
                    with self._brk_lock:
                        pass

            def drain(self):
                with self._lock:
                    with self._brk_lock:
                        pass
        """, ["lock-order"])
    assert found == []


def test_waiver_token_is_rule_specific(tmp_path):
    # a sync waiver does not silence the sort rule
    found, _ = _lint(tmp_path, "tpu_radix_join/foo.py",
                     "import jax.numpy as jnp\n"
                     "def f(x):\n"
                     "    return jnp.argsort(x)  # lint: sync-ok(nope)\n",
                     ["sort-bypass"])
    assert len(found) == 1


# ---------------------------------------------------------------- baseline
def _baseline(tmp_path, entries):
    p = tmp_path / "LINT_BASELINE.json"
    p.write_text(json.dumps({"suppressions": entries}))
    return str(p)


def test_baseline_suppresses_matching_finding(tmp_path):
    bl = _baseline(tmp_path, [{
        "rule": "sort-bypass", "path": "tpu_radix_join/foo.py",
        "key": "jnp.argsort", "reason": "fixture keep"}])
    found, res = _lint(tmp_path, "tpu_radix_join/foo.py",
                       "import jax.numpy as jnp\n"
                       "def f(x):\n"
                       "    return jnp.argsort(x)\n",
                       ["sort-bypass"], baseline=bl)
    assert found == []
    assert len(res.suppressed) == 1 and not res.stale
    assert res.exit_code(strict=True) == 0


def test_baseline_stale_entry_fails_only_under_strict(tmp_path):
    bl = _baseline(tmp_path, [{
        "rule": "sort-bypass", "path": "tpu_radix_join/gone.py",
        "key": "jnp.sort", "reason": "the finding was fixed"}])
    found, res = _lint(tmp_path, "tpu_radix_join/foo.py", "x = 1\n",
                       ["sort-bypass"], baseline=bl)
    assert found == [] and len(res.stale) == 1
    assert res.exit_code(strict=False) == 0
    assert res.exit_code(strict=True) == 1


def test_baseline_stale_check_ignores_rules_that_did_not_run(tmp_path):
    # a sort suppression cannot be judged stale by a sync-only run
    bl = _baseline(tmp_path, [{
        "rule": "sort-bypass", "path": "tpu_radix_join/gone.py",
        "key": "jnp.sort", "reason": "judged only when sort runs"}])
    _, res = _lint(tmp_path, "tpu_radix_join/foo.py", "x = 1\n",
                   ["sync-point"], baseline=bl)
    assert res.stale == []


def test_baseline_reasonless_entry_is_a_load_error(tmp_path):
    bl = _baseline(tmp_path, [{
        "rule": "sort-bypass", "path": "tpu_radix_join/foo.py",
        "key": "jnp.argsort", "reason": "   "}])
    with pytest.raises(LintError):
        _lint(tmp_path, "tpu_radix_join/foo.py", "x = 1\n",
              ["sort-bypass"], baseline=bl)


def test_unknown_rule_id_is_a_lint_error(tmp_path):
    with pytest.raises(LintError):
        run_lint(str(tmp_path), rule_ids=["no-such-rule"], paths=[])


# ------------------------------------------------------------ CLI contract
def test_cli_exit_codes(tmp_path, capsys):
    import tools_lint

    # 0: the repo's own gating invocation
    assert tools_lint.main(["--strict"]) == 0
    # 1: without the baseline the two deliberate sort keeps are live
    assert tools_lint.main(["--no-baseline", "--rule", "sort-bypass"]) == 1
    # 2: usage errors — unknown rule, missing explicit baseline
    assert tools_lint.main(["--rule", "no-such-rule"]) == 2
    assert tools_lint.main(
        ["--baseline", str(tmp_path / "missing.json")]) == 2
    capsys.readouterr()
    # --json writes the regress-gateable counters
    out = tmp_path / "lint.json"
    assert tools_lint.main(["--strict", "--json", str(out)]) == 0
    data = json.loads(out.read_text())
    assert data["lint_findings"] == 0
    assert data["stale_baseline"] == 0
    assert data["suppressed"] >= 2
    capsys.readouterr()


# ------------------------------------------------------------- self-clean
def test_repo_is_lint_clean():
    """The tier-1 gate: every rule over the real tree, baseline applied,
    strict — any new convention violation fails here with its
    path:line:rule record in the assertion message."""
    res = run_lint(REPO_ROOT,
                   baseline_path=os.path.join(REPO_ROOT,
                                              "LINT_BASELINE.json"))
    assert not res.findings, "\n".join(f.render() for f in res.findings)
    assert not res.stale, (
        "stale baseline suppressions (fixed findings must take their "
        f"entries with them): {res.stale}")
