"""Randomized configuration sweep: joins with random geometry, policies,
probe disciplines, key widths, and duplicate distributions must match the
host numpy oracle exactly.  Seeded, so failures reproduce."""

import numpy as np
import pytest

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.data.relation import host_join_count

CASES = list(range(20))


def _random_case(case: int):
    rng = np.random.default_rng(1000 + case)
    nodes = int(rng.choice([1, 2, 4, 8]))
    log_size = int(rng.integers(10, 14))
    size = (1 << log_size)
    kinds = ["unique", "modulo", "zipf"]
    s_kind = kinds[int(rng.integers(0, 3))]
    s_kw = {}
    big_domain = False
    if s_kind == "modulo":
        s_kw["modulo"] = int(rng.integers(1, size))
    elif s_kind == "zipf":
        s_kw["zipf_theta"] = float(rng.uniform(0.2, 1.2))
        # sometimes draw over a > 2**31 key domain: exercises the r5
        # full-range routing (keys above the 31-bit packing) under the oracle
        big_domain = bool(rng.random() < 0.3)
        s_kw["key_domain"] = ((1 << 31) + int(rng.integers(1, 1 << 30))
                              if big_domain else size)
    two_level = bool(rng.integers(0, 2))
    fanout = int(rng.integers(2, 6))
    window = str(rng.choice(["measured", "static"]))
    # optional disciplines, respecting JoinConfig's combination rules
    chunk = None
    if not two_level and rng.random() < 0.3:
        chunk = int(rng.choice([256, 1024]))
    skew = None
    # skew composes with two_level since r4; only chunking excludes it
    if (chunk is None and window == "measured"
            and fanout <= 5 and rng.random() < 0.3):
        skew = float(rng.uniform(1.5, 4.0))
    key_bits = 64 if rng.random() < 0.3 else 32
    # key_range only gates the 32-bit paths; "narrow" would correctly flag
    # (not silently drop) big-domain keys, but the fuzz asserts ok=True, so
    # big domains draw from the routing modes that accept them
    if key_bits == 64:
        key_range = "auto"
    elif big_domain:
        key_range = str(rng.choice(["auto", "full"]))
    else:
        key_range = str(rng.choice(["auto", "narrow", "full"]))
    cfg = JoinConfig(
        num_nodes=nodes,
        network_fanout_bits=fanout,
        local_fanout_bits=int(rng.integers(2, 5)),
        two_level=two_level,
        assignment_policy=str(rng.choice(["round_robin", "load_aware"])),
        window_sizing=window,
        allocation_factor=float(rng.uniform(2.0, 6.0)),
        max_retries=3,
        chunk_size=chunk,
        skew_threshold=skew,
        key_bits=key_bits,
        key_range=key_range,
        measure_phases=bool(rng.random() < 0.3),
    )
    r = Relation(size, nodes, "unique", seed=int(rng.integers(1, 1 << 20)),
                 key_bits=key_bits)
    s = Relation(size, nodes, s_kind, seed=int(rng.integers(1, 1 << 20)),
                 key_bits=key_bits, **s_kw)
    return cfg, r, s


def _host_keys(rel: Relation, nodes: int) -> np.ndarray:
    """Full uint64 key array for the host oracle (wide keys composed)."""
    shards = [rel.shard_np(i) for i in range(nodes)]
    if rel.key_bits == 64:
        return np.concatenate([
            (hi.astype(np.uint64) << np.uint64(32)) | lo
            for lo, hi, _ in shards])
    return np.concatenate([lo for lo, _ in shards]).astype(np.uint64)


@pytest.mark.parametrize("case", CASES)
def test_fuzz_against_host_oracle(case):
    cfg, r, s = _random_case(case)
    res = HashJoin(cfg).join(r, s)
    assert res.ok, (case, cfg, res.diagnostics)
    rk = _host_keys(r, cfg.num_nodes)
    sk = _host_keys(s, cfg.num_nodes)
    assert res.matches == host_join_count(rk, sk), (case, cfg)
