"""Randomized configuration sweep: joins with random geometry, policies,
probe disciplines, and duplicate distributions must match the host numpy
oracle exactly.  Seeded, so failures reproduce."""

import numpy as np
import pytest

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.data.relation import host_join_count

CASES = list(range(10))


def _random_case(case: int):
    rng = np.random.default_rng(1000 + case)
    nodes = int(rng.choice([1, 2, 4, 8]))
    log_size = int(rng.integers(10, 14))
    size = (1 << log_size)
    kinds = ["unique", "modulo", "zipf"]
    s_kind = kinds[int(rng.integers(0, 3))]
    s_kw = {}
    if s_kind == "modulo":
        s_kw["modulo"] = int(rng.integers(1, size))
    elif s_kind == "zipf":
        s_kw["zipf_theta"] = float(rng.uniform(0.2, 1.2))
        s_kw["key_domain"] = size
    cfg = JoinConfig(
        num_nodes=nodes,
        network_fanout_bits=int(rng.integers(2, 6)),
        local_fanout_bits=int(rng.integers(2, 5)),
        two_level=bool(rng.integers(0, 2)),
        assignment_policy=str(rng.choice(["round_robin", "load_aware"])),
        window_sizing=str(rng.choice(["measured", "static"])),
        allocation_factor=float(rng.uniform(2.0, 6.0)),
        max_retries=3,
    )
    r = Relation(size, nodes, "unique", seed=int(rng.integers(1, 1 << 20)))
    s = Relation(size, nodes, s_kind, seed=int(rng.integers(1, 1 << 20)),
                 **s_kw)
    return cfg, r, s


@pytest.mark.parametrize("case", CASES)
def test_fuzz_against_host_oracle(case):
    cfg, r, s = _random_case(case)
    res = HashJoin(cfg).join(r, s)
    assert res.ok, (case, cfg, res.diagnostics)
    rk = np.concatenate([r.shard_np(i)[0] for i in range(cfg.num_nodes)])
    sk = np.concatenate([s.shard_np(i)[0] for i in range(cfg.num_nodes)])
    assert res.matches == host_join_count(rk, sk), (case, cfg)
