import jax
import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.tuples import CompressedBatch, valid_mask
from tpu_radix_join.ops.radix import (
    exclusive_cumsum,
    local_histogram,
    reorder_by_partition,
    scatter_to_blocks,
)


def _comp(keys, rids):
    return CompressedBatch(key_rem=jnp.asarray(keys, jnp.uint32),
                           rid=jnp.asarray(rids, jnp.uint32))


def test_local_histogram_matches_numpy():
    rng = np.random.default_rng(0)
    pid = rng.integers(0, 32, 5000).astype(np.uint32)
    hist = np.asarray(local_histogram(jnp.asarray(pid), 32))
    np.testing.assert_array_equal(hist, np.bincount(pid, minlength=32))


def test_histogram_with_valid_mask():
    pid = jnp.asarray([0, 1, 1, 2], jnp.uint32)
    valid = jnp.asarray([True, False, True, True])
    np.testing.assert_array_equal(
        np.asarray(local_histogram(pid, 4, valid)), [1, 1, 1, 0])


def test_histogram_pallas_matches_xla():
    # interpret-mode parity for the TPU streaming-histogram kernel, the
    # production local_histogram path on real hardware
    rng = np.random.default_rng(3)
    pid = jnp.asarray(rng.integers(0, 32, 70000).astype(np.uint32))
    valid = jnp.asarray(rng.integers(0, 2, 70000).astype(bool))
    for v in (None, valid):
        a = np.asarray(local_histogram(pid, 32, v, impl="xla"))
        b = np.asarray(local_histogram(pid, 32, v, impl="pallas_interpret"))
        np.testing.assert_array_equal(a, b)


def test_histogram_pallas_ignores_out_of_range_ids():
    pid = jnp.asarray([0, 5, 2, 2, 9], jnp.uint32)   # 5, 9 out of range for 4
    got = np.asarray(local_histogram(pid, 4, impl="pallas_interpret"))
    np.testing.assert_array_equal(got, [1, 0, 2, 0])


def test_reorder_groups_partitions():
    rng = np.random.default_rng(1)
    keys = rng.integers(0, 1 << 16, 2000).astype(np.uint32)
    pid = (keys % 8).astype(np.uint32)
    batch = _comp(keys, np.arange(2000))
    out, out_pid, hist, offsets = reorder_by_partition(batch, jnp.asarray(pid), 8)
    out_pid = np.asarray(out_pid)
    assert (np.diff(out_pid) >= 0).all()          # grouped ascending
    np.testing.assert_array_equal(np.asarray(hist), np.bincount(pid, minlength=8))
    np.testing.assert_array_equal(np.asarray(offsets),
                                  np.concatenate([[0], np.cumsum(np.bincount(pid, minlength=8))[:-1]]))
    # same multiset of rids
    np.testing.assert_array_equal(np.sort(np.asarray(out.rid)), np.arange(2000))


def test_scatter_to_blocks_conservation():
    rng = np.random.default_rng(2)
    n = 1000
    keys = rng.integers(0, 1 << 20, n).astype(np.uint32)
    dest = rng.integers(0, 4, n).astype(np.uint32)
    batch = _comp(keys, np.arange(n))
    cap = 400
    blocks, counts, overflow = scatter_to_blocks(batch, jnp.asarray(dest), 4, cap, "inner")
    assert int(overflow) == 0
    np.testing.assert_array_equal(np.asarray(counts), np.bincount(dest, minlength=4))
    vm = np.asarray(valid_mask(blocks, "inner")).reshape(4, cap)
    np.testing.assert_array_equal(vm.sum(axis=1), np.bincount(dest, minlength=4))
    # every block's valid slots hold exactly the tuples destined to it
    brid = np.asarray(blocks.rid).reshape(4, cap)
    for d in range(4):
        got = np.sort(brid[d][vm[d]])
        np.testing.assert_array_equal(got, np.sort(np.arange(n)[dest == d]))


def test_scatter_overflow_detected():
    batch = _comp(np.arange(100), np.arange(100))
    dest = jnp.zeros(100, jnp.uint32)
    blocks, counts, overflow = scatter_to_blocks(batch, dest, 2, 64, "outer")
    assert int(overflow) == 100 - 64
    assert int(counts[0]) == 100   # unclipped demand


def test_exclusive_cumsum():
    h = jnp.asarray([3, 0, 2, 5], jnp.uint32)
    np.testing.assert_array_equal(np.asarray(exclusive_cumsum(h)), [0, 3, 3, 5])


def test_scatter_impls_identical():
    """The "gather" one-shot discipline must produce byte-identical blocks
    to the "loop" DMA discipline for every shape class — full, partial,
    empty, and overflowing destinations (exp_block_scatter.py measures which
    wins on chip; correctness is pinned here)."""
    rng = np.random.default_rng(5)
    n = 5000
    keys = rng.integers(0, 1 << 20, n).astype(np.uint32)
    # destination 3 empty, destination 0 overflowing
    dest = rng.choice(np.array([0, 0, 0, 1, 2, 4, 5], np.uint32), n)
    batch = _comp(keys, np.arange(n))
    valid = jnp.asarray(rng.random(n) < 0.9)
    for cap in (512, 2048):
        a = scatter_to_blocks(batch, jnp.asarray(dest), 6, cap, "inner",
                              valid=valid, impl="loop")
        b = scatter_to_blocks(batch, jnp.asarray(dest), 6, cap, "inner",
                              valid=valid, impl="gather")
        for la, lb in zip(jax.tree.leaves(a[0]), jax.tree.leaves(b[0])):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))
        assert int(a[2]) == int(b[2])
