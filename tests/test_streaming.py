"""Streaming loader tests: pool-backed double-buffered chunk generation must
reproduce shard_np exactly (buffer reuse can't corrupt in-flight chunks) and
feed the grid-chunked join to the oracle count."""

import numpy as np

from tpu_radix_join.data.relation import Relation
from tpu_radix_join.data.streaming import stream_chunks
from tpu_radix_join.memory.pool import Pool
from tpu_radix_join.ops.chunked import chunked_join_grid


def _concat(chunks):
    ks, rs = [], []
    for b in chunks:
        ks.append(np.asarray(b.key))
        rs.append(np.asarray(b.rid))
    return np.concatenate(ks), np.concatenate(rs)


def test_stream_equals_shard():
    rel = Relation(1 << 14, 2, "unique", seed=5)
    for chunk in (1 << 10, 1500):      # dividing and ragged chunk sizes
        key, rid = _concat(stream_chunks(rel, 1, chunk))
        ref_key, ref_rid = rel.shard_np(1)
        np.testing.assert_array_equal(key, ref_key)
        np.testing.assert_array_equal(rid, ref_rid)


def test_stream_zipf_and_modulo():
    for rel in (Relation(1 << 13, 1, "zipf", zipf_theta=0.75,
                         key_domain=1 << 13, seed=3),
                Relation(1 << 13, 1, "modulo", modulo=257)):
        key, rid = _concat(stream_chunks(rel, 0, 1000))
        ref_key, ref_rid = rel.shard_np(0)
        np.testing.assert_array_equal(key, ref_key)
        np.testing.assert_array_equal(rid, ref_rid)


def test_stream_bounded_pool():
    rel = Relation(1 << 14, 1, "unique", seed=5)
    chunk = 1 << 10
    pool = Pool(2 * 2 * chunk * 4 + 4 * 64)
    list(stream_chunks(rel, 0, chunk, pool=pool))
    # only the two double-buffer pairs were ever allocated
    assert pool.used() <= 2 * 2 * chunk * 4 + 4 * 64
    pool.close()


def test_streamed_grid_join_oracle():
    size = 1 << 13
    r = Relation(size, 1, "unique", seed=1)
    s = Relation(size, 1, "unique", seed=2)
    total = chunked_join_grid(
        list(stream_chunks(r, 0, size)),        # inner resident (one chunk)
        list(stream_chunks(s, 0, 1 << 11)),     # outer streamed
        slab_size=1 << 10)
    assert total == size


def test_streamed_grid_join_factory_ragged():
    """Factory form: outer re-streamed per inner chunk (O(chunk) device
    memory) with ragged chunk and slab sizes."""
    size = 1 << 13
    r = Relation(size, 1, "unique", seed=1)
    s = Relation(size, 1, "unique", seed=2)
    total = chunked_join_grid(
        list(stream_chunks(r, 0, 3000)),              # ragged inner chunks
        lambda: stream_chunks(s, 0, 1500),            # ragged outer, factory
        slab_size=1024)                               # non-dividing slab
    assert total == size


def test_stream_chunks_device_matches_host():
    """stream_chunks_device is bit-identical to the host stream for every
    supported kind x width (chunk boundaries ragged on purpose)."""
    import pytest

    from tpu_radix_join.data.streaming import stream_chunks_device

    cases = [
        Relation(1 << 12, 2, "unique", seed=51),
        Relation(1 << 12, 2, "unique", seed=52, key_bits=64),
        Relation(1 << 12, 2, "modulo", seed=53, modulo=300),
        Relation(1 << 12, 2, "modulo", seed=54, modulo=300, key_bits=64),
    ]
    for rel in cases:
        for node in range(2):
            host = list(stream_chunks(rel, node, 700))
            dev = list(stream_chunks_device(rel, node, 700))
            assert len(host) == len(dev)
            for h, d in zip(host, dev):
                np.testing.assert_array_equal(np.asarray(d.key),
                                              np.asarray(h.key))
                np.testing.assert_array_equal(np.asarray(d.rid),
                                              np.asarray(h.rid))
                if rel.key_bits == 64:
                    np.testing.assert_array_equal(np.asarray(d.key_hi),
                                                  np.asarray(h.key_hi))
    # zipf streams device-generated too (r4 integer-table sampler),
    # bit-identical to the host stream across ragged chunk boundaries
    zrel = Relation(1 << 12, 1, "zipf", zipf_theta=0.8, seed=55)
    for h, d in zip(stream_chunks(zrel, 0, 700),
                    stream_chunks_device(zrel, 0, 700)):
        np.testing.assert_array_equal(np.asarray(d.key), np.asarray(h.key))
        np.testing.assert_array_equal(np.asarray(d.rid), np.asarray(h.rid))


def test_device_streamed_grid_join_oracle():
    """Both sides device-generated end to end through the grid join."""
    from tpu_radix_join.data.streaming import stream_chunks_device

    size = 1 << 13
    r = Relation(size, 1, "unique", seed=1)
    s = Relation(size, 1, "unique", seed=2)
    total = chunked_join_grid(
        list(stream_chunks_device(r, 0, 3000)),
        lambda: stream_chunks_device(s, 0, 1500),
        slab_size=1024)
    assert total == size
