"""Unit tests for the service building blocks (no engine, no devices):
deadlines, admission control, the circuit breaker, SLO percentiles, the
shared retryability predicate, and the regress gate's direction pins.
The engine-integrated serve tests live in tests/test_serve.py.
"""

import pytest

from tpu_radix_join.core.config import ServiceConfig
from tpu_radix_join.observability.regress import higher_is_better
from tpu_radix_join.robustness.retry import (ADMISSION_REJECTED,
                                             BACKEND_UNAVAILABLE,
                                             CAPACITY_OVERFLOW,
                                             COORDINATOR_TIMEOUT,
                                             DATA_CORRUPTION,
                                             DEADLINE_EXCEEDED, KEY_CONTRACT,
                                             RETRYABLE_SIZING, RetryPolicy,
                                             is_retryable_class)
from tpu_radix_join.service import (CLOSED, HALF_OPEN, OPEN, AdmissionQueue,
                                    AdmissionRejected, CircuitBreaker,
                                    Deadline, DeadlineExceeded, SLORecorder,
                                    nearest_rank)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class _Req:
    def __init__(self, tenant="default", query_id="q"):
        self.tenant = tenant
        self.query_id = query_id


# ---------------------------------------------------------------- deadlines

def test_deadline_expires_with_fake_clock():
    clock = FakeClock()
    d = Deadline(1.0, clock=clock)
    d.check("early")                       # within budget: no raise
    clock.advance(0.5)
    assert d.remaining_s() == pytest.approx(0.5)
    clock.advance(0.6)
    with pytest.raises(DeadlineExceeded) as ei:
        d.check("probe")
    assert ei.value.failure_class == DEADLINE_EXCEEDED
    assert ei.value.phase == "probe"
    assert ei.value.elapsed_s == pytest.approx(1.1)


def test_deadline_unlimited_never_expires():
    clock = FakeClock()
    d = Deadline(None, clock=clock)
    clock.advance(1e9)
    d.check("whenever")
    assert not d.expired()
    assert d.remaining_s() is None
    Deadline.unlimited().check()


def test_deadline_rejects_negative_budget():
    with pytest.raises(ValueError):
        Deadline(-1.0)


# ---------------------------------------------------------------- admission

def test_admission_queue_full_rejects_classified():
    q = AdmissionQueue(max_depth=2, tenant_quota=8)
    q.submit(_Req())
    q.submit(_Req())
    with pytest.raises(AdmissionRejected) as ei:
        q.submit(_Req())
    assert ei.value.failure_class == ADMISSION_REJECTED
    assert ei.value.reason == "queue_full"
    assert q.rejected == 1 and q.admitted == 2


def test_admission_tenant_quota_isolates_noisy_neighbor():
    q = AdmissionQueue(max_depth=16, tenant_quota=2)
    q.submit(_Req("noisy"))
    q.submit(_Req("noisy"))
    with pytest.raises(AdmissionRejected) as ei:
        q.submit(_Req("noisy"))
    assert ei.value.reason == "tenant_quota"
    q.submit(_Req("quiet"))                # the quiet tenant still admits


def test_admission_quota_covers_in_flight_not_just_queued():
    q = AdmissionQueue(max_depth=16, tenant_quota=1)
    r = _Req("t")
    q.submit(r)
    popped = q.pop()
    assert popped is r and q.depth() == 0
    # popped but not done: still counts against the tenant
    with pytest.raises(AdmissionRejected):
        q.submit(_Req("t"))
    q.done(r)
    q.submit(_Req("t"))
    assert q.rejection_rate() == pytest.approx(1 / 3)


def test_admission_queue_validates_bounds():
    with pytest.raises(ValueError):
        AdmissionQueue(max_depth=0)
    with pytest.raises(ValueError):
        AdmissionQueue(tenant_quota=0)


# ------------------------------------------------------------------ breaker

def test_breaker_trips_on_consecutive_failures_only():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=3, cooldown_s=10.0, clock=clock)
    for _ in range(2):
        b.record_failure(BACKEND_UNAVAILABLE)
    b.record_success()                     # streak broken
    for _ in range(2):
        b.record_failure(BACKEND_UNAVAILABLE)
    assert b.state == CLOSED
    assert b.record_failure(BACKEND_UNAVAILABLE) is True
    assert b.state == OPEN and b.trips == 1


def test_breaker_nontripping_classes_reset_streak():
    b = CircuitBreaker(failure_threshold=2, cooldown_s=10.0,
                       clock=FakeClock())
    b.record_failure(BACKEND_UNAVAILABLE)
    b.record_failure(CAPACITY_OVERFLOW)    # query's fault, not the backend's
    b.record_failure(BACKEND_UNAVAILABLE)
    assert b.state == CLOSED
    b.record_failure(DATA_CORRUPTION)
    b.record_failure(DEADLINE_EXCEEDED)
    assert b.state == CLOSED and b.trips == 0


def test_breaker_open_half_open_closed_cycle():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, cooldown_s=5.0, clock=clock)
    b.record_failure(BACKEND_UNAVAILABLE)
    assert b.state == OPEN
    assert b.allow_primary() is False      # cooling down: degraded serving
    clock.advance(5.1)
    assert b.allow_primary() is True       # the half-open health probe
    assert b.state == HALF_OPEN and b.probes == 1
    b.record_success()
    assert b.state == CLOSED


def test_breaker_failed_probe_reopens():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, cooldown_s=5.0, clock=clock)
    b.record_failure(BACKEND_UNAVAILABLE)
    clock.advance(5.1)
    assert b.allow_primary() is True
    assert b.record_failure(BACKEND_UNAVAILABLE) is True   # probe failed
    assert b.state == OPEN and b.trips == 2
    assert b.allow_primary() is False      # cooldown restarted


# ---------------------------------------------------------------------- slo

def test_nearest_rank_is_an_observed_sample():
    vals = [1.0, 2.0, 3.0, 4.0, 5.0]
    assert nearest_rank(vals, 50) == 3.0
    assert nearest_rank(vals, 99) == 5.0
    assert nearest_rank([7.0], 50) == 7.0
    with pytest.raises(ValueError):
        nearest_rank([], 50)


def test_slo_snapshot_rates_and_per_tenant_percentiles():
    s = SLORecorder()
    for ms in (10.0, 20.0, 30.0):
        s.record("a", ms, ok=True)
    s.record("b", 100.0, ok=False, failure_class=DEADLINE_EXCEEDED)
    s.record("b", 50.0, ok=True, degraded=True)
    s.record_rejection()
    snap = s.snapshot()
    assert snap["queries_submitted"] == 6
    assert snap["queries_ok"] == 4 and snap["queries_failed"] == 1
    assert snap["admission_rejection_rate"] == pytest.approx(1 / 6, abs=1e-3)
    assert snap["deadline_miss_rate"] == pytest.approx(1 / 6, abs=1e-3)
    assert snap["degraded_rate"] == pytest.approx(1 / 6, abs=1e-3)
    assert snap["slo_p50_ms"] == 30.0          # 5 samples, nearest-rank
    assert snap["slo_a_p99_ms"] == 30.0
    assert snap["slo_b_p50_ms"] == 50.0
    assert snap["slo_b_p99_ms"] == 100.0


def test_slo_empty_snapshot_has_no_percentiles():
    snap = SLORecorder().snapshot()
    assert snap["queries_submitted"] == 0
    assert "slo_p50_ms" not in snap


# -------------------------------------------------- retryability predicate

def test_retryable_default_policy_covers_transients():
    assert is_retryable_class(CAPACITY_OVERFLOW)
    assert is_retryable_class(BACKEND_UNAVAILABLE)
    assert is_retryable_class(COORDINATOR_TIMEOUT)
    assert not is_retryable_class(KEY_CONTRACT)
    assert not is_retryable_class(DATA_CORRUPTION)
    assert not is_retryable_class(ADMISSION_REJECTED)
    assert not is_retryable_class(DEADLINE_EXCEEDED)


def test_retryable_policy_narrows_the_predicate():
    sizing = RetryPolicy(retryable_classes=RETRYABLE_SIZING)
    # the engine's capacity-regrow loop must NOT spin on a tunnel outage
    assert is_retryable_class(CAPACITY_OVERFLOW, sizing)
    assert not is_retryable_class(BACKEND_UNAVAILABLE, sizing)
    custom = RetryPolicy(retryable_classes=frozenset({KEY_CONTRACT}))
    assert is_retryable_class(KEY_CONTRACT, custom)
    assert not is_retryable_class(CAPACITY_OVERFLOW, custom)


# ----------------------------------------------------------- service config

def test_service_config_validates_and_replaces():
    svc = ServiceConfig()
    assert svc.max_queue_depth == 64 and svc.breaker_threshold == 3
    narrowed = svc.replace(tenant_quota=2, default_deadline_s=1.5)
    assert narrowed.tenant_quota == 2
    assert narrowed.default_deadline_s == 1.5
    with pytest.raises(ValueError):
        ServiceConfig(max_queue_depth=0)
    with pytest.raises(ValueError):
        ServiceConfig(breaker_cooldown_s=-1.0)
    with pytest.raises(ValueError):
        ServiceConfig(default_deadline_s=-0.1)


# ------------------------------------------------- regress direction pins

def test_regress_direction_slo_tags_are_lower_better():
    # "rate" normally marks a throughput, but MORE rejections is worse:
    # the lower-better override must win the substring scan
    assert not higher_is_better("admission_rejection_rate")
    assert not higher_is_better("deadline_miss_rate")
    assert not higher_is_better("degraded_rate")
    assert not higher_is_better("slo_p99_ms")
    assert not higher_is_better("warm_latency_p50_ms")
    # and the existing vocabulary keeps its direction
    assert higher_is_better("JRATE")
    assert higher_is_better("warm_speedup")
    assert higher_is_better("value")
