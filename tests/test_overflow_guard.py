"""uint32 count-overflow guard (VERDICT r3 weak #4).

The per-partition count contract ("each partition's count stays < 2**32",
operators/hash_join.py module docstring) is now enforced at runtime: the
probe returns its max single-outer-tuple match weight, and the pipeline
bounds every partition's count by max_weight x outer tuples — flagging
``count_overflow_risk`` (ok=False) whenever the bound can reach 2**32.
The reference cannot wrap by construction (uint64 RESULT_COUNTER,
operators/HashJoin.h:26); these tests prove this framework can no longer
wrap silently either.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.data.relation import host_join_count
from tpu_radix_join.data.tuples import TupleBatch


def _const_batch(n: int, key: int = 0) -> TupleBatch:
    return TupleBatch(key=jnp.full((n,), key, jnp.uint32),
                      rid=jnp.arange(n, dtype=jnp.uint32))


def test_deliberate_wrap_flags_not_ok_single_node():
    """2**16 copies of one key on BOTH sides: the true count is 2**32, which
    wraps a uint32 accumulator to 0 — before the guard this returned
    matches=0 with ok=True.  Now ok must be False with the risk flagged."""
    n = 1 << 16
    res = HashJoin(JoinConfig(num_nodes=1)).join_arrays(
        _const_batch(n), _const_batch(n))
    assert not res.ok
    assert res.diagnostics["count_overflow_risk"] > 0


def test_deliberate_wrap_flags_not_ok_distributed():
    """Same wrap class through the full shuffle pipeline (4 nodes): every
    tuple routes to one partition owner whose uint32 count wraps."""
    n = 1 << 16
    cfg = JoinConfig(num_nodes=4, max_retries=2)
    res = HashJoin(cfg).join_arrays(_const_batch(n), _const_batch(n))
    assert not res.ok
    assert res.diagnostics["count_overflow_risk"] > 0


def test_high_multiplicity_below_bound_stays_ok():
    """Duplicate-heavy inner side whose worst partition bound stays under
    2**32 must join exactly (no false flag on legitimate workloads)."""
    size = 1 << 12
    r = Relation(size, 1, "modulo", modulo=16, seed=3)   # multiplicity 256
    s = Relation(size, 1, "unique", seed=4)
    res = HashJoin(JoinConfig(num_nodes=1)).join(r, s)
    assert res.ok, res.diagnostics
    rk = np.concatenate([sh[0] for sh in [r.shard_np(0)]]).astype(np.uint64)
    sk = np.concatenate([sh[0] for sh in [s.shard_np(0)]]).astype(np.uint64)
    assert res.matches == host_join_count(rk, sk)


def test_chunked_join_count_raises_on_window_risk():
    """The out-of-core counter's accumulation windows are guarded too: a
    hot inner key whose multiplicity x window width can reach 2**32 raises
    instead of returning a silently wrapped total."""
    from tpu_radix_join.ops.chunked import chunked_join_count
    n = 1 << 16
    r = _const_batch(n)
    s = _const_batch(n)
    with pytest.raises(OverflowError):
        chunked_join_count(r, s, slab_size=n)


@pytest.mark.parametrize("case", range(6))
def test_fuzz_modulo_inner_against_host_oracle(case):
    """Randomized sweep with a DUPLICATE-HEAVY INNER side (the class the
    round-3 fuzz could never hit: its inner was always unique, multiplicity
    1) across probe disciplines; counts must match the host oracle exactly
    and ok must hold (bounds all well below 2**32 at these sizes)."""
    rng = np.random.default_rng(7000 + case)
    nodes = int(rng.choice([1, 2, 4]))
    size = 1 << int(rng.integers(10, 13))
    modulo = int(rng.integers(1, max(2, size // 8)))
    two_level = bool(rng.integers(0, 2))
    chunk = None
    if not two_level and rng.random() < 0.4:
        chunk = int(rng.choice([256, 1024]))
    key_bits = 64 if rng.random() < 0.3 else 32
    cfg = JoinConfig(
        num_nodes=nodes,
        network_fanout_bits=int(rng.integers(2, 6)),
        local_fanout_bits=int(rng.integers(2, 5)),
        two_level=two_level,
        chunk_size=chunk,
        allocation_factor=float(rng.uniform(2.0, 6.0)),
        max_retries=3,
        key_bits=key_bits,
        measure_phases=bool(rng.random() < 0.3),
    )
    r = Relation(size, nodes, "modulo", modulo=modulo,
                 seed=int(rng.integers(1, 1 << 20)), key_bits=key_bits)
    s_kind = str(rng.choice(["unique", "modulo"]))
    s_kw = {"modulo": int(rng.integers(1, size))} if s_kind == "modulo" else {}
    s = Relation(size, nodes, s_kind, seed=int(rng.integers(1, 1 << 20)),
                 key_bits=key_bits, **s_kw)

    def host_keys(rel):
        shards = [rel.shard_np(i) for i in range(nodes)]
        if key_bits == 64:
            return np.concatenate([
                (hi.astype(np.uint64) << np.uint64(32)) | lo
                for lo, hi, _ in shards])
        return np.concatenate([lo for lo, _ in shards]).astype(np.uint64)

    res = HashJoin(cfg).join(r, s)
    assert res.ok, (case, cfg, res.diagnostics)
    assert res.matches == host_join_count(host_keys(r), host_keys(s)), \
        (case, cfg)
