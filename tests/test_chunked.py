import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.relation import Relation, host_join_count
from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.ops.chunked import chunked_join_count, chunked_join_grid


def _batch(keys):
    keys = np.asarray(keys, np.uint32)
    return TupleBatch(key=jnp.asarray(keys),
                      rid=jnp.arange(len(keys), dtype=jnp.uint32))


def test_chunked_matches_monolithic():
    rng = np.random.default_rng(0)
    r = rng.integers(0, 4096, 1 << 14).astype(np.uint32)
    s = rng.integers(0, 4096, 1 << 14).astype(np.uint32)
    expect = host_join_count(r, s)
    for slab in (1 << 14, 1 << 12, 1 << 10):
        assert chunked_join_count(_batch(r), _batch(s), slab) == expect


def test_chunked_grid_both_sides():
    rng = np.random.default_rng(1)
    r = rng.integers(0, 1024, 1 << 12).astype(np.uint32)
    s = rng.integers(0, 1024, 1 << 12).astype(np.uint32)
    expect = host_join_count(r, s)
    r_chunks = [_batch(r[:1 << 11]), _batch(r[1 << 11:])]
    s_chunks = [_batch(s[:1 << 11]), _batch(s[1 << 11:])]
    assert chunked_join_grid(r_chunks, s_chunks, 1 << 10) == expect


def test_chunked_indivisible_slab_padded():
    # ragged outer sizes are sentinel-padded to a slab multiple, not rejected
    assert chunked_join_count(_batch([1, 2, 3]), _batch([1, 2, 3]), 2) == 3


def test_chunked_unique_oracle():
    rel_r = Relation(1 << 14, 1, "unique", seed=1)
    rel_s = Relation(1 << 14, 1, "unique", seed=2)
    r, s = rel_r.shard(0), rel_s.shard(0)
    assert chunked_join_count(r, s, 1 << 11) == 1 << 14


def test_grid_checkpoint_resume(tmp_path):
    """Interrupt after two chunk pairs; the rerun must skip completed work
    and land on the exact total (SURVEY.md §5.4 — resume is new capability,
    the reference is single-shot)."""
    import json

    rel_r = Relation(1 << 12, 1, "unique", seed=1)
    rel_s = Relation(1 << 12, 1, "unique", seed=2)
    r, s = rel_r.shard(0), rel_s.shard(0)

    def halves(batch):
        n = batch.key.shape[0] // 2
        return [TupleBatch(key=batch.key[:n], rid=batch.rid[:n]),
                TupleBatch(key=batch.key[n:], rid=batch.rid[n:])]

    ckpt = str(tmp_path / "grid.ckpt")
    calls = {"n": 0}
    real = chunked_join_count

    def failing(rb, sb, slab, **kw):
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("simulated preemption")
        return real(rb, sb, slab, **kw)

    import tpu_radix_join.ops.chunked as C
    C.chunked_join_count, orig = failing, C.chunked_join_count
    try:
        import pytest
        with pytest.raises(RuntimeError):
            chunked_join_grid(halves(r), halves(s), 1 << 10,
                              checkpoint_path=ckpt, checkpoint_tag="t")
    finally:
        C.chunked_join_count = orig
    state = json.load(open(ckpt))
    assert not state["done"] and state["total"] > 0

    total = chunked_join_grid(halves(r), halves(s), 1 << 10,
                              checkpoint_path=ckpt, checkpoint_tag="t")
    assert total == 1 << 12
    assert json.load(open(ckpt))["done"]
    # a third run short-circuits on the done marker (same fingerprint)
    assert chunked_join_grid(halves(r), halves(s), 1 << 10,
                             checkpoint_path=ckpt, checkpoint_tag="t") == total
    # different geometry, tag, or an untagged call must refuse the file
    import pytest
    with pytest.raises(ValueError):
        chunked_join_grid(halves(r), halves(s), 1 << 9,
                          checkpoint_path=ckpt, checkpoint_tag="t")
    with pytest.raises(ValueError):
        chunked_join_grid(halves(r), halves(s), 1 << 10,
                          checkpoint_path=ckpt, checkpoint_tag="other-data")
    with pytest.raises(ValueError):
        chunked_join_grid(halves(r), halves(s), 1 << 10,
                          checkpoint_path=ckpt)
    # corrupt checkpoint: restart from zero, exact result
    with open(ckpt, "w") as f:
        f.write("{trunca")
    assert chunked_join_grid(halves(r), halves(s), 1 << 10,
                             checkpoint_path=ckpt, checkpoint_tag="t") == total


def test_grid_join_wide_streamed_chunks():
    """A Relation(key_bits=64) stream through chunked_join_grid counts on
    the full (hi, lo) key — the streaming/out-of-core path must not quietly
    drop the hi lane the way round 2's driver path did."""
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.data.streaming import stream_chunks
    from tpu_radix_join.ops.chunked import chunked_join_count, chunked_join_grid

    r_rel = Relation(1 << 11, 1, "unique", seed=41, key_bits=64)
    s_rel = Relation(1 << 11, 1, "modulo", modulo=1 << 10, seed=42,
                     key_bits=64)
    total = chunked_join_grid(
        list(stream_chunks(r_rel, 0, 600)),
        lambda: stream_chunks(s_rel, 0, 700),
        slab_size=256)
    # oracle: every modulo key < 2**10 matches exactly one unique key
    assert total == s_rel.global_size

    # mixed widths must raise, not truncate
    import pytest
    narrow = Relation(1 << 10, 1, "unique", seed=1)
    wide = Relation(1 << 10, 1, "unique", seed=2, key_bits=64)
    nb = next(iter(stream_chunks(narrow, 0, 1 << 10)))
    wb = next(iter(stream_chunks(wide, 0, 1 << 10)))
    with pytest.raises(ValueError, match="mixed key widths"):
        chunked_join_count(wb, nb, 128)


def test_grid_pauses_on_bench_flag(tmp_path, monkeypatch, capsys):
    """The grid must park between chunk pairs while the bench's pause file
    exists, and resume when it disappears (cooperative single-chip yield)."""
    import threading
    import time as _t

    import jax.numpy as jnp

    from tpu_radix_join.data.tuples import TupleBatch
    from tpu_radix_join.ops.chunked import chunked_join_grid

    flag = tmp_path / "BENCH_RUNNING"
    flag.write_text("x")
    monkeypatch.setenv("TPU_RJ_PAUSE_FILE", str(flag))
    n = 1 << 10
    mk = lambda seed: TupleBatch(
        key=jnp.asarray(np.random.default_rng(seed).permutation(n)
                        .astype(np.uint32)),
        rid=jnp.arange(n, dtype=jnp.uint32))
    chunks = [mk(1), mk(1)]
    threading.Timer(3.0, flag.unlink).start()
    t0 = _t.perf_counter()
    total = chunked_join_grid([chunks[0]], [chunks[1]], slab_size=n)
    waited = _t.perf_counter() - t0
    assert total == n                      # identical permutations join fully
    assert waited >= 2.5, waited           # actually parked on the flag
    out = capsys.readouterr().out
    assert "paused" in out and "resumed" in out


def test_grid_ignores_dead_bench_and_marks_parked(tmp_path, monkeypatch):
    """PID liveness (r5 review): a pause file stamped by a dead process is
    removed and ignored; while parked on a LIVE bench the grid advertises
    GRID_RUNNING + .parked so the bench can skip its drain wait."""
    import os
    import subprocess
    import threading
    import time as _t

    import jax.numpy as jnp

    from tpu_radix_join.data.tuples import TupleBatch
    from tpu_radix_join.ops.chunked import chunked_join_grid

    n = 1 << 10
    mk = lambda s: TupleBatch(
        key=jnp.asarray(np.random.default_rng(s).permutation(n)
                        .astype(np.uint32)),
        rid=jnp.arange(n, dtype=jnp.uint32))

    # warm the jit for these shapes so the timed region below measures the
    # park behavior, not first-call compilation
    from tpu_radix_join.ops.chunked import chunked_join_count
    chunked_join_count(mk(9), mk(9), n)

    # 1) dead-PID pause file: grid must remove it and run immediately
    proc = subprocess.Popen(["true"])
    proc.wait()
    pause = tmp_path / "BENCH_RUNNING"
    pause.write_text(str(proc.pid))
    monkeypatch.setenv("TPU_RJ_PAUSE_FILE", str(pause))
    grid_f = tmp_path / "GRID_RUNNING"
    monkeypatch.setenv("TPU_RJ_GRID_FILE", str(grid_f))
    t0 = _t.perf_counter()
    assert chunked_join_grid([mk(1)], [mk(1)], slab_size=n) == n
    assert _t.perf_counter() - t0 < 4.0    # no 5s park cycle
    assert not pause.exists()              # dead holder's file removed
    assert not grid_f.exists() and not (tmp_path / "GRID_RUNNING.parked").exists()

    # 2) live-PID pause file: grid parks, advertises .parked, resumes
    pause.write_text(str(os.getpid()))
    seen = {}

    def observe_then_release():
        _t.sleep(2.5)
        seen["grid"] = grid_f.exists()
        seen["parked"] = (tmp_path / "GRID_RUNNING.parked").exists()
        pause.unlink()

    threading.Thread(target=observe_then_release).start()
    assert chunked_join_grid([mk(2)], [mk(2)], slab_size=n) == n
    assert seen == {"grid": True, "parked": True}, seen
    assert not grid_f.exists()             # presence cleaned up on exit
    assert not (tmp_path / "GRID_RUNNING.parked").exists()
