import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.relation import Relation, host_join_count
from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.ops.chunked import chunked_join_count, chunked_join_grid


def _batch(keys):
    keys = np.asarray(keys, np.uint32)
    return TupleBatch(key=jnp.asarray(keys),
                      rid=jnp.arange(len(keys), dtype=jnp.uint32))


def test_chunked_matches_monolithic():
    rng = np.random.default_rng(0)
    r = rng.integers(0, 4096, 1 << 14).astype(np.uint32)
    s = rng.integers(0, 4096, 1 << 14).astype(np.uint32)
    expect = host_join_count(r, s)
    for slab in (1 << 14, 1 << 12, 1 << 10):
        assert chunked_join_count(_batch(r), _batch(s), slab) == expect


def test_chunked_grid_both_sides():
    rng = np.random.default_rng(1)
    r = rng.integers(0, 1024, 1 << 12).astype(np.uint32)
    s = rng.integers(0, 1024, 1 << 12).astype(np.uint32)
    expect = host_join_count(r, s)
    r_chunks = [_batch(r[:1 << 11]), _batch(r[1 << 11:])]
    s_chunks = [_batch(s[:1 << 11]), _batch(s[1 << 11:])]
    assert chunked_join_grid(r_chunks, s_chunks, 1 << 10) == expect


def test_chunked_indivisible_slab_padded():
    # ragged outer sizes are sentinel-padded to a slab multiple, not rejected
    assert chunked_join_count(_batch([1, 2, 3]), _batch([1, 2, 3]), 2) == 3


def test_chunked_unique_oracle():
    rel_r = Relation(1 << 14, 1, "unique", seed=1)
    rel_s = Relation(1 << 14, 1, "unique", seed=2)
    r, s = rel_r.shard(0), rel_s.shard(0)
    assert chunked_join_count(r, s, 1 << 11) == 1 << 14
