"""Observability layer end to end: span timelines through the real driver,
live metrics sampling, the cross-rank merge, and the regression gate.

Covers the ISSUE 3 acceptance criteria directly:

  * a CPU driver run with ``--timeline-dir`` exports a well-formed
    Chrome-trace span file + >= 1 metrics sample (smoke, in-process);
  * a 2-rank run's per-rank span files merge via ``tools_make_report.py
    --emit-timeline`` into ONE timeline on a shared clock;
  * ``tools_check_regress.py`` flags a synthetic 2x JTOTAL regression and
    passes an unchanged result.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from tpu_radix_join.main import main
from tpu_radix_join.observability import (MetricsSampler, SpanTracer,
                                          load_samples, merge_timeline)
from tpu_radix_join.observability.regress import (check_result, compare_tags,
                                                  extract_tags, format_table,
                                                  parse_tag_thresholds)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_spans(path):
    with open(path) as f:
        doc = json.load(f)
    assert "traceEvents" in doc and "metadata" in doc
    return doc


def _events(doc, ph=None, name=None):
    return [e for e in doc["traceEvents"]
            if (ph is None or e.get("ph") == ph)
            and (name is None or e.get("name") == name)]


# -------------------------------------------------------------- driver smoke

def test_driver_timeline_and_metrics_smoke(tmp_path):
    """CPU driver + --timeline-dir + --metrics-interval: well-formed Chrome
    trace with the phase vocabulary as spans, >= 1 metrics sample."""
    d = str(tmp_path)
    rc = main(["--tuples-per-node", "2048", "--nodes", "2",
               "--timeline-dir", d, "--metrics-interval", "0.05"])
    assert rc == 0

    doc = _load_spans(os.path.join(d, "0.spans.json"))
    md = doc["metadata"]
    assert md["rank"] == 0 and md["epoch_s"] > 0 and md["trace_id"]
    spans = {e["name"] for e in _events(doc, ph="X")}
    # the Measurements vocabulary mirrors into the timeline automatically
    assert {"JTOTAL", "JHIST", "JPROC"} <= spans
    for e in _events(doc, ph="X"):
        assert e["ts"] >= 0 and e["dur"] >= 0 and e["pid"] == 0
    # every span carries the file-level tags (nodes) in args
    jt = _events(doc, ph="X", name="JTOTAL")[0]
    assert jt["args"].get("nodes") == 2
    # metadata events name the process/thread for Perfetto
    assert _events(doc, ph="M", name="process_name")

    samples = load_samples(os.path.join(d, "0.metrics.jsonl"))
    assert len(samples) >= 1
    assert "host" in samples[0] and "t_epoch_s" in samples[0]
    # the final (stop-time) sample snapshots the finished phase registry
    assert "JTOTAL" in samples[-1]["times_us"]


def test_driver_metrics_interval_needs_a_dir():
    with pytest.raises(SystemExit):
        main(["--tuples-per-node", "1024", "--metrics-interval", "0.1"])


def test_grid_driver_timeline_pairs_and_checkpoints(tmp_path):
    """Grid mode: per-pair spans, checkpoint-save spans, and the
    chunked_grid strategy tag all land on the timeline.  The default
    --grid-pipeline auto runs the pipelined engine on this 2x2 grid, so
    per-pair saves ride the write-behind thread (ckpt_flush spans) and
    only the final done marker is a synchronous ckpt_save."""
    tl = str(tmp_path / "tl")
    rc = main(["--nodes", "1", "--tuples-per-node", "4096",
               "--grid-chunk-tuples", "2048",
               "--checkpoint-dir", str(tmp_path / "ckpt"),
               "--timeline-dir", tl])
    assert rc == 0
    doc = _load_spans(os.path.join(tl, "0.spans.json"))
    pairs = _events(doc, ph="X", name="grid_pair")
    assert len(pairs) == 4                      # 2x2 chunk grid
    assert {(e["args"]["i"], e["args"]["j"]) for e in pairs} == {
        (0, 0), (0, 1), (1, 0), (1, 1)}
    assert all(e["args"].get("strategy") == "chunked_grid" for e in pairs)
    assert len(_events(doc, ph="X", name="ckpt_save")) >= 1   # done marker
    assert len(_events(doc, ph="X", name="ckpt_flush")) >= 1  # write-behind
    assert len(_events(doc, ph="X", name="prefetch")) >= 2    # staged chunks


# ---------------------------------------------------------- cross-rank merge

def test_two_rank_timeline_merge(tmp_path):
    """Two real jax.distributed CPU processes x --timeline-dir, merged by
    ``tools_make_report.py --emit-timeline`` into one aligned timeline:
    both ranks' host phases on one clock, per-rank shift recorded."""
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    d = str(tmp_path)
    procs = []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(rank),
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "tpu_radix_join.main",
             "--tuples-per-node", "1024", "--nodes", "8", "--hosts", "2",
             "--timeline-dir", d, "--metrics-interval", "0.1"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True, cwd=REPO))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    joined = "\n---- rank boundary ----\n".join(outs)
    assert all(p.returncode == 0 for p in procs), joined
    for rank in range(2):
        assert os.path.exists(os.path.join(d, f"{rank}.spans.json")), joined
        assert load_samples(os.path.join(d, f"{rank}.metrics.jsonl")), joined

    merged_path = str(tmp_path / "merged.json")
    cp = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools_make_report.py"),
         d, "--emit-timeline", merged_path],
        capture_output=True, text=True, cwd=REPO)
    assert cp.returncode == 0, cp.stdout + cp.stderr
    assert "2 rank(s)" in cp.stdout, cp.stdout

    with open(merged_path) as f:
        merged = json.load(f)
    md = merged["metadata"]
    assert set(md["ranks"]) == {"0", "1"}
    # the earliest rank anchors the shared clock; the other carries the
    # positive epoch-delta shift
    shifts = [md["ranks"][r]["clock_shift_us"] for r in ("0", "1")]
    assert min(shifts) == 0.0 and max(shifts) >= 0.0
    for rank in (0, 1):
        spans = {e["name"] for e in merged["traceEvents"]
                 if e.get("ph") == "X" and e.get("pid") == rank}
        assert "JTOTAL" in spans, f"rank {rank} host phases missing"
    assert all(e["ts"] >= 0 for e in merged["traceEvents"] if "ts" in e)


def test_merge_timeline_aligns_anchors(tmp_path):
    """Unit-level clock alignment: two tracers with epoch anchors 1.5s
    apart merge with a 1.5e6 us shift on the later rank."""
    t0 = 1_000_000.0
    a = SpanTracer(rank=0, epoch_s=t0, mono_s=100.0)
    b = SpanTracer(rank=1, epoch_s=t0 + 1.5, mono_s=200.0)
    for tr in (a, b):
        tr.begin("JTOTAL")
        tr.end("JTOTAL")
        tr.instant("checkpoint_load", path="x")
        tr.save(str(tmp_path))
    merged = merge_timeline(str(tmp_path))
    md = merged["metadata"]
    assert md["t0_epoch_s"] == t0
    assert md["ranks"]["0"]["clock_shift_us"] == 0.0
    assert md["ranks"]["1"]["clock_shift_us"] == pytest.approx(1.5e6)
    r1 = [e for e in merged["traceEvents"]
          if e.get("pid") == 1 and e.get("ph") == "X"]
    assert r1 and all(e["ts"] >= 1.5e6 for e in r1)
    instants = [e for e in merged["traceEvents"] if e.get("ph") == "i"]
    assert len(instants) == 2


def test_merge_timeline_grafts_device_summary(tmp_path):
    """A span file with an embedded xplane summary grows a device track
    (tid 1) whose args declare the synthetic layout."""
    tr = SpanTracer(rank=0, epoch_s=5.0, mono_s=0.0)
    tr.begin("JTOTAL")
    tr.end("JTOTAL")
    tr.save(str(tmp_path), device_summary={
        "plane": "/device:TPU:0", "busy_us": 30.0,
        "ops": {"sort": {"us": 20.0, "count": 2},
                "fusion": {"us": 10.0, "count": 1}}})
    merged = merge_timeline(str(tmp_path))
    dev = [e for e in merged["traceEvents"]
           if e.get("tid") == 1 and e.get("ph") == "X"]
    assert [e["name"] for e in dev] == ["sort", "fusion"]   # heaviest first
    assert dev[0]["dur"] == 20.0
    assert "synthetic" in dev[0]["args"]["layout"]
    # sequential layout: fusion starts where sort ends
    assert dev[1]["ts"] == pytest.approx(dev[0]["ts"] + dev[0]["dur"])


def test_merge_timeline_empty_dir(tmp_path):
    assert merge_timeline(str(tmp_path)) is None


# ------------------------------------------------------------- span tracer

def test_tracer_reentrant_and_crash_save(tmp_path):
    """Re-entered phases (retry) nest innermost-first; save() closes spans
    a crash left open and marks them."""
    tr = SpanTracer(rank=3)
    tr.begin("JPROC")
    tr.begin("JPROC")           # retry attempt re-enters the phase
    tr.end("JPROC")
    tr.end("JPROC", attempts=2)
    tr.end("JPROC")             # stray stop: dropped, not an error
    tr.begin("JTOTAL")          # crash before stop
    path = tr.save(str(tmp_path))
    doc = _load_spans(path)
    assert os.path.basename(path) == "3.spans.json"
    jp = _events(doc, ph="X", name="JPROC")
    assert len(jp) == 2
    assert jp[1]["args"]["attempts"] == 2
    jt = _events(doc, ph="X", name="JTOTAL")
    assert len(jt) == 1 and jt[0]["args"]["unclosed"] is True


def test_measurements_mirror_and_span(tmp_path):
    """Measurements.start/stop/event mirror into an attached tracer;
    Measurements.span records timeline-only spans (no times_us tag)."""
    from tpu_radix_join.performance.measurements import Measurements
    m = Measurements(node_id=0, num_nodes=1)
    tr = m.attach_tracer(nodes=1)
    m.start("JHIST")
    m.stop("JHIST")
    m.event("checkpoint_load", path="x", done=False)
    with m.span("grid_pair", i=1, j=2):
        pass
    names = {e["name"] for e in tr.events}
    assert {"JHIST", "checkpoint_load", "grid_pair"} <= names
    assert "grid_pair" not in m.times_us          # timeline-only
    pair = [e for e in tr.events if e["name"] == "grid_pair"][0]
    assert pair["args"]["i"] == 1 and pair["args"]["j"] == 2
    # shared anchors: the tracer's epoch is the registry's epoch
    assert tr.epoch_s == m.meta["epoch_s"]


def test_measurements_event_epoch_timestamps():
    """Satellite (b): events carry both the raw monotonic t_s and the
    epoch-anchored t_epoch_s the merger aligns on."""
    from tpu_radix_join.performance.measurements import Measurements
    m = Measurements()
    m.event("fault_injected", site="GRID_TRANSIENT")
    ev = m.meta["events"][-1]
    assert ev["event"] == "fault_injected"
    assert "t_s" in ev and "t_epoch_s" in ev
    # anchored twin: epoch timestamp sits at/after the init-time anchor
    # and within a sane window of it
    assert 0.0 <= ev["t_epoch_s"] - m.meta["epoch_s"] < 60.0


# ---------------------------------------------------------- metrics sampler

def test_metrics_sampler_counters_and_torn_lines(tmp_path):
    from tpu_radix_join.performance.measurements import GRIDPAIRS, Measurements
    m = Measurements()
    m.incr(GRIDPAIRS, 3)
    path = str(tmp_path / "0.metrics.jsonl")
    with MetricsSampler(path, interval_s=0.05, measurements=m):
        m.start("JTOTAL")
    samples = load_samples(path)
    assert len(samples) >= 2                    # start + stop at minimum
    assert samples[-1]["counters"]["GRIDPAIRS"] == 3
    assert samples[-1]["open_phases"] == ["JTOTAL"]
    assert samples[-1]["t_rel_s"] >= samples[0]["t_rel_s"]
    # a torn final line (SIGKILL mid-write) is skipped, not fatal
    with open(path, "a") as f:
        f.write('{"t_epoch_s": 1.0, "trunc')
    assert len(load_samples(path)) == len(samples)


def test_metrics_sampler_rejects_bad_interval(tmp_path):
    with pytest.raises(ValueError):
        MetricsSampler(str(tmp_path / "x.jsonl"), interval_s=0.0)


# ---------------------------------------------------------- regression gate

BASE = {"tags": {"JTOTAL": 100.0, "JPROC": 40.0, "value": 2.0e9}}


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def _run_gate(tmp_path, fresh, *extra):
    base = _write(tmp_path, "base.json", BASE)
    fp = _write(tmp_path, "fresh.json", fresh)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools_check_regress.py"),
         fp, "--baseline", base, *extra],
        capture_output=True, text=True, cwd=REPO)


def test_gate_flags_2x_jtotal(tmp_path):
    """Acceptance: a synthetic 2x JTOTAL regression exits non-zero with a
    readable per-tag delta table."""
    cp = _run_gate(tmp_path, {"tags": {"JTOTAL": 200.0, "JPROC": 40.0,
                                       "value": 2.0e9}})
    assert cp.returncode == 1, cp.stdout + cp.stderr
    assert "JTOTAL" in cp.stdout and "+100.0" in cp.stdout
    assert "REGRESSED: 1 tag(s)" in cp.stdout


def test_gate_passes_unchanged(tmp_path):
    cp = _run_gate(tmp_path, BASE)
    assert cp.returncode == 0, cp.stdout + cp.stderr
    assert "ok: no tag past threshold" in cp.stdout


def test_gate_allowlist_and_tag_threshold(tmp_path):
    # allowlisted regression passes; a tightened per-tag threshold fails a
    # delta the default 25% would wave through
    fresh = {"tags": {"JTOTAL": 200.0, "JPROC": 44.0, "value": 2.0e9}}
    cp = _run_gate(tmp_path, fresh, "--allow", "JTOTAL")
    assert cp.returncode == 0, cp.stdout + cp.stderr
    assert "allowed" in cp.stdout
    cp = _run_gate(tmp_path, fresh, "--allow", "JTOTAL",
                   "--tag-threshold", "JPROC=0.05")
    assert cp.returncode == 1
    assert "JPROC" in cp.stdout


def test_gate_throughput_direction(tmp_path):
    """Higher-better tags regress on DROP: halved throughput fails even
    though the number shrank."""
    cp = _run_gate(tmp_path, {"tags": {"JTOTAL": 100.0, "JPROC": 40.0,
                                       "value": 1.0e9}})
    assert cp.returncode == 1
    assert "value" in cp.stdout


def test_gate_missing_tag_strict(tmp_path):
    fresh = {"tags": {"JTOTAL": 100.0, "value": 2.0e9}}     # JPROC vanished
    assert _run_gate(tmp_path, fresh).returncode == 0
    assert _run_gate(tmp_path, fresh, "--strict").returncode == 1


def test_gate_empty_baseline_passes_with_note(tmp_path):
    """The repo's published-{} BASELINE.json has no numeric tags: nothing
    to compare is not a regression."""
    base = _write(tmp_path, "empty.json", {"published": {}})
    fp = _write(tmp_path, "fresh.json", BASE)
    cp = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools_check_regress.py"),
         fp, "--baseline", base], capture_output=True, text=True, cwd=REPO)
    assert cp.returncode == 0, cp.stdout + cp.stderr
    assert "no numeric tags" in cp.stdout


def test_gate_usage_errors(tmp_path):
    fp = _write(tmp_path, "fresh.json", BASE)
    base = _write(tmp_path, "base.json", BASE)
    cp = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools_check_regress.py"),
         fp, "--baseline", str(tmp_path / "nope.json")],
        capture_output=True, text=True, cwd=REPO)
    assert cp.returncode == 2
    cp = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools_check_regress.py"),
         fp, "--baseline", base, "--tag-threshold", "JTOTAL"],
        capture_output=True, text=True, cwd=REPO)
    assert cp.returncode == 2


def test_check_result_in_process(tmp_path):
    """bench.py's --check-regress hook: in-memory fresh dict vs baseline
    file, same verdicts as the CLI."""
    base = _write(tmp_path, "base.json", BASE)
    code, report = check_result({"JTOTAL": 200.0, "JPROC": 40.0,
                                 "value": 2.0e9}, base)
    assert code == 1 and "JTOTAL" in report
    code, report = check_result(BASE["tags"], base)
    assert code == 0


def test_extract_and_compare_units():
    assert extract_tags({"parsed": {"tags": {"a": 1, "rc": 0,
                                             "flag": True, "s": "x"}}}) == \
        {"a": 1.0}
    rows = compare_tags({"a": 10.0, "zero": 0.0}, {"a": 10.0, "zero": 1.0,
                                                   "fresh_only": 5.0})
    by = {r["tag"]: r for r in rows}
    assert by["a"]["status"] == "ok"
    assert by["zero"]["status"] == "regressed"      # 0 -> 1 cost: inf delta
    assert by["fresh_only"]["status"] == "new"
    assert rows[0]["tag"] == "zero"                 # worst first
    table = format_table(rows)
    assert "zero" in table and "inf" in table
    assert parse_tag_thresholds(["A=0.1", "B=0.5"]) == {"A": 0.1, "B": 0.5}
    with pytest.raises(ValueError):
        parse_tag_thresholds(["A"])
