"""Crash-only fleet serving tests (service/fleet.py + journal.py).

Fast unit coverage: the durable query journal's WAL + torn-line
discipline, consistent-hash ring stability, /healthz readiness, the
bounded session outcome window, postmortem incarnation grouping, and
the regress-gate tag declarations (double_exec pinned to zero).

Real-process coverage (each worker boot pays a JAX import, so these
stay small and bounded): SIGKILL-one-of-two mid-query failover through
the CLI, torn-intent replay across a supervisor restart, a fixed-seed
``fleet.worker_kill`` mini-soak on one shared supervisor, and the
graceful SIGTERM drain.  A randomized soak rides behind ``-m slow``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tpu_radix_join.performance.measurements import Measurements
from tpu_radix_join.service.fleet import (FleetSupervisor, ring_points,
                                          route_tenant)
from tpu_radix_join.service.journal import (QueryJournal,
                                            request_fingerprint)

TPN = 1 << 10
WORKER_ARGS = ["--nodes", "1", "--verify", "check"]


def _req(qid, tenant="default", **kw):
    kw.setdefault("tuples_per_node", TPN)
    kw.setdefault("seed", 7)
    return {"query_id": qid, "tenant": tenant, **kw}


def _outcome_lines(out):
    recs = [json.loads(line) for line in out.splitlines()
            if line.startswith("{")]
    return ([r for r in recs if r.get("event") == "outcome"],
            next((r for r in recs if r.get("event") == "summary"), None))


# ------------------------------------------------------------------ journal

def test_journal_roundtrip_and_unacked_ordering(tmp_path):
    j = QueryJournal(str(tmp_path))
    ra, rb = _req("qa"), _req("qb")
    fa, fb = request_fingerprint(ra), request_fingerprint(rb)
    assert fa != fb
    j.append_intent(ra, worker=0, incarnation="w0i1")
    j.append_intent(rb, worker=1, incarnation="w1i1")
    pend = j.unacknowledged()
    assert [r["fp"] for r in pend] == [fa, fb]     # acceptance order
    assert j.depth() == 2
    j.append_outcome(fa, {"query_id": "qa", "status": "ok"}, worker=0)
    assert [r["fp"] for r in j.unacknowledged()] == [fb]
    assert j.outcome_for(fa) == {"query_id": "qa", "status": "ok"}
    assert j.outcome_for(fb) is None
    aud = j.audit()
    assert (aud.intents, aud.outcomes, aud.unacked) == (2, 1, 1)
    assert aud.double_exec == 0


def test_journal_fingerprint_is_canonical():
    a = {"query_id": "q", "tenant": "t", "tuples_per_node": 8, "seed": 1}
    b = {"seed": 1, "tuples_per_node": 8, "tenant": "t", "query_id": "q"}
    assert request_fingerprint(a) == request_fingerprint(b)
    assert request_fingerprint(a) != request_fingerprint(
        {**a, "seed": 2})


def test_journal_first_outcome_wins_and_audit_counts_doubles(tmp_path):
    j = QueryJournal(str(tmp_path))
    r = _req("q")
    fp = request_fingerprint(r)
    j.append_intent(r)
    j.append_outcome(fp, {"query_id": "q", "status": "ok", "matches": 1})
    j.append_outcome(fp, {"query_id": "q", "status": "ok", "matches": 2})
    # the client is owed the FIRST answer; the duplicate is the bug the
    # audit exists to count
    assert j.outcome_for(fp)["matches"] == 1
    assert j.audit().double_exec == 1


def test_journal_tolerates_torn_and_foreign_lines(tmp_path):
    j = QueryJournal(str(tmp_path))
    r = _req("q")
    j.append_intent(r)
    with open(j.path, "a") as f:
        f.write('{"schema_version": 1, "kind": "intent", "fp": "torn')
    # the torn tail of a SIGKILLed writer is skipped, not fatal, and the
    # intact intent stays replayable
    assert [row["query_id"] for row in j.unacknowledged()] == ["q"]
    with open(j.path, "a") as f:
        f.write("\n" + json.dumps({"schema_version": 99, "kind": "intent",
                                   "fp": "future"}) + "\n")
        f.write(json.dumps({"schema_version": 1, "kind": "gossip",
                            "fp": "x"}) + "\n")
    assert len(j.rows()) == 1                      # newer-schema + unknown kind skipped
    assert j.audit().unacked == 1


# --------------------------------------------------------------------- ring

def test_ring_routing_is_deterministic_and_total():
    slots = [0, 1, 2, 3]
    assert ring_points(slots) == ring_points(slots)
    owners = {f"t{i}": route_tenant(f"t{i}", slots) for i in range(64)}
    assert set(owners.values()) <= set(slots)
    assert len(set(owners.values())) == len(slots)  # 64 tenants cover 4 slots
    assert route_tenant("t0", []) is None


def test_ring_removal_moves_only_the_dead_slots_tenants():
    slots = [0, 1, 2, 3]
    before = {f"t{i}": route_tenant(f"t{i}", slots) for i in range(64)}
    after = {t: route_tenant(t, [0, 2, 3]) for t in before}
    for t, owner in before.items():
        if owner == 1:
            assert after[t] in (0, 2, 3)           # orphans re-home...
        else:
            assert after[t] == owner               # ...everyone else stays


# ------------------------------------------------------------------ healthz

def test_healthz_readiness_in_process():
    from tpu_radix_join.observability.statusz import StatuszServer
    s = StatuszServer()
    code, body = s.health()
    assert code == 200 and body["ok"]              # liveness-only default
    s.set_readiness(lambda: {"ok": False, "reason": "breaker_open"})
    code, body = s.health()
    assert code == 503 and body["reason"] == "breaker_open"
    s.set_readiness(lambda: True)
    assert s.health()[0] == 200

    def boom():
        raise RuntimeError("introspection died")

    s.set_readiness(boom)
    code, body = s.health()
    assert code == 503 and "introspection died" in body["reason"]


def test_fleet_readiness_drain_and_no_workers(tmp_path):
    sup = FleetSupervisor(1, WORKER_ARGS, str(tmp_path))
    # never started: the slot is dead, nothing can take a query
    assert sup.readiness() == {"ok": False, "reason": "no_healthy_worker"}
    sup.draining = True
    assert sup.readiness() == {"ok": False, "reason": "draining"}


# --------------------------------------------------- bounded outcome window

def test_session_outcomes_window_is_bounded():
    from tpu_radix_join.core.config import ServiceConfig
    from tpu_radix_join.service import JoinSession
    from tpu_radix_join.core.config import JoinConfig
    sess = JoinSession(JoinConfig(num_nodes=2),
                       ServiceConfig(outcomes_keep=4))
    try:
        assert sess.outcomes.maxlen == 4
    finally:
        sess.close()
    with pytest.raises(ValueError):
        ServiceConfig(outcomes_keep=0)


# -------------------------------------------------- postmortem incarnations

def test_postmortem_merge_groups_by_worker_incarnation(tmp_path):
    from tpu_radix_join.observability.postmortem import merge_bundles
    paths = []
    for i, winc in enumerate(["w0i1", "w0i2", "w0i2"]):
        b = {"reason": "worker_death", "failure_class": "backend_unavailable",
             "rank": 0, "created_epoch_s": 100.0 + i,
             "ring": {"context": {"worker_incarnation": winc}}}
        p = tmp_path / f"bundle_{i}.json"
        p.write_text(json.dumps(b))
        paths.append(str(p))
    summary = merge_bundles(paths)
    assert summary["by_worker_incarnation"] == {"w0i1": 1, "w0i2": 2}
    assert [r["worker_incarnation"] for r in summary["rows"]] == [
        "w0i1", "w0i2", "w0i2"]


# --------------------------------------------------------- regress gate pins

def test_fleet_bench_tags_gate_lower_is_better():
    from tpu_radix_join.observability.regress import (extract_tags,
                                                      higher_is_better,
                                                      tag_is_declared)
    for tag in ("failover_ms", "cold_restart_ms", "failover", "replayn",
                "jdepth", "wincarn", "worker_restarts", "double_exec"):
        assert tag_is_declared(tag), tag
        assert not higher_is_better(tag), tag
    # scenario descriptors are skipped, not gated
    tags = extract_tags({"workers": 4, "queries": 5, "failover_ms": 500.0})
    assert "workers" not in tags and "queries" not in tags


def test_double_exec_regresses_from_zero_at_any_threshold():
    from tpu_radix_join.observability.regress import compare_tags
    rows = compare_tags({"double_exec": 0.0}, {"double_exec": 1.0},
                        threshold=1e9)
    assert [r["tag"] for r in rows
            if r["status"] == "regressed"] == ["double_exec"]
    assert not any(r["status"] == "regressed" for r in compare_tags(
        {"double_exec": 0.0}, {"double_exec": 0.0}))


# ----------------------------------------------- real-process fleet serving

def test_fleet_cli_kill_mid_query_exactly_once(capsys, tmp_path):
    """Tier-1 real-kill test: ``--fleet 2``, the 2nd dispatched query's
    routed worker is SIGKILLed with the request on its pipe, and the
    survivor serves the journal-replayed attempt — every query ends with
    exactly one oracle-exact outcome, ``double_exec == 0``."""
    from tpu_radix_join.main import main
    reqs = tmp_path / "reqs.jsonl"
    reqs.write_text("".join(
        json.dumps(_req(f"q{i}")) + "\n" for i in range(3)))
    d = tmp_path / "fleet"
    rc = main(["--fleet", "2", "--serve", str(reqs), *WORKER_ARGS,
               "--fleet-dir", str(d), "--fleet-kill-at", "2",
               "--seed", "7"])
    outcomes, summary = _outcome_lines(capsys.readouterr().out)
    assert rc == 0
    assert [o["query_id"] for o in outcomes] == ["q0", "q1", "q2"]
    assert all(o["status"] == "ok" for o in outcomes)
    assert all(o["matches"] == TPN for o in outcomes)   # nodes=1 oracle
    killed = outcomes[1]
    assert killed["fleet"]["attempts"] >= 2 and killed["fleet"]["replayed"]
    assert summary["failover"] >= 1 and summary["replayn"] >= 1
    assert summary["double_exec"] == 0 and summary["unacked"] == 0
    assert summary["drain"]["double_exec"] == 0
    # the journal on disk agrees with the summary it printed
    aud = QueryJournal(str(d)).audit()
    assert aud.double_exec == 0 and aud.unacked == 0
    assert aud.outcomes == 3


def test_torn_intent_replays_once_after_supervisor_restart(tmp_path):
    """Satellite: a supervisor that died mid-append leaves one intact
    unacknowledged intent and one torn line.  The restarted supervisor
    replays the intact intent exactly once (the torn tail is skipped,
    not resurrected), and a re-submission re-serves from the journal
    without re-executing."""
    d = str(tmp_path / "fleet")
    j = QueryJournal(d)
    r = _req("torn_q")
    j.append_intent(r, worker=0, incarnation="w0i1")
    with open(j.path, "a") as f:
        f.write('{"schema_version": 1, "kind": "intent", "fp": "dead')
    sup = FleetSupervisor(1, WORKER_ARGS, d, measurements=Measurements())
    try:
        sup.start()
        outs = sup.replay_unacknowledged()
        assert len(outs) == 1
        assert outs[0]["status"] == "ok" and outs[0]["matches"] == TPN
        assert sup.replay_unacknowledged() == []       # nothing left
        again = sup.dispatch(r)
        assert again["fleet"].get("served_from_journal")
        assert again["matches"] == TPN
        report = sup.drain()
    finally:
        sup.close()
    assert report["unacked"] == 0 and report["double_exec"] == 0
    aud = QueryJournal(d).audit()
    assert aud.outcomes == 1 and aud.double_exec == 0


def test_fleet_chaos_mini_soak_fixed_seeds(tmp_path):
    """Tier-1 fixed-seed ``fleet.worker_kill`` mini-soak: two seeded kill
    schedules through ONE shared supervisor — zero violations, zero
    double executions, the supervisor survives its workers."""
    from tpu_radix_join.robustness.chaos import FleetChaosRunner, soak_fleet
    sup = FleetSupervisor(2, WORKER_ARGS, str(tmp_path / "fleet"),
                          measurements=Measurements(),
                          restart_backoff_s=0.05)
    try:
        runner = FleetChaosRunner(sup, queries=2, size=TPN,
                                  bundle_dir=str(tmp_path / "bundles"))
        outcomes, summary = soak_fleet(2, base_seed=3, runner=runner)
    finally:
        sup.close()
    assert summary["violations"] == 0, [o.detail for o in outcomes]
    assert summary["double_exec"] == 0 and summary["unacked"] == 0
    assert summary["pass"] + summary["classified"] == 2


def test_fleet_sigterm_drains_gracefully(tmp_path):
    """SIGTERM with the request stream still open: admission stops,
    served queries stay answered, the journal drains to zero
    unacknowledged intents, every worker lease is withdrawn, exit 0."""
    d = str(tmp_path / "fleet")
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = (repo + os.pathsep + env["PYTHONPATH"]
                         if env.get("PYTHONPATH") else repo)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_radix_join.main", "--fleet", "1",
         "--serve", "-", *WORKER_ARGS, "--fleet-dir", d],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, bufsize=1, env=env)
    try:
        proc.stdin.write(json.dumps(_req("drain_q")) + "\n")
        proc.stdin.flush()
        line = proc.stdout.readline()              # the served outcome
        out = json.loads(line)
        assert out["event"] == "outcome" and out["status"] == "ok"
        proc.send_signal(signal.SIGTERM)           # stream still open
        rest, _ = proc.communicate(timeout=120)
    except Exception:
        proc.kill()
        raise
    assert proc.returncode == 0
    _, summary = _outcome_lines(line + rest)
    assert summary is not None
    assert summary["drain"]["unacked"] == 0
    assert summary["drain"]["double_exec"] == 0
    assert summary["drain"]["leases_left"] == []
    leases = [os.path.join(root, f) for root, _, fs in os.walk(d)
              for f in fs if f.startswith("lease_")]
    assert leases == []                            # all withdrawn/swept
    assert QueryJournal(d).audit().unacked == 0


@pytest.mark.slow
def test_fleet_chaos_soak_randomized(tmp_path):
    """Randomized soak (slow ring): N random-seed kill schedules on one
    supervisor; the seed prints so any violation is replayable."""
    import random

    from tpu_radix_join.robustness.chaos import FleetChaosRunner, soak_fleet
    base_seed = random.SystemRandom().randrange(1 << 20)
    print(f"fleet soak base_seed={base_seed}")
    sup = FleetSupervisor(2, WORKER_ARGS, str(tmp_path / "fleet"),
                          measurements=Measurements(),
                          restart_backoff_s=0.05)
    try:
        runner = FleetChaosRunner(sup, queries=3, size=TPN,
                                  bundle_dir=str(tmp_path / "bundles"))
        outcomes, summary = soak_fleet(4, base_seed=base_seed,
                                       runner=runner)
    finally:
        sup.close()
    assert summary["violations"] == 0, [o.detail for o in outcomes]
    assert summary["double_exec"] == 0 and summary["unacked"] == 0
