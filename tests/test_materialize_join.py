"""End-to-end materializing join tests: the distributed probe_match_rate
capability (kernels.cu:314-411) — rid pairs out, overflow detected — checked
against a host numpy join oracle on the 8-virtual-device mesh."""

import numpy as np

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.operators.hash_join import MaterializedJoinResult


def _host_pairs(r_keys, r_rids, s_keys, s_rids):
    """Oracle: all matching (r_rid, s_rid) pairs, as a sorted array."""
    by_key = {}
    for k, rid in zip(r_keys.tolist(), r_rids.tolist()):
        by_key.setdefault(k, []).append(rid)
    pairs = [(rr, sr) for k, sr in zip(s_keys.tolist(), s_rids.tolist())
             for rr in by_key.get(k, ())]
    return np.asarray(sorted(pairs), dtype=np.uint64).reshape(-1, 2)


def _pairs_of(res: MaterializedJoinResult):
    return np.asarray(
        sorted(zip(res.r_rid.tolist(), res.s_rid.tolist())),
        dtype=np.uint64).reshape(-1, 2)


def _all_shards(rel, n):
    ks, rs = zip(*(rel.shard_np(i) for i in range(n)))
    return np.concatenate(ks), np.concatenate(rs)


def test_materialize_unique_pairs():
    n, size = 8, 1 << 13
    cfg = JoinConfig(num_nodes=n, network_fanout_bits=4)
    r = Relation(size, n, "unique", seed=1)
    s = Relation(size, n, "unique", seed=9)
    res = HashJoin(cfg).join_materialize(r, s)
    assert res.ok
    assert res.matches == size
    rk, rr = _all_shards(r, n)
    sk, sr = _all_shards(s, n)
    np.testing.assert_array_equal(_pairs_of(res), _host_pairs(rk, rr, sk, sr))


def test_materialize_duplicates_within_cap():
    n = 4
    cfg = JoinConfig(num_nodes=n, network_fanout_bits=4, match_rate_cap=8)
    r = Relation(1 << 12, n, "unique", seed=1)
    # every outer key hits exactly one inner tuple; outer repeats keys 4x
    s = Relation(1 << 12, n, "modulo", modulo=1 << 10)
    res = HashJoin(cfg).join_materialize(r, s)
    assert res.ok
    assert res.matches == (1 << 12)
    rk, rr = _all_shards(r, n)
    sk, sr = _all_shards(s, n)
    np.testing.assert_array_equal(_pairs_of(res), _host_pairs(rk, rr, sk, sr))


def test_materialize_overflow_detected():
    n = 4
    # inner has each key 4x (modulo), cap 2 < 4 -> overflow must be flagged
    cfg = JoinConfig(num_nodes=n, network_fanout_bits=4, match_rate_cap=2)
    r = Relation(1 << 12, n, "modulo", modulo=1 << 10)
    s = Relation(1 << 12, n, "unique", seed=5)
    res = HashJoin(cfg).join_materialize(r, s)
    assert not res.ok   # cap overflow is reported, never silently dropped
