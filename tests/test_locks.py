"""utils/locks.py: the PID-stamped chip-reservation protocol between
bench.py and the out-of-core grid (single shared device)."""

import os
import subprocess
import threading
import time

from tpu_radix_join.utils.locks import (
    acquire_pid_file, pid_file_alive, remove_pid_file, write_pid_file)


def _dead_pid():
    p = subprocess.Popen(["true"])
    p.wait()
    return p.pid


def test_write_and_liveness(tmp_path):
    p = str(tmp_path / "lock")
    assert write_pid_file(p)
    assert pid_file_alive(p) is True          # our own pid
    open(p, "w").write(str(_dead_pid()))
    assert pid_file_alive(p) is False
    open(p, "w").write("")                    # PID-less
    assert pid_file_alive(p) is None
    remove_pid_file(p)
    assert pid_file_alive(p) is None          # missing


def test_acquire_paths(tmp_path):
    p = str(tmp_path / "lock")
    assert acquire_pid_file(p, 1) == "acquired"
    assert open(p).read() == str(os.getpid())
    # live holder (ourselves): busy at deadline, stamp untouched
    assert acquire_pid_file(p, 0.3, poll_s=0.1) == "busy"
    assert open(p).read() == str(os.getpid())
    # dead holder: broken immediately, well under the deadline
    open(p, "w").write(str(_dead_pid()))
    t0 = time.monotonic()
    assert acquire_pid_file(p, 5, poll_s=0.1) == "acquired"
    assert time.monotonic() - t0 < 1.0
    # PID-less holder: given two polls, then broken
    open(p, "w").write("")
    assert acquire_pid_file(p, 5, poll_s=0.05) == "acquired"
    remove_pid_file(p)


def test_acquire_unwritable_is_error_not_busy(tmp_path):
    # parent "directory" is a regular file -> unconditionally unwritable,
    # even for root (chmod-based denial doesn't bind uid 0)
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    assert acquire_pid_file(str(blocker / "lock"), 0.2) == "error"


def test_acquire_contention_single_winner(tmp_path):
    p = str(tmp_path / "lock")
    results = []
    barrier = threading.Barrier(8)

    def contend():
        barrier.wait()
        results.append(acquire_pid_file(p, 0.5, poll_s=0.05))

    ts = [threading.Thread(target=contend) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # same-process contenders: one wins, the rest see a live holder
    assert results.count("acquired") == 1, results
    assert results.count("busy") == 7, results
    assert not [f for f in os.listdir(tmp_path) if ".stale." in f]
