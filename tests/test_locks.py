"""utils/locks.py: the PID-stamped chip-reservation protocol between
bench.py and the out-of-core grid (single shared device)."""

import os
import subprocess
import threading
import time

from tpu_radix_join.utils.locks import (
    acquire_pid_file, pid_file_alive, remove_pid_file, write_pid_file)


def _dead_pid():
    p = subprocess.Popen(["true"])
    p.wait()
    return p.pid


def test_write_and_liveness(tmp_path):
    p = str(tmp_path / "lock")
    assert write_pid_file(p)
    assert pid_file_alive(p) is True          # our own pid
    open(p, "w").write(str(_dead_pid()))
    assert pid_file_alive(p) is False
    open(p, "w").write("")                    # PID-less
    assert pid_file_alive(p) is None
    remove_pid_file(p)
    assert pid_file_alive(p) is None          # missing


def test_acquire_paths(tmp_path):
    p = str(tmp_path / "lock")
    assert acquire_pid_file(p, 1) == "acquired"
    assert open(p).read() == str(os.getpid())
    # live holder (ourselves): busy at deadline, stamp untouched
    assert acquire_pid_file(p, 0.3, poll_s=0.1) == "busy"
    assert open(p).read() == str(os.getpid())
    # dead holder: broken immediately, well under the deadline
    open(p, "w").write(str(_dead_pid()))
    t0 = time.monotonic()
    assert acquire_pid_file(p, 5, poll_s=0.1) == "acquired"
    assert time.monotonic() - t0 < 1.0
    # PID-less holder: given two polls, then broken
    open(p, "w").write("")
    assert acquire_pid_file(p, 5, poll_s=0.05) == "acquired"
    remove_pid_file(p)


def test_acquire_unwritable_is_error_not_busy(tmp_path):
    # parent "directory" is a regular file -> unconditionally unwritable,
    # even for root (chmod-based denial doesn't bind uid 0)
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    assert acquire_pid_file(str(blocker / "lock"), 0.2) == "error"


def test_acquire_contention_single_winner(tmp_path):
    p = str(tmp_path / "lock")
    results = []
    barrier = threading.Barrier(8)

    def contend():
        barrier.wait()
        results.append(acquire_pid_file(p, 0.5, poll_s=0.05))

    ts = [threading.Thread(target=contend) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # same-process contenders: one wins, the rest see a live holder
    assert results.count("acquired") == 1, results
    assert results.count("busy") == 7, results
    assert not [f for f in os.listdir(tmp_path) if ".stale." in f]


def test_leaseboard_heartbeat_concurrent(tmp_path):
    """The heartbeat runs on the sampler's daemon tick AND the main
    thread (membership.py); both racers share one ``<path>.tmp.<pid>``
    scratch name, so only the instance lock keeps a lease from being
    torn.  N threads hammering one board must leave a valid JSON lease,
    a seq that counted every write, and no stray tmp files."""
    import json

    from tpu_radix_join.robustness.membership import LeaseBoard

    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=1)
    writes_per_thread, nthreads = 50, 8
    barrier = threading.Barrier(nthreads)

    def hammer():
        barrier.wait()
        for _ in range(writes_per_thread):
            board.heartbeat(epoch=1)

    ts = [threading.Thread(target=hammer) for _ in range(nthreads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    with open(board.lease_path(0)) as f:
        lease = json.load(f)               # a torn file would fail here
    assert lease["seq"] == writes_per_thread * nthreads
    assert lease["epoch"] == 1 and lease["rank"] == 0
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
    assert board.read(0).seq == lease["seq"]


def test_metrics_sampler_concurrent_with_rotation(tmp_path):
    """sample() races between the daemon tick and the main thread while
    a tiny rotate_bytes forces rotation mid-write: every line must stay
    intact (valid JSON), none lost, and the final file set must respect
    rotate_keep.  Unlocked, a rotation under a concurrent write loses
    lines or interleaves into a closed fd."""
    from tpu_radix_join.observability.metrics import (MetricsSampler,
                                                      load_samples)

    path = str(tmp_path / "r0.metrics.jsonl")
    s = MetricsSampler(path, interval_s=0.001, rotate_bytes=2048,
                       rotate_keep=2)
    nthreads, per_thread = 4, 40
    barrier = threading.Barrier(nthreads)

    def hammer():
        barrier.wait()
        for _ in range(per_thread):
            s.sample()

    with s:                                 # daemon tick races the hammers
        ts = [threading.Thread(target=hammer) for _ in range(nthreads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert s.rotations > 0, "rotate_bytes=2048 never rotated — dead test"
    recs = load_samples(path, include_rotated=True)
    # rotation drops whole old files past keep, never individual lines:
    # everything still on disk parses, and at least the hammer writes
    # minus the dropped rotations are present
    assert all("t_epoch_s" in r for r in recs)
    assert s.samples_written >= nthreads * per_thread + 2
