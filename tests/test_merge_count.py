import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.relation import host_join_count
from tpu_radix_join.data.tuples import R_PAD_KEY, S_PAD_KEY
from tpu_radix_join.ops.merge_count import (
    MAX_MERGE_KEY,
    merge_count_chunks,
    merge_count_per_partition,
)


def _total(counts):
    return int(np.asarray(counts).astype(np.uint64).sum())


def test_merge_count_duplicates():
    rng = np.random.default_rng(0)
    r = rng.integers(0, 300, 5000).astype(np.uint32)
    s = rng.integers(0, 300, 4000).astype(np.uint32)
    got = _total(merge_count_chunks(jnp.asarray(r), jnp.asarray(s)))
    assert got == host_join_count(r, s)


def test_merge_count_no_matches():
    r = np.arange(0, 100, dtype=np.uint32)
    s = np.arange(1000, 1100, dtype=np.uint32)
    assert _total(merge_count_chunks(jnp.asarray(r), jnp.asarray(s))) == 0


def test_merge_count_ignores_padding():
    r = np.concatenate([np.array([1, 2, 3], np.uint32),
                        np.full(10, R_PAD_KEY, np.uint32)])
    s = np.concatenate([np.array([2, 2], np.uint32),
                        np.full(20, S_PAD_KEY, np.uint32)])
    assert _total(merge_count_chunks(jnp.asarray(r), jnp.asarray(s))) == 2


def test_merge_count_out_of_range_keys_dont_match():
    # keys above MAX_MERGE_KEY are routed to pad slots (the pipeline-level
    # keys_ok check reports them); they must never produce matches
    big = np.uint32(MAX_MERGE_KEY + 1)
    r = np.array([big, 5], np.uint32)
    s = np.array([big, 5], np.uint32)
    assert _total(merge_count_chunks(jnp.asarray(r), jnp.asarray(s))) == 1


def test_merge_count_per_partition_matches_oracle():
    rng = np.random.default_rng(1)
    r = rng.integers(0, 512, 3000).astype(np.uint32)
    s = rng.integers(0, 512, 2500).astype(np.uint32)
    per = np.asarray(merge_count_per_partition(jnp.asarray(r), jnp.asarray(s), 4))
    assert per.shape == (16,)
    assert per.sum() == host_join_count(r, s)
    for p in (0, 7, 15):
        expect = host_join_count(r[(r % 16) == p], s[(s % 16) == p])
        assert per[p] == expect


def test_merge_count_asymmetric_sizes():
    rng = np.random.default_rng(2)
    r = rng.integers(0, 100, 10).astype(np.uint32)
    s = rng.integers(0, 100, 9999).astype(np.uint32)
    got = _total(merge_count_chunks(jnp.asarray(r), jnp.asarray(s)))
    assert got == host_join_count(r, s)
