"""Fused Pallas merge-scan kernel vs the XLA reference implementation.

Runs in interpret mode on CPU (the driver benches the compiled kernel on real
TPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_radix_join.data.relation import host_join_count
from tpu_radix_join.ops.merge_count import merge_count_pallas, merge_count_chunks
from tpu_radix_join.ops.pallas.merge_scan import TILE


def _total(counts):
    return int(np.asarray(counts).astype(np.uint64).sum())


@pytest.mark.parametrize("nr,ns,domain", [
    (TILE // 2, TILE // 2, 300),        # exactly one tile after pack
    (TILE, TILE // 2, 1000),            # padding needed
    (3 * TILE, 2 * TILE, 50),           # multi-tile, heavy duplicates
    (100, 5 * TILE, 7),                 # extreme duplicate runs crossing tiles
])
def test_pallas_matches_host_oracle(nr, ns, domain):
    rng = np.random.default_rng(nr + ns)
    r = rng.integers(0, domain, nr).astype(np.uint32)
    s = rng.integers(0, domain, ns).astype(np.uint32)
    got = _total(merge_count_pallas(jnp.asarray(r), jnp.asarray(s), interpret=True))
    assert got == host_join_count(r, s)


def test_pallas_matches_xla_path():
    rng = np.random.default_rng(0)
    r = rng.integers(0, 4096, TILE).astype(np.uint32)
    s = rng.integers(0, 4096, TILE).astype(np.uint32)
    a = _total(merge_count_pallas(jnp.asarray(r), jnp.asarray(s), interpret=True))
    b = _total(merge_count_chunks(jnp.asarray(r), jnp.asarray(s)))
    assert a == b


@pytest.mark.parametrize("fanout", [0, 2, 5])
def test_partition_kernel_matches_xla(fanout):
    from tpu_radix_join.ops.merge_count import merge_count_per_partition
    rng = np.random.default_rng(fanout)
    r = rng.integers(0, 3000, 2 * TILE + 17).astype(np.uint32)
    s = rng.integers(0, 3000, TILE - 5).astype(np.uint32)
    r[:3] = 0xFFFFFFF0      # out-of-range: routed to pad slots, zero weight
    a = merge_count_per_partition(jnp.asarray(r), jnp.asarray(s), fanout,
                                  impl="xla")
    b = merge_count_per_partition(jnp.asarray(r), jnp.asarray(s), fanout,
                                  impl="pallas_interpret")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_partition_kernel_hot_partition_run_across_tiles():
    # one key dominating S: its partition's count crosses many tile
    # boundaries and exercises the carried scan + pl.when accumulation
    from tpu_radix_join.ops.merge_count import merge_count_per_partition
    key = np.uint32(7 * 32 + 3)        # partition 3 under fanout 5
    r = np.concatenate([np.full(50, key, np.uint32),
                        np.arange(0, TILE, dtype=np.uint32) * 32])  # pid 0
    s = np.full(3 * TILE, key, np.uint32)
    counts = merge_count_per_partition(jnp.asarray(r), jnp.asarray(s), 5,
                                       impl="pallas_interpret")
    counts = np.asarray(counts)
    assert counts[3] == 50 * 3 * TILE
    assert counts.sum() == counts[3]


def test_pallas_run_spanning_many_tiles():
    # a single key whose R-run occupies >1 full tile: the carried base/run
    # state must survive multiple tile boundaries
    r = np.full(2 * TILE, 42, np.uint32)
    s = np.concatenate([np.full(100, 42, np.uint32),
                        np.arange(1000, 1000 + TILE - 100, dtype=np.uint32)])
    got = _total(merge_count_pallas(jnp.asarray(r), jnp.asarray(s), interpret=True))
    assert got == 2 * TILE * 100
