"""Tests for the Relation::distribute analog (parallel/distribute.py) on the
8-virtual-device mesh: conservation, uniform source mixing, and real local
shuffling — the properties the reference's pairwise exchange establishes
(Relation.cpp:99-141)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.parallel.distribute import distribute
from tpu_radix_join.parallel.mesh import make_mesh

N = 8
LOCAL = 1 << 10


def _range_sharded_batch():
    """The pre-distribute state: node i holds the dense range
    [i*LOCAL, (i+1)*LOCAL) — what a rank-local generator without the exchange
    would produce (Relation.cpp:63-73 before main.cpp:101-104)."""
    key = jnp.arange(N * LOCAL, dtype=jnp.uint32)
    rid = jnp.arange(N * LOCAL, dtype=jnp.uint32)
    return TupleBatch(key=key, rid=rid)


def _distribute(batch, seed=7):
    mesh = make_mesh(N)
    fn = jax.shard_map(
        lambda b: distribute(b, N, "nodes", seed=seed),
        mesh=mesh, in_specs=P("nodes"), out_specs=P("nodes"))
    return jax.jit(fn)(batch)


def test_conservation_and_mixing():
    out = _distribute(_range_sharded_batch())
    keys = np.asarray(out.key)
    rids = np.asarray(out.rid)
    # conservation: the global multiset of tuples is untouched
    np.testing.assert_array_equal(np.sort(keys), np.arange(N * LOCAL))
    np.testing.assert_array_equal(np.sort(rids), np.arange(N * LOCAL))
    # key/rid pairing survives the exchange (key == rid by construction)
    np.testing.assert_array_equal(keys, rids)
    # mixing: every node now holds exactly LOCAL/N keys from each source range
    per_node = keys.reshape(N, LOCAL)
    for node in range(N):
        src = per_node[node] // LOCAL
        counts = np.bincount(src, minlength=N)
        np.testing.assert_array_equal(counts, np.full(N, LOCAL // N))


def test_locally_shuffled_and_seed_dependent():
    out7 = _distribute(_range_sharded_batch(), seed=7)
    out8 = _distribute(_range_sharded_batch(), seed=8)
    k7 = np.asarray(out7.key).reshape(N, LOCAL)
    k8 = np.asarray(out8.key).reshape(N, LOCAL)
    for node in range(N):
        # not sorted (the pre-exchange state was): a real local shuffle ran
        assert (np.diff(k7[node].astype(np.int64)) < 0).any()
        # same multiset per node across seeds is not required, but determinism
        # per seed is:
    np.testing.assert_array_equal(
        np.asarray(_distribute(_range_sharded_batch(), seed=7).key), k7.reshape(-1))
    assert (k7 != k8).any()


def test_wide_keys_travel():
    key = jnp.arange(N * LOCAL, dtype=jnp.uint32)
    batch = TupleBatch(key=key, rid=key, key_hi=key ^ jnp.uint32(0x5A5A5A5A))
    out = _distribute(batch)
    keys = np.asarray(out.key)
    np.testing.assert_array_equal(np.sort(keys), np.arange(N * LOCAL))
    # lanes stay aligned
    np.testing.assert_array_equal(np.asarray(out.key_hi),
                                  keys ^ np.uint32(0x5A5A5A5A))
