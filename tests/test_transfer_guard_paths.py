"""The chunked/grid and service paths under the runtime transfer guard.

tests/test_hash_join.py already proves the 8-way engine path is
guard-clean; these tests extend the same discipline to the other two
dispatch surfaces — the out-of-core chunked/grid engine (slab loop,
both-sides grid, and the pipelined prefetcher) and the service session
(submit/run_next with the sizing pre-pass and warm-cache reuse).  All
inputs are pre-placed with an explicit ``jax.device_put`` before the
fixture arms ``jax.transfer_guard("disallow")``; a failure here means a
code path regained an implicit host transfer the static ``transfer``
IR rule (analysis/jaxpr/rules_ir.py) and ``sync-point`` AST rule exist
to prevent.  These paths are clean today, so LINT_BASELINE.json carries
no transfer-guard survivors for them — keep it that way.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_radix_join.data.relation import host_join_count
from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.ops.chunked import chunked_join_count, chunked_join_grid

NODES = 8


def _placed_batch(keys):
    """TupleBatch pre-placed on device — explicit, so legal under the
    guard; anything the join then moves implicitly is a finding."""
    keys = np.asarray(keys, np.uint32)
    return TupleBatch(
        key=jax.device_put(jnp.asarray(keys)),
        rid=jax.device_put(jnp.arange(len(keys), dtype=jnp.uint32)))


@pytest.fixture
def guarded_inputs():
    rng = np.random.default_rng(14)
    r = rng.integers(0, 1024, 1 << 12).astype(np.uint32)
    s = rng.integers(0, 1024, 1 << 12).astype(np.uint32)
    return r, s, host_join_count(r, s)


def test_chunked_slab_loop_under_guard(guarded_inputs, transfer_guard):
    r, s, expect = guarded_inputs
    rb, sb = _placed_batch(r), _placed_batch(s)
    assert chunked_join_count(rb, sb, 1 << 10) == expect


def test_chunked_grid_under_guard(guarded_inputs, transfer_guard):
    r, s, expect = guarded_inputs
    r_chunks = [_placed_batch(r[:1 << 11]), _placed_batch(r[1 << 11:])]
    s_chunks = [_placed_batch(s[:1 << 11]), _placed_batch(s[1 << 11:])]
    assert chunked_join_grid(r_chunks, s_chunks, 1 << 10) == expect


def test_chunked_grid_pipelined_under_guard(guarded_inputs, transfer_guard):
    # the prefetcher thread stages the next pair while the current one
    # joins — its hand-off must also move no implicit bytes
    r, s, expect = guarded_inputs
    r_chunks = [_placed_batch(r[:1 << 11]), _placed_batch(r[1 << 11:])]
    s_chunks = [_placed_batch(s[:1 << 11]), _placed_batch(s[1 << 11:])]
    assert chunked_join_grid(r_chunks, s_chunks, 1 << 10,
                             pipeline="on") == expect


@pytest.mark.slow
def test_service_session_under_guard():
    """submit/run_next — cold (sizing pre-pass) then warm (capacity
    cache hit) — with the guard armed around the engine dispatches.
    The session generates its inputs on device from the request seed,
    so the whole query lifecycle stays implicit-transfer-free."""
    from tpu_radix_join import JoinConfig
    from tpu_radix_join.core.config import ServiceConfig
    from tpu_radix_join.performance import Measurements
    from tpu_radix_join.service import JoinSession, QueryRequest

    m = Measurements()
    sess = JoinSession(JoinConfig(num_nodes=NODES), ServiceConfig(),
                       measurements=m)
    try:
        sess.submit(QueryRequest(query_id="g0", tenant="t",
                                 tuples_per_node=1024, seed=7))
        sess.submit(QueryRequest(query_id="g1", tenant="t",
                                 tuples_per_node=1024, seed=7))
        with jax.transfer_guard("disallow"):
            cold = sess.run_next()
            warm = sess.run_next()
        assert cold.status == "ok" and warm.status == "ok"
        assert warm.matches == cold.matches
    finally:
        sess.close()
