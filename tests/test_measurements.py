"""Measurements layer tests: tag registry, .perf round trip, rank-0 style
aggregation, derived detail counters, and population through a real join
(SURVEY.md §5.1 parity)."""

import io

import pytest

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.performance import Measurements, print_results
from tpu_radix_join.performance import measurements as M


def test_store_load_roundtrip(tmp_path):
    m = Measurements(node_id=3, num_nodes=4)
    m.start(M.JTOTAL)
    m.stop(M.JTOTAL)
    m.incr(M.RESULTS, 42)
    m.incr(M.RTUPLES, 100)
    m.incr(M.STUPLES, 100)
    path = m.store(str(tmp_path))
    assert path.endswith("3.perf")
    (loaded,) = Measurements.load(str(tmp_path))
    assert loaded.node_id == 3
    assert loaded.counters[M.RESULTS] == 42
    assert loaded.times_us[M.JTOTAL] == round(m.times_us[M.JTOTAL])
    # store() derives rates from the counters + JTOTAL
    assert loaded.counters[M.JRATE] > 0


def test_record_exchange_details():
    m = Measurements()
    m.record_exchange(num_nodes=8, cap_r=1024, cap_s=2048)
    # each node ships N blocks per relation (2 relations)
    assert m.counters[M.MWINPUTCNT] == 16
    # 8B wire tuples per slot, N blocks of each capacity
    assert m.counters[M.MWINBYTES] == 8 * 8 * (1024 + 2048)
    assert m.counters[M.WINCAPR] == 1024
    assert m.counters[M.WINCAPS] == 2048


def test_print_results_aggregates():
    ms = []
    for node in range(4):
        m = Measurements(node_id=node, num_nodes=4)
        m.times_us[M.JTOTAL] = 100.0 * (node + 1)
        m.counters[M.RESULTS] = 7
        ms.append(m)
    buf = io.StringIO()
    agg = print_results(ms, file=buf)
    text = buf.getvalue()
    assert "[RESULTS] Tuples: 7" in text
    assert agg[M.JTOTAL]["max"] == 400.0
    assert agg[M.JTOTAL]["avg"] == 250.0


def test_memory_utilization():
    m = Measurements()
    mem = m.memory_utilization()
    # Linux host in this environment: VmSize/VmRSS must parse
    assert mem.get("VmSize", 0) > 0
    assert mem.get("VmRSS", 0) > 0
    assert m.meta["memory"] is mem


def test_join_populates_registry():
    m = Measurements(num_nodes=4)
    cfg = JoinConfig(num_nodes=4)
    size = 1 << 12
    r = Relation(size, 4, "unique", seed=1)
    s = Relation(size, 4, "unique", seed=2)
    res = HashJoin(cfg, measurements=m).join(r, s)
    assert res.matches == size
    for key in (M.JTOTAL, M.SWINALLOC, M.JPROC, M.JHIST):
        assert m.times_us[key] > 0
    # fused pipeline: the JMPI/JPROC split needs measure_phases
    assert M.JMPI not in m.times_us
    assert m.counters[M.RESULTS] == size
    assert m.counters[M.MWINPUTCNT] == 8
    assert m.counters[M.JRATE] > 0
    assert m.counters[M.JPROCRATE] >= m.counters[M.JRATE]


def test_measure_phases_records_jmpi_and_jproc():
    """config.measure_phases runs shuffle and probe as two programs; the
    .perf registry must carry all four headline phase columns
    (Measurements.cpp:136-141) with nonzero values, and the result must be
    identical to the fused pipeline's."""
    size = 1 << 12
    r = Relation(size, 4, "unique", seed=1)
    s = Relation(size, 4, "unique", seed=2)
    m = Measurements(num_nodes=4)
    cfg = JoinConfig(num_nodes=4, measure_phases=True)
    res = HashJoin(cfg, measurements=m).join(r, s)
    assert res.ok and res.matches == size
    for key in (M.JTOTAL, M.JHIST, M.JMPI, M.JPROC):
        assert m.times_us[key] > 0, key
    # the completion-wait component of JMPI (the fence) is SNETCOMPL
    assert 0 < m.times_us[M.SNETCOMPL] <= m.times_us[M.JMPI]
    fused = HashJoin(JoinConfig(num_nodes=4)).join(r, s)
    assert fused.matches == res.matches
    import numpy as np
    np.testing.assert_array_equal(fused.partition_counts,
                                  res.partition_counts)


def test_measure_phases_bucket_path_records_slocprep():
    """On the two-level/bucket discipline the phase split is three programs:
    shuffle (JMPI), local partitioning (SLOCPREP — the reference's
    local-preparation column), build-probe (JPROC); results must equal the
    fused pipeline's."""
    import numpy as np
    size = 1 << 12
    r = Relation(size, 4, "unique", seed=3)
    s = Relation(size, 4, "unique", seed=4)
    base = dict(num_nodes=4, two_level=True, local_fanout_bits=3,
                allocation_factor=3.0)
    m = Measurements(num_nodes=4)
    res = HashJoin(JoinConfig(**base, measure_phases=True),
                   measurements=m).join(r, s)
    assert res.ok and res.matches == size
    for key in (M.JTOTAL, M.JHIST, M.JMPI, M.SLOCPREP, M.JPROC):
        assert m.times_us[key] > 0, key
    # build/probe sub-columns (BPBUILD = batched row sort, BPPROBE = weight
    # scan, Measurements.cpp:471-542 analogs): nested inside JPROC, so they
    # bound it from below and sum to ~all of it (host glue allowed)
    assert m.times_us["BPBUILD"] > 0
    assert m.times_us["BPPROBE"] > 0
    assert m.times_us["BPBUILD"] + m.times_us["BPPROBE"] \
        <= m.times_us[M.JPROC] * 1.01
    assert m.counters["BPBUILDTUPLES"] > 0
    assert m.counters["BPPROBETUPLES"] > 0
    # derived histogram-rate tags exist once JHIST is recorded
    assert m.counters[M.HILOCRATE] > 0
    assert m.counters[M.HOLOCRATE] > 0
    fused = HashJoin(JoinConfig(**base)).join(r, s)
    np.testing.assert_array_equal(fused.partition_counts,
                                  res.partition_counts)


def test_measure_phases_skew_and_retry_mwinwait():
    """Phase-split execution composes with the skew split, and a retried
    (undersized) attempt's time lands in MWINWAIT, not JPROC."""
    import numpy as np
    import jax.numpy as jnp
    from tpu_radix_join.data.tuples import TupleBatch
    n, size = 8, 1 << 14
    half = size // 2
    rk = np.arange(size, dtype=np.uint32)
    sk = np.concatenate([np.full(half, 3, np.uint32),
                         np.arange(half, dtype=np.uint32)])
    r = TupleBatch(key=jnp.asarray(rk),
                   rid=jnp.arange(size, dtype=jnp.uint32))
    s = TupleBatch(key=jnp.asarray(sk),
                   rid=jnp.arange(size, dtype=jnp.uint32))
    m = Measurements(num_nodes=n)
    cfg = JoinConfig(num_nodes=n, skew_threshold=4.0, measure_phases=True,
                     max_retries=1)
    res = HashJoin(cfg, measurements=m).join_arrays(r, s)
    assert res.ok and res.matches == size
    assert m.times_us[M.JMPI] > 0 and m.times_us[M.JPROC] > 0
    # retry accounting: force a shortfall via static undersized windows,
    # through BOTH execution modes
    zr = TupleBatch(key=jnp.zeros(1 << 10, jnp.uint32),   # all partition 0
                    rid=jnp.arange(1 << 10, dtype=jnp.uint32))
    su = TupleBatch(key=jnp.arange(1 << 10, dtype=jnp.uint32),
                    rid=jnp.arange(1 << 10, dtype=jnp.uint32))
    for phases in (False, True):
        m2 = Measurements(num_nodes=4)
        cfg2 = JoinConfig(num_nodes=4, window_sizing="static",
                          allocation_factor=1.0, max_retries=3,
                          measure_phases=phases)
        res2 = HashJoin(cfg2, measurements=m2).join_arrays(zr, su)
        assert res2.ok
        assert m2.counters["RETRIES"] >= 1
        assert m2.times_us[M.MWINWAIT] > 0
        assert m2.times_us[M.JPROC] > 0
        if phases:
            # superseded attempts roll every phase column back, including
            # the JMPI-nested completion wait
            assert 0 < m2.times_us[M.SNETCOMPL] <= m2.times_us[M.JMPI]


def test_measure_phases_materialize():
    """join_materialize honors measure_phases: shuffle (JMPI+SNETCOMPL) and
    the rid-pair probe (JPROC) as two programs; identical pairs to fused."""
    size = 1 << 12
    r = Relation(size, 4, "unique", seed=5)
    s = Relation(size, 4, "modulo", modulo=size // 2, seed=6)
    base = dict(num_nodes=4, match_rate_cap=4)
    m = Measurements(num_nodes=4)
    split = HashJoin(JoinConfig(**base, measure_phases=True),
                     measurements=m).join_materialize(r, s)
    assert split.ok and split.matches == size
    for key in (M.JMPI, M.SNETCOMPL, M.JPROC):
        assert m.times_us[key] > 0, key
    fused = HashJoin(JoinConfig(**base)).join_materialize(r, s)
    assert (set(zip(split.r_rid.tolist(), split.s_rid.tolist()))
            == set(zip(fused.r_rid.tolist(), fused.s_rid.tolist())))


def test_jtotal_excludes_compile():
    """A cold join's JTOTAL must not contain its XLA compilation: the
    reference's phase timers never include compile (there is none at
    runtime, Measurements.cpp:137-141), and a compile-dominated JTOTAL made
    the CLI throughput line understate the engine ~50x (VERDICT r3 weak #5).
    JCOMPILE keeps the compile time under its own tag."""
    size = 1 << 12
    r = Relation(size, 4, "unique", seed=7)
    s = Relation(size, 4, "unique", seed=8)
    m = Measurements(num_nodes=4)
    res = HashJoin(JoinConfig(num_nodes=4, measure_phases=True),
                   measurements=m).join(r, s)
    assert res.ok and res.matches == size
    # cold run: several shard_map programs compile (seconds); execution is
    # milliseconds — a JTOTAL that still contained compile would dwarf it
    assert m.times_us[M.JCOMPILE] > 0
    assert m.times_us[M.JTOTAL] < m.times_us[M.JCOMPILE]
    # JTOTAL is the phases plus host glue: it must cover the split columns
    # (JHIST rides inside SWINALLOC) and stay in their ballpark rather than
    # the compiler's
    phases = (m.times_us[M.SWINALLOC] + m.times_us[M.JMPI]
              + m.times_us[M.JPROC])
    assert m.times_us[M.JTOTAL] >= m.times_us[M.JMPI] + m.times_us[M.JPROC]
    assert m.times_us[M.JTOTAL] <= phases + 0.5e6   # 0.5s host-glue slack


def test_exclude_from_running_only_shifts_running_timers():
    import time as _time
    m = Measurements()
    m.start(M.JTOTAL)
    _time.sleep(0.01)
    m.start("JCOMPILE")
    _time.sleep(0.02)
    dt = m.stop("JCOMPILE")
    m.exclude_from_running(dt)
    total = m.stop(M.JTOTAL)
    # the 20ms "compile" left JTOTAL; the 10ms before it remains
    assert total < dt
    assert m.times_us["JCOMPILE"] >= 20e3


def test_dispatch_floor_tag():
    """SDISPATCH is a per-run floor (assigned, not accumulated) so split
    phase columns can be read net of the host-attachment round trip."""
    m = Measurements()
    us = m.measure_dispatch_floor(iters=5)
    assert us > 0
    assert m.times_us[M.SDISPATCH] == us
    again = m.measure_dispatch_floor(iters=5)
    assert m.times_us[M.SDISPATCH] == again   # floor semantics, no +=


def test_load_skips_stray_perf_files(tmp_path):
    m = Measurements(node_id=0)
    m.times_us[M.JTOTAL] = 5.0
    m.store(str(tmp_path))
    (tmp_path / "notes.perf").write_text("not a rank file\n")
    loaded = Measurements.load(str(tmp_path))
    assert len(loaded) == 1 and loaded[0].node_id == 0


def test_profiler_trace_smoke(tmp_path):
    """Measurements.trace (the PAPI/CUDA-event analog) must produce a
    profiler artifact around device work AND parse it into registry data
    (the round-3 verdict's unfulfilled-passthrough finding): meta["trace"]
    carries the busiest-timeline per-op breakdown.  CTOTAL is recorded only
    from a real device plane, which the CPU backend does not emit."""
    import glob
    import jax.numpy as jnp
    m = Measurements()
    with m.trace(str(tmp_path)):
        jnp.sort(jnp.arange(1 << 16, dtype=jnp.uint32)).block_until_ready()
    assert glob.glob(str(tmp_path) + "/**/*.xplane.pb", recursive=True)
    tr = m.meta.get("trace")
    assert tr is not None and tr["ops"], "xplane parse produced no ops"
    assert tr["busy_us"] > 0
    # every op row carries aggregated duration + occurrence counts
    name, v = next(iter(tr["ops"].items()))
    assert v["us"] >= 0 and v["count"] >= 1


def test_trace_parser_roundtrip_against_tf_proto(tmp_path):
    """The hand-rolled xplane wire decoder must agree with the canonical
    generated protobuf (tensorflow.tsl) on a real trace artifact — guards
    the hardcoded field numbers."""
    import glob
    import jax.numpy as jnp
    m = Measurements()
    with m.trace(str(tmp_path), record=False):
        jnp.sort(jnp.arange(1 << 14, dtype=jnp.uint32)).block_until_ready()
    pb2 = pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
    from tpu_radix_join.performance.trace import parse_xspace
    path = glob.glob(str(tmp_path) + "/**/*.xplane.pb", recursive=True)[0]
    data = open(path, "rb").read()
    want = pb2.XSpace.FromString(data)
    got = parse_xspace(data)
    assert len(got) == len(want.planes)
    want_by_name = {p.name: p for p in want.planes}
    for gp in got:
        wp = want_by_name[gp["name"]]
        assert {i: n.display_name or n.name
                for i, n in wp.event_metadata.items()} == gp["metadata"]
        want_lines = {(ln.display_name or ln.name): ln for ln in wp.lines}
        for line_name, per_md in gp["lines"]:
            wl = want_lines[line_name]
            want_per_md = {}
            for ev in wl.events:
                acc = want_per_md.setdefault(ev.metadata_id, [0, 0])
                acc[0] += ev.duration_ps
                acc[1] += max(1, ev.num_occurrences)
            assert want_per_md == per_md, line_name


def test_slim_meta_preserves_failure_class_and_events_count():
    """gather_all's oversized-meta fallback must not drop the fields the
    aggregate report reads: failure_class (the [RESULTS] FailureClasses
    line), the epoch anchor (timeline merge), and how many trace events
    were lost to the truncation."""
    m = Measurements(node_id=2, num_nodes=4)
    m.meta["failure_class"] = "transient_fault"
    m.meta["giant"] = "x" * (1 << 17)
    m.event("fault_injected", site="A")
    m.event("retry", attempt=1)
    slim = m._slim_meta()
    assert slim["truncated"] is True
    assert slim["failure_class"] == "transient_fault"
    assert slim["epoch_s"] == m.meta["epoch_s"]
    assert slim["events_count"] == 2
    assert "giant" not in slim and "events" not in slim
    # a registry with no failure and no events stays minimal
    bare = Measurements()._slim_meta()
    assert "failure_class" not in bare and "events_count" not in bare
