"""Measurements layer tests: tag registry, .perf round trip, rank-0 style
aggregation, derived detail counters, and population through a real join
(SURVEY.md §5.1 parity)."""

import io

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.performance import Measurements, print_results
from tpu_radix_join.performance import measurements as M


def test_store_load_roundtrip(tmp_path):
    m = Measurements(node_id=3, num_nodes=4)
    m.start(M.JTOTAL)
    m.stop(M.JTOTAL)
    m.incr(M.RESULTS, 42)
    m.incr(M.RTUPLES, 100)
    m.incr(M.STUPLES, 100)
    path = m.store(str(tmp_path))
    assert path.endswith("3.perf")
    (loaded,) = Measurements.load(str(tmp_path))
    assert loaded.node_id == 3
    assert loaded.counters[M.RESULTS] == 42
    assert loaded.times_us[M.JTOTAL] == round(m.times_us[M.JTOTAL])
    # store() derives rates from the counters + JTOTAL
    assert loaded.counters[M.JRATE] > 0


def test_record_exchange_details():
    m = Measurements()
    m.record_exchange(num_nodes=8, cap_r=1024, cap_s=2048)
    # each node ships N blocks per relation (2 relations)
    assert m.counters[M.MWINPUTCNT] == 16
    # 8B wire tuples per slot, N blocks of each capacity
    assert m.counters[M.MWINBYTES] == 8 * 8 * (1024 + 2048)
    assert m.counters[M.WINCAPR] == 1024
    assert m.counters[M.WINCAPS] == 2048


def test_print_results_aggregates():
    ms = []
    for node in range(4):
        m = Measurements(node_id=node, num_nodes=4)
        m.times_us[M.JTOTAL] = 100.0 * (node + 1)
        m.counters[M.RESULTS] = 7
        ms.append(m)
    buf = io.StringIO()
    agg = print_results(ms, file=buf)
    text = buf.getvalue()
    assert "[RESULTS] Tuples: 7" in text
    assert agg[M.JTOTAL]["max"] == 400.0
    assert agg[M.JTOTAL]["avg"] == 250.0


def test_memory_utilization():
    m = Measurements()
    mem = m.memory_utilization()
    # Linux host in this environment: VmSize/VmRSS must parse
    assert mem.get("VmSize", 0) > 0
    assert mem.get("VmRSS", 0) > 0
    assert m.meta["memory"] is mem


def test_join_populates_registry():
    m = Measurements(num_nodes=4)
    cfg = JoinConfig(num_nodes=4)
    size = 1 << 12
    r = Relation(size, 4, "unique", seed=1)
    s = Relation(size, 4, "unique", seed=2)
    res = HashJoin(cfg, measurements=m).join(r, s)
    assert res.matches == size
    for key in (M.JTOTAL, M.SWINALLOC, M.JPROC):
        assert m.times_us[key] > 0
    assert m.counters[M.RESULTS] == size
    assert m.counters[M.MWINPUTCNT] == 8
    assert m.counters[M.JRATE] > 0
    assert m.counters[M.JPROCRATE] >= m.counters[M.JRATE]


def test_profiler_trace_smoke(tmp_path):
    """Measurements.trace (the PAPI/CUDA-event analog) must produce a
    profiler artifact around device work."""
    import glob
    import jax.numpy as jnp
    m = Measurements()
    with m.trace(str(tmp_path)):
        jnp.arange(1024).sum().block_until_ready()
    assert glob.glob(str(tmp_path) + "/**/*.pb*", recursive=True) or \
        glob.glob(str(tmp_path) + "/**/*.json*", recursive=True)
