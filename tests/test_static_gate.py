"""tools_static_gate.py: the merged AST + IR gate as a tier-1 test,
plus the regress-gate direction pins for the new static counters."""

import json
import os
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def test_repo_passes_the_merged_static_gate(tmp_path, capsys):
    """The gating check itself: graftlint --strict + graftcheck --strict
    over the committed tree.  A live finding, a stale baseline entry, or
    a trace failure in either layer fails this test — which is the
    point."""
    import tools_static_gate
    out_json = tmp_path / "gate.json"
    rc = tools_static_gate.main(["--json", str(out_json)])
    printed = capsys.readouterr().out
    assert rc == 0, printed
    summary = json.loads(out_json.read_text())
    assert summary["gate_exit"] == 0
    assert summary["layers"] == {"lint": 0, "jaxpr": 0}
    assert summary["lint_findings"] == 0
    assert summary["jaxpr_findings"] == 0
    assert summary["stale_baseline"] == 0
    # the IR layer really traced the engine (stats carry live-set peaks)
    assert summary["jaxpr_stats"]["pipeline"]["peak_live_bytes"] > 0
    assert "== graftlint (AST) ==" in printed
    assert "== graftcheck (jaxpr IR) ==" in printed


def test_gate_merges_worst_exit(monkeypatch, tmp_path):
    import tools_jaxpr_audit
    import tools_lint
    import tools_static_gate
    monkeypatch.setattr(tools_lint, "main", lambda argv: 0)
    monkeypatch.setattr(tools_jaxpr_audit, "main", lambda argv: 1)
    assert tools_static_gate.main([]) == 1
    monkeypatch.setattr(tools_jaxpr_audit, "main", lambda argv: 2)
    assert tools_static_gate.main([]) == 2
    monkeypatch.setattr(tools_jaxpr_audit, "main", lambda argv: 0)
    assert tools_static_gate.main([]) == 0
    # --skip-jaxpr consults only the AST layer
    monkeypatch.setattr(tools_jaxpr_audit, "main",
                        lambda argv: pytest.fail("traced despite skip"))
    assert tools_static_gate.main(["--skip-jaxpr"]) == 0


def test_audit_cli_contract(tmp_path, capsys):
    """tools_jaxpr_audit.py: exit 0 clean + JSON counts + exit 2 on a
    bad baseline (the mandatory-reason contract)."""
    import tools_jaxpr_audit
    out_json = tmp_path / "audit.json"
    rc = tools_jaxpr_audit.main(["--entry", "pipeline",
                                 "--json", str(out_json)])
    assert rc == 0
    summary = json.loads(out_json.read_text())
    assert summary["jaxpr_findings"] == 0
    assert summary["entries"] == ["pipeline"]
    capsys.readouterr()
    bad = tmp_path / "bad_baseline.json"
    bad.write_text(json.dumps({"suppressions": [
        {"rule": "donation", "path": "p", "key": "k", "reason": ""}]}))
    rc = tools_jaxpr_audit.main(["--entry", "pipeline",
                                 "--baseline", str(bad)])
    assert rc == 2
    assert "reason" in capsys.readouterr().err
    # a deliberately tiny budget turns the clean trace into findings
    rc = tools_jaxpr_audit.main(["--entry", "pipeline", "--no-baseline",
                                 "--memory-budget", "4096"])
    assert rc == 1
    assert "static-memory" in capsys.readouterr().out


def test_regress_pins_static_counters():
    from tpu_radix_join.observability.regress import (NEUTRAL_TAGS,
                                                      higher_is_better,
                                                      tag_is_declared)
    # JSON gauge names: more findings / stale entries is strictly worse
    assert not higher_is_better("jaxpr_findings")
    assert not higher_is_better("stale_baseline")
    assert not higher_is_better("lint_findings")
    # counter tags: JXAUDIT gates lower-better, STATICMEM is geometry
    assert not higher_is_better("JXAUDIT")
    assert "STATICMEM" in NEUTRAL_TAGS
    assert tag_is_declared("JXAUDIT") and tag_is_declared("STATICMEM")
