"""Distributed out-of-core probe (config.chunk_size -> lax.scan slabs): same
exact counts as the resident probe, on the mesh — the LD capability
(kernels.cu:778-856) inside the SPMD pipeline."""

import jax.numpy as jnp
import numpy as np

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.data.tuples import CompressedBatch
from tpu_radix_join.ops.build_probe import (
    probe_count_chunked,
    probe_count_per_partition,
)


def test_op_matches_resident_probe():
    rng = np.random.default_rng(7)
    r = CompressedBatch(
        key_rem=jnp.asarray(rng.integers(0, 500, 1 << 12, dtype=np.uint32)),
        rid=jnp.arange(1 << 12, dtype=jnp.uint32))
    s = CompressedBatch(
        key_rem=jnp.asarray(rng.integers(0, 500, 3000, dtype=np.uint32)),
        rid=jnp.arange(3000, dtype=jnp.uint32))
    pid = (s.key_rem & jnp.uint32(15)).astype(jnp.uint32)
    resident = probe_count_per_partition(r, s, pid, 16)
    for slab in (256, 1000, 4096):   # divides, ragged, bigger-than-input
        chunked = probe_count_chunked(r, s, pid, 16, slab)
        np.testing.assert_array_equal(np.asarray(resident),
                                      np.asarray(chunked))


def test_join_with_chunking_exact():
    size = 1 << 14
    for nodes in (1, 8):
        cfg = JoinConfig(num_nodes=nodes, network_fanout_bits=4,
                         chunk_size=1 << 10)
        r = Relation(size, nodes, "unique", seed=1)
        s = Relation(size, nodes, "unique", seed=9)
        res = HashJoin(cfg).join(r, s)
        assert res.ok
        assert res.matches == size


def test_join_chunked_skew():
    cfg = JoinConfig(num_nodes=8, chunk_size=1 << 9,
                     assignment_policy="load_aware", allocation_factor=4.0)
    r = Relation(1 << 13, 8, "unique", seed=1)
    s = Relation(1 << 13, 8, "zipf", zipf_theta=0.75, key_domain=1 << 13,
                 seed=3)
    res = HashJoin(cfg).join(r, s)
    assert res.ok
    assert res.matches == (1 << 13)
