"""Distributed out-of-core probe (config.chunk_size -> lax.scan slabs): same
exact counts as the resident probe, on the mesh — the LD capability
(kernels.cu:778-856) inside the SPMD pipeline."""

import jax.numpy as jnp
import numpy as np

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.data.tuples import CompressedBatch
from tpu_radix_join.ops.build_probe import (
    probe_count_chunked,
    probe_count_per_partition,
    probe_materialize,
    probe_materialize_chunked,
)


def _pairs(m):
    """Set of materialized (r_rid, s_rid) pairs from a MaterializedMatches."""
    v = np.asarray(m.valid)
    return set(zip(np.asarray(m.r_rid)[v].tolist(),
                   np.asarray(m.s_rid)[v].tolist()))


def test_op_matches_resident_probe():
    rng = np.random.default_rng(7)
    r = CompressedBatch(
        key_rem=jnp.asarray(rng.integers(0, 500, 1 << 12, dtype=np.uint32)),
        rid=jnp.arange(1 << 12, dtype=jnp.uint32))
    s = CompressedBatch(
        key_rem=jnp.asarray(rng.integers(0, 500, 3000, dtype=np.uint32)),
        rid=jnp.arange(3000, dtype=jnp.uint32))
    pid = (s.key_rem & jnp.uint32(15)).astype(jnp.uint32)
    resident = probe_count_per_partition(r, s, pid, 16)
    for slab in (256, 1000, 4096):   # divides, ragged, bigger-than-input
        chunked = probe_count_chunked(r, s, pid, 16, slab)
        np.testing.assert_array_equal(np.asarray(resident),
                                      np.asarray(chunked))


def test_join_with_chunking_exact():
    size = 1 << 14
    for nodes in (1, 8):
        cfg = JoinConfig(num_nodes=nodes, network_fanout_bits=4,
                         chunk_size=1 << 10)
        r = Relation(size, nodes, "unique", seed=1)
        s = Relation(size, nodes, "unique", seed=9)
        res = HashJoin(cfg).join(r, s)
        assert res.ok
        assert res.matches == size


def test_materialize_chunked_op_matches_resident():
    """probe_materialize_chunked emits exactly the pairs probe_materialize
    does (kernels.cu:778-856: the LD probe's output-writing form), for
    dividing, ragged, and oversize slabs — narrow and wide keys."""
    rng = np.random.default_rng(11)
    rk = rng.integers(0, 800, 1 << 11, dtype=np.uint32)
    sk = rng.integers(0, 800, 1500, dtype=np.uint32)
    r = CompressedBatch(key_rem=jnp.asarray(rk),
                        rid=jnp.arange(len(rk), dtype=jnp.uint32))
    s = CompressedBatch(key_rem=jnp.asarray(sk),
                        rid=jnp.arange(len(sk), dtype=jnp.uint32))
    resident = probe_materialize(r, s, cap=8)
    want = _pairs(resident)
    assert int(resident.overflow) == 0
    for slab in (256, 700, 4096):
        got = probe_materialize_chunked(r, s, cap=8, slab_size=slab)
        assert int(got.overflow) == 0
        assert _pairs(got) == want
    # wide keys: hi lane distinguishes otherwise-equal lo lanes
    r_w = CompressedBatch(key_rem=r.key_rem, rid=r.rid,
                          key_rem_hi=jnp.asarray(rk & np.uint32(3)))
    s_w = CompressedBatch(key_rem=s.key_rem, rid=s.rid,
                          key_rem_hi=jnp.asarray(sk & np.uint32(3)))
    want_w = _pairs(probe_materialize(r_w, s_w, cap=8))
    got_w = probe_materialize_chunked(r_w, s_w, cap=8, slab_size=300)
    assert _pairs(got_w) == want_w
    assert want_w == want   # hi = f(lo) here, so the pair set is unchanged
    # compaction guarantee: wide chunked output is n_outer_padded * cap —
    # shrinking the slab must never inflate the result buffer
    n_padded = -(-s.size // 300) * 300
    assert got_w.r_rid.shape == (n_padded * 8,)


def test_materialize_chunked_overflow_detected():
    r = CompressedBatch(key_rem=jnp.zeros(64, jnp.uint32),   # 64 dup keys
                        rid=jnp.arange(64, dtype=jnp.uint32))
    s = CompressedBatch(key_rem=jnp.zeros(8, jnp.uint32),
                        rid=jnp.arange(8, dtype=jnp.uint32))
    m = probe_materialize_chunked(r, s, cap=4, slab_size=4)
    assert int(m.overflow) == 8   # every outer tuple exceeds the cap


def test_join_materialize_chunked_matches_unchunked():
    """Distributed chunked materialize == unchunked pipeline (VERDICT r2
    next #7 done-check), narrow and 64-bit keys."""
    size = 1 << 12
    for key_bits in (32, 64):
        base = dict(num_nodes=4, network_fanout_bits=4, key_bits=key_bits,
                    match_rate_cap=4)
        r = Relation(size, 4, "unique", seed=31, key_bits=key_bits)
        s = Relation(size, 4, "modulo", modulo=size // 2, seed=32,
                     key_bits=key_bits)
        plain = HashJoin(JoinConfig(**base)).join_materialize(r, s)
        chunked = HashJoin(JoinConfig(**base, chunk_size=512)
                           ).join_materialize(r, s)
        assert plain.ok and chunked.ok, (plain.diagnostics,
                                         chunked.diagnostics)
        assert chunked.matches == plain.matches == size
        want = set(zip(plain.r_rid.tolist(), plain.s_rid.tolist()))
        got = set(zip(chunked.r_rid.tolist(), chunked.s_rid.tolist()))
        assert got == want


def test_join_chunked_skew():
    cfg = JoinConfig(num_nodes=8, chunk_size=1 << 9,
                     assignment_policy="load_aware", allocation_factor=4.0)
    r = Relation(1 << 13, 8, "unique", seed=1)
    s = Relation(1 << 13, 8, "zipf", zipf_theta=0.75, key_domain=1 << 13,
                 seed=3)
    res = HashJoin(cfg).join(r, s)
    assert res.ok
    assert res.matches == (1 << 13)
