"""Tier-1 coverage for the chaos/soak harness (robustness/chaos.py): the
fixed-seed mini-soak invariant (every run passes or fails classified),
schedule determinism and JSON round-trips, and delta-debug shrinking of a
violating schedule to a minimal replayable ``(seed, arms)`` repro.  The
larger randomized soak rides behind ``-m slow``."""

import json

import pytest

from tpu_radix_join.robustness import chaos, faults

SOAK_RUNS = 25
SOAK_SEED = 100


@pytest.fixture(scope="module")
def runner():
    """One cached engine for the whole module: per-test construction would
    recompile the pipeline for every case."""
    return chaos.ChaosRunner(num_nodes=4, size=1 << 12, verify="check")


def test_generate_schedule_deterministic_and_bounded():
    a = chaos.generate_schedule(42)
    assert a == chaos.generate_schedule(42)
    assert a != chaos.generate_schedule(43)
    assert 1 <= len(a.arms) <= len(chaos.CHAOS_SITES)
    assert all(site in chaos.CHAOS_SITES for site, _ in a.arms)


def test_schedule_json_round_trip():
    sched = chaos.generate_schedule(7)
    again = chaos.Schedule.from_json(
        json.loads(json.dumps(sched.to_json())))
    assert again == sched


def test_mini_soak_invariant_holds(runner):
    """The tentpole acceptance gate: 25 fixed-seed schedules, every run
    passes or fails with a named failure class — zero violations."""
    outcomes, summary = chaos.soak(SOAK_RUNS, base_seed=SOAK_SEED,
                                   runner=runner)
    assert summary["violations"] == 0, [
        o.to_json() for o in outcomes if o.status == chaos.VIOLATION]
    assert summary["pass"] + summary["classified"] == SOAK_RUNS
    # the schedule pool actually exercises every chaos failure mode
    assert "data_corruption" in summary["failure_classes"]
    assert "capacity_overflow" in summary["failure_classes"]
    assert "device_unavailable" in summary["failure_classes"]


def test_soak_outcomes_replay(runner):
    """(seed, arms) is the repro: re-running any schedule reproduces the
    same status, class, and count."""
    first, _ = chaos.soak(3, base_seed=SOAK_SEED, runner=runner)
    for out in first:
        again = runner.run(out.schedule)
        assert (again.status, again.failure_class, again.matches) == \
            (out.status, out.failure_class, out.matches)


def test_shrink_violating_schedule_to_minimal_repro():
    """An unprotected (verify=off) engine turns the corruption arm into a
    genuine silent-wrong-count violation; shrink must strip the inert arm
    and the minimal schedule must replay deterministically."""
    unprotected = chaos.ChaosRunner(num_nodes=4, size=1 << 12, verify="off")

    def violates(s):
        return unprotected.run(s).status == chaos.VIOLATION

    sched = chaos.Schedule(seed=11, arms=(
        (faults.EXCHANGE_CORRUPT, (("at", 1),)),
        (faults.SHUFFLE_OVERFLOW, (("at", 2),)),   # never consulted twice
    ))
    shrunk = chaos.shrink(sched, violates)
    assert len(shrunk.arms) == 1
    assert shrunk.arms[0][0] == faults.EXCHANGE_CORRUPT
    a, b = unprotected.run(shrunk), unprotected.run(shrunk)
    assert a.status == b.status == chaos.VIOLATION
    assert a.matches == b.matches != unprotected.oracle
    assert "silent wrong count" in a.detail


def test_shrink_requires_violation(runner):
    clean = chaos.Schedule(seed=0, arms=())
    with pytest.raises(ValueError, match="violating"):
        chaos.shrink(clean, lambda s: False)


def test_write_repro_round_trips(tmp_path, runner):
    out = runner.run(chaos.generate_schedule(SOAK_SEED))
    path = tmp_path / "repro.json"
    line = chaos.write_repro(out, path)
    obj = json.loads(path.read_text())
    assert json.loads(line) == obj
    assert chaos.Schedule.from_json(obj["schedule"]) == out.schedule


@pytest.mark.slow
def test_randomized_soak_long():
    """Full soak: a wider randomized seed range across both verify modes.
    Excluded from tier-1 (-m 'not slow'); run explicitly before releases."""
    for verify in ("check", "repair"):
        runner = chaos.ChaosRunner(num_nodes=4, size=1 << 12, verify=verify)
        outcomes, summary = chaos.soak(100, base_seed=1000, runner=runner)
        assert summary["violations"] == 0, [
            o.to_json() for o in outcomes if o.status == chaos.VIOLATION]
