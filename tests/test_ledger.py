"""Cross-run telemetry ledger (observability/ledger.py), compile-event
telemetry (observability/compilemon.py), and metrics-heartbeat size-cap
rotation (observability/metrics.py).

The ledger is the planner's long-term memory: these tests pin the row
schema, the tolerant-reader discipline (torn lines, newer schemas), the
payload builders the run-end/per-query/bench writers use, and the
artifact backfill path the committed history flows through."""

import json
import os
import subprocess
import sys

import pytest

from tpu_radix_join.observability.ledger import (BENCH_DEFAULT_SIZE,
                                                 LEDGER_SCHEMA_VERSION,
                                                 Ledger, bench_payload,
                                                 default_ledger_dir,
                                                 ingest_artifacts, load_rows,
                                                 rows_from_perf_dir,
                                                 run_payload)
from tpu_radix_join.performance.measurements import (COMPILEMS, NCOMPILE,
                                                     WIREBYTES, Measurements)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------- core I/O
def test_append_rows_roundtrip_and_kind_filter(tmp_path):
    led = Ledger(str(tmp_path))
    r1 = led.append("run", {"counters": {"JTOTAL": 1}})
    r2 = led.append("bench", {"metric": "m", "value": 1.0})
    assert r1["schema_version"] == LEDGER_SCHEMA_VERSION
    assert r1["run_id"] and r1["run_id"] != r2["run_id"]
    assert led.path.endswith("ledger.jsonl")
    assert [r["kind"] for r in led.rows()] == ["run", "bench"]
    assert [r["kind"] for r in led.rows(kind="bench")] == ["bench"]


def test_append_rejects_unknown_kind(tmp_path):
    with pytest.raises(ValueError, match="kind"):
        Ledger(str(tmp_path)).append("nope", {})


def test_explicit_jsonl_path_and_custom_run_id(tmp_path):
    path = str(tmp_path / "custom.jsonl")
    row = Ledger(path).append("obs", {"constant": "hbm_gbps", "value": 1.0},
                              run_id="my-run", t_epoch_s=123.0)
    assert row["run_id"] == "my-run" and row["t_epoch_s"] == 123.0
    assert load_rows(path)[0]["constant"] == "hbm_gbps"


def test_reader_skips_torn_lines_and_newer_schema(tmp_path):
    led = Ledger(str(tmp_path))
    led.append("run", {"a": 1})
    with open(led.path, "a") as f:
        f.write(json.dumps({"schema_version": LEDGER_SCHEMA_VERSION + 1,
                            "kind": "run", "future": True}) + "\n")
        f.write('{"kind": "run", "torn...')      # killed-writer tail
    rows = load_rows(led.path)
    assert len(rows) == 1 and rows[0]["a"] == 1


def test_missing_ledger_reads_empty(tmp_path):
    assert load_rows(str(tmp_path / "absent")) == []


def test_default_ledger_dir_env_override(monkeypatch):
    monkeypatch.setenv("TPU_RADIX_LEDGER_DIR", "/x/y")
    assert default_ledger_dir() == "/x/y"
    monkeypatch.delenv("TPU_RADIX_LEDGER_DIR")
    assert default_ledger_dir() == os.path.join("artifacts", "ledger")


# ------------------------------------------------------------------- payloads
def test_run_payload_distills_registry():
    m = Measurements(node_id=0, num_nodes=2)
    m.add_time_us("JTOTAL", 5000.0)
    m.incr(WIREBYTES, by=4096)
    m.counters["ZERO"] = 0                       # zero counters are dropped
    m.meta.update(tuples_per_node=1 << 10, global_size=1 << 11, nodes=2,
                  plan_vs_actual={"drift_pct": 3.0},
                  config={"repeat": 2, "nested": {"x": 1}})
    p = run_payload(m)
    assert p["times_us"]["JTOTAL"] == 5000.0
    assert p["counters"] == {"WIREBYTES": 4096}
    assert p["workload"]["global_size"] == 1 << 11
    assert p["plan_vs_actual"]["drift_pct"] == 3.0
    assert p["repeat"] == 2
    assert "nested" not in p["config"]           # scalars only
    assert "host" in p["fingerprint"]


def test_bench_payload_unwraps_runner_wrapper():
    doc = {"n": 1, "rc": 0, "parsed": {"metric": "m", "value": 2.5,
                                       "unit": "u", "extra": 7,
                                       "planned": {"strategy": "x"}}}
    p = bench_payload(doc)
    assert p["metric"] == "m" and p["value"] == 2.5 and p["rc"] == 0
    assert p["size"] == BENCH_DEFAULT_SIZE       # pre-"size" rounds
    assert p["extra"] == 7 and "planned" not in p    # scalars only
    assert bench_payload({"rc": 2, "tail": "died"}) is None
    assert bench_payload({"metric": "m", "value": 1.0,
                          "size": 64})["size"] == 64


def test_rows_from_perf_dir_roundtrip(tmp_path):
    m = Measurements(node_id=0, num_nodes=1)
    m.add_time_us("JTOTAL", 1000.0)
    m.meta.update(tuples_per_node=256, global_size=256, nodes=1)
    m.store(str(tmp_path))
    rows = rows_from_perf_dir(str(tmp_path))
    assert len(rows) == 1
    run_id, payload = rows[0]
    assert run_id.endswith(":0")
    assert payload["times_us"]["JTOTAL"] == 1000.0
    assert payload["workload"]["global_size"] == 256


def test_ingest_artifacts_backfills_committed_history(tmp_path):
    out = str(tmp_path / "ledger")
    counts = ingest_artifacts(os.path.join(REPO, "artifacts"), out)
    # BENCH_r01/r02 parsed; r03..r05 died before their JSON line (rc=2)
    assert counts["bench"] == 2
    assert counts["run"] >= 1                    # committed chip perf dirs
    rows = load_rows(out)
    bench = [r for r in rows if r["kind"] == "bench"]
    assert {r["run_id"] for r in bench} == {"BENCH_r01", "BENCH_r02"}
    assert all(r["metric"] == "single_chip_join_throughput" for r in bench)


def test_emit_ledger_cli(tmp_path):
    out = subprocess.run(
        [sys.executable, "tools_make_report.py",
         os.path.join(REPO, "artifacts"), "--emit-ledger",
         str(tmp_path / "led")],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "2 bench row(s)" in out.stdout
    assert load_rows(str(tmp_path / "led"))


# -------------------------------------------------------------- compilemon
def test_compile_monitor_counts_backend_compiles():
    import jax
    import jax.numpy as jnp

    from tpu_radix_join.observability.compilemon import (
        install_compile_monitor, uninstall_compile_monitor)

    m = Measurements(node_id=0, num_nodes=1)
    install_compile_monitor(m)
    install_compile_monitor(m)                   # idempotent
    try:
        # a fresh closure + unique shape forces a real backend compile
        fn = jax.jit(lambda a: a * jnp.int32(3) + jnp.int32(41))
        jax.block_until_ready(fn(jnp.arange(641, dtype=jnp.int32)))
        assert m.counters.get(NCOMPILE, 0) >= 1
        assert COMPILEMS in m.counters
    finally:
        uninstall_compile_monitor(m)
    n = m.counters.get(NCOMPILE, 0)
    fn2 = jax.jit(lambda a: a - jnp.int32(7))
    jax.block_until_ready(fn2(jnp.arange(643, dtype=jnp.int32)))
    assert m.counters.get(NCOMPILE, 0) == n      # uninstalled: inert


# ------------------------------------------------------- heartbeat rotation
def test_metrics_sampler_rotates_at_size_cap(tmp_path):
    from tpu_radix_join.observability.metrics import (MetricsSampler,
                                                      load_samples)

    path = str(tmp_path / "0.metrics.jsonl")
    s = MetricsSampler(path, interval_s=60.0, rotate_bytes=600,
                       rotate_keep=2)
    s._file = open(path, "a")                    # sample without the thread
    for _ in range(40):
        s.sample()
    s._file.close()
    s._file = None
    assert s.rotations >= 2
    assert os.path.getsize(path) < 600 + 2048    # live file stays bounded
    assert os.path.exists(path + ".1") and os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3")       # beyond keep: dropped
    merged = load_samples(path, include_rotated=True)
    assert len(merged) > len(load_samples(path))
    ts = [r["t_epoch_s"] for r in merged]
    assert ts == sorted(ts)                      # chronological across cap


def test_metrics_sampler_rejects_bad_rotation_params(tmp_path):
    from tpu_radix_join.observability.metrics import MetricsSampler
    with pytest.raises(ValueError):
        MetricsSampler(str(tmp_path / "m"), rotate_bytes=0)
    with pytest.raises(ValueError):
        MetricsSampler(str(tmp_path / "m"), rotate_keep=0)


def test_load_samples_missing_live_file_still_raises(tmp_path):
    from tpu_radix_join.observability.metrics import load_samples
    with pytest.raises(OSError):
        load_samples(str(tmp_path / "absent.metrics.jsonl"))
