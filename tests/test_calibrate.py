"""Profile auto-calibration loop (planner/calibrate.py,
tools_profile_fit.py, --profile auto): ground-truth constants are
recovered from synthetic ledger samples within the reported CI, stale
constants trip on injected persistent drift, schema-v3 provenance
round-trips while v1/v2 profiles keep loading, and under-sampled fits
are refused at the CLI boundary."""

import json
import os
import subprocess
import sys

import pytest

from tpu_radix_join.observability.ledger import Ledger
from tpu_radix_join.planner.calibrate import (TERM_TO_CONSTANT,
                                              UnderSampledError,
                                              collect_samples, detect_stale,
                                              diff_profiles, fit_profile,
                                              robust_fit)
from tpu_radix_join.planner.profile import (FITTED_PROFILE_BASENAME,
                                            SORT_REF_ELEMS, DeviceProfile,
                                            format_provenance, load_profile,
                                            resolve_profile,
                                            sort_stage_units)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_row(unit_ms, size=1 << 24, rid="b0"):
    """A bench row whose throughput encodes a known sort-stage unit."""
    union = 2 * size
    t_ms = unit_ms * (union / SORT_REF_ELEMS) * sort_stage_units(union)
    return {"kind": "bench", "run_id": rid,
            "metric": "single_chip_join_throughput",
            "value": union / (t_ms / 1e3), "size": size}


def _partition_row(unit_ms, size=1 << 24, rid="p0"):
    """A --partition-bench row whose fused-kernel wall encodes a known
    ms/Mtuple/pass unit (the kernel makes two passes over the ids)."""
    kernel_ms = unit_ms * 2.0 * size / 1e6
    return {"kind": "bench", "run_id": rid,
            "metric": "partition_fused_speedup", "value": 1.7,
            "size": size,
            "partition_kernel_ms": kernel_ms,
            "partition_ms": kernel_ms * 1.6,
            "partition_sort_ms": kernel_ms * 2.8,
            "partition_unit_ms": unit_ms}


def _drift_row(rid, drift_pct, term="shuffle", predicted_ms=40.0):
    return {"kind": "run", "run_id": rid,
            "plan_vs_actual": {"drift_pct": drift_pct,
                               "terms": [
                                   {"term": term,
                                    "predicted_ms": predicted_ms,
                                    "actual_ms": None},
                                   {"term": "dispatch", "predicted_ms": 1.0,
                                    "actual_ms": None}]}}


# ------------------------------------------------------------ sample -> fit
def test_sort_unit_recovered_within_ci():
    truth = 0.25
    rows = [_bench_row(truth * f, rid=f"b{i}")
            for i, f in enumerate((0.97, 1.0, 1.02, 1.01, 0.99))]
    prof, fits = fit_profile(rows, base=load_profile())
    fit = fits["sort_stage_unit_ms"]
    lo, hi = fit.ci95
    assert lo <= truth <= hi
    assert abs(fit.value - truth) / truth < 0.05
    assert fit.n == 5 and "b0" in fit.runs


def test_dispatch_and_ici_samples_from_run_rows():
    rows = []
    for i in range(3):
        rows.append({"kind": "run", "run_id": f"r{i}",
                     "times_us": {"SDISPATCH": 98_000.0 + i * 1000,
                                  "JMPI": 1_000_000.0},
                     "counters": {"WIREBYTES": 50_000_000_000}})
    # tiny-run intercept: JTOTAL at <= 64K tuples is pure floor
    rows.append({"kind": "run", "run_id": "tiny",
                 "times_us": {"JTOTAL": 101_000.0},
                 "workload": {"global_size": 4096}})
    samples = collect_samples(rows)
    assert len(samples["dispatch_floor_ms"]) == 4
    assert len(samples["ici_bytes_per_s"]) == 3
    _, fits = fit_profile(rows, base=load_profile())
    assert abs(fits["dispatch_floor_ms"].value - 99.0) < 3.0
    assert fits["ici_bytes_per_s"].value == pytest.approx(5e10)


def test_partition_unit_recovered_within_ci():
    truth = 0.09
    rows = [_partition_row(truth * f, rid=f"p{i}")
            for i, f in enumerate((0.98, 1.0, 1.03, 1.0, 0.99))]
    prof, fits = fit_profile(rows, base=load_profile())
    fit = fits["partition_pass_unit_ms"]
    lo, hi = fit.ci95
    assert lo <= truth <= hi
    assert abs(fit.value - truth) / truth < 0.05
    prov = prof.provenance("partition_pass_unit_ms")
    assert prov["origin"] == "fit" and prov["n"] == 5
    assert "p0" in prov["runs"]


def test_partition_unit_falls_back_to_reduced_tag():
    # a row missing the primary kernel wall still contributes through the
    # pre-reduced partition_unit_ms tag
    row = _partition_row(0.08, rid="p9")
    del row["partition_kernel_ms"]
    samples = collect_samples([row])
    assert [s.value for s in samples["partition_pass_unit_ms"]] == [0.08]


def test_obs_rows_feed_any_constant():
    rows = [{"kind": "obs", "run_id": f"o{i}", "constant": "hbm_gbps",
             "value": 100.0 + i} for i in range(3)]
    _, fits = fit_profile(rows, base=load_profile())
    assert fits["hbm_gbps"].value == 101.0


def test_robust_fit_resists_outlier():
    from tpu_radix_join.planner.calibrate import Sample
    vals = [1.0, 1.01, 0.99, 1.02, 50.0]          # one cold-cache outlier
    fit = robust_fit([Sample(v, f"r{i}") for i, v in enumerate(vals)])
    assert abs(fit.value - 1.0) < 0.05


def test_under_sampled_fit_refused():
    with pytest.raises(UnderSampledError):
        fit_profile([], base=load_profile())
    with pytest.raises(UnderSampledError):
        # one sample < min_samples=2
        fit_profile([_bench_row(0.2)], base=load_profile())


# ------------------------------------------------------------ schema v3
def test_v3_profile_roundtrips_with_provenance(tmp_path):
    rows = [_bench_row(0.2, rid=f"b{i}") for i in range(2)]
    prof, _ = fit_profile(rows, base=load_profile(), fitted_at=1000.0)
    path = str(tmp_path / "p.json")
    prof.save(path)
    back = load_profile(path)
    assert back.schema_version == 6
    prov = back.provenance("sort_stage_unit_ms")
    assert prov["origin"] == "fit" and prov["n"] == 2
    assert prov["runs"] == ["b0", "b1"]
    assert len(prov["ci95"]) == 2 and prov["fitted_at_epoch_s"] == 1000.0
    assert back.freshness() == 1000.0
    # every constant carries provenance, fitted or inherited
    assert all(back.provenance(k) is not None for k in back.constants)
    assert back.provenance("hbm_gbps")["origin"] == "committed"


def test_v1_shim_and_committed_still_load(tmp_path):
    committed = load_profile("v5e_lite")          # the checked-in v6
    assert committed.schema_version == 6
    assert committed.freshness() is None          # no provenance: never fit
    v1 = {"schema_version": 1, "name": "old",
          "constants": {k: dict(committed.constants[k])
                        for k in committed.constants
                        if k not in ("ici_bytes_per_s",
                                     "partition_pass_unit_ms",
                                     "radix_sort_pass_unit_ms",
                                     "result_cache_lookup_ms")}}
    path = str(tmp_path / "v1.json")
    with open(path, "w") as f:
        json.dump(v1, f)
    back = load_profile(path)
    assert back.value("ici_bytes_per_s") == committed.value("ici_gbps") * 1e9
    # v4 shim: the partition pass unit derives from the cited bandwidth
    assert back.value("partition_pass_unit_ms") == pytest.approx(
        8.0 / committed.value("hbm_gbps"), rel=1e-3)
    assert back.source("partition_pass_unit_ms").startswith("shim:")
    # v5 shim: the flat-sort pass unit derives from the same bandwidth
    assert back.value("radix_sort_pass_unit_ms") == pytest.approx(
        12.0 / committed.value("hbm_gbps"), rel=1e-3)
    assert back.source("radix_sort_pass_unit_ms").startswith("shim:")
    # v6 shim: the result-cache probe derives from the dispatch floor
    assert back.value("result_cache_lookup_ms") == pytest.approx(
        committed.value("dispatch_floor_ms") / 10.0, rel=1e-3)
    assert back.source("result_cache_lookup_ms").startswith("shim:")


def test_v3_profile_shims_partition_unit(tmp_path):
    committed = load_profile("v5e_lite")
    v3 = {"schema_version": 3, "name": "old3",
          "constants": {k: dict(committed.constants[k])
                        for k in committed.constants
                        if k != "partition_pass_unit_ms"}}
    path = str(tmp_path / "v3.json")
    with open(path, "w") as f:
        json.dump(v3, f)
    back = load_profile(path)
    assert back.value("partition_pass_unit_ms") == pytest.approx(
        8.0 / committed.value("hbm_gbps"), rel=1e-3)
    assert "schema v3" in back.source("partition_pass_unit_ms")


def test_fingerprint_ignores_provenance():
    base = load_profile()
    prof, _ = fit_profile([_bench_row(base.value("sort_stage_unit_ms"),
                                      rid=f"b{i}") for i in range(2)],
                          base=base, name=base.name)
    # same values -> same fingerprint constants: provenance must not
    # invalidate plan caches
    fp = prof.fingerprint()["constants"]
    assert set(fp) == set(base.fingerprint()["constants"])


# ------------------------------------------------------------- staleness
def test_stale_trips_on_persistent_drift_attributed_to_constant():
    rows = [_drift_row(f"d{i}", 60.0) for i in range(3)]
    stale = detect_stale(rows)
    assert TERM_TO_CONSTANT["shuffle"] == "ici_bytes_per_s"
    assert "ici_bytes_per_s" in stale
    info = stale["ici_bytes_per_s"]
    assert info["hits"] == 3 and info["mean_drift_pct"] == 60.0
    assert info["runs"] == ["d0", "d1", "d2"]


def test_stale_needs_persistence_and_threshold():
    assert detect_stale([_drift_row("a", 60.0)] * 2) == {}   # < min_persist
    assert detect_stale([_drift_row(f"x{i}", 10.0)          # under threshold
                         for i in range(5)]) == {}


def test_format_provenance_shows_stale_column():
    prof, _ = fit_profile([_bench_row(0.2, rid=f"b{i}") for i in range(2)],
                          base=load_profile())
    stale = detect_stale([_drift_row(f"d{i}", 80.0) for i in range(3)])
    txt = format_provenance(prof, stale=stale)
    assert "STALE (80% drift)" in txt
    assert "tools_profile_fit.py refresh" in txt
    clean = format_provenance(prof)
    assert "STALE" not in clean and txt != clean


# ----------------------------------------------------------- resolve auto
def test_resolve_profile_prefers_fresh_fit_then_falls_back(tmp_path):
    assert resolve_profile("v5e_lite") == "v5e_lite"      # passthrough
    d = str(tmp_path)
    assert resolve_profile("auto", ledger_dir=d) == "v5e_lite"  # no fit yet
    prof, _ = fit_profile([_bench_row(0.2, rid=f"b{i}") for i in range(2)],
                          base=load_profile())
    fitted = os.path.join(d, FITTED_PROFILE_BASENAME)
    prof.save(fitted)
    assert resolve_profile("auto", ledger_dir=d) == fitted
    # an aged fit loses to the committed snapshot
    assert resolve_profile("auto", ledger_dir=d,
                           fresh_s=0.0) == "v5e_lite"


# ----------------------------------------------------------------- CLIs
def _cli(*argv, env=None):
    e = dict(os.environ, JAX_PLATFORMS="cpu")
    if env:
        e.update(env)
    return subprocess.run([sys.executable, *argv], capture_output=True,
                          text=True, cwd=REPO, timeout=180, env=e)


def test_profile_fit_cli_fit_and_diff(tmp_path):
    led = Ledger(str(tmp_path))
    for i in range(3):
        led.append("bench", _bench_row(0.3, rid=f"b{i}"))
    out = _cli("tools_profile_fit.py", "fit", "--ledger", str(tmp_path))
    assert out.returncode == 0, out.stderr
    assert "fitted 1/12 constants" in out.stdout
    fitted = str(tmp_path / FITTED_PROFILE_BASENAME)
    assert load_profile(fitted).schema_version == 6
    # 0.3 vs committed 0.147 is > 25% -> diff gates
    out = _cli("tools_profile_fit.py", "diff", "v5e_lite", fitted)
    assert out.returncode == 1
    out = _cli("tools_profile_fit.py", "diff", "v5e_lite", fitted,
               "--threshold", "2.0")
    assert out.returncode == 0


def test_profile_fit_cli_refuses_under_sampled(tmp_path):
    # tier-1 satellite: an under-sampled ledger must exit 2, not emit a
    # profile that merely echoes its base under a "fit" label
    out = _cli("tools_profile_fit.py", "fit", "--ledger", str(tmp_path))
    assert out.returncode == 2
    assert "no ledger rows" in out.stderr
    Ledger(str(tmp_path)).append("bench", _bench_row(0.2))
    out = _cli("tools_profile_fit.py", "fit", "--ledger", str(tmp_path))
    assert out.returncode == 2
    assert "under-sampled" in out.stderr
    assert not os.path.exists(str(tmp_path / FITTED_PROFILE_BASENAME))


def test_profile_fit_cli_refresh_flags_stale(tmp_path):
    led = Ledger(str(tmp_path))
    for i in range(2):
        led.append("bench", _bench_row(0.2, rid=f"b{i}"))
    for i in range(3):
        led.append("run", _drift_row(f"d{i}", 70.0))
    out = _cli("tools_profile_fit.py", "refresh", "--ledger", str(tmp_path))
    assert out.returncode == 1                    # stale evidence found
    assert "stale constants re-fit" in out.stdout
    assert "ici_bytes_per_s" in out.stdout


def test_plan_explain_shows_provenance_and_refit_changes_it(tmp_path):
    env = {"TPU_RADIX_LEDGER_DIR": str(tmp_path)}
    base_out = _cli("-m", "tpu_radix_join.main", "--plan", "explain",
                    "--tuples-per-node", "4096", "--nodes", "1", env=env)
    assert base_out.returncode == 0, base_out.stderr
    assert "provenance/staleness" in base_out.stdout
    assert "PERF_NOTES" in base_out.stdout       # committed sources cited
    # build a ledger with drift + samples, fit, and explain under auto
    led = Ledger(str(tmp_path))
    for i in range(2):
        led.append("bench", _bench_row(0.3, rid=f"b{i}"))
    for i in range(3):
        led.append("run", _drift_row(f"d{i}", 70.0))
    out = _cli("tools_profile_fit.py", "fit", "--ledger", str(tmp_path))
    assert out.returncode == 0, out.stderr
    auto_out = _cli("-m", "tpu_radix_join.main", "--plan", "explain",
                    "--profile", "auto", "--tuples-per-node", "4096",
                    "--nodes", "1", env=env)
    assert auto_out.returncode == 0, auto_out.stderr
    assert "[PROFILE] auto ->" in auto_out.stderr
    assert "origin" in auto_out.stdout and "fit" in auto_out.stdout
    assert "STALE" in auto_out.stdout            # injected drift surfaces
    # the re-fit moved sort_stage_unit_ms 0.147 -> 0.3: predictions differ
    assert auto_out.stdout != base_out.stdout


def test_diff_profiles_table():
    a = load_profile()
    b = a.replace_constants(**{"hbm_gbps": {"value": 210.0, "source": "x"}})
    rows = {r["constant"]: r for r in diff_profiles(a, b)}
    assert rows["hbm_gbps"]["rel_delta"] == 1.0
    assert rows["ici_gbps"]["rel_delta"] == 0.0
