"""64-bit key support: hi/lo uint32 lanes through the full pipeline.

The 1B CompressedTuple config (BASELINE.md #5) uses int64 keys; on TPU these
ride as two uint32 lanes.  The pipeline probes them with a three-key
lexicographic sort-merge (no device int64, no jax x64); the packed-uint64
searchsorted ops in ops/build_probe.py remain for x64-enabled hosts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_radix_join import HashJoin, JoinConfig
from tpu_radix_join.data.tuples import TupleBatch, compress, decompress, partition_ids


@pytest.fixture
def x64():
    old = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    yield
    jax.config.update("jax_enable_x64", old)


def _batch64(keys64: np.ndarray) -> TupleBatch:
    keys64 = keys64.astype(np.uint64)
    return TupleBatch(
        key=jnp.asarray((keys64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
        rid=jnp.arange(len(keys64), dtype=jnp.uint32),
        key_hi=jnp.asarray((keys64 >> np.uint64(32)).astype(np.uint32)),
    )


def _host_count(r64, s64):
    rs = np.sort(r64)
    lo = np.searchsorted(rs, s64, side="left")
    hi = np.searchsorted(rs, s64, side="right")
    return int((hi - lo).sum())


def test_probe_count_64bit(x64):
    from tpu_radix_join.ops.build_probe import probe_count
    rng = np.random.default_rng(0)
    r64 = (rng.integers(0, 1 << 40, 4000, dtype=np.uint64)
           | (np.uint64(1) << np.uint64(33)))
    s64 = rng.choice(r64, 3000)
    rb, sb = _batch64(r64), _batch64(s64)
    rc = compress(rb, 0)
    sc = compress(sb, 0)
    rc = rc._replace(key_rem_hi=rb.key_hi)
    sc = sc._replace(key_rem_hi=sb.key_hi)
    got = int(probe_count(rc, sc))
    assert got == _host_count(r64, s64)


def test_hi_lane_distinguishes_keys(x64):
    from tpu_radix_join.ops.build_probe import probe_count
    from tpu_radix_join.data.tuples import CompressedBatch
    # same low lane, different hi lane: must NOT match
    r = CompressedBatch(key_rem=jnp.asarray([5], jnp.uint32),
                        rid=jnp.asarray([0], jnp.uint32),
                        key_rem_hi=jnp.asarray([1], jnp.uint32))
    s = CompressedBatch(key_rem=jnp.asarray([5], jnp.uint32),
                        rid=jnp.asarray([0], jnp.uint32),
                        key_rem_hi=jnp.asarray([2], jnp.uint32))
    assert int(probe_count(r, s)) == 0


def test_distributed_join_64bit(x64):
    rng = np.random.default_rng(3)
    n = 1 << 12
    r64 = rng.permutation(n).astype(np.uint64) | (np.uint64(1) << np.uint64(35))
    s64 = rng.permutation(n).astype(np.uint64) | (np.uint64(1) << np.uint64(35))
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=4, key_bits=64)
    res = HashJoin(cfg).join_arrays(_batch64(r64), _batch64(s64))
    assert res.ok
    assert res.matches == n


def test_compress_roundtrip_is_exact_64(x64):
    rng = np.random.default_rng(4)
    k64 = rng.integers(0, 1 << 50, 1000, dtype=np.uint64)
    b = _batch64(k64)
    pid = partition_ids(b, 6)
    back = decompress(compress(b, 6), pid, 6)
    got = (np.asarray(back.key_hi, dtype=np.uint64) << np.uint64(32)) | np.asarray(
        back.key, dtype=np.uint64)
    np.testing.assert_array_equal(got, k64)


def test_wide_merge_count_no_x64():
    """The three-key lexicographic path needs no jax x64 — the contract that
    makes 64-bit keys TPU-native (SURVEY.md §7.4 item 3)."""
    from tpu_radix_join.ops.merge_count import merge_count_wide_per_partition
    assert not jax.config.jax_enable_x64
    rng = np.random.default_rng(3)
    r64 = rng.integers(0, 1 << 40, 4096, dtype=np.uint64)
    s64 = np.concatenate([r64[:2048],
                          rng.integers(0, 1 << 40, 2048, dtype=np.uint64)])
    rb, sb = _batch64(r64), _batch64(s64)
    counts = merge_count_wide_per_partition(rb.key, rb.key_hi,
                                            sb.key, sb.key_hi, 4)
    assert int(np.asarray(counts).astype(np.uint64).sum()) == _host_count(r64, s64)
    # per-partition split is by low lo-lane bits
    got = np.asarray(counts)
    want = np.zeros(16, np.uint64)
    rs = np.sort(r64)
    hi = np.searchsorted(rs, s64, side="right")
    lo = np.searchsorted(rs, s64, side="left")
    for k, c in zip(s64, (hi - lo)):
        want[int(k) & 15] += c
    np.testing.assert_array_equal(got.astype(np.uint64), want)


def test_pipeline_64bit_no_x64():
    """Full distributed join on 64-bit keys with x64 DISABLED."""
    assert not jax.config.jax_enable_x64
    n = 4
    cfg = JoinConfig(num_nodes=n, network_fanout_bits=4, key_bits=64)
    rng = np.random.default_rng(11)
    size = 1 << 12
    r64 = (rng.permutation(size).astype(np.uint64) | (np.uint64(1) << 40))
    s64 = (rng.permutation(size).astype(np.uint64) | (np.uint64(1) << 40))
    res = HashJoin(cfg).join_arrays(_batch64(r64), _batch64(s64))
    assert res.ok
    assert res.matches == _host_count(r64, s64) == size
