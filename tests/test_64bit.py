"""64-bit key support: hi/lo uint32 lanes through the full pipeline.

The 1B CompressedTuple config (BASELINE.md #5) uses int64 keys; on TPU these
ride as two uint32 lanes.  Every probe discipline — sort-merge, bucketized
(three-key batched row sort), chunked, materializing — compares (hi, lo)
pairs lexicographically: no device int64, no jax x64 anywhere (SURVEY.md
§7.4 item 3).  Every test here runs with x64 OFF and asserts so."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_radix_join import HashJoin, JoinConfig
from tpu_radix_join.data.tuples import (
    CompressedBatch, TupleBatch, compress, decompress, partition_ids)


def _batch64(keys64: np.ndarray) -> TupleBatch:
    keys64 = keys64.astype(np.uint64)
    return TupleBatch(
        key=jnp.asarray((keys64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
        rid=jnp.arange(len(keys64), dtype=jnp.uint32),
        key_hi=jnp.asarray((keys64 >> np.uint64(32)).astype(np.uint32)),
    )


def _comp64(b: TupleBatch) -> CompressedBatch:
    return CompressedBatch(key_rem=b.key, rid=b.rid, key_rem_hi=b.key_hi)


def _host_count(r64, s64):
    rs = np.sort(r64)
    lo = np.searchsorted(rs, s64, side="left")
    hi = np.searchsorted(rs, s64, side="right")
    return int((hi - lo).sum())


def test_no_x64_anywhere():
    assert not jax.config.jax_enable_x64


def test_probe_count_64bit():
    from tpu_radix_join.ops.build_probe import probe_count
    rng = np.random.default_rng(0)
    r64 = (rng.integers(0, 1 << 40, 4000, dtype=np.uint64)
           | (np.uint64(1) << np.uint64(33)))
    s64 = rng.choice(r64, 3000)
    got = int(probe_count(_comp64(_batch64(r64)), _comp64(_batch64(s64))))
    assert got == _host_count(r64, s64)


def test_probe_count_per_partition_64bit():
    from tpu_radix_join.ops.build_probe import probe_count_per_partition
    rng = np.random.default_rng(8)
    r64 = rng.integers(0, 1 << 38, 3000, dtype=np.uint64)
    s64 = np.concatenate([rng.choice(r64, 1500),
                          rng.integers(0, 1 << 38, 1500, dtype=np.uint64)])
    sb = _batch64(s64)
    pid = sb.key & jnp.uint32(7)
    got = np.asarray(probe_count_per_partition(
        _comp64(_batch64(r64)), _comp64(sb), pid, 8)).astype(np.uint64)
    want = np.zeros(8, np.uint64)
    rs = np.sort(r64)
    cnt = (np.searchsorted(rs, s64, "right") - np.searchsorted(rs, s64, "left"))
    for k, c in zip(s64, cnt):
        want[int(k) & 7] += c
    np.testing.assert_array_equal(got, want)


def test_hi_lane_distinguishes_keys():
    from tpu_radix_join.ops.build_probe import probe_count
    # same low lane, different hi lane: must NOT match
    r = CompressedBatch(key_rem=jnp.asarray([5], jnp.uint32),
                        rid=jnp.asarray([0], jnp.uint32),
                        key_rem_hi=jnp.asarray([1], jnp.uint32))
    s = CompressedBatch(key_rem=jnp.asarray([5], jnp.uint32),
                        rid=jnp.asarray([0], jnp.uint32),
                        key_rem_hi=jnp.asarray([2], jnp.uint32))
    assert int(probe_count(r, s)) == 0


def test_distributed_join_64bit():
    rng = np.random.default_rng(3)
    n = 1 << 12
    r64 = rng.permutation(n).astype(np.uint64) | (np.uint64(1) << np.uint64(35))
    s64 = rng.permutation(n).astype(np.uint64) | (np.uint64(1) << np.uint64(35))
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=4, key_bits=64)
    res = HashJoin(cfg).join_arrays(_batch64(r64), _batch64(s64))
    assert res.ok
    assert res.matches == n


def test_compress_roundtrip_is_exact_64():
    rng = np.random.default_rng(4)
    k64 = rng.integers(0, 1 << 50, 1000, dtype=np.uint64)
    b = _batch64(k64)
    pid = partition_ids(b, 6)
    back = decompress(compress(b, 6), pid, 6)
    got = (np.asarray(back.key_hi, dtype=np.uint64) << np.uint64(32)) | np.asarray(
        back.key, dtype=np.uint64)
    np.testing.assert_array_equal(got, k64)


def test_wide_merge_count():
    from tpu_radix_join.ops.merge_count import merge_count_wide_per_partition
    rng = np.random.default_rng(3)
    r64 = rng.integers(0, 1 << 40, 4096, dtype=np.uint64)
    s64 = np.concatenate([r64[:2048],
                          rng.integers(0, 1 << 40, 2048, dtype=np.uint64)])
    rb, sb = _batch64(r64), _batch64(s64)
    counts = merge_count_wide_per_partition(rb.key, rb.key_hi,
                                            sb.key, sb.key_hi, 4)
    assert int(np.asarray(counts).astype(np.uint64).sum()) == _host_count(r64, s64)
    # per-partition split is by low lo-lane bits
    got = np.asarray(counts)
    want = np.zeros(16, np.uint64)
    rs = np.sort(r64)
    hi = np.searchsorted(rs, s64, side="right")
    lo = np.searchsorted(rs, s64, side="left")
    for k, c in zip(s64, (hi - lo)):
        want[int(k) & 15] += c
    np.testing.assert_array_equal(got.astype(np.uint64), want)


@pytest.mark.parametrize("fanout", [0, 4])
def test_wide_partition_kernel_matches_xla(fanout):
    # interpret-mode parity for the wide fused Pallas kernel (the TPU path)
    from tpu_radix_join.ops.merge_count import merge_count_wide_per_partition
    from tpu_radix_join.ops.pallas.merge_scan import TILE
    rng = np.random.default_rng(fanout + 1)
    r64 = rng.integers(0, 1 << 36, TILE + 100, dtype=np.uint64)
    s64 = np.concatenate([rng.choice(r64, TILE // 2),
                          rng.integers(0, 1 << 36, 77, dtype=np.uint64)])
    rb, sb = _batch64(r64), _batch64(s64)
    a = merge_count_wide_per_partition(rb.key, rb.key_hi, sb.key, sb.key_hi,
                                       fanout, impl="xla")
    b = merge_count_wide_per_partition(rb.key, rb.key_hi, sb.key, sb.key_hi,
                                       fanout, impl="pallas_interpret")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_two_level_64bit():
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=4, local_fanout_bits=4,
                     two_level=True, key_bits=64, allocation_factor=2.0)
    rng = np.random.default_rng(6)
    size = 1 << 12
    r64 = rng.permutation(size).astype(np.uint64) | (np.uint64(3) << 33)
    s64 = rng.permutation(size).astype(np.uint64) | (np.uint64(3) << 33)
    res = HashJoin(cfg).join_arrays(_batch64(r64), _batch64(s64))
    assert res.ok, res.diagnostics
    assert res.matches == size


def test_chunked_64bit():
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=4, key_bits=64,
                     chunk_size=256)
    rng = np.random.default_rng(7)
    size = 1 << 12
    r64 = rng.integers(0, 1 << 39, size, dtype=np.uint64)
    s64 = np.concatenate([rng.choice(r64, size // 2),
                          rng.integers(0, 1 << 39, size // 2, dtype=np.uint64)])
    res = HashJoin(cfg).join_arrays(_batch64(r64), _batch64(s64))
    assert res.ok, res.diagnostics
    assert res.matches == _host_count(r64, s64)


def test_materialize_64bit():
    # inner repeats keys 4x -> every outer hit materializes 4 rid pairs
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=4, key_bits=64,
                     match_rate_cap=4)
    size = 1 << 10
    base = (np.arange(size // 4, dtype=np.uint64) | (np.uint64(5) << 37))
    r64 = np.tile(base, 4)
    s64 = np.concatenate([base[: size // 8],
                          (np.arange(size // 8, dtype=np.uint64)
                           | (np.uint64(9) << 37))])
    res = HashJoin(cfg).join_materialize(_rel64(r64), _rel64(s64))
    assert res.ok, res.diagnostics
    assert res.matches == (size // 8) * 4
    # every returned pair is a true match under the full 64-bit key
    rmap = {i: k for i, k in enumerate(r64)}
    smap = {i: k for i, k in enumerate(s64)}
    for rr, sr in zip(res.r_rid, res.s_rid):
        assert rmap[int(rr)] == smap[int(sr)]


def _rel64(keys64):
    """Adapter: join_materialize takes Relations; wrap raw uint64 arrays
    following the wide shard_np contract — (key_lo, key_hi, rid) 3-tuples
    (relation.Relation.shard_np)."""
    class _Fixed:
        key_bits = 64
        kind = "fixed"
        def __init__(self, k):
            self.k = k
            self.num_nodes = 4
        def generate_sharded(self, mesh, axes):
            return None   # host-only test double
        def shard_np(self, i):
            n = len(self.k) // 4
            sl = self.k[i * n:(i + 1) * n]
            return ((sl & np.uint64(0xFFFFFFFF)).astype(np.uint32),
                    (sl >> np.uint64(32)).astype(np.uint32),
                    np.arange(i * n, (i + 1) * n, dtype=np.uint32))
    return _Fixed(keys64)


def test_key_width_mismatch_raises():
    """A 64-bit config must refuse lo-lane-only inputs (and vice versa) —
    silent truncation was round 2's worst bug."""
    rng = np.random.default_rng(5)
    k64 = rng.integers(0, 1 << 40, 256, dtype=np.uint64)
    wide = _batch64(k64)
    narrow = TupleBatch(key=wide.key, rid=wide.rid)
    eng64 = HashJoin(JoinConfig(num_nodes=4, network_fanout_bits=4, key_bits=64))
    eng32 = HashJoin(JoinConfig(num_nodes=4, network_fanout_bits=4))
    with pytest.raises(ValueError, match="key_hi"):
        eng64.join_arrays(narrow, narrow)
    with pytest.raises(ValueError, match="key_hi"):
        eng32.join_arrays(wide, wide)
    with pytest.raises(ValueError, match="key_hi"):
        eng64.join_materialize_arrays(wide, narrow)
    # Relation-level mismatch dies in _place before any device work
    from tpu_radix_join.data.relation import Relation
    rel32 = Relation(1 << 10, 4, "unique", seed=1)
    with pytest.raises(ValueError, match="hi key lane"):
        eng64.join(rel32, rel32)


def test_relation_wide_generation():
    """Relation(key_bits=64) emits hi/lo lanes: host/device identical, all
    keys above 2**62 (hi lane in [2**30, 2**31)), lo lane = the 32-bit
    logical key so every oracle carries over."""
    from tpu_radix_join.data.relation import Relation
    rel = Relation(1 << 12, 2, "unique", seed=9, key_bits=64)
    lo0, hi0, rid0 = rel.shard_np(0)
    assert (hi0 >= (1 << 30)).all() and (hi0 < (1 << 31)).all()
    dev = rel.shard(0)
    assert dev.key_hi is not None
    np.testing.assert_array_equal(np.asarray(dev.key), lo0)
    np.testing.assert_array_equal(np.asarray(dev.key_hi), hi0)
    np.testing.assert_array_equal(np.asarray(dev.rid), rid0)
    # hi lanes vary (a real 64-bit domain, not one constant plane)
    assert len(np.unique(hi0)) > 1000


def test_relation_driven_join_64bit():
    """The full driver path — Relation -> _place -> join()/join_materialize()
    — on 64-bit keys returns exact counts (VERDICT r2 next #1 done-check)."""
    from tpu_radix_join.data.relation import Relation
    n = 1 << 12
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=4, key_bits=64)
    inner = Relation(n, 4, "unique", seed=21, key_bits=64)
    outer = Relation(n, 4, "unique", seed=22, key_bits=64)
    eng = HashJoin(cfg)
    res = eng.join(inner, outer)
    assert res.ok, res.diagnostics
    assert res.matches == inner.expected_matches(outer) == n
    mat = eng.join_materialize(inner, outer)
    assert mat.ok, mat.diagnostics
    assert mat.matches == n
    # every materialized pair is a true 64-bit match
    r_lo, r_hi, _ = inner.shard_np(0)
    for i in range(1, 4):
        lo_i, hi_i, _ = inner.shard_np(i)
        r_lo, r_hi = np.concatenate([r_lo, lo_i]), np.concatenate([r_hi, hi_i])
    s_lo, s_hi = [], []
    for i in range(4):
        lo_i, hi_i, _ = outer.shard_np(i)
        s_lo.append(lo_i), s_hi.append(hi_i)
    s_lo, s_hi = np.concatenate(s_lo), np.concatenate(s_hi)
    r64 = (r_hi.astype(np.uint64) << np.uint64(32)) | r_lo
    s64 = (s_hi.astype(np.uint64) << np.uint64(32)) | s_lo
    assert np.array_equal(np.sort(r64[mat.r_rid]), np.sort(s64[mat.s_rid]))


def test_streaming_wide_chunks():
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.data.streaming import stream_chunks
    rel = Relation(1 << 10, 1, "unique", seed=13, key_bits=64)
    lo, hi, _ = rel.shard_np(0)
    got_lo, got_hi = [], []
    for chunk in stream_chunks(rel, 0, 300):
        assert chunk.key_hi is not None
        got_lo.append(np.asarray(chunk.key))
        got_hi.append(np.asarray(chunk.key_hi))
    np.testing.assert_array_equal(np.concatenate(got_lo), lo)
    np.testing.assert_array_equal(np.concatenate(got_hi), hi)


def test_pipeline_64bit_no_x64():
    """Full distributed join on 64-bit keys with x64 DISABLED."""
    n = 4
    cfg = JoinConfig(num_nodes=n, network_fanout_bits=4, key_bits=64)
    rng = np.random.default_rng(11)
    size = 1 << 12
    r64 = (rng.permutation(size).astype(np.uint64) | (np.uint64(1) << 40))
    s64 = (rng.permutation(size).astype(np.uint64) | (np.uint64(1) << 40))
    res = HashJoin(cfg).join_arrays(_batch64(r64), _batch64(s64))
    assert res.ok
    assert res.matches == _host_count(r64, s64) == size
