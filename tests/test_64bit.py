"""64-bit key support: hi/lo uint32 lanes through the full pipeline.

The 1B CompressedTuple config (BASELINE.md #5) uses int64 keys; on TPU these
ride as two uint32 lanes with the probe comparing a packed uint64 sort lane
(requires jax x64)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_radix_join import HashJoin, JoinConfig
from tpu_radix_join.data.tuples import TupleBatch, compress, decompress, partition_ids


@pytest.fixture
def x64():
    old = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    yield
    jax.config.update("jax_enable_x64", old)


def _batch64(keys64: np.ndarray) -> TupleBatch:
    keys64 = keys64.astype(np.uint64)
    return TupleBatch(
        key=jnp.asarray((keys64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
        rid=jnp.arange(len(keys64), dtype=jnp.uint32),
        key_hi=jnp.asarray((keys64 >> np.uint64(32)).astype(np.uint32)),
    )


def _host_count(r64, s64):
    rs = np.sort(r64)
    lo = np.searchsorted(rs, s64, side="left")
    hi = np.searchsorted(rs, s64, side="right")
    return int((hi - lo).sum())


def test_probe_count_64bit(x64):
    from tpu_radix_join.ops.build_probe import probe_count
    rng = np.random.default_rng(0)
    r64 = (rng.integers(0, 1 << 40, 4000, dtype=np.uint64)
           | (np.uint64(1) << np.uint64(33)))
    s64 = rng.choice(r64, 3000)
    rb, sb = _batch64(r64), _batch64(s64)
    rc = compress(rb, 0)
    sc = compress(sb, 0)
    rc = rc._replace(key_rem_hi=rb.key_hi)
    sc = sc._replace(key_rem_hi=sb.key_hi)
    got = int(probe_count(rc, sc))
    assert got == _host_count(r64, s64)


def test_hi_lane_distinguishes_keys(x64):
    from tpu_radix_join.ops.build_probe import probe_count
    from tpu_radix_join.data.tuples import CompressedBatch
    # same low lane, different hi lane: must NOT match
    r = CompressedBatch(key_rem=jnp.asarray([5], jnp.uint32),
                        rid=jnp.asarray([0], jnp.uint32),
                        key_rem_hi=jnp.asarray([1], jnp.uint32))
    s = CompressedBatch(key_rem=jnp.asarray([5], jnp.uint32),
                        rid=jnp.asarray([0], jnp.uint32),
                        key_rem_hi=jnp.asarray([2], jnp.uint32))
    assert int(probe_count(r, s)) == 0


def test_distributed_join_64bit(x64):
    rng = np.random.default_rng(3)
    n = 1 << 12
    r64 = rng.permutation(n).astype(np.uint64) | (np.uint64(1) << np.uint64(35))
    s64 = rng.permutation(n).astype(np.uint64) | (np.uint64(1) << np.uint64(35))
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=4, key_bits=64)
    res = HashJoin(cfg).join_arrays(_batch64(r64), _batch64(s64))
    assert res.ok
    assert res.matches == n


def test_compress_roundtrip_is_exact_64(x64):
    rng = np.random.default_rng(4)
    k64 = rng.integers(0, 1 << 50, 1000, dtype=np.uint64)
    b = _batch64(k64)
    pid = partition_ids(b, 6)
    back = decompress(compress(b, 6), pid, 6)
    got = (np.asarray(back.key_hi, dtype=np.uint64) << np.uint64(32)) | np.asarray(
        back.key, dtype=np.uint64)
    np.testing.assert_array_equal(got, k64)
