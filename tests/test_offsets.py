"""OffsetMap parity (histograms/offset_map.py vs OffsetMap.cpp:59-93):
base offsets walk the global histogram in owner order, relative offsets are
the MPI_Exscan analog, absolute = base + relative.  The pipeline consumes
these as the disjoint-write-ranges invariant under config.debug_checks
(operators/hash_join.py _shuffle)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.histograms import compute_offsets
from tpu_radix_join.parallel.mesh import make_hierarchical_mesh, make_mesh


def _expected(local, ghist, assignment):
    n, p = local.shape
    base = np.zeros(p, np.uint32)
    for q in range(p):
        base[q] = ghist[(assignment == assignment[q])
                        & (np.arange(p) < q)].sum()
    rel = np.zeros((n, p), np.uint32)
    for rank in range(1, n):
        rel[rank] = rel[rank - 1] + local[rank - 1]
    return base, rel


def test_compute_offsets_matches_numpy():
    n, p = 4, 8
    rng = np.random.default_rng(0)
    local = rng.integers(0, 50, size=(n, p)).astype(np.uint32)
    ghist = local.sum(axis=0).astype(np.uint32)
    assignment = (rng.permutation(p) % n).astype(np.uint32)
    mesh = make_mesh(n, "nodes")

    def body(lh):
        offs = compute_offsets(lh, jnp.asarray(ghist),
                               jnp.asarray(assignment), "nodes")
        return offs.base, offs.relative, offs.absolute

    fn = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("nodes"),
                               out_specs=P("nodes")))
    base, rel, absolute = fn(jnp.asarray(local.reshape(-1)))
    base = np.asarray(base).reshape(n, p)
    rel = np.asarray(rel).reshape(n, p)
    absolute = np.asarray(absolute).reshape(n, p)
    want_base, want_rel = _expected(local, ghist, assignment)
    for rank in range(n):
        np.testing.assert_array_equal(base[rank], want_base)
    np.testing.assert_array_equal(rel, want_rel)
    np.testing.assert_array_equal(absolute, want_base[None, :] + want_rel)
    # the zero-coordination guarantee the debug_checks invariant asserts
    assert (rel + local <= ghist[None, :]).all()


@pytest.mark.parametrize("hosts", [1, 2])
def test_debug_checks_exercise_offsets(hosts):
    """debug_checks now runs compute_offsets inside the shuffle program on
    both flat and hierarchical meshes; the join must stay exact and ok."""
    n, size = 8, 1 << 13
    cfg = JoinConfig(num_nodes=n, num_hosts=hosts, debug_checks=True)
    r = Relation(size, n, "unique", seed=1)
    s = Relation(size, n, "unique", seed=2)
    res = HashJoin(cfg).join(r, s)
    assert res.ok, res.diagnostics
    assert res.matches == size
