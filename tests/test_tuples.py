import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data import tuples as T


def _batch(keys, rids, hi=None):
    return T.TupleBatch(
        key=jnp.asarray(keys, jnp.uint32),
        rid=jnp.asarray(rids, jnp.uint32),
        key_hi=None if hi is None else jnp.asarray(hi, jnp.uint32),
    )


def test_partition_ids_low_bits():
    b = _batch([0, 1, 31, 32, 33, 255], [0, 1, 2, 3, 4, 5])
    pid = T.partition_ids(b, 5)
    np.testing.assert_array_equal(np.asarray(pid), [0, 1, 31, 0, 1, 31])


def test_compress_roundtrip_32():
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 30, 1000, dtype=np.uint32)
    rids = np.arange(1000, dtype=np.uint32)
    b = _batch(keys, rids)
    for f in (0, 5, 8):
        pid = T.partition_ids(b, f)
        c = T.compress(b, f)
        back = T.decompress(c, pid, f)
        np.testing.assert_array_equal(np.asarray(back.key), keys)
        np.testing.assert_array_equal(np.asarray(back.rid), rids)


def test_compress_roundtrip_64():
    rng = np.random.default_rng(1)
    lo = rng.integers(0, 1 << 32, 500, dtype=np.uint64).astype(np.uint32)
    hi = rng.integers(0, 1 << 20, 500, dtype=np.uint64).astype(np.uint32)
    b = _batch(lo, np.arange(500), hi)
    for f in (0, 5):
        pid = T.partition_ids(b, f)
        c = T.compress(b, f)
        back = T.decompress(c, pid, f)
        np.testing.assert_array_equal(np.asarray(back.key), lo)
        np.testing.assert_array_equal(np.asarray(back.key_hi), hi)


def test_padding_and_masks():
    pad_r = T.make_padding(16, "inner")
    pad_s = T.make_padding(16, "outer")
    assert not bool(T.valid_mask(pad_r, "inner").any())
    assert not bool(T.valid_mask(pad_s, "outer").any())
    # inner sentinel never equals outer sentinel
    assert T.R_PAD_KEY != T.S_PAD_KEY
    b = _batch([1, 2], [3, 4])
    full = T.make_padding_like(b, 4, "inner")
    assert full.key.shape == (4,)
    assert bool((full.key == T.R_PAD_KEY).all())
