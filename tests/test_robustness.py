"""Tier-1 coverage for the robustness subsystem: deterministic fault
injection, retry/backoff policies, failure-class taxonomy, and the
graceful-degradation paths (engine -> chunked, device -> CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_radix_join.core.config import JoinConfig
from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.operators.hash_join import HashJoin
from tpu_radix_join.performance.measurements import (BACKOFFMS, FINJECT,
                                                     Measurements, RETRYN)
from tpu_radix_join.robustness import faults
from tpu_radix_join.robustness.faults import FaultInjector, InjectedFault
from tpu_radix_join.robustness.retry import (CAPACITY_OVERFLOW, KEY_CONTRACT,
                                             OK, RetriesExhausted,
                                             RetryPolicy,
                                             classify_diagnostics, execute,
                                             is_retryable_class)

NODES = 4


def _join_inputs(n=1 << 12, seed=0):
    rng = np.random.default_rng(seed)
    rk = rng.permutation(n).astype(np.uint32) + 1
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    oracle = int(np.isin(sk, rk).sum())
    r = TupleBatch(key=jnp.asarray(rk), rid=jnp.arange(n, dtype=jnp.uint32))
    s = TupleBatch(key=jnp.asarray(sk), rid=jnp.arange(n, dtype=jnp.uint32))
    return r, s, oracle


# ------------------------------------------------------------------ injector

def test_fault_replay_deterministic():
    """Same seed + same hit sequence -> identical fire history; a different
    seed diverges (the replayability contract in faults.py)."""

    def run(seed):
        with FaultInjector(seed=seed) as inj:
            inj.arm(faults.GRID_TRANSIENT, p=0.5)
            for _ in range(64):
                faults.fires(faults.GRID_TRANSIENT)
            return list(inj.history)

    a, b, c = run(7), run(7), run(8)
    assert a == b
    assert a            # p=0.5 over 64 hits: silence would be a dead site
    assert a != c


def test_fault_arm_at_and_times():
    with FaultInjector() as inj:
        inj.arm(faults.GRID_KILL, at=(2, 4))
        fired = [faults.fires(faults.GRID_KILL) for _ in range(6)]
    assert fired == [False, True, False, True, False, False]
    assert inj.fired(faults.GRID_KILL) == 2
    assert inj.hits(faults.GRID_KILL) == 6


def test_fault_check_raises_and_counts():
    m = Measurements()
    with FaultInjector() as inj:
        inj.arm(faults.DEVICE_INIT, at=1)
        with pytest.raises(InjectedFault) as ei:
            faults.check(faults.DEVICE_INIT, m)
        faults.check(faults.DEVICE_INIT, m)   # hit 2: quiet
    assert ei.value.site == faults.DEVICE_INIT
    assert m.counters[FINJECT] == 1
    assert any(e["event"] == "fault" for e in m.meta["events"])


def test_no_injector_is_noop():
    assert faults.active() is None
    assert not faults.fires(faults.GRID_KILL)
    faults.check(faults.GRID_KILL)   # must not raise


# ------------------------------------------------------------- retry policy

def test_backoff_schedule_fake_clock():
    """execute() sleeps exactly the policy schedule, counts RETRYN/BACKOFFMS,
    and terminally raises RetriesExhausted chaining the last error."""
    policy = RetryPolicy(max_attempts=4, base_delay_s=1.0, multiplier=2.0,
                         max_delay_s=30.0)
    sleeps, m = [], Measurements()
    with pytest.raises(RetriesExhausted) as ei:
        execute(lambda: (_ for _ in ()).throw(ConnectionError("down")),
                policy, sleep=sleeps.append, clock=lambda: 0.0,
                measurements=m, label="unit")
    assert sleeps == list(policy.schedule()) == [1.0, 2.0, 4.0]
    assert ei.value.attempts == 4
    assert isinstance(ei.value.last_error, ConnectionError)
    assert m.counters[RETRYN] == 3
    assert m.counters[BACKOFFMS] == 7000


def test_backoff_jitter_deterministic_and_bounded():
    p1 = RetryPolicy(base_delay_s=1.0, jitter=0.25, seed=3)
    p2 = RetryPolicy(base_delay_s=1.0, jitter=0.25, seed=3)
    p3 = RetryPolicy(base_delay_s=1.0, jitter=0.25, seed=4)
    d1 = [p1.delay_s(a) for a in range(8)]
    assert d1 == [p2.delay_s(a) for a in range(8)]
    assert d1 != [p3.delay_s(a) for a in range(8)]
    for a, d in enumerate(d1):
        nominal = min(30.0, 1.0 * 2.0 ** a)
        assert 0.75 * nominal <= d <= 1.25 * nominal


def test_retry_succeeds_midway_and_max_elapsed():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("transient")
        return "done"

    policy = RetryPolicy(max_attempts=5, base_delay_s=0.0)
    assert execute(flaky, policy, sleep=lambda d: None) == "done"
    assert len(calls) == 3

    # wall-clock budget terminates before max_attempts does
    t = [0.0]

    def clock():
        t[0] += 10.0
        return t[0]

    with pytest.raises(RetriesExhausted) as ei:
        execute(lambda: (_ for _ in ()).throw(TimeoutError("t")),
                RetryPolicy(max_attempts=100, base_delay_s=0.0,
                            max_elapsed_s=15.0),
                sleep=lambda d: None, clock=clock)
    assert ei.value.attempts < 100


def test_nonretryable_propagates_immediately():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("fatal")

    with pytest.raises(ValueError):
        execute(boom, RetryPolicy(max_attempts=5, base_delay_s=0.0),
                sleep=lambda d: None)
    assert len(calls) == 1


def test_classify_diagnostics_priority():
    base = {k: 0 for k in ("key_contract_violations",
                           "shuffle_overflow_r_tuples",
                           "shuffle_overflow_s_tuples",
                           "conservation_violations", "local_overflow",
                           "hot_overflow", "count_overflow_risk")}
    assert classify_diagnostics(base) == OK
    assert classify_diagnostics({**base, "local_overflow": 2}) \
        == CAPACITY_OVERFLOW
    # fatal outranks capacity even when both fire in one attempt
    assert classify_diagnostics({**base, "local_overflow": 2,
                                 "key_contract_violations": 1}) \
        == KEY_CONTRACT
    assert is_retryable_class(CAPACITY_OVERFLOW)
    assert not is_retryable_class(KEY_CONTRACT)


# ------------------------------------------------------- coordinator connect

def test_coordinator_retry_backoff_then_timeout():
    from tpu_radix_join.parallel.multihost import (CoordinatorTimeout,
                                                   initialize)
    sleeps = []
    with FaultInjector() as inj:
        inj.arm(faults.COORD_CONNECT, p=1.0)
        with pytest.raises(CoordinatorTimeout) as ei:
            initialize(coordinator_address="127.0.0.1:1",
                       num_processes=1, process_id=0,
                       retry_policy=RetryPolicy(max_attempts=3,
                                                base_delay_s=0.5,
                                                multiplier=2.0),
                       _sleep=sleeps.append)
    assert inj.fired(faults.COORD_CONNECT) == 3   # every attempt consulted
    assert sleeps == [0.5, 1.0]
    assert ei.value.failure_class == "coordinator_timeout"


def test_initialize_without_coordinator_is_noop(monkeypatch):
    from tpu_radix_join.parallel import multihost
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert multihost.initialize() is False


def test_coordinator_recovers_after_transient():
    """A connect that fails once then succeeds must not raise — but this
    process must not actually join a world, so the 'success' is asserted
    via the injected-fault accounting on a mocked initialize."""
    import jax

    from tpu_radix_join.parallel import multihost
    calls = []
    real = jax.distributed.initialize
    jax.distributed.initialize = lambda **kw: calls.append(kw)
    try:
        with FaultInjector() as inj:
            inj.arm(faults.COORD_CONNECT, at=1)
            multihost.initialize(coordinator_address="127.0.0.1:1",
                                 num_processes=1, process_id=0,
                                 retry_policy=RetryPolicy(max_attempts=3,
                                                          base_delay_s=0.0),
                                 _sleep=lambda d: None)
        assert inj.hits(faults.COORD_CONNECT) == 2
        assert len(calls) == 1
    finally:
        jax.distributed.initialize = real
        multihost._initialized = False


# ------------------------------------------------------------- engine paths

def test_engine_injected_overflow_retry_recovers():
    r, s, oracle = _join_inputs()
    m = Measurements()
    hj = HashJoin(JoinConfig(num_nodes=NODES, max_retries=2,
                             retry_backoff_s=0.001), measurements=m)
    with FaultInjector() as inj:
        inj.arm(faults.SHUFFLE_OVERFLOW, times=1)
        res = hj.join_arrays(r, s)
    assert res.matches == oracle and res.ok
    assert res.diagnostics["failure_class"] == OK
    assert inj.fired(faults.SHUFFLE_OVERFLOW) == 1
    assert m.counters["RETRIES"] == 1
    assert m.counters[RETRYN] == 1          # the backoff pause was taken
    assert m.counters[FINJECT] == 1


def test_engine_exhausted_retries_structured_failure():
    """Retries exhausted must produce ok=False + a machine-readable class —
    never an uncaught assert (the acceptance criterion)."""
    r, s, _ = _join_inputs()
    hj = HashJoin(JoinConfig(num_nodes=NODES, max_retries=1))
    with FaultInjector() as inj:
        inj.arm(faults.SHUFFLE_OVERFLOW, p=1.0)
        res = hj.join_arrays(r, s)
    assert not res.ok
    assert res.diagnostics["failure_class"] == CAPACITY_OVERFLOW


def test_engine_fallback_chunked_exact():
    r, s, oracle = _join_inputs()
    m = Measurements()
    hj = HashJoin(JoinConfig(num_nodes=NODES, max_retries=0,
                             fallback="chunked"), measurements=m)
    with FaultInjector() as inj:
        inj.arm(faults.SHUFFLE_OVERFLOW, p=1.0)
        res = hj.join_arrays(r, s)
    assert res.ok and res.matches == oracle
    assert res.diagnostics["degraded"] == "chunked"
    assert res.diagnostics["failure_class"] == CAPACITY_OVERFLOW
    assert any(e["event"] == "fallback" for e in m.meta["events"])


def test_device_init_fault_degrades_to_cpu():
    from tpu_radix_join.robustness.degrade import engine_with_cpu_fallback
    r, s, oracle = _join_inputs()
    m = Measurements()
    with FaultInjector() as inj:
        inj.arm(faults.DEVICE_INIT, at=1)
        with pytest.warns(RuntimeWarning, match=r"\[DEGRADE\]"):
            engine, info = engine_with_cpu_fallback(
                JoinConfig(num_nodes=NODES), measurements=m)
    assert info["degraded"] and info["backend"] == "cpu"
    assert info["failure_class"] == "device_unavailable"
    assert inj.hits(faults.DEVICE_INIT) == 2   # primary + fallback ctor
    res = engine.join_arrays(r, s)             # degraded engine still joins
    assert res.ok and res.matches == oracle


def test_engine_healthy_without_fallback_flag():
    from tpu_radix_join.robustness.degrade import engine_with_cpu_fallback
    engine, info = engine_with_cpu_fallback(JoinConfig(num_nodes=NODES))
    assert not info["degraded"]
    assert engine.config.num_nodes == NODES


# ------------------------------------------------------------ stream/narrow

def test_stream_corrupt_lane_detected():
    """A sentinel-damaged key lane from the streaming loader must be caught
    loudly by the narrow-path key-contract guard, not silently undercount."""
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.data.streaming import stream_chunks
    from tpu_radix_join.ops.chunked import chunked_join_count

    rel = Relation(1 << 10, 1, "unique", seed=5)
    with FaultInjector() as inj:
        inj.arm(faults.STREAM_CORRUPT, at=1)
        chunks = list(stream_chunks(rel, 0, 1 << 10))
    assert inj.fired(faults.STREAM_CORRUPT) == 1
    assert int(np.asarray(chunks[0].key)[0]) == 0xFFFFFFFF
    clean = next(iter(stream_chunks(rel, 0, 1 << 10)))
    with pytest.raises(ValueError, match="key contract violation"):
        chunked_join_count(chunks[0], clean, 256, key_range="narrow")


def test_narrow_mode_overlimit_keys_raise():
    """Satellite fix: keys above MAX_MERGE_KEY under key_range='narrow'
    previously silently undercounted (the pack clamps them to pad); they
    must raise, while 'auto' still routes them to the full-range count."""
    from tpu_radix_join.ops.chunked import chunked_join_count
    from tpu_radix_join.ops.merge_count import MAX_MERGE_KEY

    hi = np.asarray([MAX_MERGE_KEY + 1, MAX_MERGE_KEY + 2, 5, 6], np.uint32)
    batch = TupleBatch(key=jnp.asarray(hi),
                       rid=jnp.arange(4, dtype=jnp.uint32))
    with pytest.raises(ValueError, match="key contract violation"):
        chunked_join_count(batch, batch, 4, key_range="narrow")
    assert chunked_join_count(batch, batch, 4, key_range="auto") == 4
