"""Tier-1 coverage for checkpoint/resume: a killed out-of-core grid join
must resume from its last completed chunk pair with the exact total and
zero recomputed slabs (acceptance criterion), and the CheckpointManager's
atomicity/fingerprint/corruption rules must hold."""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_radix_join.data.relation import Relation
from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.ops.chunked import chunked_join_grid
from tpu_radix_join.performance.measurements import (CKPTLOAD, CKPTSAVE,
                                                     GRIDPAIRS, Measurements,
                                                     RETRYN)
from tpu_radix_join.robustness import faults
from tpu_radix_join.robustness.checkpoint import (CheckpointManager,
                                                  CheckpointMismatch)
from tpu_radix_join.robustness.faults import (FaultInjector, InjectedKill,
                                              TransientFault)
from tpu_radix_join.robustness.retry import RetryPolicy


def _quarters(seed, n=1 << 12):
    rel = Relation(n, 1, "unique", seed=seed)
    b = rel.shard(0)
    k, r = np.asarray(b.key), np.asarray(b.rid)
    q = n // 4
    return [TupleBatch(key=jnp.asarray(k[i * q:(i + 1) * q]),
                       rid=jnp.asarray(r[i * q:(i + 1) * q]))
            for i in range(4)]


def test_kill_and_resume_exact_zero_recompute(tmp_path):
    """Kill mid-grid after 2 of 16 pairs; the resumed run must reach the
    exact oracle total with CKPTLOAD >= 1 and GRIDPAIRS == 14 — completed
    pairs are never re-probed."""
    r_chunks, s_chunks = _quarters(1), _quarters(1)   # same keys: 4096 matches
    ckpt = str(tmp_path / "grid.ckpt")

    m1 = Measurements()
    with FaultInjector() as inj:
        inj.arm(faults.GRID_KILL, at=3, exc=InjectedKill)
        with pytest.raises(InjectedKill):
            chunked_join_grid(r_chunks, s_chunks, 1 << 10,
                              checkpoint_path=ckpt, checkpoint_tag="t",
                              measurements=m1)
    assert m1.counters[GRIDPAIRS] == 2
    assert m1.counters[CKPTSAVE] == 2
    state = json.load(open(ckpt))
    assert (state["i"], state["j"]) == (0, 2) and not state["done"]

    m2 = Measurements()
    total = chunked_join_grid(r_chunks, s_chunks, 1 << 10,
                              checkpoint_path=ckpt, checkpoint_tag="t",
                              measurements=m2)
    assert total == 1 << 12
    assert m2.counters[CKPTLOAD] >= 1
    assert m2.counters[GRIDPAIRS] == 14   # zero recompute
    assert json.load(open(ckpt))["done"]

    # a third run short-circuits on the done marker: no pairs probed at all
    m3 = Measurements()
    assert chunked_join_grid(r_chunks, s_chunks, 1 << 10,
                             checkpoint_path=ckpt, checkpoint_tag="t",
                             measurements=m3) == 1 << 12
    assert GRIDPAIRS not in m3.counters


def test_grid_transient_retry(tmp_path):
    """An armed per-pair transient costs one backoff, not the run."""
    r_chunks, s_chunks = _quarters(2), _quarters(2)
    m = Measurements()
    with FaultInjector() as inj:
        inj.arm(faults.GRID_TRANSIENT, times=1, exc=TransientFault)
        total = chunked_join_grid(
            r_chunks, s_chunks, 1 << 10, measurements=m,
            retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.0))
    assert total == 1 << 12
    assert inj.fired(faults.GRID_TRANSIENT) == 1
    assert m.counters[RETRYN] == 1
    assert m.counters[GRIDPAIRS] == 16


# --------------------------------------------------------- CheckpointManager

def test_checkpoint_roundtrip_and_done(tmp_path):
    m = Measurements()
    ck = CheckpointManager(str(tmp_path / "c.json"), {"slab": 8, "tag": "x"},
                           measurements=m)
    assert ck.load() is None               # missing file: fresh start
    assert ck.save({"i": 1, "j": 2, "total": 99})
    state = ck.load()
    assert state == {"i": 1, "j": 2, "total": 99, "done": False}
    assert ck.save({"i": 4, "j": 0, "total": 123}, done=True)
    assert ck.load()["done"]
    assert m.counters[CKPTSAVE] == 2 and m.counters[CKPTLOAD] == 2
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


def test_checkpoint_fingerprint_mismatch(tmp_path):
    path = str(tmp_path / "c.json")
    CheckpointManager(path, {"slab": 8}).save({"total": 1})
    with pytest.raises(CheckpointMismatch):
        CheckpointManager(path, {"slab": 16}).load()


def test_checkpoint_corrupt_restarts(tmp_path):
    path = tmp_path / "c.json"
    path.write_text('{"truncated": ')
    m = Measurements()
    assert CheckpointManager(str(path), {"slab": 8}, m).load() is None
    assert any(e["event"] == "checkpoint_corrupt" for e in m.meta["events"])
    path.write_text('{"no_fingerprint_key": 1}')
    assert CheckpointManager(str(path), {"slab": 8}, m).load() is None


def test_checkpoint_save_failure_does_not_kill_grid(tmp_path):
    """Durability beats availability: every save failing (injected OSError)
    must cost resume points, not the join."""
    r_chunks, s_chunks = _quarters(3), _quarters(3)
    m = Measurements()
    with FaultInjector() as inj:
        inj.arm(faults.CKPT_SAVE, p=1.0, exc=OSError)
        total = chunked_join_grid(r_chunks, s_chunks, 1 << 10,
                                  checkpoint_path=str(tmp_path / "g.ckpt"),
                                  checkpoint_tag="t", measurements=m)
    assert total == 1 << 12
    assert CKPTSAVE not in m.counters
    assert any(e["event"] == "checkpoint_save_failed"
               for e in m.meta["events"])


def test_checkpoint_load_fault_restarts(tmp_path):
    path = str(tmp_path / "c.json")
    CheckpointManager(path, {"slab": 8}).save({"total": 7})
    with FaultInjector() as inj:
        inj.arm(faults.CKPT_LOAD, p=1.0, exc=OSError)
        assert CheckpointManager(path, {"slab": 8}).load() is None
    assert CheckpointManager(path, {"slab": 8}).load()["total"] == 7


# ------------------------------------------------------------------ main CLI

def test_main_grid_cli_checkpoint_and_resume(tmp_path, capsys):
    from tpu_radix_join.main import main

    argv = ["--nodes", "1", "--tuples-per-node", "4096",
            "--grid-chunk-tuples", "2048",
            "--checkpoint-dir", str(tmp_path)]
    assert main(argv) == 0
    out = capsys.readouterr().out
    assert "[RESULTS] Expected: 4096 (OK)" in out
    ckpt = tmp_path / "grid.ckpt"
    assert json.loads(ckpt.read_text())["done"]

    # --resume on a done checkpoint returns the stored total without
    # probing; without --resume the stale file is removed and re-created
    assert main(argv + ["--resume"]) == 0
    assert "Expected: 4096 (OK)" in capsys.readouterr().out
    assert main(argv) == 0
    assert json.loads(ckpt.read_text())["done"]
