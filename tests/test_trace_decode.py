"""Wire-level test of the xplane protobuf decoder (performance/trace.py).

The decoder hardcodes five field numbers of the tensorflow/tsl XSpace
schema instead of importing tensorflow; this test hand-encodes a minimal
XSpace on the raw wire format — a device plane, a host plane, and unknown
fields of every wire type sprinkled in — and asserts the parse and the
``summarize_trace`` aggregation, so a schema-number typo or a broken
unknown-field skip fails here rather than silently mis-summarizing a real
profiler artifact.
"""

import os

from tpu_radix_join.performance.trace import (find_xplane_files,
                                              is_device_plane, parse_xspace,
                                              summarize_trace, top_ops)

# ------------------------------------------------------------ wire encoding


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _fld(num: int, wire: int, payload) -> bytes:
    tag = _varint((num << 3) | wire)
    if wire == 0:
        return tag + _varint(payload)
    if wire == 2:
        return tag + _varint(len(payload)) + payload
    return tag + payload            # wire 1/5: raw fixed bytes


def _unknowns() -> bytes:
    """Fields no XSpace message defines, one per wire type the decoder
    must skip: varint, 64-bit, length-delimited, 32-bit."""
    return (_fld(99, 0, 12345)
            + _fld(98, 1, b"\x01\x02\x03\x04\x05\x06\x07\x08")
            + _fld(97, 2, b"opaque")
            + _fld(96, 5, b"\xde\xad\xbe\xef"))


def _xevent(md: int, dur_ps: int, occ: int = None) -> bytes:
    body = _fld(1, 0, md) + _fld(3, 0, dur_ps)
    if occ is not None:
        body += _fld(5, 0, occ)
    return body + _unknowns()


def _xline(name: str, events, display: str = None) -> bytes:
    body = _fld(2, 2, name.encode())
    if display is not None:
        body += _fld(11, 2, display.encode())
    for ev in events:
        body += _fld(4, 2, ev)
    return body + _unknowns()


def _md_entry(md_id: int, name: str, display: str = None) -> bytes:
    inner = _fld(1, 0, md_id) + _fld(2, 2, name.encode())
    if display is not None:
        inner += _fld(4, 2, display.encode())
    return _fld(1, 0, md_id) + _fld(2, 2, inner)


def _xplane(name: str, lines, md_entries) -> bytes:
    body = _fld(2, 2, name.encode())
    for ln in lines:
        body += _fld(3, 2, ln)
    for entry in md_entries:
        body += _fld(4, 2, entry)
    return body + _unknowns()


def _xspace(planes) -> bytes:
    return b"".join(_fld(1, 2, p) for p in planes) + _unknowns()


def _minimal_space() -> bytes:
    # device plane: a sparse launch line + the busy execution line the
    # summary must pick (sort 5us x2 + fusion 2us; 300-ps varint-boundary
    # crumbs on the launch line)
    device = _xplane(
        "/device:TPU:0 (pid 1)",
        lines=[
            _xline("launch", [_xevent(3, 300)]),
            _xline("steps", [_xevent(1, 3_000_000, occ=1),
                             _xevent(1, 2_000_000, occ=1),
                             _xevent(2, 2_000_000)],
                   display="XLA Ops"),
        ],
        md_entries=[_md_entry(1, "sort.42", display="sort"),
                    _md_entry(2, "fusion.7"),
                    _md_entry(3, "launch_op")])
    # host plane: busier than nothing but must lose to the device plane
    host = _xplane(
        "/host:CPU",
        lines=[_xline("python", [_xevent(9, 50_000_000)])],
        md_entries=[_md_entry(9, "host_work")])
    return _xspace([device, host])


# ------------------------------------------------------------------- parse


def test_parse_xspace_planes_and_unknown_field_skipping():
    planes = parse_xspace(_minimal_space())
    assert [p["name"] for p in planes] == ["/device:TPU:0 (pid 1)",
                                           "/host:CPU"]
    dev = planes[0]
    # display_name wins over name at both the line and metadata level
    assert [ln[0] for ln in dev["lines"]] == ["launch", "XLA Ops"]
    assert dev["metadata"] == {1: "sort", 2: "fusion.7", 3: "launch_op"}
    # per-metadata accumulation: two sort events fold into one row
    line_name, per_md = dev["lines"][1]
    assert per_md[1] == [5_000_000, 2]
    assert per_md[2] == [2_000_000, 1]      # occurrences default to 1


def test_parse_xspace_empty_and_garbage_tolerance():
    assert parse_xspace(b"") == []
    # a space that is ONLY unknown fields parses to no planes
    assert parse_xspace(_unknowns()) == []


def test_is_device_plane():
    assert is_device_plane("/device:TPU:0 (pid 1)")
    assert is_device_plane("GPU:0 stream")
    assert not is_device_plane("/host:CPU")
    assert not is_device_plane("python threads")


# ----------------------------------------------------------------- summary


def test_summarize_trace_picks_busiest_device_line(tmp_path):
    sub = tmp_path / "plugins" / "profile"
    os.makedirs(sub)
    path = str(sub / "host.xplane.pb")
    with open(path, "wb") as f:
        f.write(_minimal_space())
    assert find_xplane_files(str(tmp_path)) == [path]

    s = summarize_trace(str(tmp_path))
    # the device plane wins although the host plane is 7x busier
    assert s["plane"] == "/device:TPU:0 (pid 1)"
    assert s["busy_us"] == 7.0              # busiest LINE, launch excluded
    assert s["ops"] == {"sort": {"us": 5.0, "count": 2},
                        "fusion.7": {"us": 2.0, "count": 1}}
    assert top_ops(s, k=1) == [("sort", 5.0, 2)]


def test_summarize_trace_empty_dir(tmp_path):
    assert summarize_trace(str(tmp_path)) is None
