"""Critical-path attribution engine + statusz introspection plane.

Covers the ISSUE 18 acceptance criteria directly:

  * a synthetic skewed-rank fixture with a *known* bounding rank: the
    path names that rank, carves the barrier skew into the straggle
    class, and sums its fractions to 1;
  * hedge claims shorten the path (measured basis when the straggler's
    stream is visible, projected otherwise);
  * missing ranks and torn spans degrade to a PARTIAL path with a
    warning — never a crash;
  * the driver prints ``[CRITPATH]`` and ``tools_critical_path.py``
    reconstructs a path matching the measured JTOTAL within 5%;
  * ``--serve --statusz PORT`` answers live JSON snapshots in-flight;
  * a 2-rank run adopts ONE join-level trace id (rank 0 mints, peers
    adopt via the lease-dir channel) and the cross-rank path carries a
    real barrier.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

import tools_critical_path
from tpu_radix_join.main import main
from tpu_radix_join.observability.critpath import (compute_critical_path,
                                                   critical_path_for_dir,
                                                   format_summary,
                                                   load_streams,
                                                   render_report)
from tpu_radix_join.observability.spans import SpanTracer
from tpu_radix_join.observability.statusz import (StatuszServer,
                                                  measurements_sections)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------- fixture helpers

def _span(name, ts, dur, rank, **args):
    return {"name": name, "ph": "X", "ts": float(ts), "dur": float(dur),
            "pid": rank, "tid": 0, "args": args}


def _instant(name, ts, rank, **args):
    return {"name": name, "ph": "i", "s": "p", "ts": float(ts),
            "pid": rank, "tid": 0, "args": args}


def _stream(rank, events, trace_id="t1", epoch_s=100.0):
    return {"rank": rank, "trace_id": trace_id, "epoch_s": epoch_s,
            "tags": {}, "events": events, "file": None}


def _skewed_streams():
    """3 ranks; rank 1 straggles through the JHIST barrier by a known
    amount.  Barrier arrivals 30/90/32 ms -> median 32, skew 58; rank 1
    then owns the tail (last to finish, at 160 ms)."""
    return [
        _stream(0, [_span("JTOTAL", 0, 100_000, 0),
                    _span("JHIST", 0, 30_000, 0),
                    _span("JPROC", 30_000, 60_000, 0)]),
        _stream(1, [_span("JTOTAL", 0, 160_000, 1),
                    _span("JHIST", 0, 90_000, 1),
                    _span("JPROC", 90_000, 70_000, 1)]),
        _stream(2, [_span("JTOTAL", 0, 100_000, 2),
                    _span("JHIST", 0, 32_000, 2),
                    _span("JPROC", 32_000, 60_000, 2)]),
    ]


# -------------------------------------------------- path over synthetic DAGs

def test_single_rank_path_equals_jtotal():
    """No peers, no barriers: the path IS the JTOTAL umbrella, exactly."""
    res = compute_critical_path([_stream(0, [
        _span("JTOTAL", 0, 50_000, 0),
        _span("JPROC", 0, 50_000, 0)])])
    assert "error" not in res
    assert res["path_ms"] == 50.0 and res["jtotal_ms"] == 50.0
    assert res["bounding_rank"] == 0 and not res["partial"]
    assert res["barriers"] == [] and res["missing_ranks"] == []
    assert res["fractions"]["compute"] == pytest.approx(1.0)
    assert res["wait_fraction"] == pytest.approx(0.0)
    assert res["top_phase"]["name"] == "JPROC"


def test_skewed_rank_bounds_the_path():
    """The known straggler bounds both the barrier and the whole path;
    its barrier skew (90 - median 32 = 58 ms) lands in the straggle
    class, attributed to rank 1."""
    res = compute_critical_path(_skewed_streams())
    assert "error" not in res and not res["partial"]
    assert res["path_ms"] == 160.0 and res["jtotal_ms"] == 160.0
    assert res["bounding_rank"] == 1

    (b,) = res["barriers"]
    assert b["name"] == "JHIST" and b["bounding_rank"] == 1
    assert b["skew_ms"] == pytest.approx(58.0)
    assert b["arrivals_ms"] == {"0": 30.0, "1": 90.0, "2": 32.0}

    f = res["fractions"]
    assert sum(f.values()) == pytest.approx(1.0, abs=1e-3)
    assert f["straggle"] == pytest.approx(58.0 / 160.0, abs=1e-3)
    assert res["wait_fraction"] == pytest.approx(58.0 / 160.0, abs=1e-3)
    # the whole path runs through rank 1 (barrier segment + tail)
    assert res["attribution_ms"] == {"1": 160.0}
    # peers idled at the fence: (90-30) + (90-32) ms
    assert res["peer_wait_ms"] == pytest.approx(118.0)
    assert [s["via"] for s in res["segments"]] == ["JHIST#0", "finish"]


def test_collective_and_gap_time_class_as_wait():
    """Exchange spans and uncovered gaps on the owner's path both land
    in collective_wait, not compute."""
    res = compute_critical_path([_stream(0, [
        _span("JTOTAL", 0, 100_000, 0),
        _span("JPROC", 0, 40_000, 0),
        _span("JMPI", 40_000, 30_000, 0),
        # 30 ms tail gap: nothing covers [70, 100] -> wait
    ])])
    f = res["fractions"]
    assert f["compute"] == pytest.approx(0.4, abs=1e-3)
    assert f["collective_wait"] == pytest.approx(0.6, abs=1e-3)
    assert res["phase_ms"]["JMPI"] == pytest.approx(30.0)


def test_hedge_claim_shortens_path_measured_basis():
    """Straggler stream visible: shortening = its late arrival minus the
    claim that released the barrier (160 ms - 100 ms claim = 60 ms)."""
    streams = _skewed_streams()
    streams[0]["events"] += [
        _instant("hedge_claim", 100_000, 0, partition=3, owner=0, epoch=2),
        _instant("hedge", 95_000, 0, straggler=1),
    ]
    res = compute_critical_path(streams)
    hedge = res["hedge"]
    assert hedge["n_claims"] == 1 and hedge["straggler"] == 1
    assert hedge["basis"] == "measured"
    assert hedge["saved_ms_estimate"] == pytest.approx(60.0)
    assert hedge["claims"][0]["partition"] == 3
    line = format_summary(res)
    assert "hedge_claims=1" in line and "saved_ms~60.0" in line
    assert "hedge shortened the path by ~60.0 ms (measured)" \
        in render_report(res)


def test_hedge_projected_basis_and_missing_rank_partial():
    """Straggler's own stream lost (died before save): the hole degrades
    the path to PARTIAL with a warning, and the hedge shortening falls
    back to rate extrapolation from the claim event's progress counters:
    80 ms elapsed at 50% progress projects 160 ms, vs 100 ms actual."""
    streams = [s for s in _skewed_streams() if s["rank"] != 1]
    streams[0]["events"] += [
        _instant("hedge_claim", 80_000, 0, partition=3, owner=0),
        _instant("hedge", 80_000, 0, straggler=1,
                 progress=50, outstanding=50),
    ]
    res = compute_critical_path(streams)
    assert "error" not in res                    # degrade, never crash
    assert res["missing_ranks"] == [1] and res["partial"]
    assert any("missing" in w for w in res["warnings"])
    hedge = res["hedge"]
    assert hedge["basis"] == "projected"
    assert hedge["saved_ms_estimate"] == pytest.approx(60.0)
    assert "PARTIAL" in format_summary(res)


def test_torn_spans_warn_but_never_crash():
    streams = [_stream(0, [
        _span("JTOTAL", 0, 40_000, 0, unclosed=True),
        _span("JPROC", 0, 40_000, 0)])]
    res = compute_critical_path(streams)
    assert "error" not in res
    assert res["partial"]
    assert any("torn" in w for w in res["warnings"])
    assert res["path_ms"] == 40.0


def test_no_streams_degrades_to_error_dict():
    res = compute_critical_path([])
    assert res["error"] and res["partial"]
    assert format_summary(res).startswith("unavailable")
    assert "critical path unavailable" in render_report(res)


def test_epoch_bumps_ride_the_path():
    streams = _skewed_streams()
    streams[2]["events"].append(_instant("rank_lost", 45_000, 2, epoch=3))
    res = compute_critical_path(streams)
    assert res["epoch_bumps"] == [
        {"rank": 2, "event": "rank_lost", "t_ms": 45.0, "epoch": 3}]


def test_window_us_slices_one_query_from_a_resident_stream():
    """Two queries in one tracer stream: the window isolates the second
    query's envelope (the per-query serve-mode path)."""
    stream = _stream(0, [
        _span("JTOTAL", 0, 10_000, 0),
        _span("JTOTAL", 20_000, 30_000, 0),
        _span("JPROC", 20_000, 30_000, 0)])
    res = compute_critical_path([stream], window_us=(15_000, 60_000))
    assert res["path_ms"] == 30.0
    res_empty = compute_critical_path([stream], window_us=(11_000, 12_000))
    assert "error" in res_empty and res_empty["partial"]


# ---------------------------------------------------- trace-id cohort loading

def test_load_streams_trace_cohorts(tmp_path):
    """A directory holding two runs' exports: the largest trace cohort
    wins; an explicit --trace-id overrides; duplicate ranks resolve to
    the newest anchor."""
    d = str(tmp_path)

    def _export(rank, trace_id, epoch_s, fname):
        tr = SpanTracer(rank=rank, trace_id=trace_id, epoch_s=epoch_s,
                        mono_s=0.0)
        tr.begin("JTOTAL")
        tr.end("JTOTAL")
        tr.save(d, filename=fname)

    _export(0, "aaa", 100.0, "r0_a.spans.json")
    _export(1, "aaa", 100.5, "r1_a.spans.json")
    _export(0, "bbb", 200.0, "r0_b.spans.json")
    _export(0, "aaa", 150.0, "r0_a2.spans.json")   # newer duplicate

    streams, warnings = load_streams(d)
    assert [s["rank"] for s in streams] == [0, 1]
    assert all(s["trace_id"] == "aaa" for s in streams)
    assert streams[0]["epoch_s"] == 150.0          # newest anchor won
    assert any("other trace_ids" in w for w in warnings)
    assert any("superseded" in w for w in warnings)

    only_b, _ = load_streams(d, trace_id="bbb")
    assert [s["trace_id"] for s in only_b] == ["bbb"]

    none, warnings = load_streams(d, trace_id="zzz")
    assert none == [] or not none
    assert any("match" in w for w in warnings)


# ------------------------------------------------- driver + CLI integration

def test_driver_critpath_line_and_cli(tmp_path, capsys):
    """A real CPU driver run prints [CRITPATH], stores the result on the
    run metadata path, and tools_critical_path.py reconstructs a path
    matching the measured JTOTAL within 5% (acceptance criterion)."""
    d = str(tmp_path)
    rc = main(["--tuples-per-node", "2048", "--nodes", "2",
               "--timeline-dir", d])
    assert rc == 0
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.startswith("[CRITPATH]")]
    assert lines, out
    assert "bound=rank0" in lines[0] and "path_ms=" in lines[0]

    res = critical_path_for_dir(d)
    assert "error" not in res
    assert res["jtotal_ms"] and res["path_ms"] == pytest.approx(
        res["jtotal_ms"], rel=0.05)

    assert tools_critical_path.main([d]) == 0
    report = capsys.readouterr().out
    assert "critical path:" in report and "measured JTOTAL" in report

    assert tools_critical_path.main([d, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["bounding_rank"] == 0

    assert tools_critical_path.main([str(tmp_path / "nope")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert tools_critical_path.main([str(empty)]) == 1


# ------------------------------------------------------------------- statusz

def test_statusz_snapshot_and_http():
    """In-process server: sections render, provider errors render in
    place (never raise), unknown sections name the known ones, and the
    HTTP plane serves the same payload as snapshot()."""
    srv = StatuszServer(port=0, sections={
        "ok": lambda: {"x": 1},
        "boom": lambda: 1 / 0})
    snap = srv.snapshot()
    assert snap["ok"] == {"x": 1}
    assert "ZeroDivisionError" in snap["boom"]["error"]
    assert "t_epoch_s" in snap

    with srv:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/statusz", timeout=10) as r:
            body = json.load(r)
        assert body["ok"] == {"x": 1}
        with urllib.request.urlopen(base + "/statusz/ok", timeout=10) as r:
            one = json.load(r)
        assert one["ok"] == {"x": 1} and "boom" not in one
        with urllib.request.urlopen(base + "/statusz/nope",
                                    timeout=10) as r:
            unk = json.load(r)
        assert "unknown section" in unk["nope"]["error"]
        assert unk["nope"]["sections"] == ["boom", "ok"]
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            assert json.load(r)["ok"] is True
    assert srv.requests_served == 4


def test_measurements_sections_reflect_registry():
    from tpu_radix_join.performance.measurements import Measurements
    m = Measurements()
    m.attach_tracer(trace_id="cafe")
    m.incr("MTUPLES", 7)
    m.tracer.begin("JPROC")
    secs = measurements_sections(m)
    phase = secs["phase"]()
    assert phase["open_spans"] == {"JPROC": 1}
    assert phase["context"].get("trace_id") == "cafe"
    counters = secs["counters"]()
    assert counters["counters"]["MTUPLES"] == 7
    m.tracer.end("JPROC")


def _wait_for_statusz_port(path, deadline_s=180.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    if line.startswith("[STATUSZ] serving"):
                        return int(line.split(":")[2].split("/")[0])
        time.sleep(0.2)
    raise AssertionError("no [STATUSZ] line on stderr")


def test_statusz_live_serve(tmp_path):
    """--serve --statusz 0 answers JSON snapshots while the session is
    in flight, and the critical_paths section fills per completed query
    (acceptance criterion)."""
    errf = str(tmp_path / "serve.err")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    with open(errf, "w") as err:
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpu_radix_join.main",
             "--serve", "-", "--nodes", "2", "--tuples-per-node", "1024",
             "--statusz", "0", "--timeline-dir", str(tmp_path / "tl")],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=err,
            text=True, cwd=REPO, env=env)
    try:
        port = _wait_for_statusz_port(errf)
        base = f"http://127.0.0.1:{port}"
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            assert json.load(r)["ok"] is True
        # a snapshot BEFORE any query: sections are wired, paths empty
        with urllib.request.urlopen(base + "/statusz", timeout=10) as r:
            body = json.load(r)
        assert {"counters", "phase", "service", "hedge",
                "critical_paths"} <= set(body)
        assert body["critical_paths"] == []

        proc.stdin.write(json.dumps(
            {"query_id": "q0", "tuples_per_node": 1024, "seed": 7}) + "\n")
        proc.stdin.flush()
        outcome = json.loads(proc.stdout.readline())
        assert outcome["query_id"] == "q0" and outcome["status"] == "ok"

        # in-flight (session still resident): per-query path is served
        with urllib.request.urlopen(base + "/statusz/critical_paths",
                                    timeout=10) as r:
            paths = json.load(r)["critical_paths"]
        assert len(paths) == 1 and paths[0]["query_id"] == "q0"
        assert paths[0]["path_ms"] > 0
        with urllib.request.urlopen(base + "/statusz/counters",
                                    timeout=10) as r:
            counters = json.load(r)["counters"]
        assert "JTOTAL" in counters["times_us"]
    finally:
        try:
            proc.stdin.close()
        except OSError:
            pass
        out_rest = proc.stdout.read()
        rc = proc.wait(timeout=180)
        proc.stdout.close()
    with open(errf) as f:
        err_text = f.read()
    assert rc == 0, out_rest + err_text


# ------------------------------------------------------- 2-rank integration

def test_two_rank_trace_adoption_and_cross_rank_path(tmp_path):
    """Two real jax.distributed CPU processes: rank 0 mints the join
    trace id, rank 1 adopts it via the lease-dir channel (one id across
    both span exports), and the reconstructed path spans both ranks with
    a real cross-rank barrier."""
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    d = str(tmp_path)
    procs = []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(rank),
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "tpu_radix_join.main",
             "--tuples-per-node", "1024", "--nodes", "8", "--hosts", "2",
             "--timeline-dir", d],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True, cwd=REPO))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    joined = "\n---- rank boundary ----\n".join(outs)
    assert all(p.returncode == 0 for p in procs), joined

    tids = set()
    for rank in range(2):
        with open(os.path.join(d, f"{rank}.spans.json")) as f:
            tids.add(json.load(f)["metadata"]["trace_id"])
    assert len(tids) == 1 and None not in tids, joined   # satellite 1

    res = critical_path_for_dir(d)
    assert "error" not in res, res
    assert res["ranks"] == [0, 1] and not res["missing_ranks"]
    assert res["trace_id"] in tids
    assert len(res["barriers"]) >= 1, res    # a real cross-rank edge
    assert res["bounding_rank"] in (0, 1)
    assert sum(res["fractions"].values()) == pytest.approx(1.0, abs=0.01)

    cp = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools_critical_path.py"),
         d, "--summary"],
        capture_output=True, text=True, cwd=REPO)
    assert cp.returncode == 0, cp.stdout + cp.stderr
    assert cp.stdout.startswith("[CRITPATH]") and "barriers=" in cp.stdout
