"""Planner subsystem (tpu_radix_join/planner/): device profiles, the
analytic cost model's crossover points, plan selection, the warm-start
plan cache, and the CLI/report wiring.

The crossover tests drive the cost model through the regime boundaries the
chip measurements established (PERF_NOTES.md): in-core -> chunked at the
memory budget, narrow -> full-range at MAX_MERGE_KEY, fused -> split
separated by exactly the dispatch floor.  The cache tests mirror
test_checkpoint_resume.py's hit/miss/corruption/fingerprint discipline,
plus the acceptance observable: a warm second run skips the engine's
sizing pre-pass (no JHIST; CKPTLOAD fires instead).
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from tpu_radix_join.ops.merge_count import MAX_MERGE_KEY
from tpu_radix_join.planner import (JoinPlan, PlanCache, Workload,
                                    explain_table, load_profile, plan_join)
from tpu_radix_join.planner.cache import ManifestMismatch
from tpu_radix_join.planner.cost_model import (PROGRAMS,
                                               enumerate_strategies)
from tpu_radix_join.planner.plan import PlanError
from tpu_radix_join.planner.profile import (REQUIRED_CONSTANTS,
                                            DeviceProfile, ProfileError)

PROF = load_profile()


def _strategy(costs, name):
    return next(c for c in costs if c.strategy == name)


# ----------------------------------------------------------------- profile

def test_checked_in_profile_has_all_cited_constants():
    for key in REQUIRED_CONSTANTS:
        assert PROF.value(key) > 0
        assert PROF.source(key).strip(), key


def test_cost_model_constants_all_declared_required():
    """Every constant the cost model reads must be in REQUIRED_CONSTANTS —
    the guard that a new cost term cannot ship with an uncited, unprofiled
    coefficient."""
    import re

    import tpu_radix_join.planner.cost_model as cm
    with open(cm.__file__) as f:
        used = set(re.findall(r'profile\.value\("([a-z_]+)"\)', f.read()))
    assert used, "cost model reads no profile constants?"
    assert used <= set(REQUIRED_CONSTANTS), used - set(REQUIRED_CONSTANTS)


def test_uncited_constant_rejected():
    bad = {k: dict(PROF.constants[k]) for k in PROF.constants}
    bad["hbm_gbps"] = {"value": 105.0, "source": "  "}
    with pytest.raises(ProfileError, match="uncited"):
        DeviceProfile(name="bad", constants=bad)


def test_missing_constant_rejected():
    bad = {k: PROF.constants[k] for k in PROF.constants if k != "ici_gbps"}
    with pytest.raises(ProfileError, match="ici_gbps"):
        DeviceProfile(name="bad", constants=bad)


def test_newer_schema_rejected():
    with pytest.raises(ProfileError, match="schema_version"):
        DeviceProfile(name="future", constants=dict(PROF.constants),
                      schema_version=99)


def test_profile_roundtrip_and_fingerprint(tmp_path):
    path = str(tmp_path / "p.json")
    PROF.save(path)
    again = load_profile(path)
    assert again.fingerprint() == PROF.fingerprint()
    tweaked = PROF.replace_constants(
        hbm_gbps={"value": 1.0, "source": "test"})
    assert tweaked.fingerprint() != PROF.fingerprint()


# ------------------------------------------------------------- crossovers

def test_crossover_memory_budget_routes_to_chunked():
    """Same relation, shrinking budget: in-core until the working set no
    longer fits, then the chunked grid is the only feasible discipline."""
    w_fits = Workload(r_tuples=1 << 20, s_tuples=1 << 20, key_bound=1 << 20)
    plan, costs = plan_join(PROF, w_fits)
    assert plan.engine == "incore"
    assert _strategy(costs, "chunked_grid").feasible

    w_oom = dataclasses.replace(w_fits, memory_budget_bytes=1 << 20)
    plan, costs = plan_join(PROF, w_oom)
    assert plan.engine == "chunked"
    # the pipelined grid row (sort-reuse + overlap) undercuts the
    # synchronous grid, so OOM workloads route to it with pipeline on
    assert plan.strategy == "chunked_grid_pipelined"
    assert plan.grid_pipeline == "on"
    assert plan.chunk_tuples and plan.chunk_tuples & (plan.chunk_tuples - 1) == 0
    assert not _strategy(costs, "incore_fused_sort_narrow").feasible


def test_crossover_key_bound_narrow_to_full():
    """key_bound straddling MAX_MERGE_KEY flips the 31-bit packed fast
    path infeasible; the full-range row absorbs the 1.7x sort factor."""
    at_limit = Workload(r_tuples=1 << 20, s_tuples=1 << 20,
                        key_bound=MAX_MERGE_KEY + 1)   # max key == limit
    plan, costs = plan_join(PROF, at_limit)
    assert plan.key_range == "narrow"
    assert _strategy(costs, "incore_fused_sort_narrow").feasible

    over = dataclasses.replace(at_limit, key_bound=MAX_MERGE_KEY + 2)
    plan, costs = plan_join(PROF, over)
    assert plan.key_range == "full"
    assert plan.strategy == "incore_fused_sort_full"
    row = _strategy(costs, "incore_fused_sort_narrow")
    assert not row.feasible and "packing limit" in row.note
    # the full-range penalty is the profiled factor, applied to sort only
    narrow_sort = _strategy(costs, "incore_fused_sort_full").terms["sort"]
    base_sort = narrow_sort / PROF.value("full_range_sort_factor")
    assert narrow_sort > base_sort


def test_crossover_fused_vs_split_is_exactly_the_dispatch_floor():
    """The split's cost excess over fused is programs_delta x floor — and
    with the floor zeroed the two tie, with fused winning the tie-break."""
    w = Workload(r_tuples=1 << 22, s_tuples=1 << 22, key_bound=1 << 22,
                 num_nodes=8)
    costs = enumerate_strategies(PROF, w)
    fused = _strategy(costs, "incore_fused_sort_narrow")
    split = _strategy(costs, "incore_split_sort_narrow")
    delta = (PROGRAMS["split_sort"] - PROGRAMS["fused"]) \
        * PROF.value("dispatch_floor_ms")
    assert split.cost_ms - fused.cost_ms == pytest.approx(delta, rel=1e-6)

    free = PROF.replace_constants(
        dispatch_floor_ms={"value": 0.0, "source": "test: zeroed floor"})
    plan, _ = plan_join(free, w)
    assert plan.fused and plan.strategy == "incore_fused_sort_narrow"


def test_pipelined_repeats_amortize_fused_dispatch_only():
    """Repeats divide the fused dispatch floor; the phase split cannot
    pipeline (fence per program), so its floor stays per join."""
    w1 = Workload(r_tuples=1 << 22, s_tuples=1 << 22, key_bound=1 << 22,
                  num_nodes=8, repeats=1)
    w10 = dataclasses.replace(w1, repeats=10)
    fused1 = _strategy(enumerate_strategies(PROF, w1),
                       "incore_fused_sort_narrow").terms["dispatch"]
    fused10 = _strategy(enumerate_strategies(PROF, w10),
                        "incore_fused_sort_narrow").terms["dispatch"]
    assert fused10 == pytest.approx(fused1 / 10, rel=1e-6)
    split1 = _strategy(enumerate_strategies(PROF, w1),
                       "incore_split_sort_narrow").terms["dispatch"]
    split10 = _strategy(enumerate_strategies(PROF, w10),
                        "incore_split_sort_narrow").terms["dispatch"]
    assert split10 == split1


def test_wide_keys_never_narrow():
    plan, costs = plan_join(PROF, Workload(r_tuples=1 << 20,
                                           s_tuples=1 << 20, key_bits=64))
    assert not _strategy(costs, "incore_fused_sort_narrow").feasible
    assert plan.key_range == "auto"


def test_chunked_grid_single_node_only():
    costs = enumerate_strategies(PROF, Workload(
        r_tuples=1 << 20, s_tuples=1 << 20, num_nodes=8))
    assert not _strategy(costs, "chunked_grid").feasible


def test_explain_table_lists_every_strategy():
    plan, costs = plan_join(PROF, Workload(r_tuples=1 << 20,
                                           s_tuples=1 << 20,
                                           key_bound=1 << 20))
    table = explain_table(costs, plan)
    for c in costs:
        assert c.strategy in table
    assert "predicted_ms" in table and "chosen:" in table


# ------------------------------------------------------------------ plans

def test_plan_roundtrip_and_validation(tmp_path):
    plan, _ = plan_join(PROF, Workload(r_tuples=1 << 20, s_tuples=1 << 20,
                                       key_bound=1 << 20))
    path = str(tmp_path / "plan.json")
    plan.save(path)
    assert JoinPlan.load(path) == plan
    doc = plan.to_dict()
    with pytest.raises(PlanError, match="unknown plan fields"):
        JoinPlan.from_dict({**doc, "surprise": 1})
    with pytest.raises(PlanError, match="schema_version"):
        JoinPlan.from_dict({**doc, "schema_version": 99})
    with pytest.raises(PlanError, match="engine"):
        JoinPlan.from_dict({**doc, "engine": "warp"})


# ------------------------------------------------------------------ cache

def _cache(tmp_path, profile=PROF, meas=None):
    return PlanCache(str(tmp_path / "cache"), profile, measurements=meas)


def test_cache_miss_then_hit(tmp_path):
    cache = _cache(tmp_path)
    fp = {"config": 1}
    assert cache.lookup(100, 100, fp) == (None, None)
    plan, _ = plan_join(PROF, Workload(r_tuples=100, s_tuples=100))
    cache.store(100, 100, fp, plan=plan,
                capacities={"cap_r": 64, "cap_s": 128, "local_slack": 1})
    got_plan, caps = cache.lookup(100, 100, fp)
    assert got_plan == plan
    assert caps == {"cap_r": 64, "cap_s": 128, "local_slack": 1}
    # different shapes / config: distinct entries, still misses
    assert cache.lookup(200, 100, fp) == (None, None)
    assert cache.lookup(100, 100, {"config": 2}) == (None, None)


def test_cache_store_merges_plan_and_capacities(tmp_path):
    cache = _cache(tmp_path)
    fp = {"config": 1}
    plan, _ = plan_join(PROF, Workload(r_tuples=100, s_tuples=100))
    cache.store(100, 100, fp, plan=plan)
    cache.store(100, 100, fp, capacities={"cap_r": 8, "cap_s": 8})
    got_plan, caps = cache.lookup(100, 100, fp)
    assert got_plan == plan and caps == {"cap_r": 8, "cap_s": 8}


def test_cache_corruption_is_a_miss(tmp_path):
    from tpu_radix_join.performance.measurements import Measurements
    meas = Measurements()
    cache = _cache(tmp_path, meas=meas)
    fp = {"config": 1}
    cache.store(100, 100, fp, capacities={"cap_r": 8, "cap_s": 8})
    [entry] = [p for p in os.listdir(cache.cache_dir)
               if p.startswith("plan_")]
    with open(os.path.join(cache.cache_dir, entry), "w") as f:
        f.write('{"trunca')
    assert cache.lookup(100, 100, fp) == (None, None)
    assert any(e.get("event") == "checkpoint_corrupt" for e in meas.meta.get("events", []))


def test_cache_profile_change_is_a_stale_miss(tmp_path):
    from tpu_radix_join.performance.measurements import Measurements
    cache = _cache(tmp_path)
    fp = {"config": 1}
    cache.store(100, 100, fp, capacities={"cap_r": 8, "cap_s": 8})
    meas = Measurements()
    recal = PROF.replace_constants(
        hbm_gbps={"value": 9.0, "source": "test"})
    cache2 = PlanCache(cache.cache_dir, recal, measurements=meas)
    assert cache2.lookup(100, 100, fp) == (None, None)
    assert any(e.get("event") == "plan_cache_stale" for e in meas.meta.get("events", []))
    # storing under the new profile overwrites; the old profile now misses
    cache2.store(100, 100, fp, capacities={"cap_r": 16, "cap_s": 16})
    assert cache2.lookup(100, 100, fp)[1] == {"cap_r": 16, "cap_s": 16}
    assert cache.lookup(100, 100, fp) == (None, None)


def test_manifest_detects_rank_and_profile_mismatch(tmp_path):
    cache = _cache(tmp_path)
    cache.check_manifest(num_ranks=2)          # fresh dir: no manifest yet
    assert cache.write_manifest(num_ranks=2, rank=0)
    cache.check_manifest(num_ranks=2)          # same topology: fine
    with pytest.raises(ManifestMismatch, match="2-rank"):
        cache.check_manifest(num_ranks=4)
    recal = PROF.replace_constants(
        hbm_gbps={"value": 9.0, "source": "test"})
    with pytest.raises(ManifestMismatch, match="constants"):
        PlanCache(cache.cache_dir, recal).check_manifest(num_ranks=2)
    # non-zero ranks never write
    assert cache.write_manifest(num_ranks=8, rank=1)
    cache.check_manifest(num_ranks=2)


# ------------------------------------------- engine warm start (tentpole)

def _batches(n, seed=0):
    import jax.numpy as jnp

    from tpu_radix_join.data.tuples import TupleBatch
    rng = np.random.default_rng(seed)
    mk = lambda k: TupleBatch(key=jnp.asarray(k),
                              rid=jnp.arange(n, dtype=jnp.uint32))
    return (mk(rng.integers(0, 1 << 20, n, dtype=np.uint32)),
            mk(rng.integers(0, 1 << 20, n, dtype=np.uint32)))


def test_warm_start_skips_sizing_prepass(tmp_path):
    """The acceptance observable: cold run sizes (JHIST present, entry
    saved); warm run skips the pre-pass (no JHIST, CKPTLOAD fired) and
    returns the identical count."""
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.performance.measurements import Measurements
    r, s = _batches(1 << 12)
    cfg = JoinConfig(num_nodes=8)

    m_cold = Measurements()
    cold = HashJoin(cfg, measurements=m_cold,
                    plan_cache=_cache(tmp_path, meas=m_cold)).join_arrays(r, s)
    assert cold.ok
    assert "JHIST" in m_cold.times_us
    assert m_cold.counters.get("CKPTSAVE", 0) >= 1
    assert m_cold.counters.get("CKPTLOAD", 0) == 0

    m_warm = Measurements()
    warm = HashJoin(cfg, measurements=m_warm,
                    plan_cache=_cache(tmp_path, meas=m_warm)).join_arrays(r, s)
    assert warm.ok and warm.matches == cold.matches
    assert "JHIST" not in m_warm.times_us
    assert m_warm.counters.get("CKPTLOAD", 0) >= 1


def test_warm_start_invalidated_by_profile_change(tmp_path):
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.performance.measurements import Measurements
    r, s = _batches(1 << 12)
    cfg = JoinConfig(num_nodes=8)
    m1 = Measurements()
    assert HashJoin(cfg, measurements=m1,
                    plan_cache=_cache(tmp_path, meas=m1)).join_arrays(r, s).ok
    recal = PROF.replace_constants(
        sort_stage_unit_ms={"value": 9.9, "source": "test"})
    m2 = Measurements()
    res = HashJoin(cfg, measurements=m2,
                   plan_cache=_cache(tmp_path, profile=recal,
                                     meas=m2)).join_arrays(r, s)
    assert res.ok
    assert "JHIST" in m2.times_us   # sized again: stale entry not trusted


def test_engine_without_cache_unchanged(tmp_path):
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.performance.measurements import Measurements
    r, s = _batches(1 << 12)
    m = Measurements()
    res = HashJoin(JoinConfig(num_nodes=8), measurements=m).join_arrays(r, s)
    assert res.ok
    assert "JHIST" in m.times_us
    assert m.counters.get("CKPTSAVE", 0) == 0


# -------------------------------------------------------------------- CLI

def test_cli_plan_explain_prints_cost_table(capsys):
    from tpu_radix_join.main import main
    rc = main(["--tuples-per-node", "4096", "--nodes", "8",
               "--plan", "explain"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "predicted_ms" in out
    assert "incore_fused_sort_narrow" in out
    assert "chunked_grid" in out
    assert "chosen:" in out


def test_cli_plan_auto_runs_and_caches(capsys, tmp_path):
    from tpu_radix_join.main import main
    cache_dir = str(tmp_path / "pc")
    argv = ["--tuples-per-node", "2048", "--nodes", "8", "--plan", "auto",
            "--plan-cache-dir", cache_dir]
    assert main(argv) == 0
    cold = capsys.readouterr().out
    assert "[PLAN] strategy=" in cold
    assert "JHIST" in cold
    assert main(argv) == 0
    warm = capsys.readouterr().out
    assert "[PLAN] strategy=" in warm
    assert "JHIST" not in warm          # sizing pre-pass skipped
    assert "CKPTLOAD" in warm
    assert "[RESULTS] Tuples: 16384" in warm


def test_cli_plan_from_file(capsys, tmp_path):
    from tpu_radix_join.main import main
    plan, _ = plan_join(PROF, Workload(r_tuples=1 << 14, s_tuples=1 << 14,
                                       key_bound=1 << 14, num_nodes=8))
    path = str(tmp_path / "plan.json")
    plan.save(path)
    rc = main(["--tuples-per-node", "2048", "--nodes", "8", "--plan", path])
    assert rc == 0
    out = capsys.readouterr().out
    assert f"[PLAN] strategy={plan.strategy}" in out
    assert "[RESULTS] Tuples: 16384" in out


def test_cli_manifest_mismatch_fails_fast(capsys, tmp_path):
    from tpu_radix_join.main import main
    cache_dir = str(tmp_path / "pc")
    cache = PlanCache(cache_dir, PROF)
    cache.write_manifest(num_ranks=4, rank=0)   # pretend a 4-host run wrote it
    rc = main(["--tuples-per-node", "1024", "--nodes", "2", "--plan", "auto",
               "--plan-cache-dir", cache_dir])
    assert rc == 2
    err = capsys.readouterr().err
    assert "4-rank" in err


def test_grid_checkpoint_rejects_different_plan(tmp_path):
    import jax.numpy as jnp

    from tpu_radix_join.data.tuples import TupleBatch
    from tpu_radix_join.ops.chunked import chunked_join_grid
    from tpu_radix_join.robustness.checkpoint import CheckpointMismatch
    keys = np.arange(4096, dtype=np.uint32)
    chunk = TupleBatch(key=jnp.asarray(keys),
                       rid=jnp.arange(4096, dtype=jnp.uint32))
    ckpt = str(tmp_path / "grid.ckpt")
    plan_a = JoinPlan(engine="chunked", strategy="chunked_grid",
                      chunk_tuples=4096)
    total = chunked_join_grid([chunk], [chunk], 1024, checkpoint_path=ckpt,
                              checkpoint_tag="t", plan=plan_a)
    assert total == 4096
    plan_b = dataclasses.replace(plan_a, chunk_tuples=2048)
    with pytest.raises(CheckpointMismatch):
        chunked_join_grid([chunk], [chunk], 1024, checkpoint_path=ckpt,
                          checkpoint_tag="t", plan=plan_b)


# ----------------------------------------------- report / profile tooling

def test_print_results_surfaces_failure_classes(capsys):
    from tpu_radix_join.performance import print_results
    from tpu_radix_join.performance.measurements import Measurements
    ok, bad = Measurements(node_id=0), Measurements(node_id=1)
    ok.meta["failure_class"] = "ok"
    bad.meta["failure_class"] = "capacity_overflow"
    print_results([ok, bad])
    out = capsys.readouterr().out
    assert "FailureClasses: 1/2 ranks not ok" in out
    assert "rank1=capacity_overflow" in out
    print_results([ok])
    assert "FailureClasses: ok x1" in capsys.readouterr().out


def test_emit_profile_distills_artifacts(tmp_path):
    import tools_make_report as tmr
    art = tmp_path / "chip_rX"
    perf = art / "perf_16m_sort"
    perf.mkdir(parents=True)
    (perf / "0.perf").write_text("SDISPATCH\t123000\tus\n")
    trace = art / "trace_pipeline"
    trace.mkdir()
    (trace / "breakdown.json").write_text(json.dumps({
        "plane": "/device:TPU:0", "busy_us": 2e5, "iters": 10,
        "sort_share": 0.5, "size": 1 << 24, "discipline": "sort"}))
    out = str(tmp_path / "prof.json")
    assert tmr.emit_profile(str(art), out, name="v5e_test") == 0
    prof = load_profile(out)
    assert prof.name == "v5e_test"
    assert prof.value("dispatch_floor_ms") == pytest.approx(123.0)
    assert "artifact:" in prof.source("dispatch_floor_ms")
    # sort unit: 10 ms/iter sort over a 33.5M union == one reference unit
    # per U(33.5M) stages
    from tpu_radix_join.planner.profile import (SORT_REF_ELEMS,
                                                sort_stage_units)
    expect = 10.0 / sort_stage_units(SORT_REF_ELEMS)
    assert prof.value("sort_stage_unit_ms") == pytest.approx(expect,
                                                             rel=1e-3)
    assert "artifact:" in prof.source("sort_stage_unit_ms")
    # untouched constants keep their committed citations
    assert prof.source("hbm_gbps") == PROF.source("hbm_gbps")


def test_bench_backend_unavailable_json():
    """bench.py satellite: an exhausted backend wait emits a parseable
    BENCH record carrying the failure class and the planned strategy."""
    import subprocess
    import sys
    env = dict(os.environ, BENCH_TUNNEL_WAIT_SEC="0",
               BENCH_PROBE_TIMEOUT_SEC="15", JAX_PLATFORMS="tpu")
    env.pop("TPU_RJ_FORCE_PLATFORM", None)
    p = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), os.pardir,
                                      "bench.py")],
        capture_output=True, text=True, timeout=300, env=env)
    assert p.returncode == 2, p.stderr[-2000:]
    line = [l for l in p.stdout.splitlines() if l.startswith("{")][-1]
    doc = json.loads(line)
    assert doc["failure_class"] == "backend_unavailable"
    # on the TPU-configured (unprobed) backend the radix-sort arm prices
    # the narrow flat sort back under the twolevel second pass at the
    # bench union — the planner must still have run and picked a chip
    # strategy
    assert doc["planned_strategy"] == "incore_fused_sort_narrow"
    assert doc["value"] == 0.0
