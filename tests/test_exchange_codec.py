"""Exchange wire codec + staged all_to_all (ISSUE 7).

Three layers under test, bottom-up:

  * the bit-packed wire format itself (data/tuples.py pack/unpack_blocks):
    property round-trip over key width x fanout x bound tightness, with
    pad-slot garbage that must not leak and sentinels that must survive
    bit-exactly;
  * the staged exchange (parallel/window.py block_all_to_all): every mode
    must deliver the byte-identical ordering of the fused route, on the
    flat and the hierarchical mesh;
  * the engine + planner wiring: an 8-node join under ``exchange_codec=
    pack, exchange_stages=4`` is oracle-exact with verification on, the
    regress gate pins the footprint tags lower-is-better, ``--plan``
    surfaces the codec choice, and schema-v1 profiles load through the
    ici_bytes_per_s shim.
"""

import copy
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_radix_join.data import tuples as T
from tpu_radix_join.parallel import window as W
from tpu_radix_join.parallel.mesh import make_hierarchical_mesh, make_mesh

N = 8


# ------------------------------------------------------------ codec core

def _contract_blocks(rng, spec, key_space, nb):
    """Blocks honoring the scatter_to_blocks_grouped contract — each block's
    valid tuples contiguous at the front and sorted by partition id — with
    every pad slot filled with all-ones garbage the codec must mask out.
    Returns (lanes dict, group_counts, per-tuple expected arrays)."""
    cap = spec.capacity
    mask = spec.num_sub - 1
    # one full block, one empty block, the rest partial
    counts = [cap, 0] + list(rng.integers(1, cap, nb - 2))
    keys = np.full(nb * cap, (1 << 64) - 1, np.uint64)
    rids = np.full(nb * cap, 0xFFFFFFFF, np.uint64)
    group_counts = np.zeros((nb, spec.num_sub), np.uint32)
    for b, cnt in enumerate(counts):
        k = rng.integers(0, key_space, cnt, dtype=np.uint64)
        if cnt:
            k[0] = key_space - 1          # exercise the exact bound edge
        pid = (k & np.uint64(mask)).astype(np.uint32)
        order = np.argsort(pid, kind="stable")
        keys[b * cap:b * cap + cnt] = k[order]
        rids[b * cap:b * cap + cnt] = rng.integers(
            0, 1 << 20, cnt, dtype=np.uint64)
        group_counts[b] = np.bincount(pid, minlength=spec.num_sub)
    return keys, rids, np.asarray(counts), group_counts


def _roundtrip(spec, keys, rids, group_counts, side):
    lo = jnp.asarray(keys & np.uint64(0xFFFFFFFF), jnp.uint32)
    hi = (jnp.asarray(keys >> np.uint64(32), jnp.uint32)
          if spec.wide else None)
    blocks = T.TupleBatch(key=lo, rid=jnp.asarray(rids, jnp.uint32),
                          key_hi=hi)
    words = T.pack_blocks(spec, blocks, jnp.asarray(group_counts))
    assert words.shape == (group_counts.shape[0] * spec.block_words,)
    return T.unpack_blocks(spec, words, side)


@pytest.mark.parametrize("wide", [False, True], ids=["key32", "key64"])
@pytest.mark.parametrize("fanout_bits", [0, 1, 2, 3, 4, 5])
@pytest.mark.parametrize("bound", ["tight", "loose", "none"])
def test_codec_roundtrip_bit_exact(wide, fanout_bits, bound):
    rng = np.random.default_rng(fanout_bits * 7 + (13 if wide else 0))
    nb, cap = 4, 64
    key_space = (1 << 44) if wide else (1 << 20)
    # spec bounds: tight hugs the data, loose wastes headroom, none falls
    # back to full lane width — all must stay exact
    key_bound = {"tight": key_space, "loose": key_space << 7,
                 "none": None}[bound]
    rid_bound = {"tight": 1 << 20, "loose": 1 << 29, "none": None}[bound]
    spec = T.make_wire_spec(cap, fanout_bits, wide=wide,
                            key_bound=key_bound, rid_bound=rid_bound)
    if bound == "tight":
        # the tight spec actually shrinks the tuple vs the no-bound layout
        free = T.make_wire_spec(cap, fanout_bits, wide=wide)
        assert spec.tuple_bits < free.tuple_bits
    keys, rids, counts, gc = _contract_blocks(rng, spec, key_space, nb)
    got, got_counts = _roundtrip(spec, keys, rids, gc, "inner")
    np.testing.assert_array_equal(np.asarray(got_counts), counts)
    valid = (np.arange(nb * cap) % cap) < counts[np.arange(nb * cap) // cap]
    got_key = np.asarray(got.key).astype(np.uint64)
    if wide:
        got_key |= np.asarray(got.key_hi).astype(np.uint64) << np.uint64(32)
    np.testing.assert_array_equal(got_key[valid], keys[valid])
    np.testing.assert_array_equal(
        np.asarray(got.rid)[valid].astype(np.uint64), rids[valid])
    # pad slots are the side's exact sentinels — garbage never leaks
    assert (np.asarray(got.key)[~valid] == T.R_PAD_KEY).all()
    assert (np.asarray(got.rid)[~valid] == np.asarray(T.PAD_RID)).all()
    assert not np.asarray(T.valid_mask(got, "inner"))[~valid].any()


def test_codec_outer_side_sentinels():
    spec = T.make_wire_spec(16, 2, key_bound=1 << 10, rid_bound=1 << 10)
    rng = np.random.default_rng(3)
    keys, rids, counts, gc = _contract_blocks(rng, spec, 1 << 10, 3)
    got, _ = _roundtrip(spec, keys, rids, gc, "outer")
    valid = (np.arange(3 * 16) % 16) < counts[np.arange(3 * 16) // 16]
    assert (np.asarray(got.key)[~valid] == T.S_PAD_KEY).all()
    assert not np.asarray(T.valid_mask(got, "outer"))[~valid].any()


def test_wire_spec_geometry_and_errors():
    spec = T.make_wire_spec(1024, 5, key_bound=1 << 20, rid_bound=1 << 20)
    # 15 kept key bits + 20 rid bits = 35-bit tuples, 32 header words
    assert spec.tuple_bits == 35 and spec.header_words == 32
    assert spec.bytes_per_tuple < 8.0
    assert spec.bytes_per_block == 4 * spec.block_words
    with pytest.raises(ValueError, match="capacity"):
        T.make_wire_spec(0, 5)
    with pytest.raises(ValueError, match="fanout_bits"):
        T.make_wire_spec(8, 32)
    with pytest.raises(ValueError, match="key_bound"):
        T.make_wire_spec(8, 0, key_bound=0)
    with pytest.raises(ValueError, match="multiple"):
        T.unpack_blocks(spec, jnp.zeros((spec.block_words + 1,),
                                        jnp.uint32), "inner")


# ------------------------------------------------- staged exchange parity

BLOCK = 96          # not divisible by 5: exercises uneven column groups


def _all_to_all(x, mode, hierarchical=False):
    if hierarchical:
        mesh = make_hierarchical_mesh(2, N)
        spec, axis = P(("dcn", "ici")), ("dcn", "ici")
    else:
        mesh = make_mesh(N)
        spec, axis = P("nodes"), "nodes"
    fn = jax.shard_map(
        lambda v: W.block_all_to_all(v, N, BLOCK, axis, mode=mode),
        mesh=mesh, in_specs=spec, out_specs=spec)
    return np.asarray(jax.jit(fn)(x))


def test_staged_orderings_match_fused():
    x = jnp.arange(N * N * BLOCK, dtype=jnp.uint32)
    fused = _all_to_all(x, "fused")
    for mode in ("staged:2", "staged:4", "staged:5", "auto", 3):
        np.testing.assert_array_equal(_all_to_all(x, mode), fused, str(mode))


def test_hierarchical_route_matches_flat_fused_and_staged():
    x = jnp.arange(N * N * BLOCK, dtype=jnp.uint32)
    fused = _all_to_all(x, "fused")
    np.testing.assert_array_equal(_all_to_all(x, "fused", True), fused)
    np.testing.assert_array_equal(_all_to_all(x, "staged:3", True), fused)


def test_parse_exchange_mode():
    assert W.parse_exchange_mode("fused", 1 << 20) == 1
    assert W.parse_exchange_mode("staged:4", 1 << 20) == 4
    assert W.parse_exchange_mode("auto", 4096) == 4
    assert W.parse_exchange_mode("auto", 4095) == 1
    assert W.parse_exchange_mode(6, 1 << 20) == 6
    assert W.parse_exchange_mode("staged:100", 3) == 3   # clamps to block
    with pytest.raises(ValueError, match="must be an integer"):
        W.parse_exchange_mode("staged:x", 8)
    with pytest.raises(ValueError, match="exchange mode"):
        W.parse_exchange_mode("bogus", 8)
    with pytest.raises(ValueError, match=">= 1"):
        W.parse_exchange_mode(0, 8)


def test_block_all_to_all_validates_length():
    with pytest.raises(ValueError, match="leading axis"):
        W.block_all_to_all(jnp.zeros((10,), jnp.uint32), N, 2, "nodes")


def test_hierarchical_validates_mesh_factorization():
    mesh = make_hierarchical_mesh(2, N)
    fn = jax.shard_map(
        lambda v: W.hierarchical_block_all_to_all(v, 6, 2, "dcn", "ici"),
        mesh=mesh, in_specs=P(("dcn", "ici")), out_specs=P(("dcn", "ici")))
    with pytest.raises(ValueError, match="factor the node count"):
        jax.jit(fn)(jnp.zeros((N * 12,), jnp.uint32))


def test_window_rejects_unresolved_auto_codec():
    with pytest.raises(ValueError, match="resolved by the caller"):
        W.Window(N, 64, "nodes", "inner", codec="auto")


# ------------------------------------------------ packed window exchange

def test_window_pack_matches_off_exchange():
    """Same tuples through the raw and the packed+staged window: identical
    per-sender receive counts, zero overflow, identical per-block tuple
    multisets (the packed route pid-sorts within blocks, so ordering inside
    one block may legally differ)."""
    mesh = make_mesh(N)
    cap, per = 256, 1000
    rng = np.random.default_rng(9)
    key = jnp.asarray(rng.integers(0, 1 << 18, N * per, dtype=np.uint64),
                      jnp.uint32)
    rid = jnp.arange(N * per, dtype=jnp.uint32)

    def run(codec, mode):
        def body(k, r):
            pid = k & jnp.uint32(7)
            win = W.Window(N, cap, "nodes", "inner", codec=codec, mode=mode,
                           fanout_bits=3, key_bound=1 << 18,
                           rid_bound=N * per)
            res = win.exchange(T.TupleBatch(key=k, rid=r), pid, pid=pid)
            return (res.batch.key, res.batch.rid, res.recv_counts,
                    res.send_overflow[None])
        fn = jax.shard_map(body, mesh=mesh,
                           in_specs=(P("nodes"), P("nodes")),
                           out_specs=(P("nodes"),) * 4)
        k, r, cnt, ovf = jax.jit(fn)(key, rid)
        return (np.asarray(k), np.asarray(r), np.asarray(cnt),
                np.asarray(ovf))

    k_off, r_off, c_off, o_off = run("off", "fused")
    k_pk, r_pk, c_pk, o_pk = run("pack", "staged:4")
    assert not o_off.any() and not o_pk.any()
    np.testing.assert_array_equal(c_pk, c_off)
    cnt = c_off.reshape(-1)
    for b in range(N * N):      # per-(receiver, sender) block multisets
        lo, hi = b * cap, b * cap + cnt[b]
        off_pairs = sorted(zip(k_off[lo:hi], r_off[lo:hi]))
        pk_pairs = sorted(zip(k_pk[lo:hi], r_pk[lo:hi]))
        assert off_pairs == pk_pairs, f"block {b}"
        # pad slots carry the inner sentinel on both routes
        assert (k_pk[b * cap + cnt[b]:(b + 1) * cap] == T.R_PAD_KEY).all()


# ------------------------------------------------------ engine + planner

def test_join_pack_staged_is_oracle_exact():
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.performance import Measurements
    from tpu_radix_join.performance.measurements import (PACKRATIO,
                                                         WIREBYTES, XSTAGES)

    inner = Relation(N << 10, N, "unique", seed=41)
    outer = Relation(N << 10, N, "unique", seed=42)
    expected = inner.expected_matches(outer)
    m = Measurements(node_id=0, num_nodes=N)
    eng = HashJoin(JoinConfig(num_nodes=N, exchange_codec="pack",
                              exchange_stages=4, verify="check"),
                   measurements=m)
    res = eng.join(inner, outer)
    assert res.ok and res.matches == expected
    xs = m.meta["exchange_plan"]
    assert xs["codec"] == "pack" and xs["stages"] == 4
    assert xs["bytes_per_tuple"] < 8.0
    assert xs["peak_exchange_bytes"] < xs["raw_bytes"]
    assert m.counters[WIREBYTES] == xs["wire_bytes"]
    assert m.counters[PACKRATIO] < 100
    assert m.counters[XSTAGES] == 4


def test_config_validates_exchange_knobs():
    from tpu_radix_join import JoinConfig
    with pytest.raises(ValueError, match="exchange codec"):
        JoinConfig(exchange_codec="bogus")
    with pytest.raises(ValueError, match="exchange_stages"):
        JoinConfig(exchange_stages=-1)


def test_regress_pins_exchange_tags_lower_is_better():
    from tpu_radix_join.observability.regress import higher_is_better
    assert not higher_is_better("WIREBYTES")
    assert not higher_is_better("peak_exchange_bytes")
    assert not higher_is_better("peak_exchange_bytes_raw")
    assert not higher_is_better("bytes_per_tuple")
    assert higher_is_better("value")            # the reduction headline
    assert higher_is_better("peak_speedup")


def test_planner_prices_codec_and_explains_choice():
    from tpu_radix_join import JoinConfig
    from tpu_radix_join.planner import (Workload, explain_table, load_profile,
                                        plan_join)
    from tpu_radix_join.planner.cost_model import (incore_resident_bytes,
                                                   plan_exchange)

    prof = load_profile()
    loose = Workload(r_tuples=N << 17, s_tuples=N << 17, key_bound=N << 17,
                     num_nodes=N)
    assert plan_exchange(prof, loose).codec == "off"
    # near the residency envelope the packed wire buys the headroom back
    tight = Workload(r_tuples=N << 17, s_tuples=N << 17, key_bound=N << 17,
                     num_nodes=N, memory_budget_bytes=int(
                         incore_resident_bytes(loose) * 1.5))
    xp = plan_exchange(prof, tight)
    assert xp.codec == "pack" and xp.bytes_per_tuple < 8.0
    plan, costs = plan_join(prof, tight)
    assert plan.exchange_codec == "pack" and plan.exchange_stages >= 1
    assert "exchange: codec=pack" in explain_table(costs, plan)
    # the plan's knobs bind directly onto JoinConfig
    cfg = JoinConfig(num_nodes=N, **plan.config_kwargs())
    assert cfg.exchange_codec == "pack"


def test_plan_schema_v4_and_older_back_compat():
    from tpu_radix_join.planner.plan import PLAN_SCHEMA_VERSION, JoinPlan
    assert PLAN_SCHEMA_VERSION == 5
    doc = JoinPlan(engine="incore", exchange_codec="pack",
                   exchange_stages=4,
                   predicted_terms={"shuffle": 1.5}).to_dict()
    again = JoinPlan.from_dict(doc)
    assert again.exchange_codec == "pack" and again.exchange_stages == 4
    assert again.predicted_terms == {"shuffle": 1.5}
    # a v4 file (pre-sort-arm) has no sort_impl: runtime auto on load
    v4 = {k: v for k, v in doc.items() if k != "sort_impl"}
    v4["schema_version"] = 4
    assert JoinPlan.from_dict(v4).sort_impl == "auto"
    # a v3 file (pre-audit) has no predicted_terms: empty table on load
    v3 = {k: v for k, v in doc.items() if k != "predicted_terms"}
    v3["schema_version"] = 3
    assert JoinPlan.from_dict(v3).predicted_terms == {}
    assert JoinPlan.from_dict(v3).exchange_codec == "pack"
    old = {k: v for k, v in v3.items()
           if k not in ("exchange_codec", "exchange_stages")}
    old["schema_version"] = 2
    assert JoinPlan.from_dict(old).exchange_codec == "off"
    assert JoinPlan.from_dict(old).exchange_stages == 1


def test_profile_v1_shim_derives_ici_bytes_per_s(tmp_path):
    from tpu_radix_join.planner import load_profile
    prof = load_profile()
    doc = copy.deepcopy(prof.to_dict())
    doc["schema_version"] = 1
    del doc["constants"]["ici_bytes_per_s"]
    path = tmp_path / "v1.json"
    path.write_text(json.dumps(doc))
    old = load_profile(str(path))
    assert old.value("ici_bytes_per_s") == prof.value("ici_gbps") * 1e9
    assert old.source("ici_bytes_per_s").startswith("shim:derived")
    # a v2 file with the constant present loads untouched
    assert prof.source("ici_bytes_per_s").startswith("PERF_NOTES")
