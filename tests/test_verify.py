"""Tier-1 coverage for end-to-end data-integrity verification
(robustness/verify.py): the checksum primitives, the engine's
``--verify check|repair`` modes against an injected exchange-lane
corruption, the ``data_corruption`` failure class, and the fault-site
observability satellites (near-miss arming warning, FaultSites report
line)."""

import io

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_radix_join.core.config import JoinConfig
from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.operators.hash_join import HashJoin
from tpu_radix_join.performance.measurements import (GRIDPAIRS, Measurements,
                                                     VCHK, VCHKN, VFAIL,
                                                     VREPAIR, print_results)
from tpu_radix_join.robustness import faults
from tpu_radix_join.robustness.faults import FaultInjector
from tpu_radix_join.robustness.retry import DATA_CORRUPTION
from tpu_radix_join.robustness import verify
from tpu_radix_join.robustness.verify import (DataCorruption,
                                              cross_check_counts,
                                              damaged_partitions,
                                              device_partition_checksums)

NODES = 4


def _join_inputs(n=1 << 12, seed=0):
    """Oracle-friendly inputs: R unique 1..n, S uniform over 1..n, so the
    exact match count is n and any corrupted lane moves the count."""
    rng = np.random.default_rng(seed)
    rk = (rng.permutation(n) + 1).astype(np.uint32)
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    r = TupleBatch(key=jnp.asarray(rk), rid=jnp.arange(n, dtype=jnp.uint32))
    s = TupleBatch(key=jnp.asarray(sk), rid=jnp.arange(n, dtype=jnp.uint32))
    return r, s, n


# ------------------------------------------------------------ primitives

def test_segmented_xor_fold_matches_reference():
    from tpu_radix_join.ops.sorting import segmented_xor_fold

    seg = jnp.asarray([2, 0, 1, 0, 2, 3], jnp.uint32)
    val = jnp.asarray([5, 13, 7, 9, 17, 11], jnp.uint32)
    out = np.asarray(segmented_xor_fold(seg, val, 4))
    assert out.tolist() == [13 ^ 9, 7, 5 ^ 17, 11]


def test_segmented_xor_fold_empty_segment_is_zero():
    from tpu_radix_join.ops.sorting import segmented_xor_fold

    seg = jnp.asarray([0, 0, 3], jnp.uint32)
    val = jnp.asarray([1, 2, 4], jnp.uint32)
    out = np.asarray(segmented_xor_fold(seg, val, 4))
    assert out.tolist() == [3, 0, 0, 4]


def test_device_partition_checksums_counts_and_valid_routing():
    key = jnp.asarray([10, 20, 30, 40, 50], jnp.uint32)
    pid = jnp.asarray([0, 1, 0, 1, 1], jnp.uint32)
    valid = jnp.asarray([True, True, True, True, False])
    adds, xors = device_partition_checksums(key, pid, 2, valid=valid)
    # row 0 = tuple counts, row 1 = key sums; the invalid lane is routed to
    # the discard bucket and must not contribute anywhere
    assert np.asarray(adds[0]).tolist() == [2, 2]
    assert np.asarray(adds[1]).tolist() == [40, 60]
    assert np.asarray(xors[0]).tolist() == [10 ^ 30, 20 ^ 40]


def test_checksums_order_independent():
    rng = np.random.default_rng(3)
    key = rng.integers(0, 1 << 20, size=257).astype(np.uint32)
    pid = (key & 7).astype(np.uint32)
    perm = rng.permutation(257)
    a = device_partition_checksums(jnp.asarray(key), jnp.asarray(pid), 8)
    b = device_partition_checksums(jnp.asarray(key[perm]),
                                   jnp.asarray(pid[perm]), 8)
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
    np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))


def test_damaged_partitions_localizes_single_bit():
    pre = np.arange(12, dtype=np.uint32).reshape(3, 4)
    post = pre.copy()
    assert damaged_partitions(pre, post).size == 0
    post[1, 2] ^= 1
    assert damaged_partitions(pre, post).tolist() == [2]
    with pytest.raises(ValueError):
        damaged_partitions(pre, post[:2])


def test_cross_check_counts_bound_and_total():
    r = np.asarray([2, 3], np.uint64)
    s = np.asarray([4, 5], np.uint64)
    ok_counts = np.asarray([[8, 15]], np.uint64)     # == r*s bound
    assert cross_check_counts(ok_counts, 23, r, s) is None
    assert cross_check_counts(ok_counts, 22, r, s) is not None
    over = np.asarray([[9, 15]], np.uint64)          # partition 0 over bound
    assert cross_check_counts(over, 24, r, s) is not None


# --------------------------------------------------------------- engine

def test_verify_check_clean_run_counts_checks():
    r, s, oracle = _join_inputs()
    m = Measurements()
    engine = HashJoin(JoinConfig(num_nodes=NODES, verify="check"),
                      measurements=m)
    res = engine.join_arrays(r, s)
    assert res.ok and res.matches == oracle
    assert m.counters[VCHKN] >= 2          # R + S exchange checksum sets
    assert m.counters.get(VFAIL, 0) == 0
    assert VCHK in m.times_us              # verification time was metered


def test_exchange_corruption_without_verify_is_silent():
    """The violation the checksums exist to rule out: with verify off, a
    flipped exchange lane yields ok=True and a wrong count."""
    r, s, oracle = _join_inputs()
    engine = HashJoin(JoinConfig(num_nodes=NODES, verify="off"))
    with FaultInjector() as inj:
        inj.arm(faults.EXCHANGE_CORRUPT, at=1)
        res = engine.join_arrays(r, s)
    assert inj.fired(faults.EXCHANGE_CORRUPT) == 1
    assert res.ok
    assert res.matches != oracle


def test_verify_check_classifies_exchange_corruption():
    r, s, oracle = _join_inputs()
    m = Measurements()
    engine = HashJoin(JoinConfig(num_nodes=NODES, verify="check"),
                      measurements=m)
    with FaultInjector(measurements=m) as inj:
        inj.arm(faults.EXCHANGE_CORRUPT, at=1)
        res = engine.join_arrays(r, s)
    assert not res.ok
    diag = res.diagnostics
    assert diag["failure_class"] == DATA_CORRUPTION
    assert diag["data_corruption_partitions"] >= 1
    # satellite: per-site fired/hit counts ride along in diagnostics
    stats = diag["fault_sites"][faults.EXCHANGE_CORRUPT]
    assert stats["fired"] == 1 and stats["hits"] == 1
    assert m.counters[VFAIL] >= 1


def test_verify_repair_recomputes_only_damaged_partition():
    """Satellite: under --verify repair a single damaged partition is
    recomputed partition-granular (one grid pair), and the repaired count
    matches the fault-free run exactly."""
    r, s, oracle = _join_inputs()
    m = Measurements()
    engine = HashJoin(JoinConfig(num_nodes=NODES, verify="repair"),
                      measurements=m)
    with FaultInjector(measurements=m) as inj:
        inj.arm(faults.EXCHANGE_CORRUPT, at=1)
        res = engine.join_arrays(r, s)
    assert res.ok
    assert res.matches == oracle
    diag = res.diagnostics
    assert diag["repaired"] == "partition"
    assert len(diag["repaired_partitions"]) == 1
    assert diag["failure_class"] == DATA_CORRUPTION   # detected, then fixed
    assert m.counters[VREPAIR] == 1
    assert m.counters[GRIDPAIRS] == 1      # exactly one recompute pair


@pytest.mark.parametrize("mode", ["check", "repair"])
def test_verify_bucket_path(mode):
    """The bucket probe keeps its own post-sort checksum sets; corruption is
    still classified, and repair falls back to a full recompute."""
    r, s, oracle = _join_inputs()
    cfg = JoinConfig(num_nodes=NODES, verify=mode, probe_algorithm="bucket")
    clean = HashJoin(JoinConfig(num_nodes=NODES, probe_algorithm="bucket",
                                verify=mode)).join_arrays(r, s)
    assert clean.ok and clean.matches == oracle
    engine = HashJoin(cfg)
    with FaultInjector() as inj:
        inj.arm(faults.EXCHANGE_CORRUPT, at=1)
        res = engine.join_arrays(r, s)
    if mode == "check":
        assert not res.ok
        assert res.diagnostics["failure_class"] == DATA_CORRUPTION
    else:
        assert res.ok and res.matches == oracle
        assert res.diagnostics["repaired"] == "full"


def test_verify_config_validation():
    with pytest.raises(ValueError, match="verify"):
        JoinConfig(num_nodes=NODES, verify="paranoid")
    with pytest.raises(ValueError, match="measure_phases"):
        JoinConfig(num_nodes=NODES, verify="check", measure_phases=True)


# ------------------------------------------------------------ satellites

def test_stream_corruption_is_data_corruption_class():
    """Satellite: a sentinel-range key lane under key_range='auto' raises
    the classified DataCorruption (failure_class='data_corruption') instead
    of a bare ValueError or a silent undercount."""
    from tpu_radix_join.ops.chunked import chunked_join_count

    n = 1 << 10
    rk = (np.random.default_rng(5).permutation(n) + 1).astype(np.uint32)
    sk = rk.copy()
    sk[0] = np.uint32(0xFFFFFFFF)          # the STREAM_CORRUPT signature
    r = TupleBatch(key=jnp.asarray(rk), rid=jnp.arange(n, dtype=jnp.uint32))
    s = TupleBatch(key=jnp.asarray(sk), rid=jnp.arange(n, dtype=jnp.uint32))
    with pytest.raises(DataCorruption) as ei:
        chunked_join_count(r, s, 256, key_range="auto")
    assert ei.value.failure_class == DATA_CORRUPTION
    assert isinstance(ei.value, ValueError)   # old except clauses still work


def test_arm_warns_on_near_miss_site_name():
    """Satellite: a typo'd site name is a silent no-op fault plan; arm()
    flags it with a did-you-mean warning against faults.SITES."""
    with FaultInjector() as inj:
        with pytest.warns(RuntimeWarning, match="did you mean"):
            inj.arm("exchange.corrupt_lan", at=1)
        with pytest.warns(RuntimeWarning, match="unknown fault site"):
            inj.arm("completely.bogus", at=1)


def test_print_results_aggregates_fault_sites():
    """Satellite: per-site fired/hit counts surface in the rank-0 report
    next to the FailureClasses line."""
    m = Measurements()
    m.meta["fault_sites"] = {
        faults.EXCHANGE_CORRUPT: {"hits": 3, "fired": 1}}
    buf = io.StringIO()
    print_results([m], file=buf)
    out = buf.getvalue()
    assert "FaultSites" in out
    assert faults.EXCHANGE_CORRUPT in out
    assert "1/3" in out
