"""graftcheck (analysis/jaxpr) tests: the tracer registry, a known-bad
fixture per IR rule (each producing exactly one finding), the waiver and
baseline contracts, the WIREBYTES cross-validation A/B, and the
static-memory planner gate's classified refusal.

Everything here is abstract tracing — no compile, no dispatch — except
the cross-validation test, which runs one real 8-way join to produce
the measured WIREBYTES side of the A/B.
"""

import json

import jax
import jax.numpy as jnp
import pytest

from tpu_radix_join.analysis.core import LintError
from tpu_radix_join.analysis.jaxpr import (AuditContext, AvalView, EqnView,
                                           IR_RULES, ProgramView,
                                           register_ir_rules, run_audit)
from tpu_radix_join.analysis.jaxpr.crossval import (collective_counts,
                                                    static_exchange_bytes,
                                                    static_for_explain)
from tpu_radix_join.analysis.jaxpr.trace import (ENTRY_NAMES, build_entries,
                                                 view_from_fn)

register_ir_rules()

N = 8
BIG = jax.ShapeDtypeStruct((1 << 16,), jnp.uint32)     # 256 KiB


# ------------------------------------------------------------ the registry

def test_registry_traces_every_entry_and_is_clean():
    views = build_entries(num_nodes=N)
    assert [v.name for v in views] == list(ENTRY_NAMES)
    res = run_audit(views)
    assert res.findings == []
    assert res.exit_code() == 0
    assert res.exit_code(strict=True) == 0
    # every entry records its live-set peak for the STATICMEM gauge
    for name in ENTRY_NAMES:
        assert res.stats[name]["peak_live_bytes"] > 0


def test_registry_rejects_unknown_entry_and_rule():
    with pytest.raises(LintError, match="unknown entry"):
        build_entries(num_nodes=N, entries=["nope"])
    with pytest.raises(LintError, match="unknown IR rule"):
        run_audit([], rule_ids=["nope"])


def test_all_five_rules_are_registered():
    assert set(IR_RULES) == {"transfer", "collective-axis", "width",
                             "donation", "static-memory"}


# ------------------------------------- known-bad fixtures, one finding each

def test_transfer_rule_fires_on_implicit_device_put():
    def bad(x):
        return jax.device_put(x).sum()

    v = view_from_fn("fx", bad, (BIG,))
    res = run_audit([v], rule_ids=["transfer"])
    assert len(res.findings) == 1
    f = res.findings[0]
    assert f.rule == "transfer" and "device_put" in f.message
    # attribution points at the staging line in THIS file
    assert f.path.endswith("test_jaxpr_audit.py")


def test_transfer_rule_ignores_scalar_placements():
    def ok(x):
        return x + jax.device_put(jnp.uint32(1))

    v = view_from_fn("fx", ok, (BIG,))
    assert run_audit([v], rule_ids=["transfer"]).findings == []


def test_width_rule_fires_on_silent_widening():
    def bad(x):
        return (x.astype(jnp.float32) * 2.0).sum()

    v = view_from_fn("fx", bad, (BIG,))
    res = run_audit([v], rule_ids=["width"])
    assert len(res.findings) == 1
    assert "float32" in res.findings[0].message


def test_donation_rule_fires_with_concrete_argnums():
    def bad(x):
        return x.sum()

    v = view_from_fn("fx", bad, (BIG,))
    res = run_audit([v], rule_ids=["donation"])
    assert len(res.findings) == 1
    assert "donate_argnums=(0,)" in res.findings[0].message
    # donating silences it
    v2 = view_from_fn("fx", bad, (BIG,), donate_argnums=(0,))
    assert run_audit([v2], rule_ids=["donation"]).findings == []


def test_static_memory_rule_fires_over_budget():
    def bad(x):
        return x.sum()

    v = view_from_fn("fx", bad, (BIG,))
    res = run_audit([v], rule_ids=["static-memory"],
                    ctx=AuditContext(memory_budget_bytes=1024))
    assert len(res.findings) == 1
    assert "exceeds the armed budget" in res.findings[0].message
    # unarmed budget: informational only, peak still recorded
    v2 = view_from_fn("fx", bad, (BIG,))
    res2 = run_audit([v2], rule_ids=["static-memory"])
    assert res2.findings == []
    assert res2.stats["fx"]["peak_live_bytes"] >= BIG.size * 4


def _mis_axised_program():
    """JAX refuses to *stage* a collective over a dead axis, so the
    collective-axis fixture is a hand-built ProgramView — the rule reads
    only the EqnView vocabulary, which is the point of the layer."""
    psum = EqnView(prim="psum",
                   invals=(AvalView((128,), "uint32", 512),),
                   outvals=(AvalView((128,), "uint32", 512),),
                   params={"axes": ("cols",)}, source="fx.py:1 (f)",
                   mesh_axes={"nodes": N}, depth=2)
    return ProgramView(name="fx", eqns=[psum], in_avals=[], out_avals=[],
                       donated=[], mesh_axes={"nodes": N})


def test_collective_axis_rule_fires_on_dead_axis():
    res = run_audit([_mis_axised_program()], rule_ids=["collective-axis"])
    assert len(res.findings) == 1
    assert "'cols'" in res.findings[0].message


def test_collective_axis_rule_fires_on_indivisible_split():
    a2a = EqnView(prim="all_to_all",
                  invals=(AvalView((6, 100), "uint32", 2400),),
                  outvals=(AvalView((6, 100), "uint32", 2400),),
                  params={"axis_name": "nodes", "split_axis": 0,
                          "concat_axis": 0},
                  source="fx.py:2 (f)", mesh_axes={"nodes": N}, depth=2)
    pv = ProgramView(name="fx", eqns=[a2a], in_avals=[], out_avals=[],
                     donated=[], mesh_axes={"nodes": N})
    res = run_audit([pv], rule_ids=["collective-axis"])
    assert len(res.findings) == 1
    assert "not divisible" in res.findings[0].message


# --------------------------------------------------------- waiver + baseline

def test_waiver_suppresses_only_with_reason():
    def bad(x):
        return x.sum()

    waived = view_from_fn("fx", bad, (BIG,),
                          waivers={"donation": "fixture: re-fed upstream"})
    assert run_audit([waived], rule_ids=["donation"]).findings == []
    # a reasonless waiver suppresses nothing (graftlint's contract)
    hollow = view_from_fn("fx", bad, (BIG,), waivers={"donation": "  "})
    assert len(run_audit([hollow], rule_ids=["donation"]).findings) == 1


def test_baseline_suppresses_and_reports_stale(tmp_path):
    def bad(x):
        return x.sum()

    v = view_from_fn("fx", bad, (BIG,))
    live = run_audit([v], rule_ids=["donation"]).findings[0]
    bl = tmp_path / "JXAUDIT_BASELINE.json"
    bl.write_text(json.dumps({"suppressions": [
        {"rule": live.rule, "path": live.path, "key": live.key,
         "reason": "known, tracked"},
        {"rule": "donation", "path": "jaxpr:gone", "key": "gone:in0",
         "reason": "finding was fixed"}]}))
    res = run_audit([v], rule_ids=["donation"], baseline_path=str(bl))
    assert res.findings == [] and len(res.suppressed) == 1
    assert len(res.stale) == 1
    assert res.exit_code() == 0 and res.exit_code(strict=True) == 1
    # a reasonless entry fails loading (exit-2 path at the CLI)
    bl.write_text(json.dumps({"suppressions": [
        {"rule": "donation", "path": "p", "key": "k", "reason": ""}]}))
    with pytest.raises(LintError, match="reason"):
        run_audit([v], rule_ids=["donation"], baseline_path=str(bl))


# ------------------------------------------- engine donation ground truth

def test_engine_probe_entries_are_donated_and_front_half_waived():
    views = {v.name: v for v in build_entries(num_nodes=N)}
    # split probe: the shuffled payloads are donated at the jit site
    assert any(views["probe"].donated)
    assert any(views["bp_build"].donated)
    # front half keeps inputs undonated, with the reason on record
    for name in ("hist", "pipeline", "shuffle"):
        assert not any(views[name].donated)
        assert views[name].waivers.get("donation", "").strip()


# --------------------------------------------------- WIREBYTES A/B (< 10%)

@pytest.mark.slow
def test_static_exchange_bytes_match_measured_wirebytes():
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.performance import Measurements
    from tpu_radix_join.performance.measurements import (WINCAPR, WINCAPS,
                                                         WIREBYTES)

    inner = Relation(N << 10, N, "unique", seed=31)
    outer = Relation(N << 10, N, "unique", seed=32)
    m = Measurements(node_id=0, num_nodes=N)
    eng = HashJoin(JoinConfig(num_nodes=N, network_fanout_bits=5),
                   measurements=m)
    res = eng.join(inner, outer)
    assert res.ok
    measured = m.counters[WIREBYTES]
    cap_r, cap_s = m.counters[WINCAPR], m.counters[WINCAPS]
    assert cap_r == cap_s  # symmetric workload
    # trace the SAME geometry the engine dispatched
    view = build_entries(num_nodes=N, per_node=1 << 10, cap=cap_r,
                         entries=["pipeline"])[0]
    static = static_exchange_bytes(view)
    assert static > 0
    drift = abs(static - measured) / measured
    assert drift < 0.10, (static, measured, drift)
    counts = collective_counts(view)
    assert counts["all_to_all"] >= 2       # keys + rids, both relations


# ----------------------------------------- STATIC-DRIFT + the planner gate

def test_static_for_explain_agrees_with_cost_model():
    from tpu_radix_join.planner import Workload, load_profile
    from tpu_radix_join.planner.cost_model import plan_exchange

    view = build_entries(num_nodes=N, entries=["pipeline"])[0]
    w = Workload(r_tuples=N * 8192, s_tuples=N * 8192,
                 key_bound=N * 8192, num_nodes=N)
    xplan = plan_exchange(load_profile(), w, fanout_bits=5)
    payload = static_for_explain(view, xplan)
    assert payload is not None
    # per-slot basis: pow2 capacity slack cancels, so raw codec-off
    # geometry must agree to well under the 10% A/B bar
    assert abs(payload["drift_pct"]) < 10.0
    assert payload["static_bytes"] > 0


def test_explain_table_grows_static_drift_column():
    from tpu_radix_join.planner import Workload, load_profile, plan_join
    from tpu_radix_join.planner.plan import explain_table

    profile = load_profile()
    w = Workload(r_tuples=N * 4096, s_tuples=N * 4096,
                 key_bound=N * 4096, num_nodes=N)
    plan, costs = plan_join(profile, w)
    payload = {"entry": "pipeline", "static_bytes": 65600,
               "static_bytes_per_tuple": 8.002,
               "plan_bytes_per_tuple": 8.0, "drift_pct": 0.02,
               "collectives": {"all_to_all": 6, "psum": 8}}
    out = explain_table(costs, plan, static=payload)
    assert "STATIC-DRIFT" in out
    assert "+0.02%" in out
    assert "static: jaxpr pipeline" in out
    # without the payload the column stays absent (old renderings stable)
    assert "STATIC-DRIFT" not in explain_table(costs, plan)


def test_planner_static_memory_gate_refuses_classified():
    from tpu_radix_join.planner import (PlanInfeasibleError, Workload,
                                        load_profile, plan_join,
                                        static_memory_gate)
    from tpu_radix_join.robustness.retry import PLAN_INFEASIBLE

    profile = load_profile()
    w = Workload(r_tuples=N * 8192, s_tuples=N * 8192,
                 key_bound=N * 8192, num_nodes=N)
    peak = static_memory_gate(w)        # unarmed budget: returns the peak
    assert peak > 0
    # a budget between the analytic resident set and the traced live-set
    # peak: the cost-model row gate admits, the static gate must refuse
    from tpu_radix_join.planner.cost_model import incore_resident_bytes
    assert incore_resident_bytes(w) < peak
    undersized = Workload(r_tuples=N * 8192, s_tuples=N * 8192,
                          key_bound=N * 8192, num_nodes=N,
                          memory_budget_bytes=int(peak * 0.8))
    with pytest.raises(PlanInfeasibleError) as ei:
        plan_join(profile, undersized, static_gate=True)
    assert ei.value.failure_class == PLAN_INFEASIBLE
    assert "refusing" in str(ei.value) and "at plan time" in str(ei.value)
    # the class is a first-class taxonomy member, not a hand-rolled string
    from tpu_radix_join.analysis.rules_failure import taxonomy
    assert PLAN_INFEASIBLE in taxonomy()
