"""Pallas LSD radix sort (ops/pallas/radix_sort.py) and its wiring
(ops/sorting impl switch, planner sort arm, fallback telemetry).

Parity contract with lax.sort: keys come out non-decreasing and the
(key, *values) row multiset is preserved.  Both engines are *unstable*
as advertised, so equal-key runs may order their value lanes differently
between arms; parity is therefore asserted on canonicalized rows (sorted
lexicographically), not element-by-element.  The radix kernel itself is
additionally STABLE (the partition pass's first-in-input-order contract,
chained across digit passes), which the duplicate-heavy sweep pins
directly — the 64-bit split-lane path depends on it.

Everything runs the interpret kernel on host CPU (tier-1); the shapes
are kept to a handful of (n, shift) combos because each distinct combo
costs a fresh trace of the pass kernel.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import tpu_radix_join.ops.pallas.radix_sort as rsmod
import tpu_radix_join.ops.sorting as sorting
from tpu_radix_join.data.tuples import effective_key_bits
from tpu_radix_join.ops.pallas.radix_sort import (num_radix_passes,
                                                  radix_sort_pallas)
from tpu_radix_join.ops.sorting import (resolve_sort_impl,
                                        segmented_xor_fold,
                                        set_default_sort_impl,
                                        sort_kv_unstable, sort_lex_unstable,
                                        sort_unstable)
from tpu_radix_join.performance.measurements import (SORTFALLBACK, SORTPASS,
                                                     Measurements)

INTERP = "pallas_interpret"
N = 4096          # one shared shape -> the pass kernel traces once per shift


def _u32(a):
    return jnp.asarray(np.asarray(a, dtype=np.uint32))


def _assert_sorted_parity(out, raw):
    """Keys non-decreasing + row multiset preserved (both arms' contract)."""
    got = [np.asarray(o) for o in out]
    assert (np.diff(got[0].astype(np.int64)) >= 0).all()
    perm_in = np.lexsort(tuple(reversed([np.asarray(r) for r in raw])))
    perm_out = np.lexsort(tuple(reversed(got)))
    for r, g in zip(raw, got):
        np.testing.assert_array_equal(np.asarray(r)[perm_in], g[perm_out])


# ------------------------------------------------------------ pass counting

def test_effective_key_bits():
    assert effective_key_bits(None) == 32
    assert effective_key_bits(1 << 16) == 16
    assert effective_key_bits(1 << 16, fanout_bits=5) == 11
    assert effective_key_bits(2) == 1
    assert effective_key_bits(1) == 1          # degenerate: single key value
    assert effective_key_bits(None, key_bits=64) == 64
    assert effective_key_bits(1 << 40, key_bits=64) == 40
    with pytest.raises(ValueError):
        effective_key_bits(0)


def test_num_radix_passes_bound_mapping():
    # the ISSUE's pin: a 16-bit bound buys exactly 2 of the 4 passes back
    assert num_radix_passes(None) == 4
    assert num_radix_passes(1 << 16) == 2
    assert num_radix_passes(1 << 8) == 1
    assert num_radix_passes(257) == 2
    assert num_radix_passes(None, key_bits=64) == 8


# --------------------------------------------------------------- the kernel

@pytest.mark.parametrize("case", ["random", "sentinel_saturated",
                                  "duplicate_heavy", "presorted",
                                  "reverse_sorted"])
@pytest.mark.parametrize("value_lanes", [0, 1, 2])
def test_sweep_parity_with_lax_sort(case, value_lanes):
    rng = np.random.default_rng(hash(case) % (1 << 16))
    keys = {
        "random": rng.integers(0, 1 << 32, N, dtype=np.uint32),
        # every uint32 is a valid key — the pad discipline is positional,
        # so even an input saturated with would-be sentinels must survive
        "sentinel_saturated": rng.choice(
            np.array([0, 1, 0xFFFFFFFE, 0xFFFFFFFF], np.uint32), N),
        "duplicate_heavy": (rng.integers(0, 1 << 32, N) % 7
                            ).astype(np.uint32),
        "presorted": np.sort(rng.integers(0, 1 << 32, N, dtype=np.uint32)),
        "reverse_sorted": np.sort(
            rng.integers(0, 1 << 32, N, dtype=np.uint32))[::-1].copy(),
    }[case]
    vals = [np.arange(N, dtype=np.uint32),
            rng.integers(0, 1 << 32, N, dtype=np.uint32)]
    raw = [keys] + vals[:value_lanes]
    out = radix_sort_pallas(tuple(_u32(a) for a in raw), num_keys=1,
                            interpret=True)
    _assert_sorted_parity(out, raw)
    if value_lanes >= 1:
        # stability: first value lane is input position — within an
        # equal-key run it must come out strictly increasing
        k, v = np.asarray(out[0]), np.asarray(out[1])
        run_starts = np.flatnonzero(np.diff(k) == 0)
        assert (v[run_starts + 1] > v[run_starts]).all()


def test_64bit_split_lane_lex_sort_matches_numpy():
    rng = np.random.default_rng(9)
    hi = rng.integers(0, 1 << 8, N, dtype=np.uint32)
    lo = rng.integers(0, 1 << 32, N, dtype=np.uint32)
    rid = np.arange(N, dtype=np.uint32)
    out = radix_sort_pallas((_u32(hi), _u32(lo), _u32(rid)), num_keys=2,
                            key_bounds=(1 << 8, None), interpret=True)
    order = np.lexsort((rid, lo, hi))    # stable -> unique expected order
    np.testing.assert_array_equal(np.asarray(out[0]), hi[order])
    np.testing.assert_array_equal(np.asarray(out[1]), lo[order])
    np.testing.assert_array_equal(np.asarray(out[2]), rid[order])


def test_bounded_keys_skip_passes(monkeypatch):
    rng = np.random.default_rng(11)
    keys = rng.integers(0, 1 << 16, N, dtype=np.uint32)
    rid = np.arange(N, dtype=np.uint32)
    calls = []
    real = rsmod.radix_pass_slots_pallas

    def counting(k, *, shift, interpret=False):
        calls.append(shift)
        return real(k, shift=shift, interpret=interpret)

    monkeypatch.setattr(rsmod, "radix_pass_slots_pallas", counting)
    out = radix_sort_pallas((_u32(keys), _u32(rid)), num_keys=1,
                            key_bounds=(1 << 16,), interpret=True)
    assert calls == [0, 8]               # 2 passes, not 4
    _assert_sorted_parity(out, [keys, rid])
    calls.clear()
    radix_sort_pallas((_u32(keys), _u32(rid)), num_keys=1, interpret=True)
    assert calls == [0, 8, 16, 24]       # unbounded worst case


def test_all_sentinel_keys_with_padding_lose_nothing():
    # n not a multiple of the tile width forces pad rows; every key is
    # 0xFFFFFFFF (= the dropped-slot marker's neighborhood), so only the
    # positional pad rule keeps real rows apart from padding
    n = N - 3
    keys = np.full(n, 0xFFFFFFFF, np.uint32)
    rid = np.arange(n, dtype=np.uint32)
    out = radix_sort_pallas((_u32(keys), _u32(rid)), num_keys=1,
                            interpret=True)
    np.testing.assert_array_equal(np.asarray(out[0]), keys)
    np.testing.assert_array_equal(np.asarray(out[1]), rid)  # stable identity


def test_tiny_and_empty_inputs():
    out = radix_sort_pallas((_u32([5]), _u32([7])), num_keys=1,
                            interpret=True)
    assert np.asarray(out[0]).tolist() == [5]
    out = radix_sort_pallas((_u32([]), _u32([])), num_keys=1, interpret=True)
    assert np.asarray(out[0]).size == 0


# ------------------------------------------------------- the sorting switch

def test_switch_wrappers_route_and_match(monkeypatch):
    rng = np.random.default_rng(21)
    keys = rng.integers(0, 1 << 32, N, dtype=np.uint32)
    rid = np.arange(N, dtype=np.uint32)
    for impl in ("xla", INTERP):
        _assert_sorted_parity([sort_unstable(_u32(keys), impl=impl)], [keys])
        _assert_sorted_parity(
            sort_kv_unstable(_u32(keys), _u32(rid), impl=impl), [keys, rid])
        _assert_sorted_parity(
            sort_lex_unstable(_u32(keys % 7), _u32(rid), num_keys=1,
                              impl=impl), [keys % 7, rid])


def test_batched_sort_quietly_ineligible_even_when_forced(capsys):
    # 2-D sorts are outside the kernel's shapes: a forced impl routes to
    # lax.sort with no fallback noise (forcing selects the impl for the
    # sorts the kernel can express, it does not redefine what it expresses)
    x = jnp.asarray(np.random.default_rng(3).integers(
        0, 99, (4, 64), dtype=np.uint32))
    out = np.asarray(sort_unstable(x, impl=INTERP))
    np.testing.assert_array_equal(out, np.sort(np.asarray(x), axis=-1))
    assert "fell back" not in capsys.readouterr().err


def test_xor_fold_exact_under_forced_radix_arm():
    rng = np.random.default_rng(5)
    seg = rng.integers(0, 16, N, dtype=np.uint32)
    vals = rng.integers(0, 1 << 32, N, dtype=np.uint32)
    expect = np.zeros(16, np.uint32)
    for q in range(16):
        expect[q] = np.bitwise_xor.reduce(vals[seg == q]) \
            if (seg == q).any() else 0
    set_default_sort_impl(INTERP)
    try:
        got = np.asarray(segmented_xor_fold(_u32(seg), _u32(vals), 16))
    finally:
        set_default_sort_impl("auto")
    np.testing.assert_array_equal(got, expect)


# ------------------------------------------------------- fallback telemetry

def test_auto_fallback_ticks_counter_once_and_logs_once(monkeypatch, capsys):
    m = Measurements()
    sorting.install_sort_observer(m)
    monkeypatch.setattr(sorting, "_fallback_logged", False)
    monkeypatch.setattr(sorting, "_fallback_ticked", False)
    try:
        # structural ineligibility is quiet even under auto
        assert resolve_sort_impl("auto", 1 << 20, "t", eligible=False) \
            == "xla"
        assert m.counters[SORTFALLBACK] == 0
        # CPU backend: auto degrades loudly — but the counter ticks ONCE
        # per process, not once per sort site (the acceptance pin)
        assert resolve_sort_impl("auto", 1 << 20, "site_a") == "xla"
        assert resolve_sort_impl(None, 1 << 20, "site_b") == "xla"
        err = capsys.readouterr().err
        assert err.count("fell back to lax.sort") == 1
        assert m.counters[SORTFALLBACK] == 1
        # explicit impls never tick the fallback
        assert resolve_sort_impl("xla", 1 << 20, "t") == "xla"
        assert resolve_sort_impl(INTERP, 1 << 20, "t") == INTERP
        assert m.counters[SORTFALLBACK] == 1
    finally:
        sorting.install_sort_observer(None)


def test_pallas_path_ticks_sortpass_span():
    m = Measurements()
    sorting.install_sort_observer(m)
    try:
        keys = _u32(np.arange(N)[::-1].copy())
        sort_kv_unstable(keys, _u32(np.arange(N)), impl=INTERP)
        assert m.counters[SORTPASS] == 1
        spans = [r for r in m.flightrec.records()
                 if r["name"] == "radix_sort" and r["kind"] == "span"]
        assert spans and spans[0]["impl"] == INTERP
        assert spans[0]["site"] == "sort_kv_unstable"
    finally:
        sorting.install_sort_observer(None)


# ------------------------------------------------------------- planner

def test_plan_sort_prices_both_arms():
    from tpu_radix_join.planner.cost_model import plan_sort
    from tpu_radix_join.planner.profile import load_profile
    prof = load_profile()
    on = plan_sort(prof, 1 << 25, pallas_ok=True)
    off = plan_sort(prof, 1 << 25, pallas_ok=False)
    assert off.impl == "xla" and on.pallas_ms == off.pallas_ms
    assert on.sort_ms == min(on.pallas_ms, on.xla_ms)
    # a bound halves the radix arm's passes and its price with them
    bounded = plan_sort(prof, 1 << 25, key_bound=1 << 16, pallas_ok=True)
    assert bounded.passes == 2 < on.passes == 4
    assert bounded.pallas_ms < on.pallas_ms
    # the radix arm prices off the schema-v5 constant
    bumped = prof.replace_constants(radix_sort_pass_unit_ms={
        "value": prof.value("radix_sort_pass_unit_ms") * 10,
        "source": "test"})
    assert plan_sort(bumped, 1 << 25, pallas_ok=True).pallas_ms \
        > on.pallas_ms
    # below the runtime's size floor and on batched rows the plan stays
    # xla, matching what trace-time selection would actually do
    assert plan_sort(prof, 1 << 10, pallas_ok=True).impl == "xla"
    assert plan_sort(prof, 1 << 25, rows=32, pallas_ok=True).impl == "xla"


def test_strategy_rows_carry_the_sort_arm():
    from tpu_radix_join.planner.cost_model import (Workload,
                                                   enumerate_strategies)
    from tpu_radix_join.planner.profile import load_profile
    rows = enumerate_strategies(load_profile(),
                                Workload(r_tuples=1 << 22, s_tuples=1 << 22,
                                         key_bound=1 << 20, num_nodes=8))
    fused = next(r for r in rows if r.strategy == "incore_fused_sort_narrow")
    assert fused.terms["sort"] > 0
    assert "sort arm:" in fused.note


def test_plan_binds_sort_impl_and_v4_plans_still_load():
    from tpu_radix_join.planner.cost_model import Workload
    from tpu_radix_join.planner.plan import (PLAN_SCHEMA_VERSION, JoinPlan,
                                             plan_join)
    from tpu_radix_join.planner.profile import load_profile
    plan, _ = plan_join(load_profile(),
                        Workload(r_tuples=1 << 22, s_tuples=1 << 22,
                                 num_nodes=8))
    assert PLAN_SCHEMA_VERSION == 5
    assert plan.sort_impl in ("pallas", "xla")
    assert plan.config_kwargs()["sort_impl"] == plan.sort_impl
    doc = plan.to_dict()
    doc.pop("sort_impl")
    doc["schema_version"] = 4
    assert JoinPlan.from_dict(doc).sort_impl == "auto"


def test_profile_v4_shims_the_sort_unit():
    from tpu_radix_join.planner.profile import load_profile
    prof = load_profile()
    doc = {"name": "old", "schema_version": 4,
           "constants": {k: dict(v) for k, v in prof.constants.items()}}
    doc["constants"].pop("radix_sort_pass_unit_ms")
    import json
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump(doc, f)
        path = f.name
    shimmed = load_profile(path)
    assert shimmed.value("radix_sort_pass_unit_ms") == pytest.approx(
        12.0 / prof.value("hbm_gbps"), rel=1e-3)
    assert "shim" in shimmed.constants["radix_sort_pass_unit_ms"]["source"]


def test_calibrate_inverts_sort_bench_rows():
    from tpu_radix_join.planner.calibrate import collect_samples
    rows = [{"kind": "bench", "run_id": "r1",
             "metric": "radix_sort_speedup", "size": 1 << 20,
             "sort_passes": 4, "sort_kernel_ms": 2.0},
            {"kind": "bench", "run_id": "r2",
             "metric": "radix_sort_speedup", "size": 1 << 19,
             "sort_passes": 2, "sort_kernel_ms": 0.5}]
    got = collect_samples(rows)["radix_sort_pass_unit_ms"]
    assert got[0].value == pytest.approx(2.0 / (4 * (1 << 20) / 1e6))
    assert got[1].value == pytest.approx(0.5 / (2 * (1 << 19) / 1e6))


# -------------------------------------------------------- engine wiring

def _oracle_join(**cfg_kw):
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.performance import Measurements

    n = 8
    inner = Relation(n << 10, n, "unique", seed=31)
    outer = Relation(n << 10, n, "unique", seed=32)
    m = Measurements(node_id=0, num_nodes=n)
    eng = HashJoin(JoinConfig(num_nodes=n, verify="check", **cfg_kw),
                   measurements=m)
    res = eng.join(inner, outer)
    assert res.ok and res.matches == inner.expected_matches(outer)
    return m


def test_join_forced_radix_sort_oracle_exact():
    try:
        m = _oracle_join(sort_impl=INTERP)
    finally:
        # the engine binds its impl process-wide; don't leak the forced
        # interpret arm (or the join's observer) into later test files
        set_default_sort_impl("auto")
        sorting.install_sort_observer(None)
    assert m.counters[SORTPASS] > 0
    spans = [r for r in m.flightrec.records()
             if r["name"] == "radix_sort" and r["kind"] == "span"]
    assert spans and all(s["impl"] == INTERP for s in spans)


def test_config_rejects_unknown_sort_impl():
    from tpu_radix_join import JoinConfig
    with pytest.raises(ValueError, match="sort impl"):
        JoinConfig(sort_impl="bogus")
