"""Hot-partition skew splitting (operators/skew.py): the probe-level split
the reference keeps in its dormant SD::OPT machinery
(kernels_optimized.cu:301-344,864-943).  Assignment-level balancing cannot
spread a single dominant partition; these tests pin the split behavior —
inner replicated, outer sharded, exact counts, per-device balance."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.operators import skew


def _batch(keys, hi=None):
    keys = np.asarray(keys, np.uint32)
    return TupleBatch(
        key=jnp.asarray(keys),
        rid=jnp.arange(keys.shape[0], dtype=jnp.uint32),
        key_hi=None if hi is None else jnp.asarray(
            np.broadcast_to(np.uint32(hi), keys.shape)))


def test_detection_helpers():
    r = np.full(32, 100, np.uint64)
    s = np.full(32, 100, np.uint64)
    s[3] = 10000
    hot = skew.detect_hot_partitions(r, s, 4.0)
    assert hot[3] and hot.sum() == 1
    bits = skew.hot_mask_bits(hot)
    assert bits == 1 << 3
    got = np.asarray(skew.is_hot(jnp.arange(32, dtype=jnp.uint32), bits))
    np.testing.assert_array_equal(got, hot)
    np.testing.assert_array_equal(
        np.asarray(skew.mask_hot(jnp.asarray(s.astype(np.uint32)), bits)),
        np.where(hot, 0, s).astype(np.uint32))


def _hot_workload(size):
    """R: dense unique keys.  S: half the relation is ONE key (partition 3
    under fanout 5), half dense unique — every S tuple matches exactly once,
    so matches == size and partition 3 is catastrophically hot."""
    half = size // 2
    rk = np.arange(size, dtype=np.uint32)
    sk = np.concatenate([np.full(half, 3, np.uint32),
                         np.arange(half, dtype=np.uint32)])
    return _batch(rk), _batch(sk)


def test_hot_key_split_balances_devices():
    # VERDICT r1 item 3's acceptance test: one key is 50% of S; the split
    # must spread its matches across the 8-device mesh with a balance bound,
    # where the unsplit pipeline piles them on one device.
    n, size = 8, 1 << 15
    r, s = _hot_workload(size)
    cfg = JoinConfig(num_nodes=n, skew_threshold=4.0, max_retries=1)
    res = HashJoin(cfg).join_arrays(r, s)
    assert res.ok, res.diagnostics
    assert res.matches == size
    pc = res.partition_counts.reshape(n, 32)
    hot = pc[:, 3].astype(np.int64)
    assert hot.sum() == (size // 2) + (size // 2) // 32
    # rid round-robin spread: every device probes a near-equal hot shard
    assert hot.min() > 0
    assert hot.max() <= 1.5 * hot.mean()

    # contrast: without splitting the whole hot partition sits on one device
    res0 = HashJoin(cfg.replace(skew_threshold=None)).join_arrays(r, s)
    assert res0.ok and res0.matches == size
    pc0 = res0.partition_counts.reshape(n, 32)
    assert (pc0[:, 3] > 0).sum() == 1


def test_hot_split_with_debug_checks():
    # the strong per-partition conservation form must hold under the split
    # routing (hot rows excluded from the per-device expectation)
    n, size = 8, 1 << 14
    r, s = _hot_workload(size)
    cfg = JoinConfig(num_nodes=n, skew_threshold=4.0, debug_checks=True)
    res = HashJoin(cfg).join_arrays(r, s)
    assert res.ok, res.diagnostics
    assert res.matches == size


def test_hot_split_wide_keys():
    # 64-bit keys ride hi/lo lanes through the same split route
    n, size = 4, 1 << 13
    half = size // 2
    rk = np.arange(size, dtype=np.uint32)
    sk = np.concatenate([np.full(half, 3, np.uint32),
                         np.arange(half, dtype=np.uint32)])
    cfg = JoinConfig(num_nodes=n, key_bits=64, skew_threshold=4.0)
    res = HashJoin(cfg).join_arrays(_batch(rk, hi=7), _batch(sk, hi=7))
    assert res.ok, res.diagnostics
    assert res.matches == size
    pc = res.partition_counts.reshape(n, 32)
    assert (pc[:, 3] > 0).all()       # hot work on every device


def test_hot_split_congruent_rids_still_balance():
    """Adversarial rid pattern (VERDICT r2 next #6): every hot-S tuple's rid
    is ≡ 0 (mod n).  Raw ``rid % n`` would pile the whole hot partition back
    on device 0; the hashed spread must keep the same balance bound the dense
    -rid test uses."""
    n, size = 8, 1 << 15
    rk = np.arange(size, dtype=np.uint32)
    # hot key 3 occupies every n-th slot -> hot rids are 0, n, 2n, ...
    sk = np.arange(size, dtype=np.uint32)
    sk[::n] = 3
    r, s = _batch(rk), _batch(sk)
    # hot key is 1/n of S (s[3] ~ 2.5x the mean partition weight)
    cfg = JoinConfig(num_nodes=n, skew_threshold=2.0, max_retries=1)
    res = HashJoin(cfg).join_arrays(r, s)
    assert res.ok, res.diagnostics
    # every S slot holds some key < size, and R is dense unique over [0, size)
    # -> every S tuple matches exactly once
    assert res.matches == size
    pc = res.partition_counts.reshape(n, 32)
    hot = pc[:, 3].astype(np.int64)
    assert hot.min() > 0
    assert hot.max() <= 1.5 * hot.mean()


def test_build_hot_partition_not_split():
    """A partition hot purely on the BUILD side must not be split: replicating
    the largest R slice n-fold is worse than single ownership (ADVICE r2)."""
    r = np.full(32, 100, np.uint64)
    s = np.full(32, 100, np.uint64)
    r[5] = 50000
    hot = skew.detect_hot_partitions(r, s, 4.0)
    assert not hot.any()
    # but the same weight on the probe side does split
    hot2 = skew.detect_hot_partitions(s, r, 4.0)
    assert hot2[5] and hot2.sum() == 1


def test_tiny_build_side_does_not_veto_split():
    """An absolutely tiny but relatively elevated R must not veto spreading
    a massively probe-hot partition: with num_nodes given, affordability is
    also judged by replication cost vs probe work (n*R <= S)."""
    r = np.full(32, 20, np.uint64)
    r[5] = 100               # ~4.4x the R mean, but only 100 tuples
    s = np.full(32, 100, np.uint64)
    s[5] = 1_000_000
    # without the absolute clause the relative R guard vetoes
    assert not skew.detect_hot_partitions(r, s, 4.0).any()
    hot = skew.detect_hot_partitions(r, s, 4.0, num_nodes=8)
    assert hot[5] and hot.sum() == 1
    # a genuinely build-heavy partition still stays single-owner
    r2 = np.full(32, 20, np.uint64)
    r2[5] = 1_000_000
    s2 = np.full(32, 100, np.uint64)
    s2[5] = 1_000_000
    assert not skew.detect_hot_partitions(r2, s2, 4.0, num_nodes=8).any()


def test_hot_split_on_hierarchical_mesh():
    """The split routing (replicate / hashed spread) composes with the
    two-stage (dcn, ici) exchange: exact counts and clean diagnostics on a
    2-host x 4-device mesh."""
    n, size = 8, 1 << 14
    r, s = _hot_workload(size)
    cfg = JoinConfig(num_nodes=n, num_hosts=2, skew_threshold=4.0,
                     max_retries=1)
    res = HashJoin(cfg).join_arrays(r, s)
    assert res.ok, res.diagnostics
    assert res.matches == size
    pc = res.partition_counts.reshape(n, 32)
    assert (pc[:, 3] > 0).all()       # hot work on every device


def test_zipf_skew_split_end_to_end():
    n, size = 8, 1 << 14
    cfg = JoinConfig(num_nodes=n, skew_threshold=3.0,
                     assignment_policy="load_aware")
    hj = HashJoin(cfg)
    r = hj._place(Relation(size, n, "unique", seed=1))
    s = hj._place(Relation(size, n, "zipf", zipf_theta=1.1,
                           key_domain=size, seed=3))
    _, _, plan = hj._measure_capacities(r, s)
    assert plan is not None and plan[0] != 0   # detection actually fired
    res = hj.join_arrays(r, s)
    assert res.ok, res.diagnostics
    assert res.matches == size


def test_config_rejects_unsupported_skew_combos():
    with pytest.raises(ValueError):
        JoinConfig(skew_threshold=2.0, chunk_size=256)
    with pytest.raises(ValueError):
        JoinConfig(skew_threshold=2.0, network_fanout_bits=6)
    with pytest.raises(ValueError):
        JoinConfig(skew_threshold=2.0, window_sizing="static")


@pytest.mark.parametrize("phases", [False, True])
def test_skew_split_on_two_level_path(phases):
    """The split composes with the two-level/bucket discipline (VERDICT r3
    missing #4 — the reference's own skew locus is its PARTITIONED probe
    kernels, kernels_optimized.cu:301-943): the replicated hot build side
    rides the local radix pass, hot S spreads by rid, and the per-bucket
    probe counts exactly — fused and phase-split (SLOCPREP/JPROC) alike,
    agreeing with the flat sort-probe pipeline."""
    n, size = 8, 1 << 14
    r, s = _hot_workload(size)
    cfg = JoinConfig(num_nodes=n, two_level=True, local_fanout_bits=3,
                     skew_threshold=4.0, allocation_factor=4.0,
                     max_retries=3, measure_phases=phases)
    hj = HashJoin(cfg)
    _, _, plan = hj._measure_capacities(r, s)
    assert plan is not None and plan[0] != 0   # detection actually fired
    res = hj.join_arrays(r, s)
    assert res.ok, res.diagnostics
    assert res.matches == size
    flat = HashJoin(JoinConfig(num_nodes=n, skew_threshold=4.0,
                               max_retries=3)).join_arrays(r, s)
    assert flat.ok and flat.matches == res.matches


def test_materialize_with_skew_split():
    """join_materialize under the hot-partition split emits exactly the
    pairs the unsplit pipeline does (the probe_match_rate arm of the skew
    machinery, kernels_optimized.cu:689-787)."""
    n, size = 8, 1 << 13
    r, s = _hot_workload(size)
    base = dict(num_nodes=n, match_rate_cap=4, max_retries=1)
    split = HashJoin(JoinConfig(**base, skew_threshold=4.0)
                     ).join_materialize_arrays(r, s)
    plain = HashJoin(JoinConfig(**base)).join_materialize_arrays(r, s)
    assert split.ok, split.diagnostics
    assert plain.ok and split.matches == plain.matches == size
    want = set(zip(plain.r_rid.tolist(), plain.s_rid.tolist()))
    got = set(zip(split.r_rid.tolist(), split.s_rid.tolist()))
    assert got == want
