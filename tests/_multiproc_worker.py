"""Subprocess worker for the real multi-process plumbing test
(test_multihost.py::test_two_process_plumbing): one rank of an N-process CPU
world — 4 virtual devices per process, ``jax.distributed`` over a localhost
coordinator (the ``mpirun`` analog, main.cpp:36-48), hierarchical
(dcn=N, ici=4) mesh join, and the rank-0 measurement gather
(Measurements.cpp:548-590).  Not a pytest module (no ``test_`` prefix)."""

import sys


def main(port: str, rank: str, nproc: str) -> None:
    # must precede any JAX backend use (tests/_multiproc_worker is launched
    # with a clean env; sitecustomize still pre-imports jax), and must NOT
    # itself touch jax.devices() — distributed.initialize comes first
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(4, defer_check=True)

    import jax
    from tpu_radix_join.parallel.multihost import initialize, process_info

    nproc = int(nproc)
    assert initialize(coordinator_address=f"127.0.0.1:{port}",
                      num_processes=nproc, process_id=int(rank))
    pid, pcount = process_info()
    assert pcount == nproc, (pid, pcount)
    assert jax.local_device_count() == 4
    assert jax.device_count() == 4 * nproc

    from tpu_radix_join import HashJoin, JoinConfig, Relation
    from tpu_radix_join.performance import Measurements, print_results

    n = jax.device_count()
    # measure_phases: the shuffle (JMPI, with cross-process collectives) and
    # the probe run as separate programs even in a real multi-process world
    cfg = JoinConfig(num_nodes=n, num_hosts=nproc, measure_phases=True)
    size = 1 << 12
    r = Relation(size, n, "unique", seed=1)
    s = Relation(size, n, "unique", seed=9)
    m = Measurements(node_id=pid, num_nodes=nproc)
    res = HashJoin(cfg, measurements=m).join(r, s)
    assert res.ok, res.diagnostics
    assert res.matches == size, res.matches
    assert m.times_us.get("JMPI", 0) > 0 and m.times_us.get("JPROC", 0) > 0

    # materializing pipeline across processes: exercises the single-
    # collective stacked result gather (hash_join.join_materialize_arrays)
    mat = HashJoin(JoinConfig(num_nodes=n, num_hosts=nproc,
                              match_rate_cap=4)).join_materialize(r, s)
    assert mat.ok, mat.diagnostics
    assert mat.matches == size, mat.matches

    # full-range auto routing across processes: the device max-key probe's
    # readback must ride the multi-host gather (_to_host), and the 2-key
    # lexicographic count must stay exact through the cross-process shuffle
    import jax.numpy as jnp
    import numpy as np
    from tpu_radix_join.data.tuples import TupleBatch
    big = ((1 << 31) + 11 * np.arange(size, dtype=np.uint64)).astype(np.uint32)
    shuffled = np.random.default_rng(0).permutation(big)
    shuffled[: size // 4] = 5
    fr = HashJoin(JoinConfig(num_nodes=n, num_hosts=nproc)).join_arrays(
        TupleBatch(key=jnp.asarray(big),
                   rid=jnp.arange(size, dtype=jnp.uint32)),
        TupleBatch(key=jnp.asarray(shuffled),
                   rid=jnp.arange(size, dtype=jnp.uint32)))
    assert fr.ok, fr.diagnostics
    assert fr.matches == size - size // 4, fr.matches

    all_m = m.gather_all()
    assert len(all_m) == nproc, len(all_m)
    assert sorted(mm.node_id for mm in all_m) == list(range(nproc))
    if pid == 0:
        assert all(mm.times_us.get("JTOTAL", 0) > 0 for mm in all_m)
        print_results(all_m)
        print(f"MULTIPROC_OK matches={res.matches} ranks={len(all_m)}")
    print(f"RANK_DONE {pid}")


if __name__ == "__main__":
    main(*sys.argv[1:4])
