"""Serving fast-path tests: content-fingerprint result cache, the
micro-batch coalescer and its fused device program, the resident
delta-merge tier with the unchanged-outer incremental probe, request
canonicalization properties, and the serving-tier cost-model rows.

The engine-integrated cases ride the conftest 8-device virtual CPU mesh
like tests/test_serve.py; the unit cases (cache, coalescer, resident
manager, merge ops) run device-light against numpy oracles.
"""

import numpy as np
import pytest

from tpu_radix_join.core.config import JoinConfig, ServiceConfig
from tpu_radix_join.performance.measurements import (DELTAMERGE, RCHIT,
                                                     RCMISS, RESBYTES,
                                                     Measurements)
from tpu_radix_join.robustness import faults
from tpu_radix_join.service import JoinSession, QueryRequest
from tpu_radix_join.service.journal import request_fingerprint
from tpu_radix_join.service.microbatch import MicroBatcher, batch_signature
from tpu_radix_join.service.resident import ResidentStateManager
from tpu_radix_join.service.resultcache import ResultCache, content_fingerprint

NODES = 8
TPN = 1 << 8


def _req(qid, **kw):
    kw.setdefault("tuples_per_node", TPN)
    kw.setdefault("seed", 7)
    return QueryRequest(query_id=qid, **kw)


# -------------------------------------------------- fingerprint properties

def test_request_fingerprint_key_order_and_float_folding():
    a = {"query_id": "q", "tuples_per_node": 1024, "seed": 2}
    b = {"seed": 2.0, "query_id": "q", "tuples_per_node": 1024.0}
    assert request_fingerprint(a) == request_fingerprint(b)


def test_request_fingerprint_drops_nonsemantic_envelope():
    base = {"query_id": "q", "tuples_per_node": 1024}
    assert (request_fingerprint(base)
            == request_fingerprint({**base, "deadline_s": 5.0}))
    # query_id IS semantic for the submission fingerprint
    assert (request_fingerprint(base)
            != request_fingerprint({**base, "query_id": "other"}))


def test_request_fingerprint_bool_is_not_int():
    # bool is an int subclass; canonicalization must keep them distinct
    a = {"query_id": "q", "flag": True}
    b = {"query_id": "q", "flag": 1}
    assert request_fingerprint(a) != request_fingerprint(b)


def test_content_fingerprint_ignores_submission_envelope():
    r1 = _req("q1", tenant="a", deadline_s=1.0)
    r2 = _req("q2", tenant="b", deadline_s=9.0)
    assert content_fingerprint(r1) == content_fingerprint(r2)
    assert content_fingerprint(r1) != content_fingerprint(
        _req("q1", seed=8))


def test_content_fingerprint_epoch_and_config_are_identity():
    r = _req("q")
    assert (content_fingerprint(r, epoch=1)
            != content_fingerprint(r, epoch=2))
    assert (content_fingerprint(r, config_fp={"nodes": 8})
            != content_fingerprint(r, config_fp={"nodes": 4}))


# ------------------------------------------------------- result cache unit

def _payload(matches=100):
    return {"matches": matches, "expected": matches, "engine": "primary"}


def test_result_cache_hit_miss_and_lru():
    cache = ResultCache(2)
    assert cache.get("a") is None               # cold miss
    cache.put("a", _payload(1))
    cache.put("b", _payload(2))
    assert cache.get("a")["matches"] == 1
    cache.put("c", _payload(3))                 # evicts b (a was touched)
    assert cache.get("b") is None
    assert cache.get("a") is not None and cache.get("c") is not None
    stats = cache.stats()
    assert stats["entries"] == 2 and stats["hits"] == 3


def test_result_cache_ttl_expiry_fake_clock():
    now = [0.0]
    cache = ResultCache(4, ttl_s=10.0, clock=lambda: now[0])
    cache.put("a", _payload())
    now[0] = 9.0
    assert cache.get("a") is not None
    now[0] = 20.1
    assert cache.get("a") is None
    assert cache.expired == 1


def test_result_cache_epoch_mismatch_drops():
    cache = ResultCache(4)
    cache.put("a", _payload(), epoch=1)
    assert cache.get("a", epoch=2) is None      # dropped, not served
    assert cache.dropped_stale == 1
    assert cache.get("a", epoch=1) is None      # really gone


def test_result_cache_poison_digest_drop():
    m = Measurements()
    cache = ResultCache(4, measurements=m)
    cache.put("a", _payload(42))
    with faults.FaultInjector(seed=1, measurements=m).arm(
            faults.CACHE_POISON, at=1):
        assert cache.get("a") is None           # corrupted -> miss
    assert cache.dropped_stale == 1
    assert int(m.counters.get(RCMISS, 0)) == 1
    assert int(m.counters.get(RCHIT, 0)) == 0


def test_result_cache_disabled_posture():
    cache = ResultCache(0)
    cache.put("a", _payload())
    assert cache.get("a") is None
    assert cache.hits == cache.misses == 0      # disabled gets don't count


# --------------------------------------------------------- merge ops units

def test_merge_sorted_matches_numpy_with_duplicates():
    from tpu_radix_join.ops.merge_delta import merge_sorted
    import jax.numpy as jnp
    rng = np.random.default_rng(3)
    for n, d in [(0, 5), (5, 0), (1, 1), (1000, 37), (512, 512)]:
        a = np.sort(rng.integers(0, 300, n).astype(np.uint32))
        b = np.sort(rng.integers(0, 300, d).astype(np.uint32))
        got = np.asarray(merge_sorted(jnp.asarray(a), jnp.asarray(b)))
        assert np.array_equal(got, np.sort(np.concatenate([a, b])))


def test_delta_merge_count_and_increment_agree():
    from tpu_radix_join.ops.merge_delta import (delta_merge_count,
                                                delta_merge_increment)
    import jax.numpy as jnp
    rng = np.random.default_rng(5)
    base = rng.permutation(np.arange(4096, dtype=np.uint32))
    delta = np.arange(4096, 4096 + 256, dtype=np.uint32)
    s = rng.integers(0, 5000, 2048).astype(np.uint32)
    lane = jnp.asarray(np.sort(base))
    union, total = delta_merge_count(lane, jnp.asarray(delta),
                                     jnp.asarray(s))
    want = int(np.isin(s, np.concatenate([base, delta])).sum())
    assert int(total) == want
    assert np.array_equal(np.asarray(union),
                          np.sort(np.concatenate([base, delta])))
    # additive counting: prior total + increment == the full recount
    prior = int(np.isin(s, base).sum())
    union2, inc = delta_merge_increment(lane, jnp.asarray(delta),
                                        jnp.asarray(np.sort(s)))
    assert prior + int(inc) == want
    assert np.array_equal(np.asarray(union2), np.asarray(union))


def test_batched_merge_count_matches_per_query():
    from tpu_radix_join.ops.merge_delta import batched_merge_count
    import jax.numpy as jnp
    rng = np.random.default_rng(9)
    key_bound = 1 << 10
    r_parts = [rng.integers(0, key_bound, n).astype(np.uint32)
               for n in (128, 256, 64)]
    s_parts = [rng.integers(0, key_bound, n).astype(np.uint32)
               for n in (200, 100, 300)]
    counts = batched_merge_count(
        jnp.asarray(np.concatenate(r_parts)),
        jnp.asarray(np.concatenate(s_parts)),
        tuple(len(p) for p in r_parts), tuple(len(p) for p in s_parts),
        key_bound)
    for i, (r, s) in enumerate(zip(r_parts, s_parts)):
        want = sum(int((r == k).sum()) for k in s)
        assert int(counts[i]) == want, f"query {i}"


def test_batch_feasible_bounds():
    from tpu_radix_join.ops.merge_delta import (MAX_SERVE_KEY,
                                                batch_feasible,
                                                composite_shift)
    assert batch_feasible(8, 1 << 20)
    assert not batch_feasible(2, MAX_SERVE_KEY)      # shift >= 32
    assert not batch_feasible(1 << 12, 1 << 20)      # tag overflows
    with pytest.raises(ValueError):
        composite_shift(0)


# -------------------------------------------------- resident state manager

class _Lane:
    def __init__(self, nbytes):
        self.nbytes = nbytes


def test_resident_budget_eviction_lru_and_gauge():
    m = Measurements()
    res = ResidentStateManager(100, measurements=m)
    assert res.put("a", _Lane(40))
    assert res.put("b", _Lane(40))
    assert res.get("a") is not None              # a becomes MRU
    assert res.put("c", _Lane(40))               # evicts b
    assert res.get("b") is None and res.get("a") is not None
    assert res.evicted == 1 and res.resident_bytes == 80
    assert int(m.counters.get(RESBYTES, 0)) == 80   # high-water held
    assert not res.put("huge", _Lane(1000))      # larger than the budget
    assert res.rejected == 1


def test_resident_epoch_mismatch_drops_lane():
    res = ResidentStateManager(100)
    res.put("a", _Lane(10), epoch=1)
    assert res.get("a", epoch=2) is None
    assert len(res) == 0


def test_resident_disabled_budget_zero():
    res = ResidentStateManager(0)
    assert not res.put("a", _Lane(1))
    assert res.get("a") is None


# ------------------------------------------------------ micro-batch window

def test_microbatcher_disabled_and_infeasible_serve_solo():
    mb = MicroBatcher(0.0, max_queries=4)
    assert mb.offer(_req("q0"), key_bound=TPN * NODES) == [_req("q0")]
    mb2 = MicroBatcher(50.0, max_queries=4)
    from tpu_radix_join.ops.merge_delta import MAX_SERVE_KEY
    assert len(mb2.offer(_req("q1"), key_bound=MAX_SERVE_KEY)) == 1


def test_microbatcher_parks_until_window_then_due():
    now = [0.0]
    mb = MicroBatcher(50.0, max_queries=8, clock=lambda: now[0])
    assert mb.offer(_req("a"), key_bound=1 << 12) is None
    assert mb.offer(_req("b"), key_bound=1 << 12) is None
    assert mb.due() == []                        # window still open
    now[0] = 0.051
    groups = mb.due()
    assert [len(g) for g in groups] == [2]
    assert mb.stats()["fused_batches"] == 1


def test_microbatcher_full_window_flushes_immediately():
    mb = MicroBatcher(1000.0, max_queries=2)
    assert mb.offer(_req("a"), key_bound=1 << 12) is None
    group = mb.offer(_req("b"), key_bound=1 << 12)
    assert group is not None and len(group) == 2
    assert mb.pending() == 0


def test_microbatcher_tight_deadline_serves_solo():
    mb = MicroBatcher(50.0, max_queries=8)
    out = mb.offer(_req("a", deadline_s=0.01), key_bound=1 << 12)
    assert out is not None and len(out) == 1     # window > deadline


def test_microbatcher_signature_separates_windows():
    now = [0.0]
    mb = MicroBatcher(50.0, max_queries=8, clock=lambda: now[0])
    mb.offer(_req("a"), key_bound=1 << 12)
    mb.offer(_req("b", outer_kind="modulo", modulo=16), key_bound=1 << 12)
    assert mb.pending() == 2
    groups = mb.flush()
    assert [len(g) for g in groups] == [1, 1]
    assert (batch_signature(groups[0][0])
            != batch_signature(groups[1][0]))


# --------------------------------------------- admission queue group pull

def test_pop_matching_preserves_order_and_limit():
    from tpu_radix_join.service.admission import AdmissionQueue
    q = AdmissionQueue()
    for i in range(5):
        q.submit(_req(f"q{i}", seed=7 if i % 2 == 0 else 8))
    first = q.pop()
    assert first.query_id == "q0"
    peers = q.pop_matching(lambda r: r.seed == 7, 8)
    assert [r.query_id for r in peers] == ["q2", "q4"]
    rest = [q.pop().query_id for _ in range(2)]
    assert rest == ["q1", "q3"]                 # relative order survives


# ------------------------------------------------- serving-tier cost rows

def test_serving_strategy_rows():
    from tpu_radix_join.planner import (ServingContext,
                                        enumerate_serving_strategies,
                                        load_profile)
    from tpu_radix_join.planner.cost_model import Workload
    prof = load_profile()
    w = Workload(r_tuples=1 << 20, s_tuples=1 << 20, key_bound=1 << 20,
                 num_nodes=8)
    rows = {c.strategy: c for c in enumerate_serving_strategies(
        prof, w, ServingContext(batch_queries=4, delta_tuples=1 << 14,
                                resident=True))}
    assert rows["serve_cached"].feasible is False    # delta never caches
    assert rows["serve_batched"].feasible
    assert rows["serve_delta"].feasible
    cached = enumerate_serving_strategies(
        prof, w, ServingContext())[0]
    assert cached.strategy == "serve_cached" and cached.feasible
    assert cached.cost_ms == pytest.approx(
        prof.value("result_cache_lookup_ms"))


# ------------------------------------------------ engine-integrated tiers

def test_session_cache_hit_stamps_and_exactness():
    cfg = JoinConfig(num_nodes=NODES)
    svc = ServiceConfig(result_cache_max=4)
    m = Measurements(node_id=0, num_nodes=NODES)
    sess = JoinSession(cfg, svc, measurements=m)
    try:
        sess.submit(_req("cold"))
        cold = sess.run_next()
        assert cold.status == "ok" and cold.matches == cold.expected
        assert sess.try_cache(_req("miss", seed=99)) is None
        hit = sess.try_cache(_req("hot"))
        assert hit is not None and hit.served_by == "cache_hit"
        assert hit.query_id == "hot"             # envelope re-stamped
        assert hit.matches == cold.matches
        assert int(m.counters.get(RCHIT, 0)) == 1
    finally:
        sess.close()


def test_session_batched_drain_fuses_cosignature_queries():
    cfg = JoinConfig(num_nodes=NODES)
    svc = ServiceConfig(batch_window_ms=50.0, batch_max_queries=8)
    sess = JoinSession(cfg, svc)
    try:
        for i in range(3):
            sess.submit(_req(f"b{i}"))
        sess.submit(_req("solo", outer_kind="modulo", modulo=16))
        outs = {o.query_id: o for o in sess.drain()}
        assert all(o.status == "ok" and o.matches == o.expected
                   for o in outs.values())
        assert [outs[f"b{i}"].served_by for i in range(3)] == ["batched"] * 3
        assert outs["solo"].served_by == "execute"
        assert sess.batches_fused == 1 and sess.batch_queries_fused == 3
    finally:
        sess.close()


def test_session_delta_chain_incremental_and_eviction_reset():
    cfg = JoinConfig(num_nodes=NODES)
    svc = ServiceConfig(resident_budget_bytes=1 << 24)
    m = Measurements(node_id=0, num_nodes=NODES)
    sess = JoinSession(cfg, svc, measurements=m)
    try:
        outs = []
        for i in range(3):
            sess.submit(_req(f"d{i}", delta_tuples_per_node=32))
            outs.append(sess.run_next())
        assert all(o.status == "ok" and o.matches == o.expected
                   for o in outs)
        assert outs[0].served_by == "execute"    # cold seed
        assert [o.served_by for o in outs[1:]] == ["delta_merge"] * 2
        # the union grows by 32 * NODES matched keys per absorbed delta
        assert outs[1].matches == outs[0].matches
        assert int(m.counters.get(DELTAMERGE, 0)) == 2
        # eviction mid-chain: residency lost -> cold rebuild, still exact
        sess.resident.invalidate()
        sess.submit(_req("d3", delta_tuples_per_node=32))
        o3 = sess.run_next()
        assert o3.status == "ok" and o3.matches == o3.expected
        assert o3.served_by == "execute"
        sess.submit(_req("d4", delta_tuples_per_node=32))
        o4 = sess.run_next()
        assert o4.served_by == "delta_merge"
        assert o4.status == "ok" and o4.matches == o4.expected
    finally:
        sess.close()


def test_session_delta_budget_zero_stays_on_full_path():
    cfg = JoinConfig(num_nodes=NODES)
    sess = JoinSession(cfg, ServiceConfig())     # residency disabled
    try:
        for i in range(2):
            sess.submit(_req(f"d{i}", delta_tuples_per_node=32))
            out = sess.run_next()
            assert out.status == "ok" and out.matches == out.expected
            assert out.served_by == "execute"
    finally:
        sess.close()
