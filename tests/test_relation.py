import numpy as np

from tpu_radix_join.data.relation import (
    Relation,
    host_join_count,
    unique_keys_device,
)


def test_unique_is_permutation():
    rel = Relation(global_size=4096, num_nodes=4, kind="unique", seed=7)
    keys = np.concatenate([rel.shard_np(i)[0] for i in range(4)])
    np.testing.assert_array_equal(np.sort(keys), np.arange(4096))


def test_unique_device_matches_host():
    rel = Relation(global_size=1 << 12, num_nodes=2, kind="unique", seed=11)
    for node in range(2):
        host_keys, _ = rel.shard_np(node)
        dev_keys = np.asarray(rel.shard(node).key)
        np.testing.assert_array_equal(dev_keys, host_keys)


def test_unique_non_pow2_domain():
    rel = Relation(global_size=3000, num_nodes=3, kind="unique", seed=3)
    keys = np.concatenate([rel.shard_np(i)[0] for i in range(3)])
    np.testing.assert_array_equal(np.sort(keys), np.arange(3000))
    dev = np.concatenate([np.asarray(rel.shard(i).key) for i in range(3)])
    np.testing.assert_array_equal(dev, keys)


def test_modulo_and_oracles():
    r = Relation(global_size=1024, kind="unique", seed=5)
    s_uni = Relation(global_size=1024, kind="unique", seed=9)
    s_mod = Relation(global_size=2048, kind="modulo", modulo=256)
    assert r.expected_matches(s_uni) == 1024
    assert r.expected_matches(s_mod) == 2048
    # cross-check with the host join oracle
    rk = r.shard_np(0)[0]
    np.testing.assert_equal(host_join_count(rk, s_mod.shard_np(0)[0]), 2048)


def test_zipf_within_domain():
    s = Relation(global_size=1000, kind="zipf", zipf_theta=0.75, key_domain=500)
    keys, _ = s.shard_np(0)
    assert keys.max() < 500
    r = Relation(global_size=500, kind="unique")
    assert r.expected_matches(s) == 1000


def test_zipf_device_twin_and_distribution():
    """The device sampler must reproduce the host sampler bit-for-bit (the
    integer-table scheme's whole point, VERDICT r3 item 6), across chunked
    starts and both key widths; and the draw must actually be Zipf-shaped
    (rank 0 clearly dominates, frequencies decay)."""
    import jax

    domain = 1 << 18
    size = 1 << 14
    # low theta: the tail past the 65536-rank head table carries ~2% mass,
    # so these 16K draws actually exercise BOTH sampler branches
    for key_bits in (32, 64):
        rel = Relation(size, 1, "zipf", zipf_theta=0.2, key_domain=domain,
                       seed=77, key_bits=key_bits)
        host = rel.shard_np(0)
        dev = jax.device_get(rel.zipf_range_device(0, size))
        np.testing.assert_array_equal(dev[0], host[0])
        if key_bits == 64:
            np.testing.assert_array_equal(dev[1], host[1])
        # chunked starts (the streaming path) agree with the full range
        mid = size // 2
        dev_b = jax.device_get(rel.zipf_range_device(mid, size - mid))
        np.testing.assert_array_equal(dev_b[0], host[0][mid:])
    keys = host[0]
    counts = np.bincount(keys, minlength=domain)
    assert counts[0] == counts.max() and counts[0] > size // 100
    # decaying head frequencies: rank 0 well above rank ~100
    assert counts[0] > 3 * counts[100]
    assert keys.max() >= (1 << 16)       # tail ranks drawn
    assert keys.max() < domain


def test_generate_sharded_matches_host():
    """On-device sharded generation (generate_sharded) is bit-identical to
    the host shard_np path per shard, for every supported kind x width, on
    the 8-device virtual mesh (SURVEY.md §7.4 item 5)."""
    from tpu_radix_join.parallel.mesh import make_mesh

    mesh = make_mesh(8)
    cases = [
        Relation(1 << 13, 8, "unique", seed=31),
        Relation(1 << 13, 8, "unique", seed=32, key_bits=64),
        Relation(1 << 13, 8, "modulo", seed=33, modulo=777),
        Relation(3000 * 8, 8, "unique", seed=34),   # non-pow2 domain
    ]
    for rel in cases:
        batch = rel.generate_sharded(mesh, "nodes")
        assert batch is not None
        keys = np.asarray(batch.key).reshape(8, -1)
        rids = np.asarray(batch.rid).reshape(8, -1)
        his = (np.asarray(batch.key_hi).reshape(8, -1)
               if batch.key_hi is not None else None)
        for node in range(8):
            sh = rel.shard_np(node)
            np.testing.assert_array_equal(keys[node], sh[0])
            np.testing.assert_array_equal(rids[node], sh[-1])
            if his is not None:
                np.testing.assert_array_equal(his[node], sh[1])
    # zipf generates on device too (r4: integer-table sampler), bit-identical
    # to the host twin
    z = Relation(1 << 12, 8, "zipf", zipf_theta=0.75)
    zb = z.generate_sharded(mesh, "nodes")
    zkeys = np.asarray(zb.key).reshape(8, -1)
    for node in range(8):
        np.testing.assert_array_equal(zkeys[node], z.shard_np(node)[0])


def test_generation_modes_drive_join():
    """place() honors config.generation: auto/device produce the same batch
    as host (bit-identical generators), and 'device' refuses kinds without
    an on-device generator."""
    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.operators.hash_join import HashJoin

    rel = Relation(1 << 12, 4, "unique", seed=41)
    zipf = Relation(1 << 12, 4, "zipf", zipf_theta=0.9, seed=42)
    by_mode = {}
    for mode in ("auto", "host", "device"):
        eng = HashJoin(JoinConfig(num_nodes=4, generation=mode))
        by_mode[mode] = eng.place(rel)
        res = eng.join(rel, Relation(1 << 12, 4, "unique", seed=43))
        assert res.ok and res.matches == 1 << 12
    np.testing.assert_array_equal(np.asarray(by_mode["auto"].key),
                                  np.asarray(by_mode["host"].key))
    np.testing.assert_array_equal(np.asarray(by_mode["device"].key),
                                  np.asarray(by_mode["host"].key))
    # zipf generates on device since r4: every mode agrees with host bits
    eng_auto = HashJoin(JoinConfig(num_nodes=4, generation="auto"))
    eng_dev = HashJoin(JoinConfig(num_nodes=4, generation="device"))
    eng_host = HashJoin(JoinConfig(num_nodes=4, generation="host"))
    zk_host = np.asarray(eng_host.place(zipf).key)
    np.testing.assert_array_equal(np.asarray(eng_auto.place(zipf).key),
                                  zk_host)
    np.testing.assert_array_equal(np.asarray(eng_dev.place(zipf).key),
                                  zk_host)


def test_generate_sharded_hierarchical_mesh():
    """Device generation over the 2-D (dcn, ici) mesh: the flat axis_index
    ordering must match shard_np's node ordering exactly."""
    from tpu_radix_join.parallel.mesh import make_hierarchical_mesh

    mesh = make_hierarchical_mesh(2, 8)
    rel = Relation(1 << 13, 8, "unique", seed=61)
    batch = rel.generate_sharded(mesh, ("dcn", "ici"))
    keys = np.asarray(batch.key).reshape(8, -1)
    rids = np.asarray(batch.rid).reshape(8, -1)
    for node in range(8):
        k, r = rel.shard_np(node)
        np.testing.assert_array_equal(keys[node], k)
        np.testing.assert_array_equal(rids[node], r)


def test_device_generation_above_int31_offsets():
    """Node offsets past 2**31 (legal: global_size caps at 2**32 - 1) must
    not overflow JAX's weak-int32 scalar promotion in the device generators
    (device_range / unique_keys_device)."""
    from tpu_radix_join.data.streaming import stream_chunks_device

    rel = Relation((1 << 32) - (1 << 20), 1 << 12, "unique", seed=1,
                   key_bits=64)
    node = (1 << 12) - 1          # start = node * local_size > 2**31
    k, hi, rid = rel.shard_np(node)
    m = 1 << 14
    batch = next(stream_chunks_device(rel, node, m))
    np.testing.assert_array_equal(np.asarray(batch.key), k[:m])
    np.testing.assert_array_equal(np.asarray(batch.key_hi), hi[:m])
    np.testing.assert_array_equal(np.asarray(batch.rid), rid[:m])
    sh = rel.shard(node)
    np.testing.assert_array_equal(np.asarray(sh.key)[:m], k[:m])
