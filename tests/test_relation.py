import numpy as np

from tpu_radix_join.data.relation import (
    Relation,
    host_join_count,
    unique_keys_device,
)


def test_unique_is_permutation():
    rel = Relation(global_size=4096, num_nodes=4, kind="unique", seed=7)
    keys = np.concatenate([rel.shard_np(i)[0] for i in range(4)])
    np.testing.assert_array_equal(np.sort(keys), np.arange(4096))


def test_unique_device_matches_host():
    rel = Relation(global_size=1 << 12, num_nodes=2, kind="unique", seed=11)
    for node in range(2):
        host_keys, _ = rel.shard_np(node)
        dev_keys = np.asarray(rel.shard(node).key)
        np.testing.assert_array_equal(dev_keys, host_keys)


def test_unique_non_pow2_domain():
    rel = Relation(global_size=3000, num_nodes=3, kind="unique", seed=3)
    keys = np.concatenate([rel.shard_np(i)[0] for i in range(3)])
    np.testing.assert_array_equal(np.sort(keys), np.arange(3000))
    dev = np.concatenate([np.asarray(rel.shard(i).key) for i in range(3)])
    np.testing.assert_array_equal(dev, keys)


def test_modulo_and_oracles():
    r = Relation(global_size=1024, kind="unique", seed=5)
    s_uni = Relation(global_size=1024, kind="unique", seed=9)
    s_mod = Relation(global_size=2048, kind="modulo", modulo=256)
    assert r.expected_matches(s_uni) == 1024
    assert r.expected_matches(s_mod) == 2048
    # cross-check with the host join oracle
    rk = r.shard_np(0)[0]
    np.testing.assert_equal(host_join_count(rk, s_mod.shard_np(0)[0]), 2048)


def test_zipf_within_domain():
    s = Relation(global_size=1000, kind="zipf", zipf_theta=0.75, key_domain=500)
    keys, _ = s.shard_np(0)
    assert keys.max() < 500
    r = Relation(global_size=500, kind="unique")
    assert r.expected_matches(s) == 1000
