"""Elastic mesh recovery (robustness/membership.py + recovery.py): lease
lifecycle and epoch fencing, the partition manifest's resume invariants,
the recovery planner/executor against the size oracle, the engine-level
rank-death → recovered-join path at every phase boundary, the rank-death
chaos mini-soak, and the REAL 2-process SIGKILL recovery (victim dies
mid-run; the survivor finishes oracle-exact with RANKLOST=1).  The
randomized larger soak rides behind ``-m slow``."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_radix_join.robustness import chaos, faults
from tpu_radix_join.robustness.checkpoint import (AsyncCheckpointWriter,
                                                  CheckpointManager,
                                                  CheckpointMismatch,
                                                  PartitionManifest)
from tpu_radix_join.robustness.membership import (LeaseBoard, MembershipView,
                                                  RankLost, StaleEpoch)
from tpu_radix_join.robustness.recovery import (execute_recovery, host_keys,
                                                partition_weights,
                                                plan_recovery)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# --------------------------------------------------------------- membership
def test_lease_heartbeat_round_trip(tmp_path):
    board = LeaseBoard(str(tmp_path), rank=1, num_ranks=3, lease_s=5.0)
    rec = board.heartbeat(epoch=2)
    assert rec["rank"] == 1 and rec["epoch"] == 2
    lease = board.read(1)
    assert lease.rank == 1 and lease.epoch == 2 and lease.seq == 1
    board.heartbeat(epoch=2)
    assert board.read(1).seq == 2


def test_lapse_detection_and_startup_grace(tmp_path):
    clk = FakeClock()
    a = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0, clock=clk)
    b = LeaseBoard(str(tmp_path), rank=1, num_ranks=2, lease_s=5.0, clock=clk)
    b.heartbeat()
    assert a.lapsed() == []            # fresh lease
    clk.t += 4.0
    assert a.lapsed() == []            # inside the window
    clk.t += 2.0
    assert a.lapsed() == []            # one missed beat is not a lapse
    clk.t += 5.0
    assert a.lapsed() == [1]           # two windows of silence: aged out
    # startup grace: a rank that never wrote a lease lapses only once a
    # full lapse window (missed_beats x lease_s) passed since creation
    c = LeaseBoard(str(tmp_path / "g"), rank=0, num_ranks=2, lease_s=5.0,
                   clock=clk)
    assert c.lapsed() == []
    clk.t += 6.0
    assert c.lapsed() == []            # inside the two-beat grace
    clk.t += 5.0
    assert c.lapsed() == [1]


def test_one_missed_beat_never_lapses(tmp_path):
    """Regression for the false-lapse bug (satellite of the elastic-growth
    PR): a healthy rank that misses ONE beat — a long device pass — used
    to be declared lost at ``lease_s``; the two-missed-beats rule holds
    the verdict until a second consecutive window passes in silence, and
    a beat anywhere inside the window fully resets the clock."""
    from tpu_radix_join.performance.measurements import RANKLOST, Measurements
    clk = FakeClock()
    m = Measurements()
    a = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0, clock=clk)
    b = LeaseBoard(str(tmp_path), rank=1, num_ranks=2, lease_s=5.0, clock=clk)
    view = MembershipView(a, measurements=m)
    b.heartbeat()
    # one whole window of silence (the slow-kernel scenario): no lapse
    clk.t += 7.0
    assert view.check() == []
    assert view.lost == set() and m.counters.get(RANKLOST, 0) == 0
    # a beat just before the second window closes resets everything
    clk.t += 2.9
    b.heartbeat()
    clk.t += 9.9
    assert view.check() == []          # inside a fresh 2-window span
    # genuine death: silence past the full lapse window declares it
    clk.t += 0.2
    assert view.check() == [1]
    assert m.counters[RANKLOST] == 1
    # missed_beats=1 restores the old single-window policy explicitly
    c = LeaseBoard(str(tmp_path / "one"), rank=0, num_ranks=2, lease_s=5.0,
                   clock=clk, missed_beats=1)
    d = LeaseBoard(str(tmp_path / "one"), rank=1, num_ranks=2, lease_s=5.0,
                   clock=clk)
    d.heartbeat()
    clk.t += 5.1
    assert c.lapsed() == [1]
    with pytest.raises(ValueError):
        LeaseBoard(str(tmp_path), rank=0, num_ranks=2, missed_beats=0)


def test_torn_lease_reads_as_absent(tmp_path):
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0)
    with open(board.lease_path(1), "w") as f:
        f.write('{"rank": 1, "epo')           # torn mid-write
    assert board.read(1) is None


def test_membership_one_epoch_bump_per_batch(tmp_path):
    from tpu_radix_join.performance.measurements import (MEPOCH, RANKLOST,
                                                         Measurements)
    clk = FakeClock()
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=4, lease_s=5.0,
                       clock=clk)
    m = Measurements()
    view = MembershipView(board, measurements=m)
    for r in (1, 2, 3):
        LeaseBoard(str(tmp_path), rank=r, num_ranks=4, lease_s=5.0,
                   clock=clk).heartbeat()
    assert view.check() == []
    clk.t += 11.0                      # all three peers lapse together
    assert view.check() == [1, 2, 3]
    assert view.epoch == 1             # ONE fence for the batch
    assert m.counters[MEPOCH] == 1 and m.counters[RANKLOST] == 3
    assert view.check() == []          # already declared: no re-bump
    assert view.survivors == [0]


def test_epoch_fence_and_require_live(tmp_path):
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0)
    view = MembershipView(board)
    view.fence(0)                      # current epoch passes
    epoch = view.declare_lost(1, cause="test")
    assert epoch == 1
    with pytest.raises(StaleEpoch) as ei:
        view.fence(0)
    assert ei.value.failure_class == "rank_lost"
    assert (ei.value.stamped, ei.value.current) == (0, 1)
    with pytest.raises(RankLost):
        view.require_live(1)


def test_suspect_triage(tmp_path):
    clk = FakeClock()
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0,
                       clock=clk)
    peer = LeaseBoard(str(tmp_path), rank=1, num_ranks=2, lease_s=5.0,
                      clock=clk)
    peer.heartbeat()
    view = MembershipView(board)
    assert view.suspect() is None      # all peers live: hang verdict stands
    clk.t += 11.0
    exc = view.suspect()
    assert isinstance(exc, RankLost) and exc.rank == 1
    assert exc.bundle_extra["membership_epoch"] == 1


def test_sampler_extra_heartbeats(tmp_path):
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=1, lease_s=5.0)
    view = MembershipView(board)
    extra = board.sampler_extra(epoch_of=view.epoch_of)
    rec = extra()
    assert rec["lease"]["rank"] == 0 and rec["lease"]["epoch"] == 0
    assert board.read(0).seq == rec["lease"]["seq"]


# --------------------------------------------------------- partition manifest
def test_manifest_resume_later_lines_win(tmp_path):
    path = str(tmp_path / "parts.manifest")
    man = PartitionManifest(path, fingerprint={"tag": "a"})
    assert man.mark_done(0, 100, owner=0)
    assert man.mark_done(1, 200, owner=1, epoch=0)
    assert man.mark_done(1, 250, owner=2, epoch=1)   # re-realized post-fence
    done = PartitionManifest(path, fingerprint={"tag": "a"}).completed()
    assert done[0]["count"] == 100
    assert done[1] == {"count": 250, "owner": 2, "epoch": 1}


def test_manifest_fingerprint_guard(tmp_path):
    path = str(tmp_path / "parts.manifest")
    PartitionManifest(path, fingerprint={"tag": "a"}).mark_done(0, 1, 0)
    with pytest.raises(CheckpointMismatch):
        PartitionManifest(path, fingerprint={"tag": "b"})


def test_manifest_torn_line_skipped(tmp_path):
    path = str(tmp_path / "parts.manifest")
    man = PartitionManifest(path, fingerprint={"tag": "a"})
    man.mark_done(0, 100, owner=0)
    with open(path, "a") as f:
        f.write('{"partition": 1, "cou')         # SIGKILL mid-append
    done = PartitionManifest(path, fingerprint={"tag": "a"}).completed()
    assert done == {0: {"count": 100, "owner": 0, "epoch": 0}}


def test_manifest_mark_many(tmp_path):
    man = PartitionManifest(str(tmp_path / "m"), fingerprint={"t": 1})
    n = man.mark_many({0: 10, 3: 30}, owner_of=lambda p: p % 2, epoch=2)
    assert n == 2
    done = man.completed()
    assert done[3] == {"count": 30, "owner": 1, "epoch": 2}


# ------------------------------------------------- async writer exit guarantee
def test_async_writer_close_idempotent_and_context_flush(tmp_path):
    """Regression for the write-behind exit guarantee: a state enqueued
    just before ``with``-exit must be on disk afterwards, and close() must
    be safe to call again (explicitly and from the atexit hook)."""
    mgr = CheckpointManager(str(tmp_path / "c.ckpt"), fingerprint={"t": 1})
    with AsyncCheckpointWriter(mgr) as w:
        w.save({"pairs": 7})
    # context exit closed (and therefore flushed) the queue
    assert mgr.load()["pairs"] == 7
    w.close()                                    # idempotent re-close
    w.save({"pairs": 8})                         # enqueue after close...
    w.close()
    assert mgr.load()["pairs"] == 7              # ...is never written


def test_async_writer_atexit_registered(tmp_path):
    import atexit
    mgr = CheckpointManager(str(tmp_path / "c.ckpt"), fingerprint={"t": 1})
    w = AsyncCheckpointWriter(mgr)
    try:
        # the exit guarantee exists iff close is on the atexit table;
        # unregister returns None either way, so probe the private table
        # via a second register/unregister cycle being harmless and the
        # thread being alive until close
        assert w._thread.is_alive()
        w.save({"pairs": 1})
        w.flush()
        assert mgr.load()["pairs"] == 1
    finally:
        w.close()
    assert not w._thread.is_alive()


# ------------------------------------------------------------ recovery planner
def test_plan_recovery_resume_and_reassignment():
    class _Man:
        def completed(self):
            return {0: {"count": 5, "owner": 0, "epoch": 0},
                    7: {"count": 9, "owner": 3, "epoch": 0}}

    plan = plan_recovery(num_nodes=4, num_partitions=8, lost_ranks=[3],
                         epoch=1, manifest=_Man())
    assert plan.survivors == (0, 1, 2)
    assert plan.resumed == {0: 5, 7: 9}
    assert plan.recompute == (1, 2, 3, 4, 5, 6)
    # every recompute partition lands on a survivor, never the dead rank
    assert set(plan.reassignment) == set(plan.recompute)
    assert all(r in plan.survivors for r in plan.reassignment.values())
    # deterministic: every survivor computes the identical map
    again = plan_recovery(num_nodes=4, num_partitions=8, lost_ranks=[3],
                          epoch=1, manifest=_Man())
    assert again.reassignment == plan.reassignment
    d = plan.to_diag()
    assert d["recovered"] is True and d["membership_epoch"] == 1
    assert d["resumed_partitions"] == [0, 7]


def test_plan_recovery_no_survivors_raises():
    with pytest.raises(RankLost):
        plan_recovery(num_nodes=2, num_partitions=4, lost_ranks=[0, 1],
                      epoch=1)


def test_execute_recovery_oracle_exact():
    """Recomputing every partition from host key lanes reproduces the size
    oracle exactly; resumed counts are trusted (never recomputed)."""
    n = 1 << 10
    num_p = 8
    rng = np.random.default_rng(3)
    rk = (rng.permutation(n) + 1).astype(np.uint32)
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    plan = plan_recovery(num_nodes=4, num_partitions=num_p, lost_ranks=[3],
                         epoch=1,
                         weights=partition_weights(rk, sk, num_p))
    matches, counts = execute_recovery(plan, rk, sk, slab=n)
    assert matches == n
    assert sorted(counts) == list(range(num_p))
    # only_rank as an int and as an iterable both restrict to that
    # survivor's share, and the shares tile the recompute set
    total = 0
    for r in plan.survivors:
        sub, _ = execute_recovery(plan, rk, sk, slab=n, only_rank=r)
        total += sub
    assert total == n
    it_matches, _ = execute_recovery(plan, rk, sk, slab=n,
                                     only_rank=list(plan.survivors))
    assert it_matches == n


def test_execute_recovery_resumes_partial_manifest(tmp_path):
    """A manifest holding half the partitions turns recovery into a
    HALF-recompute: RECOVERN stays strictly below the partition count (the
    acceptance-bar signal that resume was partition-granular)."""
    from tpu_radix_join.performance.measurements import (RECOVERN,
                                                         Measurements)
    n, num_p = 1 << 10, 8
    rng = np.random.default_rng(4)
    rk = (rng.permutation(n) + 1).astype(np.uint32)
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    # true per-partition counts: every S key matches exactly one R key
    true = np.bincount(sk & (num_p - 1), minlength=num_p)
    man = PartitionManifest(str(tmp_path / "m"), fingerprint={"t": 1})
    man.mark_many({p: int(true[p]) for p in range(4)},
                  owner_of=lambda p: p % 4)
    m = Measurements()
    plan = plan_recovery(num_nodes=4, num_partitions=num_p, lost_ranks=[3],
                         epoch=1, manifest=man)
    assert plan.recompute == (4, 5, 6, 7)
    matches, _ = execute_recovery(plan, rk, sk, slab=n, measurements=m,
                                  manifest=man)
    assert matches == n
    assert 0 < m.counters[RECOVERN] < num_p
    # the recompute appended post-realization lines: a NEXT recovery
    # resumes everything
    assert len(man.completed()) == num_p


def test_host_keys_regenerates_global_relation():
    from tpu_radix_join.data.relation import Relation
    rel = Relation(1 << 10, 4, "unique", seed=7)
    keys, hi = host_keys(rel)
    assert hi is None
    assert len(keys) == 1 << 10
    assert sorted(keys) == list(range(1 << 10))   # a permutation of 0..n-1


# ------------------------------------------------------- engine elastic path
@pytest.fixture(scope="module")
def elastic_engine():
    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.operators.hash_join import HashJoin
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=3, verify="check")
    eng = HashJoin(cfg)
    eng.elastic = True
    return eng


def _oracle_batches(n, seed=0):
    import jax.numpy as jnp
    from tpu_radix_join.data.tuples import TupleBatch
    rng = np.random.default_rng(seed)
    rk = (rng.permutation(n) + 1).astype(np.uint32)
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    rid = np.arange(n, dtype=np.uint32)
    return (TupleBatch(key=jnp.asarray(rk), rid=jnp.asarray(rid)),
            TupleBatch(key=jnp.asarray(sk), rid=jnp.asarray(rid)),
            rk, sk)


@pytest.mark.parametrize("at", [1, 2, 3])
def test_engine_recovers_rank_death_at_each_boundary(elastic_engine, at):
    """The tentpole invariant at engine level: an injected rank death at
    ANY phase boundary ends in the exact oracle count with the full
    recovery record in diagnostics — never a hang, never an overclaim."""
    from tpu_radix_join.performance.measurements import (MEPOCH, RANKLOST,
                                                         RECOVERN,
                                                         Measurements)
    n = 1 << 11
    r, s, _, _ = _oracle_batches(n, seed=1)
    m = Measurements()
    elastic_engine.measurements = m
    with faults.FaultInjector(seed=at, measurements=m).arm(
            faults.RANK_DEATH, at=at):
        result = elastic_engine.join_arrays(r, s)
    assert result.ok
    assert result.matches == n
    d = result.diagnostics
    assert d["recovered"] is True
    assert d["membership_epoch"] >= 1
    assert d["lost_ranks"] == [3]
    assert m.counters[RANKLOST] == 1 and m.counters[MEPOCH] == 1
    assert m.counters[RECOVERN] == len(d["recovered_partitions"])


def test_engine_manifest_resume_bounds_recompute(tmp_path, elastic_engine):
    """With a partition manifest holding half the partitions' true counts,
    the engine's recovery resumes them: RECOVERN < partition count and the
    spliced total still hits the oracle."""
    from tpu_radix_join.performance.measurements import (RECOVERN,
                                                         Measurements)
    n, num_p = 1 << 11, 8
    r, s, _, sk = _oracle_batches(n, seed=2)
    true = np.bincount(sk & (num_p - 1), minlength=num_p)
    man = PartitionManifest(str(tmp_path / "m"), fingerprint={"t": 1})
    man.mark_many({p: int(true[p]) for p in range(4)},
                  owner_of=lambda p: p % 4)
    m = Measurements()
    elastic_engine.measurements = m
    elastic_engine.partition_manifest = man
    try:
        with faults.FaultInjector(seed=9, measurements=m).arm(
                faults.RANK_DEATH, at=2):
            result = elastic_engine.join_arrays(r, s)
    finally:
        elastic_engine.partition_manifest = None
    assert result.ok and result.matches == n
    assert result.diagnostics["resumed_partitions"] == [0, 1, 2, 3]
    assert 0 < m.counters[RECOVERN] < num_p


def test_non_elastic_engine_classifies_rank_death():
    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.operators.hash_join import HashJoin
    from tpu_radix_join.performance.measurements import Measurements
    eng = HashJoin(JoinConfig(num_nodes=4, network_fanout_bits=3))
    n = 1 << 10
    r, s, _, _ = _oracle_batches(n, seed=5)
    m = Measurements()
    eng.measurements = m
    with pytest.raises(RankLost) as ei:
        with faults.FaultInjector(seed=1, measurements=m).arm(
                faults.RANK_DEATH, at=1):
            eng.join_arrays(r, s)
    assert ei.value.failure_class == "rank_lost"


def test_membership_epoch_fences_compile_cache(elastic_engine):
    """The compile-key prefix: the same program recompiles (different key)
    once the membership epoch moves — stale mesh-shape programs can never
    be replayed across a fence."""
    fp0 = elastic_engine._cache_config_fp()
    assert fp0["membership_epoch"] == elastic_engine._membership_epoch()


# ----------------------------------------------------------- rank admission
def test_admission_exactly_once_per_batch(tmp_path):
    """Two newcomers' joining leases land in one check() window: the
    board admits BOTH with ONE fenced epoch bump (a host bringing up
    several processes joins in one fence, not N), and the next check is
    a no-op."""
    from tpu_radix_join.performance.measurements import (MEPOCH, RANKJOIN,
                                                         Measurements)
    clk = FakeClock()
    m = Measurements()
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0,
                       clock=clk, measurements=m)
    peer = LeaseBoard(str(tmp_path), rank=1, num_ranks=2, lease_s=5.0,
                      clock=clk)
    board.heartbeat(0)
    peer.heartbeat(0)
    mv = MembershipView(board, measurements=m)
    for r in (2, 3):
        LeaseBoard(str(tmp_path), rank=r, num_ranks=2, lease_s=5.0,
                   clock=clk).heartbeat(0, status="joining")
    assert mv.check() == []            # returns losses; none here
    assert mv.joined == {2, 3}
    assert mv.epoch == 1               # ONE bump for the batch of two
    assert m.counters[RANKJOIN] == 2 and m.counters[MEPOCH] == 1
    assert mv.check() == []            # idempotent: nothing new to admit
    assert mv.epoch == 1
    assert mv.survivors == [0, 1, 2, 3]


def test_lost_rank_readmits_only_via_joining_lease(tmp_path):
    """A declared-lost rank's in-flight state is gone: a bare member
    lease from it must NOT silently re-enter the current epoch — the
    joining lease is the only door back in, at a NEW epoch."""
    clk = FakeClock()
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0,
                       clock=clk)
    peer = LeaseBoard(str(tmp_path), rank=1, num_ranks=2, lease_s=5.0,
                      clock=clk)
    board.heartbeat(0)
    peer.heartbeat(0)
    mv = MembershipView(board)
    clk.t += 11.0
    board.heartbeat(0)
    assert mv.check() == [1]
    assert mv.epoch == 1 and 1 in mv.lost
    peer.heartbeat(1)                  # zombie writes a member lease
    assert mv.check() == []
    assert 1 in mv.lost and mv.epoch == 1
    peer.heartbeat(1, status="joining")
    mv.check()
    assert mv.is_live(1) and 1 in mv.joined
    assert mv.epoch == 2               # readmitted at a NEW fence


def test_stale_joining_lease_never_admitted(tmp_path):
    """A joiner that died before admission ages out of its request: its
    joining lease older than the lapse window is skipped, a fresh beat
    is admitted."""
    clk = FakeClock()
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=1, lease_s=5.0,
                       clock=clk)
    board.heartbeat(0)
    mv = MembershipView(board)
    joiner = LeaseBoard(str(tmp_path), rank=1, num_ranks=1, lease_s=5.0,
                        clock=clk)
    joiner.heartbeat(0, status="joining")
    clk.t += 11.0                      # the joiner went silent
    board.heartbeat(0)
    mv.check()
    assert mv.joined == set() and mv.epoch == 0
    joiner.heartbeat(0, status="joining")
    mv.check()
    assert mv.joined == {1} and mv.epoch == 1


def test_joiner_sync_epoch_adopts_incumbent_fence(tmp_path):
    """A newcomer booted at epoch 0 catches up with whatever fences the
    incumbents already burned — and never rewinds."""
    LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0).heartbeat(3)
    board = LeaseBoard(str(tmp_path), rank=2, num_ranks=2, lease_s=5.0)
    board.heartbeat(0, status="joining")
    mv = MembershipView(board)
    assert mv.sync_epoch() == 3
    assert mv.sync_epoch() == 3


def test_heartbeat_carries_partitions_done(tmp_path):
    """The progress clock rides the lease: ``progress_of`` stamps every
    beat with manifest progress, and board_progress omits ranks that
    export none (-1)."""
    from tpu_radix_join.robustness.straggler import board_progress
    a = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0)
    b = LeaseBoard(str(tmp_path), rank=1, num_ranks=2, lease_s=5.0)
    a.progress_of = lambda: 7
    a.heartbeat(0)
    b.heartbeat(0)                     # no progress hook: -1
    assert a.read(0).partitions_done == 7
    assert a.read(1).partitions_done == -1
    assert board_progress(a, [0, 1]) == {0: 7}


# ------------------------------------------------------ manifest hedge fence
def test_manifest_fence_late_original_loses_to_hedge(tmp_path):
    """Direction one: the hedge realizes a partition first; the
    straggling original's later done-line is fenced — the audit flags
    one fenced duplicate and the total counts the partition ONCE."""
    man = PartitionManifest(str(tmp_path / "m"), fingerprint={"t": 2})
    man.mark_done(3, 111, 5, epoch=1)      # the hedge's writer, first
    man.mark_done(3, 111, 7, epoch=1)      # the late original
    rec = man.completed()[3]
    assert rec["owner"] == 5 and rec["count"] == 111
    aud = man.audit()
    assert aud["total"] == 111
    assert aud["fenced_duplicates"] == {3: 1}


def test_manifest_fence_hedge_after_original_loses(tmp_path):
    """Direction two: the original landed first, so a hedge claim on the
    done partition is refused and a late hedge done-line is fenced."""
    man = PartitionManifest(str(tmp_path / "m"), fingerprint={"t": 3})
    man.mark_done(3, 40, 7, epoch=1)
    assert man.claim(3, owner=5, epoch=1) is False
    man.mark_done(3, 40, 5, epoch=1)       # the hedge writes anyway
    assert man.completed()[3]["owner"] == 7
    aud = man.audit()
    assert aud["total"] == 40              # never double-counted
    assert aud["fenced_duplicates"] == {3: 1}


def test_manifest_claim_protocol(tmp_path):
    """Claims are advisory intent lines: first claimant holds within an
    epoch (idempotently for itself), a newer epoch supersedes, and done
    lines — not claims — remain the count arbiter."""
    man = PartitionManifest(str(tmp_path / "m"), fingerprint={"t": 4})
    assert man.claim(2, owner=4, epoch=1) is True
    assert man.claim(2, owner=4, epoch=1) is True
    assert man.claim(2, owner=6, epoch=1) is False
    assert man.claims()[2]["owner"] == 4
    assert man.claim(2, owner=6, epoch=2) is True   # newer epoch supersedes
    man.mark_done(2, 9, 4, epoch=1)
    man.mark_done(2, 12, 6, epoch=2)
    rec = man.completed()[2]
    assert rec["owner"] == 6 and rec["count"] == 12


# ---------------------------------------------------------- straggler detector
def test_straggler_detector_validation_and_dwell():
    from tpu_radix_join.robustness.straggler import StragglerDetector
    with pytest.raises(ValueError):
        StragglerDetector(threshold=0.0)
    with pytest.raises(ValueError):
        StragglerDetector(threshold=1.0)
    with pytest.raises(ValueError):
        StragglerDetector(dwell_checks=0)
    det = StragglerDetector(threshold=0.5, min_outstanding=2,
                            dwell_checks=2)
    prog, todo = {0: 10, 1: 10, 2: 1}, {2: 4}
    assert det.observe(prog, todo) is None     # dwell 1 of 2
    v = det.observe(prog, todo)
    assert v is not None and v.rank == 2
    assert v.median == 10.0 and v.outstanding == 4
    exc = v.to_exc(epoch=3)
    assert exc.rank == 2 and exc.epoch == 3 and exc.outstanding == 4


def test_straggler_detector_resets_and_guards():
    from tpu_radix_join.robustness.straggler import StragglerDetector
    det = StragglerDetector(threshold=0.5, dwell_checks=2)
    assert det.observe({0: 10, 2: 1}, {2: 5}) is None
    # the suspect catches up: the dwell streak resets
    assert det.observe({0: 10, 2: 9}, {2: 5}) is None
    assert det.observe({0: 10, 2: 1}, {2: 5}) is None
    # nearly-done stragglers are not worth hedging (min_outstanding)
    det2 = StragglerDetector(threshold=0.5, dwell_checks=1,
                             min_outstanding=2)
    assert det2.observe({0: 10, 2: 1}, {2: 1}) is None
    # a lone rank has no peers to be relative to; zero median is too early
    assert det2.observe({0: 0}, {0: 8}) is None
    assert det2.observe({0: 0, 1: 0}, {0: 8}) is None


def test_straggler_detector_tie_breaks_smallest_rank():
    from tpu_radix_join.robustness.straggler import StragglerDetector
    det = StragglerDetector(threshold=0.6, dwell_checks=1)
    v = det.observe({3: 1, 1: 1, 0: 10, 2: 10}, {1: 9, 3: 9})
    assert v is not None and v.rank == 1


def test_score_hedge_splits_wins_from_waste(tmp_path):
    from tpu_radix_join.performance.measurements import (HEDGEWIN,
                                                         SPECWASTE,
                                                         Measurements)
    from tpu_radix_join.robustness.straggler import score_hedge
    man = PartitionManifest(str(tmp_path / "m"), fingerprint={"t": 5})
    man.mark_done(0, 5, 2, epoch=1)        # hedge writer won
    man.mark_done(1, 5, 3, epoch=1)        # the straggler landed first
    m = Measurements()
    sc = score_hedge(man, [0, 1, 4], straggler=3, measurements=m)
    assert sc == {"hedgewin": 1, "specwaste": 1}   # partition 4: no winner yet
    assert m.counters[HEDGEWIN] == 1 and m.counters[SPECWASTE] == 1


# ------------------------------------------------------ engine hedge + regrow
def _fresh_elastic_engine():
    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.operators.hash_join import HashJoin
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=3, verify="check")
    eng = HashJoin(cfg)
    eng.elastic = True
    return eng


def test_engine_hedges_injected_straggler(tmp_path):
    """compute.straggle with hedge on: the detector flags the victim off
    manifest progress, its stripe is speculatively recomputed through the
    fence, the result is oracle-exact with NO epoch bump (the straggler
    stays a member — nothing was declared lost) and the manifest audit
    sums exactly to the oracle."""
    from tpu_radix_join.performance.measurements import (HEDGED, HEDGEWIN,
                                                         MEPOCH, RANKLOST,
                                                         Measurements)
    n = 1 << 11
    r, s, _, _ = _oracle_batches(n, seed=3)
    eng = _fresh_elastic_engine()
    m = Measurements()
    eng.measurements = m
    board = LeaseBoard(str(tmp_path / "leases"), rank=0, num_ranks=1,
                       lease_s=300.0, measurements=m)
    board.heartbeat(0)
    eng.membership = MembershipView(board, measurements=m)
    man = PartitionManifest(str(tmp_path / "m"), fingerprint={"t": 6},
                            measurements=m)
    eng.partition_manifest = man
    eng.hedge = "on"
    eng.straggle_factor = 3.0
    eng.straggle_unit_s = 0.05
    with faults.FaultInjector(seed=11, measurements=m).arm(
            faults.COMPUTE_STRAGGLE, at=1):
        result = eng.join_arrays(r, s)
    assert result.ok and result.matches == n
    d = result.diagnostics
    assert d["recovered"] is True and d.get("hedged") is True
    assert m.counters[HEDGED] == 1
    assert m.counters.get(HEDGEWIN, 0) >= 1
    assert m.counters.get(MEPOCH, 0) == 0      # no fence: the rank lives
    assert m.counters.get(RANKLOST, 0) == 0
    assert man.audit()["total"] == n


def test_engine_hedge_off_sleeps_out_the_straggle(tmp_path):
    """The control arm: hedge off absorbs the injected slowdown as plain
    tail latency — no recovery, no epoch bump, same exact count."""
    from tpu_radix_join.performance.measurements import (HEDGED,
                                                         Measurements)
    n = 1 << 11
    r, s, _, _ = _oracle_batches(n, seed=3)
    eng = _fresh_elastic_engine()
    m = Measurements()
    eng.measurements = m
    eng.straggle_factor = 2.0
    eng.straggle_unit_s = 0.01
    with faults.FaultInjector(seed=11, measurements=m).arm(
            faults.COMPUTE_STRAGGLE, at=1):
        result = eng.join_arrays(r, s)
    assert result.ok and result.matches == n
    assert not (result.diagnostics or {}).get("recovered")
    assert m.counters.get(HEDGED, 0) == 0


def test_engine_regrows_on_injected_rank_join(tmp_path):
    """membership.rank_join with elastic_grow: the injected newcomer's
    joining lease is admitted at the next boundary, the epoch fences
    once, and the re-expanded plan assigns partitions to node ids beyond
    the boot mesh — oracle-exact."""
    from tpu_radix_join.performance.measurements import (MEPOCH, RANKJOIN,
                                                         Measurements)
    n = 1 << 11
    r, s, _, _ = _oracle_batches(n, seed=4)
    eng = _fresh_elastic_engine()
    eng.elastic_grow = True
    m = Measurements()
    eng.measurements = m
    board = LeaseBoard(str(tmp_path / "leases"), rank=0, num_ranks=1,
                       lease_s=300.0, measurements=m)
    board.heartbeat(0)
    eng.membership = MembershipView(board, measurements=m)
    with faults.FaultInjector(seed=13, measurements=m).arm(
            faults.RANK_JOIN, at=1):
        result = eng.join_arrays(r, s)
    assert result.ok and result.matches == n
    d = result.diagnostics
    assert d["recovered"] is True and d.get("regrown") is True
    assert d["lost_ranks"] == []
    assert m.counters[RANKJOIN] == 1 and m.counters[MEPOCH] == 1
    # the enlarged membership really received work: owners beyond the
    # boot mesh appear in the re-expanded assignment
    owners = {int(o) for o in d["recovery_assignment"].values()}
    assert max(owners) >= 4


# ------------------------------------------------------------ chaos mini-soak
def test_recovery_mini_soak_fixed_seeds():
    """Acceptance gate: fixed-seed schedules over {rank_death, rank_join,
    compute.straggle} end oracle-exact (PASS — recovered, regrown, or
    hedged) or classified — zero violations, zero watchdog deaths, all
    three membership sites exercised, and the manifest audit sums exactly
    to the oracle on every PASS (zero double-counted partitions)."""
    runner = chaos.RecoveryChaosRunner(num_nodes=4, size=1 << 11)
    outcomes, summary = chaos.soak_recovery(6, base_seed=230, runner=runner)
    assert summary["violations"] == 0, [
        o.to_json() for o in outcomes if o.status == chaos.VIOLATION]
    assert summary["wdogtrip"] == 0
    assert summary["ranklost"] >= 1
    assert summary["rankjoin"] >= 1
    assert summary["hedged"] >= 1
    assert summary["hedgewin"] >= 1
    assert summary["recovered_partitions"] >= 1
    assert summary["max_epoch"] >= 1
    assert summary["manifest_exact"] >= summary["pass"]


def test_generate_recovery_schedule_always_arms_rank_death():
    sites_seen = set()
    for seed in range(40):
        sched = chaos.generate_recovery_schedule(seed)
        sites = [site for site, _ in sched.arms]
        assert sites[0] == faults.RANK_DEATH
        assert all(s in chaos.RECOVERY_SITES for s in sites)
        sites_seen.update(sites)
    # the growth/straggle interleavings (join-during-recovery,
    # straggle-then-die) are part of the generated vocabulary
    assert faults.RANK_JOIN in sites_seen
    assert faults.COMPUTE_STRAGGLE in sites_seen
    assert chaos.generate_recovery_schedule(3) == \
        chaos.generate_recovery_schedule(3)


@pytest.mark.slow
def test_recovery_soak_long():
    """Wider randomized rank-death soak; excluded from tier-1."""
    runner = chaos.RecoveryChaosRunner(num_nodes=4, size=1 << 11)
    outcomes, summary = chaos.soak_recovery(30, base_seed=2000,
                                            runner=runner)
    assert summary["violations"] == 0, [
        o.to_json() for o in outcomes if o.status == chaos.VIOLATION]
    assert summary["wdogtrip"] == 0
    assert summary["ranklost"] >= 5


# --------------------------------------------------- 2-process SIGKILL test
def test_two_process_sigkill_recovery(tmp_path):
    """THE multi-rank recovery scenario: two real jax.distributed CPU
    processes; the victim SIGKILLs itself mid-join (no cleanup, no
    goodbye); the survivor detects the lapse, recovers host-side, and
    exits 0 with the exact oracle count, RANKLOST=1, and a recovered
    results line — never a hang."""
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lease_dir = str(tmp_path / "leases")
    procs = []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(rank),
            PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        argv = [sys.executable, "-m", "tpu_radix_join.main",
                "--tuples-per-node", "1024", "--nodes", "8", "--hosts", "2",
                "--network-fanout", "3", "--elastic", "on",
                "--rank-lease-s", "2.0", "--lease-dir", lease_dir]
        if rank == 1:
            # the victim: really dies (SIGKILL) at its 2nd phase boundary
            env["TPU_RJ_RANK_DEATH_SUICIDE"] = "1"
            argv += ["--rank-death-at", "2"]
        procs.append(subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True, cwd=repo))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    joined = "\n---- rank boundary ----\n".join(outs)
    assert procs[1].returncode == -9, joined        # SIGKILL, as injected
    assert procs[0].returncode == 0, joined         # survivor recovered
    assert "[RESULTS] recovered:" in outs[0], joined
    assert "[RESULTS] Expected: 8192 (OK)" in outs[0], joined
    assert "RANKLOST\t1" in outs[0], joined
    assert "MEPOCH\t1" in outs[0], joined


# ---------------------------------------------- 2->3 process elastic growth
def test_two_to_three_process_elastic_join(tmp_path):
    """THE growth scenario, with real processes: a newcomer boots FIRST
    (its ``joining`` lease predates the incumbents' first boundary scan),
    two jax.distributed incumbents admit it with one fenced epoch bump
    and re-expand the plan over the grown membership, the newcomer
    executes its share through the shared manifest, and all THREE exit 0
    oracle-exact — the admission mirror of the SIGKILL test above."""
    import socket
    import time

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lease_dir = str(tmp_path / "leases")
    ckpt_dir = str(tmp_path / "ckpt")
    base = ["--tuples-per-node", "1024", "--nodes", "8",
            "--network-fanout", "3", "--elastic", "on",
            "--rank-lease-s", "5.0", "--lease-dir", lease_dir,
            "--checkpoint-dir", ckpt_dir]

    def spawn(argv_extra, env_extra):
        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "XLA_FLAGS",
                            "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                            "JAX_PROCESS_ID")}
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        env.update(env_extra)
        return subprocess.Popen(
            [sys.executable, "-m", "tpu_radix_join.main"] + base
            + argv_extra,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True, cwd=repo)

    # the newcomer first: a plain single process, no coordinator — its
    # joining lease must be on disk before the incumbents' first scan
    joiner = spawn(["--elastic-join", "2"], {})
    deadline = time.monotonic() + 60.0
    lease_path = os.path.join(lease_dir, "lease_r2.json")
    while time.monotonic() < deadline and not os.path.exists(lease_path):
        assert joiner.poll() is None, joiner.communicate()[0]
        time.sleep(0.1)
    assert os.path.exists(lease_path), "joining lease never appeared"

    incumbents = [
        spawn(["--hosts", "2", "--elastic-grow"],
              {"JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
               "JAX_NUM_PROCESSES": "2", "JAX_PROCESS_ID": str(rank)})
        for rank in range(2)]
    procs = incumbents + [joiner]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    joined = "\n---- rank boundary ----\n".join(outs)
    assert [p.returncode for p in procs] == [0, 0, 0], joined
    # the incumbents admitted, fenced once, and re-expanded
    assert "[RESULTS] regrown:" in outs[0], joined
    assert "[RESULTS] Expected: 8192 (OK)" in outs[0], joined
    assert "[RESULTS] RANKJOIN: max 1" in outs[0], joined
    assert "[RESULTS] MEPOCH: max 1" in outs[0], joined
    # the newcomer was admitted, did real work, and saw the manifest
    # reach completeness — oracle-exact from its own seat
    assert "[RESULTS] joiner: rank=2 epoch=1" in outs[2], joined
    assert "(OK)" in outs[2], joined
