"""Elastic mesh recovery (robustness/membership.py + recovery.py): lease
lifecycle and epoch fencing, the partition manifest's resume invariants,
the recovery planner/executor against the size oracle, the engine-level
rank-death → recovered-join path at every phase boundary, the rank-death
chaos mini-soak, and the REAL 2-process SIGKILL recovery (victim dies
mid-run; the survivor finishes oracle-exact with RANKLOST=1).  The
randomized larger soak rides behind ``-m slow``."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_radix_join.robustness import chaos, faults
from tpu_radix_join.robustness.checkpoint import (AsyncCheckpointWriter,
                                                  CheckpointManager,
                                                  CheckpointMismatch,
                                                  PartitionManifest)
from tpu_radix_join.robustness.membership import (LeaseBoard, MembershipView,
                                                  RankLost, StaleEpoch)
from tpu_radix_join.robustness.recovery import (execute_recovery, host_keys,
                                                partition_weights,
                                                plan_recovery)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# --------------------------------------------------------------- membership
def test_lease_heartbeat_round_trip(tmp_path):
    board = LeaseBoard(str(tmp_path), rank=1, num_ranks=3, lease_s=5.0)
    rec = board.heartbeat(epoch=2)
    assert rec["rank"] == 1 and rec["epoch"] == 2
    lease = board.read(1)
    assert lease.rank == 1 and lease.epoch == 2 and lease.seq == 1
    board.heartbeat(epoch=2)
    assert board.read(1).seq == 2


def test_lapse_detection_and_startup_grace(tmp_path):
    clk = FakeClock()
    a = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0, clock=clk)
    b = LeaseBoard(str(tmp_path), rank=1, num_ranks=2, lease_s=5.0, clock=clk)
    b.heartbeat()
    assert a.lapsed() == []            # fresh lease
    clk.t += 4.0
    assert a.lapsed() == []            # inside the window
    clk.t += 2.0
    assert a.lapsed() == [1]           # aged out
    # startup grace: a rank that never wrote a lease lapses only once a
    # full window has passed since board creation
    c = LeaseBoard(str(tmp_path / "g"), rank=0, num_ranks=2, lease_s=5.0,
                   clock=clk)
    assert c.lapsed() == []
    clk.t += 6.0
    assert c.lapsed() == [1]


def test_torn_lease_reads_as_absent(tmp_path):
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0)
    with open(board.lease_path(1), "w") as f:
        f.write('{"rank": 1, "epo')           # torn mid-write
    assert board.read(1) is None


def test_membership_one_epoch_bump_per_batch(tmp_path):
    from tpu_radix_join.performance.measurements import (MEPOCH, RANKLOST,
                                                         Measurements)
    clk = FakeClock()
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=4, lease_s=5.0,
                       clock=clk)
    m = Measurements()
    view = MembershipView(board, measurements=m)
    for r in (1, 2, 3):
        LeaseBoard(str(tmp_path), rank=r, num_ranks=4, lease_s=5.0,
                   clock=clk).heartbeat()
    assert view.check() == []
    clk.t += 10.0                      # all three peers lapse together
    assert view.check() == [1, 2, 3]
    assert view.epoch == 1             # ONE fence for the batch
    assert m.counters[MEPOCH] == 1 and m.counters[RANKLOST] == 3
    assert view.check() == []          # already declared: no re-bump
    assert view.survivors == [0]


def test_epoch_fence_and_require_live(tmp_path):
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0)
    view = MembershipView(board)
    view.fence(0)                      # current epoch passes
    epoch = view.declare_lost(1, cause="test")
    assert epoch == 1
    with pytest.raises(StaleEpoch) as ei:
        view.fence(0)
    assert ei.value.failure_class == "rank_lost"
    assert (ei.value.stamped, ei.value.current) == (0, 1)
    with pytest.raises(RankLost):
        view.require_live(1)


def test_suspect_triage(tmp_path):
    clk = FakeClock()
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=2, lease_s=5.0,
                       clock=clk)
    peer = LeaseBoard(str(tmp_path), rank=1, num_ranks=2, lease_s=5.0,
                      clock=clk)
    peer.heartbeat()
    view = MembershipView(board)
    assert view.suspect() is None      # all peers live: hang verdict stands
    clk.t += 10.0
    exc = view.suspect()
    assert isinstance(exc, RankLost) and exc.rank == 1
    assert exc.bundle_extra["membership_epoch"] == 1


def test_sampler_extra_heartbeats(tmp_path):
    board = LeaseBoard(str(tmp_path), rank=0, num_ranks=1, lease_s=5.0)
    view = MembershipView(board)
    extra = board.sampler_extra(epoch_of=view.epoch_of)
    rec = extra()
    assert rec["lease"]["rank"] == 0 and rec["lease"]["epoch"] == 0
    assert board.read(0).seq == rec["lease"]["seq"]


# --------------------------------------------------------- partition manifest
def test_manifest_resume_later_lines_win(tmp_path):
    path = str(tmp_path / "parts.manifest")
    man = PartitionManifest(path, fingerprint={"tag": "a"})
    assert man.mark_done(0, 100, owner=0)
    assert man.mark_done(1, 200, owner=1, epoch=0)
    assert man.mark_done(1, 250, owner=2, epoch=1)   # re-realized post-fence
    done = PartitionManifest(path, fingerprint={"tag": "a"}).completed()
    assert done[0]["count"] == 100
    assert done[1] == {"count": 250, "owner": 2, "epoch": 1}


def test_manifest_fingerprint_guard(tmp_path):
    path = str(tmp_path / "parts.manifest")
    PartitionManifest(path, fingerprint={"tag": "a"}).mark_done(0, 1, 0)
    with pytest.raises(CheckpointMismatch):
        PartitionManifest(path, fingerprint={"tag": "b"})


def test_manifest_torn_line_skipped(tmp_path):
    path = str(tmp_path / "parts.manifest")
    man = PartitionManifest(path, fingerprint={"tag": "a"})
    man.mark_done(0, 100, owner=0)
    with open(path, "a") as f:
        f.write('{"partition": 1, "cou')         # SIGKILL mid-append
    done = PartitionManifest(path, fingerprint={"tag": "a"}).completed()
    assert done == {0: {"count": 100, "owner": 0, "epoch": 0}}


def test_manifest_mark_many(tmp_path):
    man = PartitionManifest(str(tmp_path / "m"), fingerprint={"t": 1})
    n = man.mark_many({0: 10, 3: 30}, owner_of=lambda p: p % 2, epoch=2)
    assert n == 2
    done = man.completed()
    assert done[3] == {"count": 30, "owner": 1, "epoch": 2}


# ------------------------------------------------- async writer exit guarantee
def test_async_writer_close_idempotent_and_context_flush(tmp_path):
    """Regression for the write-behind exit guarantee: a state enqueued
    just before ``with``-exit must be on disk afterwards, and close() must
    be safe to call again (explicitly and from the atexit hook)."""
    mgr = CheckpointManager(str(tmp_path / "c.ckpt"), fingerprint={"t": 1})
    with AsyncCheckpointWriter(mgr) as w:
        w.save({"pairs": 7})
    # context exit closed (and therefore flushed) the queue
    assert mgr.load()["pairs"] == 7
    w.close()                                    # idempotent re-close
    w.save({"pairs": 8})                         # enqueue after close...
    w.close()
    assert mgr.load()["pairs"] == 7              # ...is never written


def test_async_writer_atexit_registered(tmp_path):
    import atexit
    mgr = CheckpointManager(str(tmp_path / "c.ckpt"), fingerprint={"t": 1})
    w = AsyncCheckpointWriter(mgr)
    try:
        # the exit guarantee exists iff close is on the atexit table;
        # unregister returns None either way, so probe the private table
        # via a second register/unregister cycle being harmless and the
        # thread being alive until close
        assert w._thread.is_alive()
        w.save({"pairs": 1})
        w.flush()
        assert mgr.load()["pairs"] == 1
    finally:
        w.close()
    assert not w._thread.is_alive()


# ------------------------------------------------------------ recovery planner
def test_plan_recovery_resume_and_reassignment():
    class _Man:
        def completed(self):
            return {0: {"count": 5, "owner": 0, "epoch": 0},
                    7: {"count": 9, "owner": 3, "epoch": 0}}

    plan = plan_recovery(num_nodes=4, num_partitions=8, lost_ranks=[3],
                         epoch=1, manifest=_Man())
    assert plan.survivors == (0, 1, 2)
    assert plan.resumed == {0: 5, 7: 9}
    assert plan.recompute == (1, 2, 3, 4, 5, 6)
    # every recompute partition lands on a survivor, never the dead rank
    assert set(plan.reassignment) == set(plan.recompute)
    assert all(r in plan.survivors for r in plan.reassignment.values())
    # deterministic: every survivor computes the identical map
    again = plan_recovery(num_nodes=4, num_partitions=8, lost_ranks=[3],
                          epoch=1, manifest=_Man())
    assert again.reassignment == plan.reassignment
    d = plan.to_diag()
    assert d["recovered"] is True and d["membership_epoch"] == 1
    assert d["resumed_partitions"] == [0, 7]


def test_plan_recovery_no_survivors_raises():
    with pytest.raises(RankLost):
        plan_recovery(num_nodes=2, num_partitions=4, lost_ranks=[0, 1],
                      epoch=1)


def test_execute_recovery_oracle_exact():
    """Recomputing every partition from host key lanes reproduces the size
    oracle exactly; resumed counts are trusted (never recomputed)."""
    n = 1 << 10
    num_p = 8
    rng = np.random.default_rng(3)
    rk = (rng.permutation(n) + 1).astype(np.uint32)
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    plan = plan_recovery(num_nodes=4, num_partitions=num_p, lost_ranks=[3],
                         epoch=1,
                         weights=partition_weights(rk, sk, num_p))
    matches, counts = execute_recovery(plan, rk, sk, slab=n)
    assert matches == n
    assert sorted(counts) == list(range(num_p))
    # only_rank as an int and as an iterable both restrict to that
    # survivor's share, and the shares tile the recompute set
    total = 0
    for r in plan.survivors:
        sub, _ = execute_recovery(plan, rk, sk, slab=n, only_rank=r)
        total += sub
    assert total == n
    it_matches, _ = execute_recovery(plan, rk, sk, slab=n,
                                     only_rank=list(plan.survivors))
    assert it_matches == n


def test_execute_recovery_resumes_partial_manifest(tmp_path):
    """A manifest holding half the partitions turns recovery into a
    HALF-recompute: RECOVERN stays strictly below the partition count (the
    acceptance-bar signal that resume was partition-granular)."""
    from tpu_radix_join.performance.measurements import (RECOVERN,
                                                         Measurements)
    n, num_p = 1 << 10, 8
    rng = np.random.default_rng(4)
    rk = (rng.permutation(n) + 1).astype(np.uint32)
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    # true per-partition counts: every S key matches exactly one R key
    true = np.bincount(sk & (num_p - 1), minlength=num_p)
    man = PartitionManifest(str(tmp_path / "m"), fingerprint={"t": 1})
    man.mark_many({p: int(true[p]) for p in range(4)},
                  owner_of=lambda p: p % 4)
    m = Measurements()
    plan = plan_recovery(num_nodes=4, num_partitions=num_p, lost_ranks=[3],
                         epoch=1, manifest=man)
    assert plan.recompute == (4, 5, 6, 7)
    matches, _ = execute_recovery(plan, rk, sk, slab=n, measurements=m,
                                  manifest=man)
    assert matches == n
    assert 0 < m.counters[RECOVERN] < num_p
    # the recompute appended post-realization lines: a NEXT recovery
    # resumes everything
    assert len(man.completed()) == num_p


def test_host_keys_regenerates_global_relation():
    from tpu_radix_join.data.relation import Relation
    rel = Relation(1 << 10, 4, "unique", seed=7)
    keys, hi = host_keys(rel)
    assert hi is None
    assert len(keys) == 1 << 10
    assert sorted(keys) == list(range(1 << 10))   # a permutation of 0..n-1


# ------------------------------------------------------- engine elastic path
@pytest.fixture(scope="module")
def elastic_engine():
    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.operators.hash_join import HashJoin
    cfg = JoinConfig(num_nodes=4, network_fanout_bits=3, verify="check")
    eng = HashJoin(cfg)
    eng.elastic = True
    return eng


def _oracle_batches(n, seed=0):
    import jax.numpy as jnp
    from tpu_radix_join.data.tuples import TupleBatch
    rng = np.random.default_rng(seed)
    rk = (rng.permutation(n) + 1).astype(np.uint32)
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    rid = np.arange(n, dtype=np.uint32)
    return (TupleBatch(key=jnp.asarray(rk), rid=jnp.asarray(rid)),
            TupleBatch(key=jnp.asarray(sk), rid=jnp.asarray(rid)),
            rk, sk)


@pytest.mark.parametrize("at", [1, 2, 3])
def test_engine_recovers_rank_death_at_each_boundary(elastic_engine, at):
    """The tentpole invariant at engine level: an injected rank death at
    ANY phase boundary ends in the exact oracle count with the full
    recovery record in diagnostics — never a hang, never an overclaim."""
    from tpu_radix_join.performance.measurements import (MEPOCH, RANKLOST,
                                                         RECOVERN,
                                                         Measurements)
    n = 1 << 11
    r, s, _, _ = _oracle_batches(n, seed=1)
    m = Measurements()
    elastic_engine.measurements = m
    with faults.FaultInjector(seed=at, measurements=m).arm(
            faults.RANK_DEATH, at=at):
        result = elastic_engine.join_arrays(r, s)
    assert result.ok
    assert result.matches == n
    d = result.diagnostics
    assert d["recovered"] is True
    assert d["membership_epoch"] >= 1
    assert d["lost_ranks"] == [3]
    assert m.counters[RANKLOST] == 1 and m.counters[MEPOCH] == 1
    assert m.counters[RECOVERN] == len(d["recovered_partitions"])


def test_engine_manifest_resume_bounds_recompute(tmp_path, elastic_engine):
    """With a partition manifest holding half the partitions' true counts,
    the engine's recovery resumes them: RECOVERN < partition count and the
    spliced total still hits the oracle."""
    from tpu_radix_join.performance.measurements import (RECOVERN,
                                                         Measurements)
    n, num_p = 1 << 11, 8
    r, s, _, sk = _oracle_batches(n, seed=2)
    true = np.bincount(sk & (num_p - 1), minlength=num_p)
    man = PartitionManifest(str(tmp_path / "m"), fingerprint={"t": 1})
    man.mark_many({p: int(true[p]) for p in range(4)},
                  owner_of=lambda p: p % 4)
    m = Measurements()
    elastic_engine.measurements = m
    elastic_engine.partition_manifest = man
    try:
        with faults.FaultInjector(seed=9, measurements=m).arm(
                faults.RANK_DEATH, at=2):
            result = elastic_engine.join_arrays(r, s)
    finally:
        elastic_engine.partition_manifest = None
    assert result.ok and result.matches == n
    assert result.diagnostics["resumed_partitions"] == [0, 1, 2, 3]
    assert 0 < m.counters[RECOVERN] < num_p


def test_non_elastic_engine_classifies_rank_death():
    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.operators.hash_join import HashJoin
    from tpu_radix_join.performance.measurements import Measurements
    eng = HashJoin(JoinConfig(num_nodes=4, network_fanout_bits=3))
    n = 1 << 10
    r, s, _, _ = _oracle_batches(n, seed=5)
    m = Measurements()
    eng.measurements = m
    with pytest.raises(RankLost) as ei:
        with faults.FaultInjector(seed=1, measurements=m).arm(
                faults.RANK_DEATH, at=1):
            eng.join_arrays(r, s)
    assert ei.value.failure_class == "rank_lost"


def test_membership_epoch_fences_compile_cache(elastic_engine):
    """The compile-key prefix: the same program recompiles (different key)
    once the membership epoch moves — stale mesh-shape programs can never
    be replayed across a fence."""
    fp0 = elastic_engine._cache_config_fp()
    assert fp0["membership_epoch"] == elastic_engine._membership_epoch()


# ------------------------------------------------------------ chaos mini-soak
def test_recovery_mini_soak_fixed_seeds():
    """Acceptance gate: rank-death schedules at every phase boundary end
    oracle-exact (PASS, recovered) or classified — zero violations, zero
    watchdog deaths, and at least one actual recovery in the batch."""
    runner = chaos.RecoveryChaosRunner(num_nodes=4, size=1 << 11)
    outcomes, summary = chaos.soak_recovery(4, base_seed=100, runner=runner)
    assert summary["violations"] == 0, [
        o.to_json() for o in outcomes if o.status == chaos.VIOLATION]
    assert summary["wdogtrip"] == 0
    assert summary["ranklost"] >= 1
    assert summary["recovered_partitions"] >= 1
    assert summary["max_epoch"] >= 1


def test_generate_recovery_schedule_always_arms_rank_death():
    for seed in range(20):
        sched = chaos.generate_recovery_schedule(seed)
        sites = [site for site, _ in sched.arms]
        assert sites[0] == faults.RANK_DEATH
        assert all(s in chaos.RECOVERY_SITES for s in sites)
    assert chaos.generate_recovery_schedule(3) == \
        chaos.generate_recovery_schedule(3)


@pytest.mark.slow
def test_recovery_soak_long():
    """Wider randomized rank-death soak; excluded from tier-1."""
    runner = chaos.RecoveryChaosRunner(num_nodes=4, size=1 << 11)
    outcomes, summary = chaos.soak_recovery(30, base_seed=2000,
                                            runner=runner)
    assert summary["violations"] == 0, [
        o.to_json() for o in outcomes if o.status == chaos.VIOLATION]
    assert summary["wdogtrip"] == 0
    assert summary["ranklost"] >= 5


# --------------------------------------------------- 2-process SIGKILL test
def test_two_process_sigkill_recovery(tmp_path):
    """THE multi-rank recovery scenario: two real jax.distributed CPU
    processes; the victim SIGKILLs itself mid-join (no cleanup, no
    goodbye); the survivor detects the lapse, recovers host-side, and
    exits 0 with the exact oracle count, RANKLOST=1, and a recovered
    results line — never a hang."""
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lease_dir = str(tmp_path / "leases")
    procs = []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(rank),
            PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        argv = [sys.executable, "-m", "tpu_radix_join.main",
                "--tuples-per-node", "1024", "--nodes", "8", "--hosts", "2",
                "--network-fanout", "3", "--elastic", "on",
                "--rank-lease-s", "2.0", "--lease-dir", lease_dir]
        if rank == 1:
            # the victim: really dies (SIGKILL) at its 2nd phase boundary
            env["TPU_RJ_RANK_DEATH_SUICIDE"] = "1"
            argv += ["--rank-death-at", "2"]
        procs.append(subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True, cwd=repo))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    joined = "\n---- rank boundary ----\n".join(outs)
    assert procs[1].returncode == -9, joined        # SIGKILL, as injected
    assert procs[0].returncode == 0, joined         # survivor recovered
    assert "[RESULTS] recovered:" in outs[0], joined
    assert "[RESULTS] Expected: 8192 (OK)" in outs[0], joined
    assert "RANKLOST\t1" in outs[0], joined
    assert "MEPOCH\t1" in outs[0], joined
