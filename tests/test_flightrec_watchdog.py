"""ISSUE 8 end to end: the always-on flight recorder, the hang watchdog,
plan-vs-actual drift telemetry, and post-mortem forensics bundles.

Acceptance criteria covered directly:

  * a simulated hang (``backend.stall`` fault site) under a running
    watchdog terminates as a *classified* ``backend_unavailable`` failure
    within the watchdog timeout — never a silent stall — and leaves a
    bundle carrying all-thread stacks and the plan-vs-actual table;
  * every planned strategy exercised here emits a ``PLANDRIFT`` gauge the
    regression gate pins lower-is-better;
  * a chaos VIOLATION's shrunk repro artifact names its forensics bundle;
  * bundles round-trip through the tools_postmortem.py renderer/merger.
"""

import json
import os
import time

import pytest

from tpu_radix_join.observability import postmortem
from tpu_radix_join.observability.flightrec import (FlightRecorder,
                                                    dump_all_stacks)
from tpu_radix_join.observability.watchdog import (HangDetected, Watchdog,
                                                   engine_killer)
from tpu_radix_join.performance.measurements import (PLANDRIFT, PMBUNDLE,
                                                     WDOGTRIP, Measurements)
from tpu_radix_join.planner.audit import (actuals_for_explain, audit_plan,
                                          phase_snapshot)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ flight recorder

def test_ring_bounded_and_ordered():
    fr = FlightRecorder(capacity=8)
    for i in range(20):
        fr.record("event", f"e{i}")
    snap = fr.snapshot()
    assert snap["capacity"] == 8 and snap["recorded"] == 20
    assert len(snap["records"]) == 8
    # oldest evicted, newest retained, in order
    assert [r["name"] for r in snap["records"]] == [f"e{i}"
                                                    for i in range(12, 20)]


def test_ring_context_stamps_and_clears():
    fr = FlightRecorder(capacity=4)
    fr.set_context(query_id="q7", tenant="t")
    fr.record("incr", "X", by=1)
    fr.clear_context("query_id", "tenant")
    fr.record("incr", "Y", by=1)
    recs = fr.records()
    assert recs[0]["query_id"] == "q7" and recs[0]["tenant"] == "t"
    assert "query_id" not in recs[1]


def test_ring_idle_clock():
    fr = FlightRecorder(capacity=4)
    fr.record("event", "tick")
    t0 = fr.idle_s()
    time.sleep(0.05)
    assert fr.idle_s() >= t0 + 0.04


def test_measurements_ring_always_on():
    """The recorder exists on EVERY registry — no tracer, no flag."""
    m = Measurements(node_id=0, num_nodes=1)
    assert isinstance(m.flightrec, FlightRecorder)
    m.start("JTOTAL")
    m.incr("RETRYN", 2)
    m.event("plan_decision", strategy="x")
    m.stop("JTOTAL")
    kinds = [r["kind"] for r in m.flightrec.records()]
    assert kinds == ["begin", "incr", "event", "end"]
    end = m.flightrec.records()[-1]
    assert end["name"] == "JTOTAL" and end["us"] >= 0


def test_dump_all_stacks_sees_this_thread():
    stacks = dump_all_stacks()
    assert any("MainThread" in label for label in stacks)
    joined = "\n".join(fr for frames in stacks.values() for fr in frames)
    assert "test_dump_all_stacks_sees_this_thread" in joined


# ------------------------------------------------------------------ watchdog

def _planned(nodes, per_node, repeats=1):
    from tpu_radix_join.planner import Workload, load_profile, plan_join
    profile = load_profile("v5e_lite")
    plan, costs = plan_join(profile, Workload(
        r_tuples=per_node * nodes, s_tuples=per_node * nodes,
        key_bound=per_node * nodes, num_nodes=nodes, repeats=repeats))
    return plan, costs


def test_watchdog_kills_stalled_join(tmp_path):
    """The tentpole scenario: a hung collective (simulated via the
    ``backend.stall`` site) under a running watchdog terminates within
    the watchdog timeout as classified ``backend_unavailable``, with a
    bundle carrying all-thread stacks + the plan-vs-actual table from
    the join that preceded the hang."""
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.robustness import faults

    nodes, per_node = 2, 2048
    m = Measurements(node_id=0, num_nodes=nodes)
    eng = HashJoin(JoinConfig(num_nodes=nodes), measurements=m)
    rb = eng.place(Relation(per_node * nodes, nodes, "unique", seed=3))
    sb = eng.place(Relation(per_node * nodes, nodes, "unique", seed=4))

    # one healthy planned join first: the audit stamps plan_vs_actual so
    # the hang's bundle carries the predicted-vs-measured table
    plan, _ = _planned(nodes, per_node)
    times0 = phase_snapshot(m)
    res = eng.join_arrays(rb, sb)
    assert res.ok
    table = audit_plan(plan, m, times0=times0)
    assert table is not None

    inj = faults.FaultInjector(seed=1, measurements=m)
    inj.arm(faults.BACKEND_STALL, at=1)
    timeout_s = 0.5
    wd = Watchdog(m, timeout_s=timeout_s, kill=engine_killer(eng),
                  bundle_dir=str(tmp_path))
    t0 = time.monotonic()
    with pytest.raises(HangDetected) as ei:
        with inj, wd:
            eng.join_arrays(rb, sb)
    elapsed = time.monotonic() - t0
    # trip + kill must land within the timeout plus poll/dump slack, far
    # from the 120s stall cap that guards unwatched runs
    assert elapsed < timeout_s + 10.0
    assert ei.value.failure_class == "backend_unavailable"
    assert wd.tripped and m.counters[WDOGTRIP] == 1

    bundles = postmortem.list_bundles(str(tmp_path))
    assert len(bundles) == 1
    b = postmortem.load_bundle(bundles[0])
    assert b["reason"] == "watchdog_trip"
    assert b["failure_class"] == "backend_unavailable"
    assert b["stacks"], "watchdog bundle must carry all-thread stacks"
    assert "JTOTAL" in b["open_phases"]
    # the plan-vs-actual table in the bundle is the registry's own
    assert b["plan_vs_actual"] == m.meta["plan_vs_actual"]
    assert b["counters"].get("PMBUNDLE", 0) == 0  # snapshot pre-increment
    assert m.counters[PMBUNDLE] == 1


def test_watchdog_no_trip_on_healthy_join(tmp_path):
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.data.relation import Relation

    m = Measurements(node_id=0, num_nodes=2)
    eng = HashJoin(JoinConfig(num_nodes=2), measurements=m)
    rb = eng.place(Relation(4096, 2, "unique", seed=5))
    sb = eng.place(Relation(4096, 2, "unique", seed=6))
    with Watchdog(m, timeout_s=30.0, kill=engine_killer(eng),
                  bundle_dir=str(tmp_path)) as wd:
        res = eng.join_arrays(rb, sb)
    assert res.ok and not wd.tripped
    assert postmortem.list_bundles(str(tmp_path)) == []
    assert WDOGTRIP not in m.counters


def test_stall_cap_classifies_without_watchdog(monkeypatch):
    """An UNwatched stalled join must still terminate classified: the env
    cap bounds the stall loop and raises the site's TransientFault."""
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.robustness import faults

    monkeypatch.setenv("TPU_RADIX_STALL_CAP_S", "0.2")
    m = Measurements(node_id=0, num_nodes=2)
    eng = HashJoin(JoinConfig(num_nodes=2), measurements=m)
    rb = eng.place(Relation(4096, 2, "unique", seed=7))
    sb = eng.place(Relation(4096, 2, "unique", seed=8))
    inj = faults.FaultInjector(seed=2, measurements=m)
    inj.arm(faults.BACKEND_STALL, at=1)
    with pytest.raises(faults.TransientFault) as ei:
        with inj:
            eng.join_arrays(rb, sb)
    assert ei.value.failure_class == "backend_unavailable"
    assert "JTOTAL" not in m._starts     # the timer was closed on the way out


# ------------------------------------------------------- plan-vs-actual audit

def test_audit_emits_plandrift_incore():
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.planner import explain_table

    nodes, per_node = 2, 2048
    m = Measurements(node_id=0, num_nodes=nodes)
    eng = HashJoin(JoinConfig(num_nodes=nodes), measurements=m)
    rb = eng.place(Relation(per_node * nodes, nodes, "unique", seed=9))
    sb = eng.place(Relation(per_node * nodes, nodes, "unique", seed=10))
    plan, costs = _planned(nodes, per_node)
    assert plan.predicted_terms, "plan schema v4 carries per-term breakdown"

    times0 = phase_snapshot(m)
    assert eng.join_arrays(rb, sb).ok
    table = audit_plan(plan, m, times0=times0)
    assert table["strategy"] == plan.strategy
    assert table["actual_ms"] > 0 and table["predicted_ms"] > 0
    assert table["drift_pct"] == pytest.approx(
        100.0 * abs(table["actual_ms"] - table["predicted_ms"])
        / table["predicted_ms"], abs=0.01)
    assert m.counters[PLANDRIFT] == int(round(table["drift_pct"]))
    assert m.meta["plan_vs_actual"] is table
    # term rows keep the cost model's vocabulary
    assert {r["term"] for r in table["terms"]} == set(plan.predicted_terms)

    # the explain table grows actual_ms/drift% on the chosen row only
    rendered = explain_table(costs, plan, actuals=actuals_for_explain(table))
    assert "actual_ms" in rendered and "drift%" in rendered
    chosen_line = next(l for l in rendered.splitlines() if "*" in l)
    assert f"{table['actual_ms']:.1f}" in chosen_line


def test_audit_chunked_strategy_and_delta_semantics():
    """A second audit on an accumulated registry measures only the LAST
    join (delta vs the times0 snapshot), and the chunked vocabulary
    audits through the same path."""
    m = Measurements(node_id=0, num_nodes=1)
    m.start("JTOTAL")
    time.sleep(0.01)
    m.stop("JTOTAL")
    first = dict(m.times_us)
    plan = {"strategy": "chunked_grid", "engine": "chunked",
            "predicted_ms": 10.0, "profile_name": "v5e_lite",
            "predicted_terms": {"sort": 4.0, "scan": 2.0, "dispatch": 4.0}}
    t1 = audit_plan(plan, m, times0={k: 0.0 for k in first})
    assert t1 is not None and t1["strategy"] == "chunked_grid"
    # accumulate a second, longer join; the delta audit must not blend in
    # the first join's time
    times0 = phase_snapshot(m)
    m.start("JTOTAL")
    time.sleep(0.03)
    m.stop("JTOTAL")
    t2 = audit_plan(plan, m, times0=times0)
    assert 0 < t2["actual_ms"] < t1["actual_ms"] + 60.0
    assert t2["actual_ms"] < m.times_us["JTOTAL"] / 1e3  # delta, not total
    assert PLANDRIFT in m.counters


def test_audit_none_paths():
    m = Measurements(node_id=0, num_nodes=1)
    assert audit_plan(None, m) is None           # no plan -> no audit
    plan = {"strategy": "s", "engine": "incore", "predicted_ms": 1.0}
    assert audit_plan(plan, None) is None        # no registry -> no audit
    assert audit_plan(plan, m) is None           # no measured JTOTAL
    assert actuals_for_explain(None) is None


def test_driver_plan_auto_audits(capsys):
    """The CLI path: --plan auto prints the drift line + actuals table
    and stores PLANDRIFT in the perf artifact."""
    from tpu_radix_join.main import main
    rc = main(["--tuples-per-node", "2048", "--nodes", "2",
               "--plan", "auto", "--profile", "v5e_lite"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[PLAN] actual_ms=" in out and "drift=" in out
    assert "actual_ms" in out          # explain table actuals column
    assert "PLANDRIFT" in out          # [PERF] counter line


# ------------------------------------------------------------------- bundles

def test_bundle_roundtrip_render_merge(tmp_path):
    m = Measurements(node_id=3, num_nodes=4)
    m.flightrec.set_context(query_id="q42")
    m.start("JTOTAL")
    m.incr("RETRYN")
    path = postmortem.write_bundle(
        str(tmp_path), m, reason="query_failed",
        failure_class="data_corruption",
        config={"nodes": 4}, stacks=dump_all_stacks(),
        extra={"note": "unit"})
    b = postmortem.load_bundle(path)
    assert b["bundle_version"] == 1
    assert b["rank"] == 3 and b["nodes"] == 4
    assert b["query_id"] == "q42"
    assert b["config_fingerprint"] == postmortem.config_fingerprint(
        {"nodes": 4})
    assert b["open_phases"] == ["JTOTAL"]
    text = postmortem.render_bundle(b)
    assert "query_failed" in text and "q42" in text and "RETRYN" in text
    merged = postmortem.merge_bundles([path])
    assert merged["bundles"] == 1
    assert merged["by_reason"] == {"query_failed": 1}
    assert merged["rows"][0]["query_id"] == "q42"
    # bundle emission is itself observable
    assert m.counters[PMBUNDLE] == 1
    assert any(e.get("event") == "bundle" for e in m.meta["events"])


def test_bundle_without_measurements(tmp_path):
    """bench.py's probe-exhaustion path writes bundles with no registry."""
    path = postmortem.write_bundle(
        str(tmp_path), None, reason="backend_unavailable",
        failure_class="backend_unavailable",
        extra={"probe_attempts": 9})
    b = postmortem.load_bundle(path)
    assert b["reason"] == "backend_unavailable"
    assert "ring" not in b and b["extra"]["probe_attempts"] == 9
    assert "backend_unavailable" in postmortem.render_bundle(b)


def test_tools_postmortem_cli(tmp_path, capsys):
    import tools_postmortem
    m = Measurements(node_id=0, num_nodes=1)
    postmortem.write_bundle(str(tmp_path), m, reason="watchdog_trip",
                            failure_class="backend_unavailable")
    assert tools_postmortem.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "== bundle: watchdog_trip" in out
    assert tools_postmortem.main([str(tmp_path), "--merge"]) == 0
    out = capsys.readouterr().out
    assert "by reason:" in out and "watchdog_trip" in out
    # an unreadable input is rc=1, not a crash
    bad = tmp_path / "bundle_bad_r0_1.json"
    bad.write_text("{torn")
    assert tools_postmortem.main([str(bad)]) == 1


# ------------------------------------------------------------- chaos bundles

def test_chaos_violation_carries_bundle(tmp_path):
    """A soak VIOLATION's repro artifact names its forensics bundle; the
    bundle replays the (seed, arms) schedule."""
    from tpu_radix_join.robustness import chaos, faults

    sched = chaos.Schedule(
        seed=5, arms=((faults.EXCHANGE_CORRUPT, (("at", 1),)),))
    runner = chaos.ChaosRunner(num_nodes=4, size=1 << 12, verify="off",
                               bundle_dir=str(tmp_path))
    out = runner.run(sched)
    assert out.status == chaos.VIOLATION
    assert out.bundle and os.path.exists(out.bundle)
    assert out.to_json()["bundle"] == out.bundle
    b = postmortem.load_bundle(out.bundle)
    assert b["reason"] == "chaos_violation"
    assert b["chaos"]["seed"] == 5
    assert b["chaos"]["arms"][0][0] == faults.EXCHANGE_CORRUPT
    # repro JSON line (what tools_chaos writes) round-trips the path
    line = chaos.write_repro(out, tmp_path / "repro.json")
    assert json.loads(line)["bundle"] == out.bundle
    # a protected runner (verify=check) classifies: no bundle emitted
    protected = chaos.ChaosRunner(num_nodes=4, size=1 << 12, verify="check",
                                  bundle_dir=str(tmp_path))
    out2 = protected.run(sched)
    assert out2.status == chaos.CLASSIFIED and out2.bundle is None
    assert "bundle" not in out2.to_json()


# ------------------------------------------------------------- serve bundles

def test_session_failed_query_bundle(tmp_path):
    from tpu_radix_join.core.config import JoinConfig, ServiceConfig
    from tpu_radix_join.service import JoinSession, QueryRequest

    m = Measurements(node_id=0, num_nodes=2)
    session = JoinSession(JoinConfig(num_nodes=2), ServiceConfig(),
                          measurements=m, forensics_dir=str(tmp_path))
    try:
        session.submit(QueryRequest(query_id="dead", tuples_per_node=2048,
                                    deadline_s=1e-6))
        out = session.run_next()
        assert out.status == "failed"
        assert out.failure_class == "deadline_exceeded"
        assert out.bundle and os.path.exists(out.bundle)
        assert out.to_json()["bundle"] == out.bundle
        b = postmortem.load_bundle(out.bundle)
        assert b["reason"] == "deadline_exceeded"
        assert b["query_id"] == "dead"       # stamped via the ring context
        # the context is scoped to the query, not leaked onto the session
        assert "query_id" not in m.flightrec.context
        session.submit(QueryRequest(query_id="ok1", tuples_per_node=2048))
        ok = session.run_next()
        assert ok.status == "ok" and ok.bundle is None
        assert "bundle" not in ok.to_json()
    finally:
        session.close()


# -------------------------------------------------------- timeline / regress

def test_timeline_missing_ranks(tmp_path):
    """A 3-rank world where only rank 0 left a span file: the merge names
    the gap instead of silently narrowing the world."""
    from tpu_radix_join.observability.timeline import merge_timeline

    doc0 = {"traceEvents": [{"name": "JTOTAL", "ph": "X", "ts": 0.0,
                             "dur": 5.0, "pid": 0, "tid": 0}],
            "metadata": {"rank": 0, "epoch_s": 100.0, "trace_id": "t",
                         "tags": {"nodes": 3}}}
    (tmp_path / "0.spans.json").write_text(json.dumps(doc0))
    (tmp_path / "1.spans.json").write_text("{torn")
    merged = merge_timeline(str(tmp_path))
    md = merged["metadata"]
    assert md["expected_ranks"] == 3
    assert md["missing_ranks"] == [1, 2]
    assert md["corrupt_files"] == ["1.spans.json"]
    assert md["partial"] is True


def test_regress_pins_observability_counters():
    from tpu_radix_join.observability.regress import (compare_tags,
                                                      higher_is_better)
    for tag in ("PLANDRIFT", "PMBUNDLE", "WDOGTRIP"):
        assert not higher_is_better(tag)
    rows = compare_tags({"PLANDRIFT": 10.0, "PMBUNDLE": 0.0},
                        {"PLANDRIFT": 40.0, "PMBUNDLE": 2.0},
                        threshold=0.25)
    by = {r["tag"]: r["status"] for r in rows}
    assert by == {"PLANDRIFT": "regressed", "PMBUNDLE": "regressed"}
    rows = compare_tags({"PLANDRIFT": 10.0}, {"PLANDRIFT": 9.0})
    assert rows[0]["status"] == "ok"         # drift shrinking is fine
