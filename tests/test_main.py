"""Driver CLI tests (main.cpp analog)."""

import numpy as np

from tpu_radix_join.main import main


def test_cli_single_node(capsys, tmp_path):
    rc = main(["--tuples-per-node", "4096", "--nodes", "1",
               "--network-fanout", "4", "--output-dir", str(tmp_path / "exp")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[RESULTS] Tuples: 4096" in out
    assert "(OK)" in out
    assert "Conservation: OK" in out
    assert (tmp_path / "exp" / "0.perf").exists()


def test_cli_multi_node_zipf(capsys):
    rc = main(["--tuples-per-node", "2048", "--nodes", "8",
               "--outer-kind", "zipf", "--assignment", "load_aware"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "[RESULTS] Tuples: 16384" in out


def test_cli_measurement_tags(capsys):
    main(["--tuples-per-node", "1024", "--nodes", "2"])
    out = capsys.readouterr().out
    for tag in ("JTOTAL", "JPROC", "SWINALLOC", "RESULTS", "RTUPLES"):
        assert tag in out


def test_cli_new_flags(capsys):
    from tpu_radix_join.main import main
    rc = main(["--tuples-per-node", "4096", "--nodes", "8",
               "--chunk-size", "1024", "--max-retries", "2",
               "--debug-checks"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Conservation: OK" in out


def test_cli_measure_phases(capsys):
    rc = main(["--tuples-per-node", "2048", "--nodes", "4",
               "--measure-phases"])
    assert rc == 0
    out = capsys.readouterr().out
    for tag in ("JHIST", "JMPI", "JPROC", "SNETCOMPL"):
        assert tag in out, tag


def test_cli_repeat_reports_single_join_tuples(capsys):
    rc = main(["--tuples-per-node", "1024", "--nodes", "2", "--repeat", "3"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[RESULTS] Tuples: 2048" in out
    assert "Tuples: 6144" not in out


def test_cli_generation_modes(capsys):
    """--generation device and host produce the same exact result (the
    bit-identical generator twins); device refuses kinds with no on-device
    generator."""
    for mode in ("device", "host"):
        rc = main(["--tuples-per-node", "2048", "--nodes", "4",
                   "--generation", mode])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "[RESULTS] Tuples: 8192" in out
    # zipf generates on device since r4 (integer-table sampler): the
    # device-forced zipf run matches the unique⋈zipf covered-domain oracle
    rc = main(["--tuples-per-node", "2048", "--nodes", "4",
               "--generation", "device", "--outer-kind", "zipf"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "[RESULTS] Expected: 8192 (OK)" in out


def test_cli_trace_records_ctotal(tmp_path, capsys):
    """--trace parity (VERDICT r4 missing #3): the reference writes CTOTAL
    into every rank's perf file (Measurements.cpp:90-107,137); the CLI's
    profiler bracket must land the per-op table in .info and — whenever the
    busiest timeline is a real device plane — the CTOTAL tag in .perf."""
    import json

    out_dir = tmp_path / "exp"
    rc = main(["--tuples-per-node", "2048", "--nodes", "1",
               "--trace", "--output-dir", str(out_dir)])
    assert rc == 0, capsys.readouterr().out
    info = json.loads((out_dir / "0.info").read_text())
    assert "trace" in info and info["trace"]["ops"], "per-op table missing"
    perf = (out_dir / "0.perf").read_text()
    from tpu_radix_join.performance.trace import _is_device_plane
    if _is_device_plane(info["trace"]["plane"]):   # CPU planes carry no
        assert "CTOTAL" in perf                    # cycles analog (trace.py)


def test_cli_trace_requires_output_dir(capsys):
    import pytest
    with pytest.raises(SystemExit):
        main(["--tuples-per-node", "1024", "--trace"])


def test_cli_pipeline_repeats(capsys):
    """--pipeline-repeats: the amortized dispatch mode must report the same
    single-join tuple count and oracle status as the synchronous loop."""
    rc = main(["--tuples-per-node", "1024", "--nodes", "2", "--repeat", "3",
               "--pipeline-repeats"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[RESULTS] Tuples: 2048" in out
    assert "Expected: 2048 (OK)" in out
    assert "Throughput" in out


def test_cli_pipeline_repeats_rejects_measure_phases():
    import pytest
    with pytest.raises(SystemExit):
        main(["--tuples-per-node", "1024", "--repeat", "3",
              "--pipeline-repeats", "--measure-phases"])


def test_cli_trace_composes_with_measure_phases(tmp_path):
    """--trace + --measure-phases: the profiler bracket must span the split
    programs and still land the per-op table (the reference's PAPI bracket
    wraps its phased join the same way, Measurements.cpp:90-141)."""
    import json

    out_dir = tmp_path / "exp"
    rc = main(["--tuples-per-node", "2048", "--nodes", "4",
               "--measure-phases", "--trace", "--output-dir", str(out_dir)])
    assert rc == 0
    info = json.loads((out_dir / "0.info").read_text())
    assert "trace" in info and info["trace"]["ops"]
    perf = (out_dir / "0.perf").read_text()
    assert "JMPI" in perf and "JPROC" in perf     # split columns intact
