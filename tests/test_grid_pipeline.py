"""Pipelined out-of-core grid engine (ops/chunked.py pipeline="on"):
oracle parity with the synchronous loop, observable sort reuse and
prefetch overlap, write-behind checkpoint invariants under kill, and the
hoisted key-range bound contract checks."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_radix_join.data.relation import Relation
from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.ops.chunked import chunked_join_count, chunked_join_grid
from tpu_radix_join.ops.merge_count import MAX_MERGE_KEY
from tpu_radix_join.performance.measurements import (CKPTLOAD, GRIDPAIRS,
                                                     PREFETCH, SORTREUSE,
                                                     Measurements)
from tpu_radix_join.robustness import faults
from tpu_radix_join.robustness.checkpoint import CheckpointMismatch
from tpu_radix_join.robustness.faults import (FaultInjector, InjectedKill,
                                              TransientFault)
from tpu_radix_join.robustness.retry import RetryPolicy


def _quarters(seed, n=1 << 12):
    rel = Relation(n, 1, "unique", seed=seed)
    b = rel.shard(0)
    k, r = np.asarray(b.key), np.asarray(b.rid)
    q = n // 4
    return [TupleBatch(key=jnp.asarray(k[i * q:(i + 1) * q]),
                       rid=jnp.asarray(r[i * q:(i + 1) * q]))
            for i in range(4)]


def _random_chunks(seed, n_chunks, size=1 << 10, hi=1 << 16):
    rng = np.random.default_rng(seed)
    return [TupleBatch(key=jnp.asarray(
                           rng.integers(0, hi, size, dtype=np.uint32)),
                       rid=jnp.arange(size, dtype=jnp.uint32))
            for _ in range(n_chunks)]


def _oracle(r_chunks, s_chunks):
    from collections import Counter
    cnt = Counter(np.concatenate(
        [np.asarray(c.key) for c in r_chunks]).tolist())
    return sum(cnt[k] for k in np.concatenate(
        [np.asarray(c.key) for c in s_chunks]).tolist())


# ------------------------------------------------------------ oracle parity

def test_pipelined_matches_sync_with_duplicates():
    """Both engines return the oracle total on a duplicate-heavy 3x4 grid;
    the pipelined run shows its work: SORTREUSE == rows x (cols - 1) and
    every chunk staged through the prefetch thread."""
    r_chunks = _random_chunks(1, 3)
    s_chunks = _random_chunks(2, 4)
    oracle = _oracle(r_chunks, s_chunks)

    m_off = Measurements()
    assert chunked_join_grid(r_chunks, s_chunks, 1 << 9,
                             measurements=m_off, pipeline="off") == oracle
    assert SORTREUSE not in m_off.counters
    assert PREFETCH not in m_off.counters

    m_on = Measurements()
    assert chunked_join_grid(r_chunks, s_chunks, 1 << 9,
                             measurements=m_on, pipeline="on") == oracle
    assert m_on.counters[GRIDPAIRS] == 12
    assert m_on.counters[SORTREUSE] == 3 * (4 - 1)
    # 3 inner chunks + 4 outer chunks re-staged for each of the 3 rows
    assert m_on.counters[PREFETCH] == 3 + 3 * 4


def test_pipeline_auto_resolution():
    """auto pipelines any grid larger than 1x1 and falls back to the
    synchronous loop for a single pair (nothing to overlap)."""
    chunks = _quarters(7)
    m = Measurements()
    assert chunked_join_grid(chunks, chunks, 1 << 10, measurements=m,
                             pipeline="auto") == 1 << 12
    assert m.counters[SORTREUSE] == 4 * 3

    one = [chunks[0]]
    m1 = Measurements()
    total = chunked_join_grid(one, one, 1 << 10, measurements=m1,
                              pipeline="auto")
    assert total == 1 << 10
    assert PREFETCH not in m1.counters      # resolved to the sync loop

    with pytest.raises(ValueError, match="pipeline mode"):
        chunked_join_grid(one, one, 1 << 10, pipeline="sideways")


def test_pipelined_wide_keys():
    """Wide (hi/lo) chunks ride the pipeline too — per-pair union sort
    (no presorted probe, SORTREUSE stays 0) but prefetch still stages."""
    n = 1 << 10
    rng = np.random.default_rng(5)
    lo = rng.integers(0, 1 << 16, n, dtype=np.uint32)

    def mk():
        return TupleBatch(key=jnp.asarray(lo),
                          rid=jnp.arange(n, dtype=jnp.uint32),
                          key_hi=jnp.asarray(np.zeros(n, np.uint32)))

    chunks = [mk(), mk()]
    oracle = _oracle(chunks, chunks)
    m = Measurements()
    assert chunked_join_grid(chunks, chunks, 256, measurements=m,
                             pipeline="on") == oracle
    assert m.counters[GRIDPAIRS] == 4
    assert SORTREUSE not in m.counters
    assert m.counters[PREFETCH] > 0


# --------------------------------------------------------------- real overlap

def test_prefetch_overlaps_compute():
    """Deterministic overlap: the prefetch span that stages outer chunk
    j+1 begins BEFORE the grid_pair span of pair (i, j) ends — the
    prefetch thread is already generating the next chunk while the pair
    computes, which is the entire point of the stage."""
    r_chunks = _random_chunks(11, 2)
    s_data = _random_chunks(12, 3)

    def s_factory():
        return iter(s_data)          # generator-fed outer side

    m = Measurements()
    tr = m.attach_tracer(nodes=1)
    total = chunked_join_grid(r_chunks, s_factory, 1 << 9,
                              measurements=m, pipeline="on")
    assert total == _oracle(r_chunks, s_data)

    gp = [e for e in tr.events if e["name"] == "grid_pair"
          and e["args"].get("i") == 0 and e["args"].get("j") == 0]
    pf = [e for e in tr.events if e["name"] == "prefetch"
          and e["args"].get("side") == "outer"
          and e["args"].get("chunk") == 1]
    assert gp and pf
    gp_end = gp[0]["ts"] + gp[0]["dur"]
    # earliest chunk-1 staging (row 0's) starts inside pair (0,0)'s span
    assert min(e["ts"] for e in pf) < gp_end
    # readback and checkpoint flushes are on the timeline too
    names = {e["name"] for e in tr.events}
    assert "readback_flush" in names


# ------------------------------------------- write-behind checkpoint + kill

def test_kill_during_write_behind_no_overclaim_and_zero_recompute(tmp_path):
    """Kill the pipelined grid mid-flight: the write-behind checkpoint may
    trail the dispatch front, but every CLAIMED pair is realized (the
    stored total is exactly the claimed prefix's oracle) and never exceeds
    the dispatched count; the resume probes exactly the unclaimed pairs
    and lands on the oracle — in either engine mode."""
    r_chunks, s_chunks = _quarters(1), _quarters(1)   # diag pairs match 1024
    ckpt = str(tmp_path / "grid.ckpt")

    m1 = Measurements()
    with FaultInjector() as inj:
        inj.arm(faults.GRID_KILL, at=5, exc=InjectedKill)
        with pytest.raises(InjectedKill):
            chunked_join_grid(r_chunks, s_chunks, 1 << 10,
                              checkpoint_path=ckpt, checkpoint_tag="t",
                              measurements=m1, pipeline="on")
    dispatched = m1.counters[GRIDPAIRS]
    assert dispatched == 4               # kill fired before the 5th dispatch
    state = json.load(open(ckpt))
    assert not state["done"]
    claimed = state["i"] * state["cols"] + state["j"]
    # no over-claim: the cursor never passes the readback front, and the
    # flushed total is exactly the claimed row-major prefix's matches
    assert claimed <= dispatched
    assert claimed == 2                  # readback_depth=2 pairs in flight
    diag_in_prefix = sum(1 for p in range(claimed)
                         if p // 4 == p % 4)
    assert state["total"] == diag_in_prefix * (1 << 10)

    killed_bytes = open(ckpt, "rb").read()
    for mode in ("on", "off"):           # checkpoints are engine-portable
        with open(ckpt, "wb") as f:      # restore the killed state each leg
            f.write(killed_bytes)
        m2 = Measurements()
        total = chunked_join_grid(r_chunks, s_chunks, 1 << 10,
                                  checkpoint_path=ckpt, checkpoint_tag="t",
                                  measurements=m2, pipeline=mode)
        assert total == 1 << 12
        assert m2.counters[CKPTLOAD] >= 1
        assert m2.counters[GRIDPAIRS] == 16 - claimed   # zero recompute
        assert json.load(open(ckpt))["done"]


def test_pipelined_transient_retry():
    r_chunks, s_chunks = _quarters(2), _quarters(2)
    m = Measurements()
    with FaultInjector() as inj:
        inj.arm(faults.GRID_TRANSIENT, times=1, exc=TransientFault)
        total = chunked_join_grid(
            r_chunks, s_chunks, 1 << 10, measurements=m, pipeline="on",
            retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.0))
    assert total == 1 << 12
    assert inj.fired(faults.GRID_TRANSIENT) == 1
    assert m.counters[GRIDPAIRS] == 16


# ------------------------------------------------- extent hardening + logs

def test_generator_grid_shape_mismatch_fails_fast(tmp_path):
    """A generator-fed grid has rows/cols None in the checkpoint
    fingerprint; the discovered extents recorded in the saved state must
    fail a same-tag resume whose grid discovers a different shape instead
    of mis-resuming row-major arithmetic."""
    r_chunks = _quarters(4)
    s4 = _quarters(4)
    s5 = _random_chunks(13, 5, size=1 << 10)   # one extra outer chunk
    ckpt = str(tmp_path / "grid.ckpt")

    with FaultInjector() as inj:
        # row 0 completes (cols=4 discovered and saved) before the kill
        inj.arm(faults.GRID_KILL, at=6, exc=InjectedKill)
        with pytest.raises(InjectedKill):
            chunked_join_grid(r_chunks, lambda: iter(s4), 1 << 10,
                              checkpoint_path=ckpt, checkpoint_tag="t",
                              pipeline="off")
    assert json.load(open(ckpt))["cols"] == 4

    with pytest.raises(CheckpointMismatch, match="grid shape"):
        chunked_join_grid(r_chunks, lambda: iter(s5), 1 << 10,
                          checkpoint_path=ckpt, checkpoint_tag="t",
                          pipeline="off")


def test_resume_log_and_rate_progress(tmp_path, capsys):
    r_chunks, s_chunks = _quarters(6), _quarters(6)
    ckpt = str(tmp_path / "grid.ckpt")
    with FaultInjector() as inj:
        inj.arm(faults.GRID_KILL, at=4, exc=InjectedKill)
        with pytest.raises(InjectedKill):
            chunked_join_grid(r_chunks, s_chunks, 1 << 10,
                              checkpoint_path=ckpt, checkpoint_tag="t",
                              progress=True, pipeline="off")
    out = capsys.readouterr().out
    assert "pairs/s" in out and "eta=" in out

    total = chunked_join_grid(r_chunks, s_chunks, 1 << 10,
                              checkpoint_path=ckpt, checkpoint_tag="t",
                              progress=True, pipeline="on")
    assert total == 1 << 12
    out = capsys.readouterr().out
    assert "[grid] resume: skipping 3 completed pair(s)" in out


# --------------------------------------------------- key-bound hoist contract

def test_chunked_join_count_key_bound_contracts():
    n = 256
    keys = np.arange(n, dtype=np.uint32)
    mk = lambda k: TupleBatch(key=jnp.asarray(k),
                              rid=jnp.arange(n, dtype=jnp.uint32))
    a = chunked_join_count(mk(keys), mk(keys), 64)
    assert a == chunked_join_count(mk(keys), mk(keys), 64,
                                   key_bound=int(keys.max()))
    # the bound replaces the probe, not the checks: sentinel-range bounds
    # still classify as corruption, narrow bounds above the packing raise
    with pytest.raises(ValueError, match="sentinel"):
        chunked_join_count(mk(keys), mk(keys), 64, key_bound=0xFFFFFFFE)
    with pytest.raises(ValueError, match="key contract violation"):
        chunked_join_count(mk(keys), mk(keys), 64, key_range="narrow",
                           key_bound=MAX_MERGE_KEY + 1)
    # a full-range bound routes to the lexicographic count transparently
    big = keys.copy()
    big[0] = MAX_MERGE_KEY + 5
    got = chunked_join_count(mk(big), mk(big), 64,
                             key_bound=int(big.max()))
    assert got == n


def test_pipelined_sentinel_corruption_detected():
    """The presorted probe compares raw keys, so an inner key in the
    sentinel range would silently pad-match the outer fill — the pipeline
    must classify it as corruption instead (DataCorruption <: ValueError),
    in every key_range mode."""
    n = 512
    bad = np.arange(n, dtype=np.uint32)
    bad[3] = 0xFFFFFFFF
    mk = lambda k: TupleBatch(key=jnp.asarray(k),
                              rid=jnp.arange(n, dtype=jnp.uint32))
    chunks_bad = [mk(bad), mk(bad)]
    chunks_ok = [mk(np.arange(n, dtype=np.uint32))] * 2
    with pytest.raises(ValueError, match="sentinel"):
        chunked_join_grid(chunks_bad, chunks_ok, 128, pipeline="on")


# ----------------------------------------------------------- regress wiring

def test_grid_bench_tags_gate_in_the_right_direction():
    """--grid-bench JSON tags must regress downward-is-bad: a pipeline
    that stages fewer chunks or reuses fewer sorts silently went serial."""
    from tpu_radix_join.observability.regress import higher_is_better
    for tag in ("pairs_per_sec_pipelined", "pairs_per_sec_sync", "speedup",
                "prefetch", "sortreuse", "vs_baseline", "value"):
        assert higher_is_better(tag), tag
    for tag in ("wall_s_sync", "predicted_ms"):
        assert not higher_is_better(tag), tag
