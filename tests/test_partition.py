"""Fused Pallas radix-partition kernel (ops/pallas/partition.py) and its
wiring (ops/radix impl selection, planner pricing, fallback telemetry).

Parity contract with the sort path: histograms / counts / group_counts /
overflow are byte-equal on every input; block *membership* is multiset-
equal per (block, sub) group when overflow == 0.  Under overflow the two
paths may keep different tuples of the clipped boundary group (the
unstable sort keeps an arbitrary subset, the fused kernel keeps
first-in-input-order) — both are contract-valid because overflow != 0
already voids the result (Window retries at doubled capacity), so those
tests assert membership (every kept row is a genuine tuple of its group)
plus the byte-equal accounting, not tuple identity."""

import jax.numpy as jnp
import numpy as np
import pytest

import tpu_radix_join.ops.radix as radix
from tpu_radix_join.data.tuples import CompressedBatch
from tpu_radix_join.ops.pallas.partition import (MAX_PARTITIONS,
                                                 partition_slots_pallas)
from tpu_radix_join.ops.radix import (local_histogram, reorder_by_partition,
                                      scatter_to_blocks,
                                      scatter_to_blocks_grouped)
from tpu_radix_join.performance.measurements import (PARTFALLBACK, PARTPASS,
                                                     Measurements)

INTERP = "pallas_interpret"


def _comp(keys, rids):
    return CompressedBatch(key_rem=jnp.asarray(keys, jnp.uint32),
                           rid=jnp.asarray(rids, jnp.uint32))


def _rand(n, num_blocks, num_sub=1, seed=0, valid_p=None):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 1 << 20, n).astype(np.uint32)
    batch = _comp(keys, np.arange(n))
    dest = jnp.asarray(rng.integers(0, num_blocks, n).astype(np.uint32))
    sub = jnp.asarray(rng.integers(0, num_sub, n).astype(np.uint32))
    valid = (None if valid_p is None else
             jnp.asarray(rng.random(n) < valid_p))
    return batch, dest, sub, valid


# ----------------------------------------------------------------- kernel

def test_kernel_dense_mode_is_grouping_permutation():
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 7, 5000).astype(np.uint32)
    slots, hist = partition_slots_pallas(jnp.asarray(ids), num_groups=7,
                                         interpret=True)
    slots = np.asarray(slots)
    # a permutation: every tuple lands, each slot once
    assert sorted(slots.tolist()) == list(range(5000))
    np.testing.assert_array_equal(np.asarray(hist),
                                  np.bincount(ids, minlength=7))
    # grouped by id in id order, input order within a group
    base = np.concatenate([[0], np.cumsum(np.bincount(ids, minlength=7))])
    for g in range(7):
        mine = np.flatnonzero(ids == g)
        np.testing.assert_array_equal(np.sort(slots[mine]),
                                      np.arange(base[g], base[g + 1]))
        # input order preserved within the group
        assert (np.diff(slots[mine]) > 0).all()


def test_kernel_blocked_mode_matches_numpy_reference():
    rng = np.random.default_rng(3)
    num_groups, group_size, cap = 12, 3, 40
    ids = rng.integers(0, num_groups + 2, 700).astype(np.uint32)  # some invalid
    slots, hist = partition_slots_pallas(
        jnp.asarray(ids), num_groups=num_groups, group_size=group_size,
        capacity=cap, interpret=True)
    slots = np.asarray(slots)
    np.testing.assert_array_equal(np.asarray(hist),
                                  np.bincount(ids, minlength=num_groups
                                              )[:num_groups])
    # reference: per-destination unclipped prefix in (group, input) order
    base = np.concatenate([[0], np.cumsum(np.bincount(
        np.minimum(ids, num_groups), minlength=num_groups + 1))])[:-1]
    pos_in_group = np.zeros_like(ids)
    seen = {}
    for i, g in enumerate(ids):
        seen[g] = seen.get(g, 0) + 1
        pos_in_group[i] = seen[g] - 1
    for i, g in enumerate(ids):
        if g >= num_groups:
            assert slots[i] == 0xFFFFFFFF          # invalid -> sentinel
            continue
        blk = g // group_size
        within = base[g] - base[(g // group_size) * group_size] \
            + pos_in_group[i]
        if within >= cap:
            assert slots[i] == 0xFFFFFFFF          # overflow -> dropped
        else:
            assert slots[i] == blk * cap + within


def test_kernel_multi_tile_carry():
    # > 1 grid tile (262144 ids per tile at the max block): the SMEM
    # cursors must carry across sequential grid steps
    n = 600_000
    rng = np.random.default_rng(4)
    ids = rng.integers(0, 5, n).astype(np.uint32)
    slots, hist = partition_slots_pallas(jnp.asarray(ids), num_groups=5,
                                         interpret=True)
    slots = np.asarray(slots)
    assert sorted(slots.tolist()) == list(range(n))
    np.testing.assert_array_equal(np.asarray(hist),
                                  np.bincount(ids, minlength=5))


def test_kernel_rejects_bad_geometry():
    ids = jnp.zeros((16,), jnp.uint32)
    with pytest.raises(ValueError, match=f"> {MAX_PARTITIONS}"):
        partition_slots_pallas(ids, num_groups=MAX_PARTITIONS + 1,
                               interpret=True)
    with pytest.raises(ValueError, match="multiple"):
        partition_slots_pallas(ids, num_groups=10, group_size=4,
                               capacity=8, interpret=True)


# ------------------------------------------------- flat scatter parity

def _valid_rows(blocks, counts, cap, b):
    """The occupied prefix of block ``b`` (both impls fill contiguously)."""
    k = int(min(int(counts[b]), cap))
    lo = b * cap
    return (np.asarray(blocks.key_rem)[lo:lo + k],
            np.asarray(blocks.rid)[lo:lo + k])


@pytest.mark.parametrize("valid_p", [None, 0.7])
def test_scatter_parity_no_overflow(valid_p):
    n, nb, cap = 4000, 8, 1000
    batch, dest, _, valid = _rand(n, nb, seed=5, valid_p=valid_p)
    bs, cs, os_ = scatter_to_blocks(batch, dest, nb, cap, "inner",
                                    valid=valid, impl="sort")
    bp, cp, op = scatter_to_blocks(batch, dest, nb, cap, "inner",
                                   valid=valid, impl=INTERP)
    np.testing.assert_array_equal(np.asarray(cs), np.asarray(cp))
    assert int(os_) == int(op) == 0
    for b in range(nb):
        ks, rs = _valid_rows(bs, np.asarray(cs), cap, b)
        kp, rp = _valid_rows(bp, np.asarray(cp), cap, b)
        # same multiset of tuples per block (within-block order is free)
        np.testing.assert_array_equal(np.sort(rs), np.sort(rp))
        np.testing.assert_array_equal(np.sort(ks), np.sort(kp))
    # sentinel padding past the count on both routes
    np.testing.assert_array_equal(
        np.asarray(bs.key_rem)[int(np.asarray(cs)[0]):cap],
        np.asarray(bp.key_rem)[int(np.asarray(cp)[0]):cap])


def test_scatter_parity_under_overflow():
    n, nb, cap = 4000, 4, 500                       # demand ~1000 > cap
    batch, dest, _, _ = _rand(n, nb, seed=6)
    bs, cs, os_ = scatter_to_blocks(batch, dest, nb, cap, "inner",
                                    impl="sort")
    bp, cp, op = scatter_to_blocks(batch, dest, nb, cap, "inner",
                                   impl=INTERP)
    # the accounting is byte-equal even when the kept subsets differ
    np.testing.assert_array_equal(np.asarray(cs), np.asarray(cp))
    assert int(os_) == int(op) > 0
    dest_np, rid_np = np.asarray(dest), np.arange(n)
    for b in range(nb):
        for blocks, counts in ((bs, cs), (bp, cp)):
            _, rids = _valid_rows(blocks, np.asarray(counts), cap, b)
            # membership: every kept row is a genuine tuple of this block
            assert set(rids) <= set(rid_np[dest_np == b])
            assert len(set(rids)) == len(rids) == cap


# ----------------------------------------------- grouped scatter parity

def _group_rows(blocks, group_counts, cap, b, s):
    gc = np.asarray(group_counts)
    lo = b * cap + int(gc[b, :s].sum())
    return np.asarray(blocks.rid)[lo:lo + int(gc[b, s])]


@pytest.mark.parametrize("valid_p", [None, 0.8])
def test_grouped_parity_no_overflow(valid_p):
    n, nb, ns, cap = 3000, 4, 8, 1200
    batch, dest, sub, valid = _rand(n, nb, num_sub=ns, seed=7,
                                    valid_p=valid_p)
    ss = scatter_to_blocks_grouped(batch, dest, sub, nb, ns, cap, "inner",
                                   valid=valid, impl="sort")
    pp = scatter_to_blocks_grouped(batch, dest, sub, nb, ns, cap, "inner",
                                   valid=valid, impl=INTERP)
    np.testing.assert_array_equal(np.asarray(ss[1]), np.asarray(pp[1]))
    np.testing.assert_array_equal(np.asarray(ss[2]), np.asarray(pp[2]))
    assert int(ss[3]) == int(pp[3]) == 0
    for b in range(nb):
        for s in range(ns):
            np.testing.assert_array_equal(
                np.sort(_group_rows(ss[0], ss[2], cap, b, s)),
                np.sort(_group_rows(pp[0], pp[2], cap, b, s)))


def test_grouped_parity_under_overflow_accounting():
    n, nb, ns, cap = 3000, 4, 8, 400                # demand ~750 > cap
    batch, dest, sub, _ = _rand(n, nb, num_sub=ns, seed=8)
    ss = scatter_to_blocks_grouped(batch, dest, sub, nb, ns, cap, "inner",
                                   impl="sort")
    pp = scatter_to_blocks_grouped(batch, dest, sub, nb, ns, cap, "inner",
                                   impl=INTERP)
    np.testing.assert_array_equal(np.asarray(ss[1]), np.asarray(pp[1]))
    np.testing.assert_array_equal(np.asarray(ss[2]), np.asarray(pp[2]))
    assert int(ss[3]) == int(pp[3]) > 0
    dest_np, sub_np = np.asarray(dest), np.asarray(sub)
    for b in range(nb):
        for s in range(ns):
            for res in (ss, pp):
                rids = _group_rows(res[0], res[2], cap, b, s)
                mine = set(np.flatnonzero((dest_np == b) & (sub_np == s)))
                assert set(rids) <= mine            # membership only


# ---------------------------------------------------------- reorder parity

@pytest.mark.parametrize("valid_p", [None, 0.6])
def test_reorder_parity(valid_p):
    n, p = 5000, 16
    rng = np.random.default_rng(9)
    keys = rng.integers(0, 1 << 16, n).astype(np.uint32)
    pid = jnp.asarray(rng.integers(0, p, n).astype(np.uint32))
    valid = (None if valid_p is None else
             jnp.asarray(rng.random(n) < valid_p))
    batch = _comp(keys, np.arange(n))
    outs, pids, hs, offs = reorder_by_partition(batch, pid, p, valid=valid,
                                                impl="sort")
    outp, pidp, hp, offp = reorder_by_partition(batch, pid, p, valid=valid,
                                                impl=INTERP)
    np.testing.assert_array_equal(np.asarray(hs), np.asarray(hp))
    np.testing.assert_array_equal(np.asarray(offs), np.asarray(offp))
    total = int(np.asarray(hs).sum())
    # both are grouped ascending over the valid prefix...
    for pids_ in (np.asarray(pids), np.asarray(pidp)):
        assert (np.diff(pids_[:total]) >= 0).all()
    # ...with the same per-partition multiset of rows
    off = np.concatenate([np.asarray(offs), [total]])
    for g in range(p):
        lo, hi = int(off[g]), int(off[g + 1])
        np.testing.assert_array_equal(
            np.sort(np.asarray(outs.rid)[lo:hi]),
            np.sort(np.asarray(outp.rid)[lo:hi]))


def test_reorder_sort_hist_matches_local_histogram():
    # satellite: the sort fallback derives its histogram from searchsorted
    # run bounds instead of a separate local_histogram pass — byte-identical
    n, p = 7000, 32
    rng = np.random.default_rng(10)
    pid = jnp.asarray(rng.integers(0, p, n).astype(np.uint32))
    valid = jnp.asarray(rng.random(n) < 0.5)
    batch = _comp(rng.integers(0, 99, n), np.arange(n))
    for v in (None, valid):
        _, _, hist, _ = reorder_by_partition(batch, pid, p, valid=v,
                                             impl="sort")
        np.testing.assert_array_equal(
            np.asarray(hist), np.asarray(local_histogram(pid, p, v,
                                                         impl="xla")))


# ------------------------------------------- grouped clip property test

def test_grouped_clip_eats_highest_pid_tail_property():
    """group_counts sums to the tuples actually present per block, and the
    clip keeps the lowest pids: kept[b, s] follows the cum-min formula, so
    every group below the clip point keeps its full demand and everything
    past it is eaten — the contract pack_blocks builds headers from."""
    rng = np.random.default_rng(11)
    for trial in range(8):
        nb = int(rng.integers(2, 6))
        ns = int(rng.integers(2, 9))
        n = int(rng.integers(200, 2500))
        cap = int(rng.integers(8, max(9, 2 * n // nb)))
        batch, dest, sub, valid = _rand(n, nb, num_sub=ns,
                                        seed=100 + trial,
                                        valid_p=0.9 if trial % 2 else None)
        blocks, counts, gc, overflow = scatter_to_blocks_grouped(
            batch, dest, sub, nb, ns, cap, "inner", valid=valid,
            impl="sort")
        gc = np.asarray(gc).astype(np.int64)
        d, s = np.asarray(dest).astype(np.int64), np.asarray(sub)
        ok = np.ones(n, bool) if valid is None else np.asarray(valid)
        raw = np.zeros((nb, ns), np.int64)
        np.add.at(raw, (d[ok], s[ok].astype(np.int64)), 1)
        # kept = clipped cum-min of the raw demand, low pids first
        cum = np.minimum(np.cumsum(raw, axis=1), cap)
        kept = np.concatenate([cum[:, :1], np.diff(cum, axis=1)], axis=1)
        np.testing.assert_array_equal(gc, kept)
        # sums to the tuples actually present per block (occupied prefix)
        key_np = np.asarray(blocks.key_rem).reshape(nb, cap)
        rid_np = np.asarray(blocks.rid).reshape(nb, cap)
        for b in range(nb):
            present = int(gc[b].sum())
            assert present == min(int(np.asarray(counts)[b]), cap)
            # the present rows really are this block's tuples, pid-sorted
            rids = rid_np[b, :present]
            assert set(rids) <= set(np.flatnonzero(ok & (d == b)))
            assert (np.diff(s[rids].astype(np.int64)) >= 0).all()
            del key_np  # membership checked via rid; keys ride along
            key_np = np.asarray(blocks.key_rem).reshape(nb, cap)
        assert int(overflow) == int(np.maximum(
            raw.sum(axis=1) - cap, 0).sum())


# ------------------------------------------------------- fallback telemetry

def test_auto_fallback_ticks_counter_and_logs_once(monkeypatch, capsys):
    m = Measurements()
    radix.install_partition_observer(m)
    monkeypatch.setattr(radix, "_fallback_logged", False)
    try:
        # CPU backend: auto must degrade to the sort path, loudly once
        assert radix.resolve_partition_impl(None, 8, "scatter_to_blocks") \
            == "loop"
        assert radix.resolve_partition_impl("auto", 8, "reorder") == "loop"
        err = capsys.readouterr().err
        assert err.count("fell back to the XLA sort path") == 1
        assert m.counters[PARTFALLBACK] == 2
        # explicit impls never tick the fallback
        assert radix.resolve_partition_impl("sort", 8, "x") == "loop"
        assert radix.resolve_partition_impl(INTERP, 8, "x") == INTERP
        assert m.counters[PARTFALLBACK] == 2
    finally:
        radix.install_partition_observer(None)


def test_pallas_path_ticks_partpass_span():
    m = Measurements()
    radix.install_partition_observer(m)
    try:
        batch, dest, _, _ = _rand(512, 4, seed=12)
        scatter_to_blocks(batch, dest, 4, 256, "inner", impl=INTERP)
        assert m.counters[PARTPASS] == 1
        spans = [r for r in m.flightrec.records()
                 if r["name"] == "partition_pass" and r["kind"] == "span"]
        assert spans and spans[0]["impl"] == INTERP
    finally:
        radix.install_partition_observer(None)


# ------------------------------------------------------------- planner

def test_plan_partition_prices_both_arms():
    from tpu_radix_join.planner.cost_model import plan_partition
    from tpu_radix_join.planner.profile import load_profile
    prof = load_profile()
    on = plan_partition(prof, 1 << 25, pallas_ok=True)
    off = plan_partition(prof, 1 << 25, pallas_ok=False)
    assert on.impl == "pallas" and off.impl == "sort"
    assert on.partition_ms == on.fused_ms < off.partition_ms == off.sort_ms
    # the fused arm prices off the schema-v4 constant: doubling the unit
    # moves the estimate
    bumped = prof.replace_constants(partition_pass_unit_ms={
        "value": prof.value("partition_pass_unit_ms") * 10,
        "source": "test"})
    assert plan_partition(bumped, 1 << 25, pallas_ok=True).fused_ms \
        > on.fused_ms


def test_twolevel_strategy_carries_partition_term():
    from tpu_radix_join.planner.calibrate import TERM_TO_CONSTANT
    from tpu_radix_join.planner.cost_model import (Workload,
                                                   enumerate_strategies)
    from tpu_radix_join.planner.profile import load_profile
    rows = enumerate_strategies(load_profile(),
                                Workload(r_tuples=1 << 22,
                                         s_tuples=1 << 22, num_nodes=8))
    tl = next(r for r in rows if r.strategy == "incore_fused_twolevel")
    assert "partition" in tl.terms and tl.terms["partition"] > 0
    assert "scatter" not in tl.terms
    assert TERM_TO_CONSTANT["partition"] == "partition_pass_unit_ms"


# -------------------------------------------------------- engine wiring

def _oracle_join(**cfg_kw):
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.performance import Measurements

    n = 8
    inner = Relation(n << 10, n, "unique", seed=31)
    outer = Relation(n << 10, n, "unique", seed=32)
    m = Measurements(node_id=0, num_nodes=n)
    eng = HashJoin(JoinConfig(num_nodes=n, verify="check", **cfg_kw),
                   measurements=m)
    res = eng.join(inner, outer)
    assert res.ok and res.matches == inner.expected_matches(outer)
    return m


def test_join_fused_partition_flat_mesh_oracle_exact():
    m = _oracle_join(partition_impl=INTERP, exchange_codec="pack")
    assert m.counters[PARTPASS] > 0
    # any PARTFALLBACK here is the histogram auto-select degrading on the
    # CPU backend; the forced scatter impl itself never falls back
    spans = [r for r in m.flightrec.records()
             if r["name"] == "partition_pass" and r["kind"] == "span"]
    assert spans and all(s["impl"] == INTERP for s in spans)


def test_join_fused_partition_hierarchical_mesh_oracle_exact():
    m = _oracle_join(partition_impl=INTERP, num_hosts=2,
                     exchange_codec="pack")
    assert m.counters[PARTPASS] > 0


def test_join_fused_partition_two_level_oracle_exact():
    # two_level adds the local second radix pass (local_partitioning.py),
    # which must route through the same forced impl
    m = _oracle_join(partition_impl=INTERP, two_level=True,
                     allocation_factor=2.0)
    assert m.counters[PARTPASS] > 2   # exchange scatters + local passes


def test_config_rejects_unknown_partition_impl():
    from tpu_radix_join import JoinConfig
    with pytest.raises(ValueError, match="partition impl"):
        JoinConfig(partition_impl="bogus")
