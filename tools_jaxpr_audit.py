"""graftcheck CLI: the jaxpr-level IR audit as a gating check.

    python tools_jaxpr_audit.py                  # all rules, all entries
    python tools_jaxpr_audit.py --strict         # stale suppressions fail
    python tools_jaxpr_audit.py --rule transfer --rule donation
    python tools_jaxpr_audit.py --entry pipeline --entry shuffle
    python tools_jaxpr_audit.py --memory-budget 268435456
    python tools_jaxpr_audit.py --list-rules
    python tools_jaxpr_audit.py --json JXAUDIT.json

Traces every jitted engine entry point abstractly (``jax.make_jaxpr``
over ShapeDtypeStruct inputs — no arrays, no compile, no device
dispatch; 8 virtual CPU devices are forced before jax imports, so this
runs device-free under ``JAX_PLATFORMS=cpu`` in tier-1 CI) and walks
the lowered programs with the IR rules in
``tpu_radix_join/analysis/jaxpr/``:

    transfer         implicit device_put / host callback in a hot jit
    collective-axis  collectives name live mesh axes, sizes consistent
    width            uint32 lanes silently widening to i64/f64/f32
    donation         dead-after-use inputs without donate_argnums
    static-memory    live-set peak vs --memory-budget (informational
                     when the budget is unarmed: peak still reported)

Exit contract matches tools_lint.py (0 clean / 1 findings or, under
--strict, stale suppressions / 2 usage-IO-trace errors); the committed
suppression file is ``JXAUDIT_BASELINE.json`` at the repo root, every
entry with a mandatory reason.  ``--json`` writes
``{"jaxpr_findings": N, ...}``; ``jaxpr_findings`` is pinned
lower-is-better in observability/regress.py.  ``tools_static_gate.py``
chains this with graftlint for the single merged CI gate.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tools_jaxpr_audit.py",
        description="Trace the engine's jitted entry points abstractly "
                    "and run the jaxpr-level IR rules.")
    p.add_argument("--rule", action="append", default=[], metavar="ID",
                   help="run only this IR rule id, repeatable "
                        "(default: all)")
    p.add_argument("--entry", action="append", default=[], metavar="NAME",
                   help="trace only this entry point, repeatable "
                        "(default: all)")
    p.add_argument("--nodes", type=int, default=8,
                   help="mesh width to trace at (default: 8)")
    p.add_argument("--per-node", type=int, default=8192,
                   help="tuples per node for the traced shapes")
    p.add_argument("--cap", type=int, default=2048,
                   help="wire slots per (sender, destination) block")
    p.add_argument("--memory-budget", type=int, default=None,
                   metavar="BYTES",
                   help="arm the static-memory rule: finding when any "
                        "entry's live-set peak exceeds this")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="suppression file (default: JXAUDIT_BASELINE.json "
                        "at the repo root)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--strict", action="store_true",
                   help="stale baseline suppressions also fail (exit 1)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule ids + docs and exit 0")
    p.add_argument("--json", default=None, metavar="FILE",
                   help="also write machine-readable counts "
                        "({'jaxpr_findings': N, ...})")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # devices before jax: abstract tracing still builds the engine mesh
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(max(args.nodes, 8), respect_existing=True)
    from tpu_radix_join.analysis.core import LintError
    from tpu_radix_join.analysis.jaxpr import (AuditContext, IR_RULES,
                                               JXAUDIT_BASELINE,
                                               register_ir_rules, run_audit)
    register_ir_rules()
    if args.list_rules:
        for rid in sorted(IR_RULES):
            r = IR_RULES[rid]
            print(f"{rid:18s} [{r.token}] {r.doc}")
        return 0
    baseline = None
    if not args.no_baseline:
        baseline = args.baseline or os.path.join(REPO_ROOT,
                                                 JXAUDIT_BASELINE)
        if args.baseline and not os.path.exists(args.baseline):
            print(f"error: baseline {args.baseline} not found",
                  file=sys.stderr)
            return 2
    ctx = AuditContext(memory_budget_bytes=args.memory_budget)
    try:
        from tpu_radix_join.analysis.jaxpr.trace import build_entries
        views = build_entries(num_nodes=args.nodes, per_node=args.per_node,
                              cap=args.cap, entries=args.entry or None)
        res = run_audit(views, rule_ids=args.rule or None,
                        baseline_path=baseline, ctx=ctx)
    except LintError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for f in res.findings:
        print(f.render())
    for e in res.stale:
        print(f"stale suppression: {e['rule']} {e['path']} key={e['key']!r}"
              f" — finding no longer fires; remove the entry")
    per_rule = {}
    for f in res.findings:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    summary = {"jaxpr_findings": len(res.findings),
               "suppressed": len(res.suppressed),
               "stale_baseline": len(res.stale),
               "rules_run": res.rules,
               "entries": res.entries,
               "per_rule": per_rule,
               "stats": res.stats}
    if args.json:
        try:
            with open(args.json, "w") as fh:
                json.dump(summary, fh, indent=2)
        except OSError as e:
            print(f"error: cannot write {args.json}: {e}", file=sys.stderr)
            return 2
    code = res.exit_code(strict=args.strict)
    verdict = "clean" if code == 0 else "FINDINGS"
    print(f"jaxpr audit: {verdict} — {len(res.findings)} finding(s), "
          f"{len(res.suppressed)} baselined, {len(res.stale)} stale "
          f"suppression(s), {len(res.entries)} entr"
          f"{'y' if len(res.entries) == 1 else 'ies'}, "
          f"rules: {', '.join(res.rules)}")
    return code


if __name__ == "__main__":
    sys.exit(main())
