"""The single static-analysis gate: graftlint + graftcheck, one exit.

    python tools_static_gate.py                  # both layers, strict
    python tools_static_gate.py --json GATE.json

Chains the two static layers in-process:

    1. graftlint  (tools_lint.py --strict)        — AST conventions
    2. graftcheck (tools_jaxpr_audit.py --strict) — lowered-program IR

Both run strict, so a live finding *or* a stale baseline suppression in
either layer fails the gate — baseline files only ever shrink.  The
merged exit keeps the shared contract: 0 only when both layers are
clean, 1 when either has findings/stale entries, 2 when either hit a
usage/IO/trace error (an unreadable baseline must not read as "clean").
Wired as a tier-1 test (tests/test_static_gate.py) and into ``bench.py
--static-gate``; the JSON counts (``lint_findings``,
``jaxpr_findings``, ``stale_baseline``) are pinned lower-is-better in
observability/regress.py so CI can gate their growth like a perf
regression.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tools_static_gate.py",
        description="Run graftlint + graftcheck strict as one gate.")
    p.add_argument("--json", default=None, metavar="FILE",
                   help="write merged machine-readable counts")
    p.add_argument("--skip-jaxpr", action="store_true",
                   help="AST layer only (no tracing; sub-second)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import tools_jaxpr_audit
    import tools_lint

    summary = {}
    codes = {}
    with tempfile.TemporaryDirectory() as td:
        lint_json = os.path.join(td, "lint.json")
        print("== graftlint (AST) ==")
        codes["lint"] = tools_lint.main(["--strict", "--json", lint_json])
        if os.path.exists(lint_json):
            with open(lint_json) as fh:
                summary.update(json.load(fh))
        if not args.skip_jaxpr:
            audit_json = os.path.join(td, "audit.json")
            print("== graftcheck (jaxpr IR) ==")
            codes["jaxpr"] = tools_jaxpr_audit.main(
                ["--strict", "--json", audit_json])
            if os.path.exists(audit_json):
                with open(audit_json) as fh:
                    audit = json.load(fh)
                # merge without clobbering the lint layer's counts
                summary["jaxpr_findings"] = audit.get("jaxpr_findings")
                summary["jaxpr_suppressed"] = audit.get("suppressed")
                summary["stale_baseline"] = (
                    (summary.get("stale_baseline") or 0)
                    + (audit.get("stale_baseline") or 0))
                summary["jaxpr_entries"] = audit.get("entries")
                summary["jaxpr_stats"] = audit.get("stats")
    code = (2 if 2 in codes.values()
            else 1 if 1 in codes.values() else 0)
    summary["gate_exit"] = code
    summary["layers"] = codes
    if args.json:
        try:
            with open(args.json, "w") as fh:
                json.dump(summary, fh, indent=2)
        except OSError as e:
            print(f"error: cannot write {args.json}: {e}", file=sys.stderr)
            return 2
    print(f"static gate: {'clean' if code == 0 else 'FAIL'} "
          f"(layers: {codes})")
    return code


if __name__ == "__main__":
    sys.exit(main())
