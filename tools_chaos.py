"""Chaos/soak CLI: N seeded fault schedules, pass-or-classified invariant.

    python tools_chaos.py --runs 25 --base-seed 100
    python tools_chaos.py --runs 50 --verify repair --nodes 4 --size 4096
    python tools_chaos.py --runs 10 --demo-shrink

Each run arms a seeded schedule of fault sites (robustness/chaos.py),
executes one join on known-oracle inputs with integrity verification on,
and classifies the outcome: ``pass`` (count matches the oracle),
``classified`` (the run failed but named its failure class), or
``violation`` (silent wrong count / unclassified crash).  A violating
schedule is delta-debug-shrunk to a minimal still-violating arm set and
its ``(seed, arms)`` repro is printed and written to --artifact-dir.

``--demo-shrink`` runs the harness against a verify-off engine — the
configuration the checksums exist to protect — so the exchange-corruption
arm produces a real silent-wrong-count violation, demonstrating shrink
and repro end to end.  Exits

    0  no violations (invariant held),
    1  at least one violation (repro lines printed above the summary),
    2  usage errors.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tools_chaos.py",
        description="Seeded chaos soak over the join engine with "
                    "verification on; shrinks violating schedules to "
                    "minimal replayable repros.")
    p.add_argument("--runs", type=int, default=25,
                   help="number of seeded schedules to execute (default 25)")
    p.add_argument("--base-seed", type=int, default=0,
                   help="schedule seeds are base-seed .. base-seed+runs-1")
    p.add_argument("--verify", choices=("off", "check", "repair"),
                   default="check",
                   help="engine verification mode under chaos (default "
                        "check; off demonstrates the silent-corruption "
                        "violation the harness exists to catch)")
    p.add_argument("--nodes", type=int, default=4,
                   help="mesh width of the soak engine (default 4)")
    p.add_argument("--size", type=int, default=1 << 12,
                   help="tuples per side; keys are oracle-friendly so the "
                        "true match count is exactly this (default 4096)")
    p.add_argument("--artifact-dir", default="artifacts/chaos",
                   help="where violating-schedule repro JSONs are written")
    p.add_argument("--demo-shrink", action="store_true",
                   help="force verify=off so corruption arms violate; "
                        "exercises shrink + repro replay and exits 0 iff "
                        "every shrunk repro replays deterministically")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.runs <= 0:
        print("error: --runs must be positive", file=sys.stderr)
        return 2
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)
    from tpu_radix_join.robustness import chaos

    verify = "off" if args.demo_shrink else args.verify
    # violations drop forensics bundles (observability/postmortem.py)
    # next to the repro JSONs; the repro line names its bundle path
    runner = chaos.ChaosRunner(num_nodes=args.nodes, size=args.size,
                               verify=verify,
                               bundle_dir=os.path.join(args.artifact_dir,
                                                       "forensics"))

    def show(out):
        cls = f" class={out.failure_class}" if out.failure_class else ""
        detail = f" ({out.detail})" if out.status == chaos.VIOLATION else ""
        print(f"[CHAOS] seed={out.schedule.seed} {out.status}{cls} "
              f"arms={[s for s, _ in out.schedule.arms]}{detail}")

    outcomes, summary = chaos.soak(args.runs, base_seed=args.base_seed,
                                   runner=runner, on_outcome=show)

    replay_failures = 0
    for out in outcomes:
        if out.status != chaos.VIOLATION:
            continue
        shrunk = chaos.shrink(
            out.schedule,
            lambda s: runner.run(s).status == chaos.VIOLATION)
        repro = runner.run(shrunk)
        again = runner.run(shrunk)
        if (repro.status, repro.matches) != (again.status, again.matches):
            replay_failures += 1
            print(f"[CHAOS] WARNING: shrunk seed={shrunk.seed} repro is "
                  f"not deterministic", file=sys.stderr)
        os.makedirs(args.artifact_dir, exist_ok=True)
        path = os.path.join(args.artifact_dir,
                            f"repro_seed{shrunk.seed}.json")
        print("[CHAOS] repro " + chaos.write_repro(repro, path))
        print(f"[CHAOS] repro written to {path} "
              f"(shrunk {len(out.schedule.arms)} -> {len(shrunk.arms)} arms)")
        if repro.bundle:
            print(f"[CHAOS] forensics bundle {repro.bundle} "
                  f"(render: python tools_postmortem.py {repro.bundle})")
    print("[CHAOS] " + json.dumps(summary, sort_keys=True))
    if args.demo_shrink:
        # demo mode: violations are the point; success = every shrunk
        # repro replayed deterministically
        if summary["violations"] == 0:
            print("[CHAOS] demo-shrink produced no violations (no "
                  "corruption arm drawn?) — widen --runs", file=sys.stderr)
            return 1
        return 0 if replay_failures == 0 else 1
    return 0 if summary["violations"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
