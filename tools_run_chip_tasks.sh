#!/bin/bash
# Round-3 chip-gated task runner: waits for the axon tunnel, then runs the
# experiments and canonical-workload artifacts in sequence.  Outputs under
# artifacts/chip_r3/.
set -u
cd /root/repo
export PYTHONPATH=/root/repo${PYTHONPATH:+:$PYTHONPATH}
OUT=artifacts/chip_r3
mkdir -p "$OUT"

probe() { timeout 45 python -c "import jax; print(jax.devices()[0])" >/dev/null 2>&1; }

echo "$(date -u +%H:%M:%S) waiting for TPU tunnel..."
for i in $(seq 1 200); do
  if probe; then echo "$(date -u +%H:%M:%S) tunnel up"; break; fi
  sleep 90
  if [ "$i" = 200 ]; then echo "tunnel never came back"; exit 3; fi
done

run() {
  name=$1; shift
  echo "=== $name: $* ==="
  timeout 2400 "$@" > "$OUT/$name.log" 2>&1
  echo "$name rc=$? ($(date -u +%H:%M:%S))"
}

run scatter python experiments/exp_block_scatter.py
run bench python bench.py
SIXTEEN=$((1<<24))
run cli_16m_sort python -m tpu_radix_join.main --tuples-per-node $SIXTEEN \
    --nodes 1 --repeat 3 --output-dir "$OUT/perf_16m_sort"
run cli_16m_phases python -m tpu_radix_join.main --tuples-per-node $SIXTEEN \
    --nodes 1 --two-level --measure-phases --repeat 3 \
    --output-dir "$OUT/perf_16m_phases"
run cli_20m_sort python -m tpu_radix_join.main --tuples-per-node 20000000 \
    --nodes 1 --repeat 3 --output-dir "$OUT/perf_20m_sort"
run cli_20m_phases python -m tpu_radix_join.main --tuples-per-node 20000000 \
    --nodes 1 --two-level --measure-phases --repeat 3 \
    --output-dir "$OUT/perf_20m_phases"
run out_of_core python experiments/exp_out_of_core.py 27 24
echo "ALL_CHIP_TASKS_DONE $(date -u +%H:%M:%S)"
