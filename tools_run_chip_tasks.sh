#!/bin/bash
# Round-5 chip-gated task runner (VERDICT r4 #1: invoke at round START and
# keep re-invoking until every .done marker exists).  Behavior:
#   * re-probes the tunnel before every task AND between retries;
#   * retries each task up to MAX_ATTEMPTS times;
#   * drops a .done marker per task so a rerun of the whole script resumes
#     at the first unfinished task (the out-of-core grids additionally
#     resume mid-task via chunked_join_grid checkpoints).
# Outputs under artifacts/chip_r5/.
set -u
cd /root/repo
export PYTHONPATH=/root/repo${PYTHONPATH:+:$PYTHONPATH}
OUT=artifacts/chip_r5
mkdir -p "$OUT"
MAX_ATTEMPTS=6

probe() { timeout 60 python -c "import jax; print(jax.devices()[0])" >/dev/null 2>&1; }

wait_tunnel() {
  for i in $(seq 1 400); do
    if probe; then return 0; fi
    echo "$(date -u +%H:%M:%S) tunnel down, waiting..."
    sleep 90
  done
  echo "tunnel never came back"; return 1
}

run() {
  name=$1; shift
  tmo=$1; shift
  if [ -f "$OUT/$name.done" ]; then echo "=== $name: already done, skipping ==="; return 0; fi
  echo "=== $name: $* ==="
  for attempt in $(seq 1 $MAX_ATTEMPTS); do
    wait_tunnel || return 1
    # per-attempt logs: a retry must not destroy the prior attempt's
    # failure evidence; $name.log always points at the latest attempt
    timeout "$tmo" "$@" > "$OUT/$name.a$attempt.log" 2>&1
    rc=$?
    ln -sf "$name.a$attempt.log" "$OUT/$name.log"
    echo "$name attempt $attempt rc=$rc ($(date -u +%H:%M:%S))"
    if [ "$rc" = 0 ]; then touch "$OUT/$name.done"; return 0; fi
    sleep 30
  done
  echo "$name FAILED after $MAX_ATTEMPTS attempts"
  return 1
}

SIXTEEN=$((1<<24))
run bench            2400 python bench.py
run trace_16m        2400 python experiments/exp_trace_pipeline.py 24 "$OUT/trace_16m"
run cli_16m_sort     2400 python -m tpu_radix_join.main --tuples-per-node $SIXTEEN \
                       --nodes 1 --repeat 3 --output-dir "$OUT/perf_16m_sort"
run cli_16m_trace    2400 python -m tpu_radix_join.main --tuples-per-node $SIXTEEN \
                       --nodes 1 --repeat 3 --trace --output-dir "$OUT/perf_16m_trace"
run cli_16m_phases   2400 python -m tpu_radix_join.main --tuples-per-node $SIXTEEN \
                       --nodes 1 --two-level --measure-phases --repeat 3 \
                       --output-dir "$OUT/perf_16m_phases"
run cli_20m_sort     2400 python -m tpu_radix_join.main --tuples-per-node 20000000 \
                       --nodes 1 --repeat 3 --output-dir "$OUT/perf_20m_sort"
run cli_20m_phases   2400 python -m tpu_radix_join.main --tuples-per-node 20000000 \
                       --nodes 1 --two-level --measure-phases --repeat 3 \
                       --output-dir "$OUT/perf_20m_phases"
run cli_zipf_device  2400 python -m tpu_radix_join.main --tuples-per-node $SIXTEEN \
                       --nodes 1 --outer-kind zipf --zipf-theta 0.75 \
                       --generation device --repeat 3 \
                       --output-dir "$OUT/perf_16m_zipf"
run radix_batched    2400 python experiments/exp_radix_batched.py 24
# out-of-core grids: each resumes mid-grid via artifacts/oo_ckpt on retry
run out_of_core_128m 7200 python experiments/exp_out_of_core.py 27 24
run out_of_core_1b   21600 python experiments/exp_out_of_core.py 30 26 64
echo "ALL_CHIP_TASKS_DONE $(date -u +%H:%M:%S)"
