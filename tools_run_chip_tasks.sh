#!/bin/bash
# Round-5 chip-gated task runner (VERDICT r4 #1: invoke at round START and
# keep re-invoking until every .done marker exists).  Re-probes the tunnel
# before every task and between retries; .done markers make reruns resume at
# the first unfinished task (the out-of-core grids additionally resume
# mid-task via chunked_join_grid checkpoints).  Outputs under artifacts/chip_r5/.
set -u
cd /root/repo
OUT=artifacts/chip_r5
source tools_chip_lib.sh

SIXTEEN=$((1<<24))
run bench            2400 python bench.py
run trace_16m        2400 python experiments/exp_trace_pipeline.py 24 "$OUT/trace_16m"
run cli_16m_sort     2400 python -m tpu_radix_join.main --tuples-per-node $SIXTEEN \
                       --nodes 1 --repeat 3 --output-dir "$OUT/perf_16m_sort"
run cli_16m_trace    2400 python -m tpu_radix_join.main --tuples-per-node $SIXTEEN \
                       --nodes 1 --repeat 3 --trace --output-dir "$OUT/perf_16m_trace"
run cli_16m_phases   2400 python -m tpu_radix_join.main --tuples-per-node $SIXTEEN \
                       --nodes 1 --two-level --measure-phases --repeat 3 \
                       --output-dir "$OUT/perf_16m_phases"
run cli_20m_sort     2400 python -m tpu_radix_join.main --tuples-per-node 20000000 \
                       --nodes 1 --repeat 3 --output-dir "$OUT/perf_20m_sort"
run cli_20m_phases   2400 python -m tpu_radix_join.main --tuples-per-node 20000000 \
                       --nodes 1 --two-level --measure-phases --repeat 3 \
                       --output-dir "$OUT/perf_20m_phases"
run cli_zipf_device  2400 python -m tpu_radix_join.main --tuples-per-node $SIXTEEN \
                       --nodes 1 --outer-kind zipf --zipf-theta 0.75 \
                       --generation device --repeat 3 \
                       --output-dir "$OUT/perf_16m_zipf"
run radix_batched    2400 python experiments/exp_radix_batched.py 24
# out-of-core grids: each resumes mid-grid via artifacts/oo_ckpt on retry
run out_of_core_128m 7200 python experiments/exp_out_of_core.py 27 24
run out_of_core_1b   21600 python experiments/exp_out_of_core.py 30 26 64
echo "ALL_CHIP_TASKS_DONE $(date -u +%H:%M:%S)"
