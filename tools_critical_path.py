"""Cross-rank critical-path reporter (observability/critpath.py).

    python tools_critical_path.py TIMELINE_DIR              # human report
    python tools_critical_path.py TIMELINE_DIR --json       # raw result
    python tools_critical_path.py TIMELINE_DIR --trace-id ID

Ingests the per-rank ``<rank>.spans.json`` exports a ``--timeline-dir``
run leaves behind (grouped by join-level trace id, so a directory
holding several runs still yields one coherent join), reconstructs the
cross-rank causal DAG, and prints the critical path: which rank's which
phase bounded the wall clock, how much of the path was compute vs
collective-wait vs straggle, per-barrier skew with the bounding rank
named, and any manifest hedge claims with the estimated path shortening.

Partial-tolerant: missing ranks and torn spans degrade to a PARTIAL
path with warnings.  Exits 0 on a usable path (even partial), 1 when no
path could be reconstructed, 2 on usage errors.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tpu_radix_join.observability.critpath import (critical_path_for_dir,
                                                   format_summary,
                                                   render_report)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tools_critical_path.py",
        description="Reconstruct the cross-rank critical path from a "
                    "--timeline-dir of span exports.")
    p.add_argument("timeline_dir",
                   help="directory of <rank>.spans.json exports")
    p.add_argument("--trace-id", default=None,
                   help="only ingest span files of this join-level trace "
                        "id (default: the largest coherent cohort wins)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw result dict instead of the report")
    p.add_argument("--summary", action="store_true",
                   help="one [CRITPATH] line instead of the full report")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not os.path.isdir(args.timeline_dir):
        print(f"error: not a directory: {args.timeline_dir}",
              file=sys.stderr)
        return 2
    res = critical_path_for_dir(args.timeline_dir, trace_id=args.trace_id)
    if args.json:
        print(json.dumps(res, indent=2, default=str))
    elif args.summary:
        print(f"[CRITPATH] {format_summary(res)}")
    else:
        print(render_report(res))
    return 1 if "error" in res else 0


if __name__ == "__main__":
    sys.exit(main())
