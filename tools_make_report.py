"""Assemble the round's chip evidence into one summary table.

    python tools_make_report.py [artifacts/chip_r5]
    python tools_make_report.py artifacts/chip_r5 --emit-profile out.json \
        [--profile-name v5e_r5]
    python tools_make_report.py artifacts/chip_r5 --emit-timeline out.json
    python tools_make_report.py artifacts --emit-ledger artifacts/ledger

Reads every perf dir (`<rank>.perf`/`<rank>.info`), trace breakdown
(`trace_*/breakdown.json`), and task log under the artifact dir and prints a
markdown summary (per-workload phase columns in ms/join net of repeats,
JPROCRATE, CTOTAL where present, trace sort shares, runner task status).
The output is the raw material for BASELINE.md's achieved tables — numbers
come straight from the committed artifacts, no hand transcription.

``--emit-profile`` distills the same artifacts into a planner device
profile (tpu_radix_join/planner/profile.py) instead of a table: measured
SDISPATCH becomes ``dispatch_floor_ms``, a device-plane sort-discipline
trace breakdown becomes ``sort_stage_unit_ms``, every derived constant
cites the artifact it came from, and constants the artifacts cannot
measure keep the base profile's committed values + citations.

``--emit-timeline`` merges the per-rank ``<rank>.spans.json`` files a
``--timeline-dir`` run left under the artifact dir into one Chrome-trace
JSON on a shared clock (observability.timeline.merge_timeline) — load the
output in Perfetto / chrome://tracing.

``--emit-ledger OUT`` backfills the cross-run telemetry ledger
(observability/ledger.py) from committed history: every ``BENCH_r*.json``
at the repo root becomes a ``kind="bench"`` row and every ``perf_*`` dir
under the artifact dir (one nesting level allowed) a ``kind="run"`` row,
timestamped by file mtime.  The backfilled ledger is what
``tools_profile_fit.py fit`` turns into a provenance-carrying schema-v3
profile without a single fresh chip run.
"""

import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tpu_radix_join.performance.measurements import Measurements

PHASES = ("JHIST", "JMPI", "SLOCPREP", "JPROC", "BPBUILD", "BPPROBE",
          "CTOTAL", "SDISPATCH")


def perf_row(d):
    ms = Measurements.load(d)
    if not ms:
        return None
    m = ms[0]
    repeat = 1
    info_path = os.path.join(d, f"{m.node_id}.info")
    meta = {}
    if os.path.exists(info_path):
        with open(info_path) as f:
            meta = json.load(f)
        repeat = int((meta.get("config") or {}).get("repeat") or 1)
    pipelined = bool((meta.get("config") or {}).get("pipeline_repeats"))
    row = {"dir": os.path.basename(d), "repeat": repeat,
           "pipelined": "y" if pipelined else "",
           "key_range": meta.get("key_range", "")}
    # Once-per-invocation tags: in --pipeline-repeats runs the sizing
    # pre-pass (JHIST) executes once for the whole batch of dispatches, so
    # dividing it by repeat would report a per-join cost no join pays;
    # synchronous repeats re-run it per join, where dividing is right.
    once_per_call = ("JHIST",) if pipelined else ()
    for tag in PHASES:
        if tag in m.times_us:
            div = 1 if (tag == "SDISPATCH" or tag in once_per_call) else repeat
            row[tag] = m.times_us[tag] / div / 1e3
    if "JPROCRATE" in m.counters:
        row["JPROCRATE_M/s"] = m.counters["JPROCRATE"] / 1e6
    if "RESULTS" in m.counters:
        # raw registry value: the driver stores the single-join count for
        # synchronous repeats, the cumulative for pipelined mode — dividing
        # here would guess wrong for one of them
        row["RESULTS"] = m.counters["RESULTS"]
    return row


def emit_profile(base_dir: str, out_path: str, name: str = None) -> int:
    """Distill one round's chip artifacts into a planner device profile."""
    from tpu_radix_join.performance.trace import _is_device_plane
    from tpu_radix_join.planner.profile import (SORT_REF_ELEMS, load_profile,
                                                sort_stage_units)

    base = load_profile()
    updates = {}

    # dispatch floor: the per-program SDISPATCH column; median over ranks
    # and runs (a single outlier dispatch must not define the profile)
    floors = []
    for d in sorted(glob.glob(os.path.join(base_dir, "perf_*"))):
        for m in Measurements.load(d) or []:
            if "SDISPATCH" in m.times_us:
                floors.append((m.times_us["SDISPATCH"] / 1e3,
                               os.path.basename(d)))
    if floors:
        floors.sort()
        val, src = floors[len(floors) // 2]
        updates["dispatch_floor_ms"] = {
            "value": round(val, 3),
            "source": f"artifact:{base_dir}/{src} SDISPATCH "
                      f"(median of {len(floors)} runs)"}

    # sort stage unit: newest device-plane sort-discipline trace breakdown,
    # normalized by the stage model (unit = t / ((M/ref) * U(M)))
    for path in sorted(glob.glob(os.path.join(base_dir, "trace_*",
                                              "breakdown.json")),
                       reverse=True):
        try:
            with open(path) as f:
                bd = json.load(f)
        except (OSError, ValueError):
            continue
        if (bd.get("sort_share") and bd.get("size")
                and bd.get("discipline", "sort") == "sort"
                and _is_device_plane(bd.get("plane", ""))):
            union = 2 * int(bd["size"])
            t_sort = bd["busy_us"] * bd["sort_share"] / bd["iters"] / 1e3
            unit = t_sort / ((union / SORT_REF_ELEMS)
                            * sort_stage_units(union))
            updates["sort_stage_unit_ms"] = {
                "value": round(unit, 5),
                "source": f"artifact:{os.path.relpath(path)} "
                          f"(sort_share over {bd['iters']} iters, "
                          f"union {union})"}
            break

    if not updates:
        print(f"WARNING: no distillable measurements under {base_dir}; "
              f"emitting the base profile's committed constants unchanged",
              file=sys.stderr)
    prof = base.replace_constants(
        name=name or f"{base.name}+{os.path.basename(base_dir.rstrip('/'))}",
        **updates)
    prof.save(out_path)
    print(f"wrote {out_path} ({prof.name}): "
          f"{', '.join(sorted(updates)) or 'no constants refreshed'}")
    return 0


def emit_timeline(base_dir: str, out_path: str) -> int:
    """Merge per-rank span files under ``base_dir`` into one Chrome trace."""
    from tpu_radix_join.observability.timeline import merge_timeline

    doc = merge_timeline(base_dir, out_path=out_path, trace_dir=base_dir)
    if doc is None:
        print(f"ERROR: no *.spans.json under {base_dir} — run the driver "
              f"with --timeline-dir first", file=sys.stderr)
        return 1
    md = doc["metadata"]
    spans = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    instants = sum(1 for e in doc["traceEvents"] if e.get("ph") == "i")
    print(f"wrote {out_path}: {len(md['ranks'])} rank(s), {spans} spans, "
          f"{instants} instant events on one clock "
          f"(t0={md['t0_epoch_s']:.3f}); load in Perfetto/chrome://tracing")
    # a watchdog-killed / SIGKILLed rank leaves no (or a torn) span file;
    # the merge is partial-tolerant, but the gap must be said out loud
    if md.get("missing_ranks"):
        print(f"WARNING: missing_ranks={md['missing_ranks']} — "
              f"{len(md['missing_ranks'])} of {md['expected_ranks']} "
              f"expected rank(s) left no readable span file; the timeline "
              f"is PARTIAL", file=sys.stderr)
    if md.get("corrupt_files"):
        # name each skipped file *and why* — a torn write, a permissions
        # problem, and a non-span JSON all want different operator action
        reasons = {e["file"]: e["reason"]
                   for e in md.get("corrupt_file_reasons", [])}
        detail = "; ".join(
            f"{f}: {reasons.get(f, 'unknown reason')}"
            for f in md["corrupt_files"])
        print(f"WARNING: skipped {len(md['corrupt_files'])} span "
              f"file(s) — {detail}", file=sys.stderr)
    return 0


def emit_ledger(base_dir: str, out_path: str) -> int:
    """Backfill the cross-run ledger from committed BENCH/perf history."""
    from tpu_radix_join.observability.ledger import Ledger, ingest_artifacts

    counts = ingest_artifacts(base_dir, out_path)
    total = counts["bench"] + counts["run"]
    print(f"wrote {Ledger(out_path).path}: {counts['bench']} bench row(s), "
          f"{counts['run']} run row(s)")
    if total == 0:
        print(f"WARNING: nothing to ingest under {base_dir} (and no "
              f"BENCH_r*.json at the repo root)", file=sys.stderr)
        return 1
    return 0


def main() -> int:
    argv = sys.argv[1:]
    emit = prof_name = timeline = ledger = None
    if "--emit-profile" in argv:
        i = argv.index("--emit-profile")
        emit = argv[i + 1]
        del argv[i:i + 2]
    if "--profile-name" in argv:
        i = argv.index("--profile-name")
        prof_name = argv[i + 1]
        del argv[i:i + 2]
    if "--emit-timeline" in argv:
        i = argv.index("--emit-timeline")
        timeline = argv[i + 1]
        del argv[i:i + 2]
    if "--emit-ledger" in argv:
        i = argv.index("--emit-ledger")
        ledger = argv[i + 1]
        del argv[i:i + 2]
    base = argv[0] if argv else "artifacts/chip_r5"
    if ledger is not None:
        return emit_ledger(base, ledger)
    if timeline is not None:
        return emit_timeline(base, timeline)
    if emit is not None:
        return emit_profile(base, emit, prof_name)
    print(f"# Evidence summary: {base}\n")

    print("## Task status\n")
    logs = sorted(glob.glob(os.path.join(base, "*.log")))
    names = sorted({os.path.basename(p).split(".a")[0].removesuffix(".log")
                    for p in logs})
    for name in names:
        done = os.path.exists(os.path.join(base, f"{name}.done"))
        attempts = len(glob.glob(os.path.join(base, f"{name}.a*.log")))
        print(f"- {name}: {'DONE' if done else 'pending'}"
              f" ({attempts} attempt{'s' if attempts != 1 else ''})")

    rows = [r for r in (perf_row(d) for d in sorted(
        glob.glob(os.path.join(base, "perf_*")))) if r]
    if rows:
        # the pipelined column only appears when some run used it, so
        # tables over legacy artifacts keep their committed shape
        keys = ["dir", "repeat"] + (
            ["pipelined"] if any(r["pipelined"] for r in rows) else []
        ) + ["key_range"] + [
            k for k in (*PHASES, "JPROCRATE_M/s", "RESULTS")
            if any(k in r for r in rows)]
        print("\n## Perf artifacts (ms/join; SDISPATCH = floor per program)\n")
        print("| " + " | ".join(keys) + " |")
        print("|" + "---|" * len(keys))
        for r in rows:
            cells = []
            for k in keys:
                v = r.get(k, "")
                cells.append(f"{v:.1f}" if isinstance(v, float) else str(v))
            print("| " + " | ".join(cells) + " |")

    traces = sorted(glob.glob(os.path.join(base, "trace_*",
                                           "breakdown.json")))
    if traces:
        print("\n## Trace breakdowns\n")
        for path in traces:
            with open(path) as f:
                bd = json.load(f)
            per_iter = bd["busy_us"] / bd["iters"] / 1e3
            print(f"- {os.path.relpath(path, base)}: plane `{bd['plane']}`, "
                  f"{per_iter:.1f} ms/iter device-busy, "
                  f"sort share {100 * bd['sort_share']:.1f}%")
            top = sorted(bd["ops"].items(), key=lambda kv: -kv[1]["us"])[:5]
            for name, v in top:
                print(f"    - {v['us'] / bd['iters'] / 1e3:8.2f} ms/iter  "
                      f"{name[:80]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
