"""graftlint CLI: the repo's convention rules as a gating check.

    python tools_lint.py                      # all rules, baseline applied
    python tools_lint.py --strict             # stale suppressions fail too
    python tools_lint.py --rule sort-bypass --rule counter-tag
    python tools_lint.py --no-baseline        # raw findings, nothing hidden
    python tools_lint.py --list-rules
    python tools_lint.py --json LINT.json     # machine-readable counts

Prints one ``path:line:rule-id: message`` per live finding plus the
suppressed/stale accounting, and exits

    0  clean (no live finding; under --strict also no stale suppression),
    1  at least one live finding (or a stale suppression under --strict),
    2  usage / IO errors (unknown rule, unreadable file, a baseline
       entry without a reason — suppression reasons are mandatory).

The exit-code contract matches tools_check_regress.py / tools_chaos.py,
so CI wires all three the same way.  The rules and the walker live in
``tpu_radix_join/analysis/`` (core.py + one module per rule); the
committed suppression file is ``LINT_BASELINE.json`` at the repo root —
every entry carries a one-line reason, and a stale entry (its finding
was fixed) must be removed with the fix.

``--json`` writes ``{"lint_findings": N, ...}``; ``lint_findings`` is
pinned lower-is-better in observability/regress.py, so a finding-count
regression can gate through tools_check_regress.py like a perf
regression.

The runtime twin of the ``sync-point`` rule is the transfer guard:
``main.py --transfer-guard disallow`` (and the tests'
``transfer_guard`` fixture) arms ``jax.transfer_guard("disallow")``
around the device paths, turning any implicit host sync the rule
missed into a loud runtime error.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tools_lint.py",
        description="Run the project's AST lint rules over the repo.")
    p.add_argument("--rule", action="append", default=[], metavar="ID",
                   help="run only this rule id, repeatable (default: all)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="suppression file (default: LINT_BASELINE.json "
                        "at the repo root)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--strict", action="store_true",
                   help="stale baseline suppressions also fail (exit 1)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule ids + docs and exit 0")
    p.add_argument("--json", default=None, metavar="FILE",
                   help="also write machine-readable counts "
                        "({'lint_findings': N, ...})")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from tpu_radix_join.analysis import (LintError, RULES,
                                         register_builtin_rules, run_lint)
    register_builtin_rules()
    if args.list_rules:
        for rid in sorted(RULES):
            r = RULES[rid]
            print(f"{rid:18s} [{r.token}-ok] {r.doc}")
        return 0
    baseline = None
    if not args.no_baseline:
        baseline = args.baseline or os.path.join(REPO_ROOT,
                                                 "LINT_BASELINE.json")
        if args.baseline and not os.path.exists(args.baseline):
            print(f"error: baseline {args.baseline} not found",
                  file=sys.stderr)
            return 2
    try:
        res = run_lint(REPO_ROOT, rule_ids=args.rule or None,
                       baseline_path=baseline)
    except LintError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for f in res.findings:
        print(f.render())
    for e in res.stale:
        print(f"stale suppression: {e['rule']} {e['path']} key={e['key']!r}"
              f" — finding no longer fires; remove the entry")
    per_rule = {}
    for f in res.findings:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    summary = {"lint_findings": len(res.findings),
               "suppressed": len(res.suppressed),
               "stale_baseline": len(res.stale),
               "rules_run": res.rules,
               "per_rule": per_rule}
    if args.json:
        try:
            with open(args.json, "w") as fh:
                json.dump(summary, fh, indent=2)
        except OSError as e:
            print(f"error: cannot write {args.json}: {e}", file=sys.stderr)
            return 2
    code = res.exit_code(strict=args.strict)
    verdict = "clean" if code == 0 else "FINDINGS"
    print(f"lint: {verdict} — {len(res.findings)} finding(s), "
          f"{len(res.suppressed)} baselined, {len(res.stale)} stale "
          f"suppression(s), rules: {', '.join(res.rules)}")
    return code


if __name__ == "__main__":
    sys.exit(main())
