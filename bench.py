"""Benchmark driver: single-chip radix join throughput on real TPU.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Workload: the reference's canonical per-node join scaled to one chip —
16M ⋈ 16M dense unique uint32 keys (BASELINE.md config #2; the reference runs
20M ⋈ 20M per node, main.cpp:70-71).  Correctness is asserted against the
unique-key oracle before timing.

vs_baseline: the reference publishes no numbers (BASELINE.md — published {}),
so the denominator is 1e9 tuples/sec/accelerator, a nominal figure for the
reference-era GPU build/probe kernels (sm_60-class, eth.cu) on this workload;
vs_baseline >= 1.0 therefore means beating reference-class per-accelerator
throughput.
"""

import json
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.data.tuples import TupleBatch
    from tpu_radix_join.ops.local_join import local_join_merge

    size = 1 << 24               # 16M tuples per side

    r_rel = Relation(size, 1, "unique", seed=1)
    s_rel = Relation(size, 1, "unique", seed=2)
    r = jax.block_until_ready(r_rel.shard(0))
    s = jax.block_until_ready(s_rel.shard(0))

    from tpu_radix_join.ops.merge_count import merge_count_pallas

    def run_xla():
        return local_join_merge(r, s)

    def run_pallas():
        return merge_count_pallas(r.key, s.key)

    candidates = [("xla", run_xla)]
    try:
        counts = run_pallas()
        pallas_matches = int(np.asarray(counts).astype(np.uint64).sum())
        if pallas_matches == size:
            candidates.append(("pallas", run_pallas))
        else:
            # a kernel that runs but miscounts is a correctness regression —
            # surface it loudly while letting the XLA path carry the bench
            print(f"WARNING: pallas path miscounts ({pallas_matches} != {size})",
                  file=sys.stderr)
    except Exception as e:
        print(f"note: pallas path unavailable ({type(e).__name__}); using XLA",
              file=sys.stderr)

    best = None
    for name, fn in candidates:
        counts = fn()
        matches = int(np.asarray(counts).astype(np.uint64).sum())
        assert matches == size, (name, matches, size)
        iters = 5
        t0 = time.perf_counter()
        for _ in range(iters):
            counts = fn()
        jax.block_until_ready(counts)
        dt_i = (time.perf_counter() - t0) / iters
        if best is None or dt_i < best[1]:
            best = (name, dt_i)
    dt = best[1]

    tuples_per_sec = (2 * size) / dt   # both relations processed
    print(json.dumps({
        "metric": "single_chip_join_throughput",
        "value": round(tuples_per_sec, 1),
        "unit": "tuples/sec",
        "vs_baseline": round(tuples_per_sec / 1e9, 4),
    }))


if __name__ == "__main__":
    main()
